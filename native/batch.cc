// Multi-threaded image-batch assembly.
//
// Reference equivalent: dataset/image/MTLabeledBGRImgToBatch.scala:46 — the
// parallel CPU hot path that normalizes/crops/flips decoded images and
// packs them into the training batch while the accelerator computes.
// Here: std::thread workers each own a slice of the batch; output is
// float32 NCHW (the framework's native activations layout).

#include <cstdint>
#include <thread>
#include <vector>

namespace {

void assemble_range(const uint8_t* const* images, const int* heights,
                    const int* widths, int channels, int crop_h, int crop_w,
                    const int* offsets_hw, const uint8_t* flips,
                    const float* mean, const float* stdv, float* out,
                    int begin, int end) {
  const long plane = (long)crop_h * crop_w;
  for (int i = begin; i < end; i++) {
    const uint8_t* img = images[i];
    const int w = widths[i];
    const int oy = offsets_hw[2 * i];
    const int ox = offsets_hw[2 * i + 1];
    const bool flip = flips[i] != 0;
    float* dst = out + (long)i * channels * plane;
    for (int y = 0; y < crop_h; y++) {
      const uint8_t* row = img + ((long)(y + oy) * w + ox) * channels;
      for (int x = 0; x < crop_w; x++) {
        const int sx = flip ? (crop_w - 1 - x) : x;
        const uint8_t* px = row + (long)sx * channels;
        for (int c = 0; c < channels; c++) {
          dst[(long)c * plane + (long)y * crop_w + x] =
              ((float)px[c] - mean[c]) / stdv[c];
        }
      }
    }
  }
}

void assemble_range_u8(const uint8_t* const* images, const int* widths,
                       int channels, int crop_h, int crop_w,
                       const int* offsets_hw, const uint8_t* flips,
                       uint8_t* out, int begin, int end) {
  const long plane = (long)crop_h * crop_w;
  for (int i = begin; i < end; i++) {
    const uint8_t* img = images[i];
    const int w = widths[i];
    const int oy = offsets_hw[2 * i];
    const int ox = offsets_hw[2 * i + 1];
    const bool flip = flips[i] != 0;
    uint8_t* dst = out + (long)i * channels * plane;
    for (int y = 0; y < crop_h; y++) {
      const uint8_t* row = img + ((long)(y + oy) * w + ox) * channels;
      for (int x = 0; x < crop_w; x++) {
        const int sx = flip ? (crop_w - 1 - x) : x;
        const uint8_t* px = row + (long)sx * channels;
        for (int c = 0; c < channels; c++) {
          dst[(long)c * plane + (long)y * crop_w + x] = px[c];
        }
      }
    }
  }
}

}  // namespace

extern "C" {

// Raw-uint8 variant of assemble_batch: crop/flip/pack WITHOUT
// normalization — the device-normalize ingest layout ships uint8 pixels
// and leaves (x - mean)/std to an on-device module (4x fewer
// host->device bytes); out: (n, channels, crop_h, crop_w) uint8.
void assemble_batch_u8(const uint8_t* const* images, const int* heights,
                       const int* widths, int n, int channels, int crop_h,
                       int crop_w, const int* offsets_hw,
                       const uint8_t* flips, uint8_t* out, int n_threads) {
  (void)heights;
  if (n_threads <= 1 || n <= 1) {
    assemble_range_u8(images, widths, channels, crop_h, crop_w, offsets_hw,
                      flips, out, 0, n);
    return;
  }
  if (n_threads > n) n_threads = n;
  std::vector<std::thread> threads;
  const int per = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; t++) {
    const int begin = t * per;
    const int end = begin + per < n ? begin + per : n;
    if (begin >= end) break;
    threads.emplace_back(assemble_range_u8, images, widths, channels, crop_h,
                         crop_w, offsets_hw, flips, out, begin, end);
  }
  for (auto& th : threads) th.join();
}

// images: n pointers to HWC uint8 buffers; out: (n, channels, crop_h,
// crop_w) float32, caller-allocated.
void assemble_batch(const uint8_t* const* images, const int* heights,
                    const int* widths, int n, int channels, int crop_h,
                    int crop_w, const int* offsets_hw, const uint8_t* flips,
                    const float* mean, const float* stdv, float* out,
                    int n_threads) {
  if (n_threads <= 1 || n <= 1) {
    assemble_range(images, heights, widths, channels, crop_h, crop_w,
                   offsets_hw, flips, mean, stdv, out, 0, n);
    return;
  }
  if (n_threads > n) n_threads = n;
  std::vector<std::thread> threads;
  const int per = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; t++) {
    const int begin = t * per;
    const int end = begin + per < n ? begin + per : n;
    if (begin >= end) break;
    threads.emplace_back(assemble_range, images, heights, widths, channels,
                         crop_h, crop_w, offsets_hw, flips, mean, stdv, out,
                         begin, end);
  }
  for (auto& th : threads) th.join();
}

}  // extern "C"
