// Hadoop SequenceFile reader/writer (uncompressed, version 6).
//
// Reference equivalent: the Hadoop-SequenceFile ImageNet pipeline the
// reference trains from (dataset/DataSet.scala:500-558 SeqFileFolder,
// dataset/image/SeqFileReader) — there provided by hadoop-client; here a
// small native implementation with a C ABI for ctypes.
//
// Layout (uncompressed):
//   "SEQ" <version u8> <keyClass Text> <valueClass Text>
//   <compressed u8=0> <blockCompressed u8=0>
//   <metadata count i32-BE> (k/v Text pairs)
//   <16-byte sync marker>
//   records: <recordLen i32-BE> <keyLen i32-BE> <key bytes> <value bytes>
//   every ~sync interval: <-1 i32-BE> <16-byte sync marker>
// Text = vint length + utf8 bytes (hadoop WritableUtils vint encoding).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Reader {
  FILE* f = nullptr;
  uint8_t sync[16];
  std::vector<char> key;
  std::vector<char> value;
};

struct Writer {
  FILE* f = nullptr;
  uint8_t sync[16];
  long since_sync = 0;
};

int32_t read_i32be(FILE* f, bool* ok) {
  uint8_t b[4];
  if (fread(b, 1, 4, f) != 4) { *ok = false; return 0; }
  *ok = true;
  return (int32_t)((uint32_t)b[0] << 24 | (uint32_t)b[1] << 16 |
                   (uint32_t)b[2] << 8 | (uint32_t)b[3]);
}

void write_i32be(FILE* f, int32_t v) {
  uint8_t b[4] = {(uint8_t)((uint32_t)v >> 24), (uint8_t)((uint32_t)v >> 16),
                  (uint8_t)((uint32_t)v >> 8), (uint8_t)v};
  fwrite(b, 1, 4, f);
}

// hadoop WritableUtils::readVInt
bool read_vlong(FILE* f, int64_t* out) {
  int c = fgetc(f);
  if (c == EOF) return false;
  int8_t first = (int8_t)c;
  if (first >= -112) { *out = first; return true; }
  bool neg = first < -120;
  int len = neg ? -(first + 120) : -(first + 112);
  uint64_t v = 0;
  for (int i = 0; i < len; i++) {
    c = fgetc(f);
    if (c == EOF) return false;
    v = (v << 8) | (uint8_t)c;
  }
  *out = neg ? ~(int64_t)v : (int64_t)v;
  return true;
}

void write_vlong(FILE* f, int64_t v) {
  if (v >= -112 && v <= 127) { fputc((int)(int8_t)v, f); return; }
  int len = -112;
  if (v < 0) { v = ~v; len = -120; }
  uint64_t tmp = (uint64_t)v;
  while (tmp != 0) { tmp >>= 8; len--; }
  fputc((int)(int8_t)len, f);
  int n = (len < -120) ? -(len + 120) : -(len + 112);
  for (int i = n - 1; i >= 0; i--) fputc((int)((v >> (8 * i)) & 0xFF), f);
}

bool read_text(FILE* f, std::string* out) {
  int64_t n;
  if (!read_vlong(f, &n) || n < 0) return false;
  out->resize((size_t)n);
  return n == 0 || fread(&(*out)[0], 1, (size_t)n, f) == (size_t)n;
}

void write_text(FILE* f, const char* s) {
  size_t n = strlen(s);
  write_vlong(f, (int64_t)n);
  fwrite(s, 1, n, f);
}

}  // namespace

extern "C" {

void* seqfile_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  char magic[3];
  if (fread(magic, 1, 3, f) != 3 || memcmp(magic, "SEQ", 3) != 0) {
    fclose(f);
    return nullptr;
  }
  int version = fgetc(f);
  if (version < 5) { fclose(f); return nullptr; }
  Reader* r = new Reader();
  r->f = f;
  std::string key_cls, val_cls;
  if (!read_text(f, &key_cls) || !read_text(f, &val_cls)) {
    fclose(f); delete r; return nullptr;
  }
  int compressed = fgetc(f);
  int block = fgetc(f);
  if (compressed != 0 || block != 0) { fclose(f); delete r; return nullptr; }
  bool ok;
  int32_t meta = read_i32be(f, &ok);
  if (!ok) { fclose(f); delete r; return nullptr; }
  for (int32_t i = 0; i < meta; i++) {
    std::string k, v;
    if (!read_text(f, &k) || !read_text(f, &v)) {
      fclose(f); delete r; return nullptr;
    }
  }
  if (fread(r->sync, 1, 16, f) != 16) { fclose(f); delete r; return nullptr; }
  return r;
}

// 1 = record produced, 0 = EOF, -1 = corrupt
int seqfile_next(void* handle, const char** key, int* klen,
                 const char** value, int* vlen) {
  Reader* r = (Reader*)handle;
  for (;;) {
    // clean EOF is ZERO bytes at a record boundary; 1-3 dangling bytes
    // mean the file was cut inside the length field — corruption, kept
    // in lockstep with the python reader
    uint8_t lb[4];
    size_t got = fread(lb, 1, 4, r->f);
    if (got == 0) return 0;
    if (got != 4) return -1;
    int32_t rec_len = (int32_t)((uint32_t)lb[0] << 24 | (uint32_t)lb[1] << 16 |
                                (uint32_t)lb[2] << 8 | (uint32_t)lb[3]);
    bool ok;
    if (rec_len == -1) {  // sync escape
      uint8_t sync[16];
      // a short read here is a file cut INSIDE the sync marker —
      // truncation, not clean EOF (the python reader raises on the
      // mismatched short marker; -1 keeps the two in lockstep)
      if (fread(sync, 1, 16, r->f) != 16) return -1;
      if (memcmp(sync, r->sync, 16) != 0) return -1;
      continue;
    }
    // corrupt length bytes must not reach resize(): a flipped bit can
    // read as ~2 GB and either bad_alloc (which would terminate across
    // the C ABI) or grind the host allocating it.  Records here are
    // JPEG frames (MBs); 1 GB is far beyond any legitimate record.
    if (rec_len < 0 || rec_len > (1 << 30)) return -1;
    int32_t key_len = read_i32be(r->f, &ok);
    if (!ok || key_len < 0 || key_len > rec_len) return -1;
    r->key.resize((size_t)key_len);
    r->value.resize((size_t)(rec_len - key_len));
    if (key_len && fread(r->key.data(), 1, (size_t)key_len, r->f) !=
                       (size_t)key_len)
      return -1;
    size_t v = (size_t)(rec_len - key_len);
    if (v && fread(r->value.data(), 1, v, r->f) != v) return -1;
    *key = r->key.data();
    *klen = key_len;
    *value = r->value.data();
    *vlen = (int)v;
    return 1;
  }
}

void seqfile_close(void* handle) {
  Reader* r = (Reader*)handle;
  if (r) {
    if (r->f) fclose(r->f);
    delete r;
  }
}

void* seqfile_create(const char* path, const char* key_class,
                     const char* value_class, const uint8_t* sync16) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  Writer* w = new Writer();
  w->f = f;
  memcpy(w->sync, sync16, 16);
  fwrite("SEQ", 1, 3, f);
  fputc(6, f);  // version
  write_text(f, key_class);
  write_text(f, value_class);
  fputc(0, f);  // not compressed
  fputc(0, f);  // not block-compressed
  write_i32be(f, 0);  // no metadata
  fwrite(w->sync, 1, 16, f);
  return w;
}

void seqfile_append(void* handle, const char* key, int klen,
                    const char* value, int vlen) {
  Writer* w = (Writer*)handle;
  if (w->since_sync > 2000) {  // hadoop SYNC_INTERVAL ballpark
    write_i32be(w->f, -1);
    fwrite(w->sync, 1, 16, w->f);
    w->since_sync = 0;
  }
  write_i32be(w->f, klen + vlen);
  write_i32be(w->f, klen);
  fwrite(key, 1, (size_t)klen, w->f);
  fwrite(value, 1, (size_t)vlen, w->f);
  w->since_sync += klen + vlen + 8;
}

void seqfile_close_writer(void* handle) {
  Writer* w = (Writer*)handle;
  if (w) {
    if (w->f) fclose(w->f);
    delete w;
  }
}

}  // extern "C"
