"""Expert-parallel MoE convergence artifact (GShard top-2 routing).

Runs the UNMODIFIED transformer driver on a (data=2, expert=4) mesh with
``--moe-experts 8 --moe-top-k 2`` — the GShard configuration reached
purely through public driver flags — and pins the loss curve plus the
final next-token accuracy in ``MOE_r04.json`` (the same protocol as the
ACCURACY_r03 LeNet artifact).  Uses the virtual 8-device CPU mesh, like
the multichip dryrun: expert parallelism needs an expert axis regardless
of what one physical chip offers.

Run:  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      JAX_PLATFORMS=cpu python moe_convergence.py [--out MOE_r04.json]
"""

import argparse
import io
import json
import logging
import re
import sys
from contextlib import redirect_stdout


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=30)
    ap.add_argument("--out", default="MOE_r04.json")
    args = ap.parse_args()

    from bigdl_tpu.engine import Engine
    Engine.honor_virtual_devices()

    losses = []

    class LossTap(logging.Handler):
        def emit(self, record):
            m = re.search(r"Loss is ([0-9.eE+-]*[0-9])", record.getMessage())
            if m:
                losses.append(float(m.group(1)))

    # the driver's init_logging REPLACES the bigdl_tpu handlers
    # (LoggerFilter); disable it so the loss tap survives
    from bigdl_tpu.utils import config
    config.set_property("bigdl.utils.LoggerFilter.disable", True)
    lg = logging.getLogger("bigdl_tpu")
    lg.setLevel(logging.INFO)
    lg.addHandler(LossTap())

    from bigdl_tpu.models.transformer import train as drv
    argv = ["--synthetic", "256", "--seq-len", "32",
            "--d-model", "64", "--heads", "4", "--layers", "2",
            "--moe-experts", "8", "--moe-top-k", "2",
            "--partitions", "2", "--expert-parallel", "4",
            "--max-epoch", str(args.epochs), "-b", "32"]
    buf = io.StringIO()
    with redirect_stdout(buf):
        trained = drv.main(argv)
    out = buf.getvalue()
    sys.stderr.write(out)
    m = re.search(r"Final next-token accuracy: ([0-9.]+)", out)
    if not m:
        raise SystemExit("driver did not report a final accuracy")
    acc = float(m.group(1))

    # verify through the public model that the GShard configuration was
    # really in effect (flag plumbing, not a silent Switch fallback)
    from bigdl_tpu.nn.moe import MixtureOfExperts
    moes = trained.find_modules(MixtureOfExperts)
    assert moes and all(mm.top_k == 2 for mm in moes), "top_k not applied"

    # a decimating loss curve, pinned at curve checkpoints
    idx = [0, len(losses) // 4, len(losses) // 2, 3 * len(losses) // 4, -1]
    curve = [round(losses[i], 4) for i in idx]
    record = {"metric": "moe_gshard_top2_next_token_acc",
              "value": round(acc, 4), "unit": "accuracy",
              "loss_curve": curve,
              "iterations": len(losses),
              "config": {"driver": "bigdl_tpu.models.transformer.train",
                         "mesh": "(data=2, expert=4) — 8 virtual devices",
                         "flags": " ".join(argv),
                         "experts": 8, "top_k": 2,
                         "aux_loss": "folded, weight 0.01 (Switch alpha)"}}
    with open(args.out, "w") as f:
        json.dump(record, f, indent=1)
    print(json.dumps(record))


if __name__ == "__main__":
    main()
