"""Real-data epochs-to-accuracy artifact (reference north-star protocol).

Two legs, each running an UNMODIFIED driver + its production ingest on
the only real image dataset this zero-egress image carries (UCI optical
digits via scikit-learn — 1797 real handwritten digits; neither MNIST
nor CIFAR-10 exists on disk):

- **lenet**: the reference's MNIST protocol (``models/lenet/Train.scala:
  35``) — digits upsampled to 28x28, written as idx files, parsed by
  ``dataset.datasets.load_mnist``, trained to >98% top-1 in 15 epochs.
- **vgg**: BASELINE config #2 above LeNet scale — digits rendered as
  32x32x3 CIFAR-10 BINARY batches, ingested by the VGG driver's
  ``load_cifar10``, VGG-16 trained to >90% top-1.

The measured numbers pin in ``ACCURACY_r05.json`` (round 3's
single-leg ``ACCURACY_r03.json`` is kept as history — do not overwrite
it) and regress via ``tests/test_accuracy_artifact.py``.

Run:  python accuracy.py [--legs lenet,vgg] [--out ACCURACY_r05.json]
"""

import argparse
import json
import os
import re
import struct
import sys
import tempfile

import numpy as np


def _log(*a):
    print(*a, file=sys.stderr, flush=True)


def write_idx_images(path: str, images: np.ndarray) -> None:
    """MNIST idx3 format: magic 0x803, dims, uint8 payload."""
    n, h, w = images.shape
    with open(path, "wb") as f:
        f.write(struct.pack(">IIII", 0x803, n, h, w))
        f.write(images.astype(np.uint8).tobytes())


def write_idx_labels(path: str, labels: np.ndarray) -> None:
    with open(path, "wb") as f:
        f.write(struct.pack(">II", 0x801, labels.shape[0]))
        f.write(labels.astype(np.uint8).tobytes())


def _digits_split(side: int, test_fraction: float = 0.2, seed: int = 0):
    """The shared leg protocol: real digits upscaled to ``side`` x
    ``side`` [0,255] uint8 (bilinear — real pen strokes scale smoothly;
    nearest would alias them into blocks), seeded-shuffle split.  ONE
    implementation so the legs stay comparable: same seed, same split."""
    from sklearn.datasets import load_digits
    import jax

    d = load_digits()
    imgs = np.asarray(jax.image.resize(
        d.images.astype(np.float32), (d.images.shape[0], side, side),
        "bilinear"))
    imgs = np.clip(imgs * (255.0 / 16.0), 0, 255).astype(np.uint8)
    labels = d.target.astype(np.uint8)
    rng = np.random.RandomState(seed)
    order = rng.permutation(len(imgs))
    n_test = int(len(imgs) * test_fraction)
    return imgs, labels, order[n_test:], order[:n_test]


def make_digits_idx(folder: str, test_fraction: float = 0.2, seed: int = 0):
    """Write the sklearn digits dataset as MNIST-protocol idx files."""
    imgs, labels, train, test = _digits_split(28, test_fraction, seed)
    write_idx_images(os.path.join(folder, "train-images-idx3-ubyte"),
                     imgs[train])
    write_idx_labels(os.path.join(folder, "train-labels-idx1-ubyte"),
                     labels[train])
    write_idx_images(os.path.join(folder, "t10k-images-idx3-ubyte"),
                     imgs[test])
    write_idx_labels(os.path.join(folder, "t10k-labels-idx1-ubyte"),
                     labels[test])
    return len(train), len(test)


def make_digits_cifar(folder: str, test_fraction: float = 0.2,
                      seed: int = 0):
    """Write the sklearn digits dataset in CIFAR-10 BINARY batch format
    (1 label byte + 3072 RGB bytes per record, ``data_batch_{1..5}.bin``
    + ``test_batch.bin``) so the UNMODIFIED VGG/CIFAR-10 driver
    (BASELINE config #2) ingests it through its production
    ``load_cifar10`` path; pixels replicate across the three channels."""
    imgs, labels, train, test = _digits_split(32, test_fraction, seed)

    def write_bin(path, idx):
        recs = []
        for i in idx:
            rgb = np.repeat(imgs[i][None], 3, axis=0)   # (3, 32, 32)
            recs.append(np.concatenate([[labels[i]], rgb.ravel()])
                        .astype(np.uint8))
        np.stack(recs).tofile(path)

    chunks = np.array_split(train, 5)
    for i, chunk in enumerate(chunks, start=1):
        write_bin(os.path.join(folder, f"data_batch_{i}.bin"), chunk)
    write_bin(os.path.join(folder, "test_batch.bin"), test)
    return len(train), len(test)


def _run_driver(drv_main, argv):
    import io
    from contextlib import redirect_stdout

    from bigdl_tpu.utils.random_generator import RandomGenerator

    # each leg starts from the default seed: one leg's epoch shuffles
    # must not perturb the next leg's trajectory when both run in one
    # process (the artifact numbers are per-leg reproducible)
    RandomGenerator.RNG().set_seed(5489)
    buf = io.StringIO()
    with redirect_stdout(buf):
        drv_main(argv)
    out = buf.getvalue()
    sys.stderr.write(out)
    m = re.search(r"Final Top1Accuracy:.*?([0-9.]+)", out)
    if not m:
        raise SystemExit("driver did not report a final accuracy")
    return float(m.group(1))


def run_lenet(args):
    from bigdl_tpu.models.lenet import train as drv

    with tempfile.TemporaryDirectory() as folder:
        n_train, n_test = make_digits_idx(folder)
        _log(f"digits-as-idx: {n_train} train / {n_test} test")
        acc = _run_driver(drv.main,
                          ["-f", folder, "-b", str(args.batch),
                           "--max-epoch", str(args.epochs),
                           "-r", str(args.lr)])
    return {"metric": "lenet_digits_top1", "value": round(acc, 4),
            "unit": "accuracy",
            "config": {"dataset": "sklearn-digits (UCI, real handwritten"
                                  " digits) as 28x28 idx files",
                       "driver": "bigdl_tpu.models.lenet.train",
                       "epochs": args.epochs, "batch": args.batch,
                       "lr": args.lr, "train": n_train, "test": n_test},
            "note": "MNIST itself is not present in this zero-egress "
                    "image; same driver, ingest (idx), and protocol"}


def run_vgg(args):
    """BASELINE config #2 above LeNet scale: the UNMODIFIED VGG-16
    CIFAR-10 driver (binary-batch ingest, BGR normalize, SGD momentum +
    weight decay, per-epoch Top1 validation) on the real digit images
    rendered as CIFAR binary batches."""
    from bigdl_tpu.models.vgg import train as drv

    with tempfile.TemporaryDirectory() as folder:
        n_train, n_test = make_digits_cifar(folder)
        _log(f"digits-as-cifar-bin: {n_train} train / {n_test} test")
        acc = _run_driver(drv.main,
                          ["-f", folder, "-b", str(args.batch),
                           "--max-epoch", str(args.vgg_epochs),
                           "-r", str(args.vgg_lr)])
    return {"metric": "vgg16_cifar_driver_digits_top1",
            "value": round(acc, 4), "unit": "accuracy",
            "config": {"dataset": "sklearn-digits (UCI, real handwritten "
                                  "digits) as 32x32x3 CIFAR-10 binary "
                                  "batches",
                       "driver": "bigdl_tpu.models.vgg.train (unmodified"
                                 ", BASELINE config #2)",
                       "model": "VGG-16 (VggForCifar10, ~15M params)",
                       "epochs": args.vgg_epochs, "batch": args.batch,
                       "lr": args.vgg_lr, "train": n_train,
                       "test": n_test},
            "note": "CIFAR-10 itself is not present in this zero-egress "
                    "image (only 7 sample PNGs exist on disk); same "
                    "driver, ingest (cifar .bin), model, and protocol "
                    "on the real handwritten-digit images"}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=15)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--vgg-epochs", type=int, default=30)
    ap.add_argument("--vgg-lr", type=float, default=0.01)
    ap.add_argument("--legs", default="lenet,vgg",
                    help="comma-set of artifact legs to run")
    ap.add_argument("--out", default="ACCURACY_r05.json")
    args = ap.parse_args()

    known = {"lenet": run_lenet, "vgg": run_vgg}
    legs = [l.strip() for l in args.legs.split(",") if l.strip()]
    unknown = [l for l in legs if l not in known]
    if unknown or not legs:
        raise SystemExit(f"--legs must name at least one of "
                         f"{sorted(known)}; got {args.legs!r}")
    if set(legs) != set(known) and args.out == "ACCURACY_r05.json":
        # a partial re-run must not clobber the pinned two-leg artifact
        # with a one-leg record (the schema test would then fail on the
        # missing metric)
        raise SystemExit(
            f"--legs {args.legs!r} runs a subset of the artifact's legs; "
            "pass an explicit --out so the pinned ACCURACY_r05.json "
            "(which carries ALL legs) is not overwritten")
    points = [known[l](args) for l in legs]
    record = dict(points[0])
    record["points"] = points
    with open(args.out, "w") as f:
        json.dump(record, f, indent=1)
    print(json.dumps(record))


if __name__ == "__main__":
    main()
