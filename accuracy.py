"""Real-data epochs-to-accuracy artifact (reference north-star protocol).

The reference's LeNet protocol trains on MNIST idx files to >98% top-1
(``models/lenet/Train.scala:35``).  This zero-egress image carries no
MNIST (only a 32-image test fixture exists anywhere on disk), so the
artifact runs the SAME driver and ingest path — idx-format files parsed
by ``dataset.datasets.load_mnist``, GreyImgNormalizer-style
standardization, SampleToMiniBatch, SGD, per-epoch Top1 validation — on
the bundled REAL handwritten-digit dataset (UCI optical digits via
scikit-learn: 1797 images, upsampled 8x8 -> 28x28).  The result is a
measured epochs-to-accuracy number on real data, pinned in
``ACCURACY_r03.json`` and regressed by ``tests/test_accuracy_artifact.py``.

Run:  python accuracy.py [--epochs N] [--out ACCURACY_r03.json]
"""

import argparse
import json
import os
import re
import struct
import sys
import tempfile

import numpy as np


def _log(*a):
    print(*a, file=sys.stderr, flush=True)


def write_idx_images(path: str, images: np.ndarray) -> None:
    """MNIST idx3 format: magic 0x803, dims, uint8 payload."""
    n, h, w = images.shape
    with open(path, "wb") as f:
        f.write(struct.pack(">IIII", 0x803, n, h, w))
        f.write(images.astype(np.uint8).tobytes())


def write_idx_labels(path: str, labels: np.ndarray) -> None:
    with open(path, "wb") as f:
        f.write(struct.pack(">II", 0x801, labels.shape[0]))
        f.write(labels.astype(np.uint8).tobytes())


def make_digits_idx(folder: str, test_fraction: float = 0.2, seed: int = 0):
    """Write the sklearn digits dataset as MNIST-protocol idx files."""
    from sklearn.datasets import load_digits
    import jax

    d = load_digits()
    # 8x8 [0,16] -> 28x28 [0,255] uint8, bilinear (real pen strokes scale
    # smoothly; nearest would alias them into blocks)
    imgs = np.asarray(jax.image.resize(
        d.images.astype(np.float32), (d.images.shape[0], 28, 28),
        "bilinear"))
    imgs = np.clip(imgs * (255.0 / 16.0), 0, 255).astype(np.uint8)
    labels = d.target.astype(np.uint8)

    rng = np.random.RandomState(seed)
    order = rng.permutation(len(imgs))
    n_test = int(len(imgs) * test_fraction)
    test, train = order[:n_test], order[n_test:]
    write_idx_images(os.path.join(folder, "train-images-idx3-ubyte"),
                     imgs[train])
    write_idx_labels(os.path.join(folder, "train-labels-idx1-ubyte"),
                     labels[train])
    write_idx_images(os.path.join(folder, "t10k-images-idx3-ubyte"),
                     imgs[test])
    write_idx_labels(os.path.join(folder, "t10k-labels-idx1-ubyte"),
                     labels[test])
    return len(train), n_test


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=15)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--out", default="ACCURACY_r03.json")
    args = ap.parse_args()

    import io
    from contextlib import redirect_stdout

    from bigdl_tpu.models.lenet import train as drv

    with tempfile.TemporaryDirectory() as folder:
        n_train, n_test = make_digits_idx(folder)
        _log(f"digits-as-idx: {n_train} train / {n_test} test")
        buf = io.StringIO()
        with redirect_stdout(buf):
            drv.main(["-f", folder, "-b", str(args.batch),
                      "--max-epoch", str(args.epochs),
                      "-r", str(args.lr)])
        out = buf.getvalue()
        sys.stderr.write(out)
    m = re.search(r"Final Top1Accuracy:.*?([0-9.]+)", out)
    if not m:
        raise SystemExit("driver did not report a final accuracy")
    acc = float(m.group(1))
    record = {"metric": "lenet_digits_top1", "value": round(acc, 4),
              "unit": "accuracy",
              "config": {"dataset": "sklearn-digits (UCI, real handwritten"
                                    " digits) as 28x28 idx files",
                         "driver": "bigdl_tpu.models.lenet.train",
                         "epochs": args.epochs, "batch": args.batch,
                         "lr": args.lr, "train": n_train, "test": n_test},
              "note": "MNIST itself is not present in this zero-egress "
                      "image; same driver, ingest (idx), and protocol"}
    with open(args.out, "w") as f:
        json.dump(record, f, indent=1)
    print(json.dumps(record))


if __name__ == "__main__":
    main()
