"""HLO program auditor tests (ISSUE 11 tentpole).

The contract under test (analysis/hlo_audit.py + program_contracts.py):

- the StableHLO census extracts every collective with byte counts and
  replica groups (region ops like all_reduce carry their signature on
  the closing ``})`` line), counts rank-4 transposes, and spots f64 /
  f32-compute drift;
- every fused-step family the trainers build passes its declared
  contract STRICT (the conftest arms all three passes strict for the
  whole tier-1 suite — these tests also assert it directly);
- injected violations are CAUGHT with structured reports naming the
  HLO op and the owning step: a redundant all-gather smuggled into the
  shard_map step (``bigdl.chaos.extraAllGather``) and an f32 upcast in
  a declared-bf16 program (``bigdl.chaos.f32Upcast``);
- the offline mode audits a persisted compile cache from the census
  each manifest recorded, and regression-checks against committed
  baselines.
"""

import json
import os

import numpy as np
import jax
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu import telemetry
from bigdl_tpu.analysis import hlo_audit, program_contracts
from bigdl_tpu.analysis.hlo_audit import (AuditReport, audit_step,
                                          check_against_baseline,
                                          parse_stablehlo)
from bigdl_tpu.analysis.program_contracts import (CollectiveBound,
                                                  ProgramContractError,
                                                  StepContract)
from bigdl_tpu.dataset.dataset import ShardedDataSet
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import SampleToMiniBatch
from bigdl_tpu.optim.optimizer import Optimizer
from bigdl_tpu.utils import config

N_DEV = 8


# ---------------------------------------------------------------------------
# StableHLO census (parser unit tests — synthetic IR, no compiles)
# ---------------------------------------------------------------------------

_SYNTH = """\
module @jit_step attributes {mhlo.num_replicas = 1 : i32} {
  func.func public @main(%arg0: tensor<8x4xf32>) -> tensor<4x32xf32> {
    %0 = "stablehlo.all_gather"(%arg0) <{all_gather_dim = 0 : i64, replica_groups = dense<[[0, 1, 2, 3]]> : tensor<1x4xi64>}> : (tensor<8x4xf32>) -> tensor<32x4xf32>
    %1 = "stablehlo.all_reduce"(%0) <{replica_groups = dense<[[0, 1, 2, 3]]> : tensor<1x4xi64>}> ({
    ^bb0(%a: tensor<f32>, %b: tensor<f32>):
      %s = stablehlo.add %a, %b : tensor<f32>
      stablehlo.return %s : tensor<f32>
    }) : (tensor<32x4xf32>) -> tensor<32x4xf32>
    %2 = stablehlo.transpose %1, dims = [1, 0] : (tensor<32x4xf32>) -> tensor<4x32xf32>
    return %2 : tensor<4x32xf32>
  }
}
"""

_SYNTH_DRIFT = """\
module @jit_step {
  func.func public @main(%arg0: tensor<8x4xf32>) -> tensor<8x2xf32> {
    %c = stablehlo.constant dense<1.000000e+00> : tensor<f64>
    %w = stablehlo.constant dense<0.5> : tensor<4x2xf32>
    %0 = stablehlo.dot_general %arg0, %w, contracting_dims = [1] x [0] : (tensor<8x4xf32>, tensor<4x2xf32>) -> tensor<8x2xf32>
    %1 = stablehlo.transpose %arg0, dims = [0, 2, 3, 1] : (tensor<2x3x8x8xf32>) -> tensor<2x8x8x3xf32>
    return %0 : tensor<8x2xf32>
  }
}
"""


class TestParser:
    def test_inline_collective_bytes_and_groups(self):
        c = parse_stablehlo("t", _SYNTH)
        ag = [x for x in c.collectives if x.kind == "all-gather"]
        assert len(ag) == 1
        assert ag[0].operand_bytes == 8 * 4 * 4
        assert ag[0].result_bytes == 32 * 4 * 4
        assert ag[0].traffic_bytes == 512
        assert ag[0].groups == "[[0, 1, 2, 3]]"
        assert "tensor<8x4xf32>" in ag[0].types

    def test_region_collective_signature_on_closing_line(self):
        """all_reduce carries its reduction as a region — the type
        signature lives on the closing ``})`` line, not the op line."""
        c = parse_stablehlo("t", _SYNTH)
        ar = [x for x in c.collectives if x.kind == "all-reduce"]
        assert len(ar) == 1
        assert ar[0].operand_bytes == 32 * 4 * 4
        assert ar[0].traffic_bytes == 512

    def test_aggregates(self):
        c = parse_stablehlo("t", _SYNTH)
        assert c.collective_bytes == 1024
        assert c.by_kind() == {
            "all-gather": {"ops": 1, "bytes": 512},
            "all-reduce": {"ops": 1, "bytes": 512}}
        assert c.transposes == 1 and c.rank4_transposes == 0
        assert not c.f64_ops and not c.f32_compute_ops

    def test_f64_f32_and_rank4_detection(self):
        c = parse_stablehlo("t", _SYNTH_DRIFT)
        assert len(c.f64_ops) == 1 and "constant" in c.f64_ops[0]
        assert len(c.f32_compute_ops) == 1
        assert c.f32_compute_ops[0].startswith("stablehlo.dot_general")
        assert c.rank4_transposes == 1 and c.transposes == 1
        assert c.collectives == []

    def test_summary_is_json_safe(self):
        s = parse_stablehlo("t", _SYNTH).summary()
        json.dumps(s)
        assert s["label"] == "t" and s["collective_bytes"] == 1024


# ---------------------------------------------------------------------------
# pass families over synthetic programs (conftest arms all three STRICT)
# ---------------------------------------------------------------------------

class TestPasses:
    def test_undeclared_kind_is_a_violation(self):
        contract = StepContract(label="t", collectives=(
            CollectiveBound("all-reduce"),))
        rep = audit_step("t", _SYNTH, contract=contract)
        assert not rep.ok
        v = rep.violations[0]
        assert v.pass_name == "collective"
        assert v.op == "stablehlo.all_gather"
        assert v.step == "t" and "undeclared" in v.detail
        assert rep.strict_violations          # conftest armed strict
        with pytest.raises(ProgramContractError):
            rep.raise_or_warn()

    def test_max_ops_and_max_bytes_budgets(self):
        over_ops = StepContract(label="t", collectives=(
            CollectiveBound("all-gather", max_ops=0),
            CollectiveBound("all-reduce")))
        rep = audit_step("t", _SYNTH, contract=over_ops)
        assert any("exceed the declared max of 0" in v.detail
                   for v in rep.violations)
        over_bytes = StepContract(label="t", collectives=(
            CollectiveBound("all-gather", max_bytes=100),
            CollectiveBound("all-reduce")))
        rep2 = audit_step("t", _SYNTH, contract=over_bytes)
        assert any("512 bytes exceeds the declared budget of 100"
                   in v.detail for v in rep2.violations)

    def test_within_budget_is_clean(self):
        contract = StepContract(label="t", collectives=(
            CollectiveBound("all-gather", max_ops=1, max_bytes=512),
            CollectiveBound("all-reduce", max_ops=1, max_bytes=512)))
        assert audit_step("t", _SYNTH, contract=contract).ok

    def test_f64_flagged_regardless_of_contract(self):
        rep = audit_step("t", _SYNTH_DRIFT,
                         contract=StepContract(label="t"))
        f64 = [v for v in rep.violations if "f64" in v.detail]
        assert f64 and f64[0].pass_name == "precision"

    def test_f32_compute_only_under_declared_bf16(self):
        fp32 = StepContract(label="t", activation_dtype="fp32")
        bf16 = StepContract(label="t", activation_dtype="bf16")
        text = _SYNTH_DRIFT.replace(
            "dense<1.000000e+00> : tensor<f64>",
            "dense<1.000000e+00> : tensor<f32>")   # drop the f64 finding
        assert audit_step("t", text, contract=fp32).ok
        rep = audit_step("t", text, contract=bf16)
        assert len(rep.violations) == 1
        v = rep.violations[0]
        assert v.op == "stablehlo.dot_general" and "bf16" in v.detail

    def test_rank4_transpose_budget(self):
        tight = StepContract(label="t", max_rank4_transposes=0)
        rep = audit_step("t", _SYNTH_DRIFT.replace(
            "tensor<f64>", "tensor<f32>"), contract=tight)
        mem = [v for v in rep.violations if v.pass_name == "memory"]
        assert mem and mem[0].op == "stablehlo.transpose"

    def test_off_mode_disables_pass(self):
        config.set_property("bigdl.audit.collectives", "off")
        try:
            rep = audit_step("t", _SYNTH, contract=StepContract(label="t"))
            assert rep.ok                    # undeclared kinds, pass off
        finally:
            config.set_property("bigdl.audit.collectives", "strict")

    def test_warn_mode_logs_not_raises(self, caplog):
        config.set_property("bigdl.audit.collectives", "warn")
        try:
            rep = audit_step("t", _SYNTH, contract=StepContract(label="t"))
            assert rep.violations and not rep.strict_violations
            import logging
            with caplog.at_level(logging.WARNING, logger="bigdl_tpu"):
                rep.raise_or_warn()          # no raise
            assert any("program audit" in r.message for r in caplog.records)
        finally:
            config.set_property("bigdl.audit.collectives", "strict")

    def test_metrics_exported(self):
        audit_step("metrics_probe", _SYNTH,
                   contract=StepContract(label="metrics_probe",
                                         collectives=(
                                             CollectiveBound("all-gather"),
                                             CollectiveBound("all-reduce"))))
        g = telemetry.gauge("Audit/collective_bytes",
                            labels={"step": "metrics_probe"})
        assert g.value == 1024


# ---------------------------------------------------------------------------
# real fused steps: strict-clean end to end, chaos injections caught
# ---------------------------------------------------------------------------

def _samples(n=64, dim=8, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    return [Sample(rng.normal(size=(dim,)).astype(np.float32),
                   np.int64(i % classes + 1)) for i in range(n)]


def _local_trainer(precision=None, iterations=2):
    m = (nn.Sequential().add(nn.Linear(8, 16)).add(nn.Tanh())
         .add(nn.Linear(16, 3)).add(nn.LogSoftMax()))
    m.reset(jax.random.PRNGKey(7))
    o = Optimizer.create(m, _samples(), nn.ClassNLLCriterion(),
                         batch_size=16)
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    o.set_end_when(optim.max_iteration(iterations))
    if precision:
        o.set_precision(precision)
    return o


def _distri_trainer(iterations=2):
    ds = ShardedDataSet(_samples(), N_DEV).transform(
        SampleToMiniBatch(64, N_DEV))
    m = (nn.Sequential().add(nn.Linear(8, 16)).add(nn.Tanh())
         .add(nn.Linear(16, 3)).add(nn.LogSoftMax()))
    m.reset(jax.random.PRNGKey(7))
    o = Optimizer.create(m, ds, nn.ClassNLLCriterion())
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    o.set_end_when(optim.max_iteration(iterations))
    return o


class TestLiveAudit:
    def test_local_step_audits_clean_strict(self):
        """The whole tier-1 suite runs strict (conftest); this test pins
        the property explicitly: a local fused step compiles under the
        strict auditor without a violation and exports its census."""
        assert config.get_property("bigdl.audit.collectives") == "strict"
        _local_trainer().optimize()
        g = telemetry.gauge("Audit/collective_bytes",
                            labels={"step": "local"})
        assert g.value == 0                  # single-device: no collectives

    def test_shard_map_step_audits_clean_strict(self):
        _distri_trainer().optimize()
        g = telemetry.gauge("Audit/collective_bytes",
                            labels={"step": "shard_map"})
        assert g.value > 0                   # rs + ag + scalar all-reduces

    def test_injected_extra_all_gather_caught(self):
        """Chaos: a redundant (bit-exact) second all-gather in the
        shard_map step must trip the collective contract with a report
        naming the op and the owning step."""
        config.set_property("bigdl.chaos.extraAllGather", "true")
        try:
            with pytest.raises(ProgramContractError) as ei:
                _distri_trainer().optimize()
        finally:
            config.clear_property("bigdl.chaos.extraAllGather")
        msg = str(ei.value)
        assert "stablehlo.all_gather" in msg
        assert "step 'shard_map'" in msg
        # the contract declares exactly one all-gather per overlap bucket
        # (bigdl.parallel.overlapBuckets, default 4) — the redundant extra
        # one overflows that count
        n_buckets = config.get_int("bigdl.parallel.overlapBuckets", 4)
        assert f"exceed the declared max of {n_buckets}" in msg
        assert ei.value.violations           # structured, not just a string
        v = [x for x in ei.value.violations if x.op == "stablehlo.all_gather"]
        assert v and v[0].step == "shard_map"
        assert v[0].pass_name == "collective"

    def test_injected_f32_upcast_in_bf16_program_caught(self):
        """Chaos: a numerically-identity f32 matmul smuggled past the
        module-level checker must trip the precision pass on the lowered
        program of the declared-bf16 local step."""
        config.set_property("bigdl.chaos.f32Upcast", "true")
        try:
            with pytest.raises(ProgramContractError) as ei:
                _local_trainer(precision="bf16").optimize()
        finally:
            config.clear_property("bigdl.chaos.f32Upcast")
        msg = str(ei.value)
        assert "stablehlo.dot_general" in msg
        assert "step 'local'" in msg
        assert "declared activation dtype is bf16" in msg
        v = [x for x in ei.value.violations
             if x.pass_name == "precision"]
        assert v and v[0].step == "local"

    def test_bf16_local_step_audits_clean_without_chaos(self):
        _local_trainer(precision="bf16").optimize()


# ---------------------------------------------------------------------------
# offline mode: persisted cache audit + baselines
# ---------------------------------------------------------------------------

@pytest.fixture
def cache_dir(tmp_path):
    d = str(tmp_path / "ccache")
    config.set_property("bigdl.compile.cacheDir", d)
    yield d
    config.clear_property("bigdl.compile.cacheDir")


class TestOffline:
    def test_manifest_records_census_and_cli_audits_clean(self, cache_dir,
                                                          capsys):
        """Entries stored while the audit is armed carry the census in
        their manifest; the offline CLI replays the contract check over
        them and exits 0 on a clean cache."""
        _local_trainer().optimize()
        manifests = [f for f in os.listdir(cache_dir)
                     if f.endswith(".json")]
        assert manifests
        with open(os.path.join(cache_dir, manifests[0])) as f:
            audit = json.load(f)["audit"]
        assert audit["label"] == "local"
        assert audit["collective_bytes"] == 0
        assert audit["peak_bytes"] is None or audit["peak_bytes"] > 0
        rc = hlo_audit.main([cache_dir])
        out = capsys.readouterr().out
        assert rc == 0
        assert "[local]" in out and "0 problem(s)" in out

    def test_cli_flags_undeclared_kind_in_persisted_entry(self, tmp_path,
                                                          capsys):
        """A hand-written entry whose census carries a collective its
        step contract never declared fails the offline audit."""
        d = tmp_path / "cc"
        d.mkdir()
        (d / "k1.json").write_text(json.dumps({
            "label": "local",
            "audit": {"label": "local",
                      "by_kind": {"all-gather": {"ops": 2, "bytes": 4096}},
                      "collective_bytes": 4096, "transposes": 0,
                      "rank4_transposes": 0, "f64_ops": 0,
                      "f32_compute_ops": 0, "peak_bytes": 1}}))
        (d / "k1.commit").write_text("")
        rc = hlo_audit.main([str(d)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "VIOLATION" in out and "undeclared all-gather" in out
        assert "step 'local'" in out

    def test_cli_flags_persisted_f64(self, tmp_path, capsys):
        d = tmp_path / "cc"
        d.mkdir()
        (d / "k1.json").write_text(json.dumps({
            "label": "eval",
            "audit": {"label": "eval", "by_kind": {},
                      "collective_bytes": 0, "transposes": 0,
                      "rank4_transposes": 0, "f64_ops": 3,
                      "f32_compute_ops": 0, "peak_bytes": 1}}))
        (d / "k1.commit").write_text("")
        assert hlo_audit.main([str(d)]) == 1
        assert "3 f64 op(s)" in capsys.readouterr().out

    def test_entry_without_census_is_skipped_not_failed(self, tmp_path,
                                                        capsys):
        d = tmp_path / "cc"
        d.mkdir()
        (d / "k1.json").write_text(json.dumps({"label": "local"}))
        (d / "k1.commit").write_text("")
        assert hlo_audit.main([str(d)]) == 0
        assert "no census recorded" in capsys.readouterr().out

    def test_unreadable_dir_fails(self, tmp_path):
        assert hlo_audit.main([str(tmp_path / "nope")]) == 1

    def test_baseline_regression_check(self):
        base = {"collective_bytes": 1000, "rank4_transposes": 1,
                "by_kind": {"all-reduce": {"ops": 1, "bytes": 1000}}}
        ok = {"collective_bytes": 1200, "rank4_transposes": 1,
              "by_kind": {"all-reduce": {"ops": 1, "bytes": 1200}}}
        assert check_against_baseline("s", ok, base) == []
        grown = dict(ok, collective_bytes=99999)
        assert any("regressed past 1.25x" in p
                   for p in check_against_baseline("s", grown, base))
        flipped = dict(ok, rank4_transposes=2)
        assert any("transpose census" in p
                   for p in check_against_baseline("s", flipped, base))
        new_kind = dict(ok, by_kind={"all-reduce": {"ops": 1, "bytes": 1},
                                     "all-to-all": {"ops": 1, "bytes": 1}})
        assert any("new collective kind" in p
                   for p in check_against_baseline("s", new_kind, base))

    def test_baselines_wired_through_cli(self, cache_dir, tmp_path,
                                         capsys):
        _local_trainer().optimize()
        bl = tmp_path / "audit_baselines.json"
        bl.write_text(json.dumps({"steps": {"local": {
            "collective_bytes": 0, "rank4_transposes": 0,
            "by_kind": {}}}}))
        assert hlo_audit.main([cache_dir, "--baselines", str(bl)]) == 0
        capsys.readouterr()
        # sabotage the baseline: any rank-4 transpose is now a regression
        bl.write_text(json.dumps({"steps": {"local": {
            "collective_bytes": 0, "rank4_transposes": -1,
            "by_kind": {}}}}))
        assert hlo_audit.main([cache_dir, "--baselines", str(bl)]) == 1
        assert "transpose census" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# contract registry
# ---------------------------------------------------------------------------

class TestContractRegistry:
    def test_all_step_families_have_default_contracts(self):
        for label in ("local", "local_feval", "shard_map", "gspmd",
                      "pipeline", "eval", "eval_sharded"):
            assert program_contracts.lookup(label) is not None, label

    def test_declare_overrides_default(self):
        c = StepContract(label="local", activation_dtype="bf16")
        program_contracts.declare(c)
        try:
            assert program_contracts.lookup("local") is c
        finally:
            program_contracts.reset()
        assert program_contracts.lookup("local") is not c
