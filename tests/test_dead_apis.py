"""Round-2 'make the dead APIs real' coverage: per-module timings,
TreeNNAccuracy, Nms, the LBFGS trainer path, and mesh-sharded evaluation."""

import jax
import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.engine import Engine
from bigdl_tpu.dataset import LocalDataSet, Sample, SampleToMiniBatch
from bigdl_tpu.dataset.datasets import synthetic_separable
from bigdl_tpu.optim.evaluator import Evaluator, evaluate_dataset
from bigdl_tpu.ops.nms import Nms, nms_mask


class TestModuleTiming:
    def test_forward_backward_times_populate(self):
        m = nn.Linear(4, 3)
        x = np.ones((2, 4), np.float32)
        out = m.forward(x)
        m.backward(x, np.ones_like(np.asarray(out)))
        assert m.forward_time > 0
        assert m.backward_time > 0
        times = m.get_times()
        assert times[0][1] == m.forward_time
        m.reset_times()
        assert m.forward_time == 0 and m.backward_time == 0


class TestTreeNNAccuracy:
    def test_root_node_multiclass(self):
        # (B=2, nodes=3, C=4): root predictions are argmax+1 = 2 and 4
        out = np.zeros((2, 3, 4), np.float32)
        out[0, 0, 1] = 5.0
        out[1, 0, 3] = 5.0
        target = np.array([[2.0, 9, 9], [1.0, 9, 9]])
        r = optim.TreeNNAccuracy().apply(out, target)
        assert r.final_result() == 0.5

    def test_root_node_binary(self):
        out = np.array([[[0.9], [0.1]], [[0.2], [0.8]]], np.float32)
        target = np.array([[1.0, 0.0], [0.0, 1.0]])
        r = optim.TreeNNAccuracy().apply(out, target)
        assert r.final_result() == 1.0

    def test_mergeable(self):
        a = optim.ValidationResult(1, 2, "TreeNNAccuracy")
        b = optim.ValidationResult(1, 2, "TreeNNAccuracy")
        assert (a + b).final_result() == 0.5


class TestNms:
    def test_suppresses_overlapping(self):
        boxes = np.array([[0, 0, 10, 10],
                          [1, 1, 11, 11],      # IoU ~0.68 with box 0
                          [50, 50, 60, 60]], np.float32)
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        keep = np.asarray(nms_mask(boxes, scores, 0.5))
        assert keep.tolist() == [True, False, True]

    def test_reference_call_shape(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11],
                          [50, 50, 60, 60], [0, 0, 9, 9]], np.float32)
        scores = np.array([0.5, 0.9, 0.3, 0.8], np.float32)
        buf = np.zeros(4, np.int64)
        n = Nms().nms(scores, boxes, 0.5, buf)
        assert n == 2
        assert buf[:n].tolist() == [1, 2]   # score order, overlaps suppressed

    def test_under_jit(self):
        boxes = np.random.RandomState(0).uniform(
            0, 100, size=(16, 4)).astype(np.float32)
        boxes[:, 2:] = boxes[:, :2] + 10
        scores = np.random.RandomState(1).uniform(size=16).astype(np.float32)
        keep = jax.jit(nms_mask, static_argnums=2)(boxes, scores, 0.3)
        assert np.asarray(keep).dtype == bool


class TestLBFGSTrainerPath:
    def test_lbfgs_through_optimizer_create(self):
        # weight init draws from the thread-local RandomGenerator, whose
        # state depends on every test that ran before this file — an
        # 8-hidden-unit LBFGS fit converges from most but not all draws,
        # so pin the stream (the test_layout _pin_init_stream pattern)
        # instead of inheriting whatever the suite left behind
        from bigdl_tpu.utils.random_generator import RandomGenerator
        RandomGenerator.RNG().set_seed(5489)
        samples = synthetic_separable(128, 4, n_classes=2, seed=3)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(128))
        model = (nn.Sequential().add(nn.Linear(4, 8)).add(nn.Tanh())
                 .add(nn.Linear(8, 2)).add(nn.LogSoftMax()))
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.LBFGS(max_iter=8))
        opt.set_end_when(optim.max_iteration(4))
        trained = opt.optimize()
        acc = Evaluator(trained).test(
            samples, [optim.Top1Accuracy()], 64)[0][1].final_result()
        assert acc > 0.95, f"LBFGS path failed to converge: acc={acc}"


class TestShardedEval:
    def test_mesh_eval_matches_single_device(self):
        samples = synthetic_separable(128, 4, n_classes=3, seed=5)
        model = (nn.Sequential().add(nn.Linear(4, 8)).add(nn.Tanh())
                 .add(nn.Linear(8, 3)).add(nn.LogSoftMax()))
        model._ensure_init()
        mesh = Engine.create_mesh((8,), ("data",))
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(32))
        single = evaluate_dataset(model, ds, [optim.Top1Accuracy()])
        ds2 = LocalDataSet(samples).transform(SampleToMiniBatch(32))
        sharded = evaluate_dataset(model, ds2, [optim.Top1Accuracy()],
                                   mesh=mesh)
        assert (single[0][1].final_result() ==
                sharded[0][1].final_result())

    def test_indivisible_batch_falls_back(self):
        samples = synthetic_separable(30, 4, n_classes=2, seed=5)
        model = (nn.Sequential().add(nn.Linear(4, 2)).add(nn.LogSoftMax()))
        model._ensure_init()
        mesh = Engine.create_mesh((8,), ("data",))
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(30))
        res = evaluate_dataset(model, ds, [optim.Top1Accuracy()], mesh=mesh)
        assert 0.0 <= res[0][1].final_result() <= 1.0
