"""Tensor-parallel (GSPMD/Megatron) tests on the virtual 8-device mesh.

Beyond-reference capability (the reference is data-parallel only,
SURVEY §2.12): parameters annotated over a ``model`` axis must produce
bit-identical results to replicated execution while physically splitting
the weights 1/n per device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import bigdl_tpu.nn as nn
from bigdl_tpu.engine import Engine
from bigdl_tpu.parallel.tensor_parallel import (column_parallel,
                                                head_count_divisible,
                                                row_parallel,
                                                tp_shard_params, tp_specs)

N_DEV = 8
D, HEADS, FF = 16, 8, 32


def _block(seed=4):
    m = (nn.Sequential()
         .add(nn.MultiHeadAttention(D, HEADS, causal=True))
         .add(column_parallel(nn.Linear(D, FF)))
         .add(nn.ReLU())
         .add(row_parallel(nn.Linear(FF, D))))
    m.reset(jax.random.PRNGKey(seed))
    return m


class TestTensorParallel:
    def test_specs_shape(self):
        m = _block()
        specs = tp_specs(m)
        assert specs[0]["wq"] == P(None, "model")
        assert specs[0]["wo"] == P("model", None)
        assert specs[1]["weight"] == P(None, "model")   # column
        assert specs[3]["weight"] == P("model", None)   # row
        assert specs[3]["bias"] == P()                  # row bias replicated
        assert specs[2] == {}                           # ReLU: no params

    def test_forward_and_grad_parity_with_replicated(self):
        mesh = Engine.create_mesh((N_DEV,), ("model",))
        m = _block()
        head_count_divisible(m, mesh)
        x = jnp.asarray(np.random.RandomState(0)
                        .normal(size=(2, 8, D)).astype(np.float32))

        def loss_fn(p):
            out, _ = m.apply(p, x, m.state, training=False)
            return jnp.sum(out ** 2)

        want_l, want_g = jax.value_and_grad(loss_fn)(m.params)

        tp_params = tp_shard_params(m.params, mesh, tp_specs(m))
        # weights are physically split along the model axis
        wq = tp_params[0]["wq"]
        assert wq.sharding.spec == P(None, "model")
        shard_shapes = {s.data.shape for s in wq.addressable_shards}
        assert shard_shapes == {(D, D // N_DEV)}

        got_l, got_g = jax.jit(jax.value_and_grad(loss_fn))(tp_params)
        np.testing.assert_allclose(float(got_l), float(want_l), rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(got_g),
                        jax.tree_util.tree_leaves(want_g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_training_preserves_shardings_and_converges(self):
        mesh = Engine.create_mesh((N_DEV,), ("model",))
        m = _block(seed=9)
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.normal(size=(8, 4, D)).astype(np.float32))
        # learnable target: a fixed linear map of the input
        w_true = rng.normal(size=(D, D)).astype(np.float32) * 0.3
        y = x @ jnp.asarray(w_true)

        specs = tp_specs(m)
        params = tp_shard_params(m.params, mesh, specs)

        @jax.jit
        def step(p):
            def loss_fn(pp):
                out, _ = m.apply(pp, x, m.state, training=False)
                return jnp.mean((out - y) ** 2)
            loss, g = jax.value_and_grad(loss_fn)(p)
            new_p = jax.tree_util.tree_map(lambda w, gw: w - 0.5 * gw, p, g)
            return new_p, loss

        losses = []
        for _ in range(40):
            params, loss = step(params)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.7, losses
        assert all(b < a * 1.001 for a, b in zip(losses, losses[1:])), losses
        # the update must not silently gather weights onto one device
        # (specs may normalize away trailing Nones — compare semantically)
        assert params[0]["wq"].sharding.is_equivalent_to(
            NamedSharding(mesh, P(None, "model")), 2)
        assert params[3]["weight"].sharding.is_equivalent_to(
            NamedSharding(mesh, P("model", None)), 2)

    def test_head_divisibility_guard(self):
        mesh = Engine.create_mesh((N_DEV,), ("model",))
        m = nn.Sequential().add(nn.MultiHeadAttention(12, 3))
        m._ensure_init()
        with pytest.raises(ValueError, match="divisible"):
            head_count_divisible(m, mesh)
        # the documented path (tp_specs with mesh=) runs the guard itself
        with pytest.raises(ValueError, match="divisible"):
            tp_specs(m, mesh=mesh)

    def test_bottle_wrapped_mha_gets_split_specs(self):
        m = nn.Sequential().add(
            nn.Bottle(nn.MultiHeadAttention(D, HEADS), 3, 3))
        m._ensure_init()
        specs = tp_specs(m)
        assert specs[0][0]["wq"] == P(None, "model")

    def test_unknown_composite_hiding_tp_module_raises(self):
        class Opaque(nn.Module):
            def __init__(self, inner):
                super().__init__()
                self.inner = inner

            def _init_params(self, rng):
                return {"nested": self.inner._init_params(rng)}

            def modules(self):
                return [self] + self.inner.modules()

            def apply(self, params, input, state, training=False, rng=None):
                return self.inner.apply(params["nested"], input, state,
                                        training=training, rng=rng)

        m = nn.Sequential().add(Opaque(nn.MultiHeadAttention(D, HEADS)))
        m._ensure_init()
        # better a hard error than a silently replicated attention
        with pytest.raises(ValueError, match="nested inside composites"):
            tp_specs(m)

    def test_flash_mha_rejected(self):
        m = nn.Sequential().add(nn.MultiHeadAttention(D, HEADS, flash=True))
        m._ensure_init()
        with pytest.raises(ValueError, match="flash"):
            tp_specs(m)
