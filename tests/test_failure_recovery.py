"""Failure recovery and checkpoint/resume.

Reference analogs: the retry-from-snapshot loop
(``optim/DistriOptimizer.scala:750-816``) and the fault-injection test style
(``optim/DistriOptimizerSpec.scala:89-99`` — a model that throws on
schedule).  Injection here is host-side (a transformer that fails once at a
given batch) because under jit the module Python only runs at trace time.
"""

import os

import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import LocalDataSet, SampleToMiniBatch
from bigdl_tpu.dataset.dataset import ShardedDataSet
from bigdl_tpu.dataset.datasets import synthetic_separable
from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.optim.evaluator import Evaluator
from bigdl_tpu.utils import config, file_io


class FailOnce(Transformer):
    """Raises on the k-th batch it sees, once — a transient node failure."""

    def __init__(self, fail_at: int):
        self.fail_at = fail_at
        self.seen = 0
        self.tripped = False

    def __call__(self, it):
        for batch in it:
            self.seen += 1
            if self.seen == self.fail_at and not self.tripped:
                self.tripped = True
                raise RuntimeError("injected failure (simulated node loss)")
            yield batch


def _mlp(din, nclass, seed=5):
    import jax
    m = (nn.Sequential().add(nn.Linear(din, 16)).add(nn.Tanh())
         .add(nn.Linear(16, nclass)).add(nn.LogSoftMax()))
    m.reset(jax.random.PRNGKey(seed))
    return m


@pytest.fixture(autouse=True)
def _fast_retry():
    config.set_property("bigdl.failure.retryTimeInterval", 0.0)
    yield
    config.clear_property("bigdl.failure.retryTimeInterval")


class TestRetryFromCheckpoint:
    def test_recovers_from_injected_failure(self, tmp_path):
        samples = synthetic_separable(128, 4, n_classes=2, seed=3)
        injector = FailOnce(fail_at=9)
        ds = (LocalDataSet(samples).transform(SampleToMiniBatch(32))
              .transform(injector))
        model = _mlp(4, 2)
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.5))
        opt.set_end_when(optim.max_epoch(8))
        opt.set_checkpoint(str(tmp_path / "ckpt"),
                           optim.several_iteration(2))
        trained = opt.optimize()

        assert injector.tripped, "injection never fired"
        acc = Evaluator(trained).test(
            samples, [optim.Top1Accuracy()], 32)[0][1].final_result()
        assert acc > 0.9, f"training did not recover from failure: acc={acc}"
        # counters continued rather than restarting from scratch
        assert opt.optim_method.state["evalCounter"] >= 8 * 4 - 2

    def test_gives_up_after_retry_budget(self, tmp_path):
        class AlwaysFail(Transformer):
            def __call__(self, it):
                for _ in it:
                    raise RuntimeError("permanent failure")
                yield  # pragma: no cover

        samples = synthetic_separable(64, 4, n_classes=2)
        ds = (LocalDataSet(samples).transform(SampleToMiniBatch(32))
              .transform(AlwaysFail()))
        opt = optim.Optimizer.create(_mlp(4, 2), ds, nn.ClassNLLCriterion())
        opt.set_end_when(optim.max_epoch(2))
        config.set_property("bigdl.failure.retryTimes", 3)
        try:
            with pytest.raises(RuntimeError, match="permanent failure"):
                opt.optimize()
        finally:
            config.clear_property("bigdl.failure.retryTimes")

    def test_argument_errors_not_retried(self):
        """The reference aborts immediately on IllegalArgumentException."""
        samples = synthetic_separable(64, 4, n_classes=2)
        ds = ShardedDataSet(samples, 4).transform(SampleToMiniBatch(32, 4))
        from bigdl_tpu.parallel import DistriOptimizer
        opt = DistriOptimizer(_mlp(4, 2), ds, nn.ClassNLLCriterion())
        with pytest.raises(ValueError, match="must match"):
            opt.optimize()  # mesh/partition mismatch: no retry loop


class TestSnapshotPairing:
    def test_latest_requires_model_optim_pair(self, tmp_path):
        """A crash between the ``model.N`` and ``optimMethod.N`` saves
        leaves a model-only snapshot: ``latest()`` must skip it and hand
        back the newest COMPLETE pair (regression — the old scan keyed on
        ``model.*`` alone and restore crashed on the missing optim)."""
        from bigdl_tpu.optim.optimizer import Checkpoint
        ckpt = Checkpoint(str(tmp_path), optim.every_epoch())
        ckpt.save(_mlp(4, 2), optim.SGD(learning_rate=0.1), 3)
        file_io.save(_mlp(4, 2), str(tmp_path / "model.7"))  # torn: no pair
        model_path, optim_path, n = ckpt.latest()
        assert n == 3
        assert model_path.endswith("model.3")
        # both halves load
        file_io.load(model_path)
        assert file_io.load(optim_path).state is not None

    def test_restore_falls_back_past_unloadable_snapshot(self, tmp_path):
        """``file_io.load`` failing on the newest snapshot must not kill
        the retry loop: restore walks to the next-older snapshot."""
        from bigdl_tpu.optim.optimizer import Checkpoint
        samples = synthetic_separable(64, 4, n_classes=2)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(32))
        opt = optim.Optimizer.create(_mlp(4, 2), ds, nn.ClassNLLCriterion())
        method = optim.SGD(learning_rate=0.1)
        method.state["evalCounter"] = 3
        opt.set_checkpoint(str(tmp_path), optim.every_epoch())
        opt.checkpoint.save(opt.model, method, 3)
        # newest snapshot: a complete legacy pair whose model pickle is
        # garbage (no manifest, so only the unpickler can catch it)
        (tmp_path / "model.9").write_bytes(b"\x80\x04 not a pickle")
        file_io.save(optim.SGD(learning_rate=0.1), str(tmp_path /
                                                       "optimMethod.9"))
        assert opt._restore_latest_checkpoint()
        assert opt.optim_method.state["evalCounter"] == 3


class TestRetryBackoff:
    def test_capped_exponential_with_jitter(self):
        from bigdl_tpu.optim.optimizer import _retry_backoff
        # jitter pinned at 1.0: pure capped exponential
        assert _retry_backoff(1, 2.0, 8.0, rand=1.0) == 2.0
        assert _retry_backoff(2, 2.0, 8.0, rand=1.0) == 4.0
        assert _retry_backoff(3, 2.0, 8.0, rand=1.0) == 8.0
        assert _retry_backoff(9, 2.0, 8.0, rand=1.0) == 8.0   # capped
        # a cap BELOW the base wins (operator asked for fast retries)...
        assert _retry_backoff(3, 120.0, 30.0, rand=1.0) == 30.0
        # ...and a non-positive cap means uncapped
        assert _retry_backoff(5, 2.0, 0.0, rand=1.0) == 32.0
        # jitter floor is half the interval
        assert _retry_backoff(3, 2.0, 8.0, rand=0.0) == 4.0
        # a zero base (the test fixture's config) never sleeps
        assert _retry_backoff(5, 0.0, 900.0) == 0.0
        # random jitter stays within [0.5, 1.0] x interval
        for _ in range(20):
            v = _retry_backoff(2, 2.0, 8.0)
            assert 2.0 <= v <= 4.0

    def test_sleeps_follow_backoff_with_patched_clock(self, tmp_path):
        """No real sleeping in tier-1: the retry loop's waits go through
        the injectable ``optimizer._sleep`` and must grow exponentially
        up to the cap."""
        from bigdl_tpu.optim import optimizer as optimizer_mod

        class AlwaysFail(Transformer):
            def __call__(self, it):
                for _ in it:
                    raise RuntimeError("permanent failure")
                yield  # pragma: no cover

        samples = synthetic_separable(64, 4, n_classes=2)
        ds = (LocalDataSet(samples).transform(SampleToMiniBatch(32))
              .transform(AlwaysFail()))
        opt = optim.Optimizer.create(_mlp(4, 2), ds, nn.ClassNLLCriterion())
        opt.set_end_when(optim.max_epoch(2))
        config.set_property("bigdl.failure.retryTimes", 4)
        config.set_property("bigdl.failure.retryTimeInterval", 2.0)
        config.set_property("bigdl.failure.maxRetryInterval", 4.0)
        slept = []
        orig = optimizer_mod._sleep
        optimizer_mod._sleep = slept.append
        try:
            with pytest.raises(RuntimeError, match="permanent failure"):
                opt.optimize()
        finally:
            optimizer_mod._sleep = orig
            for k in ("bigdl.failure.retryTimes",
                      "bigdl.failure.maxRetryInterval"):
                config.clear_property(k)
        # 4 attempts -> 3 waits; attempt a waits in
        # [0.5, 1.0] x min(2*2^(a-1), 4)
        assert len(slept) == 3, slept
        assert 1.0 <= slept[0] <= 2.0
        assert 2.0 <= slept[1] <= 4.0
        assert 2.0 <= slept[2] <= 4.0   # capped at maxRetryInterval

    def test_attempt_counter_resets_on_progress(self, tmp_path):
        """Mirrors the reference's retryNum reset: failures separated by
        real training progress must each start a fresh attempt budget —
        three spaced failures survive a retryTimes=2 budget that two
        back-to-back failures would exhaust."""

        class FailEvery(Transformer):
            """Trips once at each configured batch count."""

            def __init__(self, fail_ats):
                self.fail_ats = set(fail_ats)
                self.seen = 0
                self.trips = 0

            def __call__(self, it):
                for batch in it:
                    self.seen += 1
                    if self.seen in self.fail_ats:
                        self.fail_ats.discard(self.seen)
                        self.trips += 1
                        raise RuntimeError("injected repeated failure")
                    yield batch

        samples = synthetic_separable(128, 4, n_classes=2, seed=3)
        injector = FailEvery([6, 12, 18])
        ds = (LocalDataSet(samples).transform(SampleToMiniBatch(32))
              .transform(injector))
        opt = optim.Optimizer.create(_mlp(4, 2), ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.5))
        opt.set_end_when(optim.max_epoch(8))
        opt.set_checkpoint(str(tmp_path / "ckpt"),
                           optim.several_iteration(2))
        config.set_property("bigdl.failure.retryTimes", 2)
        try:
            trained = opt.optimize()
        finally:
            config.clear_property("bigdl.failure.retryTimes")
        assert injector.trips == 3, "not every failure fired"
        acc = Evaluator(trained).test(
            samples, [optim.Top1Accuracy()], 32)[0][1].final_result()
        assert acc > 0.9, f"training did not recover: acc={acc}"


class TestKillAndResume:
    def test_resumed_run_matches_uninterrupted(self, tmp_path):
        """Train 2 epochs + checkpoint, 'kill', resume from snapshot for 2
        more — final weights match an uninterrupted 4-epoch run exactly
        (shuffles disabled via fixed index order: LocalDataSet shuffles use
        the global RNG, so both runs see identical batch order per epoch)."""
        from bigdl_tpu.utils.random_generator import RandomGenerator

        samples = synthetic_separable(128, 4, n_classes=2, seed=7)

        def fresh_ds():
            return LocalDataSet(samples).transform(SampleToMiniBatch(128))

        # uninterrupted 4 epochs (full-batch: order-independent)
        model_a = _mlp(4, 2, seed=11)
        opt_a = optim.Optimizer.create(model_a, fresh_ds(),
                                       nn.ClassNLLCriterion())
        opt_a.set_optim_method(optim.SGD(learning_rate=0.3, momentum=0.9))
        opt_a.set_end_when(optim.max_epoch(4))
        opt_a.optimize()
        w_a, _ = model_a.get_parameters()

        # interrupted: 2 epochs, checkpoint, then resume in a NEW optimizer
        model_b = _mlp(4, 2, seed=11)
        opt_b = optim.Optimizer.create(model_b, fresh_ds(),
                                       nn.ClassNLLCriterion())
        opt_b.set_optim_method(optim.SGD(learning_rate=0.3, momentum=0.9))
        opt_b.set_end_when(optim.max_epoch(2))
        opt_b.set_checkpoint(str(tmp_path / "ckpt"), optim.every_epoch())
        opt_b.optimize()

        latest = opt_b.checkpoint.latest()
        assert latest is not None
        model_c = file_io.load(latest[0])
        optim_c = optim.OptimMethod.load(latest[1])
        assert optim_c.state["epoch"] >= 2

        opt_c = optim.Optimizer.create(model_c, fresh_ds(),
                                       nn.ClassNLLCriterion())
        opt_c.set_optim_method(optim_c)
        opt_c.set_end_when(optim.max_epoch(4))
        trained = opt_c.optimize()
        w_c, _ = trained.get_parameters()

        np.testing.assert_allclose(np.asarray(w_c), np.asarray(w_a),
                                   rtol=1e-4, atol=1e-6)


class TestRemoteCheckpointIntegration:
    """Integration-grade remote persistence (reference tags real-HDFS/S3
    integration suites, ``integration/HdfsSpec.scala``): the FULL
    train -> checkpoint -> crash -> retry-from-snapshot cycle against a
    remote fsspec filesystem (memory:// — the scheme this image can
    actually host; hdfs://, s3://, gs:// route through the identical
    code path, differing only in the installed client)."""

    def _clean(self):
        import fsspec
        fs = fsspec.filesystem("memory")
        if fs.exists("/bigdl_it"):
            fs.rm("/bigdl_it", recursive=True)

    def test_checkpoint_roundtrip_over_remote_scheme(self):
        self._clean()
        samples = synthetic_separable(128, 4, n_classes=2, seed=3)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(32))
        model = _mlp(4, 2)
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.5))
        opt.set_end_when(optim.max_epoch(3))
        opt.set_checkpoint("memory://bigdl_it/ckpt", optim.every_epoch())
        trained = opt.optimize()

        latest = opt.checkpoint.latest()
        assert latest is not None
        model_path, optim_path, n = latest
        assert model_path.startswith("memory://")
        reloaded = file_io.load(model_path)
        x = np.stack([s.feature for s in samples[:16]])
        np.testing.assert_allclose(
            np.asarray(reloaded.evaluate().forward(x)),
            np.asarray(trained.evaluate().forward(x)),
            rtol=1e-6)
        # optim snapshot round-trips with its counters
        ro = file_io.load(optim_path)
        assert ro.state["evalCounter"] > 0

    def test_retry_restores_from_remote_snapshot(self):
        self._clean()
        samples = synthetic_separable(128, 4, n_classes=2, seed=3)
        injector = FailOnce(fail_at=9)
        ds = (LocalDataSet(samples).transform(SampleToMiniBatch(32))
              .transform(injector))
        model = _mlp(4, 2)
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.5))
        opt.set_end_when(optim.max_epoch(8))
        opt.set_checkpoint("memory://bigdl_it/retry",
                           optim.several_iteration(2))
        trained = opt.optimize()
        assert injector.tripped
        acc = Evaluator(trained).test(
            samples, [optim.Top1Accuracy()], 32)[0][1].final_result()
        assert acc > 0.9, f"remote-checkpoint recovery failed: acc={acc}"

    def test_overwrite_false_guard_applies_remotely(self):
        self._clean()
        file_io.save({"v": 1}, "memory://bigdl_it/obj")
        with pytest.raises(FileExistsError):
            file_io.save({"v": 2}, "memory://bigdl_it/obj",
                         overwrite=False)
        assert file_io.load("memory://bigdl_it/obj")["v"] == 1

    def test_temp_sweep_is_age_gated(self, tmp_path):
        """Checkpoint.save must not reclaim a RECENT foreign temp (it may
        be another live writer's in-flight atomic write); an hour-old
        orphan from a hard-killed writer IS swept."""
        import time
        from bigdl_tpu.optim.optimizer import Checkpoint
        ckpt = Checkpoint(str(tmp_path), optim.every_epoch())
        fresh = tmp_path / "model.9.tmp_bigdl.4242.deadbeef"
        stale = tmp_path / "model.8.tmp_bigdl.4243.cafebabe"
        fresh.write_bytes(b"live writer in flight")
        stale.write_bytes(b"orphan")
        old = time.time() - Checkpoint.TEMP_SWEEP_AGE_S - 60
        os.utime(stale, (old, old))
        ckpt.save(_mlp(4, 2), optim.SGD(learning_rate=0.1), 1)
        assert fresh.exists(), "recent foreign temp was swept"
        assert not stale.exists(), "hour-old orphan survived the sweep"
        # and neither ever pollutes latest()
        _, _, n = ckpt.latest()
        assert n == 1

    def test_remote_temp_sweep_is_age_gated(self):
        """The age gate must work through the fsspec modified() branch of
        file_io.modified_time, not just local getmtime: a backdated
        memory:// orphan is swept, a fresh one survives."""
        import datetime
        import fsspec
        from bigdl_tpu.optim.optimizer import Checkpoint
        self._clean()
        root = "memory://bigdl_it/sweep"
        ckpt = Checkpoint(root, optim.every_epoch())
        fs = fsspec.filesystem("memory")
        fs.makedirs("/bigdl_it/sweep", exist_ok=True)
        for name in ("model.9.tmp_bigdl.77.aa", "model.8.tmp_bigdl.78.bb"):
            with fs.open(f"/bigdl_it/sweep/{name}", "wb") as f:
                f.write(b"x")
        fs.store["/bigdl_it/sweep/model.8.tmp_bigdl.78.bb"].modified = (
            datetime.datetime.now(datetime.timezone.utc)
            - datetime.timedelta(seconds=Checkpoint.TEMP_SWEEP_AGE_S + 60))
        assert file_io.modified_time(
            root + "/model.8.tmp_bigdl.78.bb") is not None
        ckpt.save(_mlp(4, 2), optim.SGD(learning_rate=0.1), 1)
        names = file_io.listdir(root)
        assert "model.9.tmp_bigdl.77.aa" in names, names
        assert "model.8.tmp_bigdl.78.bb" not in names, names
        _, _, n = ckpt.latest()
        assert n == 1

    def test_partial_remote_write_never_selected_as_latest(self):
        """Atomic remote saves: a crashed in-flight temp must neither be
        picked by latest() nor survive as a final object."""
        import fsspec
        from bigdl_tpu.optim.optimizer import Checkpoint
        self._clean()
        ckpt = Checkpoint("memory://bigdl_it/atomic", optim.every_epoch())
        m = _mlp(4, 2)
        ckpt.save(m, optim.SGD(learning_rate=0.1), 3)
        # simulate a crash mid-write of snapshot 7
        fs = fsspec.filesystem("memory")
        with fs.open("/bigdl_it/atomic/model.7.tmp_bigdl", "wb") as f:
            f.write(b"truncated")
        model_path, _, n = ckpt.latest()
        assert n == 3 and model_path.endswith("model.3")
        reloaded = file_io.load(model_path)
        x = np.zeros((1, 4), np.float32)
        assert np.asarray(reloaded.forward(x)).shape == (1, 2)
