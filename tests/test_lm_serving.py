"""LM token serving: continuous batching over a paged KV cache.

The claims under test (ISSUE 18 acceptance criteria): the paged pool's
three invariants (structured exhaustion — never OOM; dump block never
allocated; freed blocks zero-scrubbed, bit-asserted); the paged decode
path's parity with a teacher-forced full forward (greedy tokens
bit-identical, per-position log-probs allclose); iteration-level
batching legible in the decode-step/token ratio with zero post-warmup
retraces across prefill AND decode; per-request streaming with
partially-streamed-then-failed as a first-class outcome; the chaos trio
(``poisonPromptAt`` / ``hangDecodeAt`` / ``evictBlockAt``) and the
combined-chaos accounting identity ``completed + shed + rejected +
quarantined == submitted``, exact; and the int8 decode tier's admission
gate (auditor precision pass + fp-vs-int8 logits allclose — either
failing refuses to serve quantized).
"""

import os
import re
import time

import numpy as np
import pytest

import jax

import bigdl_tpu.nn as nn
from bigdl_tpu.models.transformer import transformer_lm
from bigdl_tpu.serving import (LMServingEngine, Overloaded, PagedKVCache,
                               QuantizationGateError, UnsupportedModelError,
                               run_lm_open_loop, sample_lm_workload)
from bigdl_tpu.serving.engine import (DeadlineExceeded, HungDispatchError,
                                      OUTCOMES, ServingDataError,
                                      ServingInfraError)
from bigdl_tpu.serving.kv_cache import DUMP_BLOCK
from bigdl_tpu.utils import chaos, config, elastic

VOCAB = 32

_LM_KEYS = (
    "bigdl.analysis.retrace", "bigdl.lm.stallFactor",
    "bigdl.lm.warmupSteps", "bigdl.lm.quantizeRtol",
    "bigdl.lm.quantizeAtol", "bigdl.lm.prefillBuckets",
    "bigdl.chaos.poisonPromptAt", "bigdl.chaos.hangDecodeAt",
    "bigdl.chaos.evictBlockAt", "bigdl.chaos.burstArrivals",
)


@pytest.fixture(autouse=True)
def _lm_env():
    """Disarmed chaos, cleared preemption, clean knobs around every
    test."""
    elastic.clear_preemption()
    yield
    chaos.uninstall()
    elastic.clear_preemption()
    for k in _LM_KEYS:
        config.clear_property(k)


def _model(seed=3, vocab=VOCAB, **kw):
    kw.setdefault("d_model", 16)
    kw.setdefault("n_head", 2)
    kw.setdefault("n_layers", 2)
    kw.setdefault("max_len", 64)
    m = transformer_lm(vocab, **kw)
    m.reset(jax.random.PRNGKey(seed))
    return m


def _engine(model=None, warm=True, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_context", 32)
    kw.setdefault("block_size", 4)
    kw.setdefault("deadline_ms", 30000.0)
    eng = LMServingEngine(model if model is not None else _model(), **kw)
    if warm:
        eng.warmup()
    return eng


def _prompt(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(1, VOCAB + 1, size=n).astype(np.int32)


def _assert_identity(stats_or_rec):
    assert stats_or_rec["unaccounted"] == 0, stats_or_rec
    total = sum(stats_or_rec[o] for o in OUTCOMES)
    assert total == stats_or_rec["submitted"], stats_or_rec


def _zero_retraces(eng):
    retr = {label: s.retraces for label, s in eng.sentinels.items()}
    assert retr and all(v == 0 for v in retr.values()), \
        f"post-warmup retraces: {retr}"


# ---------------------------------------------------------------------------
# paged KV cache invariants
# ---------------------------------------------------------------------------

class TestPagedKVCache:
    def test_exhaustion_is_structured_overloaded_never_oom(self):
        cache = PagedKVCache(2, 2, 8, n_blocks=4, block_size=4)
        cache.allocate(1, 12)                     # 3 blocks = the pool
        with pytest.raises(Overloaded) as ei:
            cache.allocate(2, 8)                  # needs 2, 0 free
        assert ei.value.retriable
        assert ei.value.blocks_needed == 2 and ei.value.blocks_free == 0
        cache.free_seq(1)
        assert cache.can_allocate(8)              # retriable for real

    def test_dump_block_never_allocated(self):
        cache = PagedKVCache(2, 2, 8, n_blocks=5, block_size=4)
        blocks = cache.allocate(1, 16)            # the whole free-list
        assert DUMP_BLOCK not in blocks
        assert sorted(blocks) == [1, 2, 3, 4]

    def test_block_reuse_is_zero_initialized_bitwise(self):
        cache = PagedKVCache(2, 2, 8, n_blocks=5, block_size=4)
        blocks = cache.allocate(7, 10)
        # simulate a decode having written k/v into the blocks
        cache.k = cache.k.at[:, np.array(blocks)].set(1.5)
        cache.v = cache.v.at[:, np.array(blocks)].set(-2.25)
        assert float(np.abs(np.asarray(cache.k[:, blocks])).max()) > 0
        cache.free_seq(7)
        # the scrub is the no-cross-request-leakage proof: bit-exact zero
        assert (np.asarray(cache.k[:, blocks]) == 0).all()
        assert (np.asarray(cache.v[:, blocks]) == 0).all()
        again = cache.allocate(8, 10)
        assert sorted(again) == sorted(blocks)    # same ids, clean bits

    def test_double_allocate_and_idempotent_free(self):
        cache = PagedKVCache(1, 1, 4, n_blocks=3, block_size=2)
        cache.allocate(1, 2)
        with pytest.raises(ValueError, match="already holds"):
            cache.allocate(1, 2)
        assert cache.free_seq(1) == 1
        assert cache.free_seq(1) == 0             # idempotent

    def test_pool_needs_room_beyond_the_dump_block(self):
        with pytest.raises(ValueError, match="dump block"):
            PagedKVCache(1, 1, 4, n_blocks=1, block_size=2)


# ---------------------------------------------------------------------------
# engine construction / validation
# ---------------------------------------------------------------------------

class TestEngineValidation:
    def test_non_lm_model_is_refused_structurally(self):
        m = (nn.Sequential().add(nn.Linear(4, 8)).add(nn.Tanh())
             .add(nn.Linear(8, 3)))
        m.reset(jax.random.PRNGKey(0))
        with pytest.raises(UnsupportedModelError,
                           match="transformer_lm-shaped"):
            LMServingEngine(m)

    def test_max_context_beyond_position_table_is_refused(self):
        with pytest.raises(ValueError, match="PositionalEncoding"):
            LMServingEngine(_model(max_len=64), max_context=128)

    def test_never_fits_prompt_rejected_at_the_door(self):
        # pool of 3 allocatable blocks x 4 slots = 12 tokens max
        eng = _engine(warm=False, cache_blocks=4)
        with pytest.raises(Overloaded, match="kv blocks exhausted"):
            eng.submit(_prompt(8), max_new_tokens=8)     # 16 > 12
        eng.close()
        _assert_identity(eng.stats())

    def test_over_context_prompt_is_quarantined(self):
        with _engine() as eng:
            eng.start()
            s = eng.submit(_prompt(30), max_new_tokens=8)   # 38 > 32
            with pytest.raises(ServingDataError, match="maxContext"):
                s.result(timeout=10)
            assert s.outcome == "quarantined"
            stats = eng.stats()
        _assert_identity(stats)


# ---------------------------------------------------------------------------
# decode-vs-full-forward parity (the paged-path correctness proof)
# ---------------------------------------------------------------------------

class TestDecodeParity:
    def test_greedy_tokens_bit_identical_logps_allclose(self):
        eng = _engine()
        toks_paged, lp_paged = eng.generate(
            _prompt(9, seed=5), max_new_tokens=12, return_logps=True)
        toks_full, lp_full = eng.generate_sequential(
            _prompt(9, seed=5), max_new_tokens=12, return_logps=True)
        assert toks_paged == toks_full          # greedy: bit-identical
        # paged logps cover generated tokens 2..N (the prefill's first
        # token has no decode row); sequential covers 1..N
        assert len(lp_paged) == len(lp_full) - 1
        for a, b in zip(lp_paged, lp_full[1:]):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)
        eng.close()

    def test_mixed_prompt_lengths_share_one_decode_shape(self):
        eng = _engine()
        for n in (1, 3, 8, 17):
            assert eng.generate(_prompt(n, seed=n), max_new_tokens=4) == \
                eng.generate_sequential(_prompt(n, seed=n),
                                        max_new_tokens=4)
        _zero_retraces(eng)
        eng.close()

    def test_generate_refused_while_scheduler_runs(self):
        with _engine() as eng:
            eng.start()
            with pytest.raises(ServingInfraError, match="offline"):
                eng.generate(_prompt(4))


# ---------------------------------------------------------------------------
# streaming + continuous batching
# ---------------------------------------------------------------------------

class TestStreamingScheduler:
    def test_stream_iterates_tokens_and_completes(self):
        with _engine() as eng:
            eng.start()
            s = eng.submit(_prompt(6), max_new_tokens=6)
            got = list(s)
            assert got == s.result(timeout=10) and len(got) == 6
            assert s.outcome == "completed"
            assert s.ttft_ms() > 0 and s.latency_ms() >= s.ttft_ms()
            stats = eng.stats()
        _assert_identity(stats)

    def test_eos_finishes_early(self):
        with _engine() as eng:
            eng.start()
            probe = eng.submit(_prompt(6, seed=2), max_new_tokens=8)
            toks = probe.result(timeout=10)
            s = eng.submit(_prompt(6, seed=2), max_new_tokens=8,
                           eos_id=toks[2])
            assert s.result(timeout=10) == toks[:3]
            assert s.outcome == "completed"

    def test_iteration_level_batching_shares_decode_steps(self):
        config.set_property("bigdl.analysis.retrace", "strict")
        with _engine() as eng:
            eng.start()
            streams = [eng.submit(_prompt(5, seed=i), max_new_tokens=8)
                       for i in range(8)]
            outs = [s.result(timeout=30) for s in streams]
            assert all(len(o) == 8 for o in outs)
            stats = eng.stats()
            # offline per-sequence decode would pay tokens - prefills
            # steps; continuous batching must share iterations
            decode_token_steps = stats["tokens_out"] - stats["prefills"]
            assert stats["decode_steps"] < decode_token_steps, stats
            # completions match the offline paged path bit-exactly
            _zero_retraces(eng)
        _assert_identity(stats)
        ref = _engine()
        for i, o in enumerate(outs):
            assert o == ref.generate(_prompt(5, seed=i), max_new_tokens=8)
        ref.close()

    def test_blocks_free_after_drain(self):
        with _engine() as eng:
            eng.start()
            for i in range(6):
                eng.submit(_prompt(4, seed=i), max_new_tokens=4)
            eng.stop()
            assert eng.cache.used_blocks == 0
            _assert_identity(eng.stats())

    def test_deadline_sheds_after_streamed_prefix(self):
        """Partially-streamed-then-failed is a first-class outcome: the
        deadline check runs AFTER the iteration's emit, so the client
        keeps the prefix and the terminal error is structured."""
        config.set_property("bigdl.chaos.hangDecodeAt", "2:0.6")
        chaos.install()
        with _engine() as eng:
            eng.start()
            s = eng.submit(_prompt(5), max_new_tokens=10, deadline_ms=250.0)
            got = []
            with pytest.raises(DeadlineExceeded):
                for tok in s:
                    got.append(tok)
            assert s.outcome == "shed"
            assert len(got) >= 1                 # the streamed prefix
            assert got == s.tokens()             # still readable
            stats = eng.stats()
        _assert_identity(stats)


# ---------------------------------------------------------------------------
# chaos trio + combined-plan identity
# ---------------------------------------------------------------------------

class TestLMChaos:
    def test_poison_prompt_quarantined_alone(self):
        config.set_property("bigdl.chaos.poisonPromptAt", "1")
        chaos.install()
        with _engine() as eng:
            eng.start()
            streams = [eng.submit(_prompt(4, seed=i), max_new_tokens=4)
                       for i in range(3)]
            assert len(streams[0].result(timeout=10)) == 4
            assert len(streams[2].result(timeout=10)) == 4
            with pytest.raises(ServingDataError, match="poison prompt"):
                streams[1].result(timeout=10)
            assert streams[1].outcome == "quarantined"
            stats = eng.stats()
        _assert_identity(stats)
        assert stats["completed"] == 2 and stats["quarantined"] == 1

    def test_evicted_block_sheds_one_sequence_retriably(self):
        config.set_property("bigdl.chaos.evictBlockAt", 2)
        chaos.install()
        with _engine() as eng:
            eng.start()
            a = eng.submit(_prompt(4, seed=0), max_new_tokens=6)
            b = eng.submit(_prompt(4, seed=1), max_new_tokens=6)
            outcomes = {}
            for s in (a, b):
                try:
                    s.result(timeout=10)
                except ServingInfraError as e:
                    assert "evicted" in str(e) and "retriable" in str(e)
                outcomes[s.index] = s.outcome
            assert sorted(outcomes.values()) == ["completed", "shed"]
            victim = a if a.outcome == "shed" else b
            assert len(victim.tokens()) >= 1     # prefix intact
            stats = eng.stats()
        _assert_identity(stats)

    def test_hung_decode_watchdog_aborts_and_cools_down(self):
        # 20x the ~1 ms decode EMA ≈ a 25 ms threshold: far above CI
        # scheduling jitter, still 100x under the injected 3 s wedge
        config.set_property("bigdl.lm.stallFactor", 20.0)
        config.set_property("bigdl.lm.warmupSteps", 2)
        config.set_property("bigdl.chaos.hangDecodeAt", "8:3.0")
        chaos.install()
        with _engine() as eng:
            eng.start()
            # decode steps 1..7 complete a clean stream and seed the EMA
            assert len(eng.submit(_prompt(4), max_new_tokens=8)
                       .result(timeout=30)) == 8
            t0 = time.monotonic()
            victim = eng.submit(_prompt(4, seed=1), max_new_tokens=8)
            with pytest.raises(HungDispatchError, match="wedged past"):
                victim.result(timeout=30)
            assert victim.outcome == "shed"
            assert time.monotonic() - t0 < 3.0, \
                "the abort must land well before the 3 s wedge expires"
            # cooldown clears once the backlog is empty; it re-serves
            deadline = time.monotonic() + 10
            while True:
                try:
                    h = eng.submit(_prompt(4, seed=2), max_new_tokens=4)
                    break
                except Overloaded as e:
                    assert e.reason == "cooldown", e
                    assert time.monotonic() < deadline
                    time.sleep(0.02)
            assert len(h.result(timeout=30)) == 4
            stats = eng.stats()
        _assert_identity(stats)

    def test_abort_mid_admission_cannot_strand_a_stream(self):
        """The watchdog abort is delivered asynchronously and can land
        while a stream sits in ``_admitting`` — popped from the queue,
        not yet slotted.  The shed sweep must account it (regression:
        a stranded stream held the accounting identity open forever)."""
        eng = _engine(warm=False)
        s = eng.submit(_prompt(4), max_new_tokens=4)
        eng._admitting = eng._q.get_nowait()
        assert eng._admitting is s
        eng._shed_active(HungDispatchError("injected mid-admission"),
                         "hung_decode")
        assert eng._admitting is None
        assert s.outcome == "shed"
        with pytest.raises(HungDispatchError):
            s.result(timeout=1)
        eng.close()
        _assert_identity(eng.stats())

    def test_combined_chaos_identity_exact(self):
        """The ISSUE-18 combined plan: poison prompt + hung decode +
        block eviction in ONE open-loop load.  Every submitted stream
        lands in exactly one outcome bucket — including sequences that
        streamed a prefix and then failed."""
        config.set_property("bigdl.lm.stallFactor", 20.0)
        config.set_property("bigdl.lm.warmupSteps", 2)
        config.set_property("bigdl.chaos.poisonPromptAt", "2")
        config.set_property("bigdl.chaos.evictBlockAt", 6)
        config.set_property("bigdl.chaos.hangDecodeAt", "20:3.0")
        chaos.install()
        reqs = sample_lm_workload(12, VOCAB, seed=9,
                                  prompt_lens=(4, 6, 8),
                                  output_lens=(4, 6, 8))
        with _engine() as eng:
            eng.start()
            rec = run_lm_open_loop(eng, reqs, rate_hz=200.0, seed=4)
            stats = eng.stats()
        _assert_identity(rec)
        _assert_identity(stats)
        assert rec["quarantined"] >= 1, rec
        assert rec["shed"] >= 1, rec
        # partially-streamed-then-failed: a shed stream keeps its prefix
        shed = [s for _, s in rec["streams"]
                if s is not None and s.outcome == "shed"]
        assert any(len(s.tokens()) >= 1 for s in shed), \
            "no shed stream retained a streamed prefix"
        _zero_retraces(eng)

    def test_combined_chaos_every_failure_explains_itself(self, tmp_path):
        """ISSUE-20 acceptance, end to end: under the ISSUE-18
        combined-chaos plan with request tracing armed, every
        non-completed request's trace id resolves to a causally-ordered
        span chain ending in its EXACT verdict; the tail-latency
        exemplar resolves to a real request; and each injected terminal
        fault writes exactly one schema-validated incident bundle whose
        event ring names the injection."""
        import json

        from bigdl_tpu import telemetry
        from bigdl_tpu.telemetry import incident, request_trace
        config.set_property("bigdl.lm.stallFactor", 20.0)
        config.set_property("bigdl.lm.warmupSteps", 2)
        config.set_property("bigdl.chaos.poisonPromptAt", "2")
        config.set_property("bigdl.chaos.evictBlockAt", 6)
        config.set_property("bigdl.chaos.hangDecodeAt", "20:3.0")
        config.set_property("bigdl.incident.dir", str(tmp_path))
        config.set_property("bigdl.incident.autoDump", True)
        request_trace.arm()
        chaos.install()
        reqs = sample_lm_workload(12, VOCAB, seed=9,
                                  prompt_lens=(4, 6, 8),
                                  output_lens=(4, 6, 8))
        try:
            with _engine() as eng:
                eng.start()
                rec = run_lm_open_loop(eng, reqs, rate_hz=200.0, seed=4)
                stats = eng.stats()
        finally:
            config.clear_property("bigdl.incident.dir")
        _assert_identity(rec)
        _assert_identity(stats)
        assert rec["quarantined"] >= 1 and rec["shed"] >= 1, rec

        # every request — admitted or rejected at the door — resolves
        # to a trace ending in its exact terminal verdict
        for key, s in rec["streams"]:
            if s is None:
                err = rec["errors"][key]
                tid = getattr(err, "trace_id", None)
                assert tid, "rejections carry their trace id on the error"
                assert request_trace.get(tid)["verdict"] == "rejected"
                continue
            tr = request_trace.get(s.trace_id)
            assert tr is not None, "every admitted stream is traced"
            assert tr["verdict"] == s.outcome, (key, s.outcome, tr)
            names = [sp["name"] for sp in tr["spans"]]
            assert names[0] == "request/queue_wait", names
            assert names[-1] == "request/verdict", names
            assert "request/admit" in names
            starts = [sp["t0_ns"] for sp in tr["spans"]]
            assert starts == sorted(starts), "span chain causally ordered"
            if s.outcome == "completed":
                assert "request/prefill" in names
                assert "request/decode_step" in names

        # exemplar round-trip: the tail of the latency histogram IS a
        # real request from this run
        ex = telemetry.histogram("LM/latency_ms").tail_exemplar()
        run_tids = {s.trace_id for _, s in rec["streams"] if s is not None}
        assert ex in run_tids
        assert request_trace.get(ex) is not None

        # one schema-validated bundle per injected terminal fault,
        # its ring naming the injection
        paths = incident.dumped()
        docs = []
        for p in paths:
            with open(p) as f:
                docs.append(json.load(f))
        reasons = [d["reason"] for d in docs]
        assert len(set(reasons)) == len(reasons), \
            "one bundle per fault slug, never duplicates"
        assert "lm/quarantine" in reasons
        assert "lm/hung_decode" in reasons
        ring_kinds = {e["kind"] for d in docs for e in d["events"]}
        assert "chaos/poison_prompt" in ring_kinds
        assert "chaos/hang_decode" in ring_kinds
        for d in docs:
            assert d["schema"] == "bigdl.incident/1"
            for k in ("reason", "written_ns", "events", "spans",
                      "metrics", "config", "threads", "trace_id"):
                assert k in d, k
        quarantine = docs[reasons.index("lm/quarantine")]
        assert quarantine["trace"] is not None
        assert quarantine["trace"]["verdict"] == "quarantined"
        _zero_retraces(eng)


# ---------------------------------------------------------------------------
# int8 decode tier
# ---------------------------------------------------------------------------

class TestInt8Tier:
    def test_gate_passes_and_serves(self):
        eng = _engine(quantize="int8")
        rep = eng.quantization_report
        assert rep["audit_ok"] and rep["allclose"], rep
        assert rep["max_abs_diff"] <= rep["atol"] + 1.0  # recorded, sane
        eng.start()
        s = eng.submit(_prompt(6), max_new_tokens=6)
        assert len(s.result(timeout=30)) == 6
        eng.close()
        _assert_identity(eng.stats())
        assert "lm_decode_int8" in eng.sentinels
        _zero_retraces(eng)

    def test_gate_refuses_on_drift(self):
        config.set_property("bigdl.lm.quantizeRtol", 0.0)
        config.set_property("bigdl.lm.quantizeAtol", 1e-9)
        with pytest.raises(QuantizationGateError, match="drifted past"):
            _engine(warm=False, quantize="int8")

    def test_unknown_tier_is_refused(self):
        with pytest.raises(ValueError, match="int8"):
            _engine(warm=False, quantize="int4")


# ---------------------------------------------------------------------------
# lint rule: unbounded-decode-loop
# ---------------------------------------------------------------------------

class TestUnboundedDecodeLoopRule:
    def _lint(self, tmp_path, body):
        from bigdl_tpu.analysis.lint import lint_paths
        d = tmp_path / "serving"
        d.mkdir(exist_ok=True)
        (d / "lm.py").write_text(body, encoding="utf-8")
        return [f for f in lint_paths([str(tmp_path)])
                if f.rule == "unbounded-decode-loop"]

    def test_flags_while_true_on_the_decode_path(self, tmp_path):
        found = self._lint(tmp_path,
                           "def decode():\n"
                           "    while True:\n"
                           "        step()\n")
        assert len(found) == 1 and found[0].line == 2

    def test_flags_unbounded_condition_name(self, tmp_path):
        found = self._lint(tmp_path,
                           "def decode(running):\n"
                           "    while running:\n"
                           "        step()\n")
        assert len(found) == 1

    def test_accepts_deadline_and_terminal_bounds(self, tmp_path):
        assert self._lint(tmp_path,
                          "def decode(self, deadline):\n"
                          "    while now() < deadline:\n"
                          "        step()\n"
                          "    while not self._terminal:\n"
                          "        step()\n"
                          "    for _ in range(max_new):\n"
                          "        step()\n") == []

    def test_production_lm_file_is_clean(self):
        from bigdl_tpu.analysis.lint import lint_paths
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        target = os.path.join(repo, "bigdl_tpu", "serving", "lm.py")
        assert [f for f in lint_paths([target])
                if f.rule == "unbounded-decode-loop"] == []


# ---------------------------------------------------------------------------
# docs drift guard: bigdl.lm.* keys
# ---------------------------------------------------------------------------

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestLMDocDrift:
    """Every ``bigdl.lm.*`` key the code registers must have a row in
    docs/configuration.md — and vice versa (same guard as the fleet,
    chaos, and ingest key families)."""

    _KEY = re.compile(r"bigdl\.lm\.[A-Za-z0-9]+(?:\.[A-Za-z0-9]+)*")

    def _keys_in(self, *parts):
        with open(os.path.join(_REPO, *parts), encoding="utf-8") as f:
            return set(self._KEY.findall(f.read()))

    def test_config_defaults_match_docs_both_ways(self):
        code = self._keys_in("bigdl_tpu", "utils", "config.py")
        docs = self._keys_in("docs", "configuration.md")
        assert code - docs == set(), \
            f"lm keys missing a docs row: {sorted(code - docs)}"
        assert docs - code == set(), \
            f"documented lm keys unknown to config.py: " \
            f"{sorted(docs - code)}"

    def test_lm_module_reads_registered_keys_only(self):
        registered = self._keys_in("bigdl_tpu", "utils", "config.py")
        used = (self._keys_in("bigdl_tpu", "serving", "lm.py") |
                self._keys_in("bigdl_tpu", "serving", "kv_cache.py"))
        assert used - registered == set(), \
            f"lm serving reads unregistered keys: " \
            f"{sorted(used - registered)}"
