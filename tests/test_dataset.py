"""Tests for the data pipeline (reference analog: dataset/ transformer specs)."""

import numpy as np
import pytest

from bigdl_tpu.dataset import (DataSet, LocalDataSet, MiniBatch, PaddingParam,
                               Sample, SampleToMiniBatch, ShardedDataSet)
from bigdl_tpu.dataset.image import (BGRImgToBatch, CenterCrop, ChannelNormalize,
                                     ColorJitter, HFlip, LabeledImage,
                                     Lighting, RandomCrop)
from bigdl_tpu.dataset.text import (Dictionary, LabeledSentenceToSample,
                                    SentenceSplitter, SentenceTokenizer,
                                    TextToLabeledSentence)
from bigdl_tpu.dataset import datasets
from bigdl_tpu.dataset.datasets import synthetic_images, synthetic_separable


class TestSampleMiniBatch:
    def test_minibatch_from_samples(self):
        samples = [Sample(np.ones((3, 4)) * i, np.float32(i)) for i in range(5)]
        mb = MiniBatch.from_samples(samples)
        assert mb.size() == 5
        assert mb.get_input().shape == (5, 3, 4)
        assert mb.get_target().shape == (5,)

    def test_slice(self):
        mb = MiniBatch(np.arange(12).reshape(6, 2), np.arange(6))
        sub = mb.slice(2, 3)
        assert sub.size() == 3
        np.testing.assert_array_equal(sub.get_input(),
                                      np.arange(12).reshape(6, 2)[2:5])

    def test_ragged_padding(self):
        samples = [Sample(np.ones((2, 3)), np.float32(1)),
                   Sample(np.ones((4, 3)), np.float32(2))]
        mb = MiniBatch.from_samples(samples, feature_padding=PaddingParam(-1.0))
        assert mb.get_input().shape == (2, 4, 3)
        assert mb.get_input()[0, 3, 0] == -1.0

    def test_fixed_length_padding(self):
        samples = [Sample(np.ones((2,)), np.float32(1))]
        mb = MiniBatch.from_samples(
            samples, feature_padding=PaddingParam(0.0, fixed_length=[5]))
        assert mb.get_input().shape == (1, 5)


class TestSampleToMiniBatch:
    def test_batching(self):
        samples = [Sample(np.ones(3), np.float32(1)) for _ in range(10)]
        batches = list(SampleToMiniBatch(4)(iter(samples)))
        assert [b.size() for b in batches] == [4, 4, 2]

    def test_partition_division(self):
        t = SampleToMiniBatch(8, partition_num=4)
        assert t.batch_per_partition == 2
        with pytest.raises(ValueError):
            SampleToMiniBatch(10, partition_num=4)


class TestLocalDataSet:
    def test_train_loops_forever(self):
        ds = LocalDataSet([1, 2, 3])
        it = ds.data(train=True)
        got = [next(it) for _ in range(7)]
        assert sorted(set(got)) == [1, 2, 3]

    def test_eval_finite(self):
        ds = LocalDataSet([1, 2, 3])
        assert sorted(ds.data(train=False)) == [1, 2, 3]

    def test_shuffle_changes_order(self):
        ds = LocalDataSet(list(range(100)))
        before = list(ds.data(train=False))
        ds.shuffle()
        after = list(ds.data(train=False))
        assert before != after
        assert sorted(after) == sorted(before)

    def test_transform_shares_index(self):
        ds = LocalDataSet(list(range(10)))
        ds2 = ds.transform(SampleToMiniBatch.__new__(SampleToMiniBatch) if False
                           else _DoubleTransformer())
        ds.shuffle()
        # transformed view sees the shuffled index
        assert sorted(ds2.data(train=False)) == [2 * i for i in range(10)]


class _DoubleTransformer:
    def __call__(self, it):
        return (2 * x for x in it)


class TestShardedDataSet:
    def test_shard_sizes_equal(self):
        ds = ShardedDataSet(list(range(10)), partition_num=4)
        sizes = [s.size() for s in ds.shards.values()]
        assert sizes == [2, 2, 2, 2]  # truncated to equal size

    def test_shard_disjoint(self):
        ds = ShardedDataSet(list(range(8)), partition_num=4)
        all_items = []
        for i in range(4):
            all_items.extend(ds.shard_data(i, train=False))
        assert sorted(all_items) == list(range(8))

    def test_epoch_order_invariant_to_partition_count(self):
        """The elastic-training contract: the global per-epoch record
        sequence is a function of (seed, round) only — never of how many
        partitions slice it — so a run checkpointed on N devices and
        resumed on M replays the identical batch stream."""
        records = list(range(24))

        def epoch_orders(parts, epochs=3):
            ds = ShardedDataSet(records, partition_num=parts)
            out = []
            for _ in range(epochs):
                ds.shuffle()
                epoch = []
                for p in range(parts):
                    epoch.extend(ds.shard_data(p, train=False))
                out.append(epoch)
            return out

        a, b = epoch_orders(4), epoch_orders(2)
        assert a == b
        assert a[0] != a[1]   # it IS a shuffle, not the identity

    def test_local_shuffle_mode_drops_nonlocal_records(self):
        """global_shuffle=False restores the pre-elastic memory
        invariant: a process holding a subset of partitions copies ONLY
        its own record blocks (the caller's full list is droppable),
        shuffles within them pure in (seed, round, partition), and the
        replay contract still holds same-topology."""
        records = list(range(24))
        ds = ShardedDataSet(records, partition_num=4,
                            local_partitions=[1, 3],
                            global_shuffle=False)
        assert ds._records is None and ds.index is None
        assert sorted(ds.shards) == [1, 3]
        assert ds.shards[1].records == records[6:12]
        assert ds.shards[3].records == records[18:24]
        assert ds.size() == 24   # global accounting is unchanged

        def shard_orders(epochs=3):
            d = ShardedDataSet(records, partition_num=4,
                               local_partitions=[1, 3],
                               global_shuffle=False)
            out = []
            for _ in range(epochs):
                d.shuffle()
                out.append({p: list(d.shard_data(p, train=False))
                            for p in (1, 3)})
            return out

        a, b = shard_orders(), shard_orders()
        assert a == b                          # pure in (seed, round, p)
        assert a[0][1] != a[1][1]              # it IS a shuffle
        for epoch in a:                        # within-block only
            assert sorted(epoch[1]) == records[6:12]
            assert sorted(epoch[3]) == records[18:24]

    def test_local_shuffle_mode_transform_sees_reshuffle(self):
        ds = ShardedDataSet(list(range(8)), partition_num=2,
                            global_shuffle=False)
        ds2 = ds.transform(_DoubleTransformer())
        ds.shuffle()
        got = sorted(ds2.shard_data(0, train=False))
        assert got == [0, 2, 4, 6]


class TestImageTransforms:
    def _img(self, h=8, w=8, c=3):
        return LabeledImage(np.arange(h * w * c, dtype=np.float32)
                            .reshape(h, w, c), 1.0)

    def test_center_crop(self):
        out = next(iter(CenterCrop(4, 4)([self._img()])))
        assert out.data.shape == (4, 4, 3)

    def test_random_crop_with_padding(self):
        out = next(iter(RandomCrop(8, 8, padding=2)([self._img()])))
        assert out.data.shape == (8, 8, 3)

    def test_hflip(self):
        img = self._img()
        out = next(iter(HFlip(threshold=1.1)([img])))
        np.testing.assert_array_equal(out.data, img.data[:, ::-1])

    def test_normalize(self):
        img = self._img()
        out = next(iter(ChannelNormalize([1.0, 2.0, 3.0],
                                         [2.0, 2.0, 2.0])([img])))
        np.testing.assert_allclose(
            out.data[..., 1], (img.data[..., 1] - 2.0) / 2.0)

    def test_color_jitter_shape_and_range(self):
        out = next(iter(ColorJitter()([self._img()])))
        assert out.data.shape == (8, 8, 3)
        assert out.data.min() >= 0.0 and out.data.max() <= 255.0

    def test_lighting(self):
        out = next(iter(Lighting()([self._img()])))
        assert out.data.shape == (8, 8, 3)

    def test_to_batch_chw(self):
        batches = list(BGRImgToBatch(2)([self._img(), self._img(),
                                         self._img()]))
        assert batches[0].get_input().shape == (2, 3, 8, 8)
        assert batches[1].get_input().shape == (1, 3, 8, 8)


class TestTransformerPlumbing:
    def test_chained_transformer_flattens_and_composes(self):
        from bigdl_tpu.dataset.transformer import (ChainedTransformer,
                                                   FuncTransformer)
        double = FuncTransformer(lambda x: x * 2)
        inc = FuncTransformer(lambda x: x + 1)
        chain = ChainedTransformer(double, inc)
        assert list(chain(iter([1, 2]))) == [3, 5]
        # nesting flattens into one stage list
        nested = ChainedTransformer(chain, double)
        assert len(nested.stages) == 3
        assert list(nested(iter([1]))) == [6]

    def test_reference_name_aliases(self):
        from bigdl_tpu.dataset import SampleToBatch
        from bigdl_tpu.dataset.image import (GreyImgNormalizer,
                                             GreyImgToBatch)
        assert SampleToBatch is SampleToMiniBatch
        assert GreyImgNormalizer is ChannelNormalize
        assert GreyImgToBatch is BGRImgToBatch

    def test_local_img_reader_scales_shorter_side(self, tmp_path):
        from PIL import Image
        from bigdl_tpu.dataset.image import LocalImgPath, LocalImgReader
        arr = np.zeros((10, 20, 3), np.uint8)
        arr[..., 0] = 200   # red in RGB -> B-last in BGR output
        p = tmp_path / "img.png"
        Image.fromarray(arr).save(p)
        out = next(iter(LocalImgReader(scale_to=16)(
            [LocalImgPath(str(p), 3.0)])))
        h, w = out.data.shape[:2]
        assert h == 16 and w == 32 and out.label == 3.0
        # BGR channel order: red lands in the LAST channel
        assert out.data[..., 2].mean() > 150 and out.data[..., 0].mean() < 10


class TestText:
    def test_split_tokenize(self):
        sents = list(SentenceSplitter()(["Hello there. How are you?"]))
        assert len(sents) == 2
        toks = next(iter(SentenceTokenizer()(["Hello, world!"])))
        assert toks == ["hello", ",", "world", "!"]

    def test_dictionary(self):
        d = Dictionary([["a", "b", "a"], ["a", "c"]], vocab_size=2)
        assert d.vocab_size() == 2
        assert d.get_index("a") == 0          # most frequent first
        assert d.get_index("zzz") == 2        # OOV index

    def test_lm_pipeline(self):
        d = Dictionary([["the", "cat", "sat"]])
        pairs = list(TextToLabeledSentence(d)([["the", "cat", "sat"]]))
        assert len(pairs) == 1
        samples = list(LabeledSentenceToSample(
            d.vocab_size() + 1, fixed_length=4)(iter(pairs)))
        s = samples[0]
        assert s.feature.shape == (4, 4)       # one-hot (T, vocab)
        assert s.label.shape == (4,)
        assert s.label[0] == d.get_index("cat") + 1  # 1-based


def test_oov_clamped_into_vocab():
    d = Dictionary([["a", "b", "a"]], vocab_size=2)
    pairs = list(TextToLabeledSentence(d)([["a", "zzz", "b"]]))  # OOV word
    # natural call: vocab_length == vocab_size() — OOV folds onto last slot
    samples = list(LabeledSentenceToSample(d.vocab_size(),
                                           fixed_length=3)(iter(pairs)))
    s = samples[0]
    assert s.feature.shape == (3, 2)
    assert s.label.max() <= d.vocab_size()


def test_news20_tree_and_movielens(tmp_path):
    # news20: label ids follow sorted subdirectory order, digit filenames only
    for i, group in enumerate(["alt.atheism", "comp.graphics"]):
        d = tmp_path / "news" / group
        d.mkdir(parents=True)
        (d / str(10000 + i)).write_text(f"post about {group}")
        (d / "README").write_text("not a post")
    # stray top-level file must not consume a label id
    (tmp_path / "news" / "20news.tar.gz").write_text("")
    texts = datasets.load_news20(str(tmp_path / "news"))
    assert [(t[1]) for t in texts] == [1, 2]
    assert "alt.atheism" in texts[0][0]

    # movielens: :: framing, int columns
    ml = tmp_path / "ml-1m"
    ml.mkdir()
    (ml / "ratings.dat").write_text("1::1193::5::978300760\n2::661::3::978302109\n")
    arr = datasets.load_movielens(str(tmp_path))        # finds ml-1m/ subdir
    assert arr.shape == (2, 4) and arr.dtype == np.int64
    assert datasets.movielens_id_pairs(str(ml)).tolist() == [[1, 1193], [2, 661]]
    assert datasets.movielens_id_ratings(str(ml))[0].tolist() == [1, 1193, 5]


def test_sentence_bipadding():
    from bigdl_tpu.dataset.text import SentenceBiPadding
    out = list(SentenceBiPadding()(["hello world"]))
    assert out == ["SENTENCESTART hello world SENTENCEEND"]


def test_synthetic_generators():
    imgs = synthetic_images(5, 3, 8, 8, 10)
    assert len(imgs) == 5 and imgs[0].data.shape == (8, 8, 3)
    samples = synthetic_separable(20, 4, n_classes=3)
    assert len(samples) == 20
    labels = {float(s.label) for s in samples}
    assert labels <= {1.0, 2.0, 3.0}
