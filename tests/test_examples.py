"""Examples-package tests (reference ``example/`` tree analogs)."""

import os

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.utils import file_io


def _tiny_classifier(n_classes=3):
    m = (nn.Sequential()
         .add(nn.Reshape([3 * 8 * 8], batch_mode=True))
         .add(nn.Linear(192, n_classes))
         .add(nn.LogSoftMax()))
    m._ensure_init()
    return m


def _write_image_tree(root, classes=3, per_class=2):
    PIL = pytest.importorskip("PIL.Image")
    rng = np.random.RandomState(0)
    paths = []
    for c in range(classes):
        d = os.path.join(root, f"class{c}")
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            arr = rng.randint(0, 255, size=(8, 8, 3), dtype=np.uint8)
            p = os.path.join(d, f"img{i}.png")
            PIL.fromarray(arr).save(p)
            paths.append(p)
    return paths


def test_model_validator_cli(tmp_path, capsys):
    from bigdl_tpu.examples import model_validator
    _write_image_tree(str(tmp_path / "val"))
    model_path = str(tmp_path / "model.snapshot")
    file_io.save(_tiny_classifier(), model_path)

    results = model_validator.main([
        "-f", str(tmp_path / "val"), "-t", "bigdl",
        "--modelPath", model_path, "-b", "2", "--crop", "8"])
    out = capsys.readouterr().out
    assert "Top1Accuracy" in out and "Top5Accuracy" in out
    top1 = results[0][1].final_result()
    assert 0.0 <= top1 <= 1.0


def test_model_validator_unknown_type(tmp_path):
    from bigdl_tpu.examples.model_validator import load_model
    with pytest.raises(SystemExit, match="caffeDefPath"):
        load_model("caffe", "whatever.caffemodel")


def test_image_predictor_cli(tmp_path, capsys):
    from bigdl_tpu.examples import image_predictor
    paths = _write_image_tree(str(tmp_path / "imgs"), classes=1, per_class=3)
    model_path = str(tmp_path / "model.snapshot")
    file_io.save(_tiny_classifier(), model_path)

    out = image_predictor.main([
        "-f", str(tmp_path / "imgs"), "--modelPath", model_path,
        "--crop", "8", "--topN", "2"])
    assert len(out) == len(paths)
    printed = capsys.readouterr().out
    assert all(os.path.basename(p) in printed for p in paths)


def test_udf_predictor_callable(tmp_path):
    from bigdl_tpu.examples.udf_predictor import make_udf
    dim, seq_len, classes = 4, 6, 2
    model = (nn.Sequential()
             .add(nn.Reshape([seq_len * dim], batch_mode=True))
             .add(nn.Linear(seq_len * dim, classes))
             .add(nn.LogSoftMax()))
    model._ensure_init()
    vectors = {"good": np.ones(dim, np.float32),
               "bad": -np.ones(dim, np.float32)}
    udf = make_udf(model, vectors, seq_len=seq_len, batch_size=2)
    labels = udf(["good good good", "bad bad", "unseen words only"])
    assert len(labels) == 3
    assert all(1 <= l <= classes for l in labels)
    # single-string convenience
    assert udf("good")[0] in (1, 2)
    # empty input: plain empty result, not a numpy crash
    assert udf([]) == []
    # empty vectors (e.g. --dim mismatch): clear error, not StopIteration
    with pytest.raises(ValueError, match="dim"):
        make_udf(model, {}, seq_len=seq_len)


def test_tensorflow_interop_save_demo(tmp_path):
    pytest.importorskip("tensorflow")
    from bigdl_tpu.examples import tensorflow_interop
    out = str(tmp_path / "model.pb")
    tensorflow_interop.main(["save", "--out", out])
    assert os.path.getsize(out) > 0
