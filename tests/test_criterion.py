"""Criterion semantics tests with golden values (SURVEY §4.1 strategy)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn


def rand(*shape):
    return jnp.asarray(np.random.randn(*shape).astype(np.float32))


class TestClassNLL:
    def test_golden(self):
        logp = jnp.log(jnp.asarray([[0.5, 0.25, 0.25], [0.1, 0.8, 0.1]]))
        target = jnp.asarray([1.0, 2.0])  # 1-based
        loss = float(nn.ClassNLLCriterion().forward(logp, target))
        exp = -(np.log(0.5) + np.log(0.8)) / 2
        np.testing.assert_allclose(loss, exp, rtol=1e-4)

    def test_no_size_average(self):
        logp = jnp.log(jnp.asarray([[0.5, 0.5]]))
        loss = float(nn.ClassNLLCriterion(size_average=False).forward(
            logp, jnp.asarray([1.0])))
        np.testing.assert_allclose(loss, -np.log(0.5), rtol=1e-5)

    def test_weights(self):
        logp = jnp.log(jnp.asarray([[0.5, 0.5], [0.5, 0.5]]))
        t = jnp.asarray([1.0, 2.0])
        loss = float(nn.ClassNLLCriterion(weights=[1.0, 3.0]).forward(logp, t))
        exp = -(1 * np.log(0.5) + 3 * np.log(0.5)) / 4
        np.testing.assert_allclose(loss, exp, rtol=1e-5)

    def test_backward_shape(self):
        logp = jax.nn.log_softmax(rand(4, 5))
        g = nn.ClassNLLCriterion().backward(logp, jnp.asarray([1., 2., 3., 4.]))
        assert g.shape == (4, 5)

    def test_crossentropy_equals_logsoftmax_nll(self):
        x = rand(4, 6)
        t = jnp.asarray([1., 3., 5., 2.])
        ce = float(nn.CrossEntropyCriterion().forward(x, t))
        nl = float(nn.ClassNLLCriterion().forward(jax.nn.log_softmax(x), t))
        np.testing.assert_allclose(ce, nl, rtol=1e-5)


class TestRegression:
    def test_mse_golden(self):
        x = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
        t = jnp.zeros((2, 2))
        np.testing.assert_allclose(float(nn.MSECriterion().forward(x, t)),
                                   (1 + 4 + 9 + 16) / 4, rtol=1e-6)

    def test_abs_golden(self):
        x = jnp.asarray([[1.0, -2.0]])
        np.testing.assert_allclose(
            float(nn.AbsCriterion().forward(x, jnp.zeros((1, 2)))), 1.5)

    def test_smooth_l1(self):
        x = jnp.asarray([0.5, 2.0])
        t = jnp.zeros((2,))
        exp = (0.5 * 0.25 + (2.0 - 0.5)) / 2
        np.testing.assert_allclose(
            float(nn.SmoothL1Criterion().forward(x, t)), exp, rtol=1e-6)

    def test_bce(self):
        x = jnp.asarray([0.9, 0.1])
        t = jnp.asarray([1.0, 0.0])
        exp = -np.log(0.9)
        np.testing.assert_allclose(float(nn.BCECriterion().forward(x, t)),
                                   exp, rtol=1e-3)

    def test_kldiv(self):
        logq = jnp.log(jnp.asarray([[0.5, 0.5]]))
        p = jnp.asarray([[0.75, 0.25]])
        exp = (0.75 * (np.log(0.75) - np.log(0.5))
               + 0.25 * (np.log(0.25) - np.log(0.5))) / 2  # / nElement
        np.testing.assert_allclose(
            float(nn.DistKLDivCriterion().forward(logq, p)), exp, rtol=1e-3)


class TestMarginFamily:
    def test_margin(self):
        x = jnp.asarray([0.5, -0.5])
        y = jnp.asarray([1.0, -1.0])
        np.testing.assert_allclose(
            float(nn.MarginCriterion().forward(x, y)), 0.5, rtol=1e-6)

    def test_soft_margin(self):
        x = jnp.asarray([1.0])
        y = jnp.asarray([1.0])
        np.testing.assert_allclose(
            float(nn.SoftMarginCriterion().forward(x, y)),
            np.log1p(np.exp(-1.0)), rtol=1e-5)

    def test_hinge_embedding(self):
        x = jnp.asarray([0.3, 0.4])
        y = jnp.asarray([1.0, -1.0])
        exp = (0.3 + max(0, 1 - 0.4)) / 2
        np.testing.assert_allclose(
            float(nn.HingeEmbeddingCriterion().forward(x, y)), exp, rtol=1e-5)

    def test_multimargin(self):
        x = jnp.asarray([[0.1, 0.2, 0.7]])
        t = jnp.asarray([3.0])
        exp = (max(0, 1 - 0.7 + 0.1) + max(0, 1 - 0.7 + 0.2)) / 3
        np.testing.assert_allclose(
            float(nn.MultiMarginCriterion().forward(x, t)), exp, rtol=1e-5)

    def test_margin_ranking(self):
        x1, x2 = jnp.asarray([0.7]), jnp.asarray([0.2])
        y = jnp.asarray([1.0])
        np.testing.assert_allclose(
            float(nn.MarginRankingCriterion().forward([x1, x2], y)),
            max(0, -(0.7 - 0.2) + 1), rtol=1e-5)

    def test_cosine_embedding(self):
        a = jnp.asarray([[1.0, 0.0]])
        b = jnp.asarray([[1.0, 0.0]])
        y = jnp.asarray([1.0])
        np.testing.assert_allclose(
            float(nn.CosineEmbeddingCriterion().forward([a, b], y)), 0.0,
            atol=1e-6)


class TestComposite:
    def test_multi_criterion(self):
        mc = nn.MultiCriterion()
        mc.add(nn.MSECriterion(), 0.5).add(nn.AbsCriterion(), 2.0)
        x, t = rand(3, 4), rand(3, 4)
        exp = 0.5 * float(nn.MSECriterion().forward(x, t)) \
            + 2.0 * float(nn.AbsCriterion().forward(x, t))
        np.testing.assert_allclose(float(mc.forward(x, t)), exp, rtol=1e-5)

    def test_parallel_criterion(self):
        pc = nn.ParallelCriterion()
        pc.add(nn.MSECriterion()).add(nn.ClassNLLCriterion())
        x1, t1 = rand(2, 3), rand(2, 3)
        x2 = jax.nn.log_softmax(rand(2, 4))
        t2 = jnp.asarray([1.0, 2.0])
        exp = float(nn.MSECriterion().forward(x1, t1)) \
            + float(nn.ClassNLLCriterion().forward(x2, t2))
        np.testing.assert_allclose(float(pc.forward([x1, x2], [t1, t2])), exp,
                                   rtol=1e-5)

    def test_time_distributed_criterion(self):
        c = nn.TimeDistributedCriterion(nn.MSECriterion(), size_average=True)
        x, t = rand(2, 5, 3), rand(2, 5, 3)
        loss = float(c.forward(x, t))
        exp = np.mean([(np.asarray(x)[:, i] - np.asarray(t)[:, i]) ** 2
                       for i in range(5)])
        np.testing.assert_allclose(loss, exp, rtol=1e-5)


class TestOthers:
    def test_l1cost(self):
        x = jnp.asarray([1.0, -2.0, 3.0])
        np.testing.assert_allclose(float(nn.L1Cost().forward(x, None)), 6.0)

    def test_dice(self):
        x = jnp.asarray([[1.0, 0.0, 1.0]])
        t = jnp.asarray([[1.0, 0.0, 1.0]])
        loss = float(nn.DiceCoefficientCriterion(epsilon=0.0).forward(x, t))
        np.testing.assert_allclose(loss, 0.0, atol=1e-6)

    def test_cosine_distance_criterion(self):
        x = jnp.asarray([[1.0, 0.0]])
        loss = float(nn.CosineDistanceCriterion().forward(x, x))
        np.testing.assert_allclose(loss, 0.0, atol=1e-6)

    def test_multilabel_soft_margin(self):
        x = jnp.asarray([[0.0, 0.0]])
        t = jnp.asarray([[1.0, 0.0]])
        exp = -np.log(0.5)
        np.testing.assert_allclose(
            float(nn.MultiLabelSoftMarginCriterion().forward(x, t)), exp,
            rtol=1e-5)

    def test_softmax_with_criterion(self):
        x = rand(2, 3, 4, 4)
        t = jnp.ones((2, 4, 4))
        loss = float(nn.SoftmaxWithCriterion().forward(x, t))
        assert np.isfinite(loss)

    def test_class_simplex(self):
        c = nn.ClassSimplexCriterion(5)
        x = rand(3, 5)
        assert np.isfinite(float(c.forward(x, jnp.asarray([1., 2., 3.]))))

    def test_multilabel_margin(self):
        x = jnp.asarray([[0.1, 0.2, 0.4, 0.8]])
        t = jnp.asarray([[3.0, 0.0, 0.0, 0.0]])  # only class 3 is a target
        loss = float(nn.MultiLabelMarginCriterion().forward(x, t))
        exp = (max(0, 1 - (0.4 - 0.1)) + max(0, 1 - (0.4 - 0.2))
               + max(0, 1 - (0.4 - 0.8))) / 4
        np.testing.assert_allclose(loss, exp, rtol=1e-5)


class TestTimeDistributedVectorizedPath:
    """The separable fast path must equal the unrolled per-timestep loop."""

    def _loop_value(self, crit, x, y):
        total = 0.0
        for t in range(x.shape[1]):
            total = total + float(crit.apply(x[:, t], y[:, t]))
        return total

    @pytest.mark.parametrize("size_average", [False, True])
    def test_classnll_matches_loop(self, size_average):
        rng = np.random.RandomState(0)
        logits = rng.normal(size=(4, 6, 5)).astype(np.float32)
        x = jnp.asarray(logits) - jnp.max(jnp.asarray(logits))
        x = jax.nn.log_softmax(x, axis=-1)
        y = jnp.asarray(rng.randint(1, 6, size=(4, 6)).astype(np.float32))
        inner = nn.ClassNLLCriterion()
        td = nn.TimeDistributedCriterion(inner, size_average=size_average)
        assert td._separable()
        got = float(td.apply(x, y))
        want = self._loop_value(inner, x, y)
        if size_average:
            want /= x.shape[1]
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_mse_and_bce_match_loop(self):
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.rand(3, 5, 4).astype(np.float32)) * 0.8 + 0.1
        y = jnp.asarray((rng.rand(3, 5, 4) > 0.5).astype(np.float32))
        for inner in (nn.MSECriterion(), nn.BCECriterion()):
            td = nn.TimeDistributedCriterion(inner)
            assert td._separable()
            np.testing.assert_allclose(float(td.apply(x, y)),
                                       self._loop_value(inner, x, y),
                                       rtol=1e-4)

    def test_crossentropy_no_size_average_not_rescaled(self):
        # CrossEntropy stores the flag on its inner NLL; the fast path must
        # read it there, not the base-class default
        inner = nn.CrossEntropyCriterion(size_average=False)
        td = nn.TimeDistributedCriterion(inner)
        assert td._separable() and not td._inner_size_average()
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.normal(size=(4, 6, 5)).astype(np.float32))
        y = jnp.asarray(rng.randint(1, 6, size=(4, 6)).astype(np.float32))
        np.testing.assert_allclose(float(td.apply(x, y)),
                                   self._loop_value(inner, x, y), rtol=1e-5)

    def test_weighted_nll_falls_back_to_loop(self):
        inner = nn.ClassNLLCriterion(weights=np.asarray([1.0, 2.0]))
        td = nn.TimeDistributedCriterion(inner)
        assert not td._separable()
        x = jnp.log(jnp.full((2, 3, 2), 0.5))
        y = jnp.ones((2, 3), jnp.float32)
        v = float(td.apply(x, y))
        np.testing.assert_allclose(v, self._loop_value(inner, x, y),
                                   rtol=1e-6)

    def test_graph_size_constant_in_t(self):
        """The vectorized path keeps the jitted HLO O(1) in T."""
        inner = nn.ClassNLLCriterion()
        td = nn.TimeDistributedCriterion(inner, size_average=True)

        def size_for(t):
            x = jnp.zeros((2, t, 4))
            y = jnp.ones((2, t))
            return len(jax.make_jaxpr(td.apply)(x, y).jaxpr.eqns)

        assert size_for(64) == size_for(8)
