"""Runtime telemetry subsystem: span tracer, step decomposition,
metrics registry, slow-step detection, and the traced end-to-end train.

The conftest arms the tracer for EVERY tier-1 test (alongside the strict
host-sync guard), so the whole suite doubles as the proof that telemetry
introduces zero device→host syncs; the end-to-end test here additionally
exports the Chrome trace and checks every promised lane is present."""

import io
import json
import os
import threading

import numpy as np
import pytest

from bigdl_tpu import telemetry
from bigdl_tpu.telemetry.metrics import MetricsRegistry
from bigdl_tpu.utils import config


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------

class TestSpanTracer:
    def test_nested_spans_record_containment(self):
        telemetry.reset_tracer()
        with telemetry.span("outer/a", tag=1):
            with telemetry.span("inner/b"):
                pass
        evs = {e["name"]: e for e in telemetry.events()}
        assert {"outer/a", "inner/b"} <= set(evs)
        outer, inner = evs["outer/a"], evs["inner/b"]
        assert outer["t0_ns"] <= inner["t0_ns"]
        assert inner["t1_ns"] <= outer["t1_ns"]
        assert outer["args"] == {"tag": 1}
        assert outer["lane"] == inner["lane"]

    def test_cross_thread_spans_land_on_distinct_lanes(self):
        telemetry.reset_tracer()
        with telemetry.span("main/span"):
            pass

        def worker():
            telemetry.name_thread("my-worker")
            with telemetry.span("worker/span"):
                pass

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        evs = telemetry.events()
        lanes = {e["name"]: e["lane"] for e in evs}
        assert lanes["main/span"] != lanes["worker/span"]
        threads = {e["name"]: e["thread"] for e in evs}
        assert threads["worker/span"] == "my-worker"

    def test_disarmed_span_records_nothing(self):
        telemetry.disarm()
        telemetry.reset_tracer()
        with telemetry.span("ghost/span"):
            pass
        telemetry.add_span("ghost/add", 0, 10)
        telemetry.instant("ghost/instant")
        assert telemetry.events() == []
        telemetry.arm(ring_size=4096)   # restore the conftest contract

    def test_ring_is_bounded(self):
        telemetry.disarm()
        telemetry.reset_tracer()
        telemetry.arm(ring_size=8)

        def burst():
            for i in range(100):
                telemetry.add_span(f"s{i}", i, i + 1)

        t = threading.Thread(target=burst)
        t.start()
        t.join()
        names = [e["name"] for e in telemetry.events()]
        assert len(names) == 8
        assert names == [f"s{i}" for i in range(92, 100)]

    def test_chrome_trace_schema(self, tmp_path):
        telemetry.reset_tracer()
        with telemetry.span("cat/span", k="v"):
            pass
        telemetry.instant("cat/marker")
        path = str(tmp_path / "trace.json")
        doc = telemetry.export_chrome_trace(path)
        # the on-disk file is the same JSON document
        assert json.load(open(path)) == json.loads(json.dumps(doc))
        assert isinstance(doc["traceEvents"], list)
        phases = {"X": 0, "M": 0, "i": 0}
        for ev in doc["traceEvents"]:
            assert ev["ph"] in phases
            phases[ev["ph"]] += 1
            assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
            if ev["ph"] == "X":
                assert ev["ts"] >= 0 and ev["dur"] >= 0
                assert ev["cat"] == ev["name"].split("/", 1)[0]
        assert phases["X"] == 1 and phases["i"] == 1
        names = [e for e in doc["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "thread_name"]
        assert names, "thread_name metadata missing"


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_counter_gauge_histogram_and_labels(self):
        reg = MetricsRegistry()
        c = reg.counter("items", labels={"stage": "decode"})
        c.inc()
        c.inc(4)
        assert reg.counter("items", labels={"stage": "decode"}) is c
        assert c.value == 5
        g = reg.gauge("occupancy")
        g.set(3.5)
        assert g.value == 3.5
        h = reg.histogram("lat", window=16)
        for v in range(10):
            h.observe(v)
        assert h.count == 10 and h.min == 0 and h.max == 9
        with pytest.raises(TypeError):
            reg.gauge("items", labels={"stage": "decode"})

    def test_snapshot_round_trips_through_json(self):
        reg = MetricsRegistry()
        reg.counter("a/count", summary=True).inc(2)
        reg.gauge("b/gauge", labels={"x": "1"}).set(7.25)
        h = reg.histogram("c/hist", window=8)
        for v in (1.0, 2.0, 3.0, 4.0):
            h.observe(v)
        reg.register_provider("p", lambda: [("p/one", 1.5)])
        snap = reg.snapshot()
        restored = json.loads(json.dumps(snap))
        assert restored == snap
        assert restored["counters"]["a/count"] == 2
        assert restored["gauges"]['b/gauge{x=1}'] == 7.25
        assert restored["histograms"]["c/hist"]["count"] == 4
        assert restored["histograms"]["c/hist"]["p50"] == np.percentile(
            [1, 2, 3, 4], 50)
        assert restored["provided"]["p/one"] == 1.5

    def test_summary_scalars_is_the_single_flush_path(self):
        reg = MetricsRegistry()
        reg.gauge("charted", summary=True).set(1.0)
        reg.gauge("uncharted").set(2.0)
        reg.register_provider("prov", lambda: [("prov/a", 3.0)])
        pairs = dict(reg.summary_scalars())
        assert pairs == {"charted": 1.0, "prov/a": 3.0}

    def test_prometheus_text_dump(self):
        reg = MetricsRegistry()
        reg.counter("Ingest/read/items", labels={"engine": "e0"}).inc(9)
        h = reg.histogram("Telemetry/step_latency_ms")
        h.observe(10.0)
        text = reg.prometheus_text()
        assert '# TYPE Ingest_read_items counter' in text
        assert 'Ingest_read_items{engine="e0"} 9.0' in text
        assert '# TYPE Telemetry_step_latency_ms histogram' in text
        assert 'Telemetry_step_latency_ms_count 1' in text
        assert 'le="+Inf"' in text

    def test_drop_prefix(self):
        reg = MetricsRegistry()
        reg.gauge("Telemetry/x", summary=True).set(1)
        reg.gauge("Other/y", summary=True).set(2)
        reg.drop_prefix("Telemetry/")
        assert dict(reg.summary_scalars()) == {"Other/y": 2.0}


# ---------------------------------------------------------------------------
# step stats: percentiles, decomposition, slow-step detection
# ---------------------------------------------------------------------------

class TestStepStats:
    def test_windowed_percentiles_match_numpy(self):
        rng = np.random.RandomState(7)
        values = rng.lognormal(3.0, 1.0, size=300)
        wp = telemetry.WindowedPercentiles(window=64)
        for v in values:
            wp.add(v)
        window = values[-64:]
        for q in (50, 90, 95, 99):
            assert wp.percentile(q) == pytest.approx(
                float(np.percentile(window, q)), rel=1e-12)

    def test_percentiles_empty_and_partial_window(self):
        wp = telemetry.WindowedPercentiles(window=8)
        assert np.isnan(wp.percentile(50))
        wp.add(5.0)
        assert wp.percentile(99) == 5.0

    def test_decomposition_sums_to_wall_exactly(self):
        telemetry.REGISTRY.drop_prefix("Telemetry/")
        acct = telemetry.StepAccount(window=16)
        rng = np.random.RandomState(0)
        for _ in range(20):
            wall = int(rng.randint(1_000_000, 50_000_000))
            parts = {p: float(rng.randint(0, wall // 4))
                     for p in telemetry.PARTS}
            acct.account(wall, **parts)
            total = sum(acct.last[p] for p in telemetry.PARTS)
            total += acct.last["unaccounted"]
            assert total == pytest.approx(wall, rel=1e-9)
        s = acct.summary()
        assert s["steps"] == 20
        closure = sum(s[f"{p}_frac"] for p in
                      telemetry.PARTS + ("unaccounted",))
        assert closure == pytest.approx(1.0, rel=1e-9)
        # the decomposition gauges ride the single flush path
        pairs = dict(telemetry.summary_scalars())
        assert "Telemetry/step_ms" in pairs
        for p in telemetry.PARTS:
            assert f"Telemetry/{p}_ms" in pairs

    def test_slow_step_detector_fires_once_per_anomaly_window(self):
        det = telemetry.SlowStepDetector(factor=3.0, warmup=3, cooldown=0)
        fired = [det.observe(100.0) for _ in range(6)]
        assert fired == [False] * 6
        # one sustained anomaly window: fires on entry, not per step
        burst = [det.observe(1000.0) for _ in range(5)]
        assert burst == [True, False, False, False, False]
        assert det.fired == 1
        # back to normal closes the window; a second window fires again
        assert det.observe(100.0) is False
        assert det.observe(1000.0) is True
        assert det.fired == 2

    def test_slow_step_detector_cooldown_separates_windows(self):
        det = telemetry.SlowStepDetector(factor=2.0, warmup=2, cooldown=3)
        for _ in range(4):
            det.observe(100.0)
        assert det.observe(500.0) is True
        assert det.observe(100.0) is False       # cooldown 3 -> 2
        assert det.observe(500.0) is False       # within cooldown: held
        for _ in range(3):
            det.observe(100.0)                   # cooldown expires
        assert det.observe(500.0) is True
        assert det.fired == 2

    def test_detector_disabled_and_ema_tracks_healthy_regime(self):
        assert telemetry.SlowStepDetector(0.0).observe(1e9) is False
        det = telemetry.SlowStepDetector(factor=2.0, warmup=1, cooldown=0)
        for _ in range(10):
            det.observe(100.0)
        ema_before = det.ema
        det.observe(10_000.0)                    # anomaly: EMA untouched
        assert det.ema == ema_before


# ---------------------------------------------------------------------------
# the traced tier-1 train: every lane, decomposition against wall time,
# registry-routed scalars with unchanged tags
# ---------------------------------------------------------------------------

def _jpeg_records(n=16, hw=(36, 36)):
    from PIL import Image

    from bigdl_tpu.dataset.image import LabeledImageBytes
    rng = np.random.RandomState(5)
    recs = []
    for i in range(n):
        img = rng.randint(0, 256, size=hw + (3,)).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(img).save(buf, "JPEG", quality=90)
        recs.append(LabeledImageBytes(f"r{i}", float(i % 4 + 1),
                                      buf.getvalue()))
    return recs


def test_traced_train_exports_all_lanes_and_decomposition(tmp_path):
    """A 3-step tier-1 train with telemetry armed end to end: streaming
    ingest + prefetcher + async checkpointing, strict retrace AND strict
    host-sync guards on (conftest).  Proves: (a) telemetry adds zero
    host syncs; (b) the exported Chrome trace carries driver, ingest,
    prefetcher, and checkpoint-writer lanes; (c) the step decomposition
    sums to the charted wall step time; (d) Ingest/* scalars arrive with
    unchanged tags through the registry's single flush path."""
    import jax

    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import LocalDataSet
    from bigdl_tpu.dataset.ingest import StreamingIngest
    from bigdl_tpu.visualization import TrainSummary

    trace_path = str(tmp_path / "trace.json")
    config.set_property("bigdl.telemetry.tracePath", trace_path)
    config.set_property("bigdl.telemetry.snapshotPath", str(tmp_path))
    try:
        recs = _jpeg_records(n=16)
        ds = LocalDataSet(recs).transform(
            StreamingIngest(4, crop=(32, 32), decode_workers=2,
                            name="teleingest"))
        model = (nn.Sequential().add(nn.Reshape((3 * 32 * 32,)))
                 .add(nn.Linear(3 * 32 * 32, 4)).add(nn.LogSoftMax()))
        model.reset(jax.random.PRNGKey(3))
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.05))
        opt.set_end_when(optim.max_iteration(3))
        opt.set_checkpoint(str(tmp_path / "ckpt"),
                           optim.several_iteration(1), async_write=True)
        ts = TrainSummary(str(tmp_path), "tele")
        opt.set_train_summary(ts)
        opt.optimize()
    finally:
        config.clear_property("bigdl.telemetry.tracePath")
        config.clear_property("bigdl.telemetry.snapshotPath")

    # (b) every promised lane shows up in the exported timeline
    doc = json.load(open(trace_path))
    lanes = {e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert "driver" in lanes
    assert any(l.startswith("ingest-reader") for l in lanes), lanes
    assert any(l.startswith("ingest-assembler") for l in lanes), lanes
    assert any(l.startswith("ingest-decode") for l in lanes), lanes
    assert any(l.startswith("prefetch-fetch") for l in lanes), lanes
    assert any(l.startswith("bigdl-ckpt-writer") for l in lanes), lanes
    span_names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    assert {"driver/fetch", "driver/device_step", "driver/host_wait",
            "driver/checkpoint", "ingest/decode",
            "ingest/assemble"} <= span_names, span_names
    assert "checkpoint/write" in span_names

    # (c) per-step decomposition sums to the charted wall step time
    step_ms = dict(ts.read_scalar("Telemetry/step_ms"))
    assert len(step_ms) == 3
    parts = {p: dict(ts.read_scalar(f"Telemetry/{p}_ms"))
             for p in telemetry.PARTS + ("unaccounted",)}
    for neval, wall in step_ms.items():
        total = sum(parts[p][neval] for p in parts)
        assert total == pytest.approx(wall, rel=0.05), (neval, total, wall)
    # rolling latency percentiles charted too
    assert len(ts.read_scalar("Telemetry/step_p50_ms")) == 3
    assert len(ts.read_scalar("Telemetry/step_p99_ms")) == 3

    # (d) Ingest/* scalars still arrive, tags unchanged, via the registry
    thr = ts.read_scalar("Ingest/teleingest/consume/throughput")
    assert thr, "Ingest/* scalars must survive the registry migration"
    # sanitizer scalars kept their historical tags as well
    assert len(ts.read_scalar("Analysis/retraces")) == 3
    assert len(ts.read_scalar("Analysis/implicit_host_syncs")) == 3

    # per-run registry snapshot landed next to the trace
    snap = json.load(open(tmp_path / "telemetry.json"))
    assert snap["step_summary"]["steps"] == 3
    assert "Telemetry/step_latency_ms" in snap["histograms"]


def test_slow_step_capture_writes_profile_and_timeline(tmp_path):
    """A forced-slow iteration fires the detector once, dumps the
    timeline, and triggers a one-shot on-demand jax.profiler capture."""
    import jax

    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import LocalDataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.datasets import synthetic_separable

    prof_dir = tmp_path / "slow"
    config.set_property("bigdl.telemetry.slowStepFactor", 5.0)
    config.set_property("bigdl.telemetry.slowStepWarmup", 3)
    config.set_property("bigdl.telemetry.slowStepCooldown", 2)
    config.set_property("bigdl.telemetry.profileOnSlowStep", str(prof_dir))
    # a short dispatch pipeline so the anomaly DRAINS while the loop is
    # still running (at the default depth 8 a 12-step run retires the
    # slow interval only in the final flush, after the capture window)
    config.set_property("bigdl.pipeline.depth", 2)
    try:
        samples = synthetic_separable(64, 8, n_classes=2, seed=2)
        base = LocalDataSet(samples).transform(SampleToMiniBatch(16))

        class Stall:
            """One artificially slow fetch, well past warmup."""
            def __init__(self):
                self.n = 0

            def __call__(self, it):
                import time as _time
                for b in it:
                    self.n += 1
                    if self.n == 8:
                        _time.sleep(0.5)
                    yield b

        ds = base.transform(Stall())
        model = (nn.Sequential().add(nn.Linear(8, 4)).add(nn.LogSoftMax()))
        model.reset(jax.random.PRNGKey(1))
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.1))
        opt.set_end_when(optim.max_iteration(12))
        opt.optimize()
        acct = opt._step_account
        assert acct.detector.fired >= 1
        dumps = [f for f in os.listdir(prof_dir)
                 if f.startswith("slowstep_") and f.endswith(".json")]
        assert dumps, "timeline dump missing"
        json.load(open(prof_dir / dumps[0]))       # well-formed
        assert (prof_dir / "slowstep_profile").is_dir(), \
            "on-demand jax.profiler capture missing"
    finally:
        for k in ("slowStepFactor", "slowStepWarmup", "slowStepCooldown",
                  "profileOnSlowStep"):
            config.clear_property(f"bigdl.telemetry.{k}")
        config.clear_property("bigdl.pipeline.depth")


def test_mfu_estimate_logged_with_throughput_line():
    """bigdl.telemetry.mfu: the fused step's cost_analysis FLOPs extend
    the reference throughput line and chart Telemetry/tflops.  A direct
    handler (not caplog) — earlier tests may leave the bigdl_tpu logger
    non-propagating via redirect_spark_info_logs."""
    import logging

    import jax

    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import LocalDataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.datasets import synthetic_separable

    class Tap(logging.Handler):
        def __init__(self):
            super().__init__()
            self.lines = []

        def emit(self, record):
            msg = record.getMessage()
            if "Throughput is" in msg:
                self.lines.append(msg)

    config.set_property("bigdl.telemetry.mfu", True)
    config.set_property("bigdl.telemetry.peakTflops", 100.0)
    lg = logging.getLogger("bigdl_tpu")
    tap = Tap()
    level = lg.level
    lg.addHandler(tap)
    lg.setLevel(logging.INFO)
    try:
        samples = synthetic_separable(64, 8, n_classes=2, seed=2)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(16))
        model = (nn.Sequential().add(nn.Linear(8, 4)).add(nn.LogSoftMax()))
        model.reset(jax.random.PRNGKey(1))
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.1))
        opt.set_end_when(optim.max_iteration(3))
        opt.optimize()
    finally:
        lg.removeHandler(tap)
        lg.setLevel(level)
        config.clear_property("bigdl.telemetry.mfu")
        config.clear_property("bigdl.telemetry.peakTflops")
    assert opt._step_flops and opt._step_flops > 0
    assert tap.lines and all("MFU is" in ln for ln in tap.lines)
