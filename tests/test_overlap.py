"""Latency-hiding bucketed ZeRO-1 schedule tests (8-device CPU mesh).

The overlapped step (``bigdl.parallel.overlap``, default on) partitions
the flat parameter vector into ``bigdl.parallel.overlapBuckets``
contiguous column buckets and runs a reduce-scatter / update /
all-gather chain per bucket so XLA's latency-hiding scheduler can
overlap ICI with compute.  These tests pin the two load-bearing
invariants: the schedule is a pure reordering (weights match the
monolithic baseline bit-for-bit after multi-step runs, for stateless
and stateful optimizers, on both the shard_map dp family and the GSPMD
dp x tp family) and the per-bucket collectives stay under the HLO
program auditor's contract (a silently dropped bucket exchange is a
MISSING-collective violation at compile time).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.analysis.hlo_audit import ProgramContractError
from bigdl_tpu.engine import Engine
from bigdl_tpu.dataset import SampleToMiniBatch
from bigdl_tpu.dataset.dataset import ShardedDataSet
from bigdl_tpu.dataset.datasets import synthetic_separable
from bigdl_tpu.parallel import AllReduceParameter, DistriOptimizer
from bigdl_tpu.parallel.tensor_parallel import column_parallel, row_parallel
from bigdl_tpu.utils import config

N_DEV = 8
SAMPLES = synthetic_separable(64, 4, n_classes=2, seed=3)


def _mlp(seed=11):
    m = (nn.Sequential()
         .add(nn.Linear(4, 16))
         .add(nn.Tanh())
         .add(nn.Linear(16, 2))
         .add(nn.LogSoftMax()))
    m.reset(jax.random.PRNGKey(seed))
    return m


def _tp_model(seed=11):
    up, down = nn.Linear(4, 16), nn.Linear(16, 2)
    column_parallel(up)
    row_parallel(down)
    m = (nn.Sequential().add(up).add(nn.Tanh()).add(down)
         .add(nn.LogSoftMax()))
    m.reset(jax.random.PRNGKey(seed))
    return m


def _run_shard_map(method_factory, overlap, buckets=None):
    config.set_property("bigdl.parallel.overlap",
                        "true" if overlap else "false")
    if buckets is not None:
        config.set_property("bigdl.parallel.overlapBuckets", str(buckets))
    try:
        model = _mlp()
        ds = ShardedDataSet(SAMPLES, N_DEV).transform(
            SampleToMiniBatch(64, N_DEV))
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(method_factory())
        opt.set_end_when(optim.max_iteration(6))
        w, _ = opt.optimize().get_parameters()
        return np.asarray(w)
    finally:
        config.clear_property("bigdl.parallel.overlap")
        config.clear_property("bigdl.parallel.overlapBuckets")


def _run_gspmd(method_factory, overlap, buckets=None):
    config.set_property("bigdl.parallel.overlap",
                        "true" if overlap else "false")
    if buckets is not None:
        config.set_property("bigdl.parallel.overlapBuckets", str(buckets))
    try:
        mesh = Engine.create_mesh((2, 4), ("data", "model"))
        m = _tp_model()
        ds = ShardedDataSet(SAMPLES, 2).transform(SampleToMiniBatch(64, 2))
        o = DistriOptimizer(m, ds, nn.ClassNLLCriterion(), mesh=mesh)
        o.set_optim_method(method_factory())
        o.set_end_when(optim.max_iteration(6))
        w, _ = o.optimize().get_parameters()
        return np.asarray(w)
    finally:
        config.clear_property("bigdl.parallel.overlap")
        config.clear_property("bigdl.parallel.overlapBuckets")


class TestBucketEdges:
    def test_partition_covers_shard_exactly_once(self):
        params = {"w": jnp.zeros((7, 9)), "b": jnp.zeros((5,))}
        arp = AllReduceParameter(params, N_DEV)
        for n in (1, 2, 3, arp.shard_size, arp.shard_size + 50):
            edges = arp.bucket_edges(n)
            assert edges[0][0] == 0 and edges[-1][1] == arp.shard_size
            for (_, b), (a2, _) in zip(edges, edges[1:]):
                assert b == a2                      # contiguous, no overlap
            assert all(b > a for a, b in edges)     # no empty buckets
            assert len(edges) == min(n, arp.shard_size)

    def test_clamps_degenerate_requests(self):
        arp = AllReduceParameter({"w": jnp.zeros((4, 4))}, N_DEV)
        assert arp.bucket_edges(0) == [(0, arp.shard_size)]
        assert arp.bucket_edges(-3) == [(0, arp.shard_size)]

    def test_bucket_roundtrip_matches_monolithic(self):
        """Per-bucket psum_scatter + all_gather, concatenated, must equal
        the single monolithic reduce-scatter / all-gather cycle."""
        from bigdl_tpu.parallel.all_reduce import shard_map
        from jax.sharding import PartitionSpec as P
        mesh = Engine.create_mesh((N_DEV,), ("data",))
        params = {"w": jnp.arange(60, dtype=jnp.float32).reshape(4, 15)}
        arp = AllReduceParameter(params, N_DEV)
        flat = arp.flatten(params)

        def mono(f):
            return arp.all_gather_weights(
                arp.reduce_scatter_gradients(f, "data"), "data")

        def bucketed(f):
            gmat = f.reshape(arp.n_shards, arp.shard_size)
            blocks = [arp.all_gather_bucket(
                arp.reduce_scatter_bucket(gmat[:, a:b], "data"), "data")
                for a, b in arp.bucket_edges(3)]
            return jnp.concatenate(blocks, axis=1).reshape(-1)

        kw = dict(mesh=mesh, in_specs=P(), out_specs=P(), check_rep=False)
        want = shard_map(mono, **kw)(flat)
        got = shard_map(bucketed, **kw)(flat)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestOverlapParity:
    """Weights after multi-step runs must match the monolithic baseline —
    the bucketed chain is a reordering of the same arithmetic."""

    @pytest.mark.parametrize("buckets", [2, 5, 7])
    def test_shard_map_sgd_momentum(self, buckets):
        f = lambda: optim.SGD(learning_rate=0.2, momentum=0.9)
        base = _run_shard_map(f, overlap=False)
        got = _run_shard_map(f, overlap=True, buckets=buckets)
        np.testing.assert_allclose(got, base, rtol=2e-4, atol=2e-5)

    def test_shard_map_adam(self):
        f = lambda: optim.Adam(learning_rate=0.05)
        base = _run_shard_map(f, overlap=False)
        got = _run_shard_map(f, overlap=True, buckets=4)
        np.testing.assert_allclose(got, base, rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("buckets", [2, 4])
    def test_gspmd_dp_x_tp_adam(self, buckets):
        f = lambda: optim.Adam(learning_rate=0.05)
        base = _run_gspmd(f, overlap=False)
        got = _run_gspmd(f, overlap=True, buckets=buckets)
        np.testing.assert_allclose(got, base, rtol=2e-4, atol=2e-5)


class TestDropBucketChaos:
    def test_dropped_bucket_reduce_scatter_caught(self):
        """Chaos: bucket k's reduce-scatter silently replaced by a local
        slice (each device keeps its own unsummed gradient columns) — the
        program has N-1 reduce-scatters where the contract requires N, and
        the auditor must refuse the compile."""
        config.set_property("bigdl.chaos.dropBucketCollective", "1")
        try:
            with pytest.raises(ProgramContractError) as ei:
                _run_shard_map(lambda: optim.SGD(learning_rate=0.2),
                               overlap=True, buckets=4)
        finally:
            config.clear_property("bigdl.chaos.dropBucketCollective")
        msg = str(ei.value)
        assert "reduce_scatter" in msg
        assert "at least" in msg            # the min_ops (missing) branch
        v = [x for x in ei.value.violations
             if "reduce_scatter" in x.op]
        assert v and v[0].step == "shard_map"
        assert v[0].pass_name == "collective"


class TestBucketContract:
    def test_shard_map_contract_pins_bucket_counts(self):
        from bigdl_tpu.analysis import program_contracts
        c = program_contracts.shard_map_contract("fp32", 1024, 1024,
                                                 n_buckets=5)
        by_kind = {b.kind: b for b in c.collectives}
        rs = by_kind["reduce-scatter"]
        ag = by_kind["all-gather"]
        assert rs.max_ops == rs.min_ops == 5
        assert ag.max_ops == ag.min_ops == 5
        # bucketing must not change total wire bytes
        assert rs.max_bytes == ag.max_bytes == 1024
