"""CI gate: the package must lint clean (ISSUE 4 acceptance criterion).

``python -m bigdl_tpu.analysis.lint bigdl_tpu`` exits 0 on the merged
tree, and the grandfather allowlist stays EMPTY — any new finding either
gets fixed or carries an inline ``# lint: allow(<rule>)`` with the reason
next to the code, never a silent allowlist entry."""

import os
import subprocess
import sys

import pytest

from bigdl_tpu.analysis.lint import (DEFAULT_ALLOWLIST, KNOWN_RULES,
                                     lint_paths, load_allowlist,
                                     main as lint_main)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "bigdl_tpu")


def test_package_lints_clean():
    findings = lint_paths([PKG], load_allowlist(DEFAULT_ALLOWLIST))
    assert findings == [], \
        "lint findings in bigdl_tpu/ (fix or silence inline):\n" + \
        "\n".join(str(f) for f in findings)


def test_allowlist_is_empty():
    assert load_allowlist(DEFAULT_ALLOWLIST) == set(), \
        "the lint allowlist must stay empty at merge — fix the finding " \
        "or silence it inline with '# lint: allow(<rule>)'"


def test_cli_entry_point_exits_zero():
    """The exact command the acceptance criterion names."""
    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.analysis.lint", "bigdl_tpu"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_unknown_rule_is_an_error_listing_known_rules(capsys):
    """``--rule`` with an unknown name must exit nonzero and list the
    known rules — a typo'd rule name silently reporting an empty, green
    result would be a CI hole."""
    rc = lint_main(["bigdl_tpu", "--rule", "no-such-rule"])
    assert rc != 0
    err = capsys.readouterr().err
    assert "unknown rule(s): no-such-rule" in err
    for rule in KNOWN_RULES:
        assert rule in err          # the listing names every known rule


def test_known_rule_filter_exits_zero(capsys):
    rc = lint_main(["bigdl_tpu/analysis/lint.py",
                    "--rule", "undeclared-collective"])
    assert rc == 0, capsys.readouterr()


def test_bench_lint_only_preflight():
    """bench.py --lint-only runs the linter + native.check_build + the
    offline HLO audit over a freshly-populated probe compile cache."""
    proc = subprocess.run(
        [sys.executable, "bench.py", "--lint-only"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "preflight" in (proc.stdout + proc.stderr)
    assert "HLO audit OK" in (proc.stdout + proc.stderr)


@pytest.mark.slow
def test_bench_audit_only_matches_baselines():
    """The acceptance criterion: --audit-only's census matches the
    committed audit_baselines.json within tolerance (nonzero exit on a
    contract or baseline regression)."""
    proc = subprocess.run(
        [sys.executable, "bench.py", "--audit-only"],
        cwd=REPO, capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "audit_collective_bytes" in proc.stdout
