"""CI gate: the package must lint clean (ISSUE 4 acceptance criterion).

``python -m bigdl_tpu.analysis.lint bigdl_tpu`` exits 0 on the merged
tree, and the grandfather allowlist stays EMPTY — any new finding either
gets fixed or carries an inline ``# lint: allow(<rule>)`` with the reason
next to the code, never a silent allowlist entry."""

import os
import subprocess
import sys

from bigdl_tpu.analysis.lint import (DEFAULT_ALLOWLIST, lint_paths,
                                     load_allowlist)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "bigdl_tpu")


def test_package_lints_clean():
    findings = lint_paths([PKG], load_allowlist(DEFAULT_ALLOWLIST))
    assert findings == [], \
        "lint findings in bigdl_tpu/ (fix or silence inline):\n" + \
        "\n".join(str(f) for f in findings)


def test_allowlist_is_empty():
    assert load_allowlist(DEFAULT_ALLOWLIST) == set(), \
        "the lint allowlist must stay empty at merge — fix the finding " \
        "or silence it inline with '# lint: allow(<rule>)'"


def test_cli_entry_point_exits_zero():
    """The exact command the acceptance criterion names."""
    proc = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.analysis.lint", "bigdl_tpu"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_bench_lint_only_preflight():
    """bench.py --lint-only runs the linter + native.check_build as a
    device-free preflight."""
    proc = subprocess.run(
        [sys.executable, "bench.py", "--lint-only"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "preflight" in (proc.stdout + proc.stderr)
