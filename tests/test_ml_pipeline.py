"""ML-pipeline estimator wrappers (reference DLEstimator/DLClassifier)."""

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset.datasets import synthetic_separable
from bigdl_tpu.ml import DLClassifier, DLEstimator


def test_classifier_fit_predict():
    samples = synthetic_separable(256, 4, n_classes=3, seed=7)
    X = np.stack([s.feature for s in samples])
    y = np.asarray([float(s.label) for s in samples])
    model = (nn.Sequential().add(nn.Linear(4, 16)).add(nn.Tanh())
             .add(nn.Linear(16, 3)).add(nn.LogSoftMax()))
    clf = (DLClassifier(model, nn.ClassNLLCriterion(), [4])
           .set_batch_size(32).set_max_epoch(15).set_learning_rate(0.5))
    fitted = clf.fit(X, y)
    preds = fitted.predict(X)
    assert preds.shape == (256,)
    acc = float((preds == y).mean())
    assert acc > 0.9, acc


def test_estimator_regression():
    rng = np.random.RandomState(0)
    X = rng.normal(size=(128, 3)).astype(np.float32)
    w_true = np.asarray([[1.0], [-2.0], [0.5]], np.float32)
    y = X @ w_true + 0.01 * rng.normal(size=(128, 1)).astype(np.float32)
    model = nn.Sequential().add(nn.Linear(3, 1))
    est = (DLEstimator(model, nn.MSECriterion(), [3], [1])
           .set_batch_size(32).set_max_epoch(60).set_learning_rate(0.1))
    fitted = est.fit(X, y)
    out = fitted.transform(X)
    assert out.shape == (128, 1)
    mse = float(((out - y) ** 2).mean())
    assert mse < 0.01, mse
