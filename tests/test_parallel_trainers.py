"""Trainer-reachable tensor/expert/pipeline parallelism (8-dev CPU mesh).

Beyond-reference capabilities (the reference is data-parallel only,
SURVEY §2.12) exposed through the PUBLIC Optimizer API: a mesh with a
``model`` axis turns DistriOptimizer into the GSPMD Megatron trainer, an
``expert`` axis turns MixtureOfExperts layers into all_to_all dispatch,
and PipelineOptimizer owns the GPipe training loop.  Each mode must
reproduce the plain dp trainer's results where semantics coincide.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.engine import Engine
from bigdl_tpu.dataset import SampleToMiniBatch
from bigdl_tpu.dataset.dataset import LocalDataSet, ShardedDataSet
from bigdl_tpu.dataset.datasets import synthetic_separable
from bigdl_tpu.nn.moe import MixtureOfExperts
from bigdl_tpu.parallel import DistriOptimizer
from bigdl_tpu.parallel.tensor_parallel import column_parallel, row_parallel

N_DEV = 8
D = 8


def _tp_model(tp):
    up, down = nn.Linear(4, 16), nn.Linear(16, 2)
    if tp:
        column_parallel(up)
        row_parallel(down)
    m = (nn.Sequential().add(up).add(nn.Tanh()).add(down)
         .add(nn.LogSoftMax()))
    m.reset(jax.random.PRNGKey(11))
    return m


def _moe_model(capacity_factor, n_classes=2):
    expert = (nn.Sequential().add(nn.Linear(D, 16)).add(nn.ReLU())
              .add(nn.Linear(16, D)))
    moe = MixtureOfExperts(D, expert, 4, capacity_factor=capacity_factor)
    m = (nn.Sequential().add(nn.Linear(4, D)).add(nn.Tanh()).add(moe)
         .add(nn.Linear(D, n_classes)).add(nn.LogSoftMax()))
    m.reset(jax.random.PRNGKey(7))
    return m


class TestTensorParallelTrainer:
    def test_dp_x_tp_matches_local_trainer(self):
        """(2 data x 4 model) GSPMD step == LocalOptimizer on the global
        batch: XLA's inserted collectives are an implementation detail."""
        samples = synthetic_separable(64, 4, n_classes=2, seed=3)

        m0 = _tp_model(tp=False)
        o0 = optim.Optimizer.create(
            m0, LocalDataSet(samples).transform(SampleToMiniBatch(64)),
            nn.ClassNLLCriterion())
        o0.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
        o0.set_end_when(optim.max_iteration(6))
        w0, _ = o0.optimize().get_parameters()

        mesh = Engine.create_mesh((2, 4), ("data", "model"))
        m1 = _tp_model(tp=True)
        ds = ShardedDataSet(samples, 2).transform(SampleToMiniBatch(64, 2))
        o1 = DistriOptimizer(m1, ds, nn.ClassNLLCriterion(), mesh=mesh)
        o1.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
        o1.set_end_when(optim.max_iteration(6))
        w1, _ = o1.optimize().get_parameters()
        np.testing.assert_allclose(np.asarray(w1), np.asarray(w0),
                                   rtol=2e-4, atol=2e-5)

    def test_tp_params_and_slots_physically_split(self):
        """Column weight lives 1/tp per device along the model axis; its
        Adam slots additionally split 1/dp over the data axis (ZeRO-1 in
        the GSPMD step) — a dp x tp run must not pay dp-fold
        optimizer-state memory."""
        samples = synthetic_separable(64, 4, n_classes=2, seed=3)
        mesh = Engine.create_mesh((2, 4), ("data", "model"))
        m = _tp_model(tp=True)
        ds = ShardedDataSet(samples, 2).transform(SampleToMiniBatch(64, 2))
        o = DistriOptimizer(m, ds, nn.ClassNLLCriterion(), mesh=mesh)
        o.set_optim_method(optim.Adam(learning_rate=0.05))
        o.set_end_when(optim.max_iteration(2))
        o.optimize()
        col_w = m.children[0].params["weight"]          # (4, 16) column
        assert {s.data.shape for s in col_w.addressable_shards} == {(4, 4)}
        slot = o.optim_method._slots["s"][0]["weight"]  # Adam m for it
        # (4, 16) -> P("data", "model"): 1/(dp*tp) = 1/8 per device
        assert {s.data.shape for s in slot.addressable_shards} == {(2, 4)}
        per_dev = sum(s.data.nbytes for s in slot.addressable_shards
                      if s.device == slot.addressable_shards[0].device)
        assert per_dev * 8 == slot.nbytes

    def test_model_axis_rejects_seq_combo(self):
        samples = synthetic_separable(64, 4, n_classes=2, seed=3)
        mesh = Engine.create_mesh((2, 2, 2), ("data", "model", "seq"))
        ds = ShardedDataSet(samples, 2).transform(SampleToMiniBatch(64, 2))
        o = DistriOptimizer(_tp_model(tp=True), ds, nn.ClassNLLCriterion(),
                            mesh=mesh)
        o.set_end_when(optim.max_iteration(1))
        with pytest.raises(ValueError, match="model"):
            o.optimize()


class TestExpertParallelTrainer:
    def test_dp_x_ep_matches_dp_exactly_when_dropfree(self):
        """(2 data x 4 expert) == plain dp8 bit-for-bit-ish when capacity
        never binds (with drops, routing groups differ by partitioning —
        the documented batch-split semantics, nn/moe.py)."""
        samples = synthetic_separable(64, 4, n_classes=2, seed=3)

        m2 = _moe_model(capacity_factor=4.0)
        ds2 = ShardedDataSet(samples, N_DEV).transform(
            SampleToMiniBatch(64, N_DEV))
        o2 = DistriOptimizer(m2, ds2, nn.ClassNLLCriterion(),
                             mesh=Engine.create_mesh((N_DEV,), ("data",)))
        o2.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
        o2.set_end_when(optim.max_iteration(6))
        w2, _ = o2.optimize().get_parameters()

        m3 = _moe_model(capacity_factor=4.0)
        ds3 = ShardedDataSet(samples, 2).transform(SampleToMiniBatch(64, 2))
        o3 = DistriOptimizer(m3, ds3, nn.ClassNLLCriterion(),
                             mesh=Engine.create_mesh((2, 4),
                                                     ("data", "expert")))
        o3.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
        o3.set_end_when(optim.max_iteration(6))
        w3, _ = o3.optimize().get_parameters()
        np.testing.assert_allclose(np.asarray(w3), np.asarray(w2),
                                   rtol=1e-5, atol=1e-6)

    def test_ep_converges_with_capacity_drops(self):
        samples = synthetic_separable(256, 4, n_classes=3, seed=9)
        m = _moe_model(capacity_factor=1.25, n_classes=3)
        ds = ShardedDataSet(samples, 2).transform(SampleToMiniBatch(64, 2))
        o = DistriOptimizer(m, ds, nn.ClassNLLCriterion(),
                            mesh=Engine.create_mesh((2, 4),
                                                    ("data", "expert")))
        o.set_optim_method(optim.Adam(learning_rate=0.01))
        o.set_end_when(optim.max_epoch(12))
        trained = o.optimize()
        from bigdl_tpu.optim.evaluator import Evaluator
        acc = Evaluator(trained).test(
            samples, [optim.Top1Accuracy()], 64)[0][1].final_result()
        assert acc > 0.85, f"dp x ep training failed to converge: acc={acc}"

    def test_expert_axis_without_moe_rejected(self):
        samples = synthetic_separable(64, 4, n_classes=2, seed=3)
        ds = ShardedDataSet(samples, 2).transform(SampleToMiniBatch(64, 2))
        o = DistriOptimizer(_tp_model(tp=False), ds, nn.ClassNLLCriterion(),
                            mesh=Engine.create_mesh((2, 4),
                                                    ("data", "expert")))
        o.set_end_when(optim.max_iteration(1))
        with pytest.raises(ValueError, match="MixtureOfExperts"):
            o.optimize()


class TestMoeAuxInObjective:
    def test_aux_pressure_balances_routing(self):
        """With the aux term in the objective (default weight), training
        drives the Switch balance diagnostic toward its 1.0 floor; with
        weight 0 it feels no pressure — the difference must show."""
        def run(weight):
            samples = synthetic_separable(256, 4, n_classes=3, seed=5)
            m = _moe_model(capacity_factor=2.0, n_classes=3)
            ds = LocalDataSet(samples).transform(SampleToMiniBatch(64))
            o = optim.Optimizer.create(m, ds, nn.ClassNLLCriterion())
            o.set_optim_method(optim.SGD(learning_rate=0.5))
            o.set_end_when(optim.max_epoch(10))
            o.set_moe_aux_weight(weight)
            trained = o.optimize()
            # measure final balance on a fresh forward
            x = np.stack([s.feature for s in samples[:64]])
            moe = trained.find_modules(MixtureOfExperts)[0]
            h = x
            for child in trained.children[:2]:       # Linear, Tanh
                h = np.asarray(child.forward(h))
            _, _, aux = moe.route(moe.params, jnp.asarray(h))
            return float(aux)

        balanced = run(0.05)
        free = run(0.0)
        assert balanced <= free + 1e-6, (balanced, free)
        assert balanced < 1.5, f"aux pressure failed to balance: {balanced}"

    def test_penalty_zero_without_moe(self):
        from bigdl_tpu.optim.optimizer import moe_aux_penalty
        m = _tp_model(tp=False)
        assert float(moe_aux_penalty(m, m.state, 0.01)) == 0.0


class TestPipelineOptimizer:
    def _samples(self, n=64):
        from bigdl_tpu.dataset import Sample
        rng = np.random.RandomState(2)
        x = rng.normal(size=(n, D)).astype(np.float32)
        w = rng.normal(size=(D, D)).astype(np.float32) * 0.4
        y = np.tanh(x @ w)
        return [Sample(x[i], y[i]) for i in range(n)]

    def _blocks(self, n=4):
        blocks = []
        for s in range(n):
            b = nn.Sequential().add(nn.Linear(D, D)).add(nn.Tanh())
            b.reset(jax.random.PRNGKey(s))
            blocks.append(b)
        return blocks

    def test_matches_local_trainer(self):
        """The GPipe schedule through the public Optimizer API must
        reproduce LocalOptimizer on the equivalent deep Sequential (these
        blocks are batch-pointwise, so microbatching is invisible)."""
        from bigdl_tpu.parallel import PipelineOptimizer
        samples = self._samples()
        # full-batch steps: both runs see identical data regardless of
        # the shared shuffle stream (the RefOptimizer oracle pattern)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(64))

        seq = nn.Sequential()
        for b in self._blocks():
            seq.add(b)
        o0 = optim.Optimizer.create(seq, ds, nn.MSECriterion())
        o0.set_optim_method(optim.SGD(learning_rate=0.5))
        o0.set_end_when(optim.max_iteration(8))
        w0, _ = o0.optimize().get_parameters()

        mesh = Engine.create_mesh((4,), ("stage",),
                                  devices=jax.devices()[:4])
        o1 = PipelineOptimizer(self._blocks(), ds, nn.MSECriterion(),
                               mesh=mesh, n_micro=4)
        o1.set_optim_method(optim.SGD(learning_rate=0.5))
        o1.set_end_when(optim.max_iteration(8))
        w1, _ = o1.optimize().get_parameters()
        np.testing.assert_allclose(np.asarray(w1), np.asarray(w0),
                                   rtol=2e-4, atol=2e-5)

    def test_stateful_block_rejected_at_any_stage(self):
        """A BatchNorm at stage 2 must trip the statelessness guard just
        like at stage 0 — its running-statistics updates would silently
        vanish in the scanned schedule (advisor r3: only blocks[0] was
        checked)."""
        import pytest
        from bigdl_tpu.parallel import PipelineOptimizer
        samples = self._samples()
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(64))
        blocks = self._blocks()
        blocks[2] = (nn.Sequential().add(nn.Linear(D, D))
                     .add(nn.BatchNormalization(D)))
        blocks[2].reset(jax.random.PRNGKey(2))
        mesh = Engine.create_mesh((4,), ("stage",),
                                  devices=jax.devices()[:4])
        o = PipelineOptimizer(blocks, ds, nn.MSECriterion(), mesh=mesh,
                              n_micro=4)
        o.set_optim_method(optim.SGD(learning_rate=0.5))
        o.set_end_when(optim.max_iteration(1))
        with pytest.raises(ValueError, match="stateless"):
            o.optimize()

    def test_pp_x_dp_trains_and_converges(self):
        from bigdl_tpu.parallel import PipelineOptimizer
        samples = self._samples()
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(16))
        mesh = Engine.create_mesh((2, 4), ("data", "stage"))
        o = PipelineOptimizer(self._blocks(), ds, nn.MSECriterion(),
                              mesh=mesh, n_micro=2)
        o.set_optim_method(optim.SGD(learning_rate=0.5))
        o.set_end_when(optim.max_epoch(10))
        trained = o.optimize()
        x = np.stack([s.feature for s in samples])
        y = np.stack([s.label for s in samples])
        mse = float(np.mean((np.asarray(trained.forward(x)) - y) ** 2))
        base = float(np.mean(y ** 2))
        assert mse < base * 0.6, (mse, base)

    @pytest.mark.slow
    def test_embed_head_lm_shape(self):
        """A full LM: embed -> pipelined blocks -> head, trained through
        the public API on a stage mesh."""
        from bigdl_tpu.dataset import Sample
        from bigdl_tpu.models.transformer import (LayerNorm,
                                                  transformer_block)
        from bigdl_tpu.parallel import PipelineOptimizer
        vocab, d, T = 16, 8, 6
        rng = np.random.RandomState(4)
        samples = [Sample((rng.randint(0, vocab, T) + 1).astype(np.float32),
                          (rng.randint(0, vocab, T) + 1).astype(np.float32))
                   for _ in range(32)]
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(8))
        embed = nn.Sequential().add(nn.LookupTable(vocab, d))
        embed.reset(jax.random.PRNGKey(0))
        head = (nn.Sequential().add(LayerNorm(d))
                .add(nn.Linear(d, vocab)).add(nn.LogSoftMax()))
        head.reset(jax.random.PRNGKey(1))
        blocks = []
        for s in range(2):
            b = transformer_block(d, 2)
            b.reset(jax.random.PRNGKey(10 + s))
            blocks.append(b)
        mesh = Engine.create_mesh((2,), ("stage",),
                                  devices=jax.devices()[:2])
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)
        o = PipelineOptimizer(blocks, ds, crit, mesh=mesh, n_micro=2,
                              embed=embed, head=head)
        o.set_optim_method(optim.Adam(learning_rate=0.01))
        o.set_end_when(optim.max_iteration(6))
        trained = o.optimize()
        x = np.stack([s.feature for s in samples[:8]])
        out = trained.forward(x)
        assert np.asarray(out).shape == (8, T, vocab)
        assert np.all(np.isfinite(np.asarray(out)))

    def test_dropout_block_requires_rng_and_trains(self):
        """pipeline_apply with training=True and no rng must reject a
        stochastic block (the old silent-no-dropout bug); the trainer
        threads rng so Dropout blocks train."""
        from bigdl_tpu.parallel import PipelineOptimizer
        from bigdl_tpu.parallel.pipeline import (pipeline_apply,
                                                 pipeline_shard_params,
                                                 stack_stage_params)
        blocks = []
        for s in range(2):
            b = (nn.Sequential().add(nn.Linear(D, D)).add(nn.Dropout(0.5))
                 .add(nn.Tanh()))
            b.reset(jax.random.PRNGKey(s))
            blocks.append(b)
        mesh = Engine.create_mesh((2,), ("stage",),
                                  devices=jax.devices()[:2])
        stacked = pipeline_shard_params(
            stack_stage_params([b.params for b in blocks]), mesh)
        with pytest.raises(ValueError, match="rng"):
            pipeline_apply(blocks[0], stacked, jnp.zeros((8, D)), 2, mesh,
                           training=True)
        # trainer threads rng: optimization proceeds
        samples = self._samples(32)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(8))
        o = PipelineOptimizer(blocks, ds, nn.MSECriterion(), mesh=mesh,
                              n_micro=2)
        o.set_optim_method(optim.SGD(learning_rate=0.1))
        o.set_end_when(optim.max_iteration(4))
        trained = o.optimize()
        w, _ = trained.get_parameters()
        assert np.all(np.isfinite(np.asarray(w)))

    def test_stage_count_mismatch_rejected(self):
        from bigdl_tpu.parallel import PipelineOptimizer
        samples = self._samples(16)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(8))
        mesh = Engine.create_mesh((4,), ("stage",),
                                  devices=jax.devices()[:4])
        with pytest.raises(ValueError, match="stage"):
            PipelineOptimizer(self._blocks(2), ds, nn.MSECriterion(),
                              mesh=mesh)


class TestPipelineMoeAndSharded:
    @pytest.mark.slow
    def test_pipeline_apply_returns_moe_aux(self):
        """return_aux collects the blocks' declared MoE diagnostics over
        real (non-drain) microbatch executions; a router at uniform
        initialization sits at the 1.0 balance floor."""
        from bigdl_tpu.models.transformer import transformer_block
        from bigdl_tpu.parallel.pipeline import (pipeline_apply,
                                                 pipeline_shard_params,
                                                 stack_stage_params)
        mesh = Engine.create_mesh((2,), ("stage",),
                                  devices=jax.devices()[:2])
        blocks = []
        for s in range(2):
            b = transformer_block(8, 2, moe_experts=2,
                                  moe_capacity_factor=2.0)
            b.reset(jax.random.PRNGKey(s))
            blocks.append(b)
        stacked = pipeline_shard_params(
            stack_stage_params([b.params for b in blocks]), mesh)
        x = jnp.asarray(np.random.RandomState(8)
                        .normal(size=(4, 6, 8)).astype(np.float32))
        out, aux = pipeline_apply(blocks[0], stacked, x, n_micro=2,
                                  mesh=mesh, return_aux=True)
        assert out.shape == x.shape
        assert float(aux) >= 0.99, float(aux)
        # dense (non-MoE) blocks: aux must be exactly zero
        dense = []
        for s in range(2):
            b = transformer_block(8, 2)
            b.reset(jax.random.PRNGKey(s))
            dense.append(b)
        dstack = pipeline_shard_params(
            stack_stage_params([b.params for b in dense]), mesh)
        _, aux0 = pipeline_apply(dense[0], dstack, x, n_micro=2,
                                 mesh=mesh, return_aux=True)
        assert float(aux0) == 0.0

    def test_pipeline_trainer_trains_moe_blocks(self):
        from bigdl_tpu.dataset import Sample
        from bigdl_tpu.models.transformer import transformer_block
        from bigdl_tpu.parallel import PipelineOptimizer
        rng = np.random.RandomState(3)
        samples = [Sample(rng.normal(size=(6, 8)).astype(np.float32),
                          rng.normal(size=(6, 8)).astype(np.float32))
                   for _ in range(16)]
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(8))
        blocks = []
        for s in range(2):
            b = transformer_block(8, 2, moe_experts=2)
            b.reset(jax.random.PRNGKey(s))
            blocks.append(b)
        mesh = Engine.create_mesh((2,), ("stage",),
                                  devices=jax.devices()[:2])
        o = PipelineOptimizer(blocks, ds, nn.MSECriterion(), mesh=mesh,
                              n_micro=2)
        o.set_optim_method(optim.Adam(learning_rate=0.01))
        o.set_end_when(optim.max_iteration(4))
        trained = o.optimize()
        w, _ = trained.get_parameters()
        assert np.all(np.isfinite(np.asarray(w)))

    def test_pipeline_trainer_sharded_dataset_global_batch(self):
        """pp x dp with a ShardedDataSet must train on the CONCATENATED
        per-partition minibatches (one per partition per step), matching
        the dp trainers' batch semantics."""
        from bigdl_tpu.dataset import Sample
        from bigdl_tpu.parallel import PipelineOptimizer
        rng = np.random.RandomState(2)
        x = rng.normal(size=(64, D)).astype(np.float32)
        y = np.tanh(x @ (rng.normal(size=(D, D)).astype(np.float32) * 0.4))
        samples = [Sample(x[i], y[i]) for i in range(64)]
        ds = ShardedDataSet(samples, 2).transform(SampleToMiniBatch(32, 2))
        blocks = []
        for s in range(4):
            b = nn.Sequential().add(nn.Linear(D, D)).add(nn.Tanh())
            b.reset(jax.random.PRNGKey(s))
            blocks.append(b)
        mesh = Engine.create_mesh((2, 4), ("data", "stage"))
        o = PipelineOptimizer(blocks, ds, nn.MSECriterion(), mesh=mesh,
                              n_micro=2)
        o.set_optim_method(optim.SGD(learning_rate=0.5))
        o.set_end_when(optim.max_iteration(2))
        seen = []
        orig = o._build_step()
        o._step_fn = lambda *a: (seen.append(int(a[2].shape[0])),
                                 orig(*a))[1]
        o.optimize()
        # 2 partitions x 16 rows each = the requested global batch of 32
        assert seen and all(b == 32 for b in seen), seen
