"""Mixture-of-experts + expert-parallelism tests (8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.engine import Engine
from bigdl_tpu.nn.moe import MixtureOfExperts
from bigdl_tpu.parallel.expert_parallel import (ep_shard_params,
                                                expert_parallel_apply)

D, E = 8, 4
N_DEV = 4


def _moe(capacity_factor=8.0, seed=3):
    expert = (nn.Sequential().add(nn.Linear(D, 2 * D)).add(nn.ReLU())
              .add(nn.Linear(2 * D, D)))
    moe = MixtureOfExperts(D, expert, E, capacity_factor=capacity_factor)
    moe.reset(jax.random.PRNGKey(seed))
    return moe


class TestMixtureOfExperts:
    def test_routing_is_top1_and_capacity_bounded(self):
        moe = _moe(capacity_factor=0.5)       # force drops
        x = jnp.asarray(np.random.RandomState(0)
                        .normal(size=(16, D)).astype(np.float32))
        dispatch, combine, aux = moe.route(moe.params, x)
        # each token occupies at most one (expert, slot)
        per_token = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
        assert set(np.unique(per_token)) <= {0.0, 1.0}
        # capacity respected per expert
        cap = moe.capacity(16)
        per_slot = np.asarray(jnp.sum(dispatch, axis=0))    # (E, C)
        assert per_slot.max() <= 1.0 and dispatch.shape[2] == cap

    def test_forward_is_gated_expert_output(self):
        moe = _moe()
        x = jnp.asarray(np.random.RandomState(1)
                        .normal(size=(10, D)).astype(np.float32))
        out = np.asarray(moe.forward(x))
        # manual per-token check against the chosen expert
        p = moe.params
        gates = jax.nn.softmax(x @ p["gate"], axis=-1)
        idx = np.asarray(jnp.argmax(gates, axis=-1))
        for t in range(10):
            ep = jax.tree_util.tree_map(lambda a, e=idx[t]: a[e],
                                        p["experts"])
            want, _ = moe.expert.apply(ep, x[t:t + 1], moe.state["expert"])
            want = np.asarray(want[0]) * float(gates[t, idx[t]])
            np.testing.assert_allclose(out[t], want, rtol=1e-4, atol=1e-5)

    def test_overflow_tokens_drop_to_zero(self):
        moe = _moe(capacity_factor=0.26)      # capacity 2 for 16 tokens
        x = jnp.asarray(np.ones((16, D), np.float32))  # all to one expert
        out = np.asarray(moe.forward(x))
        zero_rows = (np.abs(out).sum(axis=-1) < 1e-6).sum()
        assert zero_rows >= 14                # only `capacity` survive

    def test_batched_input_shape_preserved(self):
        moe = _moe()
        x = np.random.RandomState(2).normal(size=(2, 5, D)).astype(np.float32)
        out = moe.forward(x)
        assert np.asarray(out).shape == (2, 5, D)


class TestExpertParallel:
    @pytest.mark.slow
    def test_matches_dense_when_nothing_drops(self):
        mesh = Engine.create_mesh((N_DEV,), ("expert",),
                                  devices=jax.devices()[:N_DEV])
        moe = _moe(capacity_factor=8.0)
        x = jnp.asarray(np.random.RandomState(3)
                        .normal(size=(16, D)).astype(np.float32))
        want = np.asarray(moe.forward(x))
        params = ep_shard_params(moe.params, mesh)
        # expert weights are physically split 1/n
        leaf = jax.tree_util.tree_leaves(params["experts"])[0]
        assert {s.data.shape[0] for s in leaf.addressable_shards} == {1}
        got = np.asarray(expert_parallel_apply(moe, params, x, mesh))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_gradients_flow_and_stay_sharded(self):
        mesh = Engine.create_mesh((N_DEV,), ("expert",),
                                  devices=jax.devices()[:N_DEV])
        moe = _moe(seed=7)
        rng = np.random.RandomState(4)
        x = jnp.asarray(rng.normal(size=(8, D)).astype(np.float32))
        y = jnp.asarray(rng.normal(size=(8, D)).astype(np.float32))
        params = ep_shard_params(moe.params, mesh)

        def loss(p):
            out = expert_parallel_apply(moe, p, x, mesh)
            return jnp.mean((out - y) ** 2)

        g = jax.jit(jax.grad(loss))(params)
        for leaf in jax.tree_util.tree_leaves(g):
            assert np.all(np.isfinite(np.asarray(leaf)))
        gleaf = jax.tree_util.tree_leaves(g["experts"])[0]
        assert {s.data.shape[0] for s in gleaf.addressable_shards} == {1}, \
            "expert grads must stay expert-sharded"

    def test_guards(self):
        mesh = Engine.create_mesh((N_DEV,), ("expert",),
                                  devices=jax.devices()[:N_DEV])
        moe = MixtureOfExperts(D, nn.Linear(D, D), 6)   # 6 % 4 != 0
        moe._ensure_init()
        with pytest.raises(ValueError, match="divide"):
            expert_parallel_apply(moe, moe.params, jnp.zeros((8, D)), mesh)
        moe2 = _moe()
        with pytest.raises(ValueError, match="batch"):
            expert_parallel_apply(moe2, ep_shard_params(moe2.params, mesh),
                                  jnp.zeros((6, D)), mesh)


def test_stateful_expert_rejected():
    expert = nn.Sequential().add(nn.BatchNormalization(D))
    moe = MixtureOfExperts(D, expert, E)
    with pytest.raises(ValueError, match="stateless"):
        moe._ensure_init()


def test_routing_bookkeeping_survives_bf16():
    # 600 tokens to few experts: bf16 cumsum would double-book slots >256
    moe = _moe(capacity_factor=8.0)
    x = jnp.asarray(np.random.RandomState(5)
                    .normal(size=(600, D)).astype(np.float32)
                    ).astype(jnp.bfloat16)
    dispatch, _, _ = moe.route(moe.params, x)
    per_slot = np.asarray(jnp.sum(dispatch.astype(jnp.float32), axis=0))
    assert per_slot.max() <= 1.0, "capacity slot double-booked"


class TestTopK:
    def test_top2_routes_to_two_experts_with_renormalized_gates(self):
        moe = _moe()
        moe.top_k = 2
        rng = np.random.RandomState(6)
        x = jnp.asarray(rng.normal(size=(10, D)).astype(np.float32))
        dispatch, combine, _ = moe.route(moe.params, x)
        # every token occupies exactly two (expert, slot) cells
        per_token = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
        np.testing.assert_allclose(per_token, 2.0)
        # combine weights renormalize to 1 per token
        w_token = np.asarray(jnp.sum(combine, axis=(1, 2)))
        np.testing.assert_allclose(w_token, 1.0, rtol=1e-5)
        # the two chosen experts are the top-2 gates
        gates = np.asarray(jax.nn.softmax(x @ moe.params["gate"], axis=-1))
        chosen = np.asarray(jnp.sum(dispatch, axis=2))           # (t, E)
        for t in range(10):
            top2 = set(np.argsort(gates[t])[::-1][:2])
            assert set(np.nonzero(chosen[t])[0]) == top2

    def test_top2_forward_matches_manual_blend(self):
        moe = _moe(seed=11)
        moe.top_k = 2
        rng = np.random.RandomState(7)
        x = jnp.asarray(rng.normal(size=(6, D)).astype(np.float32))
        out = np.asarray(moe.forward(x))
        p = moe.params
        gates = np.asarray(jax.nn.softmax(x @ p["gate"], axis=-1))
        for t in range(6):
            top2 = np.argsort(gates[t])[::-1][:2]
            g = gates[t, top2] / gates[t, top2].sum()
            want = 0.0
            for e, gv in zip(top2, g):
                ep = jax.tree_util.tree_map(lambda a, e=e: a[e], p["experts"])
                y, _ = moe.expert.apply(ep, x[t:t + 1], moe.state["expert"])
                want = want + gv * np.asarray(y[0])
            np.testing.assert_allclose(out[t], want, rtol=1e-4, atol=1e-5)

    def test_aux_loss_in_state_and_uniform_floor(self):
        moe = _moe()
        x = np.random.RandomState(8).normal(size=(64, D)).astype(np.float32)
        _, new_state = moe.apply(moe.params, jnp.asarray(x), moe.state)
        aux = float(new_state["aux_loss"])
        # uniform router floor is 1.0; any routing stays >= ~1
        assert aux >= 0.99, aux

    def test_top_k_bounds(self):
        with pytest.raises(ValueError, match="top_k"):
            MixtureOfExperts(D, nn.Linear(D, D), E, top_k=E + 1)

    @pytest.mark.slow
    def test_ep_parity_with_top2(self):
        mesh = Engine.create_mesh((N_DEV,), ("expert",),
                                  devices=jax.devices()[:N_DEV])
        moe = _moe(capacity_factor=8.0, seed=13)
        moe.top_k = 2
        x = jnp.asarray(np.random.RandomState(9)
                        .normal(size=(16, D)).astype(np.float32))
        want = np.asarray(moe.forward(x))
        params = ep_shard_params(moe.params, mesh)
        got = np.asarray(expert_parallel_apply(moe, params, x, mesh))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_capacity_scales_with_top_k():
    moe1 = _moe(capacity_factor=1.25)
    moe2 = _moe(capacity_factor=1.25)
    moe2.top_k = 2
    assert moe2.capacity(64) == 2 * moe1.capacity(64)
    # default capacity must not systematically drop top-2 assignments
    # under near-uniform routing
    rng = np.random.RandomState(10)
    x = jnp.asarray(rng.normal(size=(64, D)).astype(np.float32) * 0.01)
    dispatch, _, _ = moe2.route(moe2.params, x)
    per_token = np.asarray(jnp.sum(dispatch, axis=(1, 2)))
    assert per_token.mean() > 1.9, "top-2 assignments dropped at default cf"


@pytest.mark.slow
def test_ep_returns_pmeant_aux():
    mesh = Engine.create_mesh((N_DEV,), ("expert",),
                              devices=jax.devices()[:N_DEV])
    moe = _moe(capacity_factor=8.0)
    x = jnp.asarray(np.random.RandomState(11)
                    .normal(size=(16, D)).astype(np.float32))
    params = ep_shard_params(moe.params, mesh)
    y, aux = expert_parallel_apply(moe, params, x, mesh, return_aux=True)
    assert y.shape == (16, D)
    assert float(aux) >= 0.99


def test_explicit_capacity_pins_budget_across_batch_sizes():
    """capacity= overrides the factor-derived, token-count-dependent
    budget: routing geometry is then stable under batch splitting (the
    microbatching contract documented in moe.py / pipeline.py)."""
    expert = (nn.Sequential().add(nn.Linear(D, 2 * D)).add(nn.ReLU())
              .add(nn.Linear(2 * D, D)))
    moe = MixtureOfExperts(D, expert, E, capacity=5)
    moe.reset(jax.random.PRNGKey(7))
    assert moe.capacity(8) == 5 and moe.capacity(64) == 5
    x = jnp.asarray(np.random.RandomState(12)
                    .normal(size=(16, D)).astype(np.float32))
    dispatch, _, _ = moe.route(moe.params, x)
    assert dispatch.shape == (16, E, 5)
    with pytest.raises(ValueError, match="capacity"):
        MixtureOfExperts(D, expert, E, capacity=0)


def test_dropfree_routing_is_batch_split_invariant():
    """With capacity_factor >= E/top_k nothing can drop, so concatenated
    half-batch forwards equal the full-batch forward exactly — the
    invariance the pipeline relies on for full-batch MoE parity."""
    moe = _moe(capacity_factor=float(E))
    x = np.random.RandomState(13).normal(size=(24, D)).astype(np.float32)
    full = np.asarray(moe.forward(jnp.asarray(x)))
    halves = np.concatenate(
        [np.asarray(moe.forward(jnp.asarray(h)))
         for h in np.split(x, 2, axis=0)], axis=0)
    np.testing.assert_allclose(halves, full, rtol=1e-5, atol=1e-6)


def test_diagnostic_scoping_is_per_module():
    """aux_loss exclusion is scoped to MixtureOfExperts' declaration: an
    unrelated module storing genuine cross-step state under the same key
    still trips the pipeline statelessness guard."""
    from bigdl_tpu.nn.module import Module, semantic_state_leaves

    class SneakyState(Module):
        def _init_params(self, rng):
            return {}

        def _init_state(self):
            return {"aux_loss": jnp.zeros((3,))}   # genuine state, bad name

        def apply(self, params, input, state, training=False, rng=None):
            return input, {"aux_loss": state["aux_loss"] + 1}

    sneaky = SneakyState()
    sneaky.reset(jax.random.PRNGKey(0))
    assert semantic_state_leaves(sneaky), \
        "undeclared aux_loss key must count as semantic state"
    moe = _moe()
    assert not semantic_state_leaves(moe), \
        "MoE's declared diagnostic must be excluded"


def _grouped(moe):
    """Context-style helper: flip the layer to the grouped execution path
    (``bigdl.moe.impl=grouped``) and drop its jit cache."""
    from bigdl_tpu.utils import config
    config.set_property("bigdl.moe.impl", "grouped")
    moe._jit_apply = None


def _einsum(moe):
    from bigdl_tpu.utils import config
    config.clear_property("bigdl.moe.impl")
    moe._jit_apply = None


class TestGroupedImpl:
    """bigdl.moe.impl=grouped: one scatter-gathered grouped batched matmul
    over all experts must reproduce the dispatch/combine einsum path
    exactly — same capacity drops, same gate weighting, same aux loss."""

    def _cmp(self, moe, x, tol=1e-6):
        _einsum(moe)
        want = np.asarray(moe.forward(x))
        _grouped(moe)
        try:
            got = np.asarray(moe.forward(x))
        finally:
            _einsum(moe)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=tol)

    def test_top1_matches_einsum(self):
        x = jnp.asarray(np.random.RandomState(0)
                        .normal(size=(16, D)).astype(np.float32))
        self._cmp(_moe(capacity_factor=8.0), x)

    def test_top1_capacity_drops_match(self):
        # cf=0.26 -> capacity 2 for 16 tokens: most tokens drop, and the
        # grouped path must drop EXACTLY the same ones (position-in-queue
        # tie-breaking included)
        x = jnp.asarray(np.random.RandomState(5)
                        .normal(size=(16, D)).astype(np.float32))
        self._cmp(_moe(capacity_factor=0.26), x)
        self._cmp(_moe(capacity_factor=0.26),
                  jnp.asarray(np.ones((16, D), np.float32)))

    def test_top2_matches_einsum(self):
        expert = (nn.Sequential().add(nn.Linear(D, 2 * D)).add(nn.ReLU())
                  .add(nn.Linear(2 * D, D)))
        for cf in (8.0, 0.26):
            moe = MixtureOfExperts(D, expert, E, capacity_factor=cf,
                                   top_k=2)
            moe.reset(jax.random.PRNGKey(9))
            x = jnp.asarray(np.random.RandomState(6)
                            .normal(size=(16, D)).astype(np.float32))
            self._cmp(moe, x)

    def test_aux_loss_matches_einsum(self):
        moe = _moe(capacity_factor=8.0)
        x = jnp.asarray(np.random.RandomState(7)
                        .normal(size=(16, D)).astype(np.float32))
        _, _, aux_e = moe.route(moe.params, x)
        _, _, _, _, aux_g = moe.route_compact(moe.params, x)
        np.testing.assert_allclose(float(aux_g), float(aux_e), rtol=1e-6)

    def test_expert_parallel_path_matches_einsum(self):
        mesh = Engine.create_mesh((N_DEV,), ("expert",),
                                  devices=jax.devices()[:N_DEV])
        x = jnp.asarray(np.random.RandomState(8)
                        .normal(size=(16, D)).astype(np.float32))
        for cf, k in ((8.0, 1), (0.26, 1), (8.0, 2)):
            expert = (nn.Sequential().add(nn.Linear(D, 2 * D))
                      .add(nn.ReLU()).add(nn.Linear(2 * D, D)))
            moe = MixtureOfExperts(D, expert, E, capacity_factor=cf,
                                   top_k=k)
            moe.reset(jax.random.PRNGKey(3))
            params = ep_shard_params(moe.params, mesh)
            _einsum(moe)
            want = np.asarray(expert_parallel_apply(moe, params, x, mesh))
            _grouped(moe)
            try:
                got = np.asarray(
                    expert_parallel_apply(moe, params, x, mesh))
            finally:
                _einsum(moe)
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_gradients_flow_through_grouped_path(self):
        moe = _moe()
        x = jnp.asarray(np.random.RandomState(9)
                        .normal(size=(8, D)).astype(np.float32))
        _grouped(moe)
        try:
            g = jax.grad(
                lambda p: jnp.mean(moe.apply(p, x, moe.state)[0] ** 2)
            )(moe.params)
        finally:
            _einsum(moe)
        for leaf in jax.tree_util.tree_leaves(g):
            assert np.all(np.isfinite(np.asarray(leaf)))

    def test_unknown_impl_rejected(self):
        from bigdl_tpu.utils import config
        moe = _moe()
        config.set_property("bigdl.moe.impl", "banana")
        try:
            with pytest.raises(ValueError, match="bigdl.moe.impl"):
                moe.forward(jnp.zeros((4, D)))
        finally:
            _einsum(moe)
