"""Tests for optim methods, schedules, triggers, metrics, and the
LocalOptimizer end-to-end slice (reference analogs: optim/ specs +
LocalOptimizerSpec's convergence tests on separable data)."""

import numpy as np
import jax.numpy as jnp
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import LocalDataSet, Sample, SampleToMiniBatch
from bigdl_tpu.dataset.datasets import synthetic_separable
from bigdl_tpu.optim.evaluator import Evaluator


def quad_feval(x):
    """f(x) = sum((x-3)^2); grad = 2(x-3)."""
    loss = jnp.sum((x - 3.0) ** 2)
    return loss, 2 * (x - 3.0)


class TestOptimMethods:
    @pytest.mark.parametrize("method,steps,tol", [
        (optim.SGD(learning_rate=0.1), 100, 1e-3),
        (optim.SGD(learning_rate=0.05, momentum=0.9), 150, 1e-2),
        (optim.SGD(learning_rate=0.05, momentum=0.9, nesterov=True,
                   dampening=0.0), 150, 1e-2),
        (optim.Adam(learning_rate=0.3), 200, 1e-2),
        (optim.Adagrad(learning_rate=1.0), 300, 1e-2),
        (optim.Adadelta(decay_rate=0.9, epsilon=1e-2), 1500, 0.2),
        (optim.Adamax(learning_rate=0.5), 200, 1e-2),
        (optim.RMSprop(learning_rate=0.1), 300, 1e-2),
    ])
    def test_converges_on_quadratic(self, method, steps, tol):
        x = jnp.array([0.0, 10.0, -5.0])
        for _ in range(steps):
            x, _ = method.optimize(quad_feval, x)
        np.testing.assert_allclose(np.asarray(x), 3.0, atol=tol)

    def test_lbfgs_converges_fast(self):
        x = jnp.array([0.0, 10.0, -5.0])
        method = optim.LBFGS(max_iter=10)
        x, losses = method.optimize(quad_feval, x)
        np.testing.assert_allclose(np.asarray(x), 3.0, atol=1e-4)
        assert losses[-1] < losses[0]

    def test_weight_decay_shrinks(self):
        m = optim.SGD(learning_rate=0.1, weight_decay=0.5)
        x = jnp.array([1.0])
        x2 = m.update(jnp.zeros(1), x)
        assert float(x2[0]) < 1.0

    def test_pytree_params(self):
        m = optim.Adam(learning_rate=0.5)
        params = {"w": jnp.zeros((2, 2)), "b": jnp.zeros(2)}

        def feval(p):
            loss = jnp.sum((p["w"] - 1) ** 2) + jnp.sum((p["b"] + 2) ** 2)
            return loss, {"w": 2 * (p["w"] - 1), "b": 2 * (p["b"] + 2)}

        for _ in range(100):
            params, _ = m.optimize(feval, params)
        np.testing.assert_allclose(np.asarray(params["w"]), 1.0, atol=0.05)
        np.testing.assert_allclose(np.asarray(params["b"]), -2.0, atol=0.05)

    def test_state_serialization(self, tmp_path):
        m = optim.Adam()
        x = jnp.zeros(3)
        for _ in range(3):
            x, _ = m.optimize(quad_feval, x)
        p = str(tmp_path / "adam.bin")
        m.save(p)
        m2 = optim.OptimMethod.load(p)
        assert m2.state["evalCounter"] == 3


class TestSchedules:
    def _clr(self, sgd):
        sgd.hyper()
        return -sgd.state["clr"]

    def test_default(self):
        s = optim.SGD(learning_rate=1.0, learning_rate_decay=0.1)
        assert self._clr(s) == 1.0
        s.state["evalCounter"] = 10
        np.testing.assert_allclose(self._clr(s), 1.0 / 2.0)

    def test_step(self):
        s = optim.SGD(learning_rate=1.0,
                      learning_rate_schedule=optim.Step(10, 0.5))
        s.state["evalCounter"] = 25
        np.testing.assert_allclose(self._clr(s), 0.25)

    def test_multistep(self):
        s = optim.SGD(learning_rate=1.0,
                      learning_rate_schedule=optim.MultiStep([10, 20], 0.1))
        s.state["evalCounter"] = 15
        np.testing.assert_allclose(self._clr(s), 0.1)

    def test_epoch_step(self):
        s = optim.SGD(learning_rate=1.0,
                      learning_rate_schedule=optim.EpochStep(2, 0.1))
        s.state["epoch"] = 5
        np.testing.assert_allclose(self._clr(s), 0.01)

    def test_poly(self):
        s = optim.SGD(learning_rate=1.0,
                      learning_rate_schedule=optim.Poly(2.0, 100))
        s.state["evalCounter"] = 50
        np.testing.assert_allclose(self._clr(s), 0.25)

    def test_exponential(self):
        s = optim.SGD(learning_rate=1.0,
                      learning_rate_schedule=optim.Exponential(
                          10, 0.5, stair_case=True))
        s.state["evalCounter"] = 25
        np.testing.assert_allclose(self._clr(s), 0.25)

    def test_plateau_reduces(self):
        sched = optim.Plateau(monitor="score", factor=0.5, patience=2,
                              mode="max")
        s = optim.SGD(learning_rate=1.0, learning_rate_schedule=sched)
        s.state["score"] = 0.9
        s.state["epoch"] = 1
        self._clr(s)
        s.state["score"] = 0.5
        for e in range(2, 4):       # no improvement for `patience` epochs
            s.state["epoch"] = e
            lr = self._clr(s)
            lr = self._clr(s)       # second call same epoch must be inert
        assert lr == 0.5            # exactly one reduction

    def test_epoch_schedule_regimes(self):
        sched = optim.EpochSchedule([
            optim.Regime(1, 3, {"learning_rate": 1e-2}),
            optim.Regime(4, 10, {"learning_rate": 1e-3}),
        ])
        s = optim.SGD(learning_rate=1.0, learning_rate_schedule=sched)
        s.state["epoch"] = 5
        np.testing.assert_allclose(self._clr(s), 1e-3)


class TestTriggers:
    def test_every_epoch(self):
        t = optim.every_epoch()
        assert not t({"epoch": 1})
        assert t({"epoch": 2})
        assert not t({"epoch": 2})

    def test_several_iteration(self):
        t = optim.several_iteration(3)
        assert [t({"neval": i}) for i in range(1, 7)] == \
            [False, False, True, False, False, True]

    def test_max_epoch_iteration(self):
        assert optim.max_epoch(5)({"epoch": 6})
        assert not optim.max_epoch(5)({"epoch": 5})
        assert optim.max_iteration(10)({"neval": 11})

    def test_min_loss_max_score_inert_on_fresh_state(self):
        # driver state initialises Loss/score to None; triggers must not crash
        fresh = {"epoch": 1, "neval": 1, "Loss": None, "score": None}
        assert not optim.min_loss(0.1)(fresh)
        assert not optim.max_score(0.9)(fresh)
        assert optim.min_loss(0.1)({"Loss": 0.05})
        assert optim.max_score(0.9)({"score": 0.95})

    def test_combinators(self):
        t = optim.max_epoch(2) | optim.max_iteration(100)
        assert t({"epoch": 3, "neval": 1})
        assert t({"epoch": 1, "neval": 101})
        assert not t({"epoch": 1, "neval": 1})

    def test_reads_loss_flag_propagates_through_combinators(self):
        # drivers flush the dispatch pipeline before evaluating
        # loss-reading end triggers — the flag must survive composition
        assert optim.min_loss(0.1).reads_loss
        assert not optim.max_epoch(2).reads_loss
        assert (optim.max_epoch(2) | optim.min_loss(0.1)).reads_loss
        assert (optim.min_loss(0.1) & optim.max_iteration(9)).reads_loss
        assert not (optim.max_epoch(2) | optim.max_iteration(9)).reads_loss


class TestValidationMethods:
    def test_top1(self):
        out = np.array([[0.1, 0.9], [0.8, 0.2]])
        target = np.array([2.0, 1.0])
        r = optim.Top1Accuracy()(out, target)
        assert r.final_result() == 1.0

    def test_top5(self):
        out = np.tile(np.arange(10.0), (2, 1))
        target = np.array([6.0, 1.0])   # class 6 in top5 (classes 6..10)
        r = optim.Top5Accuracy()(out, target)
        assert r.final_result() == 0.5

    def test_result_merge(self):
        a = optim.ValidationResult(3, 4, "x")
        b = optim.ValidationResult(1, 4, "x")
        assert (a + b).final_result() == 0.5

    def test_mae(self):
        out = np.array([[0.9, 0.1]])    # pred class 1
        target = np.array([3.0])
        assert optim.MAE()(out, target).final_result() == 2.0


def _mlp(din, nclass):
    return (nn.Sequential()
            .add(nn.Linear(din, 16))
            .add(nn.Tanh())
            .add(nn.Linear(16, nclass))
            .add(nn.LogSoftMax()))


class TestLocalOptimizerE2E:
    """The 'minimum slice': train a tiny MLP to high accuracy on separable
    data (reference LocalOptimizerSpec / DistriOptimizerSpec strategy)."""

    def test_converges_and_validates(self, tmp_path):
        samples = synthetic_separable(256, 4, n_classes=3, seed=7)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(32))
        model = _mlp(4, 3)
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.5))
        opt.set_end_when(optim.max_epoch(15))
        opt.set_checkpoint(str(tmp_path / "ckpt"), optim.every_epoch())
        opt.set_validation(optim.every_epoch(),
                           LocalDataSet(samples).transform(SampleToMiniBatch(32)),
                           [optim.Top1Accuracy()])
        trained = opt.optimize()

        results = Evaluator(trained).test(samples, [optim.Top1Accuracy()],
                                          batch_size=32)
        acc = results[0][1].final_result()
        assert acc > 0.9, f"model failed to learn separable data: acc={acc}"

        # checkpoint exists and resumes
        latest = opt.checkpoint.latest()
        assert latest is not None
        from bigdl_tpu.utils import file_io
        m2 = file_io.load(latest[0])
        r2 = Evaluator(m2).test(samples, [optim.Top1Accuracy()], 32)
        assert r2[0][1].final_result() > 0.8

    def test_adam_path(self):
        samples = synthetic_separable(128, 4, n_classes=2, seed=3)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(32))
        model = _mlp(4, 2)
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.Adam(learning_rate=0.05))
        opt.set_end_when(optim.max_iteration(60))
        trained = opt.optimize()
        acc = Evaluator(trained).test(
            samples, [optim.Top1Accuracy()], 32)[0][1].final_result()
        assert acc > 0.9

    def test_predictor(self):
        samples = synthetic_separable(64, 4, n_classes=2, seed=3)
        model = _mlp(4, 2)
        preds = model.predict_class(samples, batch_size=16)
        assert preds.shape == (64,)
        assert set(np.unique(preds)) <= {1, 2}

    def test_batch_size_factory(self):
        samples = synthetic_separable(64, 4, n_classes=2)
        model = _mlp(4, 2)
        opt = optim.Optimizer.create(model, LocalDataSet(samples),
                                     nn.ClassNLLCriterion(), batch_size=16)
        opt.set_end_when(optim.max_iteration(5))
        opt.optimize()          # runs without error


class TestTraceProfile:
    def test_profiler_window_writes_trace(self, tmp_path):
        """set_trace_profile captures a jax.profiler xplane trace of the
        requested steady-state window and training still completes."""
        samples = synthetic_separable(128, 4, n_classes=3, seed=9)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(16))
        model = _mlp(4, 3)
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.3))
        opt.set_end_when(optim.max_iteration(8))
        opt.set_trace_profile(str(tmp_path), start_iteration=3,
                              n_iterations=2)
        opt.optimize()
        import glob
        files = glob.glob(str(tmp_path / "plugins" / "profile" / "*" / "*"))
        assert files, "no profiler artifacts written"

    def test_run_ending_inside_window_closes_trace(self, tmp_path):
        """End trigger firing before the window completes must still stop
        the trace (an unterminated capture poisons the NEXT start_trace
        with 'profiler already running')."""
        samples = synthetic_separable(64, 4, n_classes=3, seed=9)
        model = _mlp(4, 3)
        for _ in range(2):   # second run would fail if the first leaked
            ds = LocalDataSet(samples).transform(SampleToMiniBatch(16))
            opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
            opt.set_optim_method(optim.SGD(learning_rate=0.3))
            opt.set_end_when(optim.max_iteration(4))
            opt.set_trace_profile(str(tmp_path), start_iteration=3,
                                  n_iterations=50)
            opt.optimize()

    def test_rejects_bad_window(self):
        model = _mlp(4, 3)
        ds = LocalDataSet(synthetic_separable(32, 4, n_classes=3)) \
            .transform(SampleToMiniBatch(16))
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        with pytest.raises(ValueError, match="n_iterations"):
            opt.set_trace_profile("/tmp/x", n_iterations=0)
        with pytest.raises(ValueError, match="start_iteration"):
            opt.set_trace_profile("/tmp/x", start_iteration=0)

    def test_second_optimize_does_not_recapture(self, tmp_path):
        """A completed capture consumes the request: calling optimize()
        again on the same Optimizer must not silently re-capture into the
        same log_dir and mix xplane artifacts.  A fresh set_trace_profile
        re-arms it."""
        import glob
        samples = synthetic_separable(128, 4, n_classes=3, seed=9)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(16))
        model = _mlp(4, 3)
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.3))
        opt.set_end_when(optim.max_iteration(6))
        opt.set_trace_profile(str(tmp_path), start_iteration=3,
                              n_iterations=2)
        opt.optimize()
        pattern = str(tmp_path / "plugins" / "profile" / "*")
        runs = set(glob.glob(pattern))
        assert runs, "first optimize() captured nothing"
        opt.set_end_when(optim.max_iteration(12))
        opt.optimize()
        assert set(glob.glob(pattern)) == runs, \
            "second optimize() re-captured into the same log_dir"
        # explicit re-arm captures again, into a fresh dir
        opt.set_trace_profile(str(tmp_path / "second"), start_iteration=1,
                              n_iterations=1)
        opt.set_end_when(optim.max_iteration(18))
        opt.optimize()
        assert glob.glob(str(tmp_path / "second" / "plugins" /
                             "profile" / "*"))

    def test_resume_past_start_iteration_still_captures(self, tmp_path):
        """A run resumed beyond the window's start (evalCounter from a
        snapshot) must still capture once, not silently skip."""
        samples = synthetic_separable(128, 4, n_classes=3, seed=9)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(16))
        model = _mlp(4, 3)
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        method = optim.SGD(learning_rate=0.3)
        method.state["evalCounter"] = 20   # as restored from a snapshot
        opt.set_optim_method(method)
        opt.set_end_when(optim.max_iteration(26))
        opt.set_trace_profile(str(tmp_path), start_iteration=10,
                              n_iterations=2)
        opt.optimize()
        import glob
        assert glob.glob(str(tmp_path / "plugins" / "profile" / "*" / "*"))


class TestValidatorNames:
    def test_validator_over_minibatch_dataset(self):
        """The reference's Validator API shape (optim/Validator.scala):
        construct over a MiniBatch dataset, test(methods)."""
        from bigdl_tpu.optim.evaluator import (DistriValidator,
                                               LocalValidator, Validator)
        assert LocalValidator is Validator and DistriValidator is Validator
        samples = synthetic_separable(64, 4, n_classes=3, seed=5)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(16))
        model = _mlp(4, 3)
        res = Validator(model, ds).test([optim.Top1Accuracy(),
                                         optim.Loss(nn.ClassNLLCriterion())])
        assert 0.0 <= res[0][1].final_result() <= 1.0
        assert np.isfinite(res[1][1].final_result())


class TestRegularizers:
    def test_penalty_values(self):
        from bigdl_tpu.optim.regularizer import (L1L2Regularizer,
                                                 L1Regularizer,
                                                 L2Regularizer)
        p = {"w": jnp.asarray([1.0, -2.0])}
        np.testing.assert_allclose(float(L1Regularizer(0.5).penalty(p)), 1.5)
        np.testing.assert_allclose(float(L2Regularizer(0.1).penalty(p)),
                                   0.05 * 5.0, rtol=1e-6)
        np.testing.assert_allclose(
            float(L1L2Regularizer(0.5, 0.1).penalty(p)), 1.5 + 0.25,
            rtol=1e-6)

    def test_layer_regularizers_reach_the_loss(self):
        """w_regularizer/b_regularizer on a layer contribute the
        reference's accGradParameters terms via the loss (here through
        autodiff): grad(w) gains l2*w, bias untouched by w_regularizer."""
        import jax
        from bigdl_tpu.optim.optimizer import regularization_penalty
        from bigdl_tpu.optim.regularizer import (L1Regularizer,
                                                 L2Regularizer)
        m = nn.Sequential().add(
            nn.Linear(3, 2, w_regularizer=L2Regularizer(0.2),
                      b_regularizer=L1Regularizer(0.3)))
        m._ensure_init()
        pen = regularization_penalty(m, m.params)
        w, b = m.children[0].params["weight"], m.children[0].params["bias"]
        want = 0.1 * float(jnp.sum(w * w)) + 0.3 * float(jnp.sum(jnp.abs(b)))
        np.testing.assert_allclose(float(pen), want, rtol=1e-6)
        g = jax.grad(lambda p: regularization_penalty(m, p))(m.params)
        np.testing.assert_allclose(np.asarray(g[0]["weight"]),
                                   0.2 * np.asarray(w), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(g[0]["bias"]),
                                   0.3 * np.sign(np.asarray(b)), rtol=1e-6)

    def test_weight_decay_via_training(self):
        """An L2-regularized layer decays toward zero when trained on a
        zero-gradient objective (the penalty is the only signal)."""
        from bigdl_tpu.optim.regularizer import L2Regularizer
        m = nn.Sequential().add(
            nn.Linear(2, 2, with_bias=False,
                      w_regularizer=L2Regularizer(1.0)))
        m._ensure_init()
        w0 = np.abs(np.asarray(m.children[0].params["weight"])).mean()
        samples = [Sample(np.zeros(2, np.float32), np.zeros(2, np.float32))
                   for _ in range(32)]
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(16))
        opt = optim.Optimizer.create(m, ds, nn.MSECriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.5))
        opt.set_end_when(optim.max_epoch(5))
        opt.optimize()
        w1 = np.abs(np.asarray(m.children[0].params["weight"])).mean()
        assert w1 < w0 * 0.1, (w0, w1)


class TestMetrics:
    def test_scalar_list_and_aggregate(self):
        """set/add/get surface (reference optim/Metrics.scala:31) and the
        distributed-accumulator kind: single-process, aggregated() equals
        the local mean (the multi-host sum is proven in
        tests/test_multihost.py's checkpoint leg)."""
        from bigdl_tpu.optim.metrics import Metrics
        import pytest

        m = Metrics()
        m.set("phase", 10.0, parallelism=2)
        m.add("phase", 6.0)
        assert m.get("phase") == 8.0           # (10 + 6) / 2
        assert m.aggregated("phase") == 8.0
        m.set("per-node", [1.0, 2.0])
        m.add("per-node", 3.0)
        assert m.get("per-node") == [1.0, 2.0, 3.0]
        with pytest.raises(KeyError):
            m.get("absent")
        with pytest.raises(KeyError):
            m.aggregated("absent")
        assert "phase" in m.summary()
