"""Model-zoo smoke tests: build each reference architecture, run a forward
pass on correctly-shaped input, check output shape and finiteness.

Reference architectures: models/lenet/LeNet5.scala, models/vgg/VggForCifar10.scala,
models/resnet/ResNet.scala, models/inception/Inception_v1.scala,
example/loadmodel/AlexNet.scala, models/rnn/SimpleRNN.scala,
example/utils/TextClassifier.scala.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from bigdl_tpu import models


def _check(out, shape):
    assert out.shape == shape, (out.shape, shape)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_lenet5():
    m = models.lenet5(10).evaluate()
    out = m.forward(jnp.ones((4, 28, 28)))
    _check(out, (4, 10))
    # log-softmax output: rows sum to 1 in prob space
    np.testing.assert_allclose(np.exp(np.asarray(out)).sum(-1), 1.0, rtol=1e-5)


def test_autoencoder():
    m = models.autoencoder(32).evaluate()
    out = m.forward(jnp.ones((4, 28 * 28)) * 0.5)
    _check(out, (4, 784))


@pytest.mark.slow
def test_vgg_for_cifar10():
    m = models.vgg_for_cifar10(10).evaluate()
    out = m.forward(jnp.ones((2, 3, 32, 32)))
    _check(out, (2, 10))


@pytest.mark.slow
def test_vgg16_imagenet():
    m = models.vgg16(1000).evaluate()
    out = m.forward(jnp.ones((1, 3, 224, 224)))
    _check(out, (1, 1000))


@pytest.mark.slow
def test_resnet_cifar_depth20():
    m = models.resnet(10, depth=20, dataset=models.DatasetType.CIFAR10)
    models.model_init(m)
    m.evaluate()
    out = m.forward(jnp.ones((2, 3, 32, 32)))
    _check(out, (2, 10))


def test_resnet_cifar_shortcut_a():
    m = models.resnet(10, depth=20, shortcut_type=models.ShortcutType.A,
                      dataset=models.DatasetType.CIFAR10).evaluate()
    out = m.forward(jnp.ones((2, 3, 32, 32)))
    _check(out, (2, 10))


@pytest.mark.slow
def test_resnet50_imagenet():
    m = models.resnet(1000, depth=50, dataset=models.DatasetType.IMAGENET)
    m.evaluate()
    out = m.forward(jnp.ones((1, 3, 224, 224)))
    _check(out, (1, 1000))


def test_resnet18_imagenet():
    m = models.resnet(1000, depth=18, dataset=models.DatasetType.IMAGENET)
    m.evaluate()
    out = m.forward(jnp.ones((1, 3, 224, 224)))
    _check(out, (1, 1000))


@pytest.mark.slow
def test_inception_v1_no_aux():
    m = models.inception_v1_no_aux_classifier(1000).evaluate()
    out = m.forward(jnp.ones((1, 3, 224, 224)))
    _check(out, (1, 1000))


@pytest.mark.slow
def test_inception_v1_aux_heads():
    m = models.inception_v1(1000).evaluate()
    out = m.forward(jnp.ones((1, 3, 224, 224)))
    _check(out, (1, 3000))  # main + 2 aux heads concatenated


@pytest.mark.slow
def test_inception_v2_no_aux():
    m = models.inception_v2_no_aux_classifier(1000).evaluate()
    out = m.forward(jnp.ones((1, 3, 224, 224)))
    _check(out, (1, 1000))


@pytest.mark.slow
def test_alexnet_owt():
    m = models.alexnet_owt(1000).evaluate()
    out = m.forward(jnp.ones((1, 3, 224, 224)))
    _check(out, (1, 1000))


def test_simple_rnn():
    m = models.simple_rnn(input_size=20, hidden_size=32, output_size=20)
    m.evaluate()
    out = m.forward(jnp.ones((2, 7, 20)))
    _check(out, (2, 7, 20))


def test_lstm_lm():
    m = models.lstm_lm(input_size=20, hidden_size=32, output_size=20).evaluate()
    out = m.forward(jnp.ones((2, 7, 20)))
    _check(out, (2, 7, 20))


def test_text_classifier():
    m = models.text_classifier(class_num=5, embedding_dim=64,
                               sequence_length=1000).evaluate()
    out = m.forward(jnp.ones((2, 1000, 64)) * 0.1)
    _check(out, (2, 5))


def test_lenet_train_step_decreases_loss():
    """End-to-end sanity: a few SGD steps on random data reduce NLL."""
    from bigdl_tpu.nn import ClassNLLCriterion
    import jax
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(16, 28, 28), jnp.float32)
    y = jnp.asarray(rng.randint(1, 11, size=(16,)))
    m = models.lenet5(10)
    # explicit init key: module-name-counter-derived default keys depend on
    # how many modules earlier tests created, making lr-0.5 steps flaky
    m.reset(jax.random.PRNGKey(7))
    crit = ClassNLLCriterion()
    losses = []
    for _ in range(5):
        out = m.forward(x)
        losses.append(float(crit.forward(out, y)))
        grad_out = crit.backward(out, y)
        m.zero_grad_parameters()
        m.backward(x, grad_out)
        m.update_parameters(0.5)
    assert losses[-1] < losses[0]
