"""Per-request tracing + the incident flight recorder (ISSUE 20).

The claims under test: every serving/LM/fleet submission gets a trace
id at the admission door and accumulates a causally-ordered span chain
ending in its exact terminal verdict; tail-latency histograms carry
exemplar trace ids so a p99 outlier resolves to a real request in one
lookup; structured errors carry ``trace_id``; the incident recorder
keeps a bounded always-on event ring and writes ONE schema'd bundle
per terminal fault (once per fault slug, bounded file count, degrading
gracefully on a full disk); and injected chaos faults are NAMED in the
bundle's event ring, so a failure never reads as spontaneous.
"""

import json
import os
import time

import numpy as np
import pytest

import jax

import bigdl_tpu.nn as nn
from bigdl_tpu import telemetry
from bigdl_tpu.fleet import Fleet
from bigdl_tpu.serving import (HungDispatchError, Overloaded,
                               ServingDataError, ServingEngine)
from bigdl_tpu.serving.engine import DeadlineExceeded, OUTCOMES
from bigdl_tpu.telemetry import incident, request_trace
from bigdl_tpu.telemetry.metrics import Histogram, MetricsRegistry
from bigdl_tpu.utils import chaos, config, elastic

DIN, DOUT = 4, 3

_KEYS = (
    "bigdl.compile.buckets", "bigdl.serving.warmupBatches",
    "bigdl.trace.requests", "bigdl.trace.maxTraces",
    "bigdl.trace.maxSpansPerTrace",
    "bigdl.incident.ringSize", "bigdl.incident.maxDumps",
    "bigdl.incident.dir", "bigdl.incident.autoDump",
    "bigdl.chaos.poisonRequestAt", "bigdl.chaos.hangDispatchAt",
    "bigdl.chaos.killReplicaAt", "bigdl.chaos.diskFullAt",
    "bigdl.chaos.slowRequestAt",
    "bigdl.fleet.maxReplicaRestarts",
)


@pytest.fixture(autouse=True)
def _trace_env():
    """Armed request tracing, disarmed chaos, clean knobs around every
    test (the conftest fixture already resets traces/ring after)."""
    from bigdl_tpu.resources import storage
    elastic.clear_preemption()
    request_trace.arm()
    yield
    chaos.uninstall()
    elastic.clear_preemption()
    storage.reset()
    for k in _KEYS:
        config.clear_property(k)


def _model(seed=7):
    m = (nn.Sequential().add(nn.Linear(DIN, 16)).add(nn.Tanh())
         .add(nn.Linear(16, DOUT)))
    m.reset(jax.random.PRNGKey(seed))
    return m


def _engine(model=None, buckets="2,4,8", warm=True, **kw):
    if buckets:
        config.set_property("bigdl.compile.buckets", buckets)
    eng = ServingEngine(model if model is not None else _model(), **kw)
    if warm:
        eng.warmup(np.zeros((DIN,), np.float32))
    return eng


def _rows(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, DIN)).astype(np.float32)


def _span_names(trace):
    return [s["name"] for s in trace["spans"]]


def _assert_identity(stats):
    assert stats["unaccounted"] == 0, stats
    assert sum(stats[o] for o in OUTCOMES) == stats["submitted"], stats


# ---------------------------------------------------------------------------
# request_trace unit behaviour
# ---------------------------------------------------------------------------

class TestRequestTraceUnit:
    def test_disarmed_mint_returns_none_and_recorders_noop(self):
        request_trace.disarm()
        tid = request_trace.mint("req")
        assert tid is None
        # every recorder must be a no-op on None — call sites thread the
        # id unconditionally
        request_trace.record_span(None, "x", 0, 1)
        request_trace.instant(None, "x")
        assert request_trace.verdict(None, "completed") is False
        assert request_trace.get(None) is None
        with request_trace.span(None, "x"):
            pass

    def test_span_chain_is_causally_ordered(self):
        tid = request_trace.mint("req", deadline_ms=50.0)
        t = telemetry.clock_ns()
        # recorded out of order on purpose: get() must sort by start
        request_trace.record_span(tid, "request/dispatch", t + 200, t + 300)
        request_trace.record_span(tid, "request/queue_wait", t, t + 100)
        request_trace.verdict(tid, "completed")
        tr = request_trace.get(tid)
        assert _span_names(tr) == ["request/queue_wait",
                                   "request/dispatch", "request/verdict"]
        assert tr["verdict"] == "completed"
        assert tr["attrs"] == {"deadline_ms": 50.0}

    def test_verdict_first_wins_and_tags_error(self):
        tid = request_trace.mint("req")
        err = Overloaded("queue full")
        assert request_trace.verdict(tid, "rejected", error=err,
                                     reason="queue_full") is True
        assert err.trace_id == tid
        # a later verdict (e.g. a racing abandon) must not overwrite
        assert request_trace.verdict(tid, "shed") is False
        tr = request_trace.get(tid)
        assert tr["verdict"] == "rejected" and tr["reason"] == "queue_full"

    def test_registry_bounded_oldest_trace_evicted(self):
        request_trace.arm(max_traces=4)
        tids = [request_trace.mint("req") for _ in range(6)]
        assert request_trace.get(tids[0]) is None
        assert request_trace.get(tids[1]) is None
        assert request_trace.get(tids[-1]) is not None
        assert len(request_trace.traces()) == 4

    def test_spans_bounded_trace_flagged_truncated(self):
        request_trace.arm(max_spans=3)
        tid = request_trace.mint("req")
        for i in range(5):
            request_trace.instant(tid, f"request/step_{i}")
        tr = request_trace.get(tid)
        assert len(tr["spans"]) == 3
        assert tr["truncated"] is True

    def test_chrome_export_gets_request_lane_with_verdict(self, tmp_path):
        tid = request_trace.mint("req")
        t = telemetry.clock_ns()
        request_trace.record_span(tid, "request/dispatch", t, t + 1000)
        request_trace.verdict(tid, "shed", reason="expired")
        doc = telemetry.export_chrome_trace(str(tmp_path / "trace.json"))
        lanes = [e["args"]["name"] for e in doc["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "thread_name"
                 and e["pid"] == 1]
        assert f"request:{tid} [shed]" in lanes
        spans = [e for e in doc["traceEvents"]
                 if e.get("cat") == "request" and e["ph"] == "X"]
        assert spans and spans[0]["args"]["trace_id"] == tid
        # the file round-trips as JSON
        with open(tmp_path / "trace.json") as f:
            assert json.load(f)["displayTimeUnit"] == "ms"

    def test_spans_mirror_onto_thread_rings_with_trace_id(self):
        tid = request_trace.mint("req")
        t = telemetry.clock_ns()
        request_trace.record_span(tid, "request/dispatch", t, t + 10)
        mirrored = [e for e in telemetry.events()
                    if (e["args"] or {}).get("trace_id") == tid]
        assert mirrored and mirrored[0]["name"] == "request/dispatch"


# ---------------------------------------------------------------------------
# histogram exemplars: the p99 -> trace lookup
# ---------------------------------------------------------------------------

class TestExemplars:
    def test_tail_exemplar_is_the_largest_observation(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat")
        for i, v in enumerate((5.0, 50.0, 2.0)):
            h.observe(v, exemplar=f"req-{i:06d}")
        h.observe(1.0)                      # untraced: no exemplar
        assert h.tail_exemplar() == "req-000001"
        ex = h.exemplars()
        assert ex[0] == (50.0, "req-000001")
        assert all(ex[i][0] >= ex[i + 1][0] for i in range(len(ex) - 1))

    def test_exemplars_bounded(self):
        from bigdl_tpu.telemetry.metrics import MAX_EXEMPLARS
        reg = MetricsRegistry()
        h = reg.histogram("lat")
        for i in range(MAX_EXEMPLARS * 3):
            h.observe(float(i), exemplar=f"req-{i:06d}")
        ex = h.exemplars()
        assert len(ex) == MAX_EXEMPLARS
        # the largest survive
        assert ex[0][0] == float(MAX_EXEMPLARS * 3 - 1)


# ---------------------------------------------------------------------------
# Prometheus text-format conformance (satellite: metrics.py export)
# ---------------------------------------------------------------------------

class TestPrometheusConformance:
    def test_type_lines_once_per_metric_name(self):
        reg = MetricsRegistry()
        reg.counter("Serving/submitted", labels={"svc": "a"}).inc()
        reg.counter("Serving/submitted", labels={"svc": "b"}).inc()
        reg.gauge("Serving/queue_depth").set(3)
        text = reg.prometheus_text()
        assert text.count("# TYPE Serving_submitted counter") == 1
        assert text.count("# TYPE Serving_queue_depth gauge") == 1
        for line in text.splitlines():
            assert line.startswith("#") or " " in line

    def test_label_values_escaped(self):
        reg = MetricsRegistry()
        reg.counter("errs", labels={"msg": 'a"b\\c\nd'}).inc()
        text = reg.prometheus_text()
        assert 'msg="a\\"b\\\\c\\nd"' in text

    def test_histogram_buckets_cumulative_with_inf_sum_count(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat")        # DEFAULT_BUCKETS ladder
        for v in (0.5, 5.0, 50.0, 20000.0):
            h.observe(v)
        counts = dict(h.bucket_counts())
        assert counts[1.0] == 1
        assert counts[5.0] == 2         # le is inclusive
        assert counts[50.0] == 3
        assert counts[10000.0] == 3
        assert counts[float("inf")] == 4
        text = reg.prometheus_text()
        assert "# TYPE lat histogram" in text
        # bucket lines cumulative and ordered, +Inf == _count
        assert 'lat_bucket{le="1.0"} 1' in text
        assert 'lat_bucket{le="5.0"} 2' in text
        assert 'lat_bucket{le="10000.0"} 3' in text
        assert 'lat_bucket{le="+Inf"} 4' in text
        assert "lat_count 4" in text
        assert f"lat_sum {0.5 + 5.0 + 50.0 + 20000.0}" in text

    def test_bucket_boundary_observation_lands_in_its_le_bucket(self):
        h = Histogram("lat", buckets=(1.0, 10.0))
        h.observe(1.0)                  # le="1.0" is inclusive
        counts = dict(h.bucket_counts())
        assert counts[1.0] == 1
        assert counts[10.0] == 1
        assert counts[float("inf")] == 1


# ---------------------------------------------------------------------------
# incident flight recorder
# ---------------------------------------------------------------------------

class TestIncidentRecorder:
    def test_ring_is_bounded_and_resizable(self):
        config.set_property("bigdl.incident.ringSize", 4)
        incident.reset()
        for i in range(10):
            incident.record("test/event", i=i)
        evs = incident.events()
        assert len(evs) == 4
        assert [e["fields"]["i"] for e in evs] == [6, 7, 8, 9]
        assert evs[0]["kind"] == "test/event"
        assert evs[0]["thread"]

    def test_bundle_schema_is_self_contained(self):
        config.set_property("bigdl.trace.maxTraces", 16)
        tid = request_trace.mint("req")
        request_trace.verdict(tid, "shed", reason="expired")
        incident.record("chaos/poison_request", index=1)
        doc = incident.bundle("unit-test", trace_id=tid)
        assert doc["schema"] == "bigdl.incident/1"
        for key in ("reason", "written_ns", "events", "spans", "metrics",
                    "config", "threads", "trace", "trace_id"):
            assert key in doc, key
        assert doc["trace"]["verdict"] == "shed"
        assert any(e["kind"] == "chaos/poison_request"
                   for e in doc["events"])
        # the effective-config capture names the non-default knob
        assert doc["config"]["bigdl.trace.maxTraces"] == 16
        # thread stacks include this very thread
        assert any("test_bundle_schema" in "".join(stack)
                   for stack in doc["threads"].values())
        json.dumps(doc, default=repr)   # JSON-serializable end to end

    def test_dump_bounded_files_oldest_evicted(self, tmp_path):
        config.set_property("bigdl.incident.dir", str(tmp_path))
        config.set_property("bigdl.incident.maxDumps", 2)
        paths = [incident.dump(f"fault-{i}") for i in range(3)]
        assert all(p is not None for p in paths)
        assert not os.path.exists(paths[0]), "oldest bundle evicted"
        assert os.path.exists(paths[1]) and os.path.exists(paths[2])
        assert incident.dumped() == paths[1:]
        with open(paths[2]) as f:
            assert json.load(f)["reason"] == "fault-2"
        assert telemetry.counter("Incident/dumps").value >= 3

    def test_maybe_dump_once_per_slug(self, tmp_path):
        config.set_property("bigdl.incident.dir", str(tmp_path))
        config.set_property("bigdl.incident.autoDump", True)
        first = incident.maybe_dump("serving/hung_dispatch")
        again = incident.maybe_dump("serving/hung_dispatch")
        other = incident.maybe_dump("serving/quarantine")
        assert first is not None and os.path.exists(first)
        assert again is None, "one bundle per fault slug per run"
        assert other is not None and other != first

    def test_maybe_dump_respects_autodump_off(self, tmp_path):
        config.set_property("bigdl.incident.dir", str(tmp_path))
        config.set_property("bigdl.incident.autoDump", False)
        assert incident.maybe_dump("anything") is None
        assert incident.dumped() == []

    def test_dump_rides_disk_full_degradation(self, tmp_path):
        """A full disk while writing the bundle must degrade the
        recorder (PR 14 discipline), never crash the failing run a
        second time."""
        from bigdl_tpu.resources import storage
        config.set_property("bigdl.incident.dir", str(tmp_path))
        config.set_property("bigdl.chaos.diskFullAt", "1:incident-")
        chaos.install()
        assert incident.dump("terminal-fault") is None
        assert storage.is_degraded("incident")
        # degraded: later dumps are suppressed without touching disk
        assert incident.dump("second-fault") is None
        assert incident.dumped() == []


# ---------------------------------------------------------------------------
# engine integration: the span chain through the serving stack
# ---------------------------------------------------------------------------

class TestServingEngineTraced:
    def test_completed_request_full_chain_and_exemplar(self):
        with _engine(deadline_ms=10000.0, max_batch=4) as eng:
            handles = [eng.submit(r) for r in _rows(8, seed=1)]
            for h in handles:
                h.result(timeout=30)
            stats = eng.stats()
        _assert_identity(stats)
        for h in handles:
            tr = request_trace.get(h.trace_id)
            assert tr is not None, "every admitted request is traced"
            names = _span_names(tr)
            assert tr["verdict"] == "completed"
            # causal order: wait -> coalesce -> dispatch -> verdict
            assert names.index("request/queue_wait") < \
                names.index("request/coalesce") < \
                names.index("request/dispatch") < \
                names.index("request/verdict")
            dispatch = next(s for s in tr["spans"]
                            if s["name"] == "request/dispatch")
            assert dispatch["args"]["pad_to_bucket"] >= \
                dispatch["args"]["rows"]
        # exemplar round-trip: the latency histogram's tail exemplar
        # resolves to a REAL completed request
        ex = telemetry.histogram("Serving/latency_ms").tail_exemplar()
        assert ex in {h.trace_id for h in handles}
        assert request_trace.get(ex)["verdict"] == "completed"

    def test_rejected_request_traced_with_verdict(self):
        eng = _engine(warm=False, start=False, max_queue_depth=4,
                      deadline_ms=10000.0)
        try:
            for _ in range(4):
                eng.submit(_rows(1)[0])
            with pytest.raises(Overloaded) as ei:
                eng.submit(_rows(1)[0])
        finally:
            eng.stop()
        seen = ei.value
        assert getattr(seen, "trace_id", None), \
            "structured serving errors carry their trace id"
        tr = request_trace.get(seen.trace_id)
        assert tr["verdict"] == "rejected"
        assert tr["reason"] == "queue_full"
        assert tr["error"] and "Overloaded" in tr["error"]
        _assert_identity(eng.stats())

    def test_expired_request_sheds_with_verdict(self):
        # chaos wedges the first handled request; everything behind it
        # ages past its 120 ms deadline and is shed at dequeue time
        config.set_property("bigdl.chaos.slowRequestAt", "1:0.5")
        chaos.install()
        with _engine(deadline_ms=120.0, max_batch=4) as eng:
            handles = [eng.submit(r) for r in _rows(4)]
            shed = []
            for h in handles:
                try:
                    h.result(timeout=30)
                except DeadlineExceeded as e:
                    shed.append((h, e))
        assert shed, "the wedge must age out the queued requests"
        for h, e in shed:
            assert e.trace_id == h.trace_id
            tr = request_trace.get(h.trace_id)
            assert tr["verdict"] == "shed" and tr["reason"] == "expired"


# ---------------------------------------------------------------------------
# chaos propagation: injected faults terminate traces AND name
# themselves in the incident bundle (satellite: trace-under-chaos)
# ---------------------------------------------------------------------------

class TestChaosTracePropagation:
    def test_poison_request_quarantined_trace_and_bundle(self, tmp_path):
        config.set_property("bigdl.chaos.poisonRequestAt", "1")
        config.set_property("bigdl.incident.dir", str(tmp_path))
        config.set_property("bigdl.incident.autoDump", True)
        chaos.install()
        with _engine(deadline_ms=10000.0, max_batch=4) as eng:
            handles = [eng.submit(r) for r in _rows(4, seed=5)]
            victim = next(h for h in handles if h.index == 1)
            with pytest.raises(ServingDataError) as ei:
                victim.result(timeout=30)
            for h in handles:
                if h is not victim:
                    h.result(timeout=30)
        _assert_identity(eng.stats())
        # the error carries the trace id; the trace ends in the verdict
        assert ei.value.trace_id == victim.trace_id
        tr = request_trace.get(victim.trace_id)
        assert tr["verdict"] == "quarantined"
        # exactly one bundle; its event ring NAMES the injected fault
        assert len(incident.dumped()) == 1
        with open(incident.dumped()[0]) as f:
            doc = json.load(f)
        kinds = [e["kind"] for e in doc["events"]]
        assert "chaos/poison_request" in kinds
        assert doc["trace"]["trace_id"] == victim.trace_id
        # once-per-position: the same plan never double-fires
        assert chaos._state.poison_fired == {1}

    def test_hang_dispatch_watchdog_trace_and_bundle(self, tmp_path):
        config.set_property("bigdl.chaos.hangDispatchAt", "5:3.0")
        config.set_property("bigdl.serving.warmupBatches", 2)
        config.set_property("bigdl.incident.dir", str(tmp_path))
        config.set_property("bigdl.incident.autoDump", True)
        chaos.install()
        with _engine(deadline_ms=30000.0, max_batch=2, stall_factor=5.0,
                     cooldown_batches=2) as eng:
            for _ in range(4):
                eng.submit(_rows(1)[0]).result(timeout=30)
            victim = eng.submit(_rows(1)[0])
            with pytest.raises(HungDispatchError) as ei:
                victim.result(timeout=30)
        assert ei.value.trace_id == victim.trace_id
        tr = request_trace.get(victim.trace_id)
        assert tr["verdict"] == "shed"
        assert tr["reason"] == "hung_dispatch"
        paths = incident.dumped()
        assert len(paths) == 1, "one incident bundle per injected fault"
        with open(paths[0]) as f:
            doc = json.load(f)
        kinds = [e["kind"] for e in doc["events"]]
        assert "chaos/hang_dispatch" in kinds
        assert "serving/abort_inflight" in kinds

    def test_kill_replica_aborted_trace_and_bundle(self, tmp_path):
        # the kill is an async-raise into the batcher thread; wedge the
        # dispatch first (slowRequestAt) so it deterministically lands
        # with a request IN FLIGHT — the stranded handle only the
        # supervisor sweep can close
        config.set_property("bigdl.chaos.killReplicaAt", "4:0")
        config.set_property("bigdl.chaos.slowRequestAt", "1:0.7")
        config.set_property("bigdl.compile.buckets", "2,4")
        config.set_property("bigdl.incident.dir", str(tmp_path))
        config.set_property("bigdl.incident.autoDump", True)
        chaos.install()
        fleet = Fleet(poll_interval=0.02)
        fleet.add_model("svc", _model(), replicas=1,
                        warm_row=np.zeros((DIN,), np.float32),
                        engine_kw={"deadline_ms": 30000.0})
        aborted = []
        try:
            handles = []
            for r in _rows(8):
                try:
                    handles.append(fleet.submit("svc", r))
                except Overloaded:
                    pass
                time.sleep(0.005)
            assert chaos._state.replica_kills == 1

            def _aborted():
                return [h for h in handles
                        if h.trace_id is not None and
                        (request_trace.get(h.trace_id) or {}).get(
                            "verdict") == "aborted"]

            deadline = time.monotonic() + 15.0
            while not _aborted() and time.monotonic() < deadline:
                time.sleep(0.05)
            assert fleet.quiesce(20.0)
            _assert_identity(fleet.stats("svc"))
            aborted = _aborted()
        finally:
            fleet.stop()
        assert aborted, "the crashed replica's in-flight requests " \
            "must end in an aborted-verdict trace"
        for h in aborted:
            tr = request_trace.get(h.trace_id)
            assert tr["verdict"] == "aborted"
            assert tr["reason"] == "replica_crash"
            assert h.outcome == "shed", \
                "the accounting identity still tallies abandons as shed"
        paths = incident.dumped()
        assert paths, "the abandon sweep writes an incident bundle"
        with open(paths[0]) as f:
            doc = json.load(f)
        kinds = [e["kind"] for e in doc["events"]]
        assert "chaos/kill_replica" in kinds
        assert "fleet/abandon" in kinds

    def test_fleet_rejection_minted_and_traced(self):
        fleet = Fleet(poll_interval=0.02)
        fleet.add_model("svc", _model(), replicas=1,
                        warm_row=np.zeros((DIN,), np.float32))
        fleet.stop()
        with pytest.raises(Overloaded) as ei:
            fleet.submit("svc", np.zeros((DIN,), np.float32))
        tr = request_trace.get(ei.value.trace_id)
        assert tr["kind"] == "fleet"
        assert tr["verdict"] == "rejected"
        assert tr["reason"] == "fleet_stopped"


# ---------------------------------------------------------------------------
# logger rotation (satellite: bounded bigdl.log)
# ---------------------------------------------------------------------------

class TestLoggerRotation:
    def test_log_file_rotates_at_size_cap(self, tmp_path):
        import logging
        from bigdl_tpu.utils.logger_filter import redirect_spark_info_logs
        path = str(tmp_path / "bigdl.log")
        config.set_property("bigdl.utils.LoggerFilter.maxBytes", 2048)
        config.set_property("bigdl.utils.LoggerFilter.backupCount", 2)
        lg = logging.getLogger("bigdl_tpu")
        prev_handlers, prev_prop = lg.handlers[:], lg.propagate
        try:
            redirect_spark_info_logs(log_file=path)
            for i in range(200):
                lg.info("rotation filler line %04d %s", i, "x" * 64)
            assert os.path.exists(path)
            assert os.path.getsize(path) <= 4096
            assert os.path.exists(path + ".1"), "rotated generation kept"
            assert not os.path.exists(path + ".3"), \
                "backupCount bounds the generations"
        finally:
            for h in lg.handlers:
                h.close()
            lg.handlers, lg.propagate = prev_handlers, prev_prop
            config.clear_property("bigdl.utils.LoggerFilter.maxBytes")
            config.clear_property("bigdl.utils.LoggerFilter.backupCount")


# ---------------------------------------------------------------------------
# lint rule: untraced-terminal-verdict (satellite: the linter proves every
# terminal error flows through a verdict-recording choke point)
# ---------------------------------------------------------------------------

class TestUntracedVerdictRule:
    def _lint(self, tmp_path, body, name="lm.py"):
        from bigdl_tpu.analysis.lint import lint_paths
        d = tmp_path / "serving"
        d.mkdir(exist_ok=True)
        (d / name).write_text(body, encoding="utf-8")
        return [f for f in lint_paths([str(tmp_path)])
                if f.rule == "untraced-terminal-verdict"]

    def test_flags_direct_raise_outside_chokes(self, tmp_path):
        found = self._lint(tmp_path,
                           "def _dispatch(self, req):\n"
                           "    raise Overloaded('no', queue_depth=1,\n"
                           "                     max_depth=1)\n")
        assert len(found) == 1 and found[0].line == 2
        assert "Overloaded" in found[0].message

    def test_flags_raise_of_bound_name(self, tmp_path):
        found = self._lint(tmp_path,
                           "def _dispatch(self, req):\n"
                           "    err = DeadlineExceeded('late')\n"
                           "    err.extra = 1\n"
                           "    raise err\n")
        assert len(found) == 1 and found[0].line == 4

    def test_flags_raw_finish_outside_accounting_chokes(self, tmp_path):
        found = self._lint(tmp_path,
                           "def _dispatch(self, req):\n"
                           "    req._finish('shed', error=None)\n")
        assert len(found) == 1
        assert "_finish" in found[0].message

    def test_accepts_choke_functions_and_minted_rejections(self, tmp_path):
        assert self._lint(
            tmp_path,
            "def _validate(self, row):\n"
            "    raise ServingDataError('bad', index=0)\n"
            "def generate(self, prompts):\n"
            "    raise ServingDataError('bad', index=0)\n"
            "def submit(self, row):\n"
            "    raise self._reject_locked('queue full')\n"
            "def _finish_stream(self, stream, outcome):\n"
            "    stream._finish(outcome, error=None)\n") == []

    def test_out_of_scope_files_are_ignored(self, tmp_path):
        from bigdl_tpu.analysis.lint import lint_paths
        (tmp_path / "optim.py").write_text(
            "def run():\n    raise Overloaded('x')\n", encoding="utf-8")
        assert [f for f in lint_paths([str(tmp_path)])
                if f.rule == "untraced-terminal-verdict"] == []

    def test_production_serving_and_fleet_are_clean(self):
        from bigdl_tpu.analysis.lint import lint_paths
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        targets = [os.path.join(repo, "bigdl_tpu", "serving"),
                   os.path.join(repo, "bigdl_tpu", "fleet")]
        assert [f for f in lint_paths(targets)
                if f.rule == "untraced-terminal-verdict"] == []
