"""Smoke tests for the model-zoo Train drivers (synthetic data mode).

Reference analog: the Train mains are exercised in integration jobs; here
each CLI runs a few iterations end-to-end on the virtual mesh, and the
LeNet driver round-trips its --model/--state resume flags.
"""

import os

import numpy as np
import pytest

from bigdl_tpu.models.lenet import train as lenet_train
from bigdl_tpu.models.vgg import train as vgg_train
from bigdl_tpu.models.resnet import train as resnet_train
from bigdl_tpu.models.rnn import train as rnn_train
from bigdl_tpu.models.textclassifier import train as tc_train


class TestTrainDrivers:
    def test_lenet_synthetic_converges(self):
        model = lenet_train.main(["--synthetic", "256", "-b", "64",
                                  "-e", "4", "-r", "0.2"])
        w, _ = model.get_parameters()
        assert np.all(np.isfinite(np.asarray(w)))

    @pytest.mark.slow
    def test_lenet_checkpoint_resume_flags(self, tmp_path):
        ckpt = str(tmp_path / "ckpt")
        lenet_train.main(["--synthetic", "128", "-b", "64", "-e", "2",
                          "--checkpoint", ckpt])
        snaps = sorted(f for f in os.listdir(ckpt) if f.startswith("model."))
        assert snaps, "no snapshot written"
        n = snaps[-1].split(".")[1]
        model = lenet_train.main([
            "--synthetic", "128", "-b", "64", "-e", "4",
            "--model", os.path.join(ckpt, f"model.{n}"),
            "--state", os.path.join(ckpt, f"optimMethod.{n}")])
        w, _ = model.get_parameters()
        assert np.all(np.isfinite(np.asarray(w)))

    @pytest.mark.slow
    def test_vgg_synthetic_smoke(self):
        vgg_train.main(["--synthetic", "64", "-b", "16",
                        "--max-iteration", "3"])

    @pytest.mark.slow
    def test_vgg_distributed_partitions(self):
        vgg_train.main(["--synthetic", "128", "-b", "32",
                        "--max-iteration", "3", "--partitions", "8"])

    @pytest.mark.slow
    def test_resnet_cifar_synthetic_smoke(self):
        resnet_train.main(["--synthetic", "64", "-b", "16", "--depth", "20",
                           "--max-iteration", "3"])

    @pytest.mark.slow
    def test_rnn_lm_synthetic(self):
        rnn_train.main(["--synthetic", "128", "-b", "32", "-e", "2",
                        "--cell", "rnn"])

    @pytest.mark.slow
    def test_lstm_lm_synthetic(self):
        rnn_train.main(["--synthetic", "64", "-b", "16",
                        "--max-iteration", "4", "--cell", "lstm"])

    @pytest.mark.slow
    def test_textclassifier_synthetic_smoke(self):
        tc_train.main(["--synthetic", "32", "-b", "8",
                       "--max-iteration", "2"])

    @pytest.mark.slow
    def test_autoencoder_synthetic(self):
        from bigdl_tpu.models.autoencoder import train as ae_train
        model = ae_train.main(["--synthetic", "256", "-b", "64", "-e", "3"])
        w, _ = model.get_parameters()
        assert np.all(np.isfinite(np.asarray(w)))

    @pytest.mark.slow
    def test_inception_synthetic_smoke(self):
        from bigdl_tpu.models.inception import train as inc_train
        inc_train.main(["--synthetic", "16", "-b", "8", "--classes", "4",
                        "--max-iteration", "2"])

    @pytest.mark.slow
    def test_lenet_eval_only_driver(self, tmp_path):
        from bigdl_tpu.models.lenet import test as lenet_test
        ckpt = str(tmp_path / "ckpt")
        lenet_train.main(["--synthetic", "128", "-b", "64", "-e", "2",
                          "--checkpoint", ckpt])
        snaps = sorted(f for f in os.listdir(ckpt) if f.startswith("model."))
        results = lenet_test.main(["--synthetic", "64",
                                   "--model", os.path.join(ckpt, snaps[-1])])
        assert results[0][0].name == "Top1Accuracy"

    @pytest.mark.slow
    def test_treelstm_sentiment_synthetic(self):
        from bigdl_tpu.models.treelstm import train as tree_train
        model = tree_train.main(["--synthetic", "128", "-b", "32",
                                 "-e", "15", "-r", "0.5"])
        from bigdl_tpu.models.treelstm.train import _synthetic
        from bigdl_tpu.optim.evaluator import Evaluator
        import bigdl_tpu.optim as optim
        val = _synthetic(64, seed=3)
        acc = Evaluator(model).test(
            val, [optim.Top1Accuracy()], 32)[0][1].final_result()
        assert acc > 0.8, f"TreeLSTM failed to learn: acc={acc}"
