"""End-to-end training through the public API for conv/pool/BN models.

Closes the round-1 blind spot: every test here pushes a model containing
pooling (and/or batch-norm) through ``Optimizer.create(...).optimize()`` —
the fused jitted step — rather than driving forward/backward by hand.
Reference analog: ``optim/LocalOptimizerSpec`` convergence tests, applied to
the conv models the BASELINE configs actually train.
"""

import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import LocalDataSet, Sample, SampleToMiniBatch
from bigdl_tpu.models.lenet import lenet5
from bigdl_tpu.optim.evaluator import Evaluator


def synthetic_digit_images(n, side=28, n_classes=4, seed=0, channels=None):
    """Class-separable images: class k lights up quadrant k (+noise)."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, n_classes, size=n)
    samples = []
    half = side // 2
    for lab in labels:
        img = rng.normal(0.0, 0.1, size=(side, side)).astype(np.float32)
        r, c = divmod(int(lab) % 4, 2)
        img[r * half:(r + 1) * half, c * half:(c + 1) * half] += 1.0
        if channels:
            img = np.repeat(img[None, :, :], channels, axis=0)
        samples.append(Sample(img, np.float32(lab + 1)))
    return samples


def _train(model, samples, lr=0.1, iters=40, batch=32):
    ds = LocalDataSet(samples).transform(SampleToMiniBatch(batch))
    opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
    opt.set_optim_method(optim.SGD(learning_rate=lr))
    opt.set_end_when(optim.max_iteration(iters))
    return opt.optimize()


class TestConvPoolE2E:
    def test_lenet_trains_through_public_api(self):
        """BASELINE config #1's model through Optimizer.create().optimize()."""
        samples = synthetic_digit_images(256, n_classes=4)
        model = _train(lenet5(4), samples, lr=0.2, iters=60)
        acc = Evaluator(model).test(
            samples, [optim.Top1Accuracy()], 32)[0][1].final_result()
        assert acc > 0.9, f"LeNet failed to learn quadrant data: acc={acc}"

    def test_avg_pool_model_trains(self):
        samples = synthetic_digit_images(128, side=16, n_classes=4)
        m = (nn.Sequential()
             .add(nn.Reshape((1, 16, 16)))
             .add(nn.SpatialConvolution(1, 8, 3, 3, 1, 1, 1, 1))
             .add(nn.ReLU())
             .add(nn.SpatialAveragePooling(2, 2, 2, 2))
             .add(nn.SpatialConvolution(8, 8, 3, 3, 1, 1, 1, 1))
             .add(nn.ReLU())
             .add(nn.SpatialMaxPooling(2, 2, 2, 2))
             .add(nn.Reshape((8 * 4 * 4,)))
             .add(nn.Linear(8 * 4 * 4, 4))
             .add(nn.LogSoftMax()))
        model = _train(m, samples, lr=0.1, iters=50)
        acc = Evaluator(model).test(
            samples, [optim.Top1Accuracy()], 32)[0][1].final_result()
        assert acc > 0.9

    def test_batchnorm_conv_model_trains(self):
        """VGG-style conv+BN+pool block through the fused step: exercises
        non-trainable state (running stats) threading inside jit."""
        samples = synthetic_digit_images(128, side=16, n_classes=4, channels=3)
        m = (nn.Sequential()
             .add(nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1))
             .add(nn.SpatialBatchNormalization(8))
             .add(nn.ReLU())
             .add(nn.SpatialMaxPooling(2, 2, 2, 2).ceil())
             .add(nn.SpatialConvolution(8, 8, 3, 3, 1, 1, 1, 1))
             .add(nn.SpatialBatchNormalization(8))
             .add(nn.ReLU())
             .add(nn.SpatialMaxPooling(2, 2, 2, 2))
             .add(nn.Reshape((8 * 4 * 4,)))
             .add(nn.Linear(8 * 4 * 4, 4))
             .add(nn.LogSoftMax()))
        model = _train(m, samples, lr=0.1, iters=60)
        model.evaluate()
        acc = Evaluator(model).test(
            samples, [optim.Top1Accuracy()], 32)[0][1].final_result()
        assert acc > 0.9
        # running stats must have moved off their init
        bn_state = model.state[1]
        assert float(np.abs(np.asarray(bn_state["running_mean"])).sum()) > 0

    def test_dropout_pool_model_trains(self):
        """Stochastic layer + pooling: rng threading through the fused step."""
        samples = synthetic_digit_images(128, side=16, n_classes=4)
        m = (nn.Sequential()
             .add(nn.Reshape((1, 16, 16)))
             .add(nn.SpatialConvolution(1, 8, 3, 3, 1, 1, 1, 1))
             .add(nn.ReLU())
             .add(nn.SpatialMaxPooling(2, 2, 2, 2))
             .add(nn.Dropout(0.2))
             .add(nn.Reshape((8 * 8 * 8,)))
             .add(nn.Linear(8 * 8 * 8, 4))
             .add(nn.LogSoftMax()))
        model = _train(m, samples, lr=0.1, iters=50)
        model.evaluate()
        acc = Evaluator(model).test(
            samples, [optim.Top1Accuracy()], 32)[0][1].final_result()
        assert acc > 0.85


class TestMixedPrecision:
    def test_bf16_lenet_converges(self):
        """set_precision('bf16'): bf16 compute, fp32 master weights."""
        import jax.numpy as jnp
        samples = synthetic_digit_images(256, n_classes=4)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(32))
        model = lenet5(4)
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.2))
        opt.set_precision("bf16")
        opt.set_end_when(optim.max_iteration(60))
        trained = opt.optimize()
        # master weights stay fp32
        import jax
        for leaf in jax.tree_util.tree_leaves(trained.params):
            assert leaf.dtype == jnp.float32
        acc = Evaluator(trained).test(
            samples, [optim.Top1Accuracy()], 32)[0][1].final_result()
        assert acc > 0.9, f"bf16 training failed to converge: acc={acc}"

    def test_bf16_distributed_converges(self):
        import jax, jax.numpy as jnp
        from bigdl_tpu.dataset.dataset import ShardedDataSet
        from bigdl_tpu.dataset.datasets import synthetic_separable
        samples = synthetic_separable(256, 4, n_classes=3, seed=9)
        ds = ShardedDataSet(samples, 8).transform(SampleToMiniBatch(64, 8))
        model = (nn.Sequential().add(nn.Linear(4, 16)).add(nn.Tanh())
                 .add(nn.Linear(16, 3)).add(nn.LogSoftMax()))
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.5))
        opt.set_precision("bf16")
        opt.set_end_when(optim.max_epoch(12))
        trained = opt.optimize()
        for leaf in jax.tree_util.tree_leaves(trained.params):
            assert leaf.dtype == jnp.float32
        acc = Evaluator(trained).test(
            samples, [optim.Top1Accuracy()], 64)[0][1].final_result()
        assert acc > 0.9

    def test_invalid_precision_rejected(self):
        samples = synthetic_digit_images(32)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(16))
        opt = optim.Optimizer.create(lenet5(4), ds, nn.ClassNLLCriterion())
        import pytest
        with pytest.raises(ValueError, match="precision"):
            opt.set_precision("fp8")
