"""Resilient compilation: persistent executable cache, AOT warmup under a
watchdog, and shape-bucketed execution (ISSUE 8).

The contract under test (utils/compile_cache.py):

- a second trainer over the same model+topology reaches its first device
  step with ZERO fresh compiles (cache hit per fused step) and
  bit-identical step results;
- torn / uncommitted / corrupt / version-skewed / foreign-topology
  entries are a logged MISS and a recompile — never a crash — with
  exact numerical parity after the fallback;
- a wedged compile is detected within ``bigdl.compile.timeoutSec``,
  aborted with a diagnosed ``CompileTimeoutError``, and the trainer's
  retry loop restores-and-retries it like a divergence;
- with ``bigdl.compile.buckets`` configured, ragged validation/predict
  batches hit only pre-compiled signatures — proven by the PR 4 strict
  retrace sentinel observing zero post-warmup retraces.
"""

import json
import os

import numpy as np
import jax
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.optim.optimizer import Optimizer
from bigdl_tpu.utils import chaos, compile_cache, config
from bigdl_tpu.utils.compile_cache import (CachedStep, CompileCache,
                                           CompileTimeoutError,
                                           backend_fingerprint, bucket_size,
                                           pad_batch, slice_rows,
                                           tracked_jit)
from bigdl_tpu import telemetry


@pytest.fixture
def cache_dir(tmp_path):
    d = str(tmp_path / "ccache")
    config.set_property("bigdl.compile.cacheDir", d)
    yield d
    config.clear_property("bigdl.compile.cacheDir")


@pytest.fixture(autouse=True)
def _no_lock_sleep(monkeypatch):
    monkeypatch.setattr(compile_cache, "_sleep", lambda s: None)
    yield


def _counter(name):
    return telemetry.REGISTRY.counter(name).value


def _pin_shuffle():
    """Training determinism across two runs in one process: the dataset
    shuffle draws from the thread-local generator."""
    from bigdl_tpu.utils.random_generator import RandomGenerator
    RandomGenerator.RNG().set_seed(1234)


def _samples(n=64, dim=8, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    return [Sample(rng.normal(size=(dim,)).astype(np.float32),
                   np.int64(i % classes + 1)) for i in range(n)]


def _trainer(samples, iterations=6):
    m = (nn.Sequential().add(nn.Linear(8, 16)).add(nn.Tanh())
         .add(nn.Linear(16, 3)).add(nn.LogSoftMax()))
    m.reset(jax.random.PRNGKey(7))
    o = Optimizer.create(m, samples, nn.ClassNLLCriterion(), batch_size=16)
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    o.set_end_when(optim.max_iteration(iterations))
    return o, m


def _flat(params):
    return np.concatenate([np.ravel(np.asarray(x))
                           for x in jax.tree_util.tree_leaves(params)])


def _cached_of(o):
    step = o._step_fn
    return getattr(step, "__wrapped__", step)


# ---------------------------------------------------------------------------
# store lifecycle
# ---------------------------------------------------------------------------

class TestStoreLifecycle:
    def test_cold_miss_write_commit(self, cache_dir):
        """A cold run compiles, stores a committed entry (payload +
        manifest + commit marker, in that order), and counts a miss."""
        samples = _samples()
        _pin_shuffle()
        o, m = _trainer(samples)
        o.optimize()
        cached = _cached_of(o)
        assert cached.compiles == 1 and cached.cache_misses == 1
        assert cached.cache_hits == 0
        names = sorted(os.listdir(cache_dir))
        keys = {n.rsplit(".", 1)[0] for n in names if n != "lock"}
        assert len(keys) == 1
        key = keys.pop()
        assert {f"{key}.bin", f"{key}.json", f"{key}.commit"} <= set(names)
        with open(os.path.join(cache_dir, f"{key}.json")) as f:
            manifest = json.load(f)
        assert manifest["label"] == "local"
        # payloads checksum at C speed with the algo recorded (the PR 2
        # helper — the pure-Python crc32c walk would cost seconds per
        # multi-MB executable on the very path the cache accelerates)
        from bigdl_tpu.utils.checkpoint_manager import payload_checksum
        assert manifest["algo"] == payload_checksum(b"")[0]
        assert manifest["fingerprint"] == backend_fingerprint()
        assert manifest["topology"]["step"] == "local"
        assert manifest["bytes"] == os.path.getsize(
            os.path.join(cache_dir, f"{key}.bin"))

    def test_warm_hit_bit_identical(self, cache_dir):
        """The warm-start contract: a SECOND trainer (fresh step object,
        as a new process would build) loads the executable instead of
        compiling and trains to bit-identical weights."""
        samples = _samples()
        _pin_shuffle()
        o1, m1 = _trainer(samples)
        o1.optimize()
        _pin_shuffle()
        o2, m2 = _trainer(samples)
        o2.optimize()
        cached = _cached_of(o2)
        assert cached.cache_hits == 1, "warm start must load, not compile"
        assert cached.compiles == 0 and cached.cache_misses == 0
        assert np.array_equal(_flat(m1.params), _flat(m2.params)), \
            "warm-start step results must be bit-identical to cold"

    def test_corrupt_entry_skipped_with_recompile_parity(self, cache_dir):
        """A bit-rotted committed payload fails its manifest checksum,
        degrades to a recompile (never a crash), and the recompiled run
        reaches exact numerical parity with the cold run."""
        samples = _samples()
        _pin_shuffle()
        o1, m1 = _trainer(samples)
        o1.optimize()
        key = next(n[:-4] for n in os.listdir(cache_dir)
                   if n.endswith(".bin"))
        p = os.path.join(cache_dir, f"{key}.bin")
        blob = bytearray(open(p, "rb").read())
        blob[len(blob) // 2] ^= 0x10
        with open(p, "wb") as f:
            f.write(bytes(blob))
        errors_before = _counter("Compile/cache_errors")
        _pin_shuffle()
        o2, m2 = _trainer(samples)
        o2.optimize()
        cached = _cached_of(o2)
        assert cached.cache_hits == 0 and cached.compiles == 1
        assert _counter("Compile/cache_errors") == errors_before + 1
        assert np.array_equal(_flat(m1.params), _flat(m2.params))

    def test_torn_and_uncommitted_entries_skipped(self, cache_dir):
        """Newest-first degradation over damaged entries: a truncated
        payload and a commit-less (torn-write) entry are both misses."""
        samples = _samples()
        _pin_shuffle()
        o1, _ = _trainer(samples)
        o1.optimize()
        key = next(n[:-4] for n in os.listdir(cache_dir)
                   if n.endswith(".bin"))
        # truncated payload (the realistic torn write: rename committed
        # a short object)
        p = os.path.join(cache_dir, f"{key}.bin")
        blob = open(p, "rb").read()
        with open(p, "wb") as f:
            f.write(blob[:len(blob) // 2])
        o2, _ = _trainer(samples)
        o2.optimize()
        assert _cached_of(o2).cache_hits == 0
        # uncommitted: the commit marker never landed
        os.unlink(os.path.join(cache_dir, f"{key}.commit"))
        o3, _ = _trainer(samples)
        o3.optimize()
        c3 = _cached_of(o3)
        assert c3.cache_hits == 0 and c3.compiles == 1

    def test_version_skew_is_miss_not_crash(self, cache_dir):
        samples = _samples()
        o1, _ = _trainer(samples)
        o1.optimize()
        key = next(n[:-5] for n in os.listdir(cache_dir)
                   if n.endswith(".json"))
        man_p = os.path.join(cache_dir, f"{key}.json")
        with open(man_p) as f:
            manifest = json.load(f)
        manifest["fingerprint"]["jax"] = "999.0.0"
        mbytes = json.dumps(manifest, sort_keys=True).encode()
        with open(man_p, "wb") as f:
            f.write(mbytes)
        from bigdl_tpu.visualization.crc32c import crc32c
        with open(os.path.join(cache_dir, f"{key}.commit"), "wb") as f:
            f.write(f"{crc32c(mbytes):08x}\n".encode())
        o2, _ = _trainer(samples)
        o2.optimize()
        c2 = _cached_of(o2)
        assert c2.cache_hits == 0 and c2.compiles == 1

    def test_newer_schema_is_miss_not_crash(self, cache_dir):
        cc = CompileCache(cache_dir)
        cc.store("deadbeef", b"payload", "x", "sig", None,
                 backend_fingerprint())
        man_p = os.path.join(cache_dir, "deadbeef.json")
        with open(man_p) as f:
            manifest = json.load(f)
        manifest["version"] = 99
        mbytes = json.dumps(manifest, sort_keys=True).encode()
        with open(man_p, "wb") as f:
            f.write(mbytes)
        from bigdl_tpu.visualization.crc32c import crc32c
        with open(os.path.join(cache_dir, "deadbeef.commit"), "wb") as f:
            f.write(f"{crc32c(mbytes):08x}\n".encode())
        assert cc.load("deadbeef", None, backend_fingerprint()) is None

    def test_topology_mismatch_is_miss(self, cache_dir):
        cc = CompileCache(cache_dir)
        topo = {"device_count": 8, "axes": {"data": 8}, "step": "shard_map",
                "slot_axis": "data"}
        fp = backend_fingerprint()
        cc.store("cafe01", b"payload", "x", "sig", topo, fp)
        assert cc.load("cafe01", topo, fp) == b"payload"
        other = dict(topo, device_count=4, axes={"data": 4})
        assert cc.load("cafe01", other, fp) is None

    def test_concurrent_writer_lock(self, cache_dir):
        """A held (fresh) lock makes the second writer back off and SKIP
        the store — no corruption, no exception; a stale lock from a
        hard-killed writer is stolen."""
        os.makedirs(cache_dir, exist_ok=True)
        lock = os.path.join(cache_dir, CompileCache.LOCK_NAME)
        with open(lock, "w") as f:
            f.write("held\n")
        cc = CompileCache(cache_dir)
        cc.lock_timeout = 0.05
        fp = backend_fingerprint()
        assert cc.store("aa01", b"data", "x", "sig", None, fp) is False
        assert not os.path.exists(os.path.join(cache_dir, "aa01.bin"))
        assert os.path.exists(lock), "a held lock must not be removed"
        # stale lock: pretend the holder died long ago
        old = os.path.getmtime(lock) - 10_000
        os.utime(lock, (old, old))
        cc.lock_stale = 600.0
        assert cc.store("aa01", b"data", "x", "sig", None, fp) is True
        assert cc.load("aa01", None, fp) == b"data"
        assert not os.path.exists(lock), "the writer releases the lock"

    def test_gc_keep_last_commit_first(self, cache_dir, monkeypatch):
        """Retention keeps the newest ``keepLast`` entries; eviction
        removes the commit marker FIRST (an interrupted GC leaves an
        ignored uncommitted entry, never a committed half-entry)."""
        cc = CompileCache(cache_dir, keep_last=2)
        fp = backend_fingerprint()
        now = [1000.0]

        def tick():
            now[0] += 10
            return now[0]

        monkeypatch.setattr(compile_cache.time, "time", tick)
        for i in range(4):
            cc.store(f"e{i:02d}", b"x" * 8, "x", "sig", None, fp)
        left = {n for n in os.listdir(cache_dir) if n.endswith(".commit")}
        assert left == {"e02.commit", "e03.commit"}
        # eviction order: commit before payload before manifest
        removed = []
        real_unlink = os.unlink
        monkeypatch.setattr(
            os, "unlink",
            lambda p: (removed.append(os.path.basename(p)),
                       real_unlink(p))[1])
        cc.keep_last = 1
        cc.gc()
        assert removed[0] == "e02.commit"
        assert removed.index("e02.commit") < removed.index("e02.bin") < \
            removed.index("e02.json")


# ---------------------------------------------------------------------------
# chaos: fault-injection proofs
# ---------------------------------------------------------------------------

class TestChaos:
    def test_corrupt_compile_cache_at_falls_back(self, cache_dir):
        """``bigdl.chaos.corruptCompileCacheAt=1`` bit-flips the first
        entry written (post-checksum): the cold run is untouched, the
        warm run detects the corruption, recompiles, and reaches exact
        weight parity."""
        samples = _samples()
        config.set_property("bigdl.chaos.corruptCompileCacheAt", 1)
        chaos.install()
        try:
            _pin_shuffle()
            o1, m1 = _trainer(samples)
            o1.optimize()
        finally:
            chaos.uninstall()
            config.clear_property("bigdl.chaos.corruptCompileCacheAt")
        _pin_shuffle()
        o2, m2 = _trainer(samples)
        o2.optimize()
        c2 = _cached_of(o2)
        assert c2.cache_hits == 0 and c2.compiles == 1, \
            "the corrupted entry must degrade to a recompile"
        assert np.array_equal(_flat(m1.params), _flat(m2.params))

    def test_hang_compile_watchdog_aborts_with_diagnosis(self):
        """``bigdl.chaos.hangCompileAt`` wedges the compile; the
        watchdog detects it within ``bigdl.compile.timeoutSec`` and the
        raised ``CompileTimeoutError`` names the signature+topology."""
        config.set_property("bigdl.compile.timeoutSec", 0.2)
        config.set_property("bigdl.chaos.hangCompileAt", "1:1.2")
        chaos.install()
        fired_before = _counter("Compile/watchdog_fired")
        step = tracked_jit(lambda x: x * 2, label="wedge",
                           topology={"device_count": 1, "step": "local"})
        t0 = telemetry.clock_ns()
        try:
            with pytest.raises(CompileTimeoutError) as ei:
                step(np.ones((4,), np.float32))
        finally:
            chaos.uninstall()
            config.clear_property("bigdl.compile.timeoutSec")
            config.clear_property("bigdl.chaos.hangCompileAt")
        wall_s = (telemetry.clock_ns() - t0) / 1e9
        assert "wedge" in str(ei.value) and "topology" in str(ei.value)
        assert ei.value.diagnosis["label"] == "wedge"
        assert _counter("Compile/watchdog_fired") == fired_before + 1
        # detected at ~timeout; the abort lands within one 20 ms chaos
        # sleep slice of the injection — all well inside the wedge span
        assert wall_s < 1.1, \
            f"abort took {wall_s:.2f}s — watchdog did not cut the wedge"

    def test_hung_compile_retried_like_divergence(self, cache_dir):
        """End to end: a wedged compile inside optimize() aborts via
        CompileTimeoutError and the retry loop RETRIES it (chaos wedges
        once), so training completes — classified like divergence
        (restore/retry), unlike Preempted (leave)."""
        samples = _samples()
        # the timeout must clear a REAL compile of this step (~0.3 s on
        # a loaded 1-core host) while still cutting the 6 s wedge fast
        config.set_property("bigdl.compile.timeoutSec", 2.0)
        config.set_property("bigdl.chaos.hangCompileAt", "1:6.0")
        config.set_property("bigdl.failure.retryTimeInterval", 0.0)
        chaos.install()
        try:
            _pin_shuffle()
            o, m = _trainer(samples)
            o.optimize()
        finally:
            chaos.uninstall()
            for k in ("bigdl.compile.timeoutSec",
                      "bigdl.chaos.hangCompileAt",
                      "bigdl.failure.retryTimeInterval"):
                config.clear_property(k)
        assert o.optim_method.state.get("evalCounter", 0) >= 6, \
            "training must complete after the compile-timeout retry"


# ---------------------------------------------------------------------------
# shape buckets
# ---------------------------------------------------------------------------

class TestBuckets:
    def test_bucket_size_rounding(self):
        buckets = [8, 16, 32]
        assert bucket_size(1, buckets) == 8
        assert bucket_size(8, buckets) == 8
        assert bucket_size(9, buckets) == 16
        assert bucket_size(32, buckets) == 32
        assert bucket_size(33, buckets) == 64   # multiples of the largest
        assert bucket_size(65, buckets) == 96

    def test_pad_and_slice_roundtrip(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        padded = pad_batch({"a": x}, 3, 8)
        assert padded["a"].shape == (8, 4)
        np.testing.assert_array_equal(padded["a"][:3], x)
        np.testing.assert_array_equal(padded["a"][3:],
                                      np.repeat(x[-1:], 5, axis=0))
        back = slice_rows(padded, 3)
        np.testing.assert_array_equal(back["a"], x)

    def _eval_model(self):
        m = (nn.Sequential().add(nn.Linear(8, 16)).add(nn.ReLU())
             .add(nn.Linear(16, 3)).add(nn.LogSoftMax()))
        m.reset(jax.random.PRNGKey(3))
        m._ensure_init()
        return m

    def test_ragged_validation_zero_retraces_strict(self):
        """THE retrace gate (acceptance criterion): ragged validation
        batch sizes under strict sentinel + buckets complete with zero
        post-warmup retraces AND identical metric results to the
        unbucketed run."""
        from bigdl_tpu.optim.evaluator import evaluate_dataset
        from bigdl_tpu.optim.validation_method import Top1Accuracy, Loss
        samples = _samples(n=57, seed=5)   # 57 = ragged under any batch
        m = self._eval_model()
        methods = [Top1Accuracy(), Loss(nn.ClassNLLCriterion())]

        def run(batch):
            from bigdl_tpu.dataset.transformer import SampleToMiniBatch
            batches = list(SampleToMiniBatch(batch)(iter(samples)))
            return evaluate_dataset(m, batches, methods)

        # baseline, no buckets (fresh eval cache)
        ref = [(meth.name, r.final_result()) for meth, r in run(16)]
        m._eval_jit = {}
        config.set_property("bigdl.compile.buckets", "4,8,16")
        try:
            # ragged sizes: 16,16,16,9 -> buckets 16 and 16(pad);
            # then batch 10 -> bucket 16 again, 7 -> 16/8 ...
            got = [(meth.name, r.final_result()) for meth, r in run(16)]
            got2 = [(meth.name, r.final_result()) for meth, r in run(10)]
            fn = m._eval_jit[id(None)]
            sentinel = fn.sentinel
            assert sentinel.retraces == 0, sentinel.last_diff
            cached = fn.__wrapped__
            # every signature the ragged runs produced was pre-compiled
            assert len(cached._mem) >= 3   # 16 + bucket variants 4, 8
        finally:
            config.clear_property("bigdl.compile.buckets")
            m._eval_jit = {}
        for (n1, a), (n2, b) in zip(ref, got):
            assert n1 == n2 and abs(a - b) < 1e-6, \
                "bucketed metrics must match the unbucketed run"
        for (n1, a), (n2, b) in zip(ref, got2):
            assert n1 == n2 and abs(a - b) < 1e-6

    def test_unbucketed_signature_is_a_retrace(self):
        """The gate has teeth: a shape that escapes the bucket plan (a
        direct eval call with an un-bucketed batch size) is a
        post-warmup retrace — strict raises."""
        from bigdl_tpu.optim.evaluator import _eval_forward
        from bigdl_tpu.analysis.retrace import RetraceError
        from bigdl_tpu.engine import to_device
        m = self._eval_model()
        config.set_property("bigdl.compile.buckets", "4,8")
        try:
            fwd = _eval_forward(m)
            fwd(to_device(np.zeros((4, 8), np.float32)))
            fwd(to_device(np.zeros((8, 8), np.float32)))   # bucket: fine
            with pytest.raises(RetraceError):
                fwd(to_device(np.zeros((5, 8), np.float32)))
        finally:
            config.clear_property("bigdl.compile.buckets")
            m._eval_jit = {}

    def test_sharded_eval_bucket_variants(self):
        """Mesh-sharded eval + buckets: variants divisible by the data
        axis precompile from abstract specs and SERVE later concrete
        ragged batches; non-divisible variants are skipped (those
        batches run the local fallback) — never fatal, zero retraces,
        metrics identical to the unbucketed sharded run."""
        from jax.sharding import Mesh
        from bigdl_tpu.optim.evaluator import evaluate_dataset
        from bigdl_tpu.optim.validation_method import Top1Accuracy
        from bigdl_tpu.dataset.transformer import SampleToMiniBatch
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device virtual mesh")
        mesh = Mesh(np.array(jax.devices()).reshape(8,), ("data",))
        m = self._eval_model()
        samples = _samples(n=57, seed=5)

        def run(batch):
            batches = list(SampleToMiniBatch(batch)(iter(samples)))
            return [(meth.name, r.final_result()) for meth, r in
                    evaluate_dataset(m, batches, [Top1Accuracy()],
                                     mesh=mesh)]

        ref = run(16)
        m._eval_jit = {}
        config.set_property("bigdl.compile.buckets", "4,8,16")
        try:
            got = run(16)           # 16,16,16,9->16: one sharded sig
            got2 = run(13)          # 13->16 hit; 5->8: the spec variant
            fn = m._eval_jit[id(mesh)]
            assert fn.sentinel.retraces == 0, fn.sentinel.last_diff
            # bucket 8 precompiled from specs; bucket 4 (not divisible
            # by the 8-way axis) skipped without killing the eval
            assert len(fn.__wrapped__._mem) == 2
        finally:
            config.clear_property("bigdl.compile.buckets")
            m._eval_jit = {}
        assert got == ref, "bucketed sharded metrics must match unbucketed"

    def test_oversize_batches_are_in_plan(self):
        """Batch sizes beyond the largest bucket round to its multiples
        — sizes the precompiler cannot enumerate ahead.  Two distinct
        oversize predict sizes of the SAME signature family must compile
        as in-plan warmup, not raise as retraces (they followed the
        bucket plan); a call differing in anything but the batch dim is
        a new family and still trips the strict gate."""
        from bigdl_tpu.optim.predictor import Predictor
        from bigdl_tpu.analysis.retrace import RetraceError
        from bigdl_tpu.engine import to_device
        m = self._eval_model()
        samples = [Sample(np.random.RandomState(i).normal(
            size=(8,)).astype(np.float32), np.float32(1))
            for i in range(48)]
        config.set_property("bigdl.compile.buckets", "4,8")
        try:
            a = Predictor(m).predict(samples, batch_size=16)  # 16 = 2x8
            b = Predictor(m).predict(samples, batch_size=24)  # 24 = 3x8
            fn = m._eval_jit[id(None)]
            assert fn.sentinel.retraces == 0, fn.sentinel.last_diff
            np.testing.assert_array_equal(a, b)
            # the gate keeps its teeth: same batch dim, different
            # feature width = a different family = a strict raise
            with pytest.raises(RetraceError):
                fn(m.params, m.state, to_device(
                    np.zeros((8, 9), np.float32)))
        finally:
            config.clear_property("bigdl.compile.buckets")
            m._eval_jit = {}

    def test_predictor_bucketed_parity(self):
        """Ragged predict batches under buckets: outputs identical to
        the unbucketed run, and execution stays inside the precompiled
        signature set."""
        from bigdl_tpu.optim.predictor import Predictor
        m = self._eval_model()
        samples = [Sample(np.random.RandomState(i).normal(
            size=(8,)).astype(np.float32), np.float32(1))
            for i in range(11)]                       # 8 + ragged 3
        ref = Predictor(m).predict(samples, batch_size=8)
        m._eval_jit = {}
        config.set_property("bigdl.compile.buckets", "4,8")
        try:
            got = Predictor(m).predict(samples, batch_size=8)
            fn = m._eval_jit[id(None)]
            assert fn.sentinel.retraces == 0
        finally:
            config.clear_property("bigdl.compile.buckets")
            m._eval_jit = {}
        np.testing.assert_array_equal(ref, got)


# ---------------------------------------------------------------------------
# AOT warmup phase
# ---------------------------------------------------------------------------

class TestWarmup:
    def test_warmup_gauge_and_prestep_compile(self, cache_dir):
        """The driver's warmup phase compiles before step 1 and charts
        ``Compile/warmup_ms``; the step object is warm by the time the
        first iteration dispatches."""
        samples = _samples()
        o, _ = _trainer(samples, iterations=3)
        o.optimize()
        snap = telemetry.REGISTRY.snapshot()["gauges"]
        assert snap.get("Compile/warmup_ms", 0) > 0
        assert _cached_of(o).warm

    def test_second_optimize_reuses_in_memory(self, cache_dir):
        samples = _samples()
        o, _ = _trainer(samples, iterations=3)
        o.optimize()
        cached = _cached_of(o)
        o.set_end_when(optim.max_iteration(6))
        o.optimize()
        assert cached.compiles == 1, \
            "a second optimize() must reuse the in-memory executable"


# ---------------------------------------------------------------------------
# lint: the untracked-jit rule
# ---------------------------------------------------------------------------

class TestUntrackedJitLint:
    def _lint(self, tmp_path, source, name="pkg/mod.py"):
        from bigdl_tpu.analysis.lint import lint_paths
        p = tmp_path / name
        p.parent.mkdir(parents=True, exist_ok=True)
        (p.parent / "__init__.py").write_text("")
        p.write_text(source)
        return [f.rule for f in lint_paths([str(p)])]

    def test_flags_jit_lower_compile(self, tmp_path):
        rules = self._lint(tmp_path, (
            "import jax\n"
            "f = jax.jit(lambda x: x)\n"
            "low = f.lower(x)\n"
            "exe = low.compile()\n"
            "@jax.jit\n"
            "def g(x):\n"
            "    return x\n"))
        assert rules.count("untracked-jit") == 4

    def test_ignores_str_lower_and_re_compile(self, tmp_path):
        rules = self._lint(tmp_path, (
            "import re\n"
            "s = 'ABC'.lower()\n"
            "rx = re.compile('a+')\n"))
        assert "untracked-jit" not in rules

    def test_inline_allow(self, tmp_path):
        rules = self._lint(tmp_path, (
            "import jax\n"
            "f = jax.jit(lambda x: x)  # lint: allow(untracked-jit)\n"))
        assert "untracked-jit" not in rules

    def test_wrapper_file_exempt(self, tmp_path):
        rules = self._lint(tmp_path, (
            "import jax\n"
            "f = jax.jit(lambda x: x)\n"
            "e = f.lower(1).compile()\n"), name="utils/compile_cache.py")
        assert "untracked-jit" not in rules


# ---------------------------------------------------------------------------
# concurrent readers of one cache dir (ISSUE 17 satellite)
# ---------------------------------------------------------------------------

class TestConcurrentReaders:
    def test_two_warm_loaders_while_a_third_writes(self, cache_dir):
        """The fleet hot-swap access pattern: replicas of a candidate
        warm-load the SAME committed entry concurrently while another
        engine's compile stores a brand-new one into the same directory
        — readers never observe a torn entry, never take a fresh
        compile, and serve bit-identical results."""
        import threading

        from bigdl_tpu.serving import ServingEngine

        config.set_property("bigdl.compile.buckets", "2,4")
        try:
            def eval_model(seed=7):
                m = (nn.Sequential().add(nn.Linear(4, 16)).add(nn.Tanh())
                     .add(nn.Linear(16, 3)))
                m.reset(jax.random.PRNGKey(seed))
                return m

            def step_of(model):
                fn = model._eval_jit[id(None)]
                return getattr(fn, "__wrapped__", fn)

            row = np.arange(4, dtype=np.float32)
            seeder = ServingEngine(eval_model())
            seeder.warmup(np.zeros((4,), np.float32))
            assert step_of(seeder.model).compiles >= 1
            want = seeder.submit(row).result(timeout=10.0)
            seeder.stop()

            barrier = threading.Barrier(3)
            results, errors = {}, []

            def reader(tag):
                try:
                    model = eval_model()
                    barrier.wait(timeout=10)
                    eng = ServingEngine(model)
                    eng.warmup(np.zeros((4,), np.float32))
                    results[tag] = (np.asarray(
                        eng.submit(row).result(timeout=10.0)),
                        step_of(model))
                    eng.stop()
                except Exception as e:       # surfaced after join
                    errors.append((tag, e))

            def writer():
                try:
                    cc = CompileCache(cache_dir)
                    fp = backend_fingerprint()
                    barrier.wait(timeout=10)
                    for i in range(20):
                        assert cc.store(f"feed{i:02d}", b"x" * 256,
                                        "probe", f"sig{i}", None, fp)
                except Exception as e:
                    errors.append(("writer", e))

            threads = [threading.Thread(target=reader, args=("r1",)),
                       threading.Thread(target=reader, args=("r2",)),
                       threading.Thread(target=writer)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert errors == []
            for tag in ("r1", "r2"):
                out, step = results[tag]
                assert step.compiles == 0, \
                    f"{tag} recompiled under a concurrent writer"
                assert step.cache_hits >= 1
                np.testing.assert_array_equal(out, want)
            # the writer's entries all committed despite the read storm
            cc = CompileCache(cache_dir)
            fp = backend_fingerprint()
            for i in range(20):
                assert cc.load(f"feed{i:02d}", None, fp) == b"x" * 256
        finally:
            config.clear_property("bigdl.compile.buckets")
