"""Channels-last (NHWC) compute path: layer/model parity across layouts,
the zero-interior-transpose HLO property, and inference conv+BN folding.

The contract under test (nn/layout.py): zoo models keep the Torch-style
NCHW public API but compute their conv trunk in NHWC — one boundary
transpose in, one out (or none when the exit map is 1x1 and a reshape
suffices) — and layer outputs/gradients match the NCHW path to float
rounding.
"""

import re

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import bigdl_tpu.nn as nn


RNG = np.random.RandomState(7)


def _x(*shape):
    return jnp.asarray(RNG.normal(size=shape).astype(np.float32))


def _nhwc(x):
    return jnp.transpose(x, (0, 2, 3, 1))


def _to_nchw(y):
    return jnp.transpose(y, (0, 3, 1, 2))


def _pair(build):
    """(NCHW layer, NHWC layer) sharing identical params/state."""
    m1 = build("NCHW")
    m1._ensure_init()
    m2 = build("NHWC")
    m2._params = jax.tree_util.tree_map(lambda a: a, m1.params)
    m2._state = jax.tree_util.tree_map(lambda a: a, m1.state)
    m2._grads = jax.tree_util.tree_map(jnp.zeros_like, m1.params)
    return m1, m2


def _check_layer(build, x, train=False, tol=1e-5):
    m1, m2 = _pair(build)
    for m in (m1, m2):
        m.training() if train else m.evaluate()
    o1 = m1.forward(x)
    o2 = m2.forward(_nhwc(x))
    np.testing.assert_allclose(np.asarray(o1), np.asarray(_to_nchw(o2)),
                               rtol=0, atol=tol)
    g = jnp.ones_like(o1)
    gi1 = m1.backward(x, g)
    gi2 = m2.backward(_nhwc(x), _nhwc(g))
    np.testing.assert_allclose(np.asarray(gi1), np.asarray(_to_nchw(gi2)),
                               rtol=0, atol=tol)
    g1 = jax.tree_util.tree_leaves(m1.grads)
    g2 = jax.tree_util.tree_leaves(m2.grads)
    for a, b in zip(g1, g2):   # kernels are HWIO in BOTH layouts
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=1e-4)
    return m1, m2


class TestLayerParityAcrossLayouts:
    def test_conv(self):
        _check_layer(lambda f: nn.SpatialConvolution(3, 8, 3, 3, 2, 2, 1, 1,
                                                     format=f),
                     _x(2, 3, 11, 11))

    def test_conv_grouped_same_pad(self):
        _check_layer(lambda f: nn.SpatialConvolution(4, 8, 3, 3, 1, 1, -1, -1,
                                                     n_group=2, format=f),
                     _x(2, 4, 9, 9))

    def test_conv_small_taps_matmul_path(self):
        # kh*kw*cin <= 32 routes through the slice-stack matmul form,
        # which must be transpose-free in NHWC too
        _check_layer(lambda f: nn.SpatialConvolution(1, 6, 5, 5, format=f),
                     _x(2, 1, 12, 12))

    def test_dilated_conv(self):
        _check_layer(lambda f: nn.SpatialDilatedConvolution(
            3, 5, 3, 3, 1, 1, 2, 2, dilation_w=2, dilation_h=2, format=f),
            _x(2, 3, 12, 12))

    def test_full_conv_transposed(self):
        _check_layer(lambda f: nn.SpatialFullConvolution(4, 3, 3, 3, 2, 2,
                                                         1, 1, format=f),
                     _x(2, 4, 7, 7))

    def test_batchnorm_eval_and_train(self):
        x = _x(4, 6, 5, 5)
        _check_layer(lambda f: nn.SpatialBatchNormalization(6, format=f), x)
        m1, m2 = _check_layer(
            lambda f: nn.SpatialBatchNormalization(6, format=f), x,
            train=True)
        # running statistics advance identically in both layouts
        for a, b in zip(jax.tree_util.tree_leaves(m1.state),
                        jax.tree_util.tree_leaves(m2.state)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=0, atol=1e-5)

    def test_max_pooling(self):
        _check_layer(lambda f: nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1,
                                                    format=f).ceil(),
                     _x(2, 4, 9, 9))

    def test_avg_pooling(self):
        _check_layer(lambda f: nn.SpatialAveragePooling(
            3, 3, 2, 2, 1, 1, count_include_pad=False, format=f),
            _x(2, 4, 9, 9))

    def test_cross_map_lrn(self):
        _check_layer(lambda f: nn.SpatialCrossMapLRN(5, 1e-4, 0.75, format=f),
                     _x(2, 8, 6, 6))

    def test_within_channel_lrn(self):
        _check_layer(lambda f: nn.SpatialWithinChannelLRN(3, 1.0, 0.75,
                                                          format=f),
                     _x(2, 4, 7, 7))

    def test_channel_normalize(self):
        _check_layer(lambda f: nn.ChannelNormalize((1.0, 2.0, 3.0),
                                                   (2.0, 2.0, 2.0), format=f),
                     _x(2, 3, 5, 5))


class TestModelParityAcrossLayouts:
    @pytest.fixture(autouse=True)
    def _pin_init_stream(self):
        """Weight init draws from the thread-local RandomGenerator,
        which is NOT reset between tests — without pinning it, which
        weights these razor-thin (atol=1e-4) parity checks get depends
        on every test that ran before this file, and adding an unrelated
        test elsewhere in the suite can flip a borderline element."""
        from bigdl_tpu.utils.random_generator import RandomGenerator
        RandomGenerator.RNG().set_seed(5489)
        yield

    def _converted_clone(self, m1):
        m1._ensure_init()
        m2 = m1.clone_module()
        return nn.to_channels_last(m2)

    def test_resnet_cifar_forward_backward(self):
        from bigdl_tpu.models.resnet import resnet, DatasetType
        m1 = resnet(10, depth=20, dataset=DatasetType.CIFAR10,
                    layout="NCHW")
        m2 = self._converted_clone(m1)
        # m3 is a SAME-layout clone of m1: the m1-vs-m3 delta measures
        # this machine's run-to-run nondeterminism (XLA:CPU's threaded
        # conv reductions reassociate differently compile-to-compile,
        # and under full-suite CPU contention the jitter can exceed any
        # fixed atol — the PR 7 flake: passes solo, fails under load).
        # The cross-layout tolerance is referenced to that measured
        # noise floor, which makes the check load-immune while keeping
        # its power: a genuine layout bug corrupts m2 by O(1) without
        # moving the m1-vs-m3 floor.
        m3 = m1.clone_module()
        x = _x(2, 3, 32, 32)
        for m in (m1, m2, m3):
            m.training()
        o1, o2, o3 = m1.forward(x), m2.forward(x), m3.forward(x)
        g = jnp.ones_like(o1)
        gi1, gi2, gi3 = (m1.backward(x, g), m2.backward(x, g),
                         m3.backward(x, g))
        _, g1 = m1.get_parameters()
        _, g2 = m2.get_parameters()
        _, g3 = m3.get_parameters()
        assert g1.shape == g2.shape  # boundary modules are parameter-free

        def maxdiff(a, b):
            return float(np.max(np.abs(np.asarray(a) - np.asarray(b))))

        for ref, other, same, base in (
                (o1, o2, o3, 1e-4),      # forward
                (gi1, gi2, gi3, 1e-3),   # input gradients
                (g1, g2, g3, 1e-3)):     # parameter gradients
            floor = maxdiff(ref, same)
            tol = max(base, 10.0 * floor)
            diff = maxdiff(ref, other)
            assert diff <= tol, (
                f"cross-layout diff {diff:.2e} exceeds tolerance "
                f"{tol:.2e} (same-layout noise floor {floor:.2e})")

    def test_resnet_shortcut_a_channel_pad_concat(self):
        # type-A shortcuts concatenate a zeroed copy along channels — the
        # Concat must follow the channel axis to the NHWC position
        from bigdl_tpu.models.resnet import resnet, DatasetType, ShortcutType
        m1 = resnet(10, depth=20, shortcut_type=ShortcutType.A,
                    dataset=DatasetType.CIFAR10, layout="NCHW")
        m2 = self._converted_clone(m1)
        x = _x(2, 3, 32, 32)
        o1 = m1.evaluate().forward(x)
        o2 = m2.evaluate().forward(x)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                                   rtol=0, atol=1e-5)

    @pytest.mark.slow
    def test_inception_v1_aux_heads_forward(self):
        from bigdl_tpu.models.inception import inception_v1
        m1 = inception_v1(1000, layout="NCHW")
        m2 = self._converted_clone(m1)
        x = _x(1, 3, 224, 224)
        o1 = m1.evaluate().forward(x)
        o2 = m2.evaluate().forward(x)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                                   rtol=0, atol=1e-4)

    @pytest.mark.slow
    def test_inception_v2_forward_backward(self):
        from bigdl_tpu.models.inception import inception_v2_no_aux_classifier
        m1 = inception_v2_no_aux_classifier(1000, layout="NCHW")
        m2 = self._converted_clone(m1)
        x = _x(1, 3, 224, 224)
        m1.training()
        m2.training()
        o1, o2 = m1.forward(x), m2.forward(x)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2),
                                   rtol=0, atol=1e-4)
        g = jnp.ones_like(o1)
        gi1, gi2 = m1.backward(x, g), m2.backward(x, g)
        # input grads thread ~70 train-mode BN backward reductions whose
        # summation order differs per layout; fp32 reassociation compounds
        # to ~3e-3 on O(1e-2) gradients here
        np.testing.assert_allclose(np.asarray(gi1), np.asarray(gi2),
                                   rtol=0, atol=5e-3)

    def test_unbatched_3d_facade(self):
        # the NCHW public API accepts unbatched (C, H, W) activations; the
        # boundary transposes must handle them too
        from bigdl_tpu.models.resnet import resnet, DatasetType
        m = resnet(10, depth=20, dataset=DatasetType.CIFAR10).evaluate()
        out = m.forward(_x(3, 32, 32))
        assert out.shape == (10,)

    def test_idempotent(self):
        from bigdl_tpu.models.resnet import resnet, DatasetType
        m = resnet(10, depth=20, dataset=DatasetType.CIFAR10)
        m._ensure_init()
        x = _x(2, 3, 32, 32)
        ref = np.asarray(m.evaluate().forward(x))
        again = nn.to_channels_last(m)   # already channels-last
        assert again is m
        n_bound = len(m.find_modules(nn.NCHWToNHWC)) + \
            len(m.find_modules(nn.NHWCToNCHW))
        assert n_bound == 2   # entry + exit only, not re-inserted
        np.testing.assert_allclose(np.asarray(m.forward(x)), ref,
                                   rtol=0, atol=0)

    def test_apply_layout_rejects_unknown(self):
        with pytest.raises(ValueError, match="layout"):
            nn.apply_layout(nn.Sequential(), "NCWH")


class TestChannelsLastHLO:
    """The falsifiable artifact: the jitted channels-last ResNet-50 forward
    contains NO interior layout transposes — exactly one rank-4 transpose
    (the NCHW->NHWC entry; the exit after global pooling is a reshape) —
    and every convolution carries NHWC dimension numbers."""

    def _rank4_transposes(self, txt):
        perms = re.findall(r"transpose.*?permutation\s*=\s*dense<\[([0-9, ]+)\]",
                           txt)
        perms += re.findall(r"stablehlo\.transpose.*?dims = \[([0-9, ]+)\]",
                            txt)
        return [p for p in perms if len(p.split(",")) == 4]

    def test_resnet50_trunk_has_no_interior_transposes(self):
        from bigdl_tpu.models.resnet import resnet, DatasetType
        m = resnet(1000, depth=50, dataset=DatasetType.IMAGENET)
        m._ensure_init()

        def fwd(p, s, xb):
            out, _ = m.apply(p, xb, s, training=False)
            return out

        x = jnp.ones((2, 3, 224, 224), jnp.float32)
        txt = jax.jit(fwd).lower(m.params, m.state, x).as_text()
        r4 = self._rank4_transposes(txt)
        assert r4 == ["0, 2, 3, 1"], \
            f"expected only the boundary NCHW->NHWC transpose, got {r4}"
        conv_inputs = set(re.findall(r"dim_numbers = \[([^\]]*)\]x", txt))
        assert conv_inputs == {"b, 0, 1, f"}, conv_inputs  # all NHWC

    def test_nchw_resnet50_convs_are_channel_first(self):
        # the A/B control: the classic layout really does emit NCHW convs
        from bigdl_tpu.models.resnet import resnet, DatasetType
        m = resnet(1000, depth=50, dataset=DatasetType.IMAGENET,
                   layout="NCHW")
        m._ensure_init()

        def fwd(p, s, xb):
            out, _ = m.apply(p, xb, s, training=False)
            return out

        x = jnp.ones((1, 3, 224, 224), jnp.float32)
        txt = jax.jit(fwd).lower(m.params, m.state, x).as_text()
        assert "b, f, 0, 1" in "".join(
            re.findall(r"dim_numbers = \[([^\]]*)\]x", txt))


class TestFoldConvBN:
    def _trained_convbn_model(self):
        m = (nn.Sequential()
             .add(nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1))
             .add(nn.SpatialBatchNormalization(8))
             .add(nn.ReLU())
             .add(nn.SpatialConvolution(8, 4, 3, 3, with_bias=False))
             .add(nn.SpatialBatchNormalization(4, affine=False)))
        m._ensure_init()
        m.training()
        for _ in range(3):   # make the running statistics non-trivial
            m.forward(_x(4, 3, 10, 10))
        return m.evaluate()

    def test_fold_matches_unfolded_eval(self):
        m = self._trained_convbn_model()
        x = _x(2, 3, 10, 10)
        ref = m.forward(x)
        folded = nn.fold_conv_bn(m.clone_module().evaluate())
        assert not folded.find_modules(nn.SpatialBatchNormalization)
        out = folded.forward(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=0, atol=1e-5)

    def test_fold_resnet20_and_channels_last_stack(self):
        from bigdl_tpu.models.resnet import resnet, model_init, DatasetType
        m = model_init(resnet(10, depth=20, dataset=DatasetType.CIFAR10))
        m.training()
        for _ in range(2):
            m.forward(_x(4, 3, 32, 32))
        m.evaluate()
        x = _x(2, 3, 32, 32)
        ref = m.forward(x)
        folded = nn.fold_conv_bn(m.clone_module().evaluate())
        assert not folded.find_modules(nn.SpatialBatchNormalization)
        np.testing.assert_allclose(np.asarray(folded.forward(x)),
                                   np.asarray(ref), rtol=0, atol=1e-5)

    def test_predictor_fold_bn_knob(self):
        from bigdl_tpu.optim.predictor import Predictor
        from bigdl_tpu.dataset.sample import Sample
        m = self._trained_convbn_model()
        samples = [Sample(np.asarray(_x(3, 10, 10)), np.float32(1))
                   for _ in range(6)]
        plain = Predictor(m).predict(samples, batch_size=4)
        folded = Predictor(m, fold_bn=True).predict(samples, batch_size=4)
        np.testing.assert_allclose(folded, plain, rtol=0, atol=1e-5)
        # the served model was a clone: the original still has its BNs
        assert m.find_modules(nn.SpatialBatchNormalization)


def test_per_layer_report_smoke(capsys):
    from bigdl_tpu.models.perf import per_layer_report
    from bigdl_tpu.models.lenet import lenet5
    import io
    m = lenet5(10).evaluate()
    buf = io.StringIO()
    recs = per_layer_report(m, _x(4, 1, 28, 28).reshape(4, 28, 28),
                            peak_tflops=197.0, file=buf)
    txt = buf.getvalue()
    assert "SpatialConvolution" in txt and "TOTAL" in txt
    conv = [r for r in recs if r["type"] == "SpatialConvolution"]
    assert conv and all(r["gflop"] > 0 for r in conv)
    # shares are rounded to 4 decimals per row before summing
    assert abs(sum(r["time_share"] for r in recs) - 1.0) < 0.01
