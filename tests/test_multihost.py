"""Multi-host (multi-process) distributed training bring-up.

The reference's defining capability is training across NODES — one Spark
executor per node, each feeding only its own cached partitions
(``optim/DistriOptimizer.scala:155-260``,
``ZippedPartitionsWithLocalityRDD.scala:28-56``).  The TPU-native analog:
one jax process per host joined via ``Engine.init_distributed``, each
process constructing ``ShardedDataSet(..., local_partitions=...)`` with
only its mesh positions' partitions and feeding them through
``jax.make_array_from_process_local_data``.

Proven here with 2 and 4 OS processes (x 8//nproc virtual CPU devices
each — the 8-device global mesh), compared against the single-process
8-device run: the final trained weights must agree to float tolerance —
per-process shard feeding is an implementation detail, not a semantics
change.  The 4-process legs mirror the reference's own multi-node sim
standard (``DistriOptimizerSpec.scala:38-40``, ``nodeNumber = 4``) and
exercise what 2 processes cannot: multiple non-writer ranks, and tp
groups split across process boundaries.
"""

import os
import socket
import subprocess
import sys
import tempfile
import textwrap

import numpy as np
import pytest

N_DEV = 8

_WORKER = textwrap.dedent("""
    import os, sys
    nproc = int(os.environ.get("BIGDL_TEST_NPROC", "2"))
    ndev = 8 // nproc
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={ndev}")
    import jax
    pid = int(sys.argv[1]); port = sys.argv[2]; outdir = sys.argv[3]
    from bigdl_tpu.engine import Engine
    Engine.init_distributed(f"127.0.0.1:{port}", nproc, pid)
    assert jax.process_count() == nproc and jax.device_count() == 8

    import numpy as np
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import ShardedDataSet
    from bigdl_tpu.dataset.datasets import synthetic_separable
    from bigdl_tpu.parallel import DistriOptimizer
    from bigdl_tpu.parallel.distri_optimizer import local_data_partitions

    mesh = Engine.create_mesh()
    local = local_data_partitions(mesh)
    assert len(local) == ndev, local
    assert local == list(range(ndev * pid, ndev * (pid + 1))), local

    # identical on every process: same records, same model init
    samples = synthetic_separable(128, 4, n_classes=2, seed=3)
    ds = ShardedDataSet(samples, 8, local_partitions=local).transform(
        SampleToMiniBatch(32, 8))
    # holds ONLY its 1/nproc of the records
    assert sum(s.size() for s in ds.shards.values()) * nproc == ds.size()

    model = (nn.Sequential().add(nn.Linear(4, 16)).add(nn.Tanh())
             .add(nn.Linear(16, 2)).add(nn.LogSoftMax()))
    model.reset(jax.random.PRNGKey(11))
    opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(), mesh=mesh)
    opt.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
    opt.set_end_when(optim.max_iteration(8))
    trained = opt.optimize()
    w, _ = trained.get_parameters()
    np.save(os.path.join(outdir, f"w{pid}.npy"), np.asarray(w))
    print("WORKER_OK", pid)
""")


def _clean_env(nproc=2):
    # strip the site hook's accelerator vars: TPU_*/PJRT_* trigger jax's
    # TPU cluster auto-detection and pre-init the backend (the same trick
    # as test_utils.py's single-process bring-up test).  BIGDL_TEST_NPROC
    # is set by the LAUNCHER alone — worker process count and launcher
    # spawn count must come from one source
    def keep(k):
        return not (k in ("JAX_PLATFORMS", "XLA_FLAGS",
                          "BIGDL_TEST_NPROC") or
                    k.startswith(("TPU_", "AXON_", "_AXON", "PALLAS_",
                                  "PJRT_")))
    env = {k: v for k, v in os.environ.items() if keep(k)}
    env["BIGDL_TEST_NPROC"] = str(nproc)
    return env


@pytest.mark.slow
@pytest.mark.parametrize("nproc", [2, 4])
def test_multi_process_training_matches_single_process(nproc):
    """nproc=4 is the reference's own multi-node sim standard
    (``optim/DistriOptimizerSpec.scala:38-40`` — ``nodeNumber = 4``):
    4 OS processes x 2 virtual devices each, every process feeding only
    its own partitions, must reproduce the single-process 8-device run."""
    with tempfile.TemporaryDirectory() as outdir:
        _run_pair(_WORKER, [outdir], "WORKER_OK", nproc=nproc)
        ws = [np.load(os.path.join(outdir, f"w{p}.npy"))
              for p in range(nproc)]
        # every process converged on identical replicated weights
        w0 = ws[0]
        for w in ws[1:]:
            np.testing.assert_array_equal(w0, w)

        # single-process oracle: same data, same model, same steps over the
        # 8-device mesh in THIS process (all partitions local)
        import jax
        import bigdl_tpu.nn as nn
        import bigdl_tpu.optim as optim
        from bigdl_tpu.dataset import SampleToMiniBatch
        from bigdl_tpu.dataset.dataset import ShardedDataSet
        from bigdl_tpu.dataset.datasets import synthetic_separable
        from bigdl_tpu.engine import Engine
        from bigdl_tpu.parallel import DistriOptimizer

        samples = synthetic_separable(128, 4, n_classes=2, seed=3)
        ds = ShardedDataSet(samples, N_DEV).transform(
            SampleToMiniBatch(32, N_DEV))
        model = (nn.Sequential().add(nn.Linear(4, 16)).add(nn.Tanh())
                 .add(nn.Linear(16, 2)).add(nn.LogSoftMax()))
        model.reset(jax.random.PRNGKey(11))
        opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(),
                              mesh=Engine.create_mesh())
        opt.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
        opt.set_end_when(optim.max_iteration(8))
        w_single, _ = opt.optimize().get_parameters()
        np.testing.assert_allclose(w0, np.asarray(w_single),
                                   rtol=2e-4, atol=2e-5)


def test_dataset_missing_local_partition_rejected():
    """A process whose mesh positions own a partition the dataset does not
    hold locally must fail loudly, not feed garbage."""
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import ShardedDataSet
    from bigdl_tpu.dataset.datasets import synthetic_separable
    from bigdl_tpu.engine import Engine
    from bigdl_tpu.parallel import DistriOptimizer

    samples = synthetic_separable(64, 4, n_classes=2, seed=3)
    # single-process: the mesh owns all 8 partitions, dataset holds 4
    ds = ShardedDataSet(samples, N_DEV,
                        local_partitions=range(4)).transform(
        SampleToMiniBatch(32, N_DEV))
    model = (nn.Sequential().add(nn.Linear(4, 2)).add(nn.LogSoftMax()))
    model.reset()
    opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(),
                          mesh=Engine.create_mesh())
    opt.set_optim_method(optim.SGD(learning_rate=0.1))
    opt.set_end_when(optim.max_iteration(1))
    with pytest.raises(ValueError, match="local_partitions"):
        opt.optimize()


_CKPT_WORKER = textwrap.dedent("""
    import os, sys
    nproc = int(os.environ.get("BIGDL_TEST_NPROC", "2"))
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={8 // nproc}")
    import jax
    pid = int(sys.argv[1]); port = sys.argv[2]; outdir = sys.argv[3]
    ckptdir = sys.argv[4]; phase = sys.argv[5]
    from bigdl_tpu.engine import Engine
    Engine.init_distributed(f"127.0.0.1:{port}", nproc, pid)

    # audit every filesystem payload write this process performs (every
    # persistence path funnels through file_io.write_bytes): the
    # single-writer discipline says rank 1 must never touch the
    # checkpoint or summary stores
    from bigdl_tpu.utils import file_io
    _saves = []
    _orig_write = file_io.write_bytes
    def _counting_write(path, data, overwrite=True):
        _saves.append(path)
        return _orig_write(path, data, overwrite)
    file_io.write_bytes = _counting_write

    import numpy as np
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import ShardedDataSet
    from bigdl_tpu.dataset.datasets import synthetic_separable
    from bigdl_tpu.parallel import DistriOptimizer
    from bigdl_tpu.parallel.distri_optimizer import local_data_partitions

    mesh = Engine.create_mesh()
    local = local_data_partitions(mesh)
    # full-batch (128 records = 1 iteration per epoch): batch order is
    # epoch-shuffle independent, so a resumed run's trajectory can be
    # compared exactly against an uninterrupted one
    samples = synthetic_separable(128, 4, n_classes=2, seed=3)
    ds = ShardedDataSet(samples, 8, local_partitions=local).transform(
        SampleToMiniBatch(128, 8))
    model = (nn.Sequential().add(nn.Linear(4, 16)).add(nn.Tanh())
             .add(nn.Linear(16, 2)).add(nn.LogSoftMax()))
    model.reset(jax.random.PRNGKey(11))
    method = optim.SGD(learning_rate=0.2, momentum=0.9)
    if phase == "resume":
        # 'cluster restart': a NEW process pair picks up the newest
        # snapshot pair and continues where the killed run stopped
        from bigdl_tpu.optim.optimizer import Checkpoint
        latest = Checkpoint(ckptdir, optim.every_epoch()).latest()
        assert latest is not None
        model = file_io.load(latest[0])
        method = optim.OptimMethod.load(latest[1])
        # several_iteration(2) fires when the post-step counter hits 2/4,
        # i.e. snapshots land at iterations 1 and 3 — latest is model.3
        assert method.state["evalCounter"] == 3, method.state

    opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(), mesh=mesh)
    opt.set_optim_method(method)
    opt.set_end_when(optim.max_iteration(4 if phase == "train" else 8))
    # train phase exercises ASYNC checkpointing under multi-host: the
    # write runs on rank 0's background writer while every rank syncs on
    # the capture barrier; the resume phase then proves the committed
    # snapshots are restorable by a fresh process group
    opt.set_checkpoint(ckptdir, optim.several_iteration(2),
                       async_write=(phase == "train"))
    trained = opt.optimize()
    # the distributed-accumulator metric kind: both ranks must agree on
    # the cross-process aggregate even though their local timings differ
    agg = opt.metrics.aggregated("computing time for each node")
    assert agg > 0
    with open(os.path.join(outdir, f"ck_{phase}_agg{pid}.txt"), "w") as f:
        f.write(repr(agg))
    w, _ = trained.get_parameters()
    np.save(os.path.join(outdir, f"ck_{phase}_w{pid}.npy"), np.asarray(w))
    with open(os.path.join(outdir, f"ck_{phase}_saves{pid}.txt"), "w") as f:
        f.write("\\n".join(_saves))
    print("CKPT_WORKER_OK", pid)
""")


def _run_pair(worker, extra_args, marker, nproc=2):
    """Launch ``nproc`` OS processes of ``worker`` (each on 8//nproc
    virtual devices — the global mesh is always 8) and assert every one
    exits 0 printing ``marker``."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = _clean_env(nproc)
    procs = [subprocess.Popen(
        [sys.executable, "-c", worker, str(pid), str(port)] + extra_args,
        cwd=repo_root, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True) for pid in range(nproc)]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=1200)
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0 and marker in out, (out, err[-3000:])


@pytest.mark.slow
@pytest.mark.parametrize("nproc", [2, 4])
def test_multi_process_checkpoint_kill_resume(nproc):
    """Single-writer checkpointing under nproc processes: rank 0 writes
    every snapshot, every OTHER rank writes NOTHING (nproc=4 is the case
    2 processes cannot express — writer-gating against MULTIPLE
    non-writers); killing the group after 4 iterations and resuming a
    fresh group from the snapshot store reproduces the uninterrupted
    8-iteration run (reference: driver-only checkpoint writes,
    ``optim/DistriOptimizer.scala:394-416``; 4-node sim standard,
    ``DistriOptimizerSpec.scala:38-40``)."""
    with tempfile.TemporaryDirectory() as outdir, \
            tempfile.TemporaryDirectory() as ckptdir:
        _run_pair(_CKPT_WORKER, [outdir, ckptdir, "train"],
                  "CKPT_WORKER_OK", nproc=nproc)
        # snapshots exist exactly once, written by rank 0 alone — and
        # each is a COMMITTED verified unit (manifest + commit marker)
        names = sorted(os.listdir(ckptdir))
        assert "model.1" in names and "model.3" in names, names
        assert "optimMethod.3" in names, names
        assert "manifest.1" in names and "commit.1" in names, names
        assert "manifest.3" in names and "commit.3" in names, names
        assert not [n for n in names if ".tmp_bigdl" in n], names
        saves0 = open(os.path.join(outdir, "ck_train_saves0.txt")).read()
        assert saves0.count("model.") == 2 and "optimMethod.3" in saves0
        for p in range(1, nproc):
            sp = open(os.path.join(outdir, f"ck_train_saves{p}.txt")).read()
            assert sp.strip() == "", f"rank {p} wrote: {sp!r}"
        # distributed accumulator: identical global aggregate on all ranks
        aggs = [eval(open(os.path.join(outdir,
                                       f"ck_train_agg{p}.txt")).read())
                for p in range(nproc)]
        assert len(set(aggs)) == 1 and aggs[0] > 0, aggs

        _run_pair(_CKPT_WORKER, [outdir, ckptdir, "resume"],
                  "CKPT_WORKER_OK", nproc=nproc)
        for p in range(1, nproc):
            sp = open(os.path.join(outdir,
                                   f"ck_resume_saves{p}.txt")).read()
            assert sp.strip() == "", f"rank {p} wrote: {sp!r}"
        assert "model.7" in os.listdir(ckptdir)
        w_res0 = np.load(os.path.join(outdir, "ck_resume_w0.npy"))
        for p in range(1, nproc):
            np.testing.assert_array_equal(
                w_res0, np.load(os.path.join(outdir,
                                             f"ck_resume_w{p}.npy")))

        # oracle: uninterrupted single-process 8-iteration run
        import jax
        import bigdl_tpu.nn as nn
        import bigdl_tpu.optim as optim
        from bigdl_tpu.dataset import SampleToMiniBatch
        from bigdl_tpu.dataset.dataset import ShardedDataSet
        from bigdl_tpu.dataset.datasets import synthetic_separable
        from bigdl_tpu.engine import Engine
        from bigdl_tpu.parallel import DistriOptimizer

        samples = synthetic_separable(128, 4, n_classes=2, seed=3)
        ds = ShardedDataSet(samples, N_DEV).transform(
            SampleToMiniBatch(128, N_DEV))
        model = (nn.Sequential().add(nn.Linear(4, 16)).add(nn.Tanh())
                 .add(nn.Linear(16, 2)).add(nn.LogSoftMax()))
        model.reset(jax.random.PRNGKey(11))
        opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(),
                              mesh=Engine.create_mesh())
        opt.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
        opt.set_end_when(optim.max_iteration(8))
        w_single, _ = opt.optimize().get_parameters()
        np.testing.assert_allclose(w_res0, np.asarray(w_single),
                                   rtol=2e-4, atol=2e-5)


_VAL_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    pid = int(sys.argv[1]); port = sys.argv[2]; outdir = sys.argv[3]
    logdir = sys.argv[4]
    from bigdl_tpu.engine import Engine
    Engine.init_distributed(f"127.0.0.1:{port}", 2, pid)

    import numpy as np
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import ShardedDataSet
    from bigdl_tpu.dataset.datasets import synthetic_separable
    from bigdl_tpu.parallel import DistriOptimizer
    from bigdl_tpu.parallel.distri_optimizer import local_data_partitions
    from bigdl_tpu.visualization import TrainSummary, ValidationSummary

    mesh = Engine.create_mesh()
    local = local_data_partitions(mesh)
    samples = synthetic_separable(128, 4, n_classes=2, seed=3)
    ds = ShardedDataSet(samples, 8, local_partitions=local).transform(
        SampleToMiniBatch(32, 8))
    model = (nn.Sequential().add(nn.Linear(4, 16)).add(nn.Tanh())
             .add(nn.Linear(16, 2)).add(nn.LogSoftMax()))
    model.reset(jax.random.PRNGKey(11))
    opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(), mesh=mesh)
    opt.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
    # end at 3 iterations: several_iteration(2) fires at post-step counter
    # 2 and 4, so the LAST validation sees the after-iteration-3 weights —
    # which are also the final weights, making the full-set oracle exact
    opt.set_end_when(optim.max_iteration(3))
    # DISTRIBUTED validation: each process holds only its half of the
    # validation partitions; partial metrics merge across processes
    # (reference DistriValidator), so both ranks must report the same
    # GLOBAL score.  Only rank 0 may produce event files.
    val_ds = ShardedDataSet(list(samples), 8,
                            local_partitions=local).transform(
        SampleToMiniBatch(32, 8))
    opt.set_validation(optim.several_iteration(2), val_ds,
                       [optim.Top1Accuracy()])
    opt.set_train_summary(TrainSummary(logdir, "mh"))
    val_summary = ValidationSummary(logdir, "mh")
    opt.set_validation_summary(val_summary)
    trained = opt.optimize()
    # oracle: the full-set score of the FINAL weights, computed locally on
    # this process (every process holds all records in `samples`) — the
    # last validation fired at the final iteration, so the merged sharded
    # score must equal this exactly
    from bigdl_tpu.optim.evaluator import Evaluator
    full = Evaluator(trained).test(list(samples), [optim.Top1Accuracy()],
                                   32)[0][1].final_result()
    # distributed prediction: each process predicts its LOCAL shard
    # records and keeps its local results (the reference's RDD shape)
    from bigdl_tpu.optim.predictor import Predictor
    preds = Predictor(trained).predict(val_ds)
    assert preds.shape == (64, 2), preds.shape
    scores = val_summary.read_scalar("Top1Accuracy") if pid == 0 else []
    with open(os.path.join(outdir, f"val_score{pid}.txt"), "w") as f:
        f.write(repr((opt.optim_method.state.get("score"), full, scores)))
    print("VAL_WORKER_OK", pid)
""")


@pytest.mark.slow
def test_two_process_validation_single_writer_summaries():
    """2-process training with DISTRIBUTED validation: each rank evaluates
    only its half of a sharded validation set, the partial metrics merge
    across processes (reference ``DistriValidator``), and the merged score
    equals a full-set evaluation of the final weights; only rank 0 emits
    TensorBoard events — exactly one events file per summary dir
    (reference: summaries are driver-side,
    ``optim/DistriOptimizer.scala:426-456``)."""
    with tempfile.TemporaryDirectory() as outdir, \
            tempfile.TemporaryDirectory() as logdir:
        _run_pair(_VAL_WORKER, [outdir, logdir], "VAL_WORKER_OK")
        s0 = open(os.path.join(outdir, "val_score0.txt")).read()
        s1 = open(os.path.join(outdir, "val_score1.txt")).read()
        score0, full0, scalars = eval(s0)
        score1, full1, _ = eval(s1)
        # identical GLOBAL scores on both ranks (each only saw half the
        # records locally — equality proves the cross-process merge)
        assert score0 is not None and score0 == score1, (s0, s1)
        # ...and the merged score IS the full-set score of the final
        # weights, not a local partial
        assert score0 == full0 == full1, (score0, full0, full1)
        # the validation summary carries both trigger firings
        assert len(scalars) == 2 and all(v > 0 for _, v in scalars), scalars
        for sub in ("train", "validation"):
            d = os.path.join(logdir, "mh", sub)
            events = [f for f in os.listdir(d)
                      if f.startswith("events.out.tfevents")]
            assert len(events) == 1, (sub, events)


_RETRY_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    pid = int(sys.argv[1]); port = sys.argv[2]; outdir = sys.argv[3]
    ckptdir = sys.argv[4]
    from bigdl_tpu.engine import Engine
    Engine.init_distributed(f"127.0.0.1:{port}", 2, pid)

    from bigdl_tpu.utils import config, file_io
    config.set_property("bigdl.failure.retryTimeInterval", 0.0)
    _saves = []
    _orig_write = file_io.write_bytes
    def _counting_write(path, data, overwrite=True):
        _saves.append(path)
        return _orig_write(path, data, overwrite)
    file_io.write_bytes = _counting_write

    import numpy as np
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import ShardedDataSet
    from bigdl_tpu.dataset.datasets import synthetic_separable
    from bigdl_tpu.dataset.transformer import Transformer
    from bigdl_tpu.parallel import DistriOptimizer
    from bigdl_tpu.parallel.distri_optimizer import local_data_partitions

    class FailOnce(Transformer):
        # trips on the k-th shard-batch pull of THIS process, once; both
        # ranks trip at the same global iteration (symmetric injection —
        # the failure surfaces at fetch time, before any collective is in
        # flight, like a data-source loss on every node at once)
        def __init__(self, fail_at):
            self.fail_at = fail_at
            self.seen = 0
            self.tripped = False
        def __call__(self, it):
            for batch in it:
                self.seen += 1
                if self.seen == self.fail_at and not self.tripped:
                    self.tripped = True
                    raise RuntimeError("injected multi-host failure")
                yield batch

    mesh = Engine.create_mesh()
    local = local_data_partitions(mesh)
    samples = synthetic_separable(128, 4, n_classes=2, seed=3)
    # full-batch epochs: 4 owned shards x 1 pull per iteration, so
    # fail_at=9 trips while fetching iteration 3 — after the iteration-1
    # snapshot (several_iteration(2) fires at post-step counter 2) is
    # written AND barrier-synced, so both ranks restore the same snapshot
    injector = FailOnce(fail_at=9)
    ds = ShardedDataSet(samples, 8, local_partitions=local).transform(
        SampleToMiniBatch(128, 8)).transform(injector)
    model = (nn.Sequential().add(nn.Linear(4, 16)).add(nn.Tanh())
             .add(nn.Linear(16, 2)).add(nn.LogSoftMax()))
    model.reset(jax.random.PRNGKey(11))
    opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(), mesh=mesh)
    opt.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
    opt.set_end_when(optim.max_iteration(6))
    opt.set_checkpoint(ckptdir, optim.several_iteration(2))
    trained = opt.optimize()
    assert injector.tripped, "injection never fired"
    w, _ = trained.get_parameters()
    np.save(os.path.join(outdir, f"rt_w{pid}.npy"), np.asarray(w))
    if pid != 0:
        assert not _saves, f"rank 1 wrote: {_saves}"
    print("RETRY_WORKER_OK", pid)
""")


@pytest.mark.slow
def test_two_process_retry_from_snapshot():
    """Distributed crash mid-epoch: both processes hit an injected fetch
    failure at iteration 3, each restores the iteration-2 snapshot written
    by rank 0 and resumes; final weights match the uninterrupted
    single-process run (reference retry loop,
    ``optim/DistriOptimizer.scala:750-816`` /
    ``DistriOptimizerSpec.scala:89-99``)."""
    with tempfile.TemporaryDirectory() as outdir, \
            tempfile.TemporaryDirectory() as ckptdir:
        _run_pair(_RETRY_WORKER, [outdir, ckptdir], "RETRY_WORKER_OK")
        w0 = np.load(os.path.join(outdir, "rt_w0.npy"))
        w1 = np.load(os.path.join(outdir, "rt_w1.npy"))
        np.testing.assert_array_equal(w0, w1)

        import jax
        import bigdl_tpu.nn as nn
        import bigdl_tpu.optim as optim
        from bigdl_tpu.dataset import SampleToMiniBatch
        from bigdl_tpu.dataset.dataset import ShardedDataSet
        from bigdl_tpu.dataset.datasets import synthetic_separable
        from bigdl_tpu.engine import Engine
        from bigdl_tpu.parallel import DistriOptimizer

        samples = synthetic_separable(128, 4, n_classes=2, seed=3)
        ds = ShardedDataSet(samples, N_DEV).transform(
            SampleToMiniBatch(128, N_DEV))
        model = (nn.Sequential().add(nn.Linear(4, 16)).add(nn.Tanh())
                 .add(nn.Linear(16, 2)).add(nn.LogSoftMax()))
        model.reset(jax.random.PRNGKey(11))
        opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(),
                              mesh=Engine.create_mesh())
        opt.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
        opt.set_end_when(optim.max_iteration(6))
        w_single, _ = opt.optimize().get_parameters()
        np.testing.assert_allclose(w0, np.asarray(w_single),
                                   rtol=2e-4, atol=2e-5)


_SP_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    pid = int(sys.argv[1]); port = sys.argv[2]; outdir = sys.argv[3]
    from bigdl_tpu.engine import Engine
    Engine.init_distributed(f"127.0.0.1:{port}", 2, pid)

    import numpy as np
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import Sample, SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import ShardedDataSet
    from bigdl_tpu.parallel import DistriOptimizer
    from bigdl_tpu.parallel.distri_optimizer import local_data_partitions
    from bigdl_tpu.nn.attention import MultiHeadAttention

    # dp=1 x sp=8: the single data row spans BOTH processes, so each
    # process owns only half the seq chunks — the partial-axis
    # time-slicing path in _global_batch must engage
    mesh = Engine.create_mesh((1, 8), ("data", "seq"))
    local = local_data_partitions(mesh)
    assert local == [0], local

    d_model, seq_t = 16, 32
    rng = np.random.RandomState(3)
    seqs = [Sample(rng.normal(size=(seq_t, d_model)).astype(np.float32),
                   (rng.randint(0, 4, seq_t) + 1).astype(np.float32))
            for _ in range(8)]
    lm = (nn.Sequential()
          .add(nn.Linear(d_model, d_model))
          .add(MultiHeadAttention(d_model, 2, causal=True))
          .add(nn.Linear(d_model, 4))
          .add(nn.LogSoftMax()))
    lm.reset(jax.random.PRNGKey(11))
    ds = ShardedDataSet(seqs, 1, local_partitions=local).transform(
        SampleToMiniBatch(4, 1))
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                       size_average=True)
    opt = DistriOptimizer(lm, ds, crit, mesh=mesh)
    opt.set_optim_method(optim.SGD(learning_rate=0.1, momentum=0.9))
    opt.set_end_when(optim.max_iteration(4))
    trained = opt.optimize()
    w, _ = trained.get_parameters()
    np.save(os.path.join(outdir, f"sp_w{pid}.npy"), np.asarray(w))
    print("SP_WORKER_OK", pid)
""")


@pytest.mark.slow
def test_two_process_seq_parallel_partial_chunk_ownership():
    """dp1 x sp8 across 2 processes: each process owns only HALF the seq
    chunks of the one data row, so _global_batch's time-slicing path runs
    for real; final weights must match the single-process (1, 8) run."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = _clean_env()
    with tempfile.TemporaryDirectory() as outdir:
        procs = [subprocess.Popen(
            [sys.executable, "-c", _SP_WORKER, str(pid), str(port), outdir],
            cwd=repo_root, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True) for pid in (0, 1)]
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=1200)
            outs.append((p.returncode, out, err))
        for rc, out, err in outs:
            assert rc == 0 and "SP_WORKER_OK" in out, (out, err[-3000:])
        w0 = np.load(os.path.join(outdir, "sp_w0.npy"))
        w1 = np.load(os.path.join(outdir, "sp_w1.npy"))
        np.testing.assert_array_equal(w0, w1)

        # single-process oracle on the same (1, 8) mesh
        import jax
        import bigdl_tpu.nn as nn
        import bigdl_tpu.optim as optim
        from bigdl_tpu.dataset import Sample, SampleToMiniBatch
        from bigdl_tpu.dataset.dataset import ShardedDataSet
        from bigdl_tpu.engine import Engine
        from bigdl_tpu.nn.attention import MultiHeadAttention
        from bigdl_tpu.parallel import DistriOptimizer

        d_model, seq_t = 16, 32
        rng = np.random.RandomState(3)
        seqs = [Sample(rng.normal(size=(seq_t, d_model)).astype(np.float32),
                       (rng.randint(0, 4, seq_t) + 1).astype(np.float32))
                for _ in range(8)]
        lm = (nn.Sequential()
              .add(nn.Linear(d_model, d_model))
              .add(MultiHeadAttention(d_model, 2, causal=True))
              .add(nn.Linear(d_model, 4))
              .add(nn.LogSoftMax()))
        lm.reset(jax.random.PRNGKey(11))
        mesh = Engine.create_mesh((1, 8), ("data", "seq"))
        ds = ShardedDataSet(seqs, 1).transform(SampleToMiniBatch(4, 1))
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)
        opt = DistriOptimizer(lm, ds, crit, mesh=mesh)
        opt.set_optim_method(optim.SGD(learning_rate=0.1, momentum=0.9))
        opt.set_end_when(optim.max_iteration(4))
        w_single, _ = opt.optimize().get_parameters()
        np.testing.assert_allclose(w0, np.asarray(w_single),
                                   rtol=2e-4, atol=2e-5)


_EP_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    pid = int(sys.argv[1]); port = sys.argv[2]; outdir = sys.argv[3]
    from bigdl_tpu.engine import Engine
    Engine.init_distributed(f"127.0.0.1:{port}", 2, pid)

    import numpy as np
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import ShardedDataSet
    from bigdl_tpu.dataset.datasets import synthetic_separable
    from bigdl_tpu.nn.moe import MixtureOfExperts
    from bigdl_tpu.parallel import DistriOptimizer
    from bigdl_tpu.parallel.distri_optimizer import local_data_partitions

    # dp=1 x ep=8: each process owns half the expert chunks of the one
    # data partition -> _global_batch's batch-row slicing engages
    mesh = Engine.create_mesh((1, 8), ("data", "expert"))
    local = local_data_partitions(mesh)
    assert local == [0], local

    samples = synthetic_separable(64, 4, n_classes=2, seed=3)
    D = 8
    expert = (nn.Sequential().add(nn.Linear(D, 16)).add(nn.ReLU())
              .add(nn.Linear(16, D)))
    moe = MixtureOfExperts(D, expert, 8, capacity_factor=8.0)
    m = (nn.Sequential().add(nn.Linear(4, D)).add(nn.Tanh()).add(moe)
         .add(nn.Linear(D, 2)).add(nn.LogSoftMax()))
    m.reset(jax.random.PRNGKey(7))
    ds = ShardedDataSet(samples, 1, local_partitions=local).transform(
        SampleToMiniBatch(32, 1))
    opt = DistriOptimizer(m, ds, nn.ClassNLLCriterion(), mesh=mesh)
    opt.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
    opt.set_end_when(optim.max_iteration(4))
    trained = opt.optimize()
    w, _ = trained.get_parameters()
    np.save(os.path.join(outdir, f"ep_w{pid}.npy"), np.asarray(w))
    print("EP_WORKER_OK", pid)
""")


@pytest.mark.slow
def test_two_process_expert_parallel_partial_chunk_ownership():
    """dp1 x ep8 across 2 processes: each process owns half the expert
    chunks, so _global_batch's batch-row slicing runs for real; weights
    must match the single-process (1, 8) run (drop-free capacity)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = _clean_env()
    with tempfile.TemporaryDirectory() as outdir:
        procs = [subprocess.Popen(
            [sys.executable, "-c", _EP_WORKER, str(pid), str(port), outdir],
            cwd=repo_root, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True) for pid in (0, 1)]
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=1200)
            outs.append((p.returncode, out, err))
        for rc, out, err in outs:
            assert rc == 0 and "EP_WORKER_OK" in out, (out, err[-3000:])
        w0 = np.load(os.path.join(outdir, "ep_w0.npy"))
        w1 = np.load(os.path.join(outdir, "ep_w1.npy"))
        np.testing.assert_array_equal(w0, w1)

        import jax
        import bigdl_tpu.nn as nn
        import bigdl_tpu.optim as optim
        from bigdl_tpu.dataset import SampleToMiniBatch
        from bigdl_tpu.dataset.dataset import ShardedDataSet
        from bigdl_tpu.dataset.datasets import synthetic_separable
        from bigdl_tpu.engine import Engine
        from bigdl_tpu.nn.moe import MixtureOfExperts
        from bigdl_tpu.parallel import DistriOptimizer

        samples = synthetic_separable(64, 4, n_classes=2, seed=3)
        D = 8
        expert = (nn.Sequential().add(nn.Linear(D, 16)).add(nn.ReLU())
                  .add(nn.Linear(16, D)))
        moe = MixtureOfExperts(D, expert, 8, capacity_factor=8.0)
        m = (nn.Sequential().add(nn.Linear(4, D)).add(nn.Tanh()).add(moe)
             .add(nn.Linear(D, 2)).add(nn.LogSoftMax()))
        m.reset(jax.random.PRNGKey(7))
        mesh = Engine.create_mesh((1, 8), ("data", "expert"))
        ds = ShardedDataSet(samples, 1).transform(SampleToMiniBatch(32, 1))
        opt = DistriOptimizer(m, ds, nn.ClassNLLCriterion(), mesh=mesh)
        opt.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
        opt.set_end_when(optim.max_iteration(4))
        w_single, _ = opt.optimize().get_parameters()
        np.testing.assert_allclose(w0, np.asarray(w_single),
                                   rtol=2e-4, atol=2e-5)


_TP_WORKER = textwrap.dedent("""
    import os, sys
    nproc = int(os.environ.get("BIGDL_TEST_NPROC", "2"))
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={8 // nproc}")
    import jax
    pid = int(sys.argv[1]); port = sys.argv[2]; outdir = sys.argv[3]
    ckptdir = sys.argv[4]
    from bigdl_tpu.engine import Engine
    Engine.init_distributed(f"127.0.0.1:{port}", nproc, pid)

    from bigdl_tpu.utils import file_io
    _saves = []
    _orig_save = file_io.save
    def _counting_save(obj, path, overwrite=True):
        _saves.append(path)
        return _orig_save(obj, path, overwrite)
    file_io.save = _counting_save

    import numpy as np
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import ShardedDataSet
    from bigdl_tpu.dataset.datasets import synthetic_separable
    from bigdl_tpu.parallel import DistriOptimizer
    from bigdl_tpu.parallel.distri_optimizer import local_data_partitions
    from bigdl_tpu.parallel.tensor_parallel import (column_parallel,
                                                    row_parallel)

    # dp x tp across hosts: (2 data, 4 model).  With 2 processes each
    # owns one data replica's full tp group (pair-psum intra-process,
    # data reduction across).  With 4 processes each owns HALF a tp
    # group — the Megatron pair-psum itself crosses processes, and two
    # processes co-feed each data partition.
    mesh = Engine.create_mesh((2, 4), ("data", "model"))
    local = local_data_partitions(mesh)
    assert local == [(pid * 2) // nproc], local

    samples = synthetic_separable(128, 4, n_classes=2, seed=3)
    ds = ShardedDataSet(samples, 2, local_partitions=local).transform(
        SampleToMiniBatch(128, 2))
    up, down = nn.Linear(4, 16), nn.Linear(16, 2)
    column_parallel(up); row_parallel(down)
    model = (nn.Sequential().add(up).add(nn.Tanh()).add(down)
             .add(nn.LogSoftMax()))
    model.reset(jax.random.PRNGKey(11))
    opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(), mesh=mesh)
    opt.set_optim_method(optim.Adam(learning_rate=0.05))
    opt.set_end_when(optim.max_iteration(4))
    # checkpointing exercises the multi-host GSPMD publish: params
    # regather to replicated, ZeRO slots go per-leaf to host numpy,
    # rank 0 alone serializes
    opt.set_checkpoint(ckptdir, optim.several_iteration(2))
    trained = opt.optimize()
    w, _ = trained.get_parameters()
    np.save(os.path.join(outdir, f"tp_w{pid}.npy"), np.asarray(w))
    if pid != 0:
        assert not _saves, f"rank 1 wrote: {_saves}"
    # the published slots are host-complete on every process (the
    # gather_to_host path): resuming from them must work anywhere
    s = opt.optim_method._slots["s"][0]["weight"]
    assert np.asarray(s).shape == (4, 16)
    print("TP_WORKER_OK", pid)
""")


@pytest.mark.slow
@pytest.mark.parametrize("nproc", [2, 4])
def test_multi_process_tensor_parallel_training_and_checkpoint(nproc):
    """dp x tp across OS processes: the GSPMD step's cross-process
    data-axis reduction plus the multi-host publish path (replicated
    param regather, per-leaf host slot gather, single-writer snapshot)
    must reproduce the single-process (2, 4) run.  At nproc=4 each
    process owns only HALF a tp group, so the Megatron pair-psum itself
    crosses process boundaries and two processes co-feed every data
    partition."""
    with tempfile.TemporaryDirectory() as outdir, \
            tempfile.TemporaryDirectory() as ckptdir:
        _run_pair(_TP_WORKER, [outdir, ckptdir], "TP_WORKER_OK",
                  nproc=nproc)
        w0 = np.load(os.path.join(outdir, "tp_w0.npy"))
        for p in range(1, nproc):
            np.testing.assert_array_equal(
                w0, np.load(os.path.join(outdir, f"tp_w{p}.npy")))
        names = sorted(os.listdir(ckptdir))
        assert "model.1" in names and "model.3" in names, names

        # single-process oracle on the same (2, 4) mesh
        import jax
        import bigdl_tpu.nn as nn
        import bigdl_tpu.optim as optim
        from bigdl_tpu.dataset import SampleToMiniBatch
        from bigdl_tpu.dataset.dataset import ShardedDataSet
        from bigdl_tpu.dataset.datasets import synthetic_separable
        from bigdl_tpu.engine import Engine
        from bigdl_tpu.parallel import DistriOptimizer
        from bigdl_tpu.parallel.tensor_parallel import (column_parallel,
                                                        row_parallel)

        samples = synthetic_separable(128, 4, n_classes=2, seed=3)
        ds = ShardedDataSet(samples, 2).transform(SampleToMiniBatch(128, 2))
        up, down = nn.Linear(4, 16), nn.Linear(16, 2)
        column_parallel(up)
        row_parallel(down)
        model = (nn.Sequential().add(up).add(nn.Tanh()).add(down)
                 .add(nn.LogSoftMax()))
        model.reset(jax.random.PRNGKey(11))
        mesh = Engine.create_mesh((2, 4), ("data", "model"))
        opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(), mesh=mesh)
        opt.set_optim_method(optim.Adam(learning_rate=0.05))
        opt.set_end_when(optim.max_iteration(4))
        w_single, _ = opt.optimize().get_parameters()
        np.testing.assert_allclose(w0, np.asarray(w_single),
                                   rtol=2e-4, atol=2e-5)


_PP_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    pid = int(sys.argv[1]); port = sys.argv[2]; outdir = sys.argv[3]
    from bigdl_tpu.engine import Engine
    Engine.init_distributed(f"127.0.0.1:{port}", 2, pid)

    import numpy as np
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import Sample, SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import ShardedDataSet
    from bigdl_tpu.parallel import PipelineOptimizer
    from bigdl_tpu.parallel.distri_optimizer import local_data_partitions

    # pp x dp across hosts: (2 data, 4 stage) — each process owns one
    # data replica's full pipeline; stage ppermute stays intra-process,
    # the data-gradient psum crosses processes
    mesh = Engine.create_mesh((2, 4), ("data", "stage"))
    local = local_data_partitions(mesh)
    assert local == [pid], local

    D = 8
    rng = np.random.RandomState(2)
    x = rng.normal(size=(32, D)).astype(np.float32)
    w_true = rng.normal(size=(D, D)).astype(np.float32) * 0.4
    y = np.tanh(x @ w_true)
    samples = [Sample(x[i], y[i]) for i in range(32)]
    ds = ShardedDataSet(samples, 2, local_partitions=local).transform(
        SampleToMiniBatch(16, 2))
    blocks = []
    for s in range(4):
        b = nn.Sequential().add(nn.Linear(D, D)).add(nn.Tanh())
        b.reset(jax.random.PRNGKey(s))
        blocks.append(b)
    opt = PipelineOptimizer(blocks, ds, nn.MSECriterion(), mesh=mesh,
                            n_micro=2)
    opt.set_optim_method(optim.SGD(learning_rate=0.5))
    opt.set_end_when(optim.max_iteration(4))
    trained = opt.optimize()
    w, _ = trained.get_parameters()
    np.save(os.path.join(outdir, f"pp_w{pid}.npy"), np.asarray(w))
    print("PP_WORKER_OK", pid)
""")


@pytest.mark.slow
def test_two_process_pipeline_training_matches_single_process():
    """pp x dp across 2 OS processes: PipelineOptimizer's per-process
    ShardedDataSet feeding + the cross-process data psum must reproduce
    the single-process (2, 4) run."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = _clean_env()
    with tempfile.TemporaryDirectory() as outdir:
        procs = [subprocess.Popen(
            [sys.executable, "-c", _PP_WORKER, str(pid), str(port), outdir],
            cwd=repo_root, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True) for pid in (0, 1)]
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=1200)
            outs.append((p.returncode, out, err))
        for rc, out, err in outs:
            assert rc == 0 and "PP_WORKER_OK" in out, (out, err[-3000:])
        w0 = np.load(os.path.join(outdir, "pp_w0.npy"))
        w1 = np.load(os.path.join(outdir, "pp_w1.npy"))
        np.testing.assert_array_equal(w0, w1)

        import jax
        import bigdl_tpu.nn as nn
        import bigdl_tpu.optim as optim
        from bigdl_tpu.dataset import Sample, SampleToMiniBatch
        from bigdl_tpu.dataset.dataset import ShardedDataSet
        from bigdl_tpu.engine import Engine
        from bigdl_tpu.parallel import PipelineOptimizer

        D = 8
        rng = np.random.RandomState(2)
        x = rng.normal(size=(32, D)).astype(np.float32)
        w_true = rng.normal(size=(D, D)).astype(np.float32) * 0.4
        y = np.tanh(x @ w_true)
        samples = [Sample(x[i], y[i]) for i in range(32)]
        ds = ShardedDataSet(samples, 2).transform(SampleToMiniBatch(16, 2))
        blocks = []
        for s in range(4):
            b = nn.Sequential().add(nn.Linear(D, D)).add(nn.Tanh())
            b.reset(jax.random.PRNGKey(s))
            blocks.append(b)
        mesh = Engine.create_mesh((2, 4), ("data", "stage"))
        opt = PipelineOptimizer(blocks, ds, nn.MSECriterion(), mesh=mesh,
                                n_micro=2)
        opt.set_optim_method(optim.SGD(learning_rate=0.5))
        opt.set_end_when(optim.max_iteration(4))
        w_single, _ = opt.optimize().get_parameters()
        np.testing.assert_allclose(w0, np.asarray(w_single),
                                   rtol=2e-4, atol=2e-5)
