"""Multi-host (multi-process) distributed training bring-up.

The reference's defining capability is training across NODES — one Spark
executor per node, each feeding only its own cached partitions
(``optim/DistriOptimizer.scala:155-260``,
``ZippedPartitionsWithLocalityRDD.scala:28-56``).  The TPU-native analog:
one jax process per host joined via ``Engine.init_distributed``, each
process constructing ``ShardedDataSet(..., local_partitions=...)`` with
only its mesh positions' partitions and feeding them through
``jax.make_array_from_process_local_data``.

Proven here with 2 OS processes x 4 virtual CPU devices each (the
8-device global mesh), compared against the single-process 8-device run:
the final trained weights must agree to float tolerance — per-process
shard feeding is an implementation detail, not a semantics change.
"""

import os
import socket
import subprocess
import sys
import tempfile
import textwrap

import numpy as np
import pytest

N_DEV = 8

_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    pid = int(sys.argv[1]); port = sys.argv[2]; outdir = sys.argv[3]
    from bigdl_tpu.engine import Engine
    Engine.init_distributed(f"127.0.0.1:{port}", 2, pid)
    assert jax.process_count() == 2 and jax.device_count() == 8

    import numpy as np
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import ShardedDataSet
    from bigdl_tpu.dataset.datasets import synthetic_separable
    from bigdl_tpu.parallel import DistriOptimizer
    from bigdl_tpu.parallel.distri_optimizer import local_data_partitions

    mesh = Engine.create_mesh()
    local = local_data_partitions(mesh)
    assert len(local) == 4, local
    assert local == (list(range(4)) if pid == 0 else list(range(4, 8)))

    # identical on every process: same records, same model init
    samples = synthetic_separable(128, 4, n_classes=2, seed=3)
    ds = ShardedDataSet(samples, 8, local_partitions=local).transform(
        SampleToMiniBatch(32, 8))
    # holds ONLY its half of the records
    assert sum(s.size() for s in ds.shards.values()) * 2 == ds.size()

    model = (nn.Sequential().add(nn.Linear(4, 16)).add(nn.Tanh())
             .add(nn.Linear(16, 2)).add(nn.LogSoftMax()))
    model.reset(jax.random.PRNGKey(11))
    opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(), mesh=mesh)
    opt.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
    opt.set_end_when(optim.max_iteration(8))
    trained = opt.optimize()
    w, _ = trained.get_parameters()
    np.save(os.path.join(outdir, f"w{pid}.npy"), np.asarray(w))
    print("WORKER_OK", pid)
""")


def _clean_env():
    # strip the site hook's accelerator vars: TPU_*/PJRT_* trigger jax's
    # TPU cluster auto-detection and pre-init the backend (the same trick
    # as test_utils.py's single-process bring-up test)
    def keep(k):
        return not (k in ("JAX_PLATFORMS", "XLA_FLAGS") or
                    k.startswith(("TPU_", "AXON_", "_AXON", "PALLAS_",
                                  "PJRT_")))
    return {k: v for k, v in os.environ.items() if keep(k)}


@pytest.mark.slow
def test_two_process_training_matches_single_process():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = _clean_env()
    with tempfile.TemporaryDirectory() as outdir:
        procs = [subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(pid), str(port), outdir],
            cwd=repo_root, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True) for pid in (0, 1)]
        outs = []
        for p in procs:
            # generous: under full-suite CPU contention the two extra
            # processes (each compiling on a 4-device virtual mesh) can
            # take minutes; 15 s on an idle machine
            out, err = p.communicate(timeout=1200)
            outs.append((p.returncode, out, err))
        for rc, out, err in outs:
            assert rc == 0 and "WORKER_OK" in out, (out, err[-3000:])
        w0 = np.load(os.path.join(outdir, "w0.npy"))
        w1 = np.load(os.path.join(outdir, "w1.npy"))
        # both processes converged on identical replicated weights
        np.testing.assert_array_equal(w0, w1)

        # single-process oracle: same data, same model, same steps over the
        # 8-device mesh in THIS process (all partitions local)
        import jax
        import bigdl_tpu.nn as nn
        import bigdl_tpu.optim as optim
        from bigdl_tpu.dataset import SampleToMiniBatch
        from bigdl_tpu.dataset.dataset import ShardedDataSet
        from bigdl_tpu.dataset.datasets import synthetic_separable
        from bigdl_tpu.engine import Engine
        from bigdl_tpu.parallel import DistriOptimizer

        samples = synthetic_separable(128, 4, n_classes=2, seed=3)
        ds = ShardedDataSet(samples, N_DEV).transform(
            SampleToMiniBatch(32, N_DEV))
        model = (nn.Sequential().add(nn.Linear(4, 16)).add(nn.Tanh())
                 .add(nn.Linear(16, 2)).add(nn.LogSoftMax()))
        model.reset(jax.random.PRNGKey(11))
        opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(),
                              mesh=Engine.create_mesh())
        opt.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
        opt.set_end_when(optim.max_iteration(8))
        w_single, _ = opt.optimize().get_parameters()
        np.testing.assert_allclose(w0, np.asarray(w_single),
                                   rtol=2e-4, atol=2e-5)


def test_dataset_missing_local_partition_rejected():
    """A process whose mesh positions own a partition the dataset does not
    hold locally must fail loudly, not feed garbage."""
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import ShardedDataSet
    from bigdl_tpu.dataset.datasets import synthetic_separable
    from bigdl_tpu.engine import Engine
    from bigdl_tpu.parallel import DistriOptimizer

    samples = synthetic_separable(64, 4, n_classes=2, seed=3)
    # single-process: the mesh owns all 8 partitions, dataset holds 4
    ds = ShardedDataSet(samples, N_DEV,
                        local_partitions=range(4)).transform(
        SampleToMiniBatch(32, N_DEV))
    model = (nn.Sequential().add(nn.Linear(4, 2)).add(nn.LogSoftMax()))
    model.reset()
    opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(),
                          mesh=Engine.create_mesh())
    opt.set_optim_method(optim.SGD(learning_rate=0.1))
    opt.set_end_when(optim.max_iteration(1))
    with pytest.raises(ValueError, match="local_partitions"):
        opt.optimize()


_SP_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    pid = int(sys.argv[1]); port = sys.argv[2]; outdir = sys.argv[3]
    from bigdl_tpu.engine import Engine
    Engine.init_distributed(f"127.0.0.1:{port}", 2, pid)

    import numpy as np
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import Sample, SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import ShardedDataSet
    from bigdl_tpu.parallel import DistriOptimizer
    from bigdl_tpu.parallel.distri_optimizer import local_data_partitions
    from bigdl_tpu.nn.attention import MultiHeadAttention

    # dp=1 x sp=8: the single data row spans BOTH processes, so each
    # process owns only half the seq chunks — the partial-axis
    # time-slicing path in _global_batch must engage
    mesh = Engine.create_mesh((1, 8), ("data", "seq"))
    local = local_data_partitions(mesh)
    assert local == [0], local

    d_model, seq_t = 16, 32
    rng = np.random.RandomState(3)
    seqs = [Sample(rng.normal(size=(seq_t, d_model)).astype(np.float32),
                   (rng.randint(0, 4, seq_t) + 1).astype(np.float32))
            for _ in range(8)]
    lm = (nn.Sequential()
          .add(nn.Linear(d_model, d_model))
          .add(MultiHeadAttention(d_model, 2, causal=True))
          .add(nn.Linear(d_model, 4))
          .add(nn.LogSoftMax()))
    lm.reset(jax.random.PRNGKey(11))
    ds = ShardedDataSet(seqs, 1, local_partitions=local).transform(
        SampleToMiniBatch(4, 1))
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                       size_average=True)
    opt = DistriOptimizer(lm, ds, crit, mesh=mesh)
    opt.set_optim_method(optim.SGD(learning_rate=0.1, momentum=0.9))
    opt.set_end_when(optim.max_iteration(4))
    trained = opt.optimize()
    w, _ = trained.get_parameters()
    np.save(os.path.join(outdir, f"sp_w{pid}.npy"), np.asarray(w))
    print("SP_WORKER_OK", pid)
""")


@pytest.mark.slow
def test_two_process_seq_parallel_partial_chunk_ownership():
    """dp1 x sp8 across 2 processes: each process owns only HALF the seq
    chunks of the one data row, so _global_batch's time-slicing path runs
    for real; final weights must match the single-process (1, 8) run."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = _clean_env()
    with tempfile.TemporaryDirectory() as outdir:
        procs = [subprocess.Popen(
            [sys.executable, "-c", _SP_WORKER, str(pid), str(port), outdir],
            cwd=repo_root, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True) for pid in (0, 1)]
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=1200)
            outs.append((p.returncode, out, err))
        for rc, out, err in outs:
            assert rc == 0 and "SP_WORKER_OK" in out, (out, err[-3000:])
        w0 = np.load(os.path.join(outdir, "sp_w0.npy"))
        w1 = np.load(os.path.join(outdir, "sp_w1.npy"))
        np.testing.assert_array_equal(w0, w1)

        # single-process oracle on the same (1, 8) mesh
        import jax
        import bigdl_tpu.nn as nn
        import bigdl_tpu.optim as optim
        from bigdl_tpu.dataset import Sample, SampleToMiniBatch
        from bigdl_tpu.dataset.dataset import ShardedDataSet
        from bigdl_tpu.engine import Engine
        from bigdl_tpu.nn.attention import MultiHeadAttention
        from bigdl_tpu.parallel import DistriOptimizer

        d_model, seq_t = 16, 32
        rng = np.random.RandomState(3)
        seqs = [Sample(rng.normal(size=(seq_t, d_model)).astype(np.float32),
                       (rng.randint(0, 4, seq_t) + 1).astype(np.float32))
                for _ in range(8)]
        lm = (nn.Sequential()
              .add(nn.Linear(d_model, d_model))
              .add(MultiHeadAttention(d_model, 2, causal=True))
              .add(nn.Linear(d_model, 4))
              .add(nn.LogSoftMax()))
        lm.reset(jax.random.PRNGKey(11))
        mesh = Engine.create_mesh((1, 8), ("data", "seq"))
        ds = ShardedDataSet(seqs, 1).transform(SampleToMiniBatch(4, 1))
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)
        opt = DistriOptimizer(lm, ds, crit, mesh=mesh)
        opt.set_optim_method(optim.SGD(learning_rate=0.1, momentum=0.9))
        opt.set_end_when(optim.max_iteration(4))
        w_single, _ = opt.optimize().get_parameters()
        np.testing.assert_allclose(w0, np.asarray(w_single),
                                   rtol=2e-4, atol=2e-5)


_EP_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    pid = int(sys.argv[1]); port = sys.argv[2]; outdir = sys.argv[3]
    from bigdl_tpu.engine import Engine
    Engine.init_distributed(f"127.0.0.1:{port}", 2, pid)

    import numpy as np
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import ShardedDataSet
    from bigdl_tpu.dataset.datasets import synthetic_separable
    from bigdl_tpu.nn.moe import MixtureOfExperts
    from bigdl_tpu.parallel import DistriOptimizer
    from bigdl_tpu.parallel.distri_optimizer import local_data_partitions

    # dp=1 x ep=8: each process owns half the expert chunks of the one
    # data partition -> _global_batch's batch-row slicing engages
    mesh = Engine.create_mesh((1, 8), ("data", "expert"))
    local = local_data_partitions(mesh)
    assert local == [0], local

    samples = synthetic_separable(64, 4, n_classes=2, seed=3)
    D = 8
    expert = (nn.Sequential().add(nn.Linear(D, 16)).add(nn.ReLU())
              .add(nn.Linear(16, D)))
    moe = MixtureOfExperts(D, expert, 8, capacity_factor=8.0)
    m = (nn.Sequential().add(nn.Linear(4, D)).add(nn.Tanh()).add(moe)
         .add(nn.Linear(D, 2)).add(nn.LogSoftMax()))
    m.reset(jax.random.PRNGKey(7))
    ds = ShardedDataSet(samples, 1, local_partitions=local).transform(
        SampleToMiniBatch(32, 1))
    opt = DistriOptimizer(m, ds, nn.ClassNLLCriterion(), mesh=mesh)
    opt.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
    opt.set_end_when(optim.max_iteration(4))
    trained = opt.optimize()
    w, _ = trained.get_parameters()
    np.save(os.path.join(outdir, f"ep_w{pid}.npy"), np.asarray(w))
    print("EP_WORKER_OK", pid)
""")


@pytest.mark.slow
def test_two_process_expert_parallel_partial_chunk_ownership():
    """dp1 x ep8 across 2 processes: each process owns half the expert
    chunks, so _global_batch's batch-row slicing runs for real; weights
    must match the single-process (1, 8) run (drop-free capacity)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = _clean_env()
    with tempfile.TemporaryDirectory() as outdir:
        procs = [subprocess.Popen(
            [sys.executable, "-c", _EP_WORKER, str(pid), str(port), outdir],
            cwd=repo_root, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True) for pid in (0, 1)]
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=1200)
            outs.append((p.returncode, out, err))
        for rc, out, err in outs:
            assert rc == 0 and "EP_WORKER_OK" in out, (out, err[-3000:])
        w0 = np.load(os.path.join(outdir, "ep_w0.npy"))
        w1 = np.load(os.path.join(outdir, "ep_w1.npy"))
        np.testing.assert_array_equal(w0, w1)

        import jax
        import bigdl_tpu.nn as nn
        import bigdl_tpu.optim as optim
        from bigdl_tpu.dataset import SampleToMiniBatch
        from bigdl_tpu.dataset.dataset import ShardedDataSet
        from bigdl_tpu.dataset.datasets import synthetic_separable
        from bigdl_tpu.engine import Engine
        from bigdl_tpu.nn.moe import MixtureOfExperts
        from bigdl_tpu.parallel import DistriOptimizer

        samples = synthetic_separable(64, 4, n_classes=2, seed=3)
        D = 8
        expert = (nn.Sequential().add(nn.Linear(D, 16)).add(nn.ReLU())
                  .add(nn.Linear(16, D)))
        moe = MixtureOfExperts(D, expert, 8, capacity_factor=8.0)
        m = (nn.Sequential().add(nn.Linear(4, D)).add(nn.Tanh()).add(moe)
             .add(nn.Linear(D, 2)).add(nn.LogSoftMax()))
        m.reset(jax.random.PRNGKey(7))
        mesh = Engine.create_mesh((1, 8), ("data", "expert"))
        ds = ShardedDataSet(samples, 1).transform(SampleToMiniBatch(32, 1))
        opt = DistriOptimizer(m, ds, nn.ClassNLLCriterion(), mesh=mesh)
        opt.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
        opt.set_end_when(optim.max_iteration(4))
        w_single, _ = opt.optimize().get_parameters()
        np.testing.assert_allclose(w0, np.asarray(w_single),
                                   rtol=2e-4, atol=2e-5)


_PP_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    pid = int(sys.argv[1]); port = sys.argv[2]; outdir = sys.argv[3]
    from bigdl_tpu.engine import Engine
    Engine.init_distributed(f"127.0.0.1:{port}", 2, pid)

    import numpy as np
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import Sample, SampleToMiniBatch
    from bigdl_tpu.dataset.dataset import ShardedDataSet
    from bigdl_tpu.parallel import PipelineOptimizer
    from bigdl_tpu.parallel.distri_optimizer import local_data_partitions

    # pp x dp across hosts: (2 data, 4 stage) — each process owns one
    # data replica's full pipeline; stage ppermute stays intra-process,
    # the data-gradient psum crosses processes
    mesh = Engine.create_mesh((2, 4), ("data", "stage"))
    local = local_data_partitions(mesh)
    assert local == [pid], local

    D = 8
    rng = np.random.RandomState(2)
    x = rng.normal(size=(32, D)).astype(np.float32)
    w_true = rng.normal(size=(D, D)).astype(np.float32) * 0.4
    y = np.tanh(x @ w_true)
    samples = [Sample(x[i], y[i]) for i in range(32)]
    ds = ShardedDataSet(samples, 2, local_partitions=local).transform(
        SampleToMiniBatch(16, 2))
    blocks = []
    for s in range(4):
        b = nn.Sequential().add(nn.Linear(D, D)).add(nn.Tanh())
        b.reset(jax.random.PRNGKey(s))
        blocks.append(b)
    opt = PipelineOptimizer(blocks, ds, nn.MSECriterion(), mesh=mesh,
                            n_micro=2)
    opt.set_optim_method(optim.SGD(learning_rate=0.5))
    opt.set_end_when(optim.max_iteration(4))
    trained = opt.optimize()
    w, _ = trained.get_parameters()
    np.save(os.path.join(outdir, f"pp_w{pid}.npy"), np.asarray(w))
    print("PP_WORKER_OK", pid)
""")


@pytest.mark.slow
def test_two_process_pipeline_training_matches_single_process():
    """pp x dp across 2 OS processes: PipelineOptimizer's per-process
    ShardedDataSet feeding + the cross-process data psum must reproduce
    the single-process (2, 4) run."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = _clean_env()
    with tempfile.TemporaryDirectory() as outdir:
        procs = [subprocess.Popen(
            [sys.executable, "-c", _PP_WORKER, str(pid), str(port), outdir],
            cwd=repo_root, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True) for pid in (0, 1)]
        outs = []
        for p in procs:
            out, err = p.communicate(timeout=1200)
            outs.append((p.returncode, out, err))
        for rc, out, err in outs:
            assert rc == 0 and "PP_WORKER_OK" in out, (out, err[-3000:])
        w0 = np.load(os.path.join(outdir, "pp_w0.npy"))
        w1 = np.load(os.path.join(outdir, "pp_w1.npy"))
        np.testing.assert_array_equal(w0, w1)

        import jax
        import bigdl_tpu.nn as nn
        import bigdl_tpu.optim as optim
        from bigdl_tpu.dataset import Sample, SampleToMiniBatch
        from bigdl_tpu.dataset.dataset import ShardedDataSet
        from bigdl_tpu.engine import Engine
        from bigdl_tpu.parallel import PipelineOptimizer

        D = 8
        rng = np.random.RandomState(2)
        x = rng.normal(size=(32, D)).astype(np.float32)
        w_true = rng.normal(size=(D, D)).astype(np.float32) * 0.4
        y = np.tanh(x @ w_true)
        samples = [Sample(x[i], y[i]) for i in range(32)]
        ds = ShardedDataSet(samples, 2).transform(SampleToMiniBatch(16, 2))
        blocks = []
        for s in range(4):
            b = nn.Sequential().add(nn.Linear(D, D)).add(nn.Tanh())
            b.reset(jax.random.PRNGKey(s))
            blocks.append(b)
        mesh = Engine.create_mesh((2, 4), ("data", "stage"))
        opt = PipelineOptimizer(blocks, ds, nn.MSECriterion(), mesh=mesh,
                                n_micro=2)
        opt.set_optim_method(optim.SGD(learning_rate=0.5))
        opt.set_end_when(optim.max_iteration(4))
        w_single, _ = opt.optimize().get_parameters()
        np.testing.assert_allclose(w0, np.asarray(w_single),
                                   rtol=2e-4, atol=2e-5)
