"""Numerical-parity tests against torch (CPU) golden implementations.

The reference's dominant test strategy: 123 spec files under
``test/.../torch/`` serialize modules to ``.t7``, run Torch7 via the TH
harness (``torch/TH.scala:33``), and assert element-wise closeness.  Here
torch IS available in-process, so each test builds the torch twin from our
randomly-initialised parameters (transposed to torch conventions) and
compares forward — and for the core training layers, gradients too.
"""

import numpy as np
import pytest

import bigdl_tpu.nn as nn

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402

RTOL, ATOL = 1e-4, 1e-5


def _np(x):
    return np.asarray(x, dtype=np.float32)


def _t(x):
    return torch.from_numpy(_np(x).copy())


class TestConvParity:
    def test_spatial_convolution(self):
        rng = np.random.RandomState(0)
        m = nn.SpatialConvolution(3, 8, 3, 5, 2, 1, 1, 2)   # kw=3 kh=5 dw=2 dh=1
        m._ensure_init()
        x = rng.normal(size=(2, 3, 11, 9)).astype(np.float32)
        ours = _np(m.forward(x))
        w = _np(m.params["weight"]).transpose(3, 2, 0, 1)   # HWIO -> OIHW
        want = F.conv2d(_t(x), _t(w), _t(m.params["bias"]),
                        stride=(1, 2), padding=(2, 1)).numpy()
        np.testing.assert_allclose(ours, want, rtol=RTOL, atol=ATOL)

    def test_spatial_convolution_grouped(self):
        rng = np.random.RandomState(1)
        m = nn.SpatialConvolution(4, 6, 3, 3, n_group=2)
        m._ensure_init()
        x = rng.normal(size=(2, 4, 7, 7)).astype(np.float32)
        ours = _np(m.forward(x))
        w = _np(m.params["weight"])
        if w.ndim == 5:   # grouped native layout (g, kh, kw, in/g, out/g)
            w = np.concatenate([w[g] for g in range(w.shape[0])], axis=-1)
        want = F.conv2d(_t(x), _t(w.transpose(3, 2, 0, 1)),
                        _t(m.params["bias"]), groups=2).numpy()
        np.testing.assert_allclose(ours, want, rtol=RTOL, atol=ATOL)

    def test_dilated_convolution(self):
        rng = np.random.RandomState(2)
        m = nn.SpatialDilatedConvolution(3, 5, 3, 3, 1, 1, 2, 2, 2, 2)
        m._ensure_init()
        x = rng.normal(size=(2, 3, 12, 12)).astype(np.float32)
        ours = _np(m.forward(x))
        w = _np(m.params["weight"]).transpose(3, 2, 0, 1)
        want = F.conv2d(_t(x), _t(w), _t(m.params["bias"]),
                        padding=2, dilation=2).numpy()
        np.testing.assert_allclose(ours, want, rtol=RTOL, atol=ATOL)

    def test_full_convolution_transposed(self):
        rng = np.random.RandomState(3)
        m = nn.SpatialFullConvolution(4, 3, 3, 3, 2, 2, 1, 1, 1, 1)
        m._ensure_init()
        x = rng.normal(size=(2, 4, 6, 6)).astype(np.float32)
        ours = _np(m.forward(x))
        w = _np(m.params["weight"]).transpose(2, 3, 0, 1)   # -> (in,out,kh,kw)
        want = F.conv_transpose2d(_t(x), _t(w), _t(m.params["bias"]),
                                  stride=2, padding=1,
                                  output_padding=1).numpy()
        np.testing.assert_allclose(ours, want, rtol=RTOL, atol=ATOL)

    def test_temporal_convolution(self):
        rng = np.random.RandomState(4)
        m = nn.TemporalConvolution(5, 7, 3, 2)
        m._ensure_init()
        x = rng.normal(size=(2, 10, 5)).astype(np.float32)  # (N, T, C)
        ours = _np(m.forward(x))
        w = _np(m.params["weight"])
        # our (kw, in, out); torch Conv1d wants (out, in*kw) applied to
        # unfolded frames — equivalently conv1d weight (out, in, kw).
        # BigDL's TemporalConvolution flattens frames first: frame t gathers
        # [x[t], x[t+1], ...] concatenated feature-major, which equals
        # conv1d with kernel reversed per-tap order preserved.
        tw = w.transpose(2, 1, 0)                           # (out, in, kw)
        want = F.conv1d(_t(x).transpose(1, 2), _t(tw), _t(m.params["bias"]),
                        stride=2).transpose(1, 2).numpy()
        np.testing.assert_allclose(ours, want, rtol=RTOL, atol=ATOL)

    def test_volumetric_convolution(self):
        rng = np.random.RandomState(5)
        m = nn.VolumetricConvolution(2, 4, 3, 3, 3, 2, 2, 2, 1, 1, 1)
        m._ensure_init()
        x = rng.normal(size=(2, 2, 7, 8, 9)).astype(np.float32)
        ours = _np(m.forward(x))
        w = _np(m.params["weight"]).transpose(4, 3, 0, 1, 2)  # -> OIDHW
        want = F.conv3d(_t(x), _t(w), _t(m.params["bias"]),
                        stride=2, padding=1).numpy()
        np.testing.assert_allclose(ours, want, rtol=RTOL, atol=ATOL)

    def test_conv_gradients(self):
        rng = np.random.RandomState(6)
        m = nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1)
        m._ensure_init()
        x = rng.normal(size=(2, 3, 8, 8)).astype(np.float32)
        g = rng.normal(size=(2, 4, 8, 8)).astype(np.float32)
        m.forward(x)
        m.zero_grad_parameters()
        grad_in = _np(m.backward(x, g))

        tx = _t(x).requires_grad_(True)
        tw = _t(_np(m.params["weight"]).transpose(3, 2, 0, 1)).requires_grad_(True)
        tb = _t(m.params["bias"]).requires_grad_(True)
        out = F.conv2d(tx, tw, tb, padding=1)
        out.backward(_t(g))
        np.testing.assert_allclose(grad_in, tx.grad.numpy(),
                                   rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(
            _np(m.grads["weight"]).transpose(3, 2, 0, 1),
            tw.grad.numpy(), rtol=RTOL, atol=1e-4)
        np.testing.assert_allclose(_np(m.grads["bias"]),
                                   tb.grad.numpy(), rtol=RTOL, atol=1e-4)


class TestPoolNormParity:
    def test_max_pooling_floor_and_ceil(self):
        rng = np.random.RandomState(7)
        x = rng.normal(size=(2, 3, 7, 7)).astype(np.float32)
        for ceil in (False, True):
            m = nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1)
            if ceil:
                m = m.ceil()
            want = F.max_pool2d(_t(x), 3, 2, 1, ceil_mode=ceil).numpy()
            np.testing.assert_allclose(_np(m.forward(x)), want,
                                       rtol=RTOL, atol=ATOL)

    def test_avg_pooling_include_exclude_pad(self):
        rng = np.random.RandomState(8)
        x = rng.normal(size=(2, 3, 8, 8)).astype(np.float32)
        for include in (True, False):
            m = nn.SpatialAveragePooling(3, 3, 2, 2, 1, 1,
                                         count_include_pad=include)
            want = F.avg_pool2d(_t(x), 3, 2, 1,
                                count_include_pad=include).numpy()
            np.testing.assert_allclose(_np(m.forward(x)), want,
                                       rtol=RTOL, atol=ATOL)

    def test_spatial_zero_padding_randomized_vs_torch(self):
        """Randomized pad/crop sweep vs torch ZeroPad2d (negative pads
        crop there too — reference ``nn/SpatialZeroPadding.scala``)."""
        rng = np.random.RandomState(11)
        for _ in range(12):
            x = rng.normal(size=(2, 3, rng.randint(4, 9),
                                 rng.randint(4, 9))).astype(np.float32)
            pl, pr, pt, pb = (int(rng.randint(-2, 3)) for _ in range(4))
            if (x.shape[3] + pl + pr < 1 or x.shape[2] + pt + pb < 1):
                continue
            m = nn.SpatialZeroPadding(pl, pr, pt, pb)
            want = torch.nn.ZeroPad2d((pl, pr, pt, pb))(_t(x)).numpy()
            np.testing.assert_allclose(_np(m.forward(x)), want,
                                       rtol=RTOL, atol=ATOL)

    def test_volumetric_max_pooling(self):
        rng = np.random.RandomState(9)
        x = rng.normal(size=(2, 2, 6, 6, 6)).astype(np.float32)
        m = nn.VolumetricMaxPooling(2, 2, 2, 2, 2, 2)
        want = F.max_pool3d(_t(x), 2, 2).numpy()
        np.testing.assert_allclose(_np(m.forward(x)), want,
                                   rtol=RTOL, atol=ATOL)

    def test_batchnorm_train_eval_and_running_stats(self):
        rng = np.random.RandomState(10)
        m = nn.SpatialBatchNormalization(5)
        m._ensure_init()
        tm = torch.nn.BatchNorm2d(5, eps=m.eps, momentum=m.momentum)
        with torch.no_grad():
            tm.weight.copy_(_t(m.params["weight"]))
            tm.bias.copy_(_t(m.params["bias"]))
        x = rng.normal(2, 3, size=(4, 5, 6, 6)).astype(np.float32)

        m.training()
        tm.train()
        np.testing.assert_allclose(_np(m.forward(x)),
                                   tm(_t(x)).detach().numpy(),
                                   rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(_np(m.state["running_mean"]),
                                   tm.running_mean.numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(_np(m.state["running_var"]),
                                   tm.running_var.numpy(),
                                   rtol=1e-4, atol=1e-5)

        m.evaluate()
        tm.eval()
        np.testing.assert_allclose(_np(m.forward(x)),
                                   tm(_t(x)).detach().numpy(),
                                   rtol=1e-3, atol=1e-4)

    def test_cross_map_lrn(self):
        rng = np.random.RandomState(11)
        x = rng.normal(size=(2, 8, 5, 5)).astype(np.float32)
        m = nn.SpatialCrossMapLRN(5, alpha=1e-3, beta=0.75, k=1.0)
        want = torch.nn.LocalResponseNorm(5, alpha=1e-3, beta=0.75,
                                          k=1.0)(_t(x)).numpy()
        np.testing.assert_allclose(_np(m.forward(x)), want,
                                   rtol=RTOL, atol=ATOL)


class TestLayerParity:
    def test_linear_and_bilinear(self):
        rng = np.random.RandomState(12)
        m = nn.Linear(6, 4)
        m._ensure_init()
        x = rng.normal(size=(3, 6)).astype(np.float32)
        want = F.linear(_t(x), _t(_np(m.params["weight"]).T),
                        _t(m.params["bias"])).numpy()
        np.testing.assert_allclose(_np(m.forward(x)), want,
                                   rtol=RTOL, atol=ATOL)

        bm = nn.Bilinear(5, 3, 4)
        bm._ensure_init()
        x1 = rng.normal(size=(3, 5)).astype(np.float32)
        x2 = rng.normal(size=(3, 3)).astype(np.float32)
        # same (out, in1, in2) weight layout as torch.nn.Bilinear
        want = F.bilinear(_t(x1), _t(x2), _t(bm.params["weight"]),
                          _t(bm.params["bias"])).numpy()
        np.testing.assert_allclose(_np(bm.forward([x1, x2])), want,
                                   rtol=RTOL, atol=ATOL)

    def test_lookup_table_is_one_based_embedding(self):
        rng = np.random.RandomState(13)
        m = nn.LookupTable(10, 4)
        m._ensure_init()
        idx = rng.randint(1, 11, size=(3, 5)).astype(np.float32)  # 1-based
        ours = _np(m.forward(idx))
        want = F.embedding(_t(idx).long() - 1,
                           _t(m.params["weight"])).numpy()
        np.testing.assert_allclose(ours, want, rtol=RTOL, atol=ATOL)

    def test_activations(self):
        rng = np.random.RandomState(14)
        x = rng.normal(0, 3, size=(4, 9)).astype(np.float32)
        tx = _t(x)
        pairs = [
            (nn.ELU(alpha=0.7), F.elu(tx, alpha=0.7)),
            (nn.LeakyReLU(0.02), F.leaky_relu(tx, 0.02)),
            (nn.SoftPlus(), F.softplus(tx)),
            (nn.SoftSign(), F.softsign(tx)),
            (nn.LogSigmoid(), F.logsigmoid(tx)),
            (nn.HardShrink(0.5), F.hardshrink(tx, 0.5)),
            (nn.SoftShrink(0.5), F.softshrink(tx, 0.5)),
            (nn.Tanh(), torch.tanh(tx)),
            (nn.LogSoftMax(), F.log_softmax(tx, dim=-1)),
            (nn.SoftMax(), F.softmax(tx, dim=-1)),
            (nn.HardTanh(-2.0, 3.0), F.hardtanh(tx, -2.0, 3.0)),
            (nn.ReLU6(), F.relu6(tx)),
        ]
        for m, want in pairs:
            np.testing.assert_allclose(
                _np(m.forward(x)), want.numpy(), rtol=RTOL, atol=ATOL,
                err_msg=type(m).__name__)

    def test_prelu_shared_parameter(self):
        rng = np.random.RandomState(15)
        m = nn.PReLU()
        m._ensure_init()
        x = rng.normal(size=(4, 6)).astype(np.float32)
        a = _np(m.params["weight"]).ravel()
        want = F.prelu(_t(x), _t(a)).numpy()
        np.testing.assert_allclose(_np(m.forward(x)), want,
                                   rtol=RTOL, atol=ATOL)


class TestCriterionParity:
    def test_class_nll(self):
        rng = np.random.RandomState(16)
        logp = F.log_softmax(_t(rng.normal(size=(6, 5)).astype(np.float32)),
                             dim=-1)
        target = rng.randint(1, 6, size=6).astype(np.float32)   # 1-based
        ours = float(nn.ClassNLLCriterion().forward(logp.numpy(), target))
        want = float(F.nll_loss(logp, _t(target).long() - 1))
        assert abs(ours - want) < 1e-5
        # backward parity
        tlp = logp.clone().requires_grad_(True)
        F.nll_loss(tlp, _t(target).long() - 1).backward()
        grad = _np(nn.ClassNLLCriterion().backward(logp.numpy(), target))
        np.testing.assert_allclose(grad, tlp.grad.numpy(),
                                   rtol=RTOL, atol=ATOL)

    def test_elementwise_criterions(self):
        rng = np.random.RandomState(17)
        x = rng.normal(size=(4, 7)).astype(np.float32)
        y = rng.normal(size=(4, 7)).astype(np.float32)
        tx, ty = _t(x), _t(y)
        sig = 1.0 / (1.0 + np.exp(-x))
        ysig = (rng.rand(4, 7) > 0.5).astype(np.float32)
        cases = [
            (nn.MSECriterion(), x, y, F.mse_loss(tx, ty)),
            (nn.AbsCriterion(), x, y, F.l1_loss(tx, ty)),
            (nn.SmoothL1Criterion(), x, y, F.smooth_l1_loss(tx, ty)),
            (nn.BCECriterion(), sig, ysig,
             F.binary_cross_entropy(torch.sigmoid(tx), _t(ysig))),
            # sizeAverage divides by nElement (reference
            # DistKLDivCriterion.scala); sum/numel avoids torch's
            # deprecated reduction="mean" semantics
            (nn.DistKLDivCriterion(), np.log(sig), ysig,
             F.kl_div(torch.log(torch.sigmoid(tx)), _t(ysig),
                      reduction="sum") / tx.numel()),
            (nn.SoftMarginCriterion(), x, np.sign(y) + (y == 0),
             F.soft_margin_loss(tx, torch.sign(ty) + (ty == 0).float())),
        ]
        for crit, a, b, want in cases:
            got = float(crit.forward(a.astype(np.float32),
                                     b.astype(np.float32)))
            assert abs(got - float(want)) < 1e-4, type(crit).__name__

    def test_margin_criterions(self):
        rng = np.random.RandomState(18)
        x = rng.normal(size=(5, 6)).astype(np.float32)
        target = rng.randint(1, 7, size=5).astype(np.float32)
        ours = float(nn.MultiMarginCriterion().forward(x, target))
        want = float(F.multi_margin_loss(_t(x), _t(target).long() - 1))
        assert abs(ours - want) < 1e-4

        x1 = rng.normal(size=(8,)).astype(np.float32)
        x2 = rng.normal(size=(8,)).astype(np.float32)
        yy = np.where(rng.rand(8) > 0.5, 1.0, -1.0).astype(np.float32)
        ours = float(nn.MarginRankingCriterion(margin=0.5).forward(
            [x1, x2], yy))
        want = float(F.margin_ranking_loss(_t(x1), _t(x2), _t(yy),
                                           margin=0.5))
        assert abs(ours - want) < 1e-4

    def test_cosine_embedding(self):
        rng = np.random.RandomState(19)
        a = rng.normal(size=(6, 5)).astype(np.float32)
        b = rng.normal(size=(6, 5)).astype(np.float32)
        y = np.where(rng.rand(6) > 0.5, 1.0, -1.0).astype(np.float32)
        ours = float(nn.CosineEmbeddingCriterion(margin=0.3).forward(
            [a, b], y))
        want = float(F.cosine_embedding_loss(_t(a), _t(b), _t(y),
                                             margin=0.3))
        assert abs(ours - want) < 1e-4

    def test_cross_entropy(self):
        rng = np.random.RandomState(20)
        logits = rng.normal(size=(6, 5)).astype(np.float32)
        target = rng.randint(1, 6, size=6).astype(np.float32)
        ours = float(nn.CrossEntropyCriterion().forward(logits, target))
        want = float(F.cross_entropy(_t(logits), _t(target).long() - 1))
        assert abs(ours - want) < 1e-4


class TestRandomizedConvPoolSweep:
    """Fuzz-style parity: random geometry configs against torch (seeded).
    Broadens the hand-picked cases above across the kernel/stride/pad
    space where off-by-one output-size bugs live."""

    def test_conv2d_sweep(self):
        rng = np.random.RandomState(42)
        for trial in range(12):
            cin = int(rng.randint(1, 5))
            cout = int(rng.randint(1, 6))
            kw, kh = int(rng.randint(1, 5)), int(rng.randint(1, 5))
            dw, dh = int(rng.randint(1, 4)), int(rng.randint(1, 4))
            pw, ph = int(rng.randint(0, 3)), int(rng.randint(0, 3))
            h = int(rng.randint(kh + 2, 14))
            w = int(rng.randint(kw + 2, 14))
            m = nn.SpatialConvolution(cin, cout, kw, kh, dw, dh, pw, ph)
            m._ensure_init()
            x = rng.normal(size=(2, cin, h, w)).astype(np.float32)
            ours = _np(m.forward(x))
            tw = _t(_np(m.params["weight"]).transpose(3, 2, 0, 1))
            want = F.conv2d(_t(x), tw, _t(m.params["bias"]),
                            stride=(dh, dw), padding=(ph, pw)).numpy()
            np.testing.assert_allclose(
                ours, want, rtol=RTOL, atol=1e-4,
                err_msg=f"trial {trial}: cin{cin} cout{cout} k({kh},{kw}) "
                        f"s({dh},{dw}) p({ph},{pw}) in({h},{w})")

    def test_pool_sweep(self):
        rng = np.random.RandomState(7)
        for trial in range(12):
            k = int(rng.randint(2, 5))
            d = int(rng.randint(1, 4))
            p = int(rng.randint(0, (k + 1) // 2))
            h = int(rng.randint(k + 2, 16))
            ceil = bool(rng.randint(0, 2))
            x = rng.normal(size=(2, 3, h, h)).astype(np.float32)

            mp = nn.SpatialMaxPooling(k, k, d, d, p, p)
            if ceil:
                mp = mp.ceil()
            want = F.max_pool2d(_t(x), k, d, p, ceil_mode=ceil).numpy()
            np.testing.assert_allclose(
                _np(mp.forward(x)), want, rtol=RTOL, atol=ATOL,
                err_msg=f"max trial {trial}: k{k} d{d} p{p} h{h} ceil{ceil}")

            include = bool(rng.randint(0, 2))
            ap = nn.SpatialAveragePooling(k, k, d, d, p, p,
                                          ceil_mode=ceil,
                                          count_include_pad=include)
            want = F.avg_pool2d(_t(x), k, d, p, ceil_mode=ceil,
                                count_include_pad=include).numpy()
            np.testing.assert_allclose(
                _np(ap.forward(x)), want, rtol=RTOL, atol=1e-4,
                err_msg=f"avg trial {trial}: k{k} d{d} p{p} h{h} "
                        f"ceil{ceil} incl{include}")
