"""Seeded reproducibility is prefetch-independent.

The BatchPrefetcher's producer thread owns epoch rollovers (reshuffles);
it must continue the MAIN thread's RandomGenerator stream — a user's
``set_seed`` before training governs every epoch's shuffle whether
prefetching is on (default) or off, and both settings produce the
identical batch sequence (advisor r3 finding #1)."""

import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import LocalDataSet, SampleToMiniBatch
from bigdl_tpu.dataset.datasets import synthetic_separable
from bigdl_tpu.utils import config
from bigdl_tpu.utils.random_generator import RandomGenerator


def _train_weights(prefetch_depth: int) -> np.ndarray:
    import jax
    config.set_property("bigdl.prefetch.depth", prefetch_depth)
    try:
        # a NON-default seed: if the producer thread fell back to a fresh
        # default-seeded thread-local generator, epoch 2+ shuffles would
        # diverge from the depth=0 run
        RandomGenerator.RNG().set_seed(20240731)
        samples = synthetic_separable(128, 4, n_classes=2, seed=3)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(32))
        model = (nn.Sequential().add(nn.Linear(4, 16)).add(nn.Tanh())
                 .add(nn.Linear(16, 2)).add(nn.LogSoftMax()))
        model.reset(jax.random.PRNGKey(11))
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        # momentum makes the trajectory batch-ORDER sensitive, so a shuffle
        # divergence shows up in the final weights
        opt.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
        opt.set_end_when(optim.max_epoch(3))
        opt.optimize()
        w, _ = model.get_parameters()
        return np.asarray(w)
    finally:
        config.clear_property("bigdl.prefetch.depth")


def test_seeded_shuffles_identical_with_and_without_prefetch():
    w_sync = _train_weights(0)
    w_prefetch = _train_weights(2)
    np.testing.assert_array_equal(w_sync, w_prefetch)


# ---------------------------------------------------------------------------
# streaming ingest engine vs the synchronous MT path
# ---------------------------------------------------------------------------

def _jpeg_records(n=24, hw=(40, 48), seed=3):
    """Losslessly-compressed records (PNG) so pixel parity is exact."""
    import io

    from PIL import Image

    from bigdl_tpu.dataset.image import LabeledImageBytes
    rng = np.random.RandomState(seed)
    recs = []
    for i in range(n):
        img = rng.randint(0, 256, size=hw + (3,)).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(img).save(buf, "PNG")
        recs.append(LabeledImageBytes(f"r{i}", float(i % 5 + 1),
                                      buf.getvalue()))
    return recs


def _batches(transformer, recs, seed=20240731):
    RandomGenerator.RNG().set_seed(seed)
    out = [(b.get_input().copy(), b.get_target().copy())
           for b in transformer(iter(recs))]
    # the post-run RNG position is part of the contract: downstream draws
    # (an epoch reshuffle) must continue from the same point
    end_state = RandomGenerator.RNG().np.get_state()
    return out, end_state


def _assert_same(a, b):
    (batches_a, state_a), (batches_b, state_b) = a, b
    assert len(batches_a) == len(batches_b)
    for (xa, ya), (xb, yb) in zip(batches_a, batches_b):
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)
    for sa, sb in zip(state_a, state_b):
        np.testing.assert_array_equal(np.asarray(sa), np.asarray(sb))


# every bigdl.ingest.* depth knob exercised at an extreme and a typical
# value: (decode workers, record ring, decoded window, batch ring)
DEPTHS = [(1, 1, 1, 1),        # fully serialized rings
          (2, 4, 3, 1),        # window smaller than a batch
          (3, 64, 16, 4),      # deep read-ahead
          (4, 256, 8, 2)]      # defaults-ish


@pytest.mark.parametrize("workers,rec_d,dec_d,batch_d", DEPTHS)
def test_streaming_engine_bit_identical_to_sync_path(workers, rec_d, dec_d,
                                                     batch_d):
    """The pipelined engine must reproduce the synchronous
    MTLabeledBGRImgToBatch batch sequence BIT-IDENTICALLY — crops, flips,
    record order, labels, and the caller's post-run RNG position — at
    every ring-depth setting (pipelining is a latency property, never a
    semantics change)."""
    from bigdl_tpu.dataset.ingest import StreamingIngest
    from bigdl_tpu.dataset.mt_batch import MTLabeledBGRImgToBatch

    recs = _jpeg_records()
    sync = _batches(MTLabeledBGRImgToBatch(4, crop=(32, 32)), recs)
    eng = StreamingIngest(4, crop=(32, 32), decode_workers=workers,
                          record_ring_depth=rec_d, decoded_ring_depth=dec_d,
                          batch_ring_depth=batch_d)
    _assert_same(sync, _batches(eng, recs))


def test_streaming_engine_honours_config_properties():
    """Depths set through ``bigdl.ingest.*`` config keys (not constructor
    args) govern the engine — and stay bit-identical to the sync path."""
    from bigdl_tpu.dataset.ingest import StreamingIngest
    from bigdl_tpu.dataset.mt_batch import MTLabeledBGRImgToBatch

    recs = _jpeg_records()
    sync = _batches(MTLabeledBGRImgToBatch(4, crop=(32, 32)), recs)
    keys = {"bigdl.ingest.decodeWorkers": 2,
            "bigdl.ingest.recordRingDepth": 3,
            "bigdl.ingest.decodedRingDepth": 5,
            "bigdl.ingest.batchRingDepth": 1}
    for k, v in keys.items():
        config.set_property(k, v)
    try:
        eng = StreamingIngest(4, crop=(32, 32))
        assert (eng.decode_workers, eng.record_ring_depth,
                eng.decoded_ring_depth, eng.batch_ring_depth) == (2, 3, 5, 1)
        _assert_same(sync, _batches(eng, recs))
    finally:
        for k in keys:
            config.clear_property(k)


def test_streaming_engine_device_normalize_layout_identical():
    """The uint8 device-normalize layout pipelines identically."""
    from bigdl_tpu.dataset.ingest import StreamingIngest
    from bigdl_tpu.dataset.mt_batch import MTLabeledBGRImgToBatch

    recs = _jpeg_records()
    sync = _batches(MTLabeledBGRImgToBatch(4, crop=(32, 32),
                                           device_normalize=True), recs)
    got = _batches(StreamingIngest(4, crop=(32, 32), device_normalize=True,
                                   decode_workers=2, decoded_ring_depth=6),
                   recs)
    assert got[0][0][0].dtype == np.uint8
    _assert_same(sync, got)


@pytest.mark.parametrize("shards", [1, 2, 3, 7])
def test_multi_shard_reader_preserves_record_order(tmp_path, shards):
    """The sharded seqfile reader must yield records in exactly the
    sorted-walk order of a sequential sweep, at every shard count."""
    from bigdl_tpu.dataset import seqfile
    from bigdl_tpu.dataset.ingest import ShardedSeqFileReader

    rng = np.random.RandomState(0)
    for fi in range(5):
        entries = [(f"f{fi}_i{i}", float(i % 3 + 1),
                    rng.bytes(rng.randint(10, 400))) for i in range(6)]
        seqfile.write_image_seqfile(str(tmp_path / f"part-{fi:02d}.seq"),
                                    entries)
    sequential = [(r.name, r.label, r.bytes)
                  for r in ShardedSeqFileReader(str(tmp_path), shards=1)]
    assert len(sequential) == 30
    sharded = [(r.name, r.label, r.bytes)
               for r in ShardedSeqFileReader(str(tmp_path), shards=shards)]
    assert sharded == sequential


@pytest.mark.parametrize("workers,rec_d,dec_d,batch_d",
                         [(1, 1, 1, 1), (3, 64, 16, 4)])
def test_seqfile_to_batches_pipeline_bit_identical(tmp_path, workers, rec_d,
                                                   dec_d, batch_d):
    """End to end: multi-shard seqfile read -> streaming engine equals the
    sequential read -> synchronous MT path, batch for batch."""
    from bigdl_tpu.dataset import seqfile
    from bigdl_tpu.dataset.ingest import (ShardedSeqFileReader,
                                          StreamingIngest)
    from bigdl_tpu.dataset.mt_batch import MTLabeledBGRImgToBatch

    recs = _jpeg_records(n=18)
    for fi in range(3):
        seqfile.write_image_seqfile(
            str(tmp_path / f"part-{fi}.seq"),
            [(r.name, r.label, r.bytes) for r in recs[fi * 6:(fi + 1) * 6]])

    sync = _batches(MTLabeledBGRImgToBatch(4, crop=(32, 32)),
                    list(ShardedSeqFileReader(str(tmp_path), shards=1)))
    eng = StreamingIngest(4, crop=(32, 32), decode_workers=workers,
                          record_ring_depth=rec_d, decoded_ring_depth=dec_d,
                          batch_ring_depth=batch_d)
    RandomGenerator.RNG().set_seed(20240731)
    got = [(b.get_input().copy(), b.get_target().copy())
           for b in eng(iter(ShardedSeqFileReader(str(tmp_path),
                                                  shards=3)))]
    got_state = RandomGenerator.RNG().np.get_state()
    _assert_same(sync, (got, got_state))


def test_abandoned_read_ahead_does_not_advance_caller_rng():
    """Pipeline read-ahead that the consumer never takes (the epoch-
    rollover discard) must not move the caller's RNG stream: the committed
    position reflects CONSUMED batches only, so a depth-8 engine abandoned
    after 2 batches leaves the stream exactly where the synchronous path
    does after 2 batches."""
    from bigdl_tpu.dataset.ingest import StreamingIngest
    from bigdl_tpu.dataset.mt_batch import MTLabeledBGRImgToBatch

    recs = _jpeg_records(n=32)

    RandomGenerator.RNG().set_seed(99)
    sync_it = MTLabeledBGRImgToBatch(4, crop=(32, 32))(iter(recs))
    sync_batches = [next(sync_it), next(sync_it)]
    sync_state = RandomGenerator.RNG().np.get_state()
    sync_it.close()

    RandomGenerator.RNG().set_seed(99)
    eng = StreamingIngest(4, crop=(32, 32), decode_workers=2,
                          record_ring_depth=64, decoded_ring_depth=16,
                          batch_ring_depth=4)
    it = eng(iter(recs))
    got_batches = [next(it), next(it)]
    import time
    time.sleep(0.2)          # let the engine read far ahead
    it.close()               # discard everything it buffered
    got_state = RandomGenerator.RNG().np.get_state()

    for s, g in zip(sync_batches, got_batches):
        np.testing.assert_array_equal(s.get_input(), g.get_input())
    for sa, sb in zip(sync_state, got_state):
        np.testing.assert_array_equal(np.asarray(sa), np.asarray(sb))


# ---------------------------------------------------------------------------
# on-device augmentation vs the host MT path (satellite: bit-parity)
# ---------------------------------------------------------------------------

def _augment_on_device(batch):
    """Apply the device-side crop/flip/transpose to a device-augment
    MiniBatch and return the resulting uint8 NCHW array on host."""
    from bigdl_tpu.dataset.device_augment import crop_flip_transpose
    frames, offs, flips = batch[0], batch[1], batch[2]
    return np.asarray(crop_flip_transpose(frames, offs, flips, 32, 32))


@pytest.mark.parametrize("workers,rec_d,dec_d,batch_d", DEPTHS)
def test_device_augment_bit_identical_to_host_path(workers, rec_d, dec_d,
                                                   batch_d):
    """Device-augment mode ships full frames + ride-along crop offsets /
    flip flags; applying the device transform must reproduce the host
    path's cropped uint8 batches BIT-IDENTICALLY — same drawer, same
    draw order, same pixels — at every ``bigdl.ingest.*`` depth."""
    from bigdl_tpu.dataset.ingest import StreamingIngest
    from bigdl_tpu.dataset.mt_batch import MTLabeledBGRImgToBatch

    recs = _jpeg_records()
    sync = _batches(MTLabeledBGRImgToBatch(4, crop=(32, 32),
                                           device_normalize=True), recs)
    eng = StreamingIngest(4, crop=(32, 32), device_augment=True,
                          decode_workers=workers, record_ring_depth=rec_d,
                          decoded_ring_depth=dec_d, batch_ring_depth=batch_d)
    got, got_state = _batches(eng, recs)
    (sync_batches, sync_state) = sync
    assert len(got) == len(sync_batches)
    for (xs, ys), (xg, yg) in zip(sync_batches, got):
        assert isinstance(xg, list) and len(xg) == 3
        assert xg[0].dtype == np.uint8 and xg[0].shape[-1] == 3  # NHWC full
        np.testing.assert_array_equal(xs, _augment_on_device(xg))
        np.testing.assert_array_equal(ys, yg)
    for sa, sb in zip(sync_state, got_state):
        np.testing.assert_array_equal(np.asarray(sa), np.asarray(sb))


def test_device_augment_mixed_shapes_host_fallback_parity():
    """Mixed-shape batches cannot stack full frames; the engine pre-crops
    on host (identity ride-alongs) and the result must still match the
    host path bit for bit after the device transform."""
    from bigdl_tpu.dataset.ingest import StreamingIngest
    from bigdl_tpu.dataset.mt_batch import MTLabeledBGRImgToBatch

    wide = _jpeg_records(n=6, hw=(40, 48), seed=3)
    tall = _jpeg_records(n=6, hw=(36, 52), seed=4)
    # interleave so EVERY batch of 4 mixes shapes (stacking impossible)
    recs = [r for pair in zip(wide, tall) for r in pair]
    sync = _batches(MTLabeledBGRImgToBatch(4, crop=(32, 32),
                                           device_normalize=True), recs)
    eng = StreamingIngest(4, crop=(32, 32), device_augment=True,
                          decode_workers=2)
    got, _ = _batches(eng, recs)
    for (xs, _), (xg, _) in zip(sync[0], got):
        np.testing.assert_array_equal(np.asarray(xg[1]), 0)  # identity offs
        np.testing.assert_array_equal(np.asarray(xg[2]), 0)  # identity flips
        np.testing.assert_array_equal(xs, _augment_on_device(xg))


def test_device_jitter_seeds_depth_invariant():
    """The per-record ColorJitter seeds ride the same clone-and-commit
    drawer as the crop/flip draws, so the seed sequence is identical at
    every pipeline depth — and the jitter transform is a pure function
    of (pixels, seed)."""
    import jax.numpy as jnp

    from bigdl_tpu.dataset.device_augment import color_jitter
    from bigdl_tpu.dataset.ingest import StreamingIngest

    recs = _jpeg_records()

    def seeds_at(workers, rec_d, dec_d, batch_d):
        eng = StreamingIngest(4, crop=(32, 32), device_augment=True,
                              device_jitter=True, decode_workers=workers,
                              record_ring_depth=rec_d,
                              decoded_ring_depth=dec_d,
                              batch_ring_depth=batch_d)
        out, _ = _batches(eng, recs)
        for x, _ in out:
            assert len(x) == 4            # frames, offs, flips, seeds
        return [np.asarray(x[3]) for x, _ in out]

    shallow = seeds_at(1, 1, 1, 1)
    deep = seeds_at(3, 64, 16, 4)
    for a, b in zip(shallow, deep):
        np.testing.assert_array_equal(a, b)

    imgs = jnp.asarray(np.random.RandomState(0).randint(
        0, 256, (4, 3, 32, 32)).astype(np.uint8))
    j1 = np.asarray(color_jitter(imgs, shallow[0], brightness=0.4,
                                 contrast=0.4, saturation=0.4))
    j2 = np.asarray(color_jitter(imgs, shallow[0], brightness=0.4,
                                 contrast=0.4, saturation=0.4))
    np.testing.assert_array_equal(j1, j2)
    assert j1.dtype == np.uint8 and j1.shape == (4, 3, 32, 32)


@pytest.mark.parametrize("ingest_depths", [(1, 1, 1, 1), (3, 64, 16, 4)])
def test_trained_weights_identical_device_augment_vs_host(ingest_depths):
    """Trained-weight parity for the tentpole: a model headed by
    ``nn.DeviceAugment`` + ``nn.ChannelNormalize`` reaches bit-identical
    weights whether fed cropped uint8 batches by the host MT path
    (DeviceAugment passes plain tensors through) or full frames +
    ride-alongs by the device-augment streaming engine."""
    import jax

    from bigdl_tpu.dataset.ingest import StreamingIngest
    from bigdl_tpu.dataset.mt_batch import MTLabeledBGRImgToBatch

    recs = _jpeg_records(n=16, hw=(36, 36))

    def train(transformer, prefetch_depth):
        config.set_property("bigdl.prefetch.depth", prefetch_depth)
        try:
            RandomGenerator.RNG().set_seed(4242)
            ds = LocalDataSet(recs).transform(transformer)
            model = (nn.Sequential()
                     .add(nn.DeviceAugment(32, 32))
                     .add(nn.ChannelNormalize((104.0, 117.0, 123.0),
                                              (1.0, 1.0, 1.0)))
                     .add(nn.Reshape((3 * 32 * 32,)))
                     .add(nn.Linear(3 * 32 * 32, 4)).add(nn.LogSoftMax()))
            model.reset(jax.random.PRNGKey(7))
            opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
            opt.set_optim_method(optim.SGD(learning_rate=0.05,
                                           momentum=0.9))
            opt.set_end_when(optim.max_epoch(3))
            opt.optimize()
            w, _ = model.get_parameters()
            return np.asarray(w)
        finally:
            config.clear_property("bigdl.prefetch.depth")

    w_host = train(MTLabeledBGRImgToBatch(4, crop=(32, 32),
                                          device_normalize=True), 0)
    workers, rec_d, dec_d, batch_d = ingest_depths
    w_dev = train(
        StreamingIngest(4, crop=(32, 32), device_augment=True,
                        decode_workers=workers, record_ring_depth=rec_d,
                        decoded_ring_depth=dec_d, batch_ring_depth=batch_d),
        2)
    np.testing.assert_array_equal(w_host, w_dev)


@pytest.mark.parametrize("ingest_depths", [(1, 1, 1, 1), (3, 64, 16, 4)])
def test_trained_weights_identical_sync_vs_streaming(ingest_depths):
    """Full training parity across epoch rollovers: momentum SGD over an
    image pipeline reaches bit-identical weights whether fed by the
    synchronous MT transformer (prefetch off) or the streaming engine
    (prefetch + transfer-ahead on) — reshuffles, crops, and flips all
    follow the same seeded stream."""
    import jax

    from bigdl_tpu.dataset.ingest import StreamingIngest
    from bigdl_tpu.dataset.mt_batch import MTLabeledBGRImgToBatch

    recs = _jpeg_records(n=16, hw=(36, 36))

    def train(transformer, prefetch_depth):
        config.set_property("bigdl.prefetch.depth", prefetch_depth)
        try:
            RandomGenerator.RNG().set_seed(4242)
            ds = LocalDataSet(recs).transform(transformer)
            model = (nn.Sequential().add(nn.Reshape((3 * 32 * 32,)))
                     .add(nn.Linear(3 * 32 * 32, 4)).add(nn.LogSoftMax()))
            model.reset(jax.random.PRNGKey(7))
            opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
            opt.set_optim_method(optim.SGD(learning_rate=0.05,
                                           momentum=0.9))
            opt.set_end_when(optim.max_epoch(3))
            opt.optimize()
            w, _ = model.get_parameters()
            return np.asarray(w)
        finally:
            config.clear_property("bigdl.prefetch.depth")

    w_sync = train(MTLabeledBGRImgToBatch(4, crop=(32, 32)), 0)
    workers, rec_d, dec_d, batch_d = ingest_depths
    w_stream = train(
        StreamingIngest(4, crop=(32, 32), decode_workers=workers,
                        record_ring_depth=rec_d, decoded_ring_depth=dec_d,
                        batch_ring_depth=batch_d), 2)
    np.testing.assert_array_equal(w_sync, w_stream)
