"""Seeded reproducibility is prefetch-independent.

The BatchPrefetcher's producer thread owns epoch rollovers (reshuffles);
it must continue the MAIN thread's RandomGenerator stream — a user's
``set_seed`` before training governs every epoch's shuffle whether
prefetching is on (default) or off, and both settings produce the
identical batch sequence (advisor r3 finding #1)."""

import numpy as np

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import LocalDataSet, SampleToMiniBatch
from bigdl_tpu.dataset.datasets import synthetic_separable
from bigdl_tpu.utils import config
from bigdl_tpu.utils.random_generator import RandomGenerator


def _train_weights(prefetch_depth: int) -> np.ndarray:
    import jax
    config.set_property("bigdl.prefetch.depth", prefetch_depth)
    try:
        # a NON-default seed: if the producer thread fell back to a fresh
        # default-seeded thread-local generator, epoch 2+ shuffles would
        # diverge from the depth=0 run
        RandomGenerator.RNG().set_seed(20240731)
        samples = synthetic_separable(128, 4, n_classes=2, seed=3)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(32))
        model = (nn.Sequential().add(nn.Linear(4, 16)).add(nn.Tanh())
                 .add(nn.Linear(16, 2)).add(nn.LogSoftMax()))
        model.reset(jax.random.PRNGKey(11))
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        # momentum makes the trajectory batch-ORDER sensitive, so a shuffle
        # divergence shows up in the final weights
        opt.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
        opt.set_end_when(optim.max_epoch(3))
        opt.optimize()
        w, _ = model.get_parameters()
        return np.asarray(w)
    finally:
        config.clear_property("bigdl.prefetch.depth")


def test_seeded_shuffles_identical_with_and_without_prefetch():
    w_sync = _train_weights(0)
    w_prefetch = _train_weights(2)
    np.testing.assert_array_equal(w_sync, w_prefetch)
