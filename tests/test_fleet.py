"""Fleet control plane: chaos-proven multi-model serving with
zero-downtime hot swap (ISSUE 17).

The claims under test (bigdl_tpu/fleet/):

- zero-downtime hot swap: a candidate warm-loads and warms beside the
  serving incumbent, traffic shifts atomically at cutover, the old
  replicas drain gracefully — ZERO requests lost during a clean rollout
  (nothing shed, nothing quarantined, nothing unaccounted);
- gated blue/green: the rollout refuses a candidate whose semantic
  fingerprint rotted between prepare and cutover
  (``bigdl.chaos.corruptCandidateAt``) or whose shadow-mirrored outputs
  diverge from the incumbent's, and rolls back automatically with the
  incumbent never missing a request;
- replica lifecycle supervision: a hard-killed replica
  (``bigdl.chaos.killReplicaAt``) is detected, its stranded in-flight
  requests are swept into ``shed`` (retriable), and the slot restarts
  within its budget; autoscaling follows queue depth + p99 latency under
  the host-memory governor's ceiling; a committed checkpoint promotes to
  serving as ONE verified step;
- and through ALL of it — including a fleet-wide SIGTERM mid-plan — the
  fleet accounting identity is exact:
  ``completed + shed + rejected + quarantined == submitted``.
"""

import os
import re
import time

import numpy as np
import pytest

import jax

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu import telemetry
from bigdl_tpu.fleet import (Fleet, FleetAutoscalePolicy, FleetSupervisor,
                             Replica, ReplicaKilled)
from bigdl_tpu.serving.engine import OUTCOMES, Overloaded, ServingInfraError
from bigdl_tpu.utils import chaos, config, elastic
from bigdl_tpu.utils.checkpoint_manager import CheckpointManager

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DIN, DOUT = 4, 3

_FLEET_KEYS = (
    "bigdl.compile.buckets",
    "bigdl.fleet.replicas", "bigdl.fleet.maxReplicaRestarts",
    "bigdl.fleet.gracePeriod", "bigdl.fleet.shadowSample",
    "bigdl.fleet.parityMode", "bigdl.fleet.promotionPollSec",
    "bigdl.fleet.autoscale.enabled", "bigdl.fleet.autoscale.intervalSec",
    "bigdl.chaos.killReplicaAt", "bigdl.chaos.corruptCandidateAt",
    "bigdl.chaos.sigtermFleetAt",
)


@pytest.fixture(autouse=True)
def _fleet_env():
    """Disarmed chaos, cleared preemption, clean knobs around every
    test."""
    elastic.clear_preemption()
    config.set_property("bigdl.compile.buckets", "2,4")
    yield
    chaos.uninstall()
    elastic.clear_preemption()
    for k in _FLEET_KEYS:
        config.clear_property(k)


def _model(seed=7):
    m = (nn.Sequential().add(nn.Linear(DIN, 16)).add(nn.Tanh())
         .add(nn.Linear(16, DOUT)))
    m.reset(jax.random.PRNGKey(seed))
    return m


_ROW = np.zeros((DIN,), np.float32)
#: generous per-request deadline: these tests assert accounting and
#: lifecycle, not tail latency — a CPU-CI hiccup must not shed for us
_ENGINE_KW = {"deadline_ms": 5000.0}


def _fleet(replicas=2, **kw):
    fleet = Fleet(poll_interval=0.02, **kw)
    fleet.add_model("svc", _model(), replicas=replicas, warm_row=_ROW,
                    engine_kw=dict(_ENGINE_KW))
    return fleet


def _rows(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, DIN)).astype(np.float32)


def _assert_identity(stats):
    assert stats["unaccounted"] == 0, stats
    assert sum(stats[o] for o in OUTCOMES) == stats["submitted"], stats


def _wait(predicate, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


# ---------------------------------------------------------------------------
# autoscale policy units
# ---------------------------------------------------------------------------

class TestAutoscalePolicy:
    def _policy(self, patience=2, cooldown=3):
        return FleetAutoscalePolicy(1, 4, up_queue_frac=0.5,
                                    down_queue_frac=0.05, p99_factor=0.8,
                                    patience=patience, cooldown=cooldown)

    def test_scale_up_needs_patience(self):
        p = self._policy()
        assert p.decide(0.9, 0.0, 100.0, 1) == 0
        assert p.decide(0.9, 0.0, 100.0, 1) == 1

    def test_hot_p99_scales_up_with_shallow_queue(self):
        p = self._policy()
        assert p.decide(0.0, 90.0, 100.0, 1) == 0
        assert p.decide(0.0, 90.0, 100.0, 1) == 1

    def test_cooldown_holds_after_action(self):
        p = self._policy(patience=1, cooldown=2)
        assert p.decide(0.9, 0.0, 100.0, 1) == 1
        assert p.decide(0.9, 0.0, 100.0, 2) == 0      # cooldown 1
        assert p.decide(0.9, 0.0, 100.0, 2) == 0      # cooldown 2
        assert p.decide(0.9, 0.0, 100.0, 2) == 1

    def test_scale_down_on_idle(self):
        p = self._policy(patience=2, cooldown=0)
        assert p.decide(0.0, 0.0, 100.0, 3) == 0
        assert p.decide(0.0, 0.0, 100.0, 3) == -1

    def test_never_below_floor_or_above_ceiling(self):
        p = self._policy(patience=1, cooldown=0)
        assert p.decide(0.0, 0.0, 100.0, 1) == 0      # at the floor
        assert p.decide(0.99, 200.0, 100.0, 4) == 0   # at the ceiling

    def test_memory_pressure_caps_and_steps_down(self):
        p = self._policy(patience=1, cooldown=0)
        # pressure forbids up even with a saturated queue...
        assert p.decide(0.99, 0.0, 100.0, 1, under_pressure=True) == 0
        # ...and forces a step down while above the floor
        assert p.decide(0.99, 0.0, 100.0, 3, under_pressure=True) == -1

    def test_flapping_signal_never_acts(self):
        p = self._policy(patience=2, cooldown=0)
        for _ in range(5):
            assert p.decide(0.9, 0.0, 100.0, 2) == 0
            assert p.decide(0.2, 0.0, 100.0, 2) == 0  # streak reset

    def test_deterministic_replay(self):
        seq = [(0.9, 0.0), (0.9, 0.0), (0.0, 90.0), (0.0, 0.0),
               (0.0, 0.0), (0.0, 0.0), (0.9, 0.0)]
        runs = []
        for _ in range(2):
            p = self._policy()
            runs.append([p.decide(q, l, 100.0, 2) for q, l in seq])
        assert runs[0] == runs[1]


# ---------------------------------------------------------------------------
# basics: routing, accounting, lifecycle
# ---------------------------------------------------------------------------

class TestFleetBasics:
    def test_serves_across_replicas_with_exact_identity(self):
        fleet = _fleet(replicas=2)
        try:
            handles = [fleet.submit("svc", r) for r in _rows(24)]
            outs = [h.result(timeout=10.0) for h in handles]
            assert all(o.shape == (DOUT,) for o in outs)
            assert fleet.quiesce(10.0)
            s = fleet.stats("svc")
            assert s["completed"] == 24 and s["replicas"] == 2
            _assert_identity(s)
        finally:
            fleet.stop()

    def test_results_bit_identical_across_replicas(self):
        """Round-robin must be invisible: every replica of one version
        answers bit-identically."""
        fleet = _fleet(replicas=2)
        try:
            row = _rows(1)[0]
            outs = [np.asarray(fleet.submit("svc", row).result(timeout=10.0))
                    for _ in range(4)]
            for o in outs[1:]:
                np.testing.assert_array_equal(o, outs[0])
        finally:
            fleet.stop()

    def test_unknown_service_is_a_keyerror(self):
        fleet = _fleet(replicas=1)
        try:
            with pytest.raises(KeyError, match="unknown service"):
                fleet.submit("nope", _ROW)
            with pytest.raises(ValueError, match="already registered"):
                fleet.add_model("svc", _model())
        finally:
            fleet.stop()

    def test_stop_is_idempotent_and_final(self):
        fleet = _fleet(replicas=1)
        fleet.submit("svc", _ROW).result(timeout=10.0)
        fleet.stop()
        fleet.stop()
        assert not fleet.supervisor.alive()
        with pytest.raises(Overloaded):
            fleet.submit("svc", _ROW)
        _assert_identity(fleet.stats("svc"))

    def test_supervisor_owns_every_fleet_thread(self):
        fleet = _fleet(replicas=1)
        try:
            names = [t.name for t in fleet.supervisor.threads()]
            assert "fleet-supervisor" in names
            assert fleet.supervisor.ticks >= 0
            assert _wait(lambda: fleet.supervisor.ticks > 0, 5.0)
            assert fleet.supervisor.tick_errors == 0
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# zero-downtime hot swap
# ---------------------------------------------------------------------------

class TestHotSwap:
    def test_clean_rollout_under_load_loses_zero_requests(self):
        """THE headline: live traffic flows continuously while the fleet
        swaps versions — no request is lost (shed == quarantined ==
        unaccounted == 0; everything completed or was rejected at the
        door, retriably)."""
        import threading

        fleet = _fleet(replicas=2)
        stop = threading.Event()
        errors = []

        def load():
            rng = np.random.default_rng(1)
            while not stop.is_set():
                try:
                    fleet.submit(
                        "svc", rng.standard_normal((DIN,)).astype(
                            np.float32))
                except Overloaded:
                    pass                      # rejected at the door: not lost
                except Exception as e:        # anything else IS a loss
                    errors.append(e)
                time.sleep(0.003)

        t = threading.Thread(target=load)
        t.start()
        try:
            _wait(lambda: fleet.stats("svc")["completed"] > 5, 10.0)
            report = fleet.rollout("svc", _model(seed=7), parity="bitwise")
            assert report.promoted and not report.rolled_back
            assert report.to_version == "v2"
            # keep serving on the new version, then drain the ledger
            _wait(lambda: fleet.stats("svc")["completed"] > 0, 5.0)
        finally:
            stop.set()
            t.join(timeout=10)
        assert errors == []
        assert fleet.quiesce(15.0)
        s = fleet.stats("svc")
        _assert_identity(s)
        assert s["shed"] == 0 and s["quarantined"] == 0, \
            f"requests lost during a clean rollout: {s}"
        assert s["completed"] > 0 and s["version"] == "v2"
        # swap-to-first-served latency was measured on the new version
        assert _wait(lambda: fleet.stats("svc")["last_swap_to_serve_ms"]
                     is not None, 5.0)
        assert fleet.stats("svc")["last_swap_to_serve_ms"] >= 0.0
        fleet.stop()

    def test_shadow_parity_runs_on_live_traffic(self):
        fleet = _fleet(replicas=1)
        try:
            for r in _rows(10):
                fleet.submit("svc", r).result(timeout=10.0)
            assert fleet.quiesce(10.0)
            report = fleet.rollout("svc", _model(seed=7), parity="bitwise")
            assert report.promoted
            assert report.parity_checked > 0, \
                "shadow traffic must actually mirror live requests"
            assert report.parity_max_abs_diff == 0.0
        finally:
            fleet.stop()

    def test_rollout_with_no_traffic_is_vacuously_clean(self):
        fleet = _fleet(replicas=1)
        try:
            report = fleet.rollout("svc", _model(seed=7), parity="bitwise")
            assert report.promoted and report.parity_checked == 0
            assert any("vacuously" in n for n in report.notes)
        finally:
            fleet.stop()

    def test_sequential_rollouts_bump_versions(self):
        fleet = _fleet(replicas=1)
        try:
            assert fleet.rollout("svc", _model(7), parity="off").promoted
            assert fleet.rollout("svc", _model(8), parity="off").promoted
            assert fleet.stats("svc")["version"] == "v3"
            fleet.submit("svc", _ROW).result(timeout=10.0)
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# gated blue/green: rollback paths
# ---------------------------------------------------------------------------

class TestRollback:
    def test_corrupt_candidate_rolls_back_on_fingerprint(self):
        """``bigdl.chaos.corruptCandidateAt``: weights rot after the
        expected fingerprint is captured — VERIFY refuses, the candidate
        is retired, and the incumbent answers the very next request."""
        config.set_property("bigdl.chaos.corruptCandidateAt", 1)
        chaos.install()
        fleet = _fleet(replicas=1)
        try:
            before = np.asarray(
                fleet.submit("svc", _ROW).result(timeout=10.0))
            report = fleet.rollout("svc", _model(seed=7), parity="bitwise")
            assert report.rolled_back and not report.promoted
            assert "fingerprint" in report.reason
            assert report.fingerprint_observed != \
                report.fingerprint_expected
            assert chaos._state.candidate_corruptions == 1
            after = np.asarray(
                fleet.submit("svc", _ROW).result(timeout=10.0))
            np.testing.assert_array_equal(after, before)
            assert fleet.stats("svc")["version"] == "v1"
        finally:
            fleet.stop()

    def test_divergent_candidate_rolls_back_on_parity(self):
        """Bit-wise shadow parity: a candidate with different weights
        must never survive an infra-swap rollout."""
        fleet = _fleet(replicas=1)
        try:
            for r in _rows(6):
                fleet.submit("svc", r).result(timeout=10.0)
            assert fleet.quiesce(10.0)
            report = fleet.rollout("svc", _model(seed=99), parity="bitwise")
            assert report.rolled_back and "parity" in report.reason
            assert report.parity_max_abs_diff > 0.0
            assert fleet.stats("svc")["version"] == "v1"
            fleet.submit("svc", _ROW).result(timeout=10.0)
        finally:
            fleet.stop()

    def test_allclose_parity_admits_tiny_drift_only(self):
        fleet = _fleet(replicas=1)
        try:
            for r in _rows(6):
                fleet.submit("svc", r).result(timeout=10.0)
            assert fleet.quiesce(10.0)
            # same weights under allclose: promoted
            assert fleet.rollout("svc", _model(seed=7),
                                 parity="allclose").promoted
            # different weights exceed rtol/atol: rolled back
            report = fleet.rollout("svc", _model(seed=99),
                                   parity="allclose")
            assert report.rolled_back and "parity" in report.reason
        finally:
            fleet.stop()

    def test_unknown_parity_mode_is_an_error(self):
        fleet = _fleet(replicas=1)
        try:
            with pytest.raises(ValueError, match="parity mode"):
                fleet.rollout("svc", _model(), parity="vibes")
        finally:
            fleet.stop()

    def test_preemption_mid_rollout_aborts_to_incumbent(self):
        """SIGTERM between rollout phases: the router must never point
        at a half-warmed candidate."""
        fleet = _fleet(replicas=1)
        try:
            elastic.request_preemption("test: mid-rollout SIGTERM")
            report = fleet.rollout("svc", _model(seed=7), parity="off")
            assert report.rolled_back and "preempted" in report.reason
            assert fleet.stats("svc")["version"] == "v1"
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# replica lifecycle supervision
# ---------------------------------------------------------------------------

class TestReplicaSupervision:
    def test_killed_replica_restarts_and_identity_survives(self):
        """``bigdl.chaos.killReplicaAt``: an async hard-kill strands the
        batcher's in-flight batch unaccounted at the ENGINE — the
        supervisor sweep abandons those handles into ``shed`` and
        restarts the slot, and the FLEET identity stays exact."""
        config.set_property("bigdl.chaos.killReplicaAt", "10:0")
        chaos.install()
        fleet = _fleet(replicas=2)
        try:
            for r in _rows(40):
                try:
                    fleet.submit("svc", r)
                except Overloaded:
                    pass
                time.sleep(0.005)
            assert chaos._state.replica_kills == 1
            assert _wait(lambda: fleet.stats("svc")["restarts"] >= 1, 10.0)
            assert fleet.quiesce(15.0)
            s = fleet.stats("svc")
            _assert_identity(s)
            assert s["replicas"] == 2, "the killed slot must be replaced"
            # the restarted fleet still serves
            fleet.submit("svc", _ROW).result(timeout=10.0)
        finally:
            fleet.stop()
        _assert_identity(fleet.stats("svc"))

    def test_restart_budget_exhausted_abandons_slot(self):
        config.set_property("bigdl.fleet.maxReplicaRestarts", 0)
        fleet = _fleet(replicas=2)
        try:
            svc = fleet._services["svc"]
            assert svc.kill_replica(0)
            assert _wait(lambda: fleet.stats("svc")["replicas"] == 1, 10.0)
            assert _wait(
                lambda: not any(r.crashed() for r in
                                svc.active_replicas()), 5.0)
            # N-1 replicas, still serving, identity intact
            fleet.submit("svc", _ROW).result(timeout=10.0)
            assert fleet.quiesce(10.0)
            _assert_identity(fleet.stats("svc"))
            assert fleet.stats("svc")["restarts"] == 0
        finally:
            fleet.stop()

    def test_autoscale_wiring_adds_and_retires_replicas(self):
        """The supervisor's autoscale tick translates policy decisions
        into replica lifecycle (the policy itself is unit-tested above;
        here it is forced, so the test is deterministic)."""
        config.set_property("bigdl.fleet.autoscale.enabled", True)
        config.set_property("bigdl.fleet.autoscale.intervalSec", 0.02)
        fleet = _fleet(replicas=1)
        try:
            svc = fleet._services["svc"]
            svc._policy.decide = lambda *a, **k: 1
            assert _wait(lambda: fleet.stats("svc")["replicas"] == 2, 10.0)
            svc._policy.decide = lambda *a, **k: -1
            assert _wait(lambda: fleet.stats("svc")["replicas"] == 1, 10.0)
            fleet.submit("svc", _ROW).result(timeout=10.0)
            assert fleet.quiesce(10.0)
            _assert_identity(fleet.stats("svc"))
        finally:
            fleet.stop()

    def test_fleet_sigterm_drains_with_exact_accounting(self):
        config.set_property("bigdl.chaos.sigtermFleetAt", 5)
        chaos.install()
        fleet = _fleet(replicas=1)
        try:
            rejected = 0
            for r in _rows(30):
                try:
                    fleet.submit("svc", r)
                except Overloaded:
                    rejected += 1
                time.sleep(0.01)
            assert chaos._state.fleet_sigterms == 1
            assert elastic.preemption_requested()
            assert rejected > 0, "late arrivals must reject retriably"
            assert fleet.quiesce(15.0)
            s = fleet.stats("svc")
            _assert_identity(s)
            assert s["completed"] > 0
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# checkpoint-to-serving promotion
# ---------------------------------------------------------------------------

class TestPromotion:
    def _save(self, tmp_path, seed=7, n=1):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_model(seed), optim.SGD(learning_rate=0.1), n)
        return mgr

    def test_new_snapshot_promotes_as_one_verified_step(self, tmp_path):
        config.set_property("bigdl.fleet.promotionPollSec", 0.05)
        fleet = _fleet(replicas=1)
        try:
            for r in _rows(4):
                fleet.submit("svc", r).result(timeout=10.0)
            fleet.watch("svc", str(tmp_path))
            self._save(tmp_path, seed=7, n=3)
            # wait on last_promotion, not version: the version flips at
            # cutover, a beat before promotion_tick records the report
            assert _wait(
                lambda: fleet._services["svc"].last_promotion is not None,
                15.0), fleet.stats("svc")
            rep = fleet._services["svc"].last_promotion
            assert rep.promoted
            assert fleet.stats("svc")["version"] == "v2"
            fleet.submit("svc", _ROW).result(timeout=10.0)
            assert fleet.quiesce(10.0)
            _assert_identity(fleet.stats("svc"))
            # the same snapshot is never promoted twice
            time.sleep(0.5)
            assert fleet.stats("svc")["version"] == "v2"
        finally:
            fleet.stop()

    def test_corrupt_snapshot_never_reaches_serving(self, tmp_path):
        """A bitflipped payload passes the cheap watch poll but fails
        deep verification at load — promotion is refused ONCE (no retry
        loop) and the incumbent keeps serving."""
        config.set_property("bigdl.fleet.promotionPollSec", 0.05)
        fleet = _fleet(replicas=1)
        try:
            fleet.watch("svc", str(tmp_path))
            self._save(tmp_path, seed=9, n=1)
            path = tmp_path / "model.1"
            blob = bytearray(path.read_bytes())
            blob[len(blob) // 2] ^= 0xFF
            path.write_bytes(bytes(blob))
            svc = fleet._services["svc"]
            assert _wait(lambda: svc._promo_attempted == 1, 15.0)
            time.sleep(0.3)
            assert fleet.stats("svc")["version"] == "v1"
            assert svc.last_promotion is None
            fleet.submit("svc", _ROW).result(timeout=10.0)
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# the combined-chaos acceptance plan (ISSUE 17 acceptance criterion)
# ---------------------------------------------------------------------------

class TestCombinedChaosPlan:
    def test_kill_plus_corrupt_plus_sigterm_exact_accounting(self):
        """One plan arms all three fleet injectors: a replica hard-kill
        mid-traffic, a corrupted candidate during the rollout (rollback
        observed while the incumbent serves), then a fleet-wide SIGTERM.
        The fleet accounting identity must hold EXACTLY across all of
        it, and every chaos counter must show its fault actually
        fired."""
        config.set_property("bigdl.chaos.killReplicaAt", "8:0")
        config.set_property("bigdl.chaos.corruptCandidateAt", 1)
        config.set_property("bigdl.chaos.sigtermFleetAt", 60)
        chaos.install()
        fleet = _fleet(replicas=2)
        try:
            # phase A: traffic; the kill fires at fleet submit #8
            for r in _rows(24, seed=1):
                try:
                    fleet.submit("svc", r)
                except Overloaded:
                    pass
                time.sleep(0.005)
            assert chaos._state.replica_kills == 1
            assert _wait(lambda: fleet.stats("svc")["restarts"] >= 1, 10.0)

            # phase B: rollout mid-plan; the candidate corrupts after
            # fingerprint capture -> rollback, incumbent still serving
            report = fleet.rollout("svc", _model(seed=7), parity="bitwise")
            assert report.rolled_back and "fingerprint" in report.reason
            assert chaos._state.candidate_corruptions == 1
            fleet.submit("svc", _ROW).result(timeout=10.0)
            assert fleet.stats("svc")["version"] == "v1"

            # phase C: keep submitting until the fleet-wide SIGTERM at
            # submit #60 flips everything to draining
            rejected_late = 0
            for r in _rows(60, seed=2):
                try:
                    fleet.submit("svc", r)
                except Overloaded:
                    rejected_late += 1
                time.sleep(0.004)
            assert chaos._state.fleet_sigterms == 1
            assert elastic.preemption_requested()
            assert rejected_late > 0

            # the ledger closes exactly across every fault
            assert fleet.quiesce(20.0)
            s = fleet.stats("svc")
            _assert_identity(s)
            agg = fleet.stats()["fleet"]
            assert agg["unaccounted"] == 0
            assert sum(agg[o] for o in OUTCOMES) == agg["submitted"]
            assert s["completed"] > 0 and s["rejected"] > 0
        finally:
            fleet.stop()
        _assert_identity(fleet.stats("svc"))


# ---------------------------------------------------------------------------
# lint rule: unsupervised-thread-in-fleet
# ---------------------------------------------------------------------------

class TestFleetThreadLint:
    def _lint(self, tmp_path, code, name="fleet/thing.py"):
        from bigdl_tpu.analysis.lint import lint_paths
        path = tmp_path / name
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(code)
        return [f.rule for f in lint_paths([str(tmp_path)])]

    def test_flags_raw_thread_in_fleet(self, tmp_path):
        rules = self._lint(tmp_path, (
            "import threading\n"
            "t = threading.Thread(target=print)\n"
            "from threading import Thread\n"
            "u = Thread(target=print)\n"))
        assert rules.count("unsupervised-thread-in-fleet") == 2

    def test_outside_fleet_is_exempt(self, tmp_path):
        rules = self._lint(tmp_path, (
            "import threading\n"
            "t = threading.Thread(target=print)\n"),
            name="serving/thing.py")
        assert "unsupervised-thread-in-fleet" not in rules

    def test_inline_allow_silences(self, tmp_path):
        rules = self._lint(tmp_path, (
            "import threading\n"
            "t = threading.Thread(  "
            "# lint: allow(unsupervised-thread-in-fleet)\n"
            "    target=print)\n"))
        assert "unsupervised-thread-in-fleet" not in rules

    def test_shipped_fleet_package_is_clean(self):
        from bigdl_tpu.analysis.lint import lint_paths
        findings = lint_paths([os.path.join(_REPO, "bigdl_tpu", "fleet")])
        assert findings == [], "\n".join(str(f) for f in findings)


# ---------------------------------------------------------------------------
# docs drift guard: bigdl.fleet.* keys
# ---------------------------------------------------------------------------

class TestFleetDocDrift:
    """Every ``bigdl.fleet.*`` key the code registers must have a row in
    docs/configuration.md — and vice versa (same guard as the chaos and
    ingest key families)."""

    _KEY = re.compile(r"bigdl\.fleet\.[A-Za-z0-9]+(?:\.[A-Za-z0-9]+)*")

    def _keys_in(self, *parts):
        with open(os.path.join(_REPO, *parts), encoding="utf-8") as f:
            return set(self._KEY.findall(f.read()))

    def test_config_defaults_match_docs_both_ways(self):
        code = self._keys_in("bigdl_tpu", "utils", "config.py")
        docs = self._keys_in("docs", "configuration.md")
        assert code - docs == set(), \
            f"fleet keys missing a docs row: {sorted(code - docs)}"
        assert docs - code == set(), \
            f"documented fleet keys unknown to config.py: " \
            f"{sorted(docs - code)}"

    def test_fleet_package_reads_registered_keys_only(self):
        registered = self._keys_in("bigdl_tpu", "utils", "config.py")
        pkg = os.path.join(_REPO, "bigdl_tpu", "fleet")
        used = set()
        for fn in os.listdir(pkg):
            if fn.endswith(".py"):
                used |= self._keys_in("bigdl_tpu", "fleet", fn)
        assert used - registered == set(), \
            f"fleet package reads unregistered keys: " \
            f"{sorted(used - registered)}"
