"""Module-system semantics: forward/backward shell over the pure core.

Test strategy follows the reference's pure-Scala layer specs (SURVEY §4.2)
plus gradient checks against numerical differentiation (the role Torch7
golden files play in the reference, §4.1, with jax.grad as the oracle).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn


def rand(*shape):
    return jnp.asarray(np.random.randn(*shape).astype(np.float32))


class TestShell:
    def test_forward_caches_output(self):
        m = nn.Linear(4, 3)
        x = rand(2, 4)
        out = m.forward(x)
        assert out.shape == (2, 3)
        assert m.output is out

    def test_backward_matches_grad(self):
        """Shell backward == jax.grad of the pure core."""
        m = nn.Linear(4, 3)
        x = rand(2, 4)
        out = m.forward(x)
        g = jnp.ones_like(out)
        gin = m.backward(x, g)

        def f(p, xx):
            y, _ = m.apply(p, xx, {}, training=True)
            return jnp.sum(y)

        exp_p = jax.grad(f, argnums=0)(m.params, x)
        exp_x = jax.grad(f, argnums=1)(m.params, x)
        np.testing.assert_allclose(np.asarray(gin), np.asarray(exp_x), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(m.grads["weight"]),
                                   np.asarray(exp_p["weight"]), rtol=1e-5)

    def test_acc_grad_accumulates(self):
        m = nn.Linear(4, 3)
        x = rand(2, 4)
        m.forward(x)
        g = jnp.ones((2, 3))
        m.backward(x, g)
        first = np.asarray(m.grads["weight"]).copy()
        m.backward(x, g)
        np.testing.assert_allclose(np.asarray(m.grads["weight"]), 2 * first,
                                   rtol=1e-5)
        m.zero_grad_parameters()
        assert float(jnp.abs(m.grads["weight"]).sum()) == 0.0

    def test_update_parameters_sgd_step(self):
        m = nn.Linear(4, 3)
        x = rand(8, 4)
        w0 = np.asarray(m.params["weight"]).copy()
        m.forward(x)
        m.backward(x, jnp.ones((8, 3)))
        m.update_parameters(0.1)
        w1 = np.asarray(m.params["weight"])
        assert not np.allclose(w0, w1)
        np.testing.assert_allclose(
            w1, w0 - 0.1 * np.asarray(m.grads["weight"]), rtol=1e-5)

    def test_get_set_flat_parameters_roundtrip(self):
        m = nn.Sequential().add(nn.Linear(4, 5)).add(nn.Tanh()).add(nn.Linear(5, 2))
        w, g = m.get_parameters()
        assert w.shape == (4 * 5 + 5 + 5 * 2 + 2,)
        m.set_flat_parameters(jnp.zeros_like(w))
        w2, _ = m.get_parameters()
        assert float(jnp.abs(w2).sum()) == 0.0

    def test_clone_module_independent(self):
        m = nn.Linear(3, 3)
        m.forward(rand(1, 3))
        c = m.clone_module()
        np.testing.assert_allclose(np.asarray(c.params["weight"]),
                                   np.asarray(m.params["weight"]))
        c.params = {"weight": jnp.zeros((3, 3)), "bias": c.params["bias"]}
        assert float(jnp.abs(m.params["weight"]).sum()) > 0

    def test_training_evaluate_mode(self):
        m = nn.Sequential().add(nn.Dropout(0.5)).add(nn.Linear(4, 2))
        m.evaluate()
        assert not m.train_mode and not m[0].train_mode
        m.training()
        assert m.train_mode and m[0].train_mode

    def test_get_parameters_table(self):
        m = nn.Sequential().add(nn.Linear(4, 5, name="fc1")).add(nn.Tanh())
        table = m.get_parameters_table()
        assert "fc1" in table and "weight" in table["fc1"]


class TestContainers:
    def test_sequential_compose(self):
        m = nn.Sequential().add(nn.Linear(4, 8)).add(nn.ReLU()).add(nn.Linear(8, 2))
        out = m.forward(rand(3, 4))
        assert out.shape == (3, 2)

    def test_concat(self):
        m = nn.Concat(2)
        m.add(nn.Linear(4, 3))
        m.add(nn.Linear(4, 5))
        out = m.forward(rand(2, 4))
        assert out.shape == (2, 8)

    def test_concat_table_and_cadd(self):
        branches = nn.ConcatTable()
        branches.add(nn.Linear(4, 3))
        branches.add(nn.Linear(4, 3))
        m = nn.Sequential().add(branches).add(nn.CAddTable())
        out = m.forward(rand(2, 4))
        assert out.shape == (2, 3)

    def test_parallel_table(self):
        m = nn.ParallelTable()
        m.add(nn.Linear(4, 3))
        m.add(nn.Linear(5, 3))
        out = m.forward([rand(2, 4), rand(2, 5)])
        assert out[0].shape == (2, 3) and out[1].shape == (2, 3)

    def test_backward_through_container_with_table(self):
        branches = nn.ConcatTable()
        branches.add(nn.Linear(4, 3))
        branches.add(nn.Identity())
        m = nn.Sequential().add(branches).add(nn.JoinTable(2))
        x = rand(2, 4)
        out = m.forward(x)
        assert out.shape == (2, 7)
        gin = m.backward(x, jnp.ones_like(out))
        assert gin.shape == x.shape

    def test_modules_traversal(self):
        inner = nn.Sequential().add(nn.Linear(2, 2))
        m = nn.Sequential().add(inner).add(nn.ReLU())
        assert len(m.modules()) == 4  # m, inner, linear, relu


class TestGraph:
    def test_linear_graph(self):
        fc1 = nn.Linear(4, 8).inputs()
        relu = nn.ReLU().inputs(fc1)
        fc2 = nn.Linear(8, 2).inputs(relu)
        g = nn.Graph(fc1, fc2)
        out = g.forward(rand(3, 4))
        assert out.shape == (3, 2)

    def test_diamond_graph_fanout_gradients(self):
        inp = nn.Identity().inputs()
        a = nn.Linear(4, 4).inputs(inp)
        b = nn.Linear(4, 4).inputs(inp)
        add = nn.CAddTable().inputs(a, b)
        g = nn.Graph(inp, add)
        x = rand(2, 4)
        out = g.forward(x)
        assert out.shape == (2, 4)
        gin = g.backward(x, jnp.ones_like(out))
        # gradient fans in from both branches
        wa = g.executions  # smoke: topo order computed
        assert gin.shape == x.shape

    def test_input_factory_node(self):
        """nn.Input() is the reference's placeholder source node."""
        inp = nn.Input()
        out = nn.Linear(4, 2).inputs(inp)
        g = nn.Graph(inp, out)
        assert g.forward(rand(3, 4)).shape == (3, 2)

    def test_multi_output_graph(self):
        inp = nn.Identity().inputs()
        a = nn.Linear(4, 3).inputs(inp)
        b = nn.Linear(4, 5).inputs(inp)
        g = nn.Graph(inp, [a, b])
        out = g.forward(rand(2, 4))
        assert out[0].shape == (2, 3) and out[1].shape == (2, 5)


def test_add_after_init_extends_params():
    """Torch allows Container.add at any time; adding to an
    already-initialized Sequential must extend the params/state lists
    (a stale shorter list IndexErrors at the next apply — hit by the
    model-zoo pattern `model_init(resnet(...)).add(LogSoftMax())`)."""
    import jax
    m = nn.Sequential().add(nn.Linear(4, 8)).add(nn.ReLU())
    m.reset(jax.random.PRNGKey(0))
    x = np.random.RandomState(0).normal(size=(2, 4)).astype(np.float32)
    mid = np.asarray(m.forward(x))
    m.add(nn.Linear(8, 3)).add(nn.LogSoftMax())
    out = np.asarray(m.forward(x))
    assert out.shape == (2, 3)
    np.testing.assert_allclose(np.exp(out).sum(-1), 1.0, rtol=1e-5)
    assert len(m.params) == 4 and len(m.state) == 4
    # the earlier children kept their pre-add weights: pushing the
    # pre-add activations through ONLY the new children reproduces the
    # full forward exactly
    want = np.asarray(m.children[3].forward(
        np.asarray(m.children[2].forward(mid))))
    np.testing.assert_allclose(out, want, rtol=1e-6)
