"""Transformer LM family tests (beyond-reference; 8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import SampleToMiniBatch
from bigdl_tpu.dataset.dataset import LocalDataSet, ShardedDataSet
from bigdl_tpu.engine import Engine
from bigdl_tpu.models.transformer import (LayerNorm, PositionalEncoding,
                                          PositionOutOfRange,
                                          transformer_lm,
                                          transformer_lm_pipeline)
from bigdl_tpu.models.transformer.train import VOCAB, _synthetic
from bigdl_tpu.parallel import DistriOptimizer


class TestTransformerLM:
    def test_forward_shapes_and_logprobs(self):
        m = transformer_lm(VOCAB, d_model=32, n_head=2, n_layers=2)
        m.reset(jax.random.PRNGKey(0))
        x = np.random.RandomState(0).randint(
            1, VOCAB + 1, size=(2, 16)).astype(np.float32)
        out = np.asarray(m.forward(x))
        assert out.shape == (2, 16, VOCAB)
        np.testing.assert_allclose(np.exp(out).sum(-1), 1.0, rtol=1e-4)

    def test_causality(self):
        """Changing a future token must not change earlier predictions."""
        m = transformer_lm(VOCAB, d_model=32, n_head=2, n_layers=2)
        m.reset(jax.random.PRNGKey(1))
        rng = np.random.RandomState(1)
        x = rng.randint(1, VOCAB + 1, size=(1, 12)).astype(np.float32)
        x2 = x.copy()
        x2[0, -1] = x2[0, -1] % VOCAB + 1      # perturb the last token
        a = np.asarray(m.forward(x))
        b = np.asarray(m.forward(x2))
        np.testing.assert_allclose(a[0, :-1], b[0, :-1], atol=1e-5)
        assert not np.allclose(a[0, -1], b[0, -1])

    @pytest.mark.parametrize("policy", [True, "dots", "save_attn"])
    def test_remat_matches_nonremat_bitwise(self, policy):
        """Activation checkpointing is a memory schedule, not a numerics
        change: the loss must match the non-remat model bit-for-bit (the
        forward is the identical program).  Gradients match to float32
        reassociation tolerance — XLA fuses the rematerialized forward
        differently inside the VJP, reordering accumulations."""
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)
        base = transformer_lm(VOCAB, d_model=32, n_head=2, n_layers=3)
        base.reset(jax.random.PRNGKey(7))
        rem = transformer_lm(VOCAB, d_model=32, n_head=2, n_layers=3,
                             remat=policy)
        rem.reset(jax.random.PRNGKey(8))
        # transplant base params into the remat structure (each wrapped
        # block's params gain one list level)
        rem.params = [[p] if isinstance(c, nn.Remat) else p
                      for c, p in zip(rem.children, base.params)]
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randint(1, VOCAB + 1, (2, 16)), jnp.float32)
        y = jnp.asarray(rng.randint(1, VOCAB + 1, (2, 16)), jnp.float32)

        def loss_of(model):
            def f(p):
                out, _ = model.apply(p, x, model.state, training=True)
                return crit.apply(out, y)
            return jax.jit(jax.value_and_grad(f))

        loss_b, grads_b = loss_of(base)(base.params)
        loss_r, grads_r = loss_of(rem)(rem.params)
        assert float(loss_b) == float(loss_r)
        # unwrap the remat nesting level before leaf comparison
        grads_r = [g[0] if isinstance(c, nn.Remat) else g
                   for c, g in zip(rem.children, grads_r)]
        for a, b in zip(jax.tree_util.tree_leaves(grads_b),
                        jax.tree_util.tree_leaves(grads_r)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-8)

    def test_remat_config_preset_applies(self):
        """bigdl.remat.policy wraps blocks when the builder argument is
        left alone; an explicit argument wins over the preset; off/none
        keep remat off; a preset typo fails at construction."""
        from bigdl_tpu.utils import config
        try:
            config.set_property("bigdl.remat.policy", "dots")
            m = transformer_lm(VOCAB, d_model=16, n_head=2, n_layers=2)
            assert all(isinstance(c, nn.Remat) for c in m.children[2:4])
            # explicit remat=None beats the preset
            m2 = transformer_lm(VOCAB, d_model=16, n_head=2, n_layers=2,
                                remat=None)
            assert not any(isinstance(c, nn.Remat) for c in m2.children)
            config.set_property("bigdl.remat.policy", "off")
            m3 = transformer_lm(VOCAB, d_model=16, n_head=2, n_layers=2)
            assert not any(isinstance(c, nn.Remat) for c in m3.children)
            config.set_property("bigdl.remat.policy", "save_attn")
            e, b, h = transformer_lm_pipeline(VOCAB, d_model=16, n_head=2,
                                              n_layers=2)
            assert all(isinstance(x, nn.Remat) for x in b)
            config.set_property("bigdl.remat.policy", "everything")
            with pytest.raises(ValueError, match="remat policy"):
                transformer_lm(VOCAB, d_model=16, n_head=2,
                               n_layers=1).forward(
                    np.ones((1, 4), np.float32))
        finally:
            config.clear_property("bigdl.remat.policy")

    def test_remat_preset_numerics_match(self):
        """A preset-wrapped model's forward is the identical program."""
        from bigdl_tpu.utils import config
        base = transformer_lm(VOCAB, d_model=16, n_head=2, n_layers=2)
        base.reset(jax.random.PRNGKey(5))
        try:
            config.set_property("bigdl.remat.policy", "nothing")
            rem = transformer_lm(VOCAB, d_model=16, n_head=2, n_layers=2)
        finally:
            config.clear_property("bigdl.remat.policy")
        rem.params = [[p] if isinstance(c, nn.Remat) else p
                      for c, p in zip(rem.children, base.params)]
        x = np.random.RandomState(4).randint(
            1, VOCAB + 1, (2, 8)).astype(np.float32)
        np.testing.assert_array_equal(np.asarray(rem.forward(x)),
                                      np.asarray(base.forward(x)))

    def test_remat_rejects_unknown_policy(self):
        with pytest.raises(ValueError, match="remat policy"):
            nn.Remat(nn.Linear(4, 4), policy="everything").forward(
                np.zeros((1, 4), np.float32))

    def test_remat_rejects_second_child(self):
        """Remat computes through exactly one child; a second add() must
        fail at the add, not as a far-away state-length IndexError."""
        with pytest.raises(ValueError, match="exactly one"):
            nn.Remat(nn.Linear(4, 4)).add(nn.ReLU())

    def test_layernorm_normalizes(self):
        ln = LayerNorm(8)
        ln._ensure_init()
        x = np.random.RandomState(2).normal(5, 3, (4, 8)).astype(np.float32)
        out = np.asarray(ln.forward(x))
        np.testing.assert_allclose(out.mean(-1), 0.0, atol=1e-5)
        np.testing.assert_allclose(out.std(-1), 1.0, atol=1e-2)

    def test_positional_encoding_offsets_under_seq_axis(self):
        """Each seq shard must add ITS chunk of the position table."""
        from bigdl_tpu.parallel.all_reduce import shard_map
        from jax.sharding import PartitionSpec as P
        mesh = Engine.create_mesh((4,), ("seq",),
                                  devices=jax.devices()[:4])
        pe = PositionalEncoding(8).set_sequence_parallel("seq")
        pe._ensure_init()
        x = jnp.zeros((1, 16, 8))

        def fn(xs):
            out, _ = pe.apply({}, xs, {})
            return out

        sharded = shard_map(fn, mesh=mesh, in_specs=P(None, "seq"),
                            out_specs=P(None, "seq"), check_rep=False)
        got = np.asarray(jax.jit(sharded)(x))
        want = np.asarray(pe.forward(x))       # unsharded reference
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_sp_training_matches_local(self):
        """dp x sp transformer training == full-sequence local training."""
        samples = _synthetic(16, 16, seed=5)
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)

        def run(distributed):
            m = transformer_lm(VOCAB, d_model=32, n_head=2, n_layers=1)
            m.reset(jax.random.PRNGKey(3))
            if distributed:
                mesh = Engine.create_mesh((4, 2), ("data", "seq"))
                ds = ShardedDataSet(samples, 4).transform(
                    SampleToMiniBatch(16, 4))
                opt = DistriOptimizer(m, ds, crit, mesh=mesh)
            else:
                ds = LocalDataSet(samples).transform(SampleToMiniBatch(16))
                opt = optim.Optimizer.create(m, ds, crit)
            opt.set_optim_method(optim.SGD(learning_rate=0.1))
            opt.set_end_when(optim.max_iteration(4))
            trained = opt.optimize()
            w, _ = trained.get_parameters()
            return np.asarray(w)

        w_local = run(False)
        w_sp = run(True)
        np.testing.assert_allclose(w_sp, w_local, rtol=5e-4, atol=5e-5)

    @pytest.mark.slow
    def test_driver_learns_synthetic_pattern(self, capsys):
        from bigdl_tpu.models.transformer import train as drv
        drv.main(["--synthetic", "64", "--seq-len", "16", "--max-epoch", "8",
                  "--batch-size", "16"])
        out = capsys.readouterr().out
        acc = float(out.strip().rsplit(" ", 1)[-1])
        assert acc > 0.5, out

    def _drive(self, capsys, extra):
        from bigdl_tpu.models.transformer import train as drv
        drv.main(["--synthetic", "48", "--seq-len", "8", "--max-epoch", "2",
                  "--batch-size", "16", "--d-model", "16", "--heads", "2"]
                 + extra)
        out = capsys.readouterr().out
        return float(out.strip().rsplit(" ", 1)[-1])

    def test_driver_tensor_parallel_flag(self, capsys):
        acc = self._drive(capsys, ["--partitions", "4",
                                   "--tensor-parallel", "2"])
        assert 0.0 <= acc <= 1.0

    def test_driver_remat_flag_composes_with_tp(self, capsys):
        """--remat dots trains through the GSPMD tp step: tp_specs must
        see through the Remat container and the checkpointed VJP must
        compose with the sharded collectives."""
        acc = self._drive(capsys, ["--partitions", "2",
                                   "--tensor-parallel", "2",
                                   "--remat", "dots"])
        assert 0.0 <= acc <= 1.0

    def test_driver_remat_flag_composes_with_pipeline(self, capsys):
        acc = self._drive(capsys, ["--pipeline", "2", "--remat", "full"])
        assert 0.0 <= acc <= 1.0

    def test_driver_pipeline_composes_with_tensor_parallel(self, capsys):
        """--pipeline --tensor-parallel together build the 3-D
        ('data','stage','model') mesh: Megatron-split stages inside the
        GPipe schedule, trained through the public driver."""
        acc = self._drive(capsys, ["--pipeline", "2", "--partitions", "2",
                                   "--tensor-parallel", "2"])
        assert 0.0 <= acc <= 1.0

    @pytest.mark.slow
    def test_driver_expert_parallel_flag(self, capsys):
        acc = self._drive(capsys, ["--moe-experts", "4", "--partitions", "2",
                                   "--expert-parallel", "4"])
        assert 0.0 <= acc <= 1.0

    @pytest.mark.slow
    def test_driver_pipeline_flag(self, capsys):
        acc = self._drive(capsys, ["--pipeline", "2", "--partitions", "2"])
        assert 0.0 <= acc <= 1.0

    def test_driver_moe_top_k_flag(self, capsys):
        """--moe-top-k 2 builds the GShard configuration end-to-end (every
        MoE layer routes top-2) through the dp x ep mesh."""
        from bigdl_tpu.models.transformer import train as drv
        trained = drv.main(["--synthetic", "48", "--seq-len", "8",
                            "--max-epoch", "2", "--batch-size", "16",
                            "--d-model", "16", "--heads", "2",
                            "--moe-experts", "4", "--moe-top-k", "2",
                            "--partitions", "2", "--expert-parallel", "4"])
        capsys.readouterr()
        from bigdl_tpu.nn.moe import MixtureOfExperts
        moes = trained.find_modules(MixtureOfExperts)
        assert moes and all(m.top_k == 2 for m in moes)

    def test_driver_rejects_mode_combo_and_missing_moe(self):
        from bigdl_tpu.models.transformer import train as drv
        # pipeline composes with tensor-parallel ONLY; other combos reject
        with pytest.raises(SystemExit, match="one parallelism"):
            drv.main(["--synthetic", "8", "--pipeline", "2",
                      "--seq-parallel", "2"])
        with pytest.raises(SystemExit, match="one parallelism"):
            drv.main(["--synthetic", "8", "--tensor-parallel", "2",
                      "--expert-parallel", "2", "--moe-experts", "2"])
        with pytest.raises(SystemExit, match="moe-experts"):
            drv.main(["--synthetic", "8", "--expert-parallel", "2"])
        with pytest.raises(SystemExit, match="moe-experts"):
            drv.main(["--synthetic", "8", "--moe-top-k", "2"])


def test_odd_d_model_positional_encoding():
    pe = PositionalEncoding(7, max_len=16)
    pe._ensure_init()
    out = np.asarray(pe.forward(np.zeros((1, 5, 7), np.float32)))
    assert out.shape == (1, 5, 7) and np.isfinite(out).all()


class TestPositionalEncodingOffset:
    """The decode path's position-offset contract: ``apply(offset=k)``
    reads table rows ``k .. k+T``, out-of-range STATIC positions raise
    the structured :class:`PositionOutOfRange` (dynamic_slice would
    silently clamp — wrong position signal with no symptom), and
    ``rows()`` is the per-slot decode lookup."""

    def test_offset_reads_shifted_table_rows(self):
        pe = PositionalEncoding(8, max_len=16)
        pe._ensure_init()
        x = np.zeros((1, 4, 8), np.float32)
        full, _ = pe.apply(pe.params, np.zeros((1, 16, 8), np.float32),
                           None)
        shifted, _ = pe.apply(pe.params, x, None, offset=5)
        np.testing.assert_array_equal(np.asarray(shifted)[0],
                                      np.asarray(full)[0, 5:9])

    def test_offset_past_capacity_raises_structured(self):
        pe = PositionalEncoding(8, max_len=16)
        pe._ensure_init()
        x = np.zeros((1, 4, 8), np.float32)
        with pytest.raises(PositionOutOfRange) as ei:
            pe.apply(pe.params, x, None, offset=13)   # rows 13..16
        assert ei.value.position == 16 and ei.value.max_len == 16
        assert "max_len 16" in str(ei.value)

    def test_sequence_past_capacity_raises_even_at_offset_zero(self):
        pe = PositionalEncoding(8, max_len=8)
        pe._ensure_init()
        with pytest.raises(PositionOutOfRange):
            pe.apply(pe.params, np.zeros((1, 9, 8), np.float32), None)

    def test_rows_lookup_matches_table_and_range_checks(self):
        pe = PositionalEncoding(8, max_len=16)
        pe._ensure_init()
        got = np.asarray(pe.rows(np.array([0, 7, 15])))
        np.testing.assert_array_equal(got, np.asarray(pe.pe)[[0, 7, 15]])
        with pytest.raises(PositionOutOfRange) as ei:
            pe.rows([3, 16])
        assert ei.value.position == 16 and ei.value.max_len == 16


def test_sp_rejects_sequence_beyond_position_capacity():
    from bigdl_tpu.dataset import Sample
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                       size_average=True)
    m = transformer_lm(VOCAB, d_model=16, n_head=2, n_layers=1, max_len=8)
    m.reset(jax.random.PRNGKey(4))
    rng = np.random.RandomState(0)
    # global T=16 > max_len=8: sharded offsets would clamp silently
    samples = [Sample(rng.randint(1, VOCAB + 1, 16).astype(np.float32),
                      np.ones(16, np.float32)) for _ in range(8)]
    mesh = Engine.create_mesh((4, 2), ("data", "seq"))
    ds = ShardedDataSet(samples, 4).transform(SampleToMiniBatch(8, 4))
    opt = DistriOptimizer(m, ds, crit, mesh=mesh)
    opt.set_optim_method(optim.SGD(learning_rate=0.1))
    opt.set_end_when(optim.max_iteration(1))
    with pytest.raises(ValueError, match="position capacity"):
        opt.optimize()


def test_residual_children_adopted():
    """Sublayers inside residual blocks must view the container's params —
    the TrainSummary 'Parameters' walk and direct sublayer.forward() would
    otherwise see freshly-reset random weights."""
    m = transformer_lm(VOCAB, d_model=16, n_head=2, n_layers=1)
    m.reset(jax.random.PRNGKey(6))
    mha = m.find_modules(nn.MultiHeadAttention)[0]
    # the adopted view must BE the container's array, not a new init
    leaves = {id(l) for l in jax.tree_util.tree_leaves(m.params)}
    assert id(mha.params["wq"]) in leaves


def test_tp_tagged_transformer_forward_parity():
    """tp=True transformer: TP-sharded forward == replicated forward."""
    from bigdl_tpu.parallel.tensor_parallel import tp_shard_params, tp_specs
    mesh = Engine.create_mesh((8,), ("model",))
    m = transformer_lm(VOCAB, d_model=16, n_head=8, n_layers=1, tp=True)
    m.reset(jax.random.PRNGKey(8))
    x = np.random.RandomState(7).randint(
        1, VOCAB + 1, size=(2, 8)).astype(np.float32)
    want = np.asarray(m.forward(x))
    specs = tp_specs(m, mesh=mesh)
    params = tp_shard_params(m.params, mesh, specs)
    # at least one weight must be PHYSICALLY split over the model axis —
    # a regression to all-replicated params would still pass the parity
    # check below
    split = [l for l in jax.tree_util.tree_leaves(params)
             if l.ndim == 2 and any(s.data.shape != l.shape
                                    for s in l.addressable_shards)]
    assert split, "no tensor-parallel weight is actually sharded"
    got = np.asarray(jax.jit(
        lambda p: m.apply(p, jnp.asarray(x), m.state, training=False)[0]
    )(params))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_moe_transformer_block_trains():
    """moe_experts=E block: Switch FFN inside the residual, loss decreases."""
    from bigdl_tpu.models.transformer import transformer_block
    blk = transformer_block(16, 2, moe_experts=4)
    blk.reset(jax.random.PRNGKey(9))
    rng = np.random.RandomState(9)
    x = jnp.asarray(rng.normal(size=(4, 8, 16)).astype(np.float32))
    w_true = rng.normal(size=(16, 16)).astype(np.float32) * 0.3
    y = x @ jnp.asarray(w_true)

    @jax.jit
    def step(p):
        def loss_fn(pp):
            out, _ = blk.apply(pp, x, blk.state, training=False)
            return jnp.mean((out - y) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(p)
        return jax.tree_util.tree_map(lambda w, gw: w - 0.2 * gw, p, g), loss

    params = blk.params
    losses = []
    for _ in range(25):
        params, loss = step(params)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses
    with pytest.raises(ValueError, match="pick one"):
        transformer_block(16, 2, tp=True, moe_experts=4)
