"""DistriOptimizer tests on the virtual 8-device CPU mesh.

Reference analogs: ``optim/DistriOptimizerSpec`` (convergence on separable
data, 4 simulated nodes in one JVM) and ``optim/RefDistriOptimizerSpec``
(agreement with a deliberately naive single-process oracle — here the
LocalOptimizer plays the oracle).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.engine import Engine
from bigdl_tpu.dataset import Sample, SampleToMiniBatch
from bigdl_tpu.dataset.dataset import LocalDataSet, ShardedDataSet
from bigdl_tpu.dataset.datasets import synthetic_separable
from bigdl_tpu.optim.evaluator import Evaluator
from bigdl_tpu.parallel import AllReduceParameter, DistriOptimizer

N_DEV = 8


def _mlp(din, nclass, seed=5):
    m = (nn.Sequential()
         .add(nn.Linear(din, 16))
         .add(nn.Tanh())
         .add(nn.Linear(16, nclass))
         .add(nn.LogSoftMax()))
    m.reset(jax.random.PRNGKey(seed))
    return m


class TestAllReduceParameter:
    def test_flatten_roundtrip_with_padding(self):
        params = {"w": jnp.arange(10, dtype=jnp.float32).reshape(2, 5),
                  "b": jnp.ones((3,))}
        arp = AllReduceParameter(params, 8)
        assert arp.padded_size % 8 == 0
        flat = arp.flatten(params)
        assert flat.shape == (arp.padded_size,)
        back = arp.unflatten(flat)
        np.testing.assert_array_equal(np.asarray(back["w"]),
                                      np.asarray(params["w"]))
        np.testing.assert_array_equal(np.asarray(back["b"]),
                                      np.asarray(params["b"]))

    def test_collectives_shape(self):
        """reduce-scatter + all-gather roundtrip under shard_map."""
        from bigdl_tpu.parallel.all_reduce import shard_map
        mesh = Engine.create_mesh((N_DEV,), ("data",))
        params = {"w": jnp.ones((4, 5))}
        arp = AllReduceParameter(params, N_DEV)

        def f(flat):
            shard = arp.reduce_scatter_gradients(flat, "data")
            return arp.all_gather_weights(shard, "data")

        g = shard_map(f, mesh=mesh, in_specs=P(), out_specs=P(),
                      check_rep=False)
        out = jax.jit(g)(arp.flatten(params))
        # psum over 8 replicated copies = 8x
        np.testing.assert_allclose(np.asarray(out[:20]), 8.0)

    def test_bf16_compression(self):
        params = {"w": jnp.full((16,), 3.14159)}
        arp = AllReduceParameter(params, 8, compression="bf16")
        assert arp.compression == "bf16"


class TestDistriOptimizer:
    def test_converges_on_separable_data(self):
        samples = synthetic_separable(512, 4, n_classes=3, seed=7)
        ds = ShardedDataSet(samples, N_DEV).transform(
            SampleToMiniBatch(64, N_DEV))
        model = _mlp(4, 3)
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        assert isinstance(opt, DistriOptimizer)
        opt.set_optim_method(optim.SGD(learning_rate=0.5))
        opt.set_end_when(optim.max_epoch(12))
        trained = opt.optimize()
        acc = Evaluator(trained).test(
            samples, [optim.Top1Accuracy()], 64)[0][1].final_result()
        assert acc > 0.9, f"distributed training failed to converge: acc={acc}"

    def test_matches_local_optimizer_exactly(self):
        """Full-batch steps: the sharded psum_scatter/update/all_gather cycle
        must reproduce the single-process trainer bit-for-bit-ish (the
        reference's RefOptimizer oracle strategy)."""
        samples = synthetic_separable(64, 4, n_classes=2, seed=3)

        def run(distributed):
            model = _mlp(4, 2, seed=11)
            if distributed:
                ds = ShardedDataSet(samples, N_DEV).transform(
                    SampleToMiniBatch(64, N_DEV))
            else:
                ds = LocalDataSet(samples).transform(SampleToMiniBatch(64))
            opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
            opt.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
            opt.set_end_when(optim.max_iteration(6))
            trained = opt.optimize()
            w, _ = trained.get_parameters()
            return np.asarray(w)

        w_local = run(False)
        w_distri = run(True)
        np.testing.assert_allclose(w_distri, w_local, rtol=2e-4, atol=2e-5)

    def test_mesh_eval_indivisible_batch_fallback(self):
        """A batch not divisible by the data axis falls back to the LOCAL
        forward; metrics must match the no-mesh evaluation exactly (100
        samples at batch 32 leaves a final batch of 4 on an 8-axis)."""
        from bigdl_tpu.dataset.transformer import SampleToMiniBatch as S2M
        from bigdl_tpu.engine import Engine
        from bigdl_tpu.optim.evaluator import evaluate_dataset
        samples = synthetic_separable(128, 4, n_classes=2, seed=3)[:100]
        model = _mlp(4, 2)
        model._ensure_init()
        batches = list(S2M(32)(iter(samples)))
        assert [b.size() for b in batches] == [32, 32, 32, 4]
        plain = evaluate_dataset(model, list(batches),
                                 [optim.Top1Accuracy()])
        meshed = evaluate_dataset(model, list(batches),
                                  [optim.Top1Accuracy()],
                                  mesh=Engine.create_mesh())
        assert (meshed[0][1].final_result() ==
                plain[0][1].final_result())
        assert meshed[0][1].count == 100

    def test_sharded_validation_matches_full_set(self):
        """Evaluating a ShardedDataSet must produce exactly the full-set
        metrics (single-process: all partitions local; the multi-host
        partial-merge path is proven in test_multihost.py)."""
        from bigdl_tpu.optim.evaluator import evaluate_dataset
        samples = synthetic_separable(128, 4, n_classes=2, seed=3)
        model = _mlp(4, 2)
        model._ensure_init()
        full = Evaluator(model).test(samples, [optim.Top1Accuracy()],
                                     32)[0][1].final_result()
        sharded = ShardedDataSet(samples, N_DEV).transform(
            SampleToMiniBatch(32, N_DEV))
        res = evaluate_dataset(model, sharded, [optim.Top1Accuracy()])
        assert res[0][1].final_result() == full

    def test_unequal_local_minibatches_rejected(self):
        """_global_batch derives the global record count as per-partition
        size x partition_num; uneven local minibatches would silently
        miscount epoch boundaries, so they must raise (advisor r3)."""
        import pytest
        from jax.sharding import NamedSharding, PartitionSpec as P
        from bigdl_tpu.dataset.sample import MiniBatch
        from bigdl_tpu.engine import Engine
        from bigdl_tpu.parallel.distri_optimizer import _global_batch

        mesh = Engine.create_mesh()
        sharding = NamedSharding(mesh, P("data"))

        def it(n):
            while True:
                yield MiniBatch(np.zeros((n, 4), np.float32),
                                np.ones((n,), np.float32))

        iters = {i: it(4) for i in range(N_DEV - 1)}
        iters[N_DEV - 1] = it(5)
        with pytest.raises(ValueError, match="unequal"):
            _global_batch(iters, sharding, mesh, N_DEV)

    def test_adam_sharded_slots(self):
        """ZeRO-1: Adam's m/v slots live sharded over the data axis."""
        samples = synthetic_separable(128, 4, n_classes=2, seed=3)
        ds = ShardedDataSet(samples, N_DEV).transform(
            SampleToMiniBatch(32, N_DEV))
        model = _mlp(4, 2)
        opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.Adam(learning_rate=0.05))
        opt.set_end_when(optim.max_iteration(50))
        trained = opt.optimize()
        acc = Evaluator(trained).test(
            samples, [optim.Top1Accuracy()], 32)[0][1].final_result()
        assert acc > 0.9
        # on-device slots are flat vectors sharded over the data axis
        leaf = jax.tree_util.tree_leaves(opt._sharded_slots)[0]
        spec = leaf.sharding.spec
        assert spec and spec[0] == "data", f"slots not sharded: {spec}"
        # published slots are in the canonical per-parameter pytree format:
        # the optim method must remain usable host-side (e.g. local resume)
        s_slots = opt.optim_method._slots["s"]   # Adam's first-moment slot
        p_leaves = jax.tree_util.tree_leaves(trained.params)
        s_leaves = jax.tree_util.tree_leaves(s_slots)
        assert [l.shape for l in s_leaves] == [l.shape for l in p_leaves]
        opt.optim_method.update(
            jax.tree_util.tree_map(jnp.zeros_like, trained.params),
            trained.params)

    def test_bf16_wire_compression_converges(self):
        """fp16-on-the-wire analog (reference FP16CompressedTensor)."""
        samples = synthetic_separable(256, 4, n_classes=3, seed=9)
        ds = ShardedDataSet(samples, N_DEV).transform(
            SampleToMiniBatch(64, N_DEV))
        model = _mlp(4, 3)
        opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(),
                              compression="bf16")
        opt.set_optim_method(optim.SGD(learning_rate=0.5))
        opt.set_end_when(optim.max_epoch(12))
        trained = opt.optimize()
        acc = Evaluator(trained).test(
            samples, [optim.Top1Accuracy()], 64)[0][1].final_result()
        assert acc > 0.9

    def test_bf16_compression_rejected_on_tp_mesh(self):
        """compression='bf16' must fail LOUDLY on a ('data','model') mesh:
        the GSPMD step's gradient collectives are XLA-inserted (f32
        accumulate-and-reduce, verified from compiled HLO), so the knob
        cannot take effect there — silence would quietly ship fp32 wire
        traffic a user believes is compressed."""
        from bigdl_tpu.engine import Engine
        from bigdl_tpu.models.transformer import transformer_lm
        mesh = Engine.create_mesh((2, 2), ("data", "model"),
                                  devices=Engine.devices()[:4])
        lm = transformer_lm(16, d_model=16, n_head=2, n_layers=1, tp=True)
        ds = ShardedDataSet(synthetic_separable(64, 4, n_classes=3), 2)
        opt = DistriOptimizer(lm, ds, nn.ClassNLLCriterion(), mesh=mesh,
                              compression="bf16")
        opt.set_optim_method(optim.SGD(learning_rate=0.1))
        opt.set_end_when(optim.max_iteration(1))
        with pytest.raises(ValueError, match="wire dtype is not"):
            opt.optimize()

    def test_conv_pool_model_distributed(self):
        """LeNet-style conv+pool through the sharded fused step."""
        from tests.test_e2e_train import synthetic_digit_images
        samples = synthetic_digit_images(256, side=16, n_classes=4)
        ds = ShardedDataSet(samples, N_DEV).transform(
            SampleToMiniBatch(32, N_DEV))
        m = (nn.Sequential()
             .add(nn.Reshape((1, 16, 16)))
             .add(nn.SpatialConvolution(1, 8, 3, 3, 1, 1, 1, 1))
             .add(nn.ReLU())
             .add(nn.SpatialMaxPooling(2, 2, 2, 2))
             .add(nn.Reshape((8 * 8 * 8,)))
             .add(nn.Linear(8 * 8 * 8, 4))
             .add(nn.LogSoftMax()))
        opt = optim.Optimizer.create(m, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.1))
        opt.set_end_when(optim.max_iteration(60))
        trained = opt.optimize()
        acc = Evaluator(trained).test(
            samples, [optim.Top1Accuracy()], 32)[0][1].final_result()
        assert acc > 0.9

    def test_batchnorm_state_stays_consistent(self):
        """BN running stats are pmean'd across shards: after training, the
        published state must be finite and moved off its init."""
        from tests.test_e2e_train import synthetic_digit_images
        samples = synthetic_digit_images(128, side=8, n_classes=2)
        ds = ShardedDataSet(samples, N_DEV).transform(
            SampleToMiniBatch(32, N_DEV))
        m = (nn.Sequential()
             .add(nn.Reshape((1, 8, 8)))
             .add(nn.SpatialConvolution(1, 4, 3, 3, 1, 1, 1, 1))
             .add(nn.SpatialBatchNormalization(4))
             .add(nn.ReLU())
             .add(nn.Reshape((4 * 8 * 8,)))
             .add(nn.Linear(4 * 8 * 8, 2))
             .add(nn.LogSoftMax()))
        opt = optim.Optimizer.create(m, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.1))
        opt.set_end_when(optim.max_iteration(30))
        trained = opt.optimize()
        bn_state = trained.state[2]
        rm = np.asarray(bn_state["running_mean"])
        assert np.all(np.isfinite(rm)) and np.abs(rm).sum() > 0

    def test_partition_mesh_mismatch_raises(self):
        samples = synthetic_separable(64, 4, n_classes=2)
        ds = ShardedDataSet(samples, 4).transform(SampleToMiniBatch(32, 4))
        opt = DistriOptimizer(_mlp(4, 2), ds, nn.ClassNLLCriterion())
        with pytest.raises(ValueError, match="must match"):
            opt.optimize()

    def test_validation_and_checkpoint_during_distributed_run(self, tmp_path):
        samples = synthetic_separable(256, 4, n_classes=2, seed=1)
        ds = ShardedDataSet(samples, N_DEV).transform(
            SampleToMiniBatch(64, N_DEV))
        model = _mlp(4, 2)
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.5))
        opt.set_end_when(optim.max_epoch(6))
        opt.set_checkpoint(str(tmp_path / "ckpt"), optim.every_epoch())
        opt.set_validation(optim.every_epoch(),
                           LocalDataSet(samples).transform(SampleToMiniBatch(64)),
                           [optim.Top1Accuracy()])
        opt.optimize()
        latest = opt.checkpoint.latest()
        assert latest is not None
        from bigdl_tpu.utils import file_io
        m2 = file_io.load(latest[0])
        acc = Evaluator(m2).test(
            samples, [optim.Top1Accuracy()], 64)[0][1].final_result()
        assert acc > 0.9


class TestSequenceParallelTraining:
    """dp x sp training: ring-attention sequence parallelism integrated in
    the DistriOptimizer step (beyond-reference long-context path)."""

    D_MODEL, N_CLASS, SEQ_T = 16, 4, 8

    def _lm(self, seed=21):
        m = (nn.Sequential()
             .add(nn.Linear(self.D_MODEL, self.D_MODEL))
             .add(nn.MultiHeadAttention(self.D_MODEL, 2, causal=True))
             .add(nn.Tanh())
             .add(nn.Linear(self.D_MODEL, self.N_CLASS))
             .add(nn.LogSoftMax()))
        m.reset(jax.random.PRNGKey(seed))
        return m

    def _samples(self, n=32, seed=9):
        rng = np.random.RandomState(seed)
        out = []
        for _ in range(n):
            x = rng.normal(size=(self.SEQ_T, self.D_MODEL)).astype(np.float32)
            # learnable signal: label at t follows the sign of feature 0
            y = (x[:, 0] > 0).astype(np.float32) + 1.0
            out.append(Sample(x, y))
        return out

    def _train(self, samples, distributed, iters=6, lr=0.1):
        model = self._lm()
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)
        if distributed:
            mesh = Engine.create_mesh((4, 2), ("data", "seq"))
            ds = ShardedDataSet(samples, 4).transform(
                SampleToMiniBatch(len(samples), 4))
            opt = DistriOptimizer(model, ds, crit, mesh=mesh)
        else:
            ds = LocalDataSet(samples).transform(
                SampleToMiniBatch(len(samples)))
            opt = optim.Optimizer.create(model, ds, crit)
        opt.set_optim_method(optim.SGD(learning_rate=lr, momentum=0.9))
        opt.set_end_when(optim.max_iteration(iters))
        trained = opt.optimize()
        w, _ = trained.get_parameters()
        return np.asarray(w), model

    def test_matches_local_training_exactly(self):
        """The dp x sp step (ring attention + psum over both axes) must
        reproduce full-sequence single-process training — the RefOptimizer
        oracle strategy applied to the long-context path."""
        samples = self._samples()
        w_local, _ = self._train(samples, distributed=False)
        w_distri, model = self._train(samples, distributed=True)
        np.testing.assert_allclose(w_distri, w_local, rtol=5e-4, atol=5e-5)
        # after training, the same model still forwards full sequences
        # outside the mesh (the ring path is shard_map-scoped)
        x = np.stack([s.feature for s in samples[:4]])
        out = np.asarray(model.forward(x))
        assert out.shape == (4, self.SEQ_T, self.N_CLASS)

    def test_converges_and_validates(self):
        samples = self._samples(n=64)
        model = self._lm(seed=5)
        mesh = Engine.create_mesh((4, 2), ("data", "seq"))
        ds = ShardedDataSet(samples, 4).transform(SampleToMiniBatch(32, 4))
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)
        opt = DistriOptimizer(model, ds, crit, mesh=mesh)
        opt.set_optim_method(optim.Adam(learning_rate=0.02))
        opt.set_end_when(optim.max_iteration(40))
        trained = opt.optimize()
        x = np.stack([s.feature for s in samples])
        pred = np.asarray(trained.forward(x)).argmax(-1) + 1
        want = np.stack([s.label for s in samples])
        acc = float((pred == want).mean())
        assert acc > 0.9, f"sp training failed to converge: acc={acc}"

    def test_time_mixing_modules_rejected(self):
        """Recurrent/temporal-conv models cannot be time-sharded: each
        chunk would restart the hidden state — must raise, not silently
        train wrong."""
        rng = np.random.RandomState(1)
        samples = [Sample(rng.normal(size=(8, 4)).astype(np.float32),
                          np.ones(8, np.float32)) for _ in range(8)]
        model = (nn.Sequential()
                 .add(nn.Recurrent().add(nn.RnnCell(4, 8, nn.Tanh())))
                 .add(nn.TimeDistributed(nn.Linear(8, 2)))
                 .add(nn.LogSoftMax()))
        mesh = Engine.create_mesh((4, 2), ("data", "seq"))
        ds = ShardedDataSet(samples, 4).transform(SampleToMiniBatch(8, 4))
        opt = DistriOptimizer(
            model, ds, nn.TimeDistributedCriterion(nn.ClassNLLCriterion()),
            mesh=mesh)
        opt.set_optim_method(optim.SGD(learning_rate=0.1))
        opt.set_end_when(optim.max_iteration(1))
        with pytest.raises(ValueError, match="Recurrent"):
            opt.optimize()

    def test_mha_wired_through_non_container_wrapper(self):
        """find_modules-based wiring reaches an MHA nested in Bottle (a
        plain-Module composite), not just Container children."""
        mha = nn.MultiHeadAttention(self.D_MODEL, 2, causal=True)
        model = (nn.Sequential()
                 .add(nn.Bottle(mha, n_input_dim=3, n_output_dim=3))
                 .add(nn.Linear(self.D_MODEL, self.N_CLASS))
                 .add(nn.LogSoftMax()))
        model.reset(jax.random.PRNGKey(2))
        samples = self._samples(n=8)
        mesh = Engine.create_mesh((4, 2), ("data", "seq"))
        ds = ShardedDataSet(samples, 4).transform(SampleToMiniBatch(8, 4))
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)
        opt = DistriOptimizer(model, ds, crit, mesh=mesh)
        opt.set_optim_method(optim.SGD(learning_rate=0.05))
        opt.set_end_when(optim.max_iteration(1))
        opt.optimize()
        assert mha.sequence_parallel == "seq"

    def test_seq_shape_guard(self):
        samples = self._samples(n=8)
        # T=8 not divisible by... use a 3-wide seq axis? 8 devices: (2, 4)
        # mesh with T=6 inputs -> T % 4 != 0 must raise clearly
        rng = np.random.RandomState(0)
        bad = [Sample(rng.normal(size=(6, self.D_MODEL)).astype(np.float32),
                      np.ones(6, np.float32)) for _ in range(8)]
        mesh = Engine.create_mesh((2, 4), ("data", "seq"))
        ds = ShardedDataSet(bad, 2).transform(SampleToMiniBatch(8, 2))
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
        opt = DistriOptimizer(self._lm(), ds, crit, mesh=mesh)
        opt.set_optim_method(optim.SGD(learning_rate=0.1))
        opt.set_end_when(optim.max_iteration(1))
        with pytest.raises(ValueError, match="divisible by the seq axis"):
            opt.optimize()
