"""Resource-exhaustion resilience: the ISSUE 14 acceptance suite.

Three exhaustion classes, each chaos-proven: device OOM answered by a
microbatch re-plan (weight parity with the uninjected run, zero
post-warmup retraces), disk-full degradation across checkpoints /
compile cache / telemetry exports (training never crashes), and the
host-memory governor (byte accounting, edge-triggered pressure,
deterministic depth shrink).  The combined test at the bottom runs ALL
three faults in ONE training run — the issue's acceptance gate.

Parity tests use full-batch datasets (one iteration per epoch) so a
replayed trajectory is bit-comparable to an uninterrupted one — the
same protocol as ``test_chaos``.
"""

import errno
import io
import os

import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu import telemetry
from bigdl_tpu.dataset import LocalDataSet, SampleToMiniBatch
from bigdl_tpu.dataset.datasets import synthetic_separable
from bigdl_tpu.resources import (GOVERNOR, DeviceMemoryError,
                                 HostMemoryError, StorageExhaustedError,
                                 is_oom_error, is_storage_exhausted,
                                 item_nbytes, storage)
from bigdl_tpu.resources import device as rdevice
from bigdl_tpu.resources import microbatch
from bigdl_tpu.utils import chaos, config, file_io


def _mlp(seed=11):
    import jax
    m = (nn.Sequential().add(nn.Linear(4, 16)).add(nn.Tanh())
         .add(nn.Linear(16, 2)).add(nn.LogSoftMax()))
    m.reset(jax.random.PRNGKey(seed))
    return m


def _full_batch_ds(samples):
    return LocalDataSet(samples).transform(SampleToMiniBatch(len(samples)))


def _train(samples, epochs, ckpt_dir=None, seed=11, ckpt_trigger=None):
    model = _mlp(seed=seed)
    opt = optim.Optimizer.create(model, _full_batch_ds(samples),
                                 nn.ClassNLLCriterion())
    opt.set_optim_method(optim.SGD(learning_rate=0.3, momentum=0.9))
    opt.set_end_when(optim.max_epoch(epochs))
    if ckpt_dir is not None:
        opt.set_checkpoint(str(ckpt_dir),
                           ckpt_trigger or optim.every_epoch())
    opt.optimize()
    w, _ = model.get_parameters()
    return np.asarray(w), opt


def _counter_value(name):
    return telemetry.counter(name).value


@pytest.fixture(autouse=True)
def _resource_env():
    """Zero retry sleeps; fresh governor/degradation/chaos state around
    every test (all three are process-global)."""
    config.set_property("bigdl.failure.retryTimeInterval", 0.0)
    GOVERNOR.reset()
    storage.reset()
    yield
    chaos.uninstall()
    GOVERNOR.reset()
    storage.reset()
    for key in ("bigdl.failure.retryTimeInterval",
                "bigdl.failure.retryTimes",
                "bigdl.resources.deviceMemBudgetMB",
                "bigdl.resources.hostMemBudgetMB",
                "bigdl.chaos.oomStepAt", "bigdl.chaos.diskFullAt",
                "bigdl.chaos.hostMemPressureAt",
                "bigdl.telemetry.maxTimelineDumps",
                "bigdl.compile.cacheDir"):
        config.clear_property(key)


# ---------------------------------------------------------------------------
# microbatch planning math
# ---------------------------------------------------------------------------


class TestMicrobatchPlan:
    def test_snap_k_smallest_divisor(self):
        assert microbatch.snap_k(128, 3) == 4
        assert microbatch.snap_k(12, 5) == 6
        assert microbatch.snap_k(7, 2) == 7      # prime: straight to B
        assert microbatch.snap_k(8, 99) == 8     # k clamps to B
        assert microbatch.snap_k(16, 1) == 1

    def test_next_k_doubling_schedule_terminates(self):
        ks, k = [], 1
        while True:
            k = microbatch.next_k(12, k)
            if k is None:
                break
            ks.append(k)
        assert ks == [2, 4, 12], ks
        assert microbatch.next_k(1, 1) is None   # nothing left to split

    def test_scan_mean_matches_full_batch_mean(self):
        import jax.numpy as jnp
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(12, 5).astype(np.float32))

        def fn(chunk):
            return {"m": jnp.mean(chunk * chunk, axis=0),
                    "s": jnp.mean(jnp.tanh(chunk), axis=0)}

        full = fn(x)
        for k in (2, 3, 4, 6, 12):
            out = microbatch.scan_mean(fn, x, k)
            for key in full:
                np.testing.assert_allclose(
                    np.asarray(out[key]), np.asarray(full[key]),
                    rtol=1e-6, atol=1e-7)

    def test_scan_mean_preserves_integer_dtype(self):
        """Module-state counters are integer leaves: equal-per-chunk
        values must floor-divide back exactly, never promote to float
        (a promoted carry would drift the re-planned step's signature)."""
        import jax.numpy as jnp
        x = jnp.ones((8, 3), np.float32)

        def fn(chunk):
            return jnp.full((), 7, jnp.int32)

        out = microbatch.scan_mean(fn, x, 4)
        assert out.dtype == jnp.int32
        assert int(out) == 7


# ---------------------------------------------------------------------------
# device preflight
# ---------------------------------------------------------------------------


class _FakeMemAnalysis:
    def __init__(self, arg, out, temp):
        self.argument_size_in_bytes = arg
        self.output_size_in_bytes = out
        self.temp_size_in_bytes = temp


class _FakeCompiled:
    def __init__(self, arg=0, out=0, temp=0, broken=False):
        self._ma = _FakeMemAnalysis(arg, out, temp)
        self._broken = broken

    def memory_analysis(self):
        if self._broken:
            raise RuntimeError("backend cannot report memory analysis")
        return self._ma


class TestDevicePreflight:
    def test_preflight_off_without_budget(self):
        assert rdevice.preflight(_FakeCompiled(1 << 40, 0, 0), "s") is None

    def test_preflight_passes_under_budget(self):
        config.set_property("bigdl.resources.deviceMemBudgetMB", 10)
        peak = rdevice.preflight(_FakeCompiled(1 << 20, 1 << 20, 0), "s")
        assert peak == 2 << 20

    def test_preflight_breach_raises_structured(self):
        config.set_property("bigdl.resources.deviceMemBudgetMB", 1)
        with pytest.raises(DeviceMemoryError) as ei:
            rdevice.preflight(_FakeCompiled(0, 0, 2 << 20), "fused")
        e = ei.value
        assert e.phase == "preflight" and e.label == "fused"
        assert e.peak_bytes == 2 << 20 and e.budget_bytes == 1 << 20

    def test_preflight_never_false_positive_when_unreportable(self):
        config.set_property("bigdl.resources.deviceMemBudgetMB", 1)
        assert rdevice.preflight(_FakeCompiled(broken=True), "s") is None

    def test_classify_dispatch_error(self):
        oom = RuntimeError("RESOURCE_EXHAUSTED: Out of memory while "
                           "trying to allocate 17179869184 bytes")
        err = rdevice.classify_dispatch_error(oom, "fused")
        assert isinstance(err, DeviceMemoryError)
        assert err.phase == "dispatch" and err.__cause__ is oom
        assert rdevice.classify_dispatch_error(
            ValueError("shape mismatch"), "fused") is None

    def test_oom_marker_classifier(self):
        assert is_oom_error(RuntimeError("OOM when allocating tensor"))
        assert not is_oom_error(RuntimeError("divide by zero"))


# ---------------------------------------------------------------------------
# host-memory governor
# ---------------------------------------------------------------------------


class TestGovernor:
    def test_account_clamped_ledger(self):
        a = GOVERNOR.account("t_ring")
        assert GOVERNOR.account("t_ring") is a     # idempotent
        a.add(100)
        a.sub(30)
        assert a.nbytes == 70
        a.sub(1000)                                # clamp, never negative
        assert a.nbytes == 0
        a.set(5)
        assert a.nbytes == 5
        b = GOVERNOR.account("t_window")
        b.add(7)
        assert GOVERNOR.total_bytes() == 12

    def test_item_nbytes_estimates(self):
        arr = np.zeros((4, 4), np.float32)
        assert item_nbytes(arr) == 64
        assert item_nbytes(b"abcd") == 4
        assert item_nbytes("abc") == 3
        assert item_nbytes(None) == 0
        assert item_nbytes({"a": arr, "b": b"xy"}) == 66
        assert item_nbytes([arr, [arr]]) == 128
        deep = [[[[[arr]]]]]                       # past the depth cap
        assert item_nbytes(deep) == 0

    def test_free_bytes_sentinel_without_budget(self):
        assert GOVERNOR.free_bytes() == 1 << 62
        config.set_property("bigdl.resources.hostMemBudgetMB", 1)
        GOVERNOR.account("t").add(1 << 19)
        assert GOVERNOR.free_bytes() == (1 << 20) - (1 << 19)

    def test_check_item_escalates_oversized_item(self):
        GOVERNOR.check_item("t", 1 << 40)          # no budget: no-op
        config.set_property("bigdl.resources.hostMemBudgetMB", 1)
        GOVERNOR.check_item("t", 1 << 20)          # exactly at budget: ok
        before = _counter_value("Resources/host_budget_exceeded")
        with pytest.raises(HostMemoryError) as ei:
            GOVERNOR.check_item("t_batch", (1 << 20) + 1)
        e = ei.value
        assert e.account == "t_batch" and e.budget_bytes == 1 << 20
        assert _counter_value(
            "Resources/host_budget_exceeded") == before + 1

    def test_poll_edge_triggered_shrinkers(self):
        """A sustained breach fires the shrinkers ONCE per excursion;
        recovery re-arms the edge."""
        config.set_property("bigdl.resources.hostMemBudgetMB", 1)
        fired = []
        GOVERNOR.register_shrinker("t", lambda: fired.append(1))
        acct = GOVERNOR.account("t_ring")
        acct.add(2 << 20)
        assert GOVERNOR.poll() is True
        assert GOVERNOR.under_pressure()
        assert GOVERNOR.poll() is False            # still under: no re-fire
        assert len(fired) == 1
        acct.set(0)
        assert GOVERNOR.poll() is False            # recovered
        assert not GOVERNOR.under_pressure()
        acct.add(2 << 20)
        assert GOVERNOR.poll() is True             # second excursion
        assert len(fired) == 2

    def test_broken_shrinker_does_not_kill_the_poll(self):
        config.set_property("bigdl.resources.hostMemBudgetMB", 1)

        def bad():
            raise RuntimeError("shrinker bug")

        GOVERNOR.register_shrinker("bad", bad)
        GOVERNOR.account("t").add(2 << 20)
        assert GOVERNOR.poll() is True             # no propagation

    def test_injected_pressure_fires_once_per_plan(self):
        config.set_property("bigdl.chaos.hostMemPressureAt", 2)
        chaos.install()
        fired = []
        GOVERNOR.register_shrinker("t", lambda: fired.append(1))
        assert GOVERNOR.poll() is False            # poll 1: armed, quiet
        assert GOVERNOR.poll() is True             # poll 2: injected
        assert chaos._state.pressure_fired == 1
        assert GOVERNOR.poll() is False            # once per plan
        assert len(fired) == 1

    def test_summary_scalars_roll_up(self):
        GOVERNOR.account("t_ring").add(10)
        GOVERNOR.account("t_window").add(5)
        scalars = dict(GOVERNOR.summary_scalars())
        assert scalars["Resources/host_bytes"] == 15.0
        assert scalars["Resources/host_bytes_t_ring"] == 10.0
        assert scalars["Resources/host_bytes_t_window"] == 5.0
        assert "Resources/host_pressure_events" in scalars
        # and the registry provider surfaces the same tags
        tags = {t for t, _ in telemetry.REGISTRY.summary_scalars()}
        assert "Resources/host_bytes" in tags


# ---------------------------------------------------------------------------
# chaos injectors
# ---------------------------------------------------------------------------


class TestChaosInjectors:
    def test_parse_disk_full_plan(self):
        assert chaos._parse_disk_full(None) == []
        assert chaos._parse_disk_full("3") == [
            {"k": 3, "substr": "", "count": 0, "fired": False}]
        assert chaos._parse_disk_full("2:ckpt") == [
            {"k": 2, "substr": "ckpt", "count": 0, "fired": False}]
        assert chaos._parse_disk_full("2:checkpoints, 1:compile_cache") == [
            {"k": 2, "substr": "checkpoints", "count": 0, "fired": False},
            {"k": 1, "substr": "compile_cache", "count": 0, "fired": False}]

    def test_take_oom_dispatch_once_at_k(self):
        config.set_property("bigdl.chaos.oomStepAt", 3)
        chaos.install()
        chaos.take_oom_dispatch("s")
        chaos.take_oom_dispatch("s")
        with pytest.raises(RuntimeError) as ei:
            chaos.take_oom_dispatch("s")
        assert is_oom_error(ei.value), "must replicate the XLA message"
        chaos.take_oom_dispatch("s")               # once per plan
        assert chaos._state.oom_fired == 1
        assert chaos._state.step_dispatches == 4

    def test_take_disk_full_substring_matched(self):
        config.set_property("bigdl.chaos.diskFullAt", "2:ckpt")
        chaos.install()
        chaos.take_disk_full("/tmp/other/file")    # no substring match
        chaos.take_disk_full("/tmp/ckpt/model.1")  # match 1 of 2
        with pytest.raises(OSError) as ei:
            chaos.take_disk_full("/tmp/ckpt/optimMethod.1")
        assert ei.value.errno == errno.ENOSPC
        assert not isinstance(ei.value, StorageExhaustedError), \
            "the injector must raise the RAW error so classification " \
            "at the choke point is exercised, not bypassed"
        assert is_storage_exhausted(ei.value)
        chaos.take_disk_full("/tmp/ckpt/manifest.1")   # entry spent
        assert chaos._state.disk_full_fired == 1

    def test_disarmed_hooks_are_noops(self):
        chaos.take_oom_dispatch("s")
        chaos.take_disk_full("/tmp/x")
        assert chaos.host_mem_pressure(99) is False


# ---------------------------------------------------------------------------
# disk-full degradation
# ---------------------------------------------------------------------------


class TestStorageDegradation:
    def test_write_bytes_classifies_enospc(self, tmp_path):
        config.set_property("bigdl.chaos.diskFullAt", "1")
        chaos.install()
        with pytest.raises(StorageExhaustedError) as ei:
            file_io.write_bytes(str(tmp_path / "payload"), b"x" * 64)
        e = ei.value
        assert e.fatal is True and e.errno == errno.ENOSPC
        assert "payload" in e.path
        # the torn temp never commits
        assert not (tmp_path / "payload").exists()

    def test_note_degraded_once_semantics(self):
        before = _counter_value("Resources/storage_degraded"
                                "{component=checkpoints}")
        err = OSError(errno.ENOSPC, "No space left on device")
        assert storage.note_degraded("checkpoints", err) is True
        assert storage.note_degraded("checkpoints", err) is False
        assert storage.is_degraded("checkpoints")
        assert storage.is_degraded()
        assert not storage.is_degraded("compile_cache")
        assert "checkpoints" in storage.degraded_components()
        assert telemetry.counter(
            "Resources/storage_degraded",
            labels={"component": "checkpoints"}).value == before + 1

    def test_guarded_export_degrades_and_skips(self):
        ran = []
        assert storage.guarded_export("telemetry", lambda: ran.append(1))
        assert ran == [1]

        def full():
            raise OSError(errno.ENOSPC, "No space left on device")

        assert storage.guarded_export("telemetry", full) is False
        assert storage.is_degraded("telemetry")
        # degraded: the export is skipped without even calling fn
        assert storage.guarded_export("telemetry",
                                      lambda: ran.append(2)) is False
        assert ran == [1]

    def test_guarded_export_propagates_non_storage_errors(self):
        def boom():
            raise ValueError("not a disk problem")

        with pytest.raises(ValueError):
            storage.guarded_export("telemetry", boom)
        assert not storage.is_degraded("telemetry")

    def test_bounded_timeline_export_evicts_oldest(self, tmp_path):
        config.set_property("bigdl.telemetry.maxTimelineDumps", 3)
        paths = [str(tmp_path / f"dump_{i}.json") for i in range(5)]
        for p in paths:
            assert storage.bounded_timeline_export(p) is True
        assert storage.timeline_dump_count() == 3
        survivors = sorted(os.listdir(tmp_path))
        assert survivors == ["dump_2.json", "dump_3.json", "dump_4.json"]

    def test_bounded_timeline_export_cap_zero_disables(self, tmp_path):
        config.set_property("bigdl.telemetry.maxTimelineDumps", 0)
        assert storage.bounded_timeline_export(
            str(tmp_path / "d.json")) is False
        assert os.listdir(tmp_path) == []

    def test_checkpoint_degrades_to_memory_snapshot(self, tmp_path):
        """Disk fills during snapshot 2: the save must NOT crash, disk
        restore must land on the newest PRE-ENOSPC snapshot, and
        load_latest must prefer the newer in-RAM snapshot."""
        from bigdl_tpu.optim.optimizer import Checkpoint
        ckpt = Checkpoint(str(tmp_path), optim.every_epoch())
        m, sgd = _mlp(), optim.SGD(learning_rate=0.1)
        ckpt.save(m, sgd, 1)
        config.set_property("bigdl.chaos.diskFullAt", "1:model.2")
        chaos.install()
        ckpt.save(m, sgd, 2)                       # degrades, no crash
        assert chaos._state.disk_full_fired == 1
        assert storage.is_degraded("checkpoints")
        _, _, n = ckpt.latest()
        assert n == 1, "disk restore must land on the pre-ENOSPC snapshot"
        # the degraded-mode RAM snapshot is newer and wins load_latest
        restored = ckpt.manager.load_latest()
        assert restored is not None and restored[2] == 2
        # further saves stay in-memory, still no crash, no new files
        names_before = sorted(os.listdir(tmp_path))
        ckpt.save(m, sgd, 3)
        assert sorted(os.listdir(tmp_path)) == names_before
        assert ckpt.manager.load_latest()[2] == 3


# ---------------------------------------------------------------------------
# microbatch backoff: injected device OOM -> re-plan -> weight parity
# ---------------------------------------------------------------------------


class TestMicrobatchBackoff:
    def test_oom_replan_reaches_weight_parity(self, tmp_path):
        """The tentpole's core claim: a device OOM at step k is answered
        by a microbatch re-plan (k accumulation chunks, Kahan mean), the
        run finishes, the weights are allclose to the uninjected run,
        and the re-planned program never trips the strict retrace gate."""
        samples = synthetic_separable(128, 4, n_classes=2, seed=7)
        w_clean, _ = _train(samples, epochs=4)

        replans_before = _counter_value("Resources/microbatch_replans")
        config.set_property("bigdl.chaos.oomStepAt", 2)
        chaos.install()
        w_chaos, opt = _train(samples, epochs=4,
                              ckpt_dir=tmp_path / "ckpt",
                              ckpt_trigger=optim.several_iteration(1))
        assert chaos._state.oom_fired == 1, "the injected OOM never fired"
        assert opt._microbatch_k > 1, "the driver never re-planned"
        np.testing.assert_allclose(w_chaos, w_clean, rtol=1e-5, atol=1e-7)
        sent = opt._retrace_sentinel
        assert sent is not None and sent.retraces == 0, \
            "the re-planned program must register as a FRESH signature"
        assert _counter_value(
            "Resources/microbatch_replans") >= replans_before + 1

    def test_oom_without_split_left_is_fatal(self):
        """Per-sample already (B == 1): no further split exists, so the
        structured DeviceMemoryError must surface, not loop."""
        samples = synthetic_separable(1, 4, n_classes=2, seed=7)
        config.set_property("bigdl.chaos.oomStepAt", 1)
        config.set_property("bigdl.failure.retryTimes", 2)
        chaos.install()
        model = _mlp()
        opt = optim.Optimizer.create(model, _full_batch_ds(samples),
                                     nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.3))
        opt.set_end_when(optim.max_epoch(2))
        with pytest.raises(DeviceMemoryError):
            opt.optimize()


# ---------------------------------------------------------------------------
# governor depth shrink: deterministic batch stream
# ---------------------------------------------------------------------------


def _png_records(n=12, hw=(40, 48), seed=3):
    from PIL import Image
    from bigdl_tpu.dataset.image import LabeledImageBytes
    rng = np.random.RandomState(seed)
    recs = []
    for i in range(n):
        img = rng.randint(0, 256, size=hw + (3,)).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(img).save(buf, "PNG")
        recs.append(LabeledImageBytes(f"r{i}", float(i % 5 + 1),
                                      buf.getvalue()))
    return recs


class TestGovernorShrinkDeterminism:
    def test_mid_epoch_depth_shrink_keeps_batches_bit_identical(self):
        """Injected pressure mid-stream halves the ingest ring depths;
        the emitted batch stream must stay BIT-identical — backpressure
        may change timing, never data."""
        from bigdl_tpu.dataset.ingest import StreamingIngest

        def _eng():
            # deterministic decode (center crop, no flip): any payload
            # difference is then attributable to the shrink, not RNG
            return StreamingIngest(4, crop=(32, 32), decode_workers=2,
                                   random_crop=False, hflip=False)

        recs = _png_records(n=16)
        clean = [(b.get_input().copy(), b.get_target().copy())
                 for b in _eng()(iter(recs))]
        assert len(clean) == 4

        GOVERNOR.reset()
        config.set_property("bigdl.chaos.hostMemPressureAt", 2)
        chaos.install()
        eng2 = _eng()
        shrunk = [(b.get_input().copy(), b.get_target().copy())
                  for b in eng2(iter(recs))]
        assert chaos._state.pressure_fired == 1, \
            "the injected pressure excursion never fired"
        assert len(shrunk) == len(clean)
        for (xi, yi), (xc, yc) in zip(shrunk, clean):
            np.testing.assert_array_equal(xi, xc)
            np.testing.assert_array_equal(yi, yc)


# ---------------------------------------------------------------------------
# the acceptance gate: ALL THREE faults in ONE run
# ---------------------------------------------------------------------------


class TestCombinedChaos:
    def test_one_run_survives_all_three_exhaustion_faults(self, tmp_path):
        """ISSUE 14 acceptance: one training run takes a device OOM at
        step 2, a full disk during BOTH a checkpoint snapshot and a
        compile-cache store, and an injected host-memory pressure
        excursion — and still completes with weight parity against the
        uninjected run, zero post-warmup retraces, the ``Resources/*``
        counters firing for every fault class, and no crash."""
        samples = synthetic_separable(128, 4, n_classes=2, seed=7)
        w_clean, _ = _train(samples, epochs=6)

        pressure_before = _counter_value("Resources/host_pressure")
        oom_before = _counter_value("Resources/device_oom")
        replans_before = _counter_value("Resources/microbatch_replans")

        GOVERNOR.reset()
        config.set_property("bigdl.chaos.oomStepAt", 2)
        # snapshot 1's writes land in .../ckpt; the SECOND matching
        # write (optimMethod.1) hits the full disk -> checkpoint manager
        # degrades to the in-RAM snapshot; the FIRST write into the
        # compile-cache dir degrades the cache to memory-only
        config.set_property("bigdl.chaos.diskFullAt",
                            "2:ckpt,1:compile_cache")
        config.set_property("bigdl.chaos.hostMemPressureAt", 3)
        config.set_property("bigdl.compile.cacheDir",
                            str(tmp_path / "compile_cache"))
        chaos.install()

        w_chaos, opt = _train(samples, epochs=6,
                              ckpt_dir=tmp_path / "ckpt",
                              ckpt_trigger=optim.several_iteration(1))

        st = chaos._state
        assert st.oom_fired == 1, "device OOM never fired"
        assert st.disk_full_fired >= 1, "disk-full never fired"
        assert st.pressure_fired == 1, "host pressure never fired"

        # every fault class left its structured trace
        assert storage.is_degraded("checkpoints")
        assert _counter_value("Resources/device_oom") >= oom_before + 1
        assert _counter_value(
            "Resources/microbatch_replans") >= replans_before + 1
        assert _counter_value(
            "Resources/host_pressure") >= pressure_before + 1

        # ... and the run itself is unharmed
        np.testing.assert_allclose(w_chaos, w_clean, rtol=1e-5, atol=1e-7)
        assert opt._microbatch_k > 1
        sent = opt._retrace_sentinel
        assert sent is not None and sent.retraces == 0, \
            f"post-warmup retraces after the re-plan: {sent.last_diff}"
