"""Layer-zoo semantics tests (shape + golden-value checks).

Torch-parity strategy (SURVEY §4.1): where the reference shells out to Torch7
for golden values, we assert against hand-computed/numpy references.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn


def rand(*shape):
    return jnp.asarray(np.random.randn(*shape).astype(np.float32))


class TestConv:
    def test_spatial_convolution_shape(self):
        m = nn.SpatialConvolution(3, 16, 3, 3, 2, 2, 1, 1)
        out = m.forward(rand(2, 3, 32, 32))
        assert out.shape == (2, 16, 16, 16)

    def test_spatial_convolution_golden_identity_kernel(self):
        # 1x1 kernel with identity weight reproduces input channels
        m = nn.SpatialConvolution(2, 2, 1, 1, with_bias=False)
        eye = np.zeros((1, 1, 2, 2), np.float32)
        eye[0, 0, 0, 0] = 1
        eye[0, 0, 1, 1] = 1
        m.reset()
        m.params = {"weight": jnp.asarray(eye)}
        x = rand(1, 2, 5, 5)
        np.testing.assert_allclose(np.asarray(m.forward(x)), np.asarray(x),
                                   rtol=1e-6)

    def test_conv_cross_correlation_semantics(self):
        # single 2x2 kernel of ones = sliding window sum (no flip)
        m = nn.SpatialConvolution(1, 1, 2, 2, with_bias=False)
        m.reset()
        m.params = {"weight": jnp.ones((2, 2, 1, 1))}
        x = jnp.arange(9.0).reshape(1, 1, 3, 3)
        out = np.asarray(m.forward(x))[0, 0]
        exp = np.array([[0 + 1 + 3 + 4, 1 + 2 + 4 + 5],
                        [3 + 4 + 6 + 7, 4 + 5 + 7 + 8]], np.float32)
        np.testing.assert_allclose(out, exp)

    def test_grouped_conv(self):
        m = nn.SpatialConvolution(4, 8, 3, 3, n_group=2)
        out = m.forward(rand(2, 4, 8, 8))
        assert out.shape == (2, 8, 6, 6)

    def test_same_padding(self):
        m = nn.SpatialConvolution(3, 5, 3, 3, 1, 1, -1, -1)
        out = m.forward(rand(2, 3, 7, 7))
        assert out.shape == (2, 5, 7, 7)

    def test_3d_input_no_batch(self):
        m = nn.SpatialConvolution(3, 4, 3, 3)
        out = m.forward(rand(3, 10, 10))
        assert out.shape == (4, 8, 8)

    def test_dilated(self):
        m = nn.SpatialDilatedConvolution(2, 4, 3, 3, dilation_w=2, dilation_h=2)
        out = m.forward(rand(1, 2, 9, 9))
        assert out.shape == (1, 4, 5, 5)

    def test_full_convolution_upsamples(self):
        m = nn.SpatialFullConvolution(2, 3, 4, 4, 2, 2, 1, 1)
        out = m.forward(rand(1, 2, 8, 8))
        # out = (in-1)*stride - 2*pad + kernel = 7*2 - 2 + 4 = 16
        assert out.shape == (1, 3, 16, 16)

    def test_full_conv_gradient(self):
        m = nn.SpatialFullConvolution(2, 2, 3, 3, 2, 2)
        x = rand(1, 2, 4, 4)
        out = m.forward(x)
        gin = m.backward(x, jnp.ones_like(out))
        assert gin.shape == x.shape

    def test_temporal_convolution(self):
        m = nn.TemporalConvolution(8, 16, 3, 1)
        out = m.forward(rand(2, 10, 8))
        assert out.shape == (2, 8, 16)

    def test_volumetric_convolution(self):
        m = nn.VolumetricConvolution(2, 4, 3, 3, 3)
        out = m.forward(rand(1, 2, 8, 8, 8))
        assert out.shape == (1, 4, 6, 6, 6)

    def test_convolution_map(self):
        table = nn.SpatialConvolutionMap.one_to_one(3)
        m = nn.SpatialConvolutionMap(table, 3, 3)
        out = m.forward(rand(1, 3, 8, 8))
        assert out.shape == (1, 3, 6, 6)


class TestPooling:
    def test_max_pool_golden(self):
        m = nn.SpatialMaxPooling(2, 2, 2, 2)
        x = jnp.arange(16.0).reshape(1, 1, 4, 4)
        out = np.asarray(m.forward(x))[0, 0]
        np.testing.assert_allclose(out, [[5, 7], [13, 15]])

    def test_max_pool_ceil_mode(self):
        m = nn.SpatialMaxPooling(3, 3, 2, 2).ceil()
        out = m.forward(rand(1, 2, 6, 6))
        assert out.shape == (1, 2, 3, 3)
        m2 = nn.SpatialMaxPooling(3, 3, 2, 2)
        assert m2.forward(rand(1, 2, 6, 6)).shape == (1, 2, 2, 2)

    def test_avg_pool_golden(self):
        m = nn.SpatialAveragePooling(2, 2, 2, 2)
        x = jnp.arange(16.0).reshape(1, 1, 4, 4)
        out = np.asarray(m.forward(x))[0, 0]
        np.testing.assert_allclose(out, [[2.5, 4.5], [10.5, 12.5]])

    def test_global_avg_pool(self):
        m = nn.SpatialAveragePooling(0, 0, 1, 1, global_pooling=True)
        out = m.forward(rand(2, 3, 5, 5))
        assert out.shape == (2, 3, 1, 1)

    def test_max_pool_gradient_routes_to_max(self):
        m = nn.SpatialMaxPooling(2, 2, 2, 2)
        x = jnp.asarray([[[[1.0, 2.0], [3.0, 4.0]]]])
        out = m.forward(x)
        gin = np.asarray(m.backward(x, jnp.ones_like(out)))
        np.testing.assert_allclose(gin[0, 0], [[0, 0], [0, 1]])

    def test_volumetric_max_pool(self):
        m = nn.VolumetricMaxPooling(2, 2, 2)
        out = m.forward(rand(1, 2, 4, 4, 4))
        assert out.shape == (1, 2, 2, 2, 2)

    def test_roi_pooling(self):
        m = nn.RoiPooling(3, 3, 1.0)
        data = rand(2, 4, 16, 16)
        rois = jnp.asarray([[0, 0, 0, 7, 7], [1, 4, 4, 15, 15]], jnp.float32)
        out = m.forward([data, rois])
        assert out.shape == (2, 4, 3, 3)


class TestActivations:
    @pytest.mark.parametrize("layer,fn", [
        (nn.ReLU(), lambda x: np.maximum(x, 0)),
        (nn.ReLU6(), lambda x: np.clip(x, 0, 6)),
        (nn.Tanh(), np.tanh),
        (nn.Sigmoid(), lambda x: 1 / (1 + np.exp(-x))),
        (nn.Abs(), np.abs),
        (nn.Square(), lambda x: x * x),
        (nn.Exp(), np.exp),
        (nn.SoftSign(), lambda x: x / (1 + np.abs(x))),
        (nn.TanhShrink(), lambda x: x - np.tanh(x)),
        (nn.HardTanh(), lambda x: np.clip(x, -1, 1)),
        (nn.LeakyReLU(0.1), lambda x: np.where(x >= 0, x, 0.1 * x)),
        (nn.ELU(), lambda x: np.where(x > 0, x, np.exp(x) - 1)),
    ])
    def test_elementwise_golden(self, layer, fn):
        x = rand(3, 7)
        np.testing.assert_allclose(np.asarray(layer.forward(x)),
                                   fn(np.asarray(x)), rtol=1e-4, atol=1e-5)

    def test_logsoftmax_rows_sum_to_one(self):
        out = np.exp(np.asarray(nn.LogSoftMax().forward(rand(4, 9))))
        np.testing.assert_allclose(out.sum(-1), np.ones(4), rtol=1e-3)

    def test_softmin(self):
        x = rand(2, 5)
        out = np.asarray(nn.SoftMin().forward(x))
        exp = np.asarray(jax.nn.softmax(-x, axis=-1))
        np.testing.assert_allclose(out, exp, rtol=1e-5)

    def test_prelu_learnable(self):
        m = nn.PReLU(3)
        x = rand(2, 3, 4, 4)
        out = m.forward(x)
        assert out.shape == x.shape
        m.backward(x, jnp.ones_like(out))
        assert m.grads["weight"].shape == (3,)

    def test_dropout_train_vs_eval(self):
        m = nn.Dropout(0.5)
        x = jnp.ones((100, 100))
        out = m.forward(x)
        frac = float((np.asarray(out) == 0).mean())
        assert 0.3 < frac < 0.7  # ~half dropped
        kept = np.asarray(out)[np.asarray(out) != 0]
        np.testing.assert_allclose(kept, 2.0, rtol=1e-6)  # inverted scaling
        m.evaluate()
        np.testing.assert_allclose(np.asarray(m.forward(x)), 1.0)

    def test_rrelu_eval_deterministic(self):
        m = nn.RReLU().evaluate()
        x = -jnp.ones((4,))
        out = np.asarray(m.forward(x))
        np.testing.assert_allclose(out, -(1 / 8 + 1 / 3) / 2, rtol=1e-5)


class TestNormalization:
    def test_batchnorm_normalizes(self):
        m = nn.BatchNormalization(8)
        x = rand(32, 8) * 5 + 3
        out = np.asarray(m.forward(x))
        w = np.abs(np.asarray(m.params["weight"]))
        np.testing.assert_allclose(out.mean(0), 0, atol=1e-4)
        # affine scale: per-channel std equals |weight| (bias init is 0)
        np.testing.assert_allclose(out.std(0) / w, 1, atol=5e-2)

    def test_batchnorm_running_stats_updated(self):
        m = nn.SpatialBatchNormalization(4)
        x = rand(8, 4, 5, 5) + 2.0
        m.forward(x)
        rm = np.asarray(m.state["running_mean"])
        assert np.abs(rm).sum() > 0  # moved off zero

    def test_batchnorm_eval_uses_running_stats(self):
        m = nn.BatchNormalization(4)
        for _ in range(50):
            m.forward(rand(64, 4) + 1.0)
        m.evaluate()
        out = np.asarray(m.forward(jnp.ones((4, 4))))
        # running mean ~1, var ~1 -> output ~ (1-1)/1 * w + b ~ 0 modulo w
        assert np.abs(out.mean()) < 0.5

    def test_lrn_shape(self):
        m = nn.SpatialCrossMapLRN(5, 1.0, 0.75, 1.0)
        out = m.forward(rand(2, 8, 6, 6))
        assert out.shape == (2, 8, 6, 6)

    def test_normalize_l2(self):
        m = nn.Normalize(2)
        out = np.asarray(m.forward(rand(4, 10)))
        np.testing.assert_allclose(np.linalg.norm(out, axis=-1), 1.0, rtol=1e-4)

    def test_subtractive_normalization_zero_mean_constant(self):
        m = nn.SpatialSubtractiveNormalization(1)
        x = jnp.ones((1, 1, 16, 16)) * 7.0
        out = np.asarray(m.forward(x))
        np.testing.assert_allclose(out, 0.0, atol=1e-4)


class TestStructural:
    def test_reshape_batch_auto(self):
        m = nn.Reshape([12, 4])
        assert m.forward(rand(5, 48)).shape == (5, 12, 4)
        assert m.forward(rand(48)).shape == (12, 4)

    def test_view_infer(self):
        m = nn.View(-1, 6)
        assert m.forward(rand(3, 12)).shape == (6, 6)

    def test_select_narrow(self):
        x = rand(4, 6, 5)
        assert nn.Select(2, 3).forward(x).shape == (4, 5)
        np.testing.assert_allclose(np.asarray(nn.Select(2, 3).forward(x)),
                                   np.asarray(x)[:, 2, :])
        out = nn.Narrow(2, 2, 3).forward(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x)[:, 1:4])

    def test_squeeze_unsqueeze_transpose(self):
        x = rand(3, 1, 5)
        assert nn.Squeeze(2).forward(x).shape == (3, 5)
        assert nn.Unsqueeze(2).forward(rand(3, 5)).shape == (3, 1, 5)
        assert nn.Transpose([(1, 2)]).forward(rand(3, 5)).shape == (5, 3)

    def test_sum_mean_max_min(self):
        x = rand(4, 6)
        np.testing.assert_allclose(np.asarray(nn.Sum(2).forward(x)),
                                   np.asarray(x).sum(1), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(nn.Mean(1).forward(x)),
                                   np.asarray(x).mean(0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(nn.Max(2).forward(x)),
                                   np.asarray(x).max(1), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(nn.Min(2).forward(x)),
                                   np.asarray(x).min(1), rtol=1e-5)

    def test_replicate(self):
        # nDim=1: (4,5) is batch of 1-D samples -> new dim after batch
        assert nn.Replicate(3, 1, 1).forward(rand(4, 5)).shape == (4, 3, 5)
        # unbatched: insert at dim 1
        assert nn.Replicate(3, 1).forward(rand(4, 5)).shape == (3, 4, 5)

    def test_padding(self):
        out = nn.Padding(2, 2, 2).forward(rand(3, 4))
        assert out.shape == (3, 6)
        out = nn.Padding(2, -2, 2).forward(rand(3, 4))
        assert out.shape == (3, 6)

    def test_spatial_zero_padding(self):
        assert nn.SpatialZeroPadding(1, 2, 3, 4).forward(
            rand(1, 2, 5, 5)).shape == (1, 2, 12, 8)

    def test_spatial_zero_padding_negative_crops(self):
        """Negative pads crop the matching border (reference
        ``nn/SpatialZeroPadding.scala`` narrows the input)."""
        x = rand(1, 2, 5, 6)
        out = nn.SpatialZeroPadding(-1, -2, -1, 0).forward(x)
        assert out.shape == (1, 2, 4, 3)
        np.testing.assert_array_equal(out, x[:, :, 1:, 1:-2])
        # mixed: pad left, crop top
        out = nn.SpatialZeroPadding(1, 0, -2, 0).forward(x)
        assert out.shape == (1, 2, 3, 7)
        np.testing.assert_array_equal(out[:, :, :, 1:], x[:, :, 2:, :])
        np.testing.assert_array_equal(out[:, :, :, 0], 0)
        with pytest.raises(ValueError, match="too small"):
            nn.SpatialZeroPadding(-3, -3).forward(rand(1, 2, 5, 5))

    def test_mm_mv_dot(self):
        a, b = rand(2, 3, 4), rand(2, 4, 5)
        assert nn.MM().forward([a, b]).shape == (2, 3, 5)
        m, v = rand(2, 3, 4), rand(2, 4)
        assert nn.MV().forward([m, v]).shape == (2, 3)
        x, y = rand(5, 7), rand(5, 7)
        np.testing.assert_allclose(np.asarray(nn.DotProduct().forward([x, y])),
                                   (np.asarray(x) * np.asarray(y)).sum(-1),
                                   rtol=1e-5)

    def test_gradient_reversal(self):
        m = nn.GradientReversal(2.0)
        x = rand(3, 3)
        out = m.forward(x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))
        gin = m.backward(x, jnp.ones_like(x))
        np.testing.assert_allclose(np.asarray(gin), -2.0)

    def test_pack_reverse(self):
        xs = [rand(3, 4), rand(3, 4)]
        assert nn.Pack(2).forward(xs).shape == (3, 2, 4)
        x = rand(5, 3)
        np.testing.assert_allclose(np.asarray(nn.Reverse(1).forward(x)),
                                   np.asarray(x)[::-1])


class TestTableOps:
    def test_join_split_roundtrip(self):
        x = rand(4, 3, 5)
        parts = nn.SplitTable(2).forward(x)
        assert len(parts) == 3 and parts[0].shape == (4, 5)
        packed = nn.Pack(2).forward(parts)
        np.testing.assert_allclose(np.asarray(packed), np.asarray(x))

    def test_select_narrow_flatten_table(self):
        xs = [rand(2), rand(3), rand(4)]
        assert nn.SelectTable(2).forward(xs).shape == (3,)
        assert nn.SelectTable(-1).forward(xs).shape == (4,)
        assert len(nn.NarrowTable(2, 2).forward(xs)) == 2
        nested = [rand(2), [rand(3), [rand(4)]]]
        assert len(nn.FlattenTable().forward(nested)) == 3

    def test_arith_tables(self):
        a, b = rand(3, 4), rand(3, 4)
        an, bn = np.asarray(a), np.asarray(b)
        np.testing.assert_allclose(np.asarray(nn.CAddTable().forward([a, b])),
                                   an + bn, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(nn.CSubTable().forward([a, b])),
                                   an - bn, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(nn.CMulTable().forward([a, b])),
                                   an * bn, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(nn.CMaxTable().forward([a, b])),
                                   np.maximum(an, bn), rtol=1e-5)

    def test_mixture_table(self):
        gates = jnp.asarray([[0.3, 0.7], [0.5, 0.5]])
        e1, e2 = rand(2, 4), rand(2, 4)
        out = np.asarray(nn.MixtureTable().forward([gates, [e1, e2]]))
        exp = (np.asarray(gates)[:, 0:1] * np.asarray(e1)
               + np.asarray(gates)[:, 1:2] * np.asarray(e2))
        np.testing.assert_allclose(out, exp, rtol=1e-5)

    def test_distances(self):
        a, b = rand(3, 4), rand(3, 4)
        d = np.asarray(nn.PairwiseDistance(2).forward([a, b]))
        np.testing.assert_allclose(
            d, np.linalg.norm(np.asarray(a) - np.asarray(b), axis=-1), rtol=1e-4)
        c = np.asarray(nn.CosineDistance().forward([a, b]))
        assert c.shape == (3,)


class TestLinearFamily:
    def test_linear_golden(self):
        m = nn.Linear(3, 2)
        m.params = {"weight": jnp.asarray([[1., 0.], [0., 1.], [1., 1.]]),
                    "bias": jnp.asarray([0.5, -0.5])}
        out = np.asarray(m.forward(jnp.asarray([[1., 2., 3.]])))
        np.testing.assert_allclose(out, [[1 + 3 + 0.5, 2 + 3 - 0.5]])

    def test_lookup_table_one_based(self):
        m = nn.LookupTable(10, 4)
        idx = jnp.asarray([[1., 10.], [3., 3.]])
        out = m.forward(idx)
        assert out.shape == (2, 2, 4)
        np.testing.assert_allclose(np.asarray(out[0, 0]),
                                   np.asarray(m.params["weight"][0]))
        np.testing.assert_allclose(np.asarray(out[0, 1]),
                                   np.asarray(m.params["weight"][9]))

    def test_bilinear(self):
        m = nn.Bilinear(3, 4, 2)
        out = m.forward([rand(5, 3), rand(5, 4)])
        assert out.shape == (5, 2)

    def test_cmul_cadd(self):
        x = rand(2, 3)
        m = nn.CMul([3])
        np.testing.assert_allclose(np.asarray(m.forward(x)),
                                   np.asarray(x) * np.asarray(m.params["weight"]),
                                   rtol=1e-5)
        m2 = nn.CAdd([3])
        np.testing.assert_allclose(np.asarray(m2.forward(x)),
                                   np.asarray(x) + np.asarray(m2.params["bias"]),
                                   rtol=1e-5)

    def test_euclidean_cosine(self):
        assert nn.Euclidean(4, 6).forward(rand(2, 4)).shape == (2, 6)
        out = np.asarray(nn.Cosine(4, 6).forward(rand(2, 4)))
        assert out.shape == (2, 6) and np.all(np.abs(out) <= 1 + 1e-5)


class TestTfHelperOps:
    """reference nn/tf/* helper ops."""

    def test_const(self):
        m = nn.Const(np.arange(6).reshape(2, 3))
        out = m.forward(np.zeros(5))
        assert np.asarray(out).shape == (2, 3)

    def test_fill(self):
        m = nn.Fill()
        out = m.forward([np.array([2, 3]), np.array(7.0)])
        np.testing.assert_array_equal(np.asarray(out), np.full((2, 3), 7.0))

    def test_shape(self):
        m = nn.Shape()
        out = m.forward(np.zeros((3, 5, 7)))
        np.testing.assert_array_equal(np.asarray(out), [3, 5, 7])

    def test_split_and_select(self):
        x = np.arange(12, dtype=np.float32).reshape(2, 6)
        m = nn.SplitAndSelect(2, index=2, num_split=3)
        out = np.asarray(m.forward(x))
        np.testing.assert_array_equal(out, x[:, 2:4])
        m2 = nn.SplitAndSelect(-1, index=1, num_split=2)
        np.testing.assert_array_equal(np.asarray(m2.forward(x)), x[:, :3])

    def test_stride_slice(self):
        x = np.arange(24, dtype=np.float32).reshape(4, 6)
        m = nn.StrideSlice([(1, 2, 4), (2, 1, 3)])
        out = np.asarray(m.forward(x))
        np.testing.assert_array_equal(out, x[1:3, 0:2])
