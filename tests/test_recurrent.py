"""Recurrent stack tests.

Strategy mirrors the reference (SURVEY §4): numerical parity against a
reference implementation (torch.nn on CPU plays the role Torch7 played for
the Scala tests), plus shape/gradient/scan-semantics checks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from bigdl_tpu import nn


def _np(x):
    return np.asarray(x)


class TestRnnCell:
    def test_shapes_and_manual_step(self):
        cell = nn.RnnCell(4, 3)
        rec = nn.Recurrent().add(cell)
        x = np.random.randn(2, 5, 4).astype(np.float32)
        out = rec.forward(jnp.asarray(x))
        assert out.shape == (2, 5, 3)
        # manual unroll must agree with the scan
        p = rec.params[0]
        h = np.zeros((2, 3), np.float32)
        for t in range(5):
            h = np.tanh(x[:, t] @ _np(p["w_ih"]) + _np(p["bias"])
                        + h @ _np(p["w_hh"]))
            np.testing.assert_allclose(_np(out[:, t]), h, atol=1e-5)


class TestLSTMTorchParity:
    def test_lstm_matches_torch(self):
        D, H, B, T = 4, 6, 3, 7
        cell = nn.LSTM(D, H)
        rec = nn.Recurrent().add(cell)
        rec.reset()
        p = rec.params[0]

        tl = torch.nn.LSTM(D, H, batch_first=True)
        # torch gate order (i, f, g, o) matches ours; torch stores (4H, D)
        with torch.no_grad():
            tl.weight_ih_l0.copy_(torch.from_numpy(_np(p["w_ih"]).T))
            tl.weight_hh_l0.copy_(torch.from_numpy(_np(p["w_hh"]).T))
            tl.bias_ih_l0.copy_(torch.from_numpy(_np(p["bias"])))
            tl.bias_hh_l0.zero_()

        x = np.random.randn(B, T, D).astype(np.float32)
        ours = _np(rec.forward(jnp.asarray(x)))
        theirs = tl(torch.from_numpy(x))[0].detach().numpy()
        np.testing.assert_allclose(ours, theirs, atol=1e-5)

    def test_gru_matches_torch(self):
        D, H, B, T = 5, 4, 2, 6
        cell = nn.GRU(D, H)
        rec = nn.Recurrent().add(cell)
        rec.reset()
        p = rec.params[0]

        tl = torch.nn.GRU(D, H, batch_first=True)
        with torch.no_grad():
            tl.weight_ih_l0.copy_(torch.from_numpy(_np(p["w_ih"]).T))
            tl.weight_hh_l0.copy_(torch.from_numpy(_np(p["w_hh"]).T))
            tl.bias_ih_l0.copy_(torch.from_numpy(_np(p["b_ih"])))
            tl.bias_hh_l0.copy_(torch.from_numpy(_np(p["b_hh"])))

        x = np.random.randn(B, T, D).astype(np.float32)
        ours = _np(rec.forward(jnp.asarray(x)))
        theirs = tl(torch.from_numpy(x))[0].detach().numpy()
        np.testing.assert_allclose(ours, theirs, atol=1e-5)


class TestLSTMPeephole:
    def test_forward_backward(self):
        rec = nn.Recurrent().add(nn.LSTMPeephole(3, 4))
        x = jnp.asarray(np.random.randn(2, 5, 3).astype(np.float32))
        out = rec.forward(x)
        assert out.shape == (2, 5, 4)
        gin = rec.backward(x, jnp.ones_like(out))
        assert gin.shape == x.shape
        g = rec.grads[0]
        assert all(np.isfinite(_np(v)).all() for v in g.values())
        assert float(jnp.abs(g["w_ci"]).sum()) > 0  # peepholes get gradient


class TestConvLSTM:
    def test_shapes(self):
        rec = nn.Recurrent().add(nn.ConvLSTMPeephole(2, 3, 3, 3))
        x = jnp.asarray(np.random.randn(2, 4, 2, 8, 8).astype(np.float32))
        out = rec.forward(x)
        assert out.shape == (2, 4, 3, 8, 8)

    def test_no_peephole(self):
        rec = nn.Recurrent().add(
            nn.ConvLSTMPeephole(2, 3, with_peephole=False))
        x = jnp.asarray(np.random.randn(1, 3, 2, 6, 6).astype(np.float32))
        assert rec.forward(x).shape == (1, 3, 3, 6, 6)


class TestBiRecurrent:
    def test_add_merge(self):
        bi = nn.BiRecurrent(merge="add").add(nn.RnnCell(4, 3))
        x = jnp.asarray(np.random.randn(2, 5, 4).astype(np.float32))
        assert bi.forward(x).shape == (2, 5, 3)

    def test_concat_merge(self):
        bi = nn.BiRecurrent(merge="concat").add(nn.LSTM(4, 3))
        x = jnp.asarray(np.random.randn(2, 5, 4).astype(np.float32))
        assert bi.forward(x).shape == (2, 5, 6)

    def test_reverse_direction_differs(self):
        bi = nn.BiRecurrent(merge="concat").add(nn.RnnCell(3, 3))
        x = jnp.asarray(np.random.randn(1, 4, 3).astype(np.float32))
        out = _np(bi.forward(x))
        fwd, bwd = out[..., :3], out[..., 3:]
        assert not np.allclose(fwd, bwd)


class TestTimeDistributed:
    def test_linear_per_timestep(self):
        inner = nn.Linear(4, 2)
        td = nn.TimeDistributed(inner)
        x = np.random.randn(3, 5, 4).astype(np.float32)
        out = td.forward(jnp.asarray(x))
        assert out.shape == (3, 5, 2)
        p = td.params[0]
        want = x @ _np(p["weight"]) + _np(p["bias"])
        np.testing.assert_allclose(_np(out), want, atol=1e-5)


class TestCellStandalone:
    def test_cell_table_semantics(self):
        cell = nn.LSTM(4, 3)
        cell.reset()
        x = jnp.asarray(np.random.randn(2, 4).astype(np.float32))
        h0 = cell.init_hidden(cell.params, (2,))
        (out, h1), _ = cell.apply(cell.params, [x, h0], {})
        assert out.shape == (2, 3)
        assert h1[0].shape == (2, 3) and h1[1].shape == (2, 3)


class TestRecurrentTraining:
    @pytest.mark.slow
    def test_char_lm_loss_decreases(self):
        """Tiny SimpleRNN-style LM learns a repeating pattern
        (reference ``models/rnn`` config)."""
        V, H, B, T = 5, 16, 8, 6
        model = nn.Sequential()
        model.add(nn.Recurrent().add(nn.RnnCell(V, H)))
        model.add(nn.TimeDistributed(nn.Linear(H, V)))
        # pin the init: default reset() keys off auto-generated module
        # names (a global counter), so the starting point — and whether
        # 30 steps reach the 0.5x loss bar — would depend on test order
        model.reset(jax.random.PRNGKey(42))
        crit = nn.TimeDistributedCriterion(nn.CrossEntropyCriterion())

        seq = np.arange(T * B).reshape(B, T) % V
        x = jax.nn.one_hot(jnp.asarray(seq), V)
        y = jnp.asarray((seq + 1) % V)

        model.training()
        losses = []
        for _ in range(30):
            out = model.forward(x)
            losses.append(float(crit.forward(out, y)))
            gout = crit.backward(out, y)
            model.zero_grad_parameters()
            model.backward(x, gout)
            model.update_parameters(0.5)
        assert losses[-1] < losses[0] * 0.5


class TestBinaryTreeLSTM:
    def test_topological_composition(self):
        # tree over 3 leaves: node3=(0,1), node4=(3,2)
        D, H = 4, 5
        m = nn.BinaryTreeLSTM(D, H)
        emb = jnp.asarray(np.random.randn(2, 3, D).astype(np.float32))
        tree = jnp.asarray(np.array([[[0, 1], [3, 2]]] * 2, np.int32))
        out = m.forward([emb, tree])
        assert out.shape == (2, 2, H)
        assert np.isfinite(_np(out)).all()

    def test_padded_nodes_masked(self):
        D, H = 3, 4
        m = nn.BinaryTreeLSTM(D, H)
        emb = jnp.asarray(np.random.randn(1, 2, D).astype(np.float32))
        tree = jnp.asarray(np.array([[[0, 1], [-1, -1]]], np.int32))
        out = _np(m.forward([emb, tree]))
        assert np.abs(out[0, 1]).sum() == 0  # padded node contributes zeros
