"""Functional semantics for layers with no direct coverage elsewhere.

These are the zoo entries a coverage audit (round 5) found constructed by
no other test: elementwise/constant maps, binary table ops, gather/mask
ops, stochastic regularizers, the spatial normalization family, shared /
transposed conv variants, and the Fast-RCNN-era criterions.  Assertions
are hand-computed/numpy golden values (SURVEY §4.1 strategy), torch where
torch has the same op.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn


def rand(*shape):
    return jnp.asarray(np.random.RandomState(
        sum(shape) + len(shape)).randn(*shape).astype(np.float32))


class TestElementwiseAndConstants:
    def test_clamp_negative_and_sqrt_square(self):
        x = rand(3, 4)
        np.testing.assert_allclose(nn.Clamp(-0.5, 0.5).forward(x),
                                   np.clip(np.asarray(x), -0.5, 0.5))
        pos = jnp.abs(x) + 0.1
        np.testing.assert_allclose(nn.Sqrt().forward(pos),
                                   np.sqrt(np.asarray(pos)), rtol=1e-6)
        np.testing.assert_allclose(nn.Square().forward(x),
                                   np.asarray(x) ** 2, rtol=1e-6)

    def test_add_mul_constants_and_negative(self):
        x = rand(2, 3)
        np.testing.assert_allclose(nn.AddConstant(2.5).forward(x),
                                   np.asarray(x) + 2.5, rtol=1e-6)
        np.testing.assert_allclose(nn.MulConstant(-3.0).forward(x),
                                   np.asarray(x) * -3.0, rtol=1e-6)
        np.testing.assert_allclose(nn.Negative().forward(x),
                                   -np.asarray(x))

    def test_mul_learnable_scalar_trains(self):
        m = nn.Mul()
        x = rand(4, 4)
        out = m.forward(x)
        w = float(m.params["weight"])
        np.testing.assert_allclose(np.asarray(out), np.asarray(x) * w,
                                   rtol=1e-6)
        m.backward(x, jnp.ones_like(x))
        np.testing.assert_allclose(float(m.grads["weight"]),
                                   float(jnp.sum(x)), rtol=1e-5)

    def test_echo_and_contiguous_are_identity(self):
        x = rand(2, 3)
        np.testing.assert_array_equal(nn.Contiguous().forward(x), x)
        np.testing.assert_array_equal(nn.Echo().forward(x), x)


class TestTableOps:
    def test_binary_table_ops(self):
        rng = np.random.RandomState(42)
        a = jnp.asarray(rng.randn(3, 4).astype(np.float32))
        b = jnp.asarray(rng.randn(3, 4).astype(np.float32))
        b = jnp.where(jnp.abs(b) < 0.1, 0.5, b)   # keep the divide tame
        assert bool(jnp.any(a > b)) and bool(jnp.any(b > a))
        for mod, want in [
                (nn.CDivTable(), np.asarray(a) / np.asarray(b)),
                (nn.CMaxTable(), np.maximum(np.asarray(a), np.asarray(b))),
                (nn.CMinTable(), np.minimum(np.asarray(a), np.asarray(b)))]:
            np.testing.assert_allclose(mod.forward([a, b]), want, rtol=1e-6)

    def test_map_table_shares_the_one_child(self):
        m = nn.MapTable(nn.Linear(4, 2))
        a, b = rand(3, 4), rand(5, 4)
        out = m.forward([a, b])
        assert out[0].shape == (3, 2) and out[1].shape == (5, 2)
        # same params applied to both elements
        lin = m.children[0]
        w, bias = lin.params["weight"], lin.params["bias"]   # (in, out)
        np.testing.assert_allclose(np.asarray(out[1]),
                                   np.asarray(b @ w + bias), rtol=1e-5)


class TestGatherMask:
    def test_index_gathers_1based(self):
        x = rand(4, 5)
        idx = jnp.asarray([3.0, 1.0])
        out = nn.Index(1).forward([x, idx])
        np.testing.assert_array_equal(out, np.asarray(x)[[2, 0]])
        out2 = nn.Index(2).forward([x, idx])
        np.testing.assert_array_equal(out2, np.asarray(x)[:, [2, 0]])

    def test_masked_select_packs_front(self):
        x = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
        mask = jnp.asarray([[1.0, 0.0], [0.0, 1.0]])
        out = np.asarray(nn.MaskedSelect().forward([x, mask]))
        np.testing.assert_array_equal(out, [1.0, 4.0, 0.0, 0.0])


class TestStochasticRegularizers:
    def test_gaussian_dropout_stats_and_eval_identity(self):
        m = nn.GaussianDropout(0.5)   # stddev = sqrt(0.5/0.5) = 1
        x = jnp.ones((200, 200))
        out = np.asarray(m.forward(x))
        assert abs(out.mean() - 1.0) < 0.02
        assert abs(out.std() - 1.0) < 0.02
        m.evaluate()
        np.testing.assert_array_equal(np.asarray(m.forward(x)), 1.0)

    def test_gaussian_noise_stats_and_eval_identity(self):
        m = nn.GaussianNoise(0.3)
        x = jnp.zeros((200, 200))
        out = np.asarray(m.forward(x))
        assert abs(out.mean()) < 0.02 and abs(out.std() - 0.3) < 0.02
        m.evaluate()
        np.testing.assert_array_equal(np.asarray(m.forward(x)), 0.0)

    def test_l1penalty_identity_forward_sparsity_grad(self):
        m = nn.L1Penalty(l1weight=0.1)
        m.training()
        x = jnp.asarray([[1.5, -2.0, 0.5]])
        np.testing.assert_array_equal(m.forward(x), x)

        def f(z):
            out, _ = m.apply({}, z, {}, training=True)
            return jnp.sum(out * 3.0)

        g = np.asarray(jax.grad(f)(x))
        # upstream grad 3.0 plus l1weight * sign(x)
        np.testing.assert_allclose(g, [[3.1, 2.9, 3.1]], rtol=1e-6)


class TestSpatialNormalizationFamily:
    def test_subtractive_kills_constant_input(self):
        m = nn.SpatialSubtractiveNormalization(2)
        x = jnp.ones((1, 2, 12, 12)) * 7.0
        out = np.asarray(m.forward(x))
        np.testing.assert_allclose(out, 0.0, atol=1e-4)

    def test_subtractive_bf16_input(self):
        m = nn.SpatialSubtractiveNormalization(2)
        x = jnp.ones((1, 2, 8, 8), jnp.bfloat16) * 3.0
        out = m.forward(x)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(out, np.float32), 0.0,
                                   atol=0.05)

    def test_subtractive_mean_is_cross_channel(self):
        """The reference computes ONE mean map across all input planes
        (kernel summed over channels / nInputPlane) and subtracts it from
        every plane: channels [2, 6] see mean 4 -> [-2, +2]."""
        m = nn.SpatialSubtractiveNormalization(2)
        x = jnp.stack([jnp.full((12, 12), 2.0),
                       jnp.full((12, 12), 6.0)])[None]
        out = np.asarray(m.forward(x))
        np.testing.assert_allclose(out[0, 0], -2.0, atol=1e-4)
        np.testing.assert_allclose(out[0, 1], 2.0, atol=1e-4)

    def test_divisive_normalizes_scale(self):
        m = nn.SpatialDivisiveNormalization(1)
        x = rand(1, 1, 16, 16)
        out_small = np.asarray(m.forward(x))
        out_big = np.asarray(m.forward(x * 100.0))
        # scale-invariant up to the mean-std floor: both land near unit std
        np.testing.assert_allclose(out_small, out_big, rtol=1e-3)

    def test_contrastive_composes_sub_then_div(self):
        x = rand(1, 1, 10, 10)
        want = nn.SpatialDivisiveNormalization(1).forward(
            nn.SpatialSubtractiveNormalization(1).forward(x))
        got = nn.SpatialContrastiveNormalization(1).forward(x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5)

    def test_within_channel_lrn_golden(self):
        size, alpha, beta = 3, 1.0, 0.75
        x = rand(1, 2, 5, 5)
        xn = np.asarray(x)
        sq = xn * xn
        padded = np.pad(sq, ((0, 0), (0, 0), (1, 1), (1, 1)))
        window = np.zeros_like(xn)
        for i in range(size):
            for j in range(size):
                window += padded[:, :, i:i + 5, j:j + 5]
        want = xn / (1.0 + alpha / (size * size) * window) ** beta
        got = nn.SpatialWithinChannelLRN(size, alpha, beta).forward(x)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)


class TestConvVariants:
    def test_share_convolution_matches_spatial_convolution(self):
        share = nn.SpatialShareConvolution(3, 8, 3, 3, 2, 2, 1, 1)
        plain = nn.SpatialConvolution(3, 8, 3, 3, 2, 2, 1, 1)
        share._ensure_init()
        plain.params = share.params
        x = rand(2, 3, 10, 10)
        np.testing.assert_array_equal(share.forward(x), plain.forward(x))

    def test_volumetric_full_convolution_vs_torch(self):
        import torch
        import torch.nn.functional as F
        m = nn.VolumetricFullConvolution(2, 3, 2, 2, 2, d_t=2, d_w=2, d_h=2)
        m._ensure_init()
        x = rand(1, 2, 3, 4, 4)
        got = np.asarray(m.forward(x))
        # our kernel layout (t, h, w, in, out) -> torch (in, out, t, h, w)
        w = np.transpose(np.asarray(m.params["weight"]), (3, 4, 0, 1, 2))
        want = F.conv_transpose3d(
            torch.from_numpy(np.asarray(x)), torch.from_numpy(w),
            torch.from_numpy(np.asarray(m.params["bias"])),
            stride=2).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_conv_lstm_peephole_3d_forward(self):
        rec = nn.Recurrent().add(nn.ConvLSTMPeephole3D(2, 4))
        x = rand(1, 3, 2, 4, 4, 4)   # (B, T, C, D, H, W)
        out = np.asarray(rec.forward(x))
        assert out.shape == (1, 3, 4, 4, 4, 4)
        assert np.all(np.isfinite(out))


class TestRcnnEraCriterions:
    def test_l1_hinge_embedding(self):
        a, b = jnp.asarray([1.0, 2.0]), jnp.asarray([0.5, 0.0])
        d = 2.5
        crit = nn.L1HingeEmbeddingCriterion(margin=3.0)
        np.testing.assert_allclose(float(crit.apply([a, b], 1.0)), d)
        np.testing.assert_allclose(float(crit.apply([a, b], -1.0)), 3.0 - d)

    def test_smooth_l1_with_weights(self):
        x = jnp.asarray([0.2, 3.0])
        t = jnp.asarray([0.0, 0.0])
        inw = jnp.asarray([1.0, 1.0])
        outw = jnp.asarray([2.0, 0.5])
        crit = nn.SmoothL1CriterionWithWeights(sigma=1.0, num=2)
        want = (2.0 * 0.5 * 0.2 ** 2 + 0.5 * (3.0 - 0.5)) / 2
        np.testing.assert_allclose(float(crit.apply(x, [t, inw, outw])),
                                   want, rtol=1e-6)


class TestInitMethods:
    def test_const_ones_zeros(self):
        from bigdl_tpu.nn import init
        key = jax.random.PRNGKey(0)
        np.testing.assert_array_equal(init.Zeros()(key, (3, 4)), 0.0)
        np.testing.assert_array_equal(init.Ones()(key, (3, 4)), 1.0)
        np.testing.assert_array_equal(init.ConstInitMethod(0.25)(key, (5,)),
                                      0.25)

    def test_statistical_inits(self):
        from bigdl_tpu.nn import init
        key = jax.random.PRNGKey(1)
        w = np.asarray(init.RandomUniform()(key, (400, 100), fan_in=400))
        bound = 1.0 / np.sqrt(400)
        assert w.min() >= -bound and w.max() <= bound
        w = np.asarray(init.Xavier()(key, (400, 100),
                                     fan_in=400, fan_out=100))
        b = np.sqrt(6.0 / 500)
        assert w.min() >= -b and w.max() <= b
        assert abs(w.std() - b / np.sqrt(3)) < 0.01   # uniform stddev
        w = np.asarray(init.MsraFiller()(key, (400, 100), fan_in=400))
        assert abs(w.std() - np.sqrt(2.0 / 400)) < 0.005
        w = np.asarray(init.RandomNormal(1.0, 0.5)(key, (400, 100)))
        assert abs(w.mean() - 1.0) < 0.01 and abs(w.std() - 0.5) < 0.01

    def test_bilinear_filler_interpolates(self):
        """The factor-2 kernel is the Caffe bilinear outer([.25 .75 .75
        .25]); a stride-2 SpatialFullConvolution with it preserves a
        constant image in the interior (each output pixel's weights sum
        to 1 away from the borders)."""
        from bigdl_tpu.nn import init
        k = np.asarray(init.BilinearFiller()(jax.random.PRNGKey(0),
                                             (4, 4, 1, 1)))[:, :, 0, 0]
        want1d = np.array([0.25, 0.75, 0.75, 0.25])
        np.testing.assert_allclose(k, np.outer(want1d, want1d), rtol=1e-6)
        m = nn.SpatialFullConvolution(1, 1, 4, 4, 2, 2, 1, 1, no_bias=True)
        m.set_init_method(weight_init=init.BilinearFiller())
        out = np.asarray(m.forward(jnp.ones((1, 1, 5, 5))))
        assert out.shape == (1, 1, 10, 10)
        np.testing.assert_allclose(out[0, 0, 1:-1, 1:-1], 1.0, rtol=1e-5)

    def test_set_init_method_on_linear_and_conv(self):
        from bigdl_tpu.nn import init
        lin = nn.Linear(4, 3).set_init_method(
            weight_init=init.ConstInitMethod(2.0),
            bias_init=init.Zeros())
        lin._ensure_init()
        np.testing.assert_array_equal(lin.params["weight"], 2.0)
        np.testing.assert_array_equal(lin.params["bias"], 0.0)
        conv = nn.SpatialConvolution(2, 4, 3, 3).set_init_method(
            weight_init=init.Ones())
        conv._ensure_init()
        np.testing.assert_array_equal(conv.params["weight"], 1.0)


class TestScaleLayer:
    def test_scale_cmul_cadd(self):
        m = nn.Scale((3,), init_weight=[1.0, 2.0, 3.0],
                     init_bias=[0.5, 0.0, -0.5])
        x = rand(2, 3)
        want = np.asarray(x) * [1.0, 2.0, 3.0] + [0.5, 0.0, -0.5]
        np.testing.assert_allclose(np.asarray(m.forward(x)), want, rtol=1e-6)
