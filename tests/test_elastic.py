"""Elastic, preemption-tolerant training (ISSUE 6 acceptance proofs).

The claims under test:

- **Topology-elastic restore.**  A run checkpointed on N devices resumes
  on M (N != M, both directions, dp and dp x tp meshes) and the restore +
  reshard is LOSSLESS: bit-exact weight and optimizer-slot parity against
  a control that injects the same snapshot state directly into a fresh
  M-device trainer.  Against a fully uninterrupted M-device run the
  elastic trajectory agrees to reduction-association tolerance — the
  partition-count-invariant ``ShardedDataSet`` order makes that
  comparison meaningful at all (same batches, different psum grouping).
- **Manifest schema hardening.**  Snapshots record their saving topology
  and a schema version; unknown-schema and (reshard-disabled)
  topology-mismatched snapshots are rejected with structured errors
  naming the mismatch; pre-schema-2 snapshots restore same-topology.
- **Preemption.**  A chaos-injected (and a real-SIGTERM) preemption
  drains gracefully — final verified snapshot + resumable marker — and
  the resumed run reaches bit-exact weight parity with an uninterrupted
  one (shuffle-round replay makes the epoch streams identical).
- **Hung-step watchdog.**  Fires once per stall with cooldown semantics,
  is compile-warmup exempt, and end-to-end aborts a chaos-stalled step
  into a restore instead of hanging the run.

Parity tests use full-batch sharded datasets (one iteration per epoch)
so trajectories are bit-comparable — the protocol of
``test_chaos.TestChaosKill`` extended across topology changes.
"""

import os
import shutil
import signal
import time

import jax
import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu import telemetry
from bigdl_tpu.engine import Engine
from bigdl_tpu.dataset import SampleToMiniBatch
from bigdl_tpu.dataset.dataset import ShardedDataSet
from bigdl_tpu.dataset.datasets import synthetic_separable
from bigdl_tpu.nn.module import Container
from bigdl_tpu.parallel import DistriOptimizer
from bigdl_tpu.parallel.tensor_parallel import column_parallel, row_parallel
from bigdl_tpu.utils import chaos, config, elastic
from bigdl_tpu.utils.checkpoint_manager import (CheckpointManager,
                                                SnapshotSchemaError)

SAMPLES = synthetic_separable(64, 4, n_classes=2, seed=3)


def _mlp(seed=11, tp=False):
    up, down = nn.Linear(4, 16), nn.Linear(16, 2)
    if tp:
        column_parallel(up)
        row_parallel(down)
    m = (nn.Sequential().add(up).add(nn.Tanh()).add(down)
         .add(nn.LogSoftMax()))
    m.reset(jax.random.PRNGKey(seed))
    return m


def _trainer(mesh_shape, axes, epochs, ckpt=None, seed=11, tp=False,
             opt_method=None):
    """DistriOptimizer over the FIRST prod(mesh_shape) devices — how a
    shrunken (or regrown) slice looks to a resuming process — with the
    full-batch sharded dataset (data partitions == data axis size)."""
    n_dev = int(np.prod(mesh_shape))
    mesh = Engine.create_mesh(mesh_shape, axes,
                              devices=jax.devices()[:n_dev])
    parts = mesh.shape["data"]
    m = _mlp(seed=seed, tp=tp)
    ds = ShardedDataSet(SAMPLES, parts).transform(
        SampleToMiniBatch(64, parts))
    o = DistriOptimizer(m, ds, nn.ClassNLLCriterion(), mesh=mesh)
    o.set_optim_method(opt_method or
                       optim.SGD(learning_rate=0.3, momentum=0.9))
    o.set_end_when(optim.max_epoch(epochs))
    if ckpt is not None:
        o.set_checkpoint(str(ckpt), optim.every_epoch())
    return o, m


def _weights(model):
    w, _ = model.get_parameters()
    return np.asarray(w)


def _slot_leaves(o):
    return [np.asarray(x)
            for x in jax.tree_util.tree_leaves(o.optim_method._slots)]


def _inject_snapshot(o, model, snapshot_dir):
    """The control arm: load the snapshot and push its state straight
    into a fresh trainer — no manifest, no topology check, no reshard
    machinery.  Elastic restore must be bit-identical to this."""
    mdl, opt_loaded, n = CheckpointManager(str(snapshot_dir)).load_latest()
    model.params = mdl.params
    model.state = mdl.state
    if isinstance(model, Container):
        model._adopt()
    o.optim_method.state = opt_loaded.state
    o.optim_method.set_slots(opt_loaded._slots)
    return n


@pytest.fixture(autouse=True)
def _elastic_env():
    """Zero retry sleeps; disarmed chaos, cleared preemption flag, and
    default config after every test."""
    config.set_property("bigdl.failure.retryTimeInterval", 0.0)
    yield
    chaos.uninstall()
    elastic.clear_preemption()
    for key in ("bigdl.failure.retryTimeInterval",
                "bigdl.failure.retryTimes",
                "bigdl.chaos.preemptAt", "bigdl.chaos.stallStepAt",
                "bigdl.chaos.topologyChangeAt", "bigdl.chaos.failStepAt",
                "bigdl.elastic.reshardOnRestore",
                "bigdl.elastic.handleSignals", "bigdl.elastic.gracePeriod",
                "bigdl.watchdog.stallFactor", "bigdl.watchdog.warmupSteps",
                "bigdl.watchdog.pollInterval",
                "bigdl.watchdog.cooldownSteps"):
        config.clear_property(key)


class TestElasticRestore:
    """Checkpoint on N devices, resume on M — both directions."""

    @pytest.mark.parametrize("n,m", [(4, 2), (2, 4)])
    def test_dp_restore_bit_exact_vs_control(self, tmp_path, n, m):
        o1, _ = _trainer((n,), ("data",), 2, ckpt=tmp_path)
        o1.optimize()
        frozen = tmp_path.parent / f"frozen_{n}_{m}"
        shutil.copytree(tmp_path, frozen)

        # elastic: restore the N-device snapshot onto the M-device mesh
        # (manifest topology check -> reshard path) and train 2 more
        o2, m2 = _trainer((m,), ("data",), 4, ckpt=tmp_path)
        assert o2._restore_latest_checkpoint()
        saved = o2.checkpoint.manager.last_loaded_manifest["topology"]
        assert saved["axes"] == {"data": n}
        o2.optimize()

        # control: identical snapshot state injected directly
        o3, m3 = _trainer((m,), ("data",), 4)
        _inject_snapshot(o3, m3, frozen)
        o3.optimize()

        np.testing.assert_array_equal(_weights(m2), _weights(m3))
        for a, b in zip(_slot_leaves(o2), _slot_leaves(o3)):
            np.testing.assert_array_equal(a, b)
        # the reshard was actually timed into the registry
        snap = telemetry.REGISTRY.snapshot()["gauges"]
        assert "Elastic/reshard_ms" in snap
        assert "Elastic/restore_ms" in snap

    def test_dp_elastic_vs_uninterrupted(self, tmp_path):
        """2 epochs on dp4 + 2 elastic epochs on dp2 vs 4 uninterrupted
        epochs on dp2: the partition-count-invariant batch stream makes
        the only difference the psum grouping of the first 2 epochs —
        reduction-association noise, nothing structural."""
        o1, _ = _trainer((4,), ("data",), 2, ckpt=tmp_path)
        o1.optimize()
        o2, m2 = _trainer((2,), ("data",), 4, ckpt=tmp_path)
        assert o2._restore_latest_checkpoint()
        o2.optimize()

        o3, m3 = _trainer((2,), ("data",), 4)
        o3.optimize()
        np.testing.assert_allclose(_weights(m2), _weights(m3),
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("n_shape,m_shape", [((2, 2), (4, 2)),
                                                 ((4, 2), (2, 2))])
    def test_dp_tp_restore_bit_exact_vs_control(self, tmp_path, n_shape,
                                                m_shape):
        """The GSPMD dp x tp leg: Adam slots saved under one data x model
        split re-place onto a different device count AND a different tp
        width, bit-exactly (map_over_slots is the pivot)."""
        axes = ("data", "model")
        o1, _ = _trainer(n_shape, axes, 2, ckpt=tmp_path, tp=True,
                         opt_method=optim.Adam(learning_rate=0.05))
        o1.optimize()
        frozen = tmp_path.parent / f"frozen_tp_{n_shape[0]}_{m_shape[0]}"
        shutil.copytree(tmp_path, frozen)

        o2, m2 = _trainer(m_shape, axes, 4, ckpt=tmp_path, tp=True,
                          opt_method=optim.Adam(learning_rate=0.05))
        assert o2._restore_latest_checkpoint()
        assert (o2.checkpoint.manager.last_loaded_manifest["topology"]
                ["step"] == "gspmd")
        o2.optimize()

        o3, m3 = _trainer(m_shape, axes, 4, tp=True,
                          opt_method=optim.Adam(learning_rate=0.05))
        _inject_snapshot(o3, m3, frozen)
        o3.optimize()

        np.testing.assert_array_equal(_weights(m2), _weights(m3))
        for a, b in zip(_slot_leaves(o2), _slot_leaves(o3)):
            np.testing.assert_array_equal(a, b)

    @pytest.mark.slow
    def test_elastic_soak_many_pairs(self, tmp_path):
        """Slow soak: every direction across 2/4/8 devices with Adam,
        longer runs, restore-at-every-epoch — bit-exact at each hop."""
        pairs = [(8, 2), (2, 8), (4, 8), (8, 4)]
        for i, (n, m) in enumerate(pairs):
            d = tmp_path / f"pair{i}"
            o1, _ = _trainer((n,), ("data",), 3, ckpt=d,
                             opt_method=optim.Adam(learning_rate=0.02))
            o1.optimize()
            frozen = tmp_path / f"pair{i}_frozen"
            shutil.copytree(d, frozen)
            o2, m2 = _trainer((m,), ("data",), 6, ckpt=d,
                              opt_method=optim.Adam(learning_rate=0.02))
            assert o2._restore_latest_checkpoint()
            o2.optimize()
            o3, m3 = _trainer((m,), ("data",), 6,
                              opt_method=optim.Adam(learning_rate=0.02))
            _inject_snapshot(o3, m3, frozen)
            o3.optimize()
            np.testing.assert_array_equal(_weights(m2), _weights(m3))
            for a, b in zip(_slot_leaves(o2), _slot_leaves(o3)):
                np.testing.assert_array_equal(a, b)


class TestManifestSchema:
    """Satellite: version + topology metadata, structured rejections,
    and pre-schema-2 compatibility."""

    def _rewrite_manifest(self, path, n, mutate):
        """Load manifest.n, apply ``mutate``, re-write it AND its commit
        marker (the marker cross-checks the manifest bytes)."""
        import json
        from bigdl_tpu.visualization.crc32c import crc32c
        mpath = os.path.join(str(path), f"manifest.{n}")
        with open(mpath) as f:
            manifest = json.load(f)
        mutate(manifest)
        mbytes = json.dumps(manifest, sort_keys=True).encode("utf-8")
        with open(mpath, "wb") as f:
            f.write(mbytes)
        with open(os.path.join(str(path), f"commit.{n}"), "wb") as f:
            f.write((f"{crc32c(mbytes):08x}\n").encode("ascii"))

    def test_topology_recorded_in_manifest(self, tmp_path):
        # every_epoch arms on its first observation, so the first
        # snapshot lands at evalCounter 2
        o, _ = _trainer((4,), ("data",), 2, ckpt=tmp_path)
        o.optimize()
        import json
        with open(tmp_path / "manifest.2") as f:
            manifest = json.load(f)
        assert manifest["version"] == 3
        assert all("fingerprint" in meta
                   for meta in manifest["files"].values())
        assert manifest["topology"] == {
            "device_count": 4, "axes": {"data": 4},
            "step": "shard_map", "slot_axis": "data"}

    def test_unknown_schema_rejected_with_structured_error(self, tmp_path):
        o, _ = _trainer((2,), ("data",), 2, ckpt=tmp_path)
        o.optimize()
        self._rewrite_manifest(tmp_path, 2,
                               lambda m: m.update(version=99))
        with pytest.raises(SnapshotSchemaError, match="99"):
            CheckpointManager(str(tmp_path)).load_latest()

    def test_unknown_schema_propagates_from_latest_valid(self, tmp_path):
        """latest_valid()/verify() must not swallow the deliberate
        schema rejection and silently answer with an older snapshot —
        a supervisor probing resumability has to see the same refusal
        the actual restore path raises."""
        o, _ = _trainer((2,), ("data",), 4, ckpt=tmp_path)
        o.optimize()
        mgr = CheckpointManager(str(tmp_path))
        newest = mgr.candidates()[0][0]
        self._rewrite_manifest(tmp_path, newest,
                               lambda m: m.update(version=99))
        with pytest.raises(SnapshotSchemaError, match="99"):
            mgr.latest_valid()

    def test_gc_never_deletes_newer_schema_snapshots(self, tmp_path):
        """A mixed-version rollout can leave a newer release's snapshot
        in the directory: this release's GC must neither crash on it nor
        reclaim it as debris."""
        o, _ = _trainer((2,), ("data",), 4, ckpt=tmp_path)
        o.optimize()
        mgr = CheckpointManager(str(tmp_path), keep_last=1)
        snaps = [n for n, _ in mgr.candidates()]
        assert len(snaps) >= 2
        foreign = snaps[1]          # older than the newest valid one
        self._rewrite_manifest(tmp_path, foreign,
                               lambda m: m.update(version=99))
        mgr.gc()
        names = set(os.listdir(tmp_path))
        for stem in ("model", "optimMethod", "manifest", "commit"):
            assert f"{stem}.{foreign}" in names
        # and the newest snapshot still restores
        assert CheckpointManager(str(tmp_path)).load_latest() is not None

    def test_topology_mismatch_rejected_without_reshard(self, tmp_path):
        o, _ = _trainer((4,), ("data",), 2, ckpt=tmp_path)
        o.optimize()
        config.set_property("bigdl.elastic.reshardOnRestore", False)
        o2, _ = _trainer((2,), ("data",), 2, ckpt=tmp_path)
        with pytest.raises(elastic.TopologyMismatchError,
                           match="axis 'data' 4 -> 2"):
            o2._restore_latest_checkpoint()

    def test_pre_schema2_snapshot_restores_same_topology(self, tmp_path):
        """A version-1 manifest with no topology record (what pre-PR-6
        code wrote) restores onto the same topology unchanged."""
        o, _ = _trainer((2,), ("data",), 2, ckpt=tmp_path)
        o.optimize()

        def downgrade(m):
            m["version"] = 1
            m.pop("topology", None)

        self._rewrite_manifest(tmp_path, 2, downgrade)
        o2, m2 = _trainer((2,), ("data",), 4, ckpt=tmp_path)
        assert o2._restore_latest_checkpoint()
        o2.optimize()   # resumes and finishes
        assert o2.optim_method.state["evalCounter"] == 4

    def test_async_writer_flushes_at_interpreter_exit(self, tmp_path):
        """Satellite: the atexit drain — a snapshot submitted to the
        async writer reaches its commit marker through the registered
        shutdown hook, with no explicit join."""
        from bigdl_tpu.utils.checkpoint_manager import (
            _LIVE_ASYNC_MANAGERS, drain_all_async_writers)
        mgr = CheckpointManager(str(tmp_path), async_write=True)
        assert mgr in _LIVE_ASYNC_MANAGERS
        mgr.save(_mlp(), optim.SGD(learning_rate=0.1), 1)
        # what atexit runs at interpreter shutdown (daemon threads would
        # otherwise be killed mid-write)
        drain_all_async_writers()
        names = os.listdir(tmp_path)
        assert "commit.1" in names and "manifest.1" in names
        assert CheckpointManager(str(tmp_path)).load_latest() is not None


class TestPreemption:
    def test_chaos_preemption_resumes_bit_exact(self, tmp_path):
        """The acceptance test: chaos-injected SIGTERM mid-run drains
        into a grace-period snapshot + marker; the resumed run reaches
        bit-exact weight parity with an uninterrupted one (shuffle-round
        replay keeps the epoch streams identical)."""
        config.set_property("bigdl.chaos.preemptAt", 3)
        chaos.install()
        o1, _ = _trainer((2,), ("data",), 6, ckpt=tmp_path)
        with pytest.raises(elastic.Preempted, match="drained"):
            o1.optimize()
        chaos.uninstall()

        marker = elastic.read_preemption_marker(str(tmp_path))
        assert marker is not None and marker["neval"] == 2
        assert "commit.2" in os.listdir(tmp_path)

        o2, m2 = _trainer((2,), ("data",), 6, ckpt=tmp_path)
        assert o2._restore_latest_checkpoint()
        o2.optimize()
        # a resumed run that trains on clears the stale marker
        assert elastic.read_preemption_marker(str(tmp_path)) is None

        o3, m3 = _trainer((2,), ("data",), 6)
        o3.optimize()
        np.testing.assert_array_equal(_weights(m2), _weights(m3))

    def test_real_sigterm_drains_gracefully(self, tmp_path):
        """bigdl.elastic.handleSignals: an actual SIGTERM delivered to
        the process lands in the PreemptionHandler, and the driver
        drains at the next iteration boundary."""
        config.set_property("bigdl.elastic.handleSignals", True)

        class KillAt:
            """end_when trigger that delivers SIGTERM once at iteration
            ``at`` — deterministic, unlike a timer thread racing the
            run."""
            reads_loss = False

            def __init__(self, at):
                self.at = at
                self.sent = False

            def __call__(self, state):
                if not self.sent and state["neval"] > self.at:
                    self.sent = True
                    os.kill(os.getpid(), signal.SIGTERM)
                return state["epoch"] > 50   # fallback: never reached

        o, _ = _trainer((2,), ("data",), 6, ckpt=tmp_path)
        o.set_end_when(KillAt(2))
        prev = signal.getsignal(signal.SIGTERM)
        with pytest.raises(elastic.Preempted):
            o.optimize()
        # handler restored after the run
        assert signal.getsignal(signal.SIGTERM) == prev
        assert elastic.read_preemption_marker(str(tmp_path)) is not None
        assert any(n.startswith("commit.") for n in os.listdir(tmp_path))

    def test_preemption_without_checkpoint_still_unwinds(self, tmp_path):
        config.set_property("bigdl.chaos.preemptAt", 2)
        chaos.install()
        o, _ = _trainer((2,), ("data",), 4)
        with pytest.raises(elastic.Preempted):
            o.optimize()

    def test_failed_grace_snapshot_skips_marker(self, tmp_path,
                                                monkeypatch):
        """A grace-period drain whose (async) snapshot write failed must
        NOT drop the resumable marker — a marker naming a snapshot that
        never landed would misreport a botched drain as an orderly
        preemption."""
        from bigdl_tpu.utils.checkpoint_manager import SnapshotWriteError
        o, _ = _trainer((2,), ("data",), 4, ckpt=tmp_path)

        def deferred_failure(raise_errors=True):
            raise SnapshotWriteError("simulated deferred write failure")

        monkeypatch.setattr(o.checkpoint, "join", deferred_failure)
        o._commit_preemption_snapshot()   # must swallow, not propagate
        assert elastic.read_preemption_marker(str(tmp_path)) is None

    def test_preemption_not_retried(self, tmp_path):
        """Preemption must never burn the failure-retry budget looping:
        one Preempted raise exits optimize() on the first attempt."""
        config.set_property("bigdl.failure.retryTimes", 5)
        config.set_property("bigdl.chaos.preemptAt", 2)
        chaos.install()
        o, _ = _trainer((2,), ("data",), 4, ckpt=tmp_path)
        t0 = time.perf_counter()
        with pytest.raises(elastic.Preempted):
            o.optimize()
        # a retried preemption would re-run optimize() bodies; the drain
        # path exits in one attempt (seconds, not retry-loop multiples)
        assert chaos._state is None or chaos._state.preempts <= 1
        assert time.perf_counter() - t0 < 60


class TestWatchdog:
    def _beats(self, wd, n, dt=0.005):
        for _ in range(n):
            time.sleep(dt)
            wd.heartbeat()

    def test_fires_once_per_stall_with_cooldown(self):
        fires = []
        wd = elastic.HungStepWatchdog(
            factor=2.0, warmup=2, cooldown=2, poll_interval=0.02,
            abort=False, on_fire=lambda o, t: fires.append(o))
        wd.start()
        try:
            self._beats(wd, 6)            # warmup + EMA (~5 ms steps)
            time.sleep(0.5)               # one long stall, many polls
            assert wd.fired == 1          # fires ONCE for the stall
            wd.heartbeat()                # stall ends -> cooldown starts
            time.sleep(0.4)               # second stall inside cooldown
            assert wd.fired == 1          # suppressed
            self._beats(wd, 4)            # consume the cooldown
            time.sleep(0.5)               # third stall, re-armed
            assert wd.fired == 2
        finally:
            wd.stop()
        assert len(fires) == 2

    def test_paused_every_step_still_arms_and_excludes_pause(self):
        """A pause every iteration (checkpoint-per-epoch runs) must not
        starve the EMA — the watchdog would silently disarm — and the
        paused span itself must stay out of the observed step time."""
        wd = elastic.HungStepWatchdog(factor=3.0, warmup=2,
                                      poll_interval=0.02, abort=False)
        wd.start()
        try:
            for _ in range(6):
                time.sleep(0.005)
                with wd.paused():
                    time.sleep(0.05)      # pause dwarfs the step
                time.sleep(0.005)
                wd.heartbeat()
            thr = wd.threshold_ns()
            assert thr != float("inf")    # armed despite per-step pauses
            # steps are ~10 ms sans pause; a pause-counting EMA would be
            # ~60 ms and put the threshold near 180 ms
            assert thr < 3.0 * 45e6
            time.sleep(0.4)               # a real stall still detected
            assert wd.fired == 1
        finally:
            wd.stop()

    def test_compile_warmup_exempt(self):
        wd = elastic.HungStepWatchdog(factor=2.0, warmup=4,
                                      poll_interval=0.02, abort=False)
        wd.start()
        try:
            wd.heartbeat()
            time.sleep(0.3)     # looks like a stall, but EMA unseeded
            assert wd.fired == 0
            assert wd.threshold_ns() == float("inf")
        finally:
            wd.stop()

    def test_e2e_stall_aborts_to_restore(self, tmp_path):
        """Chaos wedges iteration 6; the watchdog aborts it with
        HungStepError, the retry loop restores the newest snapshot, and
        the run still completes — instead of hanging forever."""
        fired_before = telemetry.counter("Elastic/watchdog_fired").value
        config.set_property("bigdl.watchdog.stallFactor", 5.0)
        config.set_property("bigdl.watchdog.warmupSteps", 2)
        config.set_property("bigdl.watchdog.pollInterval", 0.05)
        config.set_property("bigdl.chaos.stallStepAt", "6:1.5")
        chaos.install()
        o, _ = _trainer((2,), ("data",), 10, ckpt=tmp_path)
        o.optimize()
        assert o.optim_method.state["evalCounter"] == 10
        assert (telemetry.counter("Elastic/watchdog_fired").value
                == fired_before + 1)
        assert ("Elastic/watchdog_detect_ms"
                in telemetry.REGISTRY.snapshot()["gauges"])


class TestTopologyChangeChaos:
    def test_mid_run_topology_change_resumes_elsewhere(self, tmp_path):
        """bigdl.chaos.topologyChangeAt: the dp4 mesh dies mid-run; the
        rehearsal resumes the snapshot on dp2 and finishes with bit-exact
        parity vs direct state injection."""
        config.set_property("bigdl.failure.retryTimes", 1)  # don't retry
        config.set_property("bigdl.chaos.topologyChangeAt", 3)
        chaos.install()
        o1, _ = _trainer((4,), ("data",), 6, ckpt=tmp_path)
        with pytest.raises(chaos.ChaosError, match="topology"):
            o1.optimize()
        chaos.uninstall()
        frozen = tmp_path.parent / "frozen_topo"
        shutil.copytree(tmp_path, frozen)

        o2, m2 = _trainer((2,), ("data",), 6, ckpt=tmp_path)
        assert o2._restore_latest_checkpoint()
        o2.optimize()

        o3, m3 = _trainer((2,), ("data",), 6)
        _inject_snapshot(o3, m3, frozen)
        o3.optimize()
        np.testing.assert_array_equal(_weights(m2), _weights(m3))


class TestSignalLintRule:
    def test_signal_in_hot_path_flagged(self, tmp_path):
        import textwrap
        from bigdl_tpu.analysis.lint import lint_paths
        p = tmp_path / "optim" / "opt.py"
        p.parent.mkdir(parents=True)
        p.write_text(textwrap.dedent("""
            import signal
            def drain(item, nxt):
                signal.signal(signal.SIGTERM, lambda *a: None)
            def run_scope():
                signal.signal(signal.SIGTERM, lambda *a: None)
        """))
        findings = lint_paths([str(tmp_path)])
        rules = [f.rule for f in findings]
        assert rules == ["signal-handler-in-hot-path"]
        assert findings[0].line == 4
