"""Decoded-epoch cache (tentpole: decode JPEG once, feed every epoch).

Covers the segment ring's RAM and disk legs, the corruption quarantine
(bit-flipped segment fixture — satellite c), governor accounting and
pressure-driven shrink, and end-to-end engine parity: cached epochs must
stay bit-identical to the uncached (and synchronous) batch stream."""

import io
import os

import numpy as np
import pytest

from bigdl_tpu.dataset.epoch_cache import DecodedEpochCache
from bigdl_tpu.dataset.image import LabeledImageBytes
from bigdl_tpu.resources import GOVERNOR
from bigdl_tpu.utils import chaos, config
from bigdl_tpu.utils.random_generator import RandomGenerator


@pytest.fixture(autouse=True)
def _clean_env():
    GOVERNOR.reset()
    yield
    chaos.uninstall()
    GOVERNOR.reset()
    for k in ("bigdl.ingest.epochCache", "bigdl.ingest.epochCacheDir",
              "bigdl.ingest.epochCacheBudgetMB",
              "bigdl.ingest.epochCacheSegmentRecords",
              "bigdl.resources.hostMemBudgetMB",
              "bigdl.chaos.hostMemPressureAt"):
        config.clear_property(k)


def _frames(n, seed=0, hw=(8, 6)):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 256, size=hw + (3,)).astype(np.uint8)
            for _ in range(n)]


class TestSegmentRing:
    def test_ram_roundtrip_bit_exact(self):
        cache = DecodedEpochCache("t", segment_records=4)
        frames = _frames(10)
        for i, f in enumerate(frames):
            cache.put(f"r{i}", f)
        for i, f in enumerate(frames):      # sealed segments + open tail
            np.testing.assert_array_equal(cache.get(f"r{i}"), f)
        s = cache.stats()
        assert s["hits"] == 10 and s["ram_segments"] == 2
        assert s["open_records"] == 2
        cache.close()

    def test_unknown_and_unnamed_keys_are_misses(self):
        cache = DecodedEpochCache("t")
        assert cache.get("nope") is None
        cache.put(None, _frames(1)[0])      # unnamed record: never cached
        assert cache.stats()["open_records"] == 0
        assert cache.stats()["misses"] == 1
        cache.close()

    def test_disk_spill_and_readback(self, tmp_path):
        cache = DecodedEpochCache("t", cache_dir=str(tmp_path),
                                  segment_records=4)
        frames = _frames(8, seed=1)
        for i, f in enumerate(frames):
            cache.put(f"r{i}", f)
        s = cache.stats()
        assert s["disk_segments"] == 2 and s["ram_segments"] == 0
        assert s["ram_bytes"] == 0          # RAM released at the spill
        assert len(list(tmp_path.glob("*.bin"))) == 2
        for i, f in enumerate(frames):
            np.testing.assert_array_equal(cache.get(f"r{i}"), f)
        cache.close()

    def test_budget_cap_stops_admission_without_crashing(self):
        cache = DecodedEpochCache("t", budget_mb=0, segment_records=2)
        cache._cap = lambda: 1              # nothing fits
        for i, f in enumerate(_frames(4)):
            cache.put(f"r{i}", f)
        assert cache.stats()["ram_bytes"] <= 1
        cache.close()


class TestCorruptionQuarantine:
    def _spilled(self, tmp_path, n=4):
        cache = DecodedEpochCache("t", cache_dir=str(tmp_path),
                                  segment_records=n)
        frames = _frames(n, seed=2)
        for i, f in enumerate(frames):
            cache.put(f"r{i}", f)
        (path,) = list(tmp_path.glob("*.bin"))
        return cache, frames, path

    def test_bitflipped_segment_quarantined_not_crash(self, tmp_path):
        """Satellite c: one flipped payload bit fails the segment CRC;
        every read of that segment returns a miss (the caller re-decodes)
        and the segment is counted quarantined — never an exception."""
        cache, frames, path = self._spilled(tmp_path)
        blob = bytearray(path.read_bytes())
        blob[-3] ^= 0x40                    # payload bit
        path.write_bytes(bytes(blob))
        assert cache.get("r0") is None
        assert cache.stats()["corrupt_segments"] == 1
        assert cache.get("r1") is None      # whole segment dropped at once
        assert cache.stats()["corrupt_segments"] == 1
        # the cache still admits and serves fresh segments afterwards
        fresh = _frames(1, seed=9)[0]
        for i in range(4):
            cache.put(f"s{i}", fresh)
        np.testing.assert_array_equal(cache.get("s0"), fresh)
        cache.close()

    def test_truncated_header_quarantined(self, tmp_path):
        cache, _, path = self._spilled(tmp_path)
        path.write_bytes(path.read_bytes()[:7])
        assert cache.get("r2") is None
        assert cache.stats()["corrupt_segments"] == 1
        cache.close()

    def test_deleted_segment_file_quarantined(self, tmp_path):
        cache, _, path = self._spilled(tmp_path)
        os.remove(path)
        assert cache.get("r0") is None
        assert cache.stats()["corrupt_segments"] == 1
        cache.close()


class TestGovernorIntegration:
    def test_bytes_ride_a_named_account(self):
        cache = DecodedEpochCache("eng0", segment_records=2)
        for i, f in enumerate(_frames(4, seed=3)):
            cache.put(f"r{i}", f)
        scalars = dict(GOVERNOR.summary_scalars())
        key = "Resources/host_bytes_ingest_epoch_cache:eng0"
        assert scalars[key] > 0
        cache.close()
        assert dict(GOVERNOR.summary_scalars())[key] == 0.0

    def test_injected_pressure_shrinks_the_cache(self):
        """The governor stays the authority: a pressure excursion fires
        the cache's (weakly-registered) shrinker and evicts the oldest
        RAM segments, dropping the accounted bytes."""
        cache = DecodedEpochCache("eng1", segment_records=2)
        for i, f in enumerate(_frames(8, seed=4)):
            cache.put(f"r{i}", f)
        before = cache.stats()["ram_bytes"]
        config.set_property("bigdl.chaos.hostMemPressureAt", 1)
        chaos.install()
        assert GOVERNOR.poll() is True
        after = cache.stats()
        assert after["ram_bytes"] < before
        assert after["evicted_segments"] >= 1
        # evicted records re-decode (miss), surviving ones still hit
        assert cache.get("r0") is None
        cache.close()


class TestEngineEndToEnd:
    def _png_records(self, n=12, hw=(40, 48), seed=3):
        from PIL import Image
        rng = np.random.RandomState(seed)
        recs = []
        for i in range(n):
            img = rng.randint(0, 256, size=hw + (3,)).astype(np.uint8)
            buf = io.BytesIO()
            Image.fromarray(img).save(buf, "PNG")
            recs.append(LabeledImageBytes(f"r{i}", float(i % 5 + 1),
                                          buf.getvalue()))
        return recs

    def _batches(self, transformer, recs, seed=20240731):
        RandomGenerator.RNG().set_seed(seed)
        return [(b.get_input().copy(), b.get_target().copy())
                for b in transformer(iter(recs))]

    def test_cached_epochs_bit_identical_and_hitting(self):
        """Epoch 2 must serve every decode from the cache AND stay
        bit-identical to the uncached stream: the crop/flip draws happen
        after the cache, so caching is a pure throughput property."""
        from bigdl_tpu.dataset.ingest import StreamingIngest
        from bigdl_tpu.dataset.mt_batch import MTLabeledBGRImgToBatch

        recs = self._png_records()
        sync1 = self._batches(MTLabeledBGRImgToBatch(4, crop=(32, 32)),
                              recs, seed=11)
        sync2 = self._batches(MTLabeledBGRImgToBatch(4, crop=(32, 32)),
                              recs, seed=12)
        config.set_property("bigdl.ingest.epochCache", True)
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2)
        assert eng.epoch_cache is not None
        got1 = self._batches(eng, recs, seed=11)
        assert eng.epoch_cache.stats()["misses"] == len(recs)
        got2 = self._batches(eng, recs, seed=12)
        assert eng.epoch_cache.stats()["hits"] == len(recs)
        for sync, got in ((sync1, got1), (sync2, got2)):
            for (xs, ys), (xg, yg) in zip(sync, got):
                np.testing.assert_array_equal(xs, xg)
                np.testing.assert_array_equal(ys, yg)
