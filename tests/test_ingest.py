"""Streaming ingest engine: pipeline behaviour, teardown, error paths,
stage counters, and the (slow-marked) soak."""

import io
import threading
import time

import numpy as np
import pytest

from bigdl_tpu.dataset.image import LabeledImageBytes
from bigdl_tpu.dataset.ingest import (ShardedSeqFileReader, StageStats,
                                      StreamingIngest, summary_scalars)
from bigdl_tpu.utils.random_generator import RandomGenerator


def _png_records(n=12, hw=(40, 48), seed=3):
    from PIL import Image
    rng = np.random.RandomState(seed)
    recs = []
    for i in range(n):
        img = rng.randint(0, 256, size=hw + (3,)).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(img).save(buf, "PNG")
        recs.append(LabeledImageBytes(f"r{i}", float(i % 5 + 1),
                                      buf.getvalue()))
    return recs


class TestStreamingIngest:
    def test_batches_and_trailing_partial(self):
        recs = _png_records(n=10)
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2)
        batches = list(eng(iter(recs)))
        assert [b.size() for b in batches] == [4, 4, 2]
        assert batches[0].get_input().shape == (4, 3, 32, 32)
        assert batches[0].get_input().dtype == np.float32

    def test_empty_upstream(self):
        eng = StreamingIngest(4, crop=(32, 32))
        assert list(eng(iter([]))) == []

    def test_upstream_error_propagates(self):
        def gen():
            yield from _png_records(n=6)
            raise RuntimeError("upstream boom")

        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2)
        it = eng(gen())
        assert next(it).size() == 4
        with pytest.raises(RuntimeError, match="upstream boom"):
            list(it)

    def test_decode_error_propagates(self):
        recs = _png_records(n=8)
        recs[5] = LabeledImageBytes("bad", 1.0, b"not an image at all")
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2)
        with pytest.raises(Exception):
            list(eng(iter(recs)))

    def test_undersized_record_raises_named_error(self):
        recs = _png_records(n=4, hw=(40, 48))
        recs[2:3] = _png_records(n=1, hw=(20, 48))
        recs[2].label = 9.0
        for random_crop in (False, True):
            eng = StreamingIngest(4, crop=(32, 32),
                                  random_crop=random_crop, decode_workers=2)
            with pytest.raises(ValueError, match=r"record 2 .*20x48.*32x32"):
                list(eng(iter(recs)))

    def test_teardown_joins_threads_and_drains_rings(self):
        """Abandoning the iterator mid-stream must stop every stage
        thread (bounded) and leave nothing pinned in the rings."""
        before = threading.active_count()
        recs = _png_records(n=8)

        def infinite():
            while True:
                yield from recs

        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2,
                              record_ring_depth=8, decoded_ring_depth=8,
                              batch_ring_depth=4)
        it = eng(infinite())
        for _ in range(3):
            next(it)
        it.close()
        deadline = time.monotonic() + 10
        while (threading.active_count() > before and
               time.monotonic() < deadline):
            time.sleep(0.05)
        assert threading.active_count() <= before, "stage thread leaked"

    def test_stats_counters_consistent(self):
        recs = _png_records(n=12)
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2)
        n = sum(b.size() for b in eng(iter(recs)))
        stats = eng.stats()
        assert n == 12
        assert set(stats) == {"read", "decode", "assemble", "consume"}
        assert stats["read"]["items"] == 12
        assert stats["decode"]["items"] == 12
        assert stats["assemble"]["items"] == 12
        assert stats["consume"]["items"] == 3          # batches
        for snap in stats.values():
            assert snap["throughput_per_sec"] >= 0
            assert snap["busy_s"] >= 0
            assert snap["starve_s"] >= 0
            assert snap["backpressure_s"] >= 0

    def test_summary_scalars_surface_live_engines(self):
        recs = _png_records(n=8)
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2)
        it = eng(iter(recs))
        next(it)                     # engine is mid-run: scalars surface
        tags = {t for t, _ in summary_scalars()}
        it.close()
        assert any(t == f"Ingest/{eng.name}/decode/throughput"
                   for t in tags)
        assert any(t.endswith("/stall_frac") for t in tags)
        # finished engines drop out of the summary (stale counters must
        # not pollute a later run's series); stats() still serves them
        assert not eng.has_active_run()
        assert all(f"/{eng.name}/" not in t
                   for t, _ in summary_scalars())
        assert eng.stats()["decode"]["items"] >= 4

    def test_backpressure_bounds_read_ahead(self):
        """A tiny batch ring with a slow consumer must hold the reader
        back (bounded memory), not let it slurp the whole stream."""
        recs = _png_records(n=8)
        progress = {"n": 0}

        def counted():
            for r in recs * 50:                    # 400 records available
                progress["n"] += 1
                yield r

        eng = StreamingIngest(4, crop=(32, 32), decode_workers=1,
                              record_ring_depth=2, decoded_ring_depth=4,
                              batch_ring_depth=1)
        it = eng(counted())
        next(it)
        time.sleep(0.3)                            # engine runs ahead
        # bounded by rings: record(2) + window(4) + batches((1+1)*4) + slack
        assert progress["n"] <= 24, progress["n"]
        it.close()


class TestStageStats:
    def test_snapshot_fields(self):
        s = StageStats("x")
        s.add(items=3, busy_s=0.5, starve_s=0.25, backpressure_s=0.25)
        s.sample_occupancy(2)
        s.sample_occupancy(4)
        snap = s.snapshot()
        assert snap["items"] == 3
        assert snap["mean_queue_depth"] == 3.0
        assert snap["busy_s"] == 0.5


class TestShardedSeqFileReader:
    def test_missing_dir_is_empty(self, tmp_path):
        assert list(ShardedSeqFileReader(str(tmp_path))) == []

    def test_corrupt_file_raises_on_merge_side(self, tmp_path):
        from bigdl_tpu.dataset import seqfile
        good = [(f"k{i}", 1.0, b"v" * 50) for i in range(4)]
        seqfile.write_image_seqfile(str(tmp_path / "a.seq"), good)
        seqfile.write_image_seqfile(str(tmp_path / "b.seq"), good)
        with open(tmp_path / "b.seq", "r+b") as f:
            f.truncate(60)                          # cut inside a record
        with pytest.raises(IOError):
            list(ShardedSeqFileReader(str(tmp_path), shards=2))

    def test_abandonment_stops_reader_threads(self, tmp_path):
        from bigdl_tpu.dataset import seqfile
        for fi in range(4):
            seqfile.write_image_seqfile(
                str(tmp_path / f"p{fi}.seq"),
                [(f"k{fi}_{i}", 1.0, b"v" * 2000) for i in range(50)])
        before = threading.active_count()
        it = iter(ShardedSeqFileReader(str(tmp_path), shards=3,
                                       ring_depth=6))
        next(it)
        it.close()
        deadline = time.monotonic() + 10
        while (threading.active_count() > before and
               time.monotonic() < deadline):
            time.sleep(0.05)
        assert threading.active_count() <= before, "reader thread leaked"


class TestMultiEngineOneStream:
    """Two engines forked from ONE RandomGenerator stream (the multi-shard
    ShardedDataSet shape, shard iterators pulled alternately): the first
    fork owns the stream's commits, secondaries draw decorrelated
    deterministic per-shard streams — alternating consumption must be
    run-to-run deterministic, never an incoherent interleaving."""

    def _run_once(self):
        from bigdl_tpu.dataset.dataset import ShardedDataSet

        recs = _png_records(n=24)
        RandomGenerator.RNG().set_seed(515)
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2,
                              decoded_ring_depth=6)
        ds = ShardedDataSet(recs, 2).transform(eng)
        its = [ds.shard_data(p, train=False) for p in (0, 1)]
        out = []
        for _ in range(3):           # alternate pulls, like _global_batch
            for it in its:
                b = next(it)
                out.append((b.get_input().copy(), b.get_target().copy()))
        # the ONE transformer instance runs once per shard: stats() must
        # merge both live runs, not report just the last-started shard
        assert eng.has_active_run()
        assert eng.stats()["consume"]["items"] == 6
        for it in its:
            it.close()
        return out, RandomGenerator.RNG().np.get_state()

    def test_alternating_shard_consumption_is_deterministic(self):
        (a, sa), (b, sb) = self._run_once(), self._run_once()
        assert len(a) == len(b) == 6
        for (xa, ya), (xb, yb) in zip(a, b):
            np.testing.assert_array_equal(xa, xb)
            np.testing.assert_array_equal(ya, yb)
        for s0, s1 in zip(sa, sb):
            np.testing.assert_array_equal(np.asarray(s0), np.asarray(s1))

    def test_secondary_fork_is_decorrelated(self):
        """Both shards forking the same state must NOT apply identical
        crop/flip sequences (correlated augmentation across shards)."""
        out, _ = self._run_once()
        # same underlying record content per shard position differs, so
        # compare the two shards' first batches: they must not be equal
        # as a whole (decorrelated draws on distinct records)
        assert not np.array_equal(out[0][0], out[1][0])


@pytest.mark.slow
def test_ingest_soak():
    """Soak: many epochs of sustained pipelining at adversarially small
    ring depths — counters stay exact, nothing deadlocks, and the batch
    stream stays bit-identical to the synchronous path throughout."""
    from bigdl_tpu.dataset.mt_batch import MTLabeledBGRImgToBatch

    recs = _png_records(n=40)
    stream = recs * 50                               # 2000 records

    RandomGenerator.RNG().set_seed(11)
    sync = [(b.get_input().copy(), b.get_target().copy())
            for b in MTLabeledBGRImgToBatch(8, crop=(32, 32))(iter(stream))]

    RandomGenerator.RNG().set_seed(11)
    eng = StreamingIngest(8, crop=(32, 32), decode_workers=3,
                          record_ring_depth=4, decoded_ring_depth=10,
                          batch_ring_depth=2)
    got = [(b.get_input().copy(), b.get_target().copy())
           for b in eng(iter(stream))]

    assert len(got) == len(sync) == 250
    for (xs, ys), (xg, yg) in zip(sync, got):
        np.testing.assert_array_equal(xs, xg)
        np.testing.assert_array_equal(ys, yg)
    stats = eng.stats()
    assert stats["decode"]["items"] == 2000
    assert stats["assemble"]["items"] == 2000
    assert stats["consume"]["items"] == 250
