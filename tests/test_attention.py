"""Attention + ring-attention sequence parallelism.

Ring attention on the 8-device mesh must match single-device full attention
bit-for-bit-ish — the long-context capability is only real if the sharded
path is numerically the same function.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.engine import Engine
from bigdl_tpu.nn.attention import (MultiHeadAttention,
                                    scaled_dot_product_attention)
from bigdl_tpu.parallel.ring_attention import (ring_attention,
                                               ring_self_attention)

N_DEV = 8


def _qkv(b=2, t=32, h=4, dh=8, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.normal(size=(b, t, h, dh))
                             .astype(np.float32)) for _ in range(3))


class TestFullAttention:
    def test_softmax_rows_sum_to_one_effect(self):
        q, k, v = _qkv()
        # attention of anything against identical v rows returns those rows
        v_const = jnp.ones_like(v)
        out = scaled_dot_product_attention(q, k, v_const)
        np.testing.assert_allclose(np.asarray(out), 1.0, rtol=1e-5)

    def test_causal_masks_future(self):
        q, k, v = _qkv(t=8)
        out = scaled_dot_product_attention(q, k, v, causal=True)
        # position 0 attends only to key 0
        expect0 = v[:, 0]
        np.testing.assert_allclose(np.asarray(out[:, 0]),
                                   np.asarray(expect0), rtol=1e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_chunked_matches_full(self, causal):
        """chunked_attention is the same softmax, q-block-scanned: parity
        with the one-shot path to float tolerance, fwd and grad."""
        from bigdl_tpu.nn.attention import chunked_attention
        q, k, v = _qkv(t=32)

        def full(q):
            return jnp.sum(
                scaled_dot_product_attention(q, k, v, causal=causal) ** 2)

        def chunked(q):
            return jnp.sum(
                chunked_attention(q, k, v, causal=causal, chunk=8) ** 2)

        np.testing.assert_allclose(float(full(q)), float(chunked(q)),
                                   rtol=1e-5)
        gf = jax.grad(full)(q)
        gc = jax.grad(chunked)(q)
        np.testing.assert_allclose(np.asarray(gc), np.asarray(gf),
                                   rtol=1e-4, atol=1e-5)

    def test_chunked_cross_attention_causal_alignment(self):
        """Tq != Tkv: the causal mask must stay bottom-right aligned like
        the one-shot path (query i sees keys up to i + Tkv - Tq), not
        top-left (the flash kernel's divergence this path must NOT have)."""
        from bigdl_tpu.nn.attention import chunked_attention
        rng = np.random.RandomState(4)
        q = jnp.asarray(rng.normal(size=(2, 16, 4, 8)).astype(np.float32))
        k, v = (jnp.asarray(rng.normal(size=(2, 32, 4, 8))
                            .astype(np.float32)) for _ in range(2))
        want = scaled_dot_product_attention(q, k, v, causal=True)
        got = chunked_attention(q, k, v, causal=True, chunk=8)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_old_snapshot_without_chunk_attr_still_forwards(self):
        """Snapshots pickled before the chunk/flash attributes existed
        must load and forward (class-level defaults backfill them)."""
        import pickle
        mha = MultiHeadAttention(32, 4)
        mha._ensure_init()
        state = mha.__getstate__()
        for key in ("chunk", "flash", "sequence_parallel"):
            state.pop(key, None)       # as an old pickle would lack them
        old = MultiHeadAttention.__new__(MultiHeadAttention)
        old.__setstate__(state)
        x = jnp.asarray(np.random.RandomState(6)
                        .normal(size=(1, 8, 32)).astype(np.float32))
        assert np.asarray(old.forward(x)).shape == (1, 8, 32)

    def test_chunked_rejects_indivisible_t(self):
        from bigdl_tpu.nn.attention import chunked_attention
        q, k, v = _qkv(t=12)
        with pytest.raises(ValueError, match="divisible"):
            chunked_attention(q, k, v, chunk=8)

    def test_mha_chunk_param_end_to_end(self):
        """MultiHeadAttention(chunk=N) must produce the standard module's
        output on the same params."""
        base = MultiHeadAttention(32, 4, causal=True)
        base._ensure_init()
        ch = MultiHeadAttention(32, 4, causal=True, chunk=8)
        ch._params = base._params
        ch._state = base._state
        ch._grads = base._grads
        x = jnp.asarray(np.random.RandomState(5)
                        .normal(size=(2, 16, 32)).astype(np.float32))
        np.testing.assert_allclose(np.asarray(ch.forward(x)),
                                   np.asarray(base.forward(x)),
                                   rtol=1e-5, atol=1e-6)

    def test_mha_flash_and_chunk_exclusive(self):
        with pytest.raises(ValueError, match="pick one"):
            MultiHeadAttention(32, 4, flash=True, chunk=8)

    def test_mha_module_shapes_and_grad(self):
        mha = MultiHeadAttention(32, 4)
        x = np.random.RandomState(1).normal(size=(2, 16, 32)).astype(np.float32)
        out = mha.forward(jnp.asarray(x))
        assert out.shape == (2, 16, 32)
        gin = mha.backward(jnp.asarray(x), jnp.ones_like(out))
        assert gin.shape == x.shape
        assert np.all(np.isfinite(np.asarray(gin)))

    def test_cross_attention_table_input(self):
        mha = MultiHeadAttention(32, 4)
        rng = np.random.RandomState(2)
        q_src = jnp.asarray(rng.normal(size=(2, 5, 32)).astype(np.float32))
        kv_src = jnp.asarray(rng.normal(size=(2, 9, 32)).astype(np.float32))
        out = mha.forward([q_src, kv_src])
        assert out.shape == (2, 5, 32)


class TestRingAttention:
    def test_matches_full_attention(self):
        mesh = Engine.create_mesh((N_DEV,), ("seq",))
        q, k, v = _qkv(t=64)
        full = scaled_dot_product_attention(q, k, v)
        ring = ring_attention(q, k, v, mesh)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(full),
                                   rtol=2e-5, atol=2e-6)

    def test_matches_full_attention_causal(self):
        mesh = Engine.create_mesh((N_DEV,), ("seq",))
        q, k, v = _qkv(t=64, seed=3)
        full = scaled_dot_product_attention(q, k, v, causal=True)
        ring = ring_attention(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(full),
                                   rtol=2e-5, atol=2e-6)

    def test_ring_self_attention_matches_module(self):
        mesh = Engine.create_mesh((N_DEV,), ("seq",))
        mha = MultiHeadAttention(32, 4, causal=True)
        mha._ensure_init()
        x = jnp.asarray(np.random.RandomState(4).normal(
            size=(2, 64, 32)).astype(np.float32))
        full, _ = mha.apply(mha.params, x, {}, training=False)
        ring = ring_self_attention(mha, mha.params, x, mesh)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(full),
                                   rtol=2e-5, atol=2e-6)

    @pytest.mark.slow
    def test_gradients_flow_through_ring(self):
        """Training viability: grads of the ring path are finite and close
        to the full-attention grads."""
        mesh = Engine.create_mesh((N_DEV,), ("seq",))
        mha = MultiHeadAttention(16, 2)
        mha._ensure_init()
        x = jnp.asarray(np.random.RandomState(5).normal(
            size=(1, 32, 16)).astype(np.float32))

        def loss_ring(p):
            return jnp.sum(ring_self_attention(mha, p, x, mesh) ** 2)

        def loss_full(p):
            out, _ = mha.apply(p, x, {}, training=False)
            return jnp.sum(out ** 2)

        g_ring = jax.grad(loss_ring)(mha.params)
        g_full = jax.grad(loss_full)(mha.params)
        for a, b in zip(jax.tree_util.tree_leaves(g_ring),
                        jax.tree_util.tree_leaves(g_full)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-5)

    def test_2d_mesh_data_and_seq(self):
        """dp x sp: batch sharded over 'data', sequence over 'seq'."""
        mesh = Engine.create_mesh((2, 4), ("data", "seq"))
        q, k, v = _qkv(b=4, t=32, seed=6)
        full = scaled_dot_product_attention(q, k, v)

        from bigdl_tpu.parallel.all_reduce import shard_map
        from functools import partial
        from jax.sharding import PartitionSpec as P
        from bigdl_tpu.parallel.ring_attention import _ring_attention_shard
        spec = P("data", "seq")
        fn = shard_map(partial(_ring_attention_shard, axis_name="seq",
                               causal=False),
                       mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_rep=False)
        ring = fn(q, k, v)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(full),
                                   rtol=2e-5, atol=2e-6)


class TestFlashGate:
    def test_auto_falls_back_off_tpu(self):
        """On the CPU test mesh, flash=None silently uses the reference
        path and results stay correct."""
        mha = MultiHeadAttention(32, 4)
        x = jnp.asarray(np.random.RandomState(7).normal(
            size=(2, 128, 32)).astype(np.float32))
        out = mha.forward(x)
        assert out.shape == (2, 128, 32)

    def test_flash_true_raises_when_unsupported(self):
        mha = MultiHeadAttention(32, 4, flash=True)
        x = jnp.asarray(np.zeros((1, 128, 32), np.float32))
        with pytest.raises(ValueError, match="flash=True"):
            mha.forward(x)

    def test_flash_false_forces_reference(self):
        mha = MultiHeadAttention(32, 4, flash=False)
        q = jnp.zeros((1, 128, 4, 8))
        assert mha._flash_ok(q, q) is False
