"""Real-endpoint persistence integration tests (opt-in).

The reference tags genuine HDFS/S3 integration specs that run only
against live clusters (``integration/HdfsSpec.scala``, ``S3Spec.scala``
— excluded from the default suite, enabled on the integration CI).
This zero-egress build image cannot host real endpoints, so the default
suite exercises the identical fsspec code path over ``memory://``
(tests/test_failure_recovery.py::TestRemoteCheckpointIntegration); this
module is the explicit, runnable analog for environments that DO have
endpoints:

    BIGDL_IT_HDFS=hdfs://namenode:8020/tmp/bigdl_it \
    BIGDL_IT_S3=s3://bucket/bigdl_it \
        python -m pytest tests/integration -q --runslow

Each test is skipped unless its endpoint env var is set, so the gap
between "fsspec path proven over memory://" and "proven against a real
store" stays visible instead of silent.
"""

import os

import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import LocalDataSet, SampleToMiniBatch
from bigdl_tpu.dataset.datasets import synthetic_separable
from bigdl_tpu.utils import file_io

ENDPOINTS = [("BIGDL_IT_HDFS", "hdfs"), ("BIGDL_IT_S3", "s3")]


def _mlp(din, nclass, seed=5):
    import jax
    m = (nn.Sequential().add(nn.Linear(din, 16)).add(nn.Tanh())
         .add(nn.Linear(16, nclass)).add(nn.LogSoftMax()))
    m.reset(jax.random.PRNGKey(seed))
    return m


@pytest.mark.parametrize("env_var,scheme", ENDPOINTS)
class TestRealEndpointPersistence:
    def _root(self, env_var):
        root = os.environ.get(env_var)
        if not root:
            pytest.skip(f"set {env_var}=<url> to run against a real "
                        "endpoint (reference integration/HdfsSpec.scala)")
        return root.rstrip("/")

    def test_save_load_roundtrip(self, env_var, scheme):
        root = self._root(env_var)
        path = f"{root}/roundtrip/obj"
        file_io.save({"answer": 42, "arr": np.arange(8)}, path)
        back = file_io.load(path)
        assert back["answer"] == 42
        np.testing.assert_array_equal(back["arr"], np.arange(8))
        file_io.remove(path)

    def test_overwrite_guard(self, env_var, scheme):
        root = self._root(env_var)
        path = f"{root}/guard/obj"
        file_io.save({"v": 1}, path)
        with pytest.raises(FileExistsError):
            file_io.save({"v": 2}, path, overwrite=False)
        assert file_io.load(path)["v"] == 1
        file_io.remove(path)

    def test_train_checkpoint_resume_cycle(self, env_var, scheme):
        """The full train -> checkpoint -> reload -> continue protocol
        against the live store (reference HdfsSpec's model round-trip)."""
        root = self._root(env_var)
        ckpt = f"{root}/ckpt_cycle"
        samples = synthetic_separable(128, 4, n_classes=2, seed=3)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(32))
        model = _mlp(4, 2)
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.5))
        opt.set_end_when(optim.max_epoch(2))
        opt.set_checkpoint(ckpt, optim.every_epoch())
        opt.optimize()

        latest = opt.checkpoint.latest()
        assert latest is not None
        model2 = file_io.load(latest[0])
        method2 = optim.OptimMethod.load(latest[1])
        assert method2.state["evalCounter"] > 0
        opt2 = optim.Optimizer.create(
            model2, LocalDataSet(samples).transform(SampleToMiniBatch(32)),
            nn.ClassNLLCriterion())
        opt2.set_optim_method(method2)
        opt2.set_end_when(optim.max_epoch(4))
        trained = opt2.optimize()
        acc = optim.Evaluator(trained).test(
            samples, [optim.Top1Accuracy()], 32)[0][1].final_result()
        assert acc > 0.9
