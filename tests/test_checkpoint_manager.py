"""Verified snapshots: manifest/commit protocol, retention GC, async
writer, and restore fallback (utils/checkpoint_manager.py).

Reference analog: the snapshot files the retry loop restores
(``optim/DistriOptimizer.scala:394-416,766-788``) — here hardened into
committed, checksum-verified units so one torn write can never brick
recovery.
"""

import json
import os

import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.utils import file_io
from bigdl_tpu.utils.checkpoint_manager import (CheckpointManager,
                                                SnapshotWriteError, _capture)
from bigdl_tpu.visualization.crc32c import crc32c


def _mlp(seed=5):
    import jax
    m = (nn.Sequential().add(nn.Linear(4, 8)).add(nn.Tanh())
         .add(nn.Linear(8, 2)).add(nn.LogSoftMax()))
    m.reset(jax.random.PRNGKey(seed))
    return m


def _sgd():
    return optim.SGD(learning_rate=0.1, momentum=0.9)


class TestManifestProtocol:
    def test_snapshot_writes_manifest_and_commit(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_mlp(), _sgd(), 3)
        names = sorted(os.listdir(tmp_path))
        assert names == ["commit.3", "manifest.3", "model.3",
                         "optimMethod.3"]
        manifest = json.loads((tmp_path / "manifest.3").read_bytes())
        assert manifest["neval"] == 3
        from bigdl_tpu.utils.checkpoint_manager import checksum_by_algo
        for fname in ("model.3", "optimMethod.3"):
            data = (tmp_path / fname).read_bytes()
            assert manifest["files"][fname]["bytes"] == len(data)
            assert manifest["files"][fname]["checksum"] == \
                checksum_by_algo(manifest["algo"], data)
        # the commit marker cross-checks the manifest bytes themselves
        mbytes = (tmp_path / "manifest.3").read_bytes()
        assert (tmp_path / "commit.3").read_text().strip() == \
            f"{crc32c(mbytes):08x}"

    def test_latest_valid_requires_pair(self, tmp_path):
        """A crash between the model and optimMethod saves leaves a
        model-only snapshot: it must never be selected (regression — the
        old ``latest()`` picked it and restore crashed)."""
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_mlp(), _sgd(), 3)
        file_io.save(_mlp(), str(tmp_path / "model.7"))   # no optimMethod.7
        path_m, path_o, n = mgr.latest_valid()
        assert n == 3 and path_m.endswith("model.3")

    def test_latest_valid_skips_uncommitted(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_mlp(), _sgd(), 1)
        mgr.save(_mlp(), _sgd(), 2)
        os.unlink(tmp_path / "commit.2")   # writer died before the commit
        assert mgr.latest_valid()[2] == 1

    def test_latest_valid_skips_truncated_payload(self, tmp_path):
        """Shallow verification (one stat, no payload transfer) catches
        the realistic torn-write mode: a short object committed by the
        rename."""
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_mlp(), _sgd(), 1)
        mgr.save(_mlp(), _sgd(), 2)
        data = (tmp_path / "model.2").read_bytes()
        (tmp_path / "model.2").write_bytes(data[:len(data) // 2])
        assert mgr.latest_valid()[2] == 1
        assert mgr.load_latest()[2] == 1

    def test_load_skips_bitflip_corruption(self, tmp_path):
        """Same-size bit corruption passes the shallow stat check (by
        design — catching it needs the bytes) but the full checksum at
        load time rejects it and restore falls back."""
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_mlp(), _sgd(), 1)
        mgr.save(_mlp(), _sgd(), 2)
        data = bytearray((tmp_path / "model.2").read_bytes())
        data[len(data) // 2] ^= 0xFF       # one flipped byte, same size
        (tmp_path / "model.2").write_bytes(bytes(data))
        model, om, n = mgr.load_latest()
        assert n == 1 and om.state["evalCounter"] == 0
        # deep verification names the corruption explicitly too
        assert not mgr.verify(2, True, deep=True)

    def test_legacy_pair_without_manifest_restorable(self, tmp_path):
        """Snapshots from before the manifest era (bare pairs) stay
        restorable."""
        file_io.save(_mlp(), str(tmp_path / "model.4"))
        file_io.save(_sgd(), str(tmp_path / "optimMethod.4"))
        mgr = CheckpointManager(str(tmp_path))
        assert mgr.latest_valid()[2] == 4
        model, om, n = mgr.load_latest()
        assert n == 4
        x = np.zeros((1, 4), np.float32)
        assert np.asarray(model.forward(x)).shape == (1, 2)

    def test_load_falls_back_when_unpickling_fails(self, tmp_path):
        """A corrupt LEGACY snapshot has no manifest to fail against —
        the unpickler is its verifier, and restore walks to the
        next-older snapshot instead of dying inside the retry loop."""
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_mlp(), _sgd(), 3)
        (tmp_path / "model.9").write_bytes(b"not a pickle")
        file_io.save(_sgd(), str(tmp_path / "optimMethod.9"))
        model, om, n = mgr.load_latest()
        assert n == 3

    def test_empty_dir(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        assert mgr.latest_valid() is None
        assert mgr.load_latest() is None


class TestRetention:
    def test_keep_last_gc(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_last=2)
        for n in range(1, 6):
            mgr.save(_mlp(), _sgd(), n)
        names = sorted(os.listdir(tmp_path))
        kept = {int(f.split(".")[1]) for f in names}
        assert kept == {4, 5}, names
        # every kept snapshot is a full verified unit
        assert len(names) == 8
        assert mgr.latest_valid()[2] == 5

    def test_gc_never_counts_uncommitted(self, tmp_path):
        """An uncommitted snapshot never consumes a keep_last slot — and,
        once older than the newest restorable snapshot, it is torn-write
        debris and gets swept (it can never become whole)."""
        mgr = CheckpointManager(str(tmp_path), keep_last=2)
        mgr.save(_mlp(), _sgd(), 1)
        mgr.save(_mlp(), _sgd(), 2)
        os.unlink(tmp_path / "commit.2")
        mgr.save(_mlp(), _sgd(), 3)
        kept = {int(f.split(".")[1]) for f in os.listdir(tmp_path)}
        assert kept == {1, 3}
        assert mgr.latest_valid()[2] == 3

    def test_gc_bounds_legacy_snapshots_too(self, tmp_path):
        """A directory of pre-manifest pairs must still be bounded by
        keep_last — 'committed-only' retention would hoard legacy
        snapshots forever."""
        for n in range(1, 6):
            file_io.save(_mlp(), str(tmp_path / f"model.{n}"))
            file_io.save(_sgd(), str(tmp_path / f"optimMethod.{n}"))
        mgr = CheckpointManager(str(tmp_path), keep_last=2)
        mgr.save(_mlp(), _sgd(), 6)
        kept = {int(f.split(".")[1]) for f in os.listdir(tmp_path)}
        assert kept == {5, 6}, sorted(os.listdir(tmp_path))
        assert mgr.load_latest()[2] == 6

    def test_gc_sweeps_torn_debris(self, tmp_path):
        """Crashed-write leftovers (pair-incomplete snapshots older than
        the newest committed one) are collected by retention GC — they
        can never become whole, and without the sweep every failed write
        leaks files into a keep_last-bounded directory forever."""
        mgr = CheckpointManager(str(tmp_path), keep_last=2)
        mgr.save(_mlp(), _sgd(), 1)
        (tmp_path / "model.2").write_bytes(b"torn, writer died")  # no pair
        mgr.save(_mlp(), _sgd(), 3)
        mgr.save(_mlp(), _sgd(), 4)
        names = os.listdir(tmp_path)
        assert "model.2" not in names, names
        kept = {int(f.split(".")[1]) for f in names}
        assert kept == {3, 4}, names

    def test_gc_never_evicts_last_valid_for_a_corrupt_newest(self,
                                                             tmp_path):
        """A committed-but-truncated newest snapshot must not occupy the
        keep_last=1 slot and push the only VALID snapshot out of the
        retention window — that would brick recovery under the exact
        silent-truncation fault the harness proves survivable."""
        writer = CheckpointManager(str(tmp_path))   # retention off
        writer.save(_mlp(), _sgd(), 1)
        writer.save(_mlp(), _sgd(), 2)
        data = (tmp_path / "model.2").read_bytes()
        (tmp_path / "model.2").write_bytes(data[:len(data) // 2])
        mgr = CheckpointManager(str(tmp_path), keep_last=1)
        mgr.gc()
        assert (tmp_path / "model.1").exists(), os.listdir(tmp_path)
        assert mgr.load_latest()[2] == 1
        # the next healthy snapshot reclaims the corrupt debris
        mgr.save(_mlp(), _sgd(), 3)
        kept = {int(f.split(".")[1]) for f in os.listdir(tmp_path)}
        assert kept == {3}, sorted(os.listdir(tmp_path))

    def test_keep_all_by_default(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        for n in range(1, 5):
            mgr.save(_mlp(), _sgd(), n)
        kept = {int(f.split(".")[1]) for f in os.listdir(tmp_path)}
        assert kept == {1, 2, 3, 4}


class TestAsyncWriter:
    def test_async_roundtrip(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), async_write=True)
        model = _mlp()
        mgr.save(model, _sgd(), 1)
        mgr.join()
        assert sorted(os.listdir(tmp_path)) == [
            "commit.1", "manifest.1", "model.1", "optimMethod.1"]
        loaded, _, n = mgr.load_latest()
        x = np.ones((2, 4), np.float32)
        np.testing.assert_allclose(np.asarray(loaded.evaluate().forward(x)),
                                   np.asarray(model.evaluate().forward(x)),
                                   rtol=1e-6)

    def test_writer_error_reraised_at_next_save(self, tmp_path):
        from bigdl_tpu.utils import chaos, config
        config.set_property("bigdl.chaos.failWriteAt", 1)
        chaos.install()
        try:
            mgr = CheckpointManager(str(tmp_path), async_write=True)
            mgr.save(_mlp(), _sgd(), 1)     # enqueue; the write dies async
            with pytest.raises(SnapshotWriteError):
                mgr.save(_mlp(), _sgd(), 2)
        finally:
            chaos.uninstall()
            config.clear_property("bigdl.chaos.failWriteAt")

    def test_writer_error_reraised_at_join(self, tmp_path):
        from bigdl_tpu.utils import chaos, config
        config.set_property("bigdl.chaos.failWriteAt", 1)
        chaos.install()
        try:
            mgr = CheckpointManager(str(tmp_path), async_write=True)
            mgr.save(_mlp(), _sgd(), 1)
            with pytest.raises(SnapshotWriteError):
                mgr.join()
            # the error is consumed: a second join is clean
            mgr.join()
        finally:
            chaos.uninstall()
            config.clear_property("bigdl.chaos.failWriteAt")


class TestCapture:
    def test_captured_snapshot_ignores_later_publishes(self):
        """The async writer receives DETACHED byte payloads: the driver
        republishing new params or bumping counters between capture and
        write must not leak into the snapshot."""
        import pickle

        import jax
        model, method = _mlp(), _sgd()
        method.state["evalCounter"] = 7
        before = jax.tree_util.tree_map(np.asarray, model.params)
        blobs, _fps = _capture(model, method, 7)
        # simulate the next publish: wholesale tree replacement + counter
        model.params = jax.tree_util.tree_map(np.zeros_like, model.params)
        method.state["evalCounter"] = 99
        snap_model = pickle.loads(blobs["model.7"])
        snap_optim = pickle.loads(blobs["optimMethod.7"])
        for a, b in zip(jax.tree_util.tree_leaves(before),
                        jax.tree_util.tree_leaves(snap_model.params)):
            np.testing.assert_array_equal(a, np.asarray(b))
        assert snap_optim.state["evalCounter"] == 7


class TestRemoteScheme:
    def _clean(self):
        import fsspec
        fs = fsspec.filesystem("memory")
        if fs.exists("/ckpt_mgr"):
            fs.rm("/ckpt_mgr", recursive=True)

    def test_verified_snapshot_over_memory_scheme(self):
        self._clean()
        mgr = CheckpointManager("memory://ckpt_mgr/run")
        mgr.save(_mlp(), _sgd(), 2)
        names = set(file_io.listdir("memory://ckpt_mgr/run"))
        assert names == {"commit.2", "manifest.2", "model.2",
                         "optimMethod.2"}
        assert mgr.latest_valid()[2] == 2
        assert mgr.load_latest()[2] == 2


class TestWatchLatest:
    """The fleet promotion watcher's O(1)-per-tick poll (ISSUE 17)."""

    def test_empty_then_sees_new_commits(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        assert mgr.watch_latest() is None
        mgr.save(_mlp(), _sgd(), 1)
        assert mgr.watch_latest() == 1
        mgr.save(_mlp(), _sgd(), 5)
        assert mgr.watch_latest() == 5

    def test_steady_state_is_one_stat_no_listing(self, tmp_path,
                                                 monkeypatch):
        """While the directory mtime holds stable, repeat polls return
        the cached answer after a single stat — no listdir, no manifest
        reads."""
        import time as _time
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_mlp(), _sgd(), 3)
        # age the directory past the hot-window guard so the mtime is a
        # trustworthy fast-path anchor
        old = _time.time() - 60.0
        os.utime(tmp_path, (old, old))
        assert mgr.watch_latest() == 3
        calls = {"candidates": 0}
        real = mgr.candidates

        def counting():
            calls["candidates"] += 1
            return real()

        monkeypatch.setattr(mgr, "candidates", counting)
        for _ in range(50):
            assert mgr.watch_latest() == 3
        assert calls["candidates"] == 0

    def test_verify_runs_once_per_new_snapshot(self, tmp_path,
                                               monkeypatch):
        """A hot directory (mtime within the guard window) re-lists
        names every tick, but known-good snapshots are never
        re-verified — manifest reads stay at one per NEW snapshot."""
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_mlp(), _sgd(), 1)
        calls = {"verify": 0}
        real = mgr.verify

        def counting(n, has_manifest, deep=False):
            calls["verify"] += 1
            return real(n, has_manifest, deep)

        monkeypatch.setattr(mgr, "verify", counting)
        for _ in range(10):
            assert mgr.watch_latest() == 1
        assert calls["verify"] == 1
        mgr.save(_mlp(), _sgd(), 2)
        for _ in range(10):
            assert mgr.watch_latest() == 2
        assert calls["verify"] == 2

    def test_uncommitted_snapshot_is_invisible(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_mlp(), _sgd(), 1)
        assert mgr.watch_latest() == 1
        mgr2 = CheckpointManager(str(tmp_path))
        mgr2.save(_mlp(), _sgd(), 9)
        os.remove(tmp_path / "commit.9")
        assert mgr.watch_latest() == 1

    def test_corrupt_newest_falls_back_to_older(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(_mlp(), _sgd(), 1)
        mgr.save(_mlp(), _sgd(), 2)
        with open(tmp_path / "model.2", "r+b") as f:
            f.truncate(10)
        assert mgr.watch_latest() == 1
