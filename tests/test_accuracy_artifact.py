"""Real-data epochs-to-accuracy regression (reference north-star
protocol, ``models/lenet/Train.scala:35``).

ACCURACY_r03.json pins the TPU-measured number (98.05% top-1 in 15
epochs on real handwritten digits through the actual LeNet driver and
idx ingest); these tests regress the artifact's schema/threshold and
re-run a shortened training on the CPU mesh so the pipeline itself is
exercised every suite run.
"""

import json
import os

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_pinned_artifact_meets_protocol():
    path = os.path.join(REPO, "ACCURACY_r03.json")
    assert os.path.exists(path), "ACCURACY_r03.json missing"
    with open(path) as f:
        rec = json.load(f)
    assert rec["metric"] == "lenet_digits_top1"
    assert rec["value"] >= 0.98, rec
    assert rec["config"]["driver"] == "bigdl_tpu.models.lenet.train"


def test_pinned_r05_artifact_meets_protocol():
    """Round-5 artifact: TWO legs — the LeNet protocol plus the
    above-LeNet-scale point (the unmodified VGG-16 CIFAR-10 driver on
    real digit images in CIFAR binary format, BASELINE config #2)."""
    path = os.path.join(REPO, "ACCURACY_r05.json")
    assert os.path.exists(path), "ACCURACY_r05.json missing"
    with open(path) as f:
        rec = json.load(f)
    by_metric = {p["metric"]: p for p in rec["points"]}
    assert by_metric["lenet_digits_top1"]["value"] >= 0.98
    vgg = by_metric["vgg16_cifar_driver_digits_top1"]
    assert vgg["value"] >= 0.90, vgg
    assert "vgg" in vgg["config"]["driver"]


def test_digits_as_cifar_roundtrips_through_driver_ingest(tmp_path):
    """The r05 VGG leg's DATA PATH: real digit images written by
    ``make_digits_cifar`` must round-trip through the driver's
    production ``load_cifar10`` binary-batch parser with intact labels
    and pixel content.  (The 30-epoch 98.3% convergence itself runs on
    the chip via ``accuracy.py`` — a single VGG-16 CPU epoch is ~9 min,
    unaffordable in the suite, so the suite pins ingest + artifact.)"""
    from accuracy import make_digits_cifar
    from bigdl_tpu.dataset.datasets import load_cifar10

    n_train, n_test = make_digits_cifar(str(tmp_path))
    train = load_cifar10(str(tmp_path), "train")
    test = load_cifar10(str(tmp_path), "test")
    assert len(train) == n_train and len(test) == n_test
    labs = sorted({int(im.label) for im in train})
    assert labs == list(range(1, 11)), labs     # 1-based, all 10 digits
    img = train[0].data
    assert img.shape == (32, 32, 3)
    # grey replicated across channels survives the BGR flip unchanged
    np.testing.assert_array_equal(img[..., 0], img[..., 2])
    assert img.max() > 100, "pixels lost dynamic range in the round-trip"


@pytest.mark.slow
def test_driver_reaches_accuracy_on_real_digits(tmp_path, capsys):
    """Shortened re-run of the artifact protocol: real data through the
    real driver (idx ingest, normalizer, validation) must converge."""
    from accuracy import make_digits_idx
    from bigdl_tpu.models.lenet import train as drv

    make_digits_idx(str(tmp_path))
    drv.main(["-f", str(tmp_path), "-b", "32", "--max-epoch", "8",
              "-r", "0.05"])
    out = capsys.readouterr().out
    acc = float(out.strip().rsplit("Final Top1Accuracy:", 1)[-1]
                .split("(")[0])
    assert acc > 0.93, out
