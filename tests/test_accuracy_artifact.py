"""Real-data epochs-to-accuracy regression (reference north-star
protocol, ``models/lenet/Train.scala:35``).

ACCURACY_r03.json pins the TPU-measured number (98.05% top-1 in 15
epochs on real handwritten digits through the actual LeNet driver and
idx ingest); these tests regress the artifact's schema/threshold and
re-run a shortened training on the CPU mesh so the pipeline itself is
exercised every suite run.
"""

import json
import os

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_pinned_artifact_meets_protocol():
    path = os.path.join(REPO, "ACCURACY_r03.json")
    assert os.path.exists(path), "ACCURACY_r03.json missing"
    with open(path) as f:
        rec = json.load(f)
    assert rec["metric"] == "lenet_digits_top1"
    assert rec["value"] >= 0.98, rec
    assert rec["config"]["driver"] == "bigdl_tpu.models.lenet.train"


@pytest.mark.slow
def test_driver_reaches_accuracy_on_real_digits(tmp_path, capsys):
    """Shortened re-run of the artifact protocol: real data through the
    real driver (idx ingest, normalizer, validation) must converge."""
    from accuracy import make_digits_idx
    from bigdl_tpu.models.lenet import train as drv

    make_digits_idx(str(tmp_path))
    drv.main(["-f", str(tmp_path), "-b", "32", "--max-epoch", "8",
              "-r", "0.05"])
    out = capsys.readouterr().out
    acc = float(out.strip().rsplit("Final Top1Accuracy:", 1)[-1]
                .split("(")[0])
    assert acc > 0.93, out
