"""Keep the driver entry points working: single-chip forward compile and the
8-device distributed dry run."""

import jax
import numpy as np
import pytest

import __graft_entry__ as graft


def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)


@pytest.mark.slow
def test_entry_forward_compiles():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 1000)
    assert np.all(np.isfinite(np.asarray(out)))
