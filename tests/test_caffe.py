"""Caffe interop: persister → loader round-trip with forward parity, and
prototxt parsing (reference ``CaffeLoaderSpec`` / ``CaffePersisterSpec``)."""

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.utils.caffe import CaffeLoader, load_caffe, persister


def _cnn():
    m = (nn.Sequential()
         .add(nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1, name="conv1"))
         .add(nn.ReLU(name="relu1"))
         .add(nn.SpatialMaxPooling(2, 2, 2, 2, name="pool1"))
         .add(nn.SpatialCrossMapLRN(5, 1.0, 0.75, 1.0, name="lrn1"))
         .add(nn.Reshape((8 * 8 * 8,), batch_mode=True, name="flat"))
         .add(nn.Linear(8 * 8 * 8, 10, name="fc1"))
         .add(nn.SoftMax(name="prob")))
    m._ensure_init()
    return m


class TestCaffeRoundTrip:
    def test_cnn_export_import_forward_parity(self, tmp_path):
        model = _cnn()
        proto = str(tmp_path / "net.prototxt")
        weights = str(tmp_path / "net.caffemodel")
        persister.save(model, proto, weights, input_shape=[1, 3, 16, 16])

        back = load_caffe(proto, weights)
        x = np.random.RandomState(0).normal(
            size=(2, 3, 16, 16)).astype(np.float32)
        ours = np.asarray(model.evaluate().forward(x))
        theirs = np.asarray(back.evaluate().forward(x))
        np.testing.assert_allclose(theirs, ours, rtol=1e-5, atol=1e-5)

    def test_prototxt_is_text_and_structure_only(self, tmp_path):
        model = _cnn()
        proto = str(tmp_path / "net.prototxt")
        weights = str(tmp_path / "net.caffemodel")
        persister.save(model, proto, weights, input_shape=[1, 3, 16, 16])
        text = open(proto).read()
        assert 'type: "Convolution"' in text
        assert "blobs" not in text
        # binary weights larger than structure
        import os
        assert os.path.getsize(weights) > os.path.getsize(proto)

    def test_mlp_roundtrip(self, tmp_path):
        m = (nn.Sequential()
             .add(nn.Linear(6, 12, name="ip1")).add(nn.Tanh(name="t"))
             .add(nn.Linear(12, 3, name="ip2")).add(nn.SoftMax(name="p")))
        m._ensure_init()
        proto = str(tmp_path / "m.prototxt")
        weights = str(tmp_path / "m.caffemodel")
        persister.save(m, proto, weights, input_shape=[1, 6])
        back = load_caffe(proto, weights)
        x = np.random.RandomState(1).normal(size=(4, 6)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(back.evaluate().forward(x)),
            np.asarray(m.evaluate().forward(x)), rtol=1e-5, atol=1e-6)

    def test_unsupported_layer_reports_type(self, tmp_path):
        proto = tmp_path / "bad.prototxt"
        proto.write_text(
            'name: "bad"\ninput: "data"\n'
            'input_shape { dim: 1 dim: 4 }\n'
            'layer { name: "x" type: "MVN" bottom: "data" top: "x" }\n')
        with pytest.raises(ValueError, match="MVN"):
            load_caffe(str(proto))

    def test_train_phase_layers_skipped(self, tmp_path):
        m = (nn.Sequential().add(nn.Linear(4, 2, name="ip")).add(
            nn.SoftMax(name="p")))
        m._ensure_init()
        proto = str(tmp_path / "m.prototxt")
        weights = str(tmp_path / "m.caffemodel")
        persister.save(m, proto, weights, input_shape=[1, 4])
        # append a TRAIN-only layer to the prototxt
        with open(proto, "a") as f:
            f.write('layer { name: "drop" type: "Dropout" bottom: "blob1" '
                    'top: "blob1" include { phase: TRAIN } }\n')
        back = load_caffe(proto, weights)
        x = np.ones((1, 4), np.float32)
        out = np.asarray(back.evaluate().forward(x))
        assert out.shape == (1, 2)


class TestCaffeRegressions:
    def test_eltwise_sum_coeff_subtraction(self, tmp_path):
        proto = tmp_path / "sub.prototxt"
        proto.write_text(
            'name: "sub"\ninput: "a"\ninput: "b"\n'
            'input_shape { dim: 1 dim: 4 }\ninput_shape { dim: 1 dim: 4 }\n'
            'layer { name: "diff" type: "Eltwise" bottom: "a" bottom: "b" '
            'top: "diff" eltwise_param { operation: SUM coeff: 1 coeff: -1 } }\n')
        net = load_caffe(str(proto))
        a = np.asarray([[1., 2., 3., 4.]], np.float32)
        b = np.asarray([[0.5, 0.5, 0.5, 0.5]], np.float32)
        out = np.asarray(net.evaluate().forward([a, b]))
        np.testing.assert_allclose(out, a - b)

    def test_channel_softmax_on_4d(self, tmp_path):
        proto = tmp_path / "sm.prototxt"
        proto.write_text(
            'name: "sm"\ninput: "data"\n'
            'input_shape { dim: 1 dim: 3 dim: 2 dim: 2 }\n'
            'layer { name: "prob" type: "Softmax" bottom: "data" top: "prob" }\n')
        net = load_caffe(str(proto))
        x = np.random.RandomState(0).normal(size=(1, 3, 2, 2)).astype(np.float32)
        out = np.asarray(net.evaluate().forward(x))
        np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)

    def test_non_flatten_reshape_export_rejected(self, tmp_path):
        from bigdl_tpu.models.lenet import lenet5
        m = lenet5(10)
        m._ensure_init()
        with pytest.raises(ValueError, match="no caffe mapping"):
            persister.save(m, str(tmp_path / "x.prototxt"),
                           str(tmp_path / "x.caffemodel"),
                           input_shape=[1, 784])

    def test_floor_pooling_roundtrip_preserves_shape(self, tmp_path):
        """round_mode FLOOR survives export->import (caffe defaults to
        ceil; shape-changing silently without round_mode)."""
        m = (nn.Sequential()
             .add(nn.SpatialConvolution(1, 2, 3, 3, name="c"))
             .add(nn.SpatialMaxPooling(2, 2, 2, 2, name="p"))  # floor: 6->3
             .add(nn.InferReshape([0, -1], name="f"))
             .add(nn.Linear(2 * 6 * 6, 2, name="ip"))
             .add(nn.SoftMax(name="sm")))
        m._ensure_init()
        x = np.random.RandomState(2).normal(size=(1, 1, 15, 15)).astype(np.float32)
        ours = np.asarray(m.evaluate().forward(x))   # pool 13->6 floor
        proto = str(tmp_path / "f.prototxt")
        weights = str(tmp_path / "f.caffemodel")
        persister.save(m, proto, weights, input_shape=[1, 1, 15, 15])
        back = load_caffe(proto, weights)
        theirs = np.asarray(back.evaluate().forward(x))
        np.testing.assert_allclose(theirs, ours, rtol=1e-5, atol=1e-5)

    def test_trailing_inplace_layer_is_output(self, tmp_path):
        """A net ending in an in-place layer (bottom == top) must load with
        that blob as the output."""
        m = (nn.Sequential().add(nn.Linear(4, 6, name="ip")))
        m._ensure_init()
        proto = str(tmp_path / "ip.prototxt")
        weights = str(tmp_path / "ip.caffemodel")
        persister.save(m, proto, weights, input_shape=[1, 4])
        with open(proto, "a") as f:
            f.write('layer { name: "relu" type: "ReLU" bottom: "blob0" '
                    'top: "blob0" }\n')
        net = load_caffe(proto, weights)
        x = np.random.RandomState(3).normal(size=(2, 4)).astype(np.float32)
        out = np.asarray(net.evaluate().forward(x))
        assert out.shape == (2, 6)
        assert np.all(out >= 0), "trailing in-place ReLU not applied"


class TestLegacyV1Format:
    """Pre-2014 `layers { type: ENUM }` prototxts/caffemodels (reference
    ``V1LayerConverter.scala``): upgraded in place, converted by the same
    V2 converter set."""

    _PROTO = '''name: "legacy"
layers { name: "mnist" type: DATA top: "data" top: "label"
         include { phase: TEST } }
layers { name: "conv1" type: CONVOLUTION bottom: "data" top: "conv1"
         convolution_param { num_output: 2 kernel_size: 3 } }
layers { name: "relu1" type: RELU bottom: "conv1" top: "conv1" }
layers { name: "pool1" type: POOLING bottom: "conv1" top: "pool1"
         pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layers { name: "ip1" type: INNER_PRODUCT bottom: "pool1" top: "ip1"
         inner_product_param { num_output: 3 } }
layers { name: "split" type: SPLIT bottom: "ip1" top: "ip1_a" top: "ip1_b" }
layers { name: "accuracy" type: ACCURACY bottom: "ip1_a" bottom: "label" }
layers { name: "loss" type: SOFTMAX_LOSS bottom: "ip1_b" bottom: "label"
         top: "loss" }
'''

    def _weights(self, tmp_path):
        import bigdl_tpu.utils.caffe.caffe_minimal_pb2 as pb
        rng = np.random.RandomState(0)
        net = pb.NetParameter()
        conv = net.layers.add()
        conv.name, conv.type = "conv1", pb.V1LayerParameter.CONVOLUTION
        w = rng.normal(size=(2, 1, 3, 3)).astype(np.float32)
        b = rng.normal(size=(2,)).astype(np.float32)
        for arr in (w, b):
            blob = conv.blobs.add()
            blob.shape.dim.extend(arr.shape)
            blob.data.extend(arr.ravel().tolist())
        ip = net.layers.add()
        ip.name, ip.type = "ip1", pb.V1LayerParameter.INNER_PRODUCT
        # 6x6 input -> conv3 -> 4x4 -> pool2 -> 2x2 -> flatten 2*2*2=8
        wip = rng.normal(size=(3, 8)).astype(np.float32)
        bip = rng.normal(size=(3,)).astype(np.float32)
        for arr in (wip, bip):
            blob = ip.blobs.add()
            blob.shape.dim.extend(arr.shape)
            blob.data.extend(arr.ravel().tolist())
        path = str(tmp_path / "legacy.caffemodel")
        with open(path, "wb") as f:
            f.write(net.SerializeToString())
        return path, w, b, wip, bip

    def test_v1_train_val_net_loads_and_matches_manual(self, tmp_path):
        proto = tmp_path / "legacy.prototxt"
        proto.write_text(self._PROTO)
        weights, w, b, wip, bip = self._weights(tmp_path)
        net = load_caffe(str(proto), weights)

        x = np.random.RandomState(1).normal(
            size=(1, 1, 6, 6)).astype(np.float32)
        # graph inputs: [data, label] (DATA layer tops); label unused
        out = np.asarray(net.evaluate().forward([x, np.zeros((1,), np.float32)]))

        ref = (nn.Sequential()
               .add(nn.SpatialConvolution(1, 2, 3, 3,
                                          init_weight=np.transpose(w, (2, 3, 1, 0)),
                                          init_bias=b))
               .add(nn.ReLU())
               .add(nn.SpatialMaxPooling(2, 2, 2, 2).ceil())
               .add(nn.InferReshape([0, -1]))
               .add(nn.Linear(8, 3, init_weight=np.ascontiguousarray(wip.T),
                              init_bias=bip)))
        logits = np.asarray(ref.evaluate().forward(x))
        want = np.exp(logits) / np.exp(logits).sum(axis=1, keepdims=True)
        np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)

    def test_unsupported_v1_type_reports_name(self, tmp_path):
        proto = tmp_path / "bad.prototxt"
        proto.write_text('layers { name: "w" type: WINDOW_DATA top: "x" }\n')
        with pytest.raises(ValueError, match="WINDOW_DATA|24"):
            load_caffe(str(proto))

    def test_topless_loss_and_legacy_4d_ip_blobs(self, tmp_path):
        """The canonical pre-2014 train prototxt: topless SOFTMAX_LOSS and
        BlobShape-free 4-D legacy-dim weight blobs."""
        import bigdl_tpu.utils.caffe.caffe_minimal_pb2 as pb
        proto = tmp_path / "legacy.prototxt"
        proto.write_text('''name: "legacy"
input: "data"
input_shape { dim: 1 dim: 4 }
layers { name: "ip1" type: INNER_PRODUCT bottom: "data" top: "ip1"
         inner_product_param { num_output: 3 } }
layers { name: "loss" type: SOFTMAX_LOSS bottom: "ip1" bottom: "label" }
''')
        rng = np.random.RandomState(2)
        w = rng.normal(size=(3, 4)).astype(np.float32)
        b = rng.normal(size=(3,)).astype(np.float32)
        net = pb.NetParameter()
        ip = net.layers.add()
        ip.name, ip.type = "ip1", pb.V1LayerParameter.INNER_PRODUCT
        blob = ip.blobs.add()      # legacy dims, NO BlobShape
        blob.num, blob.channels = 1, 1
        blob.height, blob.width = 3, 4
        blob.data.extend(w.ravel().tolist())
        bb = ip.blobs.add()
        bb.num = bb.channels = bb.height = 1
        bb.width = 3
        bb.data.extend(b.tolist())
        # an exotic layer in the WEIGHTS net must not block the load
        junk = net.layers.add()
        junk.name, junk.type = "im2col", pb.V1LayerParameter.IM2COL
        weights = str(tmp_path / "legacy.caffemodel")
        with open(weights, "wb") as f:
            f.write(net.SerializeToString())

        loaded = load_caffe(str(proto), weights)
        x = rng.normal(size=(1, 4)).astype(np.float32)
        out = np.asarray(loaded.evaluate().forward(x))
        logits = x @ w.T + b
        want = np.exp(logits) / np.exp(logits).sum(axis=1, keepdims=True)
        np.testing.assert_allclose(out, want[:, :, None] if out.ndim == 3
                                   else want, rtol=1e-4, atol=1e-5)

    def test_dangling_split_branch_is_output(self, tmp_path):
        proto = tmp_path / "split.prototxt"
        proto.write_text('''name: "s"
input: "data"
input_shape { dim: 1 dim: 4 }
layers { name: "split" type: SPLIT bottom: "data" top: "a" top: "b" }
layers { name: "acc" type: ACCURACY bottom: "a" bottom: "a" }
''')
        net = load_caffe(str(proto))
        x = np.ones((1, 4), np.float32)
        out = np.asarray(net.evaluate().forward(x))
        np.testing.assert_allclose(out, x)

    def test_mixed_layer_formats_rejected(self, tmp_path):
        proto = tmp_path / "mix.prototxt"
        proto.write_text(
            'layers { name: "c" type: CONVOLUTION top: "c" }\n'
            'layer { name: "r" type: "ReLU" bottom: "c" top: "c" }\n')
        with pytest.raises(ValueError, match="mixes legacy"):
            load_caffe(str(proto))


class TestConverterRegistryParity:
    """The reference's full converter registry (``Converter.scala:573-605``):
    BatchNorm/Scale (the ResNet-era pair) plus the activation/shape layer
    set and the loss->criterion channel (``CaffeLoader.scala:401-418``)."""

    def _write_net(self, tmp_path, prototxt, weight_layers):
        """weight_layers: [(name, type, [np blobs])] -> caffemodel file."""
        from bigdl_tpu.utils.caffe import caffe_minimal_pb2 as pb
        proto = tmp_path / "net.prototxt"
        proto.write_text(prototxt)
        net = pb.NetParameter()
        for name, ltype, blobs in weight_layers:
            layer = net.layer.add()
            layer.name, layer.type = name, ltype
            for arr in blobs:
                b = layer.blobs.add()
                b.shape.dim.extend(arr.shape)
                b.data.extend(float(v) for v in arr.ravel())
        weights = tmp_path / "net.caffemodel"
        weights.write_bytes(net.SerializeToString())
        return str(proto), str(weights)

    def test_batchnorm_scale_eltwise_resnet_branch(self, tmp_path):
        """The reference-era ResNet building block: Conv -> BatchNorm ->
        Scale -> ReLU with an Eltwise residual add — golden parity against
        the manual computation from the same blobs."""
        rng = np.random.RandomState(0)
        C = 4
        kern = rng.normal(size=(C, C, 3, 3)).astype(np.float32) * 0.2
        mean = rng.normal(size=(C,)).astype(np.float32)
        var = rng.uniform(0.5, 2.0, size=(C,)).astype(np.float32)
        sf = np.asarray([4.0], np.float32)          # BVLC unscaled sums
        gamma = rng.uniform(0.5, 1.5, size=(C,)).astype(np.float32)
        beta = rng.normal(size=(C,)).astype(np.float32)
        proto, weights = self._write_net(
            tmp_path,
            'name: "branch"\ninput: "data"\n'
            'input_shape { dim: 1 dim: 4 dim: 6 dim: 6 }\n'
            'layer { name: "conv" type: "Convolution" bottom: "data" '
            'top: "c" convolution_param { num_output: 4 kernel_size: 3 '
            'pad: 1 bias_term: false } }\n'
            'layer { name: "bn" type: "BatchNorm" bottom: "c" top: "c" '
            'batch_norm_param { eps: 0.001 } }\n'
            'layer { name: "sc" type: "Scale" bottom: "c" top: "c" '
            'scale_param { bias_term: true } }\n'
            'layer { name: "sum" type: "Eltwise" bottom: "c" '
            'bottom: "data" top: "s" }\n'
            'layer { name: "relu" type: "ReLU" bottom: "s" top: "s" }\n',
            [("conv", "Convolution", [kern * sf[0] / sf[0]]),
             ("bn", "BatchNorm", [mean * sf[0], var * sf[0], sf]),
             ("sc", "Scale", [gamma, beta])])
        # re-write conv blob without the silly identity math
        net = load_caffe(proto, weights)
        x = rng.normal(size=(2, C, 6, 6)).astype(np.float32)
        got = np.asarray(net.evaluate().forward(x))

        import jax.numpy as jnp
        import jax
        conv = jax.lax.conv_general_dilated(
            jnp.asarray(x), jnp.asarray(kern), (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        bn = (np.asarray(conv) - mean[None, :, None, None]) / np.sqrt(
            var[None, :, None, None] + 1e-3)
        scaled = bn * gamma[None, :, None, None] + beta[None, :, None, None]
        want = np.maximum(scaled + x, 0.0)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_batchnorm_affine_roundtrip_export(self, tmp_path):
        """Affine BN exports as a BatchNorm + Scale pair and re-imports
        with forward parity (the VERDICT done-criterion round trip)."""
        m = (nn.Sequential()
             .add(nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1,
                                        name="conv"))
             .add(nn.SpatialBatchNormalization(4, name="bn"))
             .add(nn.ReLU(name="relu")))
        m._ensure_init()
        # non-trivial running stats + affine params
        rng = np.random.RandomState(1)
        bn = m.children[1]
        bn.state["running_mean"] = rng.normal(size=(4,)).astype(np.float32)
        bn.state["running_var"] = rng.uniform(
            0.5, 2.0, size=(4,)).astype(np.float32)
        proto = str(tmp_path / "bn.prototxt")
        weights = str(tmp_path / "bn.caffemodel")
        persister.save(m, proto, weights, input_shape=[1, 3, 8, 8])
        assert 'type: "BatchNorm"' in open(proto).read()
        assert 'type: "Scale"' in open(proto).read()
        back = load_caffe(proto, weights)
        x = rng.normal(size=(2, 3, 8, 8)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(back.evaluate().forward(x)),
            np.asarray(m.evaluate().forward(x)), rtol=1e-4, atol=1e-4)

    def test_activation_and_shape_layers_roundtrip(self, tmp_path):
        """ELU/PReLU/Power/Log/Exp/AbsVal/Threshold/Bias/Tile/Reshape all
        export and re-import with forward parity."""
        m = (nn.Sequential()
             .add(nn.ELU(0.7, name="elu"))
             .add(nn.Abs(name="abs"))
             .add(nn.Power(2.0, 1.5, 0.25, name="pow"))
             .add(nn.Log(name="log"))
             .add(nn.Exp(name="exp"))
             .add(nn.Threshold(0.9, name="th"))
             .add(nn.PReLU(4, name="prelu"))
             .add(nn.Add(4, name="bias"))
             .add(nn.InferReshape([0, 2, 2], name="rs")))
        m._ensure_init()
        proto = str(tmp_path / "acts.prototxt")
        weights = str(tmp_path / "acts.caffemodel")
        persister.save(m, proto, weights, input_shape=[1, 4])
        back = load_caffe(proto, weights)
        x = np.random.RandomState(2).normal(size=(3, 4)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(back.evaluate().forward(x)),
            np.asarray(m.evaluate().forward(x)), rtol=1e-5, atol=1e-6)

    def test_tile_roundtrip(self, tmp_path):
        m = nn.Sequential().add(nn.Replicate(3, 2, name="tile"))
        m._ensure_init()
        proto = str(tmp_path / "t.prototxt")
        weights = str(tmp_path / "t.caffemodel")
        persister.save(m, proto, weights, input_shape=[1, 4])
        back = load_caffe(proto, weights)
        x = np.random.RandomState(3).normal(size=(2, 4)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(back.forward(x)),
                                   np.asarray(m.forward(x)))

    def test_slice_imports_with_slice_points(self, tmp_path):
        proto = tmp_path / "sl.prototxt"
        proto.write_text(
            'name: "sl"\ninput: "data"\n'
            'input_shape { dim: 1 dim: 6 }\n'
            'layer { name: "sl" type: "Slice" bottom: "data" top: "a" '
            'top: "b" slice_param { axis: 1 slice_point: 2 } }\n'
            'layer { name: "pa" type: "Power" bottom: "a" top: "pa" '
            'power_param { power: 1 scale: 2 } }\n'
            'layer { name: "pb" type: "Power" bottom: "b" top: "pb" '
            'power_param { power: 1 scale: 3 } }\n')
        net = load_caffe(str(proto))
        x = np.arange(12, dtype=np.float32).reshape(2, 6)
        out = net.evaluate().forward(x)
        np.testing.assert_allclose(np.asarray(out[0]), x[:, :2] * 2)
        np.testing.assert_allclose(np.asarray(out[1]), x[:, 2:] * 3)

    def test_loss_layers_become_criterions(self, tmp_path):
        """SOFTMAX_LOSS keeps the inference softmax AND registers
        ClassNLLCriterion; EuclideanLoss is criterion-only (no module,
        bottoms consumed)."""
        from bigdl_tpu.utils.caffe.loader import CaffeLoader
        proto = tmp_path / "train.prototxt"
        proto.write_text(
            'name: "train"\ninput: "data"\ninput: "label"\n'
            'input_shape { dim: 1 dim: 4 }\ninput_shape { dim: 1 }\n'
            'layer { name: "ip" type: "InnerProduct" bottom: "data" '
            'top: "ip" inner_product_param { num_output: 3 } }\n'
            'layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip" '
            'bottom: "label" top: "loss" }\n')
        from bigdl_tpu.utils.caffe import caffe_minimal_pb2 as pb
        net = pb.NetParameter()
        layer = net.layer.add()
        layer.name, layer.type = "ip", "InnerProduct"
        w = np.random.RandomState(4).normal(size=(3, 4)).astype(np.float32)
        for arr in (w, np.zeros(3, np.float32)):
            b = layer.blobs.add()
            b.shape.dim.extend(arr.shape)
            b.data.extend(float(v) for v in arr.ravel())
        weights = tmp_path / "train.caffemodel"
        weights.write_bytes(net.SerializeToString())
        loader = CaffeLoader(str(proto), str(weights))
        g = loader.load()
        crit = loader.criterion()
        assert isinstance(crit, nn.ClassNLLCriterion)
        x = np.random.RandomState(5).normal(size=(2, 4)).astype(np.float32)
        out = np.asarray(g.evaluate().forward([x, np.zeros((2, 1),
                                                           np.float32)]))
        np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)

        proto2 = tmp_path / "euc.prototxt"
        proto2.write_text(
            'name: "euc"\ninput: "pred"\ninput: "tgt"\n'
            'input_shape { dim: 1 dim: 4 }\ninput_shape { dim: 1 dim: 4 }\n'
            'layer { name: "id" type: "Power" bottom: "pred" top: "out" }\n'
            'layer { name: "loss" type: "EuclideanLoss" bottom: "out" '
            'bottom: "tgt" top: "loss" }\n')
        loader2 = CaffeLoader(str(proto2))
        g2 = loader2.load()
        assert isinstance(loader2.criterion(), nn.MSECriterion)
        # criterion-only layer left no module: "out" is the graph output
        y = np.ones((1, 4), np.float32)
        out2 = np.asarray(g2.evaluate().forward([y, y]))
        np.testing.assert_allclose(out2, y)

    def test_v1_power_threshold_slice_upgrade(self, tmp_path):
        proto = tmp_path / "v1.prototxt"
        proto.write_text(
            'name: "v1"\ninput: "data"\n'
            'input_dim: 1\ninput_dim: 4\n'
            'layers { name: "p" type: POWER bottom: "data" top: "p" '
            'power_param { power: 2 } }\n'
            'layers { name: "t" type: THRESHOLD bottom: "p" top: "t" '
            'threshold_param { threshold: 4 } }\n')
        net = load_caffe(str(proto))
        x = np.asarray([[1., 2., 3., 4.]], np.float32)
        out = np.asarray(net.evaluate().forward(x))
        np.testing.assert_allclose(out, (x ** 2 > 4).astype(np.float32) *
                                   (x ** 2))
