"""Caffe interop: persister → loader round-trip with forward parity, and
prototxt parsing (reference ``CaffeLoaderSpec`` / ``CaffePersisterSpec``)."""

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.utils.caffe import CaffeLoader, load_caffe, persister


def _cnn():
    m = (nn.Sequential()
         .add(nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1, name="conv1"))
         .add(nn.ReLU(name="relu1"))
         .add(nn.SpatialMaxPooling(2, 2, 2, 2, name="pool1"))
         .add(nn.SpatialCrossMapLRN(5, 1.0, 0.75, 1.0, name="lrn1"))
         .add(nn.Reshape((8 * 8 * 8,), batch_mode=True, name="flat"))
         .add(nn.Linear(8 * 8 * 8, 10, name="fc1"))
         .add(nn.SoftMax(name="prob")))
    m._ensure_init()
    return m


class TestCaffeRoundTrip:
    def test_cnn_export_import_forward_parity(self, tmp_path):
        model = _cnn()
        proto = str(tmp_path / "net.prototxt")
        weights = str(tmp_path / "net.caffemodel")
        persister.save(model, proto, weights, input_shape=[1, 3, 16, 16])

        back = load_caffe(proto, weights)
        x = np.random.RandomState(0).normal(
            size=(2, 3, 16, 16)).astype(np.float32)
        ours = np.asarray(model.evaluate().forward(x))
        theirs = np.asarray(back.evaluate().forward(x))
        np.testing.assert_allclose(theirs, ours, rtol=1e-5, atol=1e-5)

    def test_prototxt_is_text_and_structure_only(self, tmp_path):
        model = _cnn()
        proto = str(tmp_path / "net.prototxt")
        weights = str(tmp_path / "net.caffemodel")
        persister.save(model, proto, weights, input_shape=[1, 3, 16, 16])
        text = open(proto).read()
        assert 'type: "Convolution"' in text
        assert "blobs" not in text
        # binary weights larger than structure
        import os
        assert os.path.getsize(weights) > os.path.getsize(proto)

    def test_mlp_roundtrip(self, tmp_path):
        m = (nn.Sequential()
             .add(nn.Linear(6, 12, name="ip1")).add(nn.Tanh(name="t"))
             .add(nn.Linear(12, 3, name="ip2")).add(nn.SoftMax(name="p")))
        m._ensure_init()
        proto = str(tmp_path / "m.prototxt")
        weights = str(tmp_path / "m.caffemodel")
        persister.save(m, proto, weights, input_shape=[1, 6])
        back = load_caffe(proto, weights)
        x = np.random.RandomState(1).normal(size=(4, 6)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(back.evaluate().forward(x)),
            np.asarray(m.evaluate().forward(x)), rtol=1e-5, atol=1e-6)

    def test_unsupported_layer_reports_type(self, tmp_path):
        proto = tmp_path / "bad.prototxt"
        proto.write_text(
            'name: "bad"\ninput: "data"\n'
            'input_shape { dim: 1 dim: 4 }\n'
            'layer { name: "x" type: "PReLU" bottom: "data" top: "x" }\n')
        with pytest.raises(ValueError, match="PReLU"):
            load_caffe(str(proto))

    def test_train_phase_layers_skipped(self, tmp_path):
        m = (nn.Sequential().add(nn.Linear(4, 2, name="ip")).add(
            nn.SoftMax(name="p")))
        m._ensure_init()
        proto = str(tmp_path / "m.prototxt")
        weights = str(tmp_path / "m.caffemodel")
        persister.save(m, proto, weights, input_shape=[1, 4])
        # append a TRAIN-only layer to the prototxt
        with open(proto, "a") as f:
            f.write('layer { name: "drop" type: "Dropout" bottom: "blob1" '
                    'top: "blob1" include { phase: TRAIN } }\n')
        back = load_caffe(proto, weights)
        x = np.ones((1, 4), np.float32)
        out = np.asarray(back.evaluate().forward(x))
        assert out.shape == (1, 2)


class TestCaffeRegressions:
    def test_eltwise_sum_coeff_subtraction(self, tmp_path):
        proto = tmp_path / "sub.prototxt"
        proto.write_text(
            'name: "sub"\ninput: "a"\ninput: "b"\n'
            'input_shape { dim: 1 dim: 4 }\ninput_shape { dim: 1 dim: 4 }\n'
            'layer { name: "diff" type: "Eltwise" bottom: "a" bottom: "b" '
            'top: "diff" eltwise_param { operation: SUM coeff: 1 coeff: -1 } }\n')
        net = load_caffe(str(proto))
        a = np.asarray([[1., 2., 3., 4.]], np.float32)
        b = np.asarray([[0.5, 0.5, 0.5, 0.5]], np.float32)
        out = np.asarray(net.evaluate().forward([a, b]))
        np.testing.assert_allclose(out, a - b)

    def test_channel_softmax_on_4d(self, tmp_path):
        proto = tmp_path / "sm.prototxt"
        proto.write_text(
            'name: "sm"\ninput: "data"\n'
            'input_shape { dim: 1 dim: 3 dim: 2 dim: 2 }\n'
            'layer { name: "prob" type: "Softmax" bottom: "data" top: "prob" }\n')
        net = load_caffe(str(proto))
        x = np.random.RandomState(0).normal(size=(1, 3, 2, 2)).astype(np.float32)
        out = np.asarray(net.evaluate().forward(x))
        np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)

    def test_non_flatten_reshape_export_rejected(self, tmp_path):
        from bigdl_tpu.models.lenet import lenet5
        m = lenet5(10)
        m._ensure_init()
        with pytest.raises(ValueError, match="no caffe mapping"):
            persister.save(m, str(tmp_path / "x.prototxt"),
                           str(tmp_path / "x.caffemodel"),
                           input_shape=[1, 784])

    def test_floor_pooling_roundtrip_preserves_shape(self, tmp_path):
        """round_mode FLOOR survives export->import (caffe defaults to
        ceil; shape-changing silently without round_mode)."""
        m = (nn.Sequential()
             .add(nn.SpatialConvolution(1, 2, 3, 3, name="c"))
             .add(nn.SpatialMaxPooling(2, 2, 2, 2, name="p"))  # floor: 6->3
             .add(nn.InferReshape([0, -1], name="f"))
             .add(nn.Linear(2 * 6 * 6, 2, name="ip"))
             .add(nn.SoftMax(name="sm")))
        m._ensure_init()
        x = np.random.RandomState(2).normal(size=(1, 1, 15, 15)).astype(np.float32)
        ours = np.asarray(m.evaluate().forward(x))   # pool 13->6 floor
        proto = str(tmp_path / "f.prototxt")
        weights = str(tmp_path / "f.caffemodel")
        persister.save(m, proto, weights, input_shape=[1, 1, 15, 15])
        back = load_caffe(proto, weights)
        theirs = np.asarray(back.evaluate().forward(x))
        np.testing.assert_allclose(theirs, ours, rtol=1e-5, atol=1e-5)

    def test_trailing_inplace_layer_is_output(self, tmp_path):
        """A net ending in an in-place layer (bottom == top) must load with
        that blob as the output."""
        m = (nn.Sequential().add(nn.Linear(4, 6, name="ip")))
        m._ensure_init()
        proto = str(tmp_path / "ip.prototxt")
        weights = str(tmp_path / "ip.caffemodel")
        persister.save(m, proto, weights, input_shape=[1, 4])
        with open(proto, "a") as f:
            f.write('layer { name: "relu" type: "ReLU" bottom: "blob0" '
                    'top: "blob0" }\n')
        net = load_caffe(proto, weights)
        x = np.random.RandomState(3).normal(size=(2, 4)).astype(np.float32)
        out = np.asarray(net.evaluate().forward(x))
        assert out.shape == (2, 6)
        assert np.all(out >= 0), "trailing in-place ReLU not applied"
