"""Concurrency soundness: the whole-package static pass + the runtime
lock witness (tentpole) and the chaos ``lockDelayAt`` injector.

Three legs:

- **Static pass units** — synthetic modules prove each rule fires
  (missing guarded-by, mutation outside its guard, package lock-order
  inversion, unguarded async abort) and that inline
  ``# lint: allow(...)`` silences exactly the annotated line.
- **Gate** — the real package analyzes clean, through the same CLI the
  acceptance criterion names.
- **Runtime witness** — strict raises a structured
  :class:`LockOrderViolation` (both sites, both stacks) BEFORE the
  blocking acquire; a chaos-seeded two-thread A→B/B→A inversion is
  caught deterministically with the violation in hand, not a wedged
  suite.
"""

import os
import subprocess
import sys
import threading
import time

import pytest

from bigdl_tpu.analysis import concurrency as conc
from bigdl_tpu.analysis import lockwitness
from bigdl_tpu.utils import chaos, config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "bigdl_tpu")


def _write(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


_THREADED_HEADER = """\
import threading

from bigdl_tpu import analysis


class Worker:
    def __init__(self):
        self._lock = analysis.make_lock("synth.worker")
        self.count = 0{annotation}
        self._t = None

    def start(self):
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()
"""


class TestStaticGuardedBy:
    def test_two_root_mutation_without_annotation_is_flagged(self, tmp_path):
        src = _THREADED_HEADER.format(annotation="") + """
    def _run(self):
        while True:
            self.count += 1

    def bump(self):
        self.count += 1
"""
        findings = conc.analyze([_write(tmp_path, "counting.py", src)])
        assert [f.rule for f in findings] == ["missing-guarded-by"]
        assert "Worker.count" in str(findings[0])
        assert "guarded-by" in str(findings[0])

    def test_annotated_and_locked_everywhere_is_clean(self, tmp_path):
        src = _THREADED_HEADER.format(
            annotation="   # guarded-by: _lock") + """
    def _run(self):
        while True:
            with self._lock:
                self.count += 1

    def bump(self):
        with self._lock:
            self.count += 1
"""
        assert conc.analyze([_write(tmp_path, "clean.py", src)]) == []

    def test_mutation_outside_named_guard_is_flagged(self, tmp_path):
        src = _THREADED_HEADER.format(
            annotation="   # guarded-by: _lock") + """
    def _run(self):
        while True:
            with self._lock:
                self.count += 1

    def bump(self):
        self.count += 1
"""
        findings = conc.analyze([_write(tmp_path, "outside.py", src)])
        assert [f.rule for f in findings] == ["guarded-mutation-outside-lock"]
        assert "'_lock'" in str(findings[0])

    def test_guard_held_by_caller_propagates(self, tmp_path):
        """A private helper mutating guarded state is clean when EVERY
        caller holds the guard (must-held propagation through calls)."""
        src = _THREADED_HEADER.format(
            annotation="   # guarded-by: _lock") + """
    def _run(self):
        while True:
            with self._lock:
                self._bump_locked()

    def bump(self):
        with self._lock:
            self._bump_locked()

    def _bump_locked(self):
        self.count += 1
"""
        assert conc.analyze([_write(tmp_path, "helper.py", src)]) == []

    def test_inline_allow_silences_exactly_that_line(self, tmp_path):
        src = _THREADED_HEADER.format(annotation="") + """
    def _run(self):
        while True:
            self.count += 1   # lint: allow(missing-guarded-by)

    def bump(self):
        self.count += 1
"""
        # the finding anchors at the FIRST live mutation site; allowing
        # it there silences the (single) finding for this attribute
        assert conc.analyze([_write(tmp_path, "allowed.py", src)]) == []


class TestStaticLockOrder:
    def test_package_wide_inversion_is_flagged_with_both_sites(
            self, tmp_path):
        src = """
import threading

from bigdl_tpu import analysis


class Pair:
    def __init__(self):
        self._a = analysis.make_lock("synth.a")
        self._b = analysis.make_lock("synth.b")
        self._t = threading.Thread(target=self.fwd, daemon=True)

    def fwd(self):
        with self._a:
            with self._b:
                pass

    def rev(self):
        with self._b:
            with self._a:
                pass
"""
        findings = conc.analyze([_write(tmp_path, "inverted.py", src)])
        assert [f.rule for f in findings] == ["lock-order-inversion"]
        msg = str(findings[0])
        assert "'synth.a'" in msg and "'synth.b'" in msg
        # both sites named: the finding line and the reverse site
        assert "inverted.py:" in msg.split("] ", 1)[1]

    def test_consistent_order_is_clean(self, tmp_path):
        src = """
import threading

from bigdl_tpu import analysis


class Pair:
    def __init__(self):
        self._a = analysis.make_lock("synth.c")
        self._b = analysis.make_lock("synth.d")
        self._t = threading.Thread(target=self.fwd, daemon=True)

    def fwd(self):
        with self._a:
            with self._b:
                pass

    def also_fwd(self):
        with self._a:
            with self._b:
                pass
"""
        assert conc.analyze([_write(tmp_path, "ordered.py", src)]) == []


class TestStaticAsyncAbort:
    def test_unguarded_async_raise_is_flagged(self, tmp_path):
        src = """
from bigdl_tpu.utils.elastic import _async_raise


def kill(tid):
    _async_raise(tid, RuntimeError)
"""
        findings = conc.analyze([_write(tmp_path, "aborter.py", src)])
        assert [f.rule for f in findings] == ["async-abort-unguarded"]

    def test_abort_under_lock_with_recheck_is_clean(self, tmp_path):
        src = """
import threading

from bigdl_tpu import analysis
from bigdl_tpu.utils.elastic import _async_raise


class Watchdog:
    def __init__(self):
        self._lock = analysis.make_lock("synth.watchdog")
        self.done = False

    def fire(self, tid):
        with self._lock:
            if self.done:
                return
            _async_raise(tid, RuntimeError)
"""
        assert conc.analyze([_write(tmp_path, "guarded.py", src)]) == []


class TestPackageGate:
    def test_package_analyzes_clean(self):
        findings = conc.analyze([PKG])
        assert findings == [], \
            "concurrency findings in bigdl_tpu/ (fix or silence inline):" \
            "\n" + "\n".join(str(f) for f in findings)

    def test_cli_entry_point_exits_zero(self):
        """The exact command the acceptance criterion names."""
        proc = subprocess.run(
            [sys.executable, "-m", "bigdl_tpu.analysis.concurrency",
             "bigdl_tpu"],
            cwd=REPO, capture_output=True, text=True, timeout=300,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_unknown_rule_is_an_error_listing_known_rules(self, capsys):
        rc = conc.main(["bigdl_tpu", "--rule", "no-such-rule"])
        assert rc != 0
        err = capsys.readouterr().err
        assert "unknown rule(s): no-such-rule" in err
        for rule in conc.CONCURRENCY_RULES:
            assert rule in err

    def test_inventory_names_the_runtime_locks(self):
        inv = conc.thread_inventory([PKG])
        names = {l["name"] for l in inv["locks"]}
        # the factory-routed core: one witness name per lock class
        for expect in ("serving.engine", "serving.handle", "lm.engine",
                       "lm.stream", "engine.prefetch", "fleet.supervisor",
                       "ingest.ring", "checkpoint.writer"):
            assert expect in names, f"{expect} missing from inventory"
        assert inv["threads"], "no thread entry points found"


# ---------------------------------------------------------------------------
# runtime witness
# ---------------------------------------------------------------------------

class TestLockWitness:
    def test_tier1_suite_runs_armed_strict(self):
        """The conftest autouse fixture must have armed the witness for
        this very test."""
        assert lockwitness.armed() == "strict"

    def test_inversion_raises_structured_violation(self):
        a = lockwitness.make_lock("t.struct_a")
        b = lockwitness.make_lock("t.struct_b")
        with a:
            with b:
                pass
        with b:
            with pytest.raises(lockwitness.LockOrderViolation) as ei:
                with a:
                    pass
        v = ei.value
        assert v.edge == ("t.struct_b", "t.struct_a")
        assert v.reverse_edge == ("t.struct_a", "t.struct_b")
        assert "test_concurrency.py" in v.site
        assert "test_concurrency.py" in v.reverse_site
        assert v.stack and v.reverse_stack
        # both stacks ride the message too
        assert "this acquisition" in str(v) and "prior acquisition" in str(v)

    def test_check_runs_before_the_blocking_acquire(self):
        """The witness must raise while the conflicting lock is HELD by
        another thread — i.e. before this thread blocks on it — or it
        could never report the deadlock it exists to prevent."""
        a = lockwitness.make_lock("t.pre_a")
        b = lockwitness.make_lock("t.pre_b")
        with a:
            with b:
                pass
        entered = threading.Event()
        release = threading.Event()

        def holder():
            with a:                      # other thread HOLDS a
                entered.set()
                release.wait(5.0)

        t = threading.Thread(target=holder, daemon=True)
        t.start()
        assert entered.wait(5.0)
        try:
            with b:
                # without the pre-acquire check this would deadlock
                # against holder(); instead it raises immediately
                with pytest.raises(lockwitness.LockOrderViolation):
                    with a:
                        pass
        finally:
            release.set()
            t.join(5.0)

    def test_warn_mode_counts_instead_of_raising(self):
        lockwitness.reset()
        lockwitness.arm("warn")
        try:
            a = lockwitness.make_lock("t.warn_a")
            b = lockwitness.make_lock("t.warn_b")
            with a:
                with b:
                    pass
            with b:
                with a:                  # would raise under strict
                    pass
            assert lockwitness.snapshot()["violations"] == 1
        finally:
            lockwitness.reset()
            lockwitness.arm("strict")    # hand back to the fixture's mode

    def test_rlock_reentry_adds_no_self_edge(self):
        r = lockwitness.make_rlock("t.reent")
        with r:
            with r:                      # reentrant: no edge, no raise
                pass
        assert "t.reent" not in lockwitness.order_graph().get("t.reent",
                                                              set())

    def test_same_name_nesting_adds_no_self_edge(self):
        """Two instances of one lock class (same witness name) nested —
        e.g. two governor accounts — must not self-edge."""
        x = lockwitness.make_lock("t.class")
        y = lockwitness.make_lock("t.class")
        with x:
            with y:
                pass
        assert "t.class" not in lockwitness.order_graph().get("t.class",
                                                              set())

    def test_condition_wait_keeps_held_stack_truthful(self):
        cv = lockwitness.make_condition("t.cv")
        other = lockwitness.make_lock("t.cv_other")
        done = []

        def waiter():
            with cv:
                cv.wait(timeout=5.0)
                done.append(True)

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        time.sleep(0.1)
        with cv:                         # acquirable: wait() released it
            cv.notify_all()
        t.join(5.0)
        assert done == [True]
        with cv:
            with other:                  # cv -> other edge records cleanly
                pass
        assert "t.cv_other" in lockwitness.order_graph().get("t.cv", set())

    def test_disarmed_is_plain_delegation(self):
        lockwitness.disarm()
        try:
            lk = lockwitness.make_lock("t.disarmed")
            before = lockwitness.snapshot()["acquires"]
            with lk:
                pass
            assert lockwitness.snapshot()["acquires"] == before
        finally:
            lockwitness.arm("strict")    # hand back to the fixture's mode

    def test_factory_exports_ride_the_analysis_namespace(self):
        from bigdl_tpu import analysis
        assert analysis.make_lock is lockwitness.make_lock
        assert analysis.make_rlock is lockwitness.make_rlock
        assert analysis.make_condition is lockwitness.make_condition
        assert analysis.LockOrderViolation is lockwitness.LockOrderViolation


# ---------------------------------------------------------------------------
# chaos: seeded inversion (satellite a)
# ---------------------------------------------------------------------------

class TestChaosLockDelay:
    @pytest.fixture(autouse=True)
    def _chaos_env(self):
        yield
        chaos.uninstall()
        config.clear_property("bigdl.chaos.lockDelayAt")

    def test_seeded_two_thread_inversion_is_caught_with_both_stacks(self):
        """The reproduce-on-demand story end to end: thread one takes
        A→B, thread two takes B→A.  ``lockDelayAt`` stalls thread one's
        inner acquire of B — AFTER its A→B edge is recorded, BEFORE it
        blocks — deterministically holding the racy window open so
        thread two runs its inverted acquisition into the witness while
        thread one still holds A.  Without the witness this interleaving
        is a real deadlock; with it, thread two gets the structured
        violation and the suite reports instead of wedging."""
        config.set_property("bigdl.chaos.lockDelayAt", "t.seed_b:1:0.4")
        chaos.install()
        a = lockwitness.make_lock("t.seed_a")
        b = lockwitness.make_lock("t.seed_b")
        caught = []

        def forward():
            with a:
                with b:            # 1st acquire of t.seed_b: stalls 0.4 s
                    pass

        def inverted():
            time.sleep(0.15)       # let forward() record A->B and stall
            try:
                with b:
                    with a:
                        pass
            except lockwitness.LockOrderViolation as e:
                caught.append(e)

        t1 = threading.Thread(target=forward, daemon=True)
        t2 = threading.Thread(target=inverted, daemon=True)
        t1.start()
        t2.start()
        t1.join(10.0)
        t2.join(10.0)
        assert len(caught) == 1, "witness missed the seeded inversion"
        v = caught[0]
        assert v.edge == ("t.seed_b", "t.seed_a")
        assert v.reverse_edge == ("t.seed_a", "t.seed_b")
        assert "forward" in v.reverse_stack    # the other thread's stack
        assert "inverted" in v.stack           # this thread's stack
        assert chaos._state.lock_delays == 1   # the stall actually fired

    def test_delay_fires_once_per_position_per_plan(self):
        config.set_property("bigdl.chaos.lockDelayAt", "t.once:2:0.2")
        chaos.install()
        lk = lockwitness.make_lock("t.once")
        t0 = time.monotonic()
        for _ in range(4):
            with lk:
                pass
        elapsed = time.monotonic() - t0
        assert chaos._state.lock_delays == 1
        assert 0.2 <= elapsed < 2.0

    def test_install_pushes_target_uninstall_clears_it(self):
        config.set_property("bigdl.chaos.lockDelayAt", "t.push:1")
        chaos.install()
        assert lockwitness._WITNESS.chaos_target == "t.push"
        chaos.uninstall()
        assert lockwitness._WITNESS.chaos_target is None


# ---------------------------------------------------------------------------
# regressions for the genuine findings the static pass surfaced
# (satellite b: each fixed race keeps a test)
# ---------------------------------------------------------------------------

class TestRaceRegressions:
    def test_handle_terminal_transition_is_first_wins_exactly_once(self):
        """RequestHandle._finish was Event-based check-then-act: a
        dispatch completion and a supervisor abandon() racing from two
        threads could BOTH pass the gate and double-count the outcome.
        Now the done-check and the state writes are one atomic region —
        hammer the transition from many threads and exactly one wins."""
        from bigdl_tpu.serving.engine import RequestHandle
        wins = []
        errs = []
        for _ in range(50):
            h = RequestHandle(None, 0, 0, 1 << 62)
            barrier = threading.Barrier(4)
            del wins[:]

            def racer(tag):
                barrier.wait(5.0)
                if h._finish(tag, result=tag):
                    wins.append(tag)

            ts = [threading.Thread(target=racer, args=(f"o{i}",))
                  for i in range(4)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(5.0)
            if len(wins) != 1:
                errs.append(list(wins))
            assert h.outcome in ("o0", "o1", "o2", "o3")
            assert h._result == h.outcome     # writes from ONE racer only
        assert errs == [], f"non-atomic first-wins transitions: {errs}"

    def test_abandon_after_completion_never_double_releases(self):
        """abandon() on an already-completed handle must neither flip
        the outcome nor release payload bytes a second time."""
        from bigdl_tpu.resources import GOVERNOR
        from bigdl_tpu.serving.engine import RequestHandle
        acct = GOVERNOR.account("serving_admission")
        base = acct.nbytes
        h = RequestHandle(None, 0, 0, 1 << 62)
        with h._lock:
            h.payload_nbytes = 1024
        acct.add(1024)
        assert h._finish("ok", result=1)      # dispatch completion wins
        # the engine's completion path released the bytes:
        with h._lock:
            nbytes, h.payload_nbytes = h.payload_nbytes, 0
        acct.sub(nbytes)
        assert not h.abandon()                # loses the race, releases 0
        assert h.outcome == "ok" and h.result() == 1
        assert acct.nbytes == base

    def test_admission_bytes_are_charged_before_enqueue(self):
        """The payload charge now happens BEFORE the handle enters the
        queue: once queued the batcher owns it, and a completion racing
        a post-enqueue charge would read payload_nbytes == 0 and leak
        the governor accounting.  A completed request must leave the
        admission account exactly where it started."""
        import numpy as np
        import jax
        import bigdl_tpu.nn as nn
        from bigdl_tpu.resources import GOVERNOR
        from bigdl_tpu.serving import ServingEngine
        acct = GOVERNOR.account("serving_admission")
        base = acct.nbytes
        model = nn.Sequential().add(nn.Linear(4, 2))
        model.reset(jax.random.PRNGKey(0))
        eng = ServingEngine(model)
        try:
            eng.warmup(np.zeros((4,), np.float32))
            h = eng.submit(np.zeros((4,), np.float32))
            assert h.payload_nbytes or h.done()   # charged at admission
            h.result(timeout=10.0)
            deadline = time.monotonic() + 5.0
            while acct.nbytes != base and time.monotonic() < deadline:
                time.sleep(0.01)                  # _account runs post-set
            assert acct.nbytes == base            # charged then released
        finally:
            eng.stop()

    def test_prefetch_error_stash_is_first_error_wins(self):
        """BatchPrefetcher._stash_error raced two producer threads and
        the stopping consumer over ``self.error``; the check-and-write
        is now one atomic region — the first error sticks, later ones
        never overwrite it."""
        from bigdl_tpu.engine import BatchPrefetcher
        pf = BatchPrefetcher.__new__(BatchPrefetcher)
        pf._stats_lock = lockwitness.make_lock("t.prefetch_stats")
        pf.error = None
        first, second = RuntimeError("first"), RuntimeError("second")
        pf._stash_error((first, None))
        pf._stash_error((second, None))
        assert pf.error is first
        pf._stash_error((None, None))             # non-errors never clear
        assert pf.error is first
