"""Recompile-sentinel coverage across all three fused training steps.

The sentinel wraps the jitted step of each trainer (local, shard_map
data-parallel, GSPMD tensor-parallel); a 3-step run must report ZERO
post-warmup retraces and exactly one abstract signature, and a
deliberately drifting signature must be caught with a structured
shape/dtype diff (ISSUE 4 acceptance criteria)."""

import jax
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.analysis.retrace import (RetraceError, RetraceSentinel,
                                        abstract_signature)
from bigdl_tpu.engine import Engine
from bigdl_tpu.dataset import Sample
from bigdl_tpu.dataset.dataset import LocalDataSet, ShardedDataSet
from bigdl_tpu.dataset.transformer import SampleToMiniBatch
from bigdl_tpu.optim import SGD
from bigdl_tpu.optim import trigger as triggers
from bigdl_tpu.optim.optimizer import LocalOptimizer
from bigdl_tpu.parallel import DistriOptimizer

N_DEV = 8


def _samples(n=32, din=4):
    rng = np.random.RandomState(0)
    return [Sample(rng.randn(din).astype(np.float32),
                   np.array([1 + i % 2], np.float32)) for i in range(n)]


def _mlp(seed=0):
    m = (nn.Sequential().add(nn.Linear(4, 8)).add(nn.Tanh())
         .add(nn.Linear(8, 2)).add(nn.LogSoftMax()))
    m.reset(jax.random.PRNGKey(seed))
    return m


def _tp_mlp(seed=0):
    from bigdl_tpu.parallel.tensor_parallel import (column_parallel,
                                                    row_parallel)
    m = (nn.Sequential().add(column_parallel(nn.Linear(4, 8))).add(nn.Tanh())
         .add(row_parallel(nn.Linear(8, 2))).add(nn.LogSoftMax()))
    m.reset(jax.random.PRNGKey(seed))
    return m


def _assert_stable(opt, expect_calls=3):
    sent = opt._retrace_sentinel
    assert sent is not None, "sentinel must be armed by the conftest fixture"
    assert sent.calls == expect_calls
    assert sent.retraces == 0, f"post-warmup retraces: {sent.last_diff}"
    assert len(sent._seen) == 1, "the fused step must hold ONE signature"


class TestFusedStepsStayStable:
    def test_local_step_zero_retraces(self):
        opt = LocalOptimizer(
            _mlp(), LocalDataSet(_samples()).transform(SampleToMiniBatch(8)),
            nn.ClassNLLCriterion())
        opt.set_optim_method(SGD(learning_rate=0.1))
        opt.set_end_when(triggers.max_iteration(3))
        opt.optimize()
        _assert_stable(opt)

    def test_shard_map_step_zero_retraces(self):
        ds = ShardedDataSet(_samples(), partition_num=N_DEV).transform(
            SampleToMiniBatch(16, N_DEV))
        opt = DistriOptimizer(_mlp(1), ds, nn.ClassNLLCriterion())
        opt.set_optim_method(SGD(learning_rate=0.1))
        opt.set_end_when(triggers.max_iteration(3))
        opt.optimize()
        _assert_stable(opt)

    def test_gspmd_step_zero_retraces(self):
        mesh = Engine.create_mesh((4, 2), ("data", "model"))
        ds = ShardedDataSet(_samples(), partition_num=4).transform(
            SampleToMiniBatch(16, 4))
        opt = DistriOptimizer(_tp_mlp(2), ds, nn.ClassNLLCriterion(),
                              mesh=mesh)
        opt.set_optim_method(SGD(learning_rate=0.1))
        opt.set_end_when(triggers.max_iteration(3))
        opt.optimize()
        _assert_stable(opt)


class TestSignatureDriftIsCaught:
    def test_local_drifting_batch_raises_with_diff(self):
        """A batch whose shape drifts after warmup must raise RetraceError
        naming the drifted leaf."""
        opt = LocalOptimizer(
            _mlp(3), LocalDataSet(_samples()).transform(SampleToMiniBatch(8)),
            nn.ClassNLLCriterion())
        opt.set_optim_method(SGD(learning_rate=0.1))
        opt.set_end_when(triggers.max_iteration(3))
        opt.optimize()
        sent = opt._retrace_sentinel
        import jax.numpy as jnp
        drifted = jnp.zeros((12, 4))              # batch 8 -> 12
        targets = jnp.ones((12,))
        with pytest.raises(RetraceError) as ei:
            opt._step_fn(opt.model.params, opt.optim_method._slots,
                         opt.model.state, drifted, targets,
                         opt.optim_method.hyper(), jax.random.PRNGKey(0))
        msg = str(ei.value)
        assert "shape" in msg and "(8, 4)" in msg and "(12, 4)" in msg
        assert sent.retraces == 1

    def test_dtype_drift_named_in_diff(self):
        import jax.numpy as jnp
        s = RetraceSentinel("t", mode="strict", warmup_steps=1, budget=1)
        f = s.wrap(lambda x: x)
        f(jnp.zeros((4,), jnp.float32))
        with pytest.raises(RetraceError) as ei:
            f(jnp.zeros((4,), jnp.bfloat16))
        assert "dtype" in str(ei.value)
        assert "float32" in str(ei.value) and "bfloat16" in str(ei.value)

    def test_weak_type_drift_named_in_diff(self):
        import jax.numpy as jnp
        s = RetraceSentinel("t", mode="strict", warmup_steps=1, budget=1)
        f = s.wrap(lambda x: x)
        f(jnp.float32(1.0) * jnp.zeros(()))       # strong f32
        with pytest.raises(RetraceError) as ei:
            f(1.0)                                # weak python scalar
        assert "weak-type" in str(ei.value)

    def test_warn_mode_counts_without_raising(self):
        import jax.numpy as jnp
        s = RetraceSentinel("t", mode="warn", warmup_steps=1, budget=1)
        f = s.wrap(lambda x: x)
        f(jnp.zeros((2,)))
        f(jnp.zeros((3,)))
        f(jnp.zeros((4,)))
        f(jnp.zeros((2,)))                        # seen before: no event
        assert s.retraces == 2
        assert s.calls == 4

    def test_warmup_budget_tolerates_expected_compiles(self):
        import jax.numpy as jnp
        s = RetraceSentinel("t", mode="strict", warmup_steps=2, budget=2)
        f = s.wrap(lambda x: x)
        f(jnp.zeros((2,)))
        f(jnp.zeros((3,)))                        # 2nd compile inside budget
        assert s.retraces == 0 and s.compiles_in_warmup == 2


class TestAbstractSignature:
    def test_equal_signatures_for_equal_avals(self):
        import jax.numpy as jnp
        a = abstract_signature((jnp.zeros((2, 3)), {"lr": 0.1}))
        b = abstract_signature((jnp.ones((2, 3)), {"lr": 0.5}))
        assert a == b                             # values never retrace

    def test_structure_change_detected(self):
        import jax.numpy as jnp
        a = abstract_signature(({"x": jnp.zeros(2)},))
        b = abstract_signature(({"x": jnp.zeros(2), "y": jnp.zeros(2)},))
        assert a != b
