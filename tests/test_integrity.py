"""Training-state integrity: fingerprints, agreement, self-healing.

The claims under test (ISSUE 13 acceptance criteria): the fused steps
carry on-device fingerprints whose continuity check catches a single
flipped mantissa bit — corruption that stays finite and is invisible to
``all_finite`` — in all three trainer families; the shard_map family's
cross-replica agreement names the minority replica and heals IN PLACE
by re-broadcasting the agreeing majority (no checkpoint restore); a
snapshot corrupted in memory before serialization passes every payload
checksum but is refused at restore by its semantic fingerprint, falling
back to the next-older snapshot; and every healed run reaches weight
parity with an uninjected one.

Parity tests use full-batch datasets (one iteration per epoch, shuffle
order irrelevant) — the same protocol as ``test_chaos``.  Restore-replay
parity is compared at the repo's established restore tolerance
(``rtol=1e-5, atol=1e-7``); bit-exactness does not survive the
host→device round trip of a restore.
"""

import os
import pickle
import re

import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu import integrity, telemetry
from bigdl_tpu.dataset import LocalDataSet, SampleToMiniBatch
from bigdl_tpu.dataset.datasets import synthetic_separable
from bigdl_tpu.utils import chaos, config

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mlp(seed=11):
    import jax
    m = (nn.Sequential().add(nn.Linear(4, 16)).add(nn.Tanh())
         .add(nn.Linear(16, 2)).add(nn.LogSoftMax()))
    m.reset(jax.random.PRNGKey(seed))
    return m


def _full_batch_ds(samples):
    return LocalDataSet(samples).transform(SampleToMiniBatch(len(samples)))


@pytest.fixture(autouse=True)
def _integrity_env():
    """Synchronous driver, zero retry sleeps, disarmed chaos, clean
    integrity knobs before/after every test."""
    config.set_property("bigdl.failure.retryTimeInterval", 0.0)
    yield
    chaos.uninstall()
    for key in ("bigdl.failure.retryTimeInterval",
                "bigdl.failure.retryTimes",
                "bigdl.integrity.everyN", "bigdl.integrity.seed",
                "bigdl.integrity.healthFactor",
                "bigdl.integrity.healthWarmup",
                "bigdl.integrity.healthCooldown",
                "bigdl.pipeline.depth",
                "bigdl.chaos.bitflipParamAt",
                "bigdl.chaos.desyncReplicaAt",
                "bigdl.chaos.corruptStateBeforeSaveAt",
                "bigdl.divergence.guard"):
        config.clear_property(key)


class TestFingerprint:
    def test_deterministic_and_seed_sensitive(self):
        import jax
        tree = {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
                "b": np.ones(3, np.float32)}
        a = np.asarray(integrity.fingerprint_tree(tree, 0x51D0))
        b = np.asarray(integrity.fingerprint_tree(tree, 0x51D0))
        np.testing.assert_array_equal(a, b)
        c = np.asarray(integrity.fingerprint_tree(tree, 0x51D1))
        # the plain sum is seed-independent; the projection must move
        assert a[0] == c[0] and a[1] != c[1]

    def test_injected_bit_flip_changes_key(self):
        from bigdl_tpu.integrity.monitor import _flip_low_bit
        tree = {"w": np.linspace(-1, 1, 64, dtype=np.float32)}
        before = integrity.fingerprint_key(
            np.asarray(integrity.fingerprint_tree(tree, 0x51D0)))
        flipped = {"w": _flip_low_bit(tree["w"])}
        assert np.isfinite(flipped["w"]).all()  # SDC stays finite
        after = integrity.fingerprint_key(
            np.asarray(integrity.fingerprint_tree(flipped, 0x51D0)))
        assert before != after

    def test_host_and_device_sign_streams_agree(self):
        from bigdl_tpu.integrity.fingerprint import (_device_signs,
                                                     _host_signs)
        for n, seed in ((1, 7), (65, 0x51D0), (1024, 12345)):
            np.testing.assert_array_equal(
                np.asarray(_device_signs(n, seed)), _host_signs(n, seed))

    def test_host_fingerprint_stable_under_pickle_round_trip(self):
        model = _mlp()
        norm = pickle.loads(pickle.dumps(model))
        fp1 = integrity.host_fingerprint(norm)
        fp2 = integrity.host_fingerprint(pickle.loads(pickle.dumps(norm)))
        assert integrity.fingerprint_key(fp1) == \
            integrity.fingerprint_key(fp2)

    def test_continuity_latch_catches_mutated_carry(self):
        import jax.numpy as jnp
        fp = jnp.asarray(np.array([3.5, -1.25], np.float32))
        fp_s = jnp.asarray(np.array([0.5, 2.0], np.float32))
        carry = jnp.asarray(np.asarray(integrity.init_carry()))

        def tick(k):
            return jnp.asarray(k, jnp.int32)

        # step 1: carry unseen, anything passes; pack the outputs
        ok, latch, bad = integrity.continuity_check(carry, fp, fp_s,
                                                    tick(1))
        assert bool(ok) and int(latch) == 0
        carry = integrity.pack_carry(latch, bad, fp, fp_s)
        # step 2, intact bits: still clean
        ok, latch, bad = integrity.continuity_check(carry, fp, fp_s,
                                                    tick(2))
        assert bool(ok) and int(latch) == 0
        carry = integrity.pack_carry(latch, bad, fp, fp_s)
        # step 3, the bits moved between steps: latch fires, names tick 3
        ok, latch, bad = integrity.continuity_check(
            carry, fp + 1e-3, fp_s, tick(3))
        assert not bool(ok) and int(latch) == 1 and int(bad) == 3
        # the latch (and first-bad tick) stay sticky even after the bits
        # go back to agreeing — cont_ok is only the per-step verdict
        carry = integrity.pack_carry(latch, bad, fp, fp_s)
        ok, latch, bad = integrity.continuity_check(carry, fp, fp_s,
                                                    tick(4))
        assert bool(ok) and int(latch) == 1 and int(bad) == 3


class TestAllFiniteHardening:
    def test_empty_and_int_trees_are_constant_true(self):
        from bigdl_tpu.optim.optimizer import all_finite
        for tree in ({}, [], {"n": np.arange(3)},
                     {"a": np.int32(1), "b": [np.arange(2, dtype=np.int64)]}):
            ok = all_finite(tree)
            assert isinstance(ok, np.bool_) and bool(ok)

    def test_float_leaves_still_checked(self):
        from bigdl_tpu.optim.optimizer import all_finite
        assert bool(all_finite({"x": np.ones(3, np.float32)}))
        assert not bool(all_finite({"x": np.array([1.0, np.nan],
                                                  np.float32)}))


class TestDiagnosedDivergence:
    def test_first_nonfinite_names_the_bad_leaf(self):
        grads = {"fc1": {"weight": np.ones((2, 2), np.float32),
                         "bias": np.ones(2, np.float32)},
                 "fc2": {"weight": np.ones((2, 2), np.float32)}}
        names = integrity.nonfinite_names(("loss", 0.0), ("grad", grads))
        assert names[0] == "loss"
        ok, idx = integrity.first_nonfinite(np.float32(1.0), grads)
        assert bool(ok) and int(idx) == integrity.NF_SENTINEL
        bad = {**grads, "fc2": {"weight": np.full((2, 2), np.inf,
                                                  np.float32)}}
        ok, idx = integrity.first_nonfinite(np.float32(1.0), bad)
        assert not bool(ok)
        assert "fc2" in names[int(idx)] and "weight" in names[int(idx)]

    def test_divergence_error_names_leaf_end_to_end(self):
        # NaN features make every step genuinely non-finite ON DEVICE, so
        # the step's recorded first-non-finite index reaches the error
        samples = synthetic_separable(64, 4, n_classes=2, seed=3)
        for s in samples:
            s.features[0][:] = np.nan
        model = _mlp()
        opt = optim.Optimizer.create(model, _full_batch_ds(samples),
                                     nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.3))
        opt.set_end_when(optim.max_iteration(10))
        config.set_property("bigdl.pipeline.depth", 1)
        config.set_property("bigdl.divergence.maxBadSteps", 2)
        config.set_property("bigdl.failure.retryTimes", 0)
        from bigdl_tpu.optim.optimizer import DivergenceError
        try:
            with pytest.raises(DivergenceError,
                               match="first non-finite: loss"):
                opt.optimize()
        finally:
            config.clear_property("bigdl.divergence.maxBadSteps")


class TestWeightHealthMonitor:
    def test_gate_fires_once_on_excursion(self):
        mon = integrity.WeightHealthMonitor(3.0, warmup=3, cooldown=100)
        assert mon.enabled
        for i in range(6):
            assert not mon.observe("grad_norm", 1.0, i)
        assert mon.observe("grad_norm", 50.0, 6)
        assert mon.anomalies == 1
        # cooldown holds the gate closed; NaN is ignored outright
        assert not mon.observe("grad_norm", 50.0, 7)
        assert not mon.observe("grad_norm", float("nan"), 8)

    def test_factor_zero_disables(self):
        mon = integrity.WeightHealthMonitor(0.0)
        assert not mon.enabled
        assert not mon.observe("grad_norm", 1e30, 1)


class TestMajoritySplit:
    def test_minority_named(self):
        major, minority = integrity.majority_split(
            [b"aa", b"aa", b"bb", b"aa"])
        assert major == b"aa" and minority == [2]

    def test_tie_breaks_toward_lowest_replica(self):
        major, minority = integrity.majority_split([b"xx", b"yy"])
        assert major == b"xx" and minority == [1]


class TestChaosDocDrift:
    """Every ``bigdl.chaos.*`` key the code knows must have a row in
    docs/configuration.md — and vice versa (satellite: drift guard)."""

    _KEY = re.compile(r"bigdl\.chaos\.[A-Za-z0-9]+")

    def _keys_in(self, path):
        with open(path, encoding="utf-8") as f:
            return set(self._KEY.findall(f.read()))

    def test_config_defaults_match_docs_both_ways(self):
        code = self._keys_in(
            os.path.join(_REPO, "bigdl_tpu", "utils", "config.py"))
        docs = self._keys_in(
            os.path.join(_REPO, "docs", "configuration.md"))
        assert code - docs == set(), \
            f"chaos keys missing a docs row: {sorted(code - docs)}"
        assert docs - code == set(), \
            f"documented chaos keys unknown to config.py: " \
            f"{sorted(docs - code)}"

    def test_chaos_module_keys_are_registered_defaults(self):
        used = self._keys_in(
            os.path.join(_REPO, "bigdl_tpu", "utils", "chaos.py"))
        registered = self._keys_in(
            os.path.join(_REPO, "bigdl_tpu", "utils", "config.py"))
        assert used - registered == set(), \
            f"chaos.py reads unregistered keys: {sorted(used - registered)}"


class TestAnalysisDocDrift:
    """Every ``bigdl.analysis.*`` key the code registers must have a
    row in docs/configuration.md — and vice versa (the lockWitness knob
    rides the same both-ways drift guard as the chaos keys)."""

    _KEY = re.compile(r"bigdl\.analysis\.[A-Za-z0-9]+")

    def _keys_in(self, *parts):
        with open(os.path.join(_REPO, *parts), encoding="utf-8") as f:
            return set(self._KEY.findall(f.read()))

    def test_config_defaults_match_docs_both_ways(self):
        code = self._keys_in("bigdl_tpu", "utils", "config.py")
        docs = self._keys_in("docs", "configuration.md")
        assert code - docs == set(), \
            f"analysis keys missing a docs row: {sorted(code - docs)}"
        assert docs - code == set(), \
            f"documented analysis keys unknown to config.py: " \
            f"{sorted(docs - code)}"


class TestIngestDocDrift:
    """Every ``bigdl.ingest.*`` key the code registers must have a row
    in docs/configuration.md — and vice versa (satellite e: the
    autoscale.* / epochCache* knobs ride the same drift guard as the
    chaos keys)."""

    # dotted sub-keys (autoscale.enabled, ...) must match whole: a key
    # can never end at a dot
    _KEY = re.compile(r"bigdl\.ingest\.[A-Za-z0-9]+(?:\.[A-Za-z0-9]+)*")

    def _keys_in(self, *parts):
        with open(os.path.join(_REPO, *parts), encoding="utf-8") as f:
            return set(self._KEY.findall(f.read()))

    def test_config_defaults_match_docs_both_ways(self):
        code = self._keys_in("bigdl_tpu", "utils", "config.py")
        docs = self._keys_in("docs", "configuration.md")
        assert code - docs == set(), \
            f"ingest keys missing a docs row: {sorted(code - docs)}"
        # prose may name a dot-boundary PREFIX of a key family
        # ("bigdl.ingest.autoscale" for the knob group) — only a
        # documented key that is neither registered nor such a prefix
        # is drift
        unknown = {d for d in docs - code
                   if not any(k.startswith(d + ".") for k in code)}
        assert unknown == set(), \
            f"documented ingest keys unknown to config.py: {sorted(unknown)}"

    def test_ingest_module_keys_are_registered_defaults(self):
        used = self._keys_in("bigdl_tpu", "dataset", "ingest.py")
        registered = self._keys_in("bigdl_tpu", "utils", "config.py")
        unknown = {u for u in used - registered
                   if not any(k.startswith(u + ".") for k in registered)}
        assert unknown == set(), \
            f"ingest.py reads unregistered keys: {sorted(unknown)}"


class TestForensicsDocDrift:
    """Every ``bigdl.trace.*`` / ``bigdl.incident.*`` /
    ``bigdl.utils.LoggerFilter.*`` key the code registers must have a
    row in docs/configuration.md — and vice versa (the forensic layer's
    knobs ride the same both-ways drift guard as the chaos keys)."""

    _PATTERNS = (
        re.compile(r"bigdl\.trace\.[A-Za-z0-9]+"),
        re.compile(r"bigdl\.incident\.[A-Za-z0-9]+"),
        re.compile(r"bigdl\.utils\.LoggerFilter\.[A-Za-z0-9]+"),
    )

    def _keys_in(self, *parts):
        with open(os.path.join(_REPO, *parts), encoding="utf-8") as f:
            text = f.read()
        out = set()
        for pat in self._PATTERNS:
            out |= set(pat.findall(text))
        return out

    def test_config_defaults_match_docs_both_ways(self):
        code = self._keys_in("bigdl_tpu", "utils", "config.py")
        docs = self._keys_in("docs", "configuration.md")
        assert code - docs == set(), \
            f"forensics keys missing a docs row: {sorted(code - docs)}"
        assert docs - code == set(), \
            f"documented forensics keys unknown to config.py: " \
            f"{sorted(docs - code)}"

    def test_module_keys_are_registered_defaults(self):
        registered = self._keys_in("bigdl_tpu", "utils", "config.py")
        for parts in (("bigdl_tpu", "telemetry", "request_trace.py"),
                      ("bigdl_tpu", "telemetry", "incident.py"),
                      ("bigdl_tpu", "utils", "logger_filter.py")):
            used = self._keys_in(*parts)
            assert used - registered == set(), \
                f"{parts[-1]} reads unregistered keys: " \
                f"{sorted(used - registered)}"


class TestSemanticCheckpointFingerprint:
    """Satellite d: a snapshot whose payload checksums verify but whose
    save-time fingerprint mismatches is refused with a structured log
    and the next-oldest valid snapshot restores."""

    def _mgr(self, tmp_path):
        from bigdl_tpu.utils.checkpoint_manager import CheckpointManager
        return CheckpointManager(str(tmp_path))

    def test_corrupted_capture_refused_next_oldest_restores(
            self, tmp_path, caplog):
        import logging
        mgr = self._mgr(tmp_path)
        model, sgd = _mlp(), optim.SGD(learning_rate=0.1)
        mgr.save(model, sgd, 1)
        config.set_property("bigdl.chaos.corruptStateBeforeSaveAt", 1)
        chaos.install()
        mgr.save(model, sgd, 2)
        chaos.uninstall()
        # the torn-write machinery sees nothing wrong: bytes committed,
        # checksums verify
        assert mgr.latest_valid()[2] == 2
        with caplog.at_level(logging.WARNING, logger="bigdl_tpu"):
            got = mgr.load_latest()
        assert got is not None and got[2] == 1
        assert any("fingerprint" in r.getMessage() for r in caplog.records)

    def test_deep_verify_names_the_semantic_corruption(self, tmp_path):
        mgr = self._mgr(tmp_path)
        model, sgd = _mlp(), optim.SGD(learning_rate=0.1)
        mgr.save(model, sgd, 1)
        config.set_property("bigdl.chaos.corruptStateBeforeSaveAt", 1)
        chaos.install()
        mgr.save(model, sgd, 2)
        chaos.uninstall()
        assert mgr.verify(2, True) is True          # shallow: bytes fine
        assert mgr.verify(2, True, deep=True) is False
        assert mgr.verify(1, True, deep=True) is True

    def test_legacy_manifest_without_fingerprints_restores(self, tmp_path):
        import json
        from bigdl_tpu.utils import file_io
        from bigdl_tpu.visualization.crc32c import crc32c
        mgr = self._mgr(tmp_path)
        mgr.save(_mlp(), optim.SGD(learning_rate=0.1), 2)
        p = file_io.join(str(tmp_path), "manifest.2")
        man = json.loads(file_io.read_bytes(p).decode())
        for meta in man["files"].values():
            meta.pop("fingerprint", None)
        man["version"] = 2
        mb = json.dumps(man, sort_keys=True).encode()
        file_io.write_bytes(p, mb, True)
        file_io.write_bytes(file_io.join(str(tmp_path), "commit.2"),
                            (f"{crc32c(mb):08x}\n").encode(), True)
        got = mgr.load_latest()
        assert got is not None and got[2] == 2


def _arm_integrity():
    config.set_property("bigdl.integrity.everyN", 1)
    config.set_property("bigdl.pipeline.depth", 1)


def _train_local(samples, ckpt=None, iters=8):
    model = _mlp()
    opt = optim.Optimizer.create(model, _full_batch_ds(samples),
                                 nn.ClassNLLCriterion())
    opt.set_optim_method(optim.SGD(learning_rate=0.3, momentum=0.9))
    opt.set_end_when(optim.max_iteration(iters))
    if ckpt:
        opt.set_checkpoint(str(ckpt), optim.several_iteration(1))
    opt.optimize()
    w, _ = model.get_parameters()
    return np.asarray(w)


def _train_shard_map(samples, ckpt=None, iters=8):
    import jax
    from bigdl_tpu.dataset.dataset import ShardedDataSet
    from bigdl_tpu.engine import Engine
    from bigdl_tpu.parallel import DistriOptimizer
    mesh = Engine.create_mesh((8,), ("data",))
    ds = ShardedDataSet(samples, 8).transform(SampleToMiniBatch(128, 8))
    model = _mlp()
    opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(), mesh=mesh)
    opt.set_optim_method(optim.SGD(learning_rate=0.3, momentum=0.9))
    opt.set_end_when(optim.max_iteration(iters))
    if ckpt:
        opt.set_checkpoint(str(ckpt), optim.several_iteration(1))
    opt.optimize()
    w, _ = model.get_parameters()
    return np.asarray(w)


def _train_gspmd(samples, ckpt=None, iters=8):
    import jax
    from bigdl_tpu.dataset.dataset import ShardedDataSet
    from bigdl_tpu.engine import Engine
    from bigdl_tpu.parallel import DistriOptimizer
    from bigdl_tpu.parallel.tensor_parallel import (column_parallel,
                                                    row_parallel)
    up, down = nn.Linear(4, 16), nn.Linear(16, 2)
    column_parallel(up)
    row_parallel(down)
    model = (nn.Sequential().add(up).add(nn.Tanh()).add(down)
             .add(nn.LogSoftMax()))
    model.reset(jax.random.PRNGKey(11))
    mesh = Engine.create_mesh((2, 4), ("data", "model"))
    ds = ShardedDataSet(samples, 2).transform(SampleToMiniBatch(128, 2))
    opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(), mesh=mesh)
    opt.set_optim_method(optim.SGD(learning_rate=0.3, momentum=0.9))
    opt.set_end_when(optim.max_iteration(iters))
    if ckpt:
        opt.set_checkpoint(str(ckpt), optim.several_iteration(1))
    opt.optimize()
    w, _ = model.get_parameters()
    return np.asarray(w)


# restore-replay parity tolerance: bit-exactness does not survive the
# restore's host round trip (see test_chaos restore-parity precedent)
_PARITY = dict(rtol=1e-5, atol=1e-7)


class TestEndToEndHealing:
    """One injected fault per family: detection fires, the run heals,
    and final weights reach parity with an uninjected run."""

    def test_local_bitflip_detected_and_healed_via_restore(self, tmp_path):
        samples = synthetic_separable(128, 4, n_classes=2, seed=7)
        _arm_integrity()
        w_clean = _train_local(samples)
        config.set_property("bigdl.chaos.bitflipParamAt", 4)
        chaos.install()
        w = _train_local(samples, ckpt=tmp_path)
        chaos.uninstall()
        np.testing.assert_allclose(w, w_clean, **_PARITY)
        assert telemetry.counter(
            "Integrity/continuity_failures").value >= 1

    def test_shard_map_minority_bitflip_heals_in_place(self, tmp_path):
        samples = synthetic_separable(128, 4, n_classes=2, seed=7)
        _arm_integrity()
        w_clean = _train_shard_map(samples)
        config.set_property("bigdl.chaos.bitflipParamAt", "4:2")
        chaos.install()
        w = _train_shard_map(samples, ckpt=tmp_path)
        chaos.uninstall()
        np.testing.assert_allclose(w, w_clean, **_PARITY)
        assert telemetry.counter("Integrity/desync_detected").value >= 1

    def test_desync_verdict_names_minority_replica(self):
        # unit-level: a gathered fingerprint table with one divergent row
        # classifies as ReplicaDesyncError naming exactly that replica
        table = np.tile(np.array([3.5, -1.25], np.float32), (8, 1))
        table[5] += 1e-3
        aux = {"fps_all": table, "cont": np.float32(0.0),
               "bad_iter": np.float32(4.0)}
        integ = integrity.DriverIntegrity("shard_map", ["loss"], every_n=1)
        with pytest.raises(integrity.ReplicaDesyncError) as ei:
            integ.check(aux, neval=5)
        assert ei.value.replicas == (5,)
        assert ei.value.iteration == 4
        assert "[5]" in str(ei.value)

    def test_shard_map_in_step_desync_heals_in_place(self, tmp_path):
        samples = synthetic_separable(128, 4, n_classes=2, seed=7)
        _arm_integrity()
        w_clean = _train_shard_map(samples)
        config.set_property("bigdl.chaos.desyncReplicaAt", "4:3")
        chaos.install()
        w = _train_shard_map(samples, ckpt=tmp_path)
        chaos.uninstall()
        np.testing.assert_allclose(w, w_clean, **_PARITY)

    def test_gspmd_bitflip_detected_and_healed_via_restore(self, tmp_path):
        samples = synthetic_separable(128, 4, n_classes=2, seed=7)
        _arm_integrity()
        w_clean = _train_gspmd(samples)
        config.set_property("bigdl.chaos.bitflipParamAt", "4:1")
        chaos.install()
        w = _train_gspmd(samples, ckpt=tmp_path)
        chaos.uninstall()
        np.testing.assert_allclose(w, w_clean, **_PARITY)

    @pytest.mark.slow
    def test_soak_repeated_faults_across_families(self, tmp_path):
        """Several injected faults in sequence, each healing cleanly."""
        samples = synthetic_separable(128, 4, n_classes=2, seed=7)
        _arm_integrity()
        w_clean = _train_shard_map(samples, iters=12)
        for spec in ("3:1", "6:4", "9:7"):
            config.set_property("bigdl.chaos.desyncReplicaAt", spec)
            chaos.install()
            w = _train_shard_map(samples, ckpt=tmp_path, iters=12)
            chaos.uninstall()
            np.testing.assert_allclose(w, w_clean, **_PARITY)
