"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's multi-node-without-a-cluster simulation
(``optim/DistriOptimizerSpec.scala:38-40``: Engine.init(4 nodes) over
local[1]): here 8 virtual XLA host devices play 8 TPU chips so sharding and
collectives run for real without hardware.

Must set env vars BEFORE jax is imported anywhere.
"""

import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax
import numpy as np
import pytest

# The environment's sitecustomize may pre-register an accelerator backend and
# force it via jax_platforms; tests run on the virtual CPU mesh regardless.
jax.config.update("jax_platforms", "cpu")

# Numerical-parity tests need full fp32 matmuls; the framework's production
# default stays backend-default (bf16 passes on the MXU — the TPU-first choice).
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(autouse=True)
def _seed():
    np.random.seed(0)
    yield


@pytest.fixture(autouse=True)
def _sanitizers_armed():
    """Arm analysis passes 1-2 in STRICT mode for every tier-1 test: a
    post-warmup retrace of any fused step raises RetraceError, and an
    implicit device→host sync inside the optimizer hot loop raises
    HostSyncError with its call-site.  This makes the sanitizers a
    standing CI contract — any change that reintroduces signature drift
    or a stray float()/np.asarray in the hot loop fails the suite, not a
    production run three weeks later."""
    from bigdl_tpu.utils import config

    config.set_property("bigdl.analysis.retrace", "strict")
    config.set_property("bigdl.analysis.hostSync", "strict")
    # the HLO program auditor, strict for every tier-1 compile: any
    # fused step whose lowered program breaks its declared collective
    # contract, drifts precision, or blows its layout budget raises
    # ProgramContractError at warmup
    config.set_property("bigdl.audit.collectives", "strict")
    config.set_property("bigdl.audit.precision", "strict")
    config.set_property("bigdl.audit.memory", "strict")
    yield
    config.clear_property("bigdl.analysis.retrace")
    config.clear_property("bigdl.analysis.hostSync")
    for k in ("collectives", "precision", "memory"):
        config.clear_property(f"bigdl.audit.{k}")


@pytest.fixture(autouse=True)
def _lock_witness_armed():
    """Arm the runtime lock witness STRICT for every tier-1 test: any
    lock acquisition that closes a cycle in the process-wide
    acquisition-order graph raises LockOrderViolation (both sites, both
    stacks) BEFORE the blocking acquire — the suite fails on a deadlock
    that never had to happen this run.  Graph and counters are dropped
    after each test so one test's acquisition order can never poison
    another's."""
    from bigdl_tpu.analysis import lockwitness
    from bigdl_tpu.utils import config

    config.set_property("bigdl.analysis.lockWitness", "strict")
    lockwitness.arm()
    yield
    lockwitness.disarm()
    lockwitness.reset()
    config.clear_property("bigdl.analysis.lockWitness")


@pytest.fixture(autouse=True, scope="session")
def _no_thread_leaks():
    """End-of-suite leak check: every framework thread spawned during the
    run must be gone (joined or daemonized-and-idle) by session end.  A
    non-daemon thread still alive here means some stop()/close() path
    forgot a join — exactly the class of bug the concurrency pass exists
    to keep out — and it would hang the interpreter at exit."""
    import threading

    baseline = {t.ident for t in threading.enumerate()}
    yield
    # stragglers get one grace join: a worker mid-teardown on a loaded
    # CI box is latency, not a leak
    leaked = [t for t in threading.enumerate()
              if t.ident not in baseline and not t.daemon and t.is_alive()]
    for t in leaked:
        t.join(timeout=5.0)
    leaked = [t for t in leaked if t.is_alive()]
    assert not leaked, (
        "non-daemon threads leaked past session end (missing join in a "
        "stop()/close() path):\n" + "\n".join(
            f"  - {t.name} (ident={t.ident}, daemon={t.daemon})"
            for t in leaked))


@pytest.fixture(autouse=True)
def _telemetry_armed():
    """Arm the span tracer for EVERY tier-1 test: telemetry must be able
    to ride along any training run without changing its behaviour — in
    particular, with the strict host-sync guard above also armed, a
    traced train proves the tracer itself introduces zero device→host
    syncs.  Rings are small (memory stays flat across the session) and
    dropped after each test."""
    from bigdl_tpu import telemetry

    telemetry.arm(ring_size=4096)
    yield
    telemetry.disarm()
    telemetry.reset_tracer()


@pytest.fixture(autouse=True)
def _forensics_isolated():
    """Per-test isolation for the forensic layers: request traces and
    the incident event ring are dropped after each test, and automatic
    incident-bundle dumps are disabled (a chaos test shedding requests
    must not litter incident-*.json into the CWD — tests that assert on
    bundles opt back in or call incident.dump() themselves)."""
    from bigdl_tpu.telemetry import incident, request_trace
    from bigdl_tpu.utils import config

    config.set_property("bigdl.incident.autoDump", False)
    yield
    request_trace.disarm()
    request_trace.reset()
    incident.reset()
    config.clear_property("bigdl.incident.autoDump")


@pytest.fixture(autouse=True)
def _hang_guard(request):
    """Per-test hard timeout without pytest-timeout (not installed in
    this image): SIGALRM fails the test at 1200 s — generous enough for
    the 2-OS-process multihost legs compiling under full-suite CPU
    contention, small enough that a genuine deadlock fails the run
    instead of wedging it.  pytest's built-in ``faulthandler_timeout``
    (pytest.ini, 900 s) dumps all stacks first, so a kill always comes
    with a diagnosis."""
    import signal

    if os.name != "posix":  # pragma: no cover
        yield
        return

    def on_alarm(signum, frame):
        raise TimeoutError(
            f"test exceeded the 1200 s hang guard: {request.node.nodeid}")

    prev = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(1200)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, prev)


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="run tests marked slow (multihost subprocess legs, model-zoo "
             "builds); deselected by default so `pytest -q` stays fast")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip = pytest.mark.skip(reason="slow: opt in with --runslow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
