"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's multi-node-without-a-cluster simulation
(``optim/DistriOptimizerSpec.scala:38-40``: Engine.init(4 nodes) over
local[1]): here 8 virtual XLA host devices play 8 TPU chips so sharding and
collectives run for real without hardware.

Must set env vars BEFORE jax is imported anywhere.
"""

import os

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax
import numpy as np
import pytest

# The environment's sitecustomize may pre-register an accelerator backend and
# force it via jax_platforms; tests run on the virtual CPU mesh regardless.
jax.config.update("jax_platforms", "cpu")

# Numerical-parity tests need full fp32 matmuls; the framework's production
# default stays backend-default (bf16 passes on the MXU — the TPU-first choice).
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(autouse=True)
def _seed():
    np.random.seed(0)
    yield


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running model builds")
