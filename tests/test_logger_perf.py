"""LoggerFilter + perf harness + driver log hygiene coverage."""

import logging
import os

from bigdl_tpu.models import perf
from bigdl_tpu.utils import config
from bigdl_tpu.utils.logger_filter import redirect_spark_info_logs


def test_logger_filter_writes_file(tmp_path):
    log = str(tmp_path / "bigdl.log")
    path = redirect_spark_info_logs(log)
    assert path == log
    logging.getLogger("bigdl_tpu").info("hello from the driver")
    for h in logging.getLogger("bigdl_tpu").handlers:
        h.flush()
    assert "hello from the driver" in open(log).read()
    # restore default handlers for other tests
    logging.getLogger("bigdl_tpu").handlers = []
    logging.getLogger("bigdl_tpu").propagate = True


def test_perf_harness_lenet():
    opt = perf.main(["-m", "lenet5", "-b", "32", "-i", "3"])
    assert opt.metrics.get("computing time for each node") > 0


def test_perf_harness_distributed():
    opt = perf.main(["-m", "lenet5", "-b", "32", "-i", "3",
                     "--partitions", "8"])
    assert opt.metrics.get("computing time for each node") > 0


class _ThroughputTap(logging.Handler):
    """Counts emitted per-iteration throughput records; a non-trivial
    ``emit`` makes any formatting/handling cost observable."""

    def __init__(self):
        super().__init__()
        self.lines = []

    def emit(self, record):
        msg = record.getMessage()
        if "Throughput is" in msg:
            self.lines.append(msg)


def _train_with_tap(iterations: int):
    import jax

    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import LocalDataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.datasets import synthetic_separable

    samples = synthetic_separable(64, 8, n_classes=2, seed=4)
    ds = LocalDataSet(samples).transform(SampleToMiniBatch(16))
    model = nn.Sequential().add(nn.Linear(8, 2)).add(nn.LogSoftMax())
    model.reset(jax.random.PRNGKey(0))
    opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
    opt.set_optim_method(optim.SGD(learning_rate=0.1))
    opt.set_end_when(optim.max_iteration(iterations))
    lg = logging.getLogger("bigdl_tpu")
    tap = _ThroughputTap()
    level = lg.level
    lg.addHandler(tap)
    lg.setLevel(logging.INFO)
    try:
        opt.optimize()
    finally:
        lg.removeHandler(tap)
        lg.setLevel(level)
    return tap.lines


def test_throughput_log_default_every_iteration():
    """Default bigdl.telemetry.logEveryN=1: the reference protocol is
    unchanged — one throughput line per iteration."""
    assert len(_train_with_tap(6)) == 6


def test_throughput_log_rate_limited():
    """bigdl.telemetry.logEveryN=3 logs iterations 3 and 6 only — the
    skipped iterations must not even reach a handler (no formatting, no
    emission: zero per-step logging cost on the drain path)."""
    config.set_property("bigdl.telemetry.logEveryN", 3)
    try:
        lines = _train_with_tap(6)
    finally:
        config.clear_property("bigdl.telemetry.logEveryN")
    assert len(lines) == 2
    assert "[Iteration 3]" in lines[0] and "[Iteration 6]" in lines[1]


def test_rate_limited_run_keeps_loss_and_summary_series():
    """Rate limiting is LOG hygiene only: driver state, metrics, and the
    per-iteration summary protocol are untouched."""
    import tempfile

    import jax

    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset import LocalDataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.datasets import synthetic_separable
    from bigdl_tpu.visualization import TrainSummary

    config.set_property("bigdl.telemetry.logEveryN", 100)
    try:
        samples = synthetic_separable(64, 8, n_classes=2, seed=4)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(16))
        model = nn.Sequential().add(nn.Linear(8, 2)).add(nn.LogSoftMax())
        model.reset(jax.random.PRNGKey(0))
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.1))
        opt.set_end_when(optim.max_iteration(5))
        ts = TrainSummary(tempfile.mkdtemp(), "ratelimit")
        opt.set_train_summary(ts)
        opt.optimize()
        assert opt.metrics.get("computing time for each node") > 0
        assert len(ts.read_scalar("Loss")) == 5
        assert len(ts.read_scalar("Throughput")) == 5
    finally:
        config.clear_property("bigdl.telemetry.logEveryN")
