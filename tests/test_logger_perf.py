"""LoggerFilter + perf harness coverage."""

import logging
import os

from bigdl_tpu.models import perf
from bigdl_tpu.utils.logger_filter import redirect_spark_info_logs


def test_logger_filter_writes_file(tmp_path):
    log = str(tmp_path / "bigdl.log")
    path = redirect_spark_info_logs(log)
    assert path == log
    logging.getLogger("bigdl_tpu").info("hello from the driver")
    for h in logging.getLogger("bigdl_tpu").handlers:
        h.flush()
    assert "hello from the driver" in open(log).read()
    # restore default handlers for other tests
    logging.getLogger("bigdl_tpu").handlers = []
    logging.getLogger("bigdl_tpu").propagate = True


def test_perf_harness_lenet():
    opt = perf.main(["-m", "lenet5", "-b", "32", "-i", "3"])
    assert opt.metrics.get("computing time for each node") > 0


def test_perf_harness_distributed():
    opt = perf.main(["-m", "lenet5", "-b", "32", "-i", "3",
                     "--partitions", "8"])
    assert opt.metrics.get("computing time for each node") > 0
