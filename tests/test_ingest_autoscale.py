"""Ingest stage autoscaling (tentpole: feed the chip).

The supervisor-driven autoscaler must be a pure function of its signal
trace (determinism), respect the floor/ceiling/governor authority, and
— driven end to end by the ``bigdl.chaos.starveStageAt`` injector — add
decode workers when the assemble stage starves (satellite f: the
acceptance test for the chaos hook)."""

import io

import numpy as np
import pytest

from bigdl_tpu.dataset.image import LabeledImageBytes
from bigdl_tpu.dataset.ingest import (AutoscalePolicy, StreamingIngest,
                                      _DecodePool, summary_scalars)
from bigdl_tpu.utils import chaos, config
from bigdl_tpu.utils.random_generator import RandomGenerator

_AUTOSCALE_KEYS = ("bigdl.ingest.autoscale.enabled",
                   "bigdl.ingest.autoscale.min",
                   "bigdl.ingest.autoscale.max",
                   "bigdl.ingest.autoscale.intervalSec",
                   "bigdl.ingest.autoscale.upStarveFrac",
                   "bigdl.ingest.autoscale.downStarveFrac",
                   "bigdl.ingest.autoscale.patience",
                   "bigdl.ingest.autoscale.cooldown",
                   "bigdl.chaos.starveStageAt")


@pytest.fixture(autouse=True)
def _clean_env():
    yield
    chaos.uninstall()
    for k in _AUTOSCALE_KEYS:
        config.clear_property(k)


def _png_records(n=12, hw=(40, 48), seed=3):
    from PIL import Image
    rng = np.random.RandomState(seed)
    recs = []
    for i in range(n):
        img = rng.randint(0, 256, size=hw + (3,)).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(img).save(buf, "PNG")
        recs.append(LabeledImageBytes(f"r{i}", float(i % 5 + 1),
                                      buf.getvalue()))
    return recs


# ---------------------------------------------------------------------------
# the pure policy: deterministic hysteresis
# ---------------------------------------------------------------------------


class TestAutoscalePolicy:
    def _run(self, trace, **kw):
        policy = AutoscalePolicy(kw.pop("min_workers", 1),
                                 kw.pop("max_workers", 8),
                                 kw.pop("up", 0.2), kw.pop("down", 0.02),
                                 kw.pop("patience", 2),
                                 kw.pop("cooldown", 3))
        workers, out = kw.pop("start", 2), []
        assert not kw
        for starve, bp, pressure in trace:
            d = policy.decide(starve, bp, workers, pressure)
            workers += d
            out.append(d)
        return out, workers

    def test_fixed_starve_trace_is_deterministic(self):
        """Satellite c: the same signal trace always yields the same
        action sequence — patience delays the first action, cooldown
        spaces the rest."""
        trace = [(0.5, 0.0, False)] * 8
        first = self._run(trace)
        second = self._run(trace)
        assert first == second
        assert first[0] == [0, 1, 0, 0, 0, 0, 1, 0]

    def test_ceiling_and_floor_are_hard(self):
        acts, workers = self._run([(0.9, 0.0, False)] * 20,
                                  max_workers=3, patience=1, cooldown=0)
        assert workers == 3 and all(a >= 0 for a in acts)
        acts, workers = self._run([(0.0, 0.0, False)] * 20,
                                  start=1, patience=1, cooldown=0)
        assert workers == 1 and acts == [0] * 20

    def test_governor_pressure_only_scales_down(self):
        """The host-memory governor is the upper-bound authority: under
        pressure a starving pipeline still may not grow."""
        acts, workers = self._run([(0.9, 0.0, True)] * 6,
                                  start=4, patience=1, cooldown=0)
        assert workers < 4 and all(a <= 0 for a in acts)

    def test_backpressure_bound_pipeline_scales_down(self):
        """High backpressure means the CONSUMER is the bottleneck —
        more decode workers cannot help, so the verdict is down."""
        acts, _ = self._run([(0.5, 0.9, False)] * 4,
                            start=4, patience=1, cooldown=0)
        assert acts[0] == -1


# ---------------------------------------------------------------------------
# the resizable decode pool
# ---------------------------------------------------------------------------


class TestDecodePool:
    def test_resize_up_and_down(self):
        pool = _DecodePool(2)
        try:
            assert pool.workers == 2
            assert pool.set_workers(4) == 4
            assert [f.result(5) for f in
                    [pool.submit(lambda v: v * v, i) for i in range(8)]] \
                == [i * i for i in range(8)]
            assert pool.set_workers(1) == 1       # cooperative shrink
        finally:
            pool.shutdown(wait=False)

    def test_submitted_exception_propagates(self):
        pool = _DecodePool(1)
        try:
            fut = pool.submit(lambda: 1 / 0)
            with pytest.raises(ZeroDivisionError):
                fut.result(5)
        finally:
            pool.shutdown(wait=False)


# ---------------------------------------------------------------------------
# end to end: chaos-starved decode stage -> scale-up (satellite f)
# ---------------------------------------------------------------------------


class TestAutoscaleEndToEnd:
    def test_worker_gauges_surface_in_summary(self):
        recs = _png_records(n=8)
        RandomGenerator.RNG().set_seed(7)
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2)
        it = eng(iter(recs))
        next(it)
        scalars = dict(summary_scalars())
        it.close()
        assert scalars[f"Ingest/{eng.name}/decode/workers"] == 2
        assert scalars[f"Ingest/{eng.name}/assemble/workers"] >= 1

    def test_starved_decode_stage_scales_up(self):
        """Arm ``bigdl.chaos.starveStageAt`` on the decode stage: its
        output rate collapses, the assembler starves, and the autoscaler
        must add decode workers (counted in ``autoscale_events`` and
        reflected in ``stage_workers``) — while the batch stream itself
        stays complete and correct."""
        config.set_property("bigdl.ingest.autoscale.intervalSec", 0.05)
        config.set_property("bigdl.ingest.autoscale.upStarveFrac", 0.05)
        config.set_property("bigdl.ingest.autoscale.patience", 1)
        config.set_property("bigdl.ingest.autoscale.cooldown", 0)
        config.set_property("bigdl.ingest.autoscale.max", 4)
        config.set_property("bigdl.chaos.starveStageAt", "decode:1:10")
        chaos.install()
        recs = _png_records(n=48)
        RandomGenerator.RNG().set_seed(7)
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=1)
        n = sum(b.size() for b in eng(iter(recs)))
        assert n == 48
        assert eng.autoscale_events["up"] >= 1
        assert eng.stage_workers["decode"] >= 2
        assert chaos._state.stage_starve_throttles > 0

    def test_autoscale_disabled_holds_worker_count(self):
        config.set_property("bigdl.ingest.autoscale.enabled", False)
        config.set_property("bigdl.chaos.starveStageAt", "decode:1:10")
        chaos.install()
        recs = _png_records(n=16)
        RandomGenerator.RNG().set_seed(7)
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=1)
        assert sum(b.size() for b in eng(iter(recs))) == 16
        assert eng.autoscale_events == {"up": 0, "down": 0}
        assert eng.stage_workers["decode"] == 1
