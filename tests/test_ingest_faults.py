"""Self-healing ingest: error taxonomy, quarantine, supervised stage
restarts, stall detection, sync fallback, seqfile resync, and the
prefetcher fault paths — every leg chaos-injected and parity-asserted."""

import io
import struct
import threading
import time

import numpy as np
import pytest

from bigdl_tpu.dataset.image import LabeledImageBytes
from bigdl_tpu.dataset.ingest import (IngestInfraError, IngestStallError,
                                      QuarantineExceededError,
                                      RecordQuarantine, ShardedSeqFileReader,
                                      StreamingIngest)
from bigdl_tpu.dataset.mt_batch import MTLabeledBGRImgToBatch
from bigdl_tpu.utils import chaos, config
from bigdl_tpu.utils.random_generator import RandomGenerator


def _png_records(n=12, hw=(40, 48), seed=3):
    from PIL import Image
    rng = np.random.RandomState(seed)
    recs = []
    for i in range(n):
        img = rng.randint(0, 256, size=hw + (3,)).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(img).save(buf, "PNG")
        recs.append(LabeledImageBytes(f"r{i}", float(i % 5 + 1),
                                      buf.getvalue()))
    return recs


@pytest.fixture(autouse=True)
def _fast_retries():
    """No real backoff sleeps in tier-1; chaos plans reset per test."""
    config.set_property("bigdl.io.retryInterval", 0.001)
    yield
    config.clear_property("bigdl.io.retryInterval")
    chaos.uninstall()


def _chaos(**props):
    for k, v in props.items():
        config.set_property(f"bigdl.chaos.{k}", v)
    chaos.install()
    for k in props:
        config.clear_property(f"bigdl.chaos.{k}")


def _batches(transformer, records):
    return [(b.get_input().copy(), b.get_target().copy())
            for b in transformer(iter(records))]


def _sync_batches(records, seed=7, batch=4):
    RandomGenerator.RNG().set_seed(seed)
    return _batches(MTLabeledBGRImgToBatch(batch, crop=(32, 32)), records)


def _assert_stream_equal(got, want):
    assert len(got) == len(want)
    for (xg, yg), (xw, yw) in zip(got, want):
        np.testing.assert_array_equal(xg, xw)
        np.testing.assert_array_equal(yg, yw)


class TestQuarantine:
    def test_corrupt_record_skipped_and_stream_matches_survivors(self):
        """A corrupt record quarantines; the surviving batch stream is
        bit-identical to the sync path over the surviving records (the
        skipped record draws no RNG)."""
        recs = _png_records(12)
        _chaos(corruptRecordAt="5")
        RandomGenerator.RNG().set_seed(7)
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2,
                              max_bad_records=3)
        got = _batches(eng, recs)
        assert eng.quarantine.count == 1
        sample = eng.quarantine.samples[0]
        assert sample["stage"] == "read" and sample["index"] == 5
        _assert_stream_equal(got, _sync_batches(recs[:5] + recs[6:]))

    def test_decode_failure_quarantined_before_any_draw(self):
        recs = _png_records(12)
        _chaos(failDecodeAt="3")
        RandomGenerator.RNG().set_seed(7)
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2,
                              max_bad_records=3)
        got = _batches(eng, recs)
        assert eng.quarantine.count == 1
        assert eng.quarantine.by_stage == {"decode": 1}
        _assert_stream_equal(got, _sync_batches(recs[:3] + recs[4:]))

    def test_genuinely_undecodable_bytes_quarantined(self):
        recs = _png_records(10)
        recs[5] = LabeledImageBytes("junk", 1.0, b"not an image at all")
        RandomGenerator.RNG().set_seed(7)
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2,
                              max_bad_records=1)
        got = _batches(eng, recs)
        assert eng.quarantine.count == 1
        _assert_stream_equal(got, _sync_batches(recs[:5] + recs[6:]))

    def test_undersized_record_quarantined_with_budget(self):
        recs = _png_records(6, hw=(40, 48))
        recs[2:3] = _png_records(1, hw=(20, 48))
        RandomGenerator.RNG().set_seed(7)
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2,
                              max_bad_records=1)
        got = _batches(eng, recs)
        assert eng.quarantine.by_stage == {"assemble": 1}
        _assert_stream_equal(got, _sync_batches(recs[:2] + recs[3:]))

    def test_budget_zero_keeps_fail_fast_contract(self):
        """maxBadRecords=0 (the default) re-raises the ORIGINAL data
        error — today's behaviour, bit for bit."""
        recs = _png_records(8)
        _chaos(corruptRecordAt="2")
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2)
        with pytest.raises(chaos.CorruptRecord):
            list(eng(iter(recs)))

    def test_budget_exceeded_fails_loudly_with_offender_sample(self):
        recs = _png_records(12)
        _chaos(corruptRecordAt="2:8")
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2,
                              max_bad_records=2)
        with pytest.raises(QuarantineExceededError, match="maxBadRecords"):
            list(eng(iter(recs)))
        assert len(eng.quarantine.samples) == 3
        assert eng.quarantine.samples[0]["index"] == 2

    def test_quarantine_counts_flow_to_metrics_registry(self):
        from bigdl_tpu import telemetry
        recs = _png_records(8)
        _chaos(failDecodeAt="1")
        before = telemetry.counter("Ingest/quarantined",
                                   summary=True).value
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2,
                              max_bad_records=2)
        list(eng(iter(recs)))
        assert telemetry.counter("Ingest/quarantined",
                                 summary=True).value == before + 1
        assert eng.fault_stats()["quarantine"]["count"] == 1


class TestTransientReads:
    def test_transient_read_blips_retry_to_bit_parity(self):
        """Reader blips absorb into the capped-backoff retry: nothing
        quarantined, stream bit-identical to an undisturbed run."""
        recs = _png_records(12)
        _chaos(transientReads=2)
        RandomGenerator.RNG().set_seed(7)
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2,
                              max_bad_records=3)
        got = _batches(eng, recs)
        assert eng.quarantine.count == 0
        _assert_stream_equal(got, _sync_batches(recs))

    def test_blips_beyond_retry_budget_surface_as_infra_error(self):
        recs = _png_records(8)
        config.set_property("bigdl.io.retryTimes", 2)
        try:
            _chaos(transientReads=5)
            eng = StreamingIngest(4, crop=(32, 32), decode_workers=2,
                                  max_bad_records=3)
            with pytest.raises(chaos.ChaosError, match="transient"):
                list(eng(iter(recs)))
            assert eng.quarantine.count == 0   # a blip is not dirty data
        finally:
            config.clear_property("bigdl.io.retryTimes")


class TestSupervisedStages:
    @pytest.mark.parametrize("plan", ["reader:4", "assembler:6"])
    def test_killed_stage_thread_restarts_to_bit_parity(self, plan):
        """A silently-dead stage thread is detected, restarted from
        shared stage state, and the stream completes bit-identical to
        the synchronous path — the RNG clone-and-commit contract
        survives the restart."""
        recs = _png_records(12)
        _chaos(killStageThread=plan)
        RandomGenerator.RNG().set_seed(7)
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2)
        got = _batches(eng, recs)
        assert eng.supervisor.restarts == 1
        _assert_stream_equal(got, _sync_batches(recs))

    def test_dead_decode_worker_resubmitted(self):
        recs = _png_records(12)
        _chaos(killStageThread="decode:5")
        RandomGenerator.RNG().set_seed(7)
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2)
        got = _batches(eng, recs)
        assert eng.supervisor.restarts == 1
        _assert_stream_equal(got, _sync_batches(recs))

    def test_restart_budget_exhausted_escalates_with_diagnosis(self):
        recs = _png_records(12)
        _chaos(killStageThread="assembler:6")
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2,
                              max_stage_restarts=0)
        with pytest.raises(IngestInfraError, match="assembler") as ei:
            list(eng(iter(recs)))
        # the failure carries the per-stage stats, naming the sick stage
        assert set(ei.value.diagnosis) >= {"read", "decode", "assemble"}

    def test_orderly_completion_never_restarts(self):
        recs = _png_records(8)
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2)
        assert sum(b.size() for b in eng(iter(recs))) == 8
        assert eng.supervisor.restarts == 0
        assert eng.supervisor.failure is None

    def test_teardown_joins_supervisor_and_stage_threads(self):
        before = threading.active_count()
        recs = _png_records(8)

        def infinite():
            while True:
                yield from recs

        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2)
        it = eng(infinite())
        next(it)
        it.close()
        deadline = time.monotonic() + 10
        while (threading.active_count() > before and
               time.monotonic() < deadline):
            time.sleep(0.05)
        assert threading.active_count() <= before, "thread leaked"


class TestStallAndFallback:
    def test_wedged_ring_detected_with_stage_diagnosis(self):
        """Producer hung + consumer blocked: the per-ring heartbeats
        declare the engine dead within the stall window instead of
        hanging forever, and the error names the per-stage stats."""
        recs = _png_records(4)

        def hung():
            yield from recs[:2]
            time.sleep(3600)    # a wedged upstream read, forever

        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2,
                              stall_timeout=0.5)
        t0 = time.monotonic()
        with pytest.raises(IngestStallError, match="stallTimeoutSec") as ei:
            list(eng(hung()))
        assert time.monotonic() - t0 < 10
        assert "read" in ei.value.diagnosis

    def test_fallback_finishes_epoch_on_sync_path_bit_identically(self):
        """A supervisor-declared-dead engine with fallbackOnFailure
        switches to the synchronous path mid-epoch: same drawer RNG, so
        the full stream equals an undisturbed run bit for bit."""
        recs = _png_records(12)
        _chaos(killStageThread="assembler:6")
        RandomGenerator.RNG().set_seed(7)
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2,
                              max_stage_restarts=0,
                              fallback_on_failure=True)
        got = _batches(eng, recs)
        assert eng.fallbacks == 1
        _assert_stream_equal(got, _sync_batches(recs))

    def test_fallback_after_reader_death_pulls_remaining_upstream(self):
        recs = _png_records(12)
        _chaos(killStageThread="reader:4")
        RandomGenerator.RNG().set_seed(7)
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2,
                              max_stage_restarts=0,
                              fallback_on_failure=True)
        got = _batches(eng, recs)
        assert eng.fallbacks == 1
        _assert_stream_equal(got, _sync_batches(recs))

    def test_fallback_quarantines_bad_records_in_tail(self):
        """Quarantine keeps working after the switch: a corrupt record
        past the failure point still skips instead of raising."""
        recs = _png_records(12)
        _chaos(killStageThread="assembler:2", corruptRecordAt="9")
        RandomGenerator.RNG().set_seed(7)
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2,
                              max_stage_restarts=0,
                              fallback_on_failure=True,
                              max_bad_records=2)
        got = _batches(eng, recs)
        assert eng.fallbacks == 1
        assert eng.quarantine.count == 1
        _assert_stream_equal(got, _sync_batches(recs[:9] + recs[10:]))

    def test_watchdog_stall_diagnostics_include_live_engines(self):
        """The hung-step watchdog's fire path reports the ingest
        engines' per-stage stats + ring ages: a driver stall rooted in
        a wedged data pipeline is diagnosed, not just detected."""
        from bigdl_tpu.utils import elastic
        recs = _png_records(8)
        eng = StreamingIngest(4, crop=(32, 32), decode_workers=2)
        it = eng(iter(recs))
        next(it)
        diag = elastic.stall_diagnostics()
        try:
            assert eng.name in diag["ingest"]
            entry = diag["ingest"][eng.name]
            assert "read" in entry["stats"]
            assert set(entry["faults"]["ring_ages_s"]) == {
                "record_ring", "batch_ring"}
        finally:
            it.close()

    def test_mt_transformer_accepts_explicit_drawer(self):
        """The sync path's injectable drawer (the fallback's RNG hook):
        an explicit RandomGenerator replaces the thread-local stream."""
        recs = _png_records(8)
        rng_a = RandomGenerator(99)
        got = _batches(MTLabeledBGRImgToBatch(4, crop=(32, 32), rng=rng_a),
                       recs)
        rng_b = RandomGenerator(99)
        again = _batches(MTLabeledBGRImgToBatch(4, crop=(32, 32),
                                                rng=rng_b), recs)
        _assert_stream_equal(got, again)


class TestSeqfileResync:
    def _write(self, tmp_path, n=10, payload=1100):
        from bigdl_tpu.dataset import seqfile
        path = str(tmp_path / "a.seq")
        entries = [(f"k{i}", float(i + 1), bytes([i % 256]) * payload)
                   for i in range(n)]
        seqfile.write_image_seqfile(path, entries)
        return path, entries

    def _record_offsets(self, path):
        from bigdl_tpu.dataset import seqfile
        offs = []
        with open(path, "rb") as f:
            sync = seqfile._read_header(f, path)
            while True:
                o = f.tell()
                raw = f.read(4)
                if not raw:
                    return offs
                (rl,) = struct.unpack(">i", raw)
                if rl == -1:
                    f.read(16)
                    continue
                offs.append(o)
                f.read(4 + rl)

    def test_corrupt_error_names_offset_and_record_index(self, tmp_path):
        from bigdl_tpu.dataset import seqfile
        path, _ = self._write(tmp_path)
        offs = self._record_offsets(path)
        with open(path, "r+b") as f:     # flip the length field of rec 4
            f.seek(offs[4])
            f.write(b"\x7f\xff\xff\xff")
        with pytest.raises(IOError, match=rf"record 4 at offset {offs[4]}"):
            list(seqfile.read_image_seqfile(path))

    def test_resync_skips_to_next_marker_not_the_whole_shard(self, tmp_path):
        from bigdl_tpu.dataset import seqfile
        path, entries = self._write(tmp_path)
        clean = list(seqfile.read_image_seqfile(path))
        offs = self._record_offsets(path)
        with open(path, "r+b") as f:
            f.seek(offs[4])
            f.write(b"\x7f\xff\xff\xff")
        skips = []
        got = list(seqfile.read_image_seqfile_resilient(
            path, on_skip=lambda e, resume: skips.append((e, resume))))
        assert len(skips) == 1
        err, resume = skips[0]
        assert isinstance(err, seqfile.CorruptRecordError)
        assert err.record_index == 4 and err.offset == offs[4]
        assert resume is not None and resume > offs[4]
        # the prefix survives exactly; only records between the damage
        # and the next sync marker are lost — never the shard
        assert got[:4] == clean[:4]
        tail = clean[-len(got) + 4:] if len(got) > 4 else []
        assert got[4:] == tail
        assert len(got) >= len(clean) - 3

    def test_find_next_sync_none_past_last_marker(self, tmp_path):
        from bigdl_tpu.dataset import seqfile
        path, _ = self._write(tmp_path)
        size = (tmp_path / "a.seq").stat().st_size
        assert seqfile.find_next_sync(path, size - 4) is None

    def test_sharded_reader_quarantines_corrupt_records(self, tmp_path):
        from bigdl_tpu.dataset import seqfile
        good = [(f"k{i}", 1.0, bytes([i]) * 1100) for i in range(8)]
        seqfile.write_image_seqfile(str(tmp_path / "a.seq"), good)
        seqfile.write_image_seqfile(str(tmp_path / "b.seq"), good)
        path_b = str(tmp_path / "b.seq")
        offs = self._record_offsets(path_b)
        with open(path_b, "r+b") as f:
            f.seek(offs[3])
            f.write(b"\x7f\xff\xff\xff")
        q = RecordQuarantine(budget=4)
        reader = ShardedSeqFileReader(str(tmp_path), shards=2, quarantine=q)
        names = [r.name for r in reader]
        assert q.count >= 1
        assert all(n.startswith("k") for n in names)
        # file a intact: all 8 records; file b loses only the resync gap
        assert sum(1 for n in names) >= 8 + 5
        # budget 0 (the default) keeps the historical fail-fast contract
        with pytest.raises(IOError):
            list(ShardedSeqFileReader(str(tmp_path), shards=2))


class TestPrefetcherFaultPaths:
    """BatchPrefetcher: a fetch-thread exception during transfer-ahead
    (in-flight device_put outstanding) must surface the ORIGINAL error
    at the consuming call site and tear down without deadlock."""

    def _fetcher(self, fail_at, payload_mb=5):
        import jax.numpy as jnp
        state = {"n": 0}

        def fetch():
            state["n"] += 1
            if state["n"] == fail_at:
                raise RuntimeError("fetch boom")
            # large enough to cross READY_BYTES: the transfer stage
            # really blocks an in-flight upload device-resident
            return jnp.ones((payload_mb * 256 * 1024,), jnp.float32)

        return fetch, state

    def test_fetch_error_during_transfer_ahead_surfaces_original(self):
        from bigdl_tpu.engine import BatchPrefetcher
        fetch, _ = self._fetcher(fail_at=3)
        p = BatchPrefetcher(fetch, depth=2, transfer_ahead=3)
        try:
            got = [p() for _ in range(2)]
            assert all(g is not None for g in got)
            with pytest.raises(RuntimeError, match="fetch boom"):
                p()
        finally:
            p.stop()

    def test_teardown_with_outstanding_uploads_does_not_deadlock(self):
        from bigdl_tpu.engine import BatchPrefetcher
        fetch, state = self._fetcher(fail_at=10 ** 9)
        p = BatchPrefetcher(fetch, depth=2, transfer_ahead=3)
        p()                                  # pipeline primed, uploads live
        t0 = time.monotonic()
        p.stop()                             # must join, not hang
        assert time.monotonic() - t0 < 15
        assert not p._thread.is_alive()
        assert not p._transfer_thread.is_alive()

    def test_error_before_first_batch_raises_immediately(self):
        from bigdl_tpu.engine import BatchPrefetcher
        fetch, _ = self._fetcher(fail_at=1)
        p = BatchPrefetcher(fetch, depth=2, transfer_ahead=2)
        try:
            with pytest.raises(RuntimeError, match="fetch boom"):
                p()
        finally:
            p.stop()


class TestConsumerAbandonment:
    """The serving shed path: a consumer that stops consuming mid-stream
    must be able to tear down in-flight device_put/dispatch work without
    deadlock — and a producer failure it never got around to reading
    must still SURFACE, not vanish with the drained rings."""

    def test_prefetcher_abandon_surfaces_unconsumed_error(self):
        from bigdl_tpu.engine import BatchPrefetcher
        import jax.numpy as jnp
        state = {"n": 0}

        def fetch():
            state["n"] += 1
            if state["n"] == 3:
                raise RuntimeError("fetch boom")
            return jnp.ones((64,), jnp.float32)

        p = BatchPrefetcher(fetch, depth=2, transfer_ahead=3)
        p()                           # consume ONE, then abandon
        deadline = time.monotonic() + 10
        while p._thread.is_alive() and time.monotonic() < deadline:
            time.sleep(0.01)          # producer runs to its failure
        t0 = time.monotonic()
        p.stop()                      # must join, not hang
        assert time.monotonic() - t0 < 15
        assert isinstance(p.error, RuntimeError)
        assert "fetch boom" in str(p.error)

    def test_prefetcher_abandon_with_blocked_producer_no_deadlock(self):
        """Abandon while the producer is BLOCKED pushing into full rings
        (the worst case: nothing is consuming, every queue is at
        capacity, uploads in flight)."""
        from bigdl_tpu.engine import BatchPrefetcher
        import jax.numpy as jnp
        p = BatchPrefetcher(lambda: jnp.ones((2 * 1024 * 1024,),
                                             jnp.float32),
                            depth=2, transfer_ahead=3)
        time.sleep(0.3)               # rings fill, producer wedges in put
        t0 = time.monotonic()
        p.stop()
        assert time.monotonic() - t0 < 15
        assert not p._thread.is_alive()
        assert not p._transfer_thread.is_alive()
        assert p.error is None        # no failure happened — none invented

    def test_dispatch_pipeline_abandon_skips_drain(self):
        from bigdl_tpu.engine import DispatchPipeline
        drained = []
        p = DispatchPipeline(lambda item, nxt: drained.append(item[0]),
                             depth=8)
        for i in range(5):
            p.push(i)
        assert p.abandon() == 5
        p.flush()
        assert drained == [], "abandoned items must never hit drain"
        # the pipeline keeps working for a consumer that comes back
        p.push(7)
        p.flush()
        assert drained == [7]


@pytest.mark.slow
def test_chaos_ingest_soak_trained_weight_parity():
    """The acceptance soak: training through StreamingIngest with an
    injected corrupt record, transient reader IO errors, AND one killed
    stage thread completes and reaches BIT-EXACT trained-weight parity
    with a clean run over the same surviving records.

    Oracle construction: the faulty run's quarantine log names exactly
    which record was dropped (positional injectors fire once per plan);
    the clean run streams the same records through an un-chaosed engine
    with that one record dropped at its first occurrence — identical
    surviving stream, identical RNG draws, so the weights must match to
    the bit."""
    import jax
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset.dataset import LocalDataSet
    from bigdl_tpu.dataset.transformer import Transformer

    recs = _png_records(n=48, hw=(40, 48), seed=5)

    class ToSamples(Transformer):
        def __call__(self, it):
            from bigdl_tpu.dataset.sample import MiniBatch
            for b in it:
                x = b.get_input().reshape(b.size(), -1)[:, :64] / 255.0
                y = (b.get_target() % 2) + 1
                yield MiniBatch(x.astype(np.float32),
                                y.astype(np.float32))

    class DropOnce(Transformer):
        """Skip the FIRST occurrence of the named record — replays the
        faulty run's quarantine decision on the clean stream."""

        def __init__(self, name):
            self.name = name
            self.dropped = False

        def __call__(self, it):
            for r in it:
                if not self.dropped and r.name == self.name:
                    self.dropped = True
                    continue
                yield r

    def train(engine, head):
        model = (nn.Sequential().add(nn.Linear(64, 16)).add(nn.Tanh())
                 .add(nn.Linear(16, 2)).add(nn.LogSoftMax()))
        model.reset(jax.random.PRNGKey(3))
        ds = LocalDataSet(list(recs), head + [engine, ToSamples()])
        o = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        o.set_optim_method(optim.SGD(learning_rate=0.1))
        o.set_end_when(optim.max_epoch(3))
        return np.asarray(o.optimize().get_parameters()[0])

    config.set_property("bigdl.io.retryInterval", 0.001)
    try:
        # one plan, three fault classes: a corrupt record, transient
        # reader IO blips, and a silently-killed assembler thread
        config.set_property("bigdl.chaos.corruptRecordAt", "17")
        config.set_property("bigdl.chaos.transientReads", 2)
        config.set_property("bigdl.chaos.killStageThread", "assembler:9")
        chaos.install()
        for k in ("corruptRecordAt", "transientReads", "killStageThread"):
            config.clear_property(f"bigdl.chaos.{k}")
        RandomGenerator.RNG().set_seed(41)
        eng = StreamingIngest(8, crop=(32, 32), decode_workers=2,
                              max_bad_records=4)
        w_faulty = train(eng, head=[])
        quarantined = [s for run in eng.run_history
                       for s in run["quarantine"]["samples"]]
        restarts = sum(run["stage_restarts"] for run in eng.run_history)
        assert len(quarantined) == 1, quarantined
        assert restarts >= 1
        chaos.uninstall()

        RandomGenerator.RNG().set_seed(41)
        eng2 = StreamingIngest(8, crop=(32, 32), decode_workers=2)
        w_clean = train(eng2, head=[DropOnce(quarantined[0]["name"])])
        np.testing.assert_array_equal(w_faulty, w_clean)
    finally:
        chaos.uninstall()
        for k in ("corruptRecordAt", "transientReads", "killStageThread"):
            config.clear_property(f"bigdl.chaos.{k}")
        config.clear_property("bigdl.io.retryInterval")
