"""Visualization tests: CRC32C vectors, TFRecord framing, proto round-trip,
and — the real proof — stock TensorBoard parsing our event files.

Reference analogs: ``visualization/*Spec`` + the requirement that
``RecordWriter``'s output is readable by stock TensorBoard.
"""

import os
import time

import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import LocalDataSet, SampleToMiniBatch
from bigdl_tpu.dataset.datasets import synthetic_separable
from bigdl_tpu.visualization import (FileWriter, TrainSummary,
                                     ValidationSummary, crc32c, masked_crc32c,
                                     read_records, scalar_summary,
                                     histogram_summary)
from bigdl_tpu.visualization import proto


class TestCrc32c:
    def test_known_vectors(self):
        # canonical CRC32C check value
        assert crc32c(b"123456789") == 0xE3069283
        assert crc32c(b"") == 0
        assert crc32c(b"\x00" * 32) == 0x8A9136AA

    def test_masking_matches_tfrecord_spec(self):
        crc = crc32c(b"hello")
        expected = (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF
        assert masked_crc32c(b"hello") == expected


class TestProto:
    def test_scalar_event_roundtrip(self):
        s = scalar_summary("Loss", 1.5)
        ev = proto.encode_event(step=7, summary=s)
        d = proto.decode_event(ev)
        assert d["step"] == 7
        assert d["values"][0]["tag"] == "Loss"
        assert abs(d["values"][0]["simple_value"] - 1.5) < 1e-6

    def test_histogram_encodes(self):
        s = histogram_summary("w", np.random.RandomState(0).normal(size=100))
        ev = proto.encode_event(step=1, summary=s)
        d = proto.decode_event(ev)
        assert d["values"][0]["histo"] is not None


class TestFileWriter:
    def test_records_survive_crc_check(self, tmp_path):
        w = FileWriter(str(tmp_path))
        for i in range(5):
            w.add_summary(scalar_summary("Loss", float(i)), i)
        w.close()
        files = [f for f in os.listdir(tmp_path)
                 if f.startswith("events.out.tfevents")]
        assert len(files) == 1
        recs = list(read_records(os.path.join(tmp_path, files[0])))
        # file_version marker + 5 scalars
        assert len(recs) == 6
        assert proto.decode_event(recs[0])["file_version"] == "brain.Event:2"

    def test_stock_tensorboard_parses_our_files(self, tmp_path):
        """The reference's acceptance bar: stock TensorBoard reads the file
        (RecordWriter.scala framing + Event protos)."""
        from tensorboard.backend.event_processing import event_accumulator

        w = FileWriter(str(tmp_path))
        for i in range(10):
            w.add_summary(scalar_summary("Loss", 10.0 - i), i)
        w.add_summary(histogram_summary(
            "weights", np.random.RandomState(0).normal(size=256)), 9)
        w.close()

        acc = event_accumulator.EventAccumulator(
            str(tmp_path), size_guidance={
                event_accumulator.SCALARS: 0,
                event_accumulator.HISTOGRAMS: 0})
        acc.Reload()
        assert "Loss" in acc.Tags()["scalars"]
        scalars = acc.Scalars("Loss")
        assert len(scalars) == 10
        assert scalars[0].value == 10.0
        assert scalars[9].step == 9
        assert "weights" in acc.Tags()["histograms"]
        h = acc.Histograms("weights")[0].histogram_value
        assert h.num == 256


class TestSummariesInTraining:
    def test_train_and_validation_summaries(self, tmp_path):
        samples = synthetic_separable(128, 4, n_classes=2, seed=3)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(32))
        model = (nn.Sequential().add(nn.Linear(4, 8)).add(nn.Tanh())
                 .add(nn.Linear(8, 2)).add(nn.LogSoftMax()))
        ts = TrainSummary(str(tmp_path), "app")
        ts.set_summary_trigger("Parameters", optim.every_epoch())
        vs = ValidationSummary(str(tmp_path), "app")
        opt = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.5))
        opt.set_end_when(optim.max_epoch(3))
        opt.set_train_summary(ts)
        opt.set_validation_summary(vs)
        opt.set_validation(optim.every_epoch(),
                           LocalDataSet(samples).transform(SampleToMiniBatch(32)),
                           [optim.Top1Accuracy()])
        opt.optimize()

        losses = ts.read_scalar("Loss")
        assert len(losses) == 12  # 4 iterations/epoch x 3 epochs
        assert losses[-1][1] < losses[0][1]
        assert len(ts.read_scalar("Throughput")) == 12
        assert len(ts.read_scalar("LearningRate")) == 12
        accs = vs.read_scalar("Top1Accuracy")
        assert len(accs) >= 2
        assert accs[-1][1] > 0.9

        # Parameters trigger produced per-layer histograms
        from tensorboard.backend.event_processing import event_accumulator
        acc = event_accumulator.EventAccumulator(
            ts.log_dir, size_guidance={event_accumulator.HISTOGRAMS: 0})
        acc.Reload()
        assert len(acc.Tags()["histograms"]) > 0
        ts.close()
        vs.close()
