"""Tests for bigdl_tpu.utils (reference test analog: utils/ specs)."""

import os

import numpy as np
import pytest

from bigdl_tpu.utils import (DirectedGraph, Edge, Node, RandomGenerator, T,
                             Table, file_io, kth_largest)


class TestTable:
    def test_t_constructor(self):
        t = T(10, 20, x=3)
        assert t[1] == 10 and t[2] == 20 and t["x"] == 3
        assert t.length() == 2

    def test_insert_remove(self):
        t = T("a", "b", "c")
        t.insert(2, "z")
        assert t.to_seq() == ["a", "z", "b", "c"]
        assert t.remove(2) == "z"
        assert t.to_seq() == ["a", "b", "c"]
        t.insert("d")
        assert t.length() == 4

    def test_pytree(self):
        import jax
        t = T(np.ones(3), np.zeros(2))
        doubled = jax.tree_util.tree_map(lambda x: x * 2, t)
        assert isinstance(doubled, Table)
        np.testing.assert_allclose(doubled[1], 2 * np.ones(3))

    def test_get_or_update(self):
        t = Table()
        assert t.get_or_update("k", lambda: 5) == 5
        assert t.get_or_update("k", lambda: 99) == 5


class TestFileIO:
    def test_save_load_roundtrip(self, tmp_path):
        obj = {"a": np.arange(5), "b": "text"}
        p = str(tmp_path / "obj.bin")
        file_io.save(obj, p)
        loaded = file_io.load(p)
        np.testing.assert_array_equal(loaded["a"], obj["a"])
        assert loaded["b"] == "text"

    def test_no_overwrite(self, tmp_path):
        p = str(tmp_path / "f.bin")
        file_io.save(1, p)
        with pytest.raises(FileExistsError):
            file_io.save(2, p, overwrite=False)

    def test_remote_scheme_dispatches_to_fsspec(self):
        # schemes route through fsspec, which names the missing client
        # (s3fs / a JVM for HDFS) when one is not installed in this image
        with pytest.raises(Exception, match="s3fs|S3"):
            file_io.save(1, "s3://bucket/path")


class TestRandomGenerator:
    def test_seed_reproducible(self):
        a = RandomGenerator(42)
        b = RandomGenerator(42)
        assert [a.uniform() for _ in range(5)] == [b.uniform() for _ in range(5)]

    def test_thread_local_singleton(self):
        assert RandomGenerator.RNG() is RandomGenerator.RNG()

    def test_permutation(self):
        p = RandomGenerator(1).permutation(10)
        assert sorted(p.tolist()) == list(range(10))


class TestDirectedGraph:
    def _diamond(self):
        a, b, c, d = Node("a"), Node("b"), Node("c"), Node("d")
        a.add(b)
        a.add(c)
        b.add(d)
        c.add(d)
        return a, b, c, d

    def test_topsort(self):
        a, b, c, d = self._diamond()
        order = [n.element for n in a.graph().topology_sort()]
        assert order[0] == "a" and order[-1] == "d"
        assert set(order) == {"a", "b", "c", "d"}

    def test_bfs_dfs(self):
        a, *_ = self._diamond()
        assert len(list(a.graph().bfs())) == 4
        assert len(list(a.graph().dfs())) == 4

    def test_reverse_graph(self):
        a, b, c, d = self._diamond()
        rev = [n.element for n in d.graph(reverse=True).topology_sort()]
        assert rev[0] == "d" and rev[-1] == "a"

    def test_cycle_detection(self):
        a, b = Node("a"), Node("b")
        a.add(b)
        b.add(a)
        with pytest.raises(ValueError):
            a.graph().topology_sort()

    def test_clone(self):
        a, *_ = self._diamond()
        g2 = a.graph().clone_graph()
        assert g2.size() == 4
        assert g2.source is not a


def test_kth_largest():
    arr = [5, 1, 9, 3, 7]
    assert kth_largest(arr, 1) == 9
    assert kth_largest(arr, 3) == 5
    assert kth_largest(arr, 5) == 1


def test_init_distributed_single_process_bringup():
    """Engine.init_distributed joins the jax distributed runtime (the
    multi-host tier) — exercised single-process in a subprocess so the
    global coordination client cannot leak into this test session."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    code = f"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
from bigdl_tpu.engine import Engine
Engine.init_distributed("127.0.0.1:{port}", 1, 0)
Engine.init_distributed("127.0.0.1:{port}", 1, 0)   # idempotent no-op
import jax
assert jax.process_count() == 1
assert jax.process_index() == 0
print("BRINGUP_OK")
"""
    # strip the site hook's accelerator vars: TPU_*/PJRT_* would trigger
    # jax's TPU cluster auto-detection and pre-init the backend
    def _keep(k):
        return not (k in ("JAX_PLATFORMS", "XLA_FLAGS") or
                    k.startswith(("TPU_", "AXON_", "_AXON", "PALLAS_",
                                  "PJRT_")))
    env = {k: v for k, v in os.environ.items() if _keep(k)}
    env["JAX_PLATFORMS"] = "cpu"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run([sys.executable, "-c", code], cwd=repo_root,
                         capture_output=True, text=True, timeout=120,
                         env=env)
    assert "BRINGUP_OK" in out.stdout, out.stderr[-2000:]


class TestDispatchPipeline:
    def test_depth_one_is_synchronous(self):
        from bigdl_tpu.engine import DispatchPipeline
        drained = []
        p = DispatchPipeline(lambda item, nxt: drained.append(item[0]),
                             depth=1)
        p.push("a")
        assert drained == ["a"], "depth=1 must drain at every push"
        p.push("b")
        assert drained == ["a", "b"]

    def test_bounded_in_flight_and_fifo(self):
        from bigdl_tpu.engine import DispatchPipeline
        drained = []
        p = DispatchPipeline(lambda item, nxt: drained.append(
            (item[0], None if nxt is None else nxt[0])), depth=3)
        for v in "abcde":
            p.push(v)
        # depth 3 keeps 2 in flight: a/b/c drained, with next-item peeks
        assert [d[0] for d in drained] == ["a", "b", "c"]
        assert drained[0] == ("a", "b")
        p.flush()
        assert [d[0] for d in drained] == list("abcde")
        assert drained[-1] == ("e", None)


def test_allgather_sum_exact_above_f32_integer_range(monkeypatch):
    """allgather_sum must keep integer exactness past 2^24 even though
    process_allgather downcasts float64 wires to float32 (jax_enable_x64
    off): values ride as float32 (hi, lo) pairs recombined in float64."""
    import jax

    from bigdl_tpu import engine as eng

    monkeypatch.setattr(jax, "process_count", lambda: 2)

    def fake_allgather(x):
        # emulate the real wire: per-process float32 payloads, stacked
        assert x.dtype == np.float32, "wire must already be float32-safe"
        return np.stack([x, x])

    fake_mod = type("M", (), {"process_allgather": staticmethod(
        fake_allgather)})
    import jax.experimental
    monkeypatch.setattr(jax.experimental, "multihost_utils", fake_mod,
                        raising=False)

    big = float(2 ** 25 + 1)            # not representable in float32
    out = eng.allgather_sum(np.array([big, 3.0]))
    np.testing.assert_array_equal(out, [2.0 * big, 6.0])


def test_batch_prefetcher_blocks_only_large_batches():
    """The ready-before-handoff guard is SIZE-GATED: bulk batches are
    blocked device-resident (dispatching against an in-flight bulk
    transfer costs ~10x step latency on the tunneled backend), while
    small batches stay async — blocking them costs a full round-trip per
    iteration, a measured ~20x small-model driver regression."""
    from bigdl_tpu.engine import BatchPrefetcher

    calls = []

    class FakeLeaf:
        def __init__(self, nbytes):
            self.nbytes = nbytes

        def block_until_ready(self):
            calls.append(self.nbytes)

    small = (FakeLeaf(1024), FakeLeaf(2048), 64)
    big = (FakeLeaf(8 << 20), FakeLeaf(1024), 64)
    batches = iter([small, big])
    pf = BatchPrefetcher(lambda: next(batches), depth=0)
    pf()
    assert calls == [], "small batch must not be blocked"
    pf()
    assert sorted(calls) == [1024, 8 << 20], "large batch blocks all leaves"


class TestConfigProperties:
    def test_resolution_order_override_env_default(self, monkeypatch):
        from bigdl_tpu.utils import config
        # table default (shield from any ambient env setting)
        monkeypatch.delenv("BIGDL_FAILURE_RETRYTIMES", raising=False)
        assert config.get_int("bigdl.failure.retryTimes") == 5
        # env var wins over default (dots -> underscores, upper-cased)
        monkeypatch.setenv("BIGDL_FAILURE_RETRYTIMES", "9")
        assert config.get_int("bigdl.failure.retryTimes") == 9
        # programmatic override wins over env
        config.set_property("bigdl.failure.retryTimes", 3)
        try:
            assert config.get_int("bigdl.failure.retryTimes") == 3
        finally:
            config.clear_property("bigdl.failure.retryTimes")
        assert config.get_int("bigdl.failure.retryTimes") == 9

    def test_typed_getters_and_diagnostics(self, monkeypatch):
        from bigdl_tpu.utils import config
        monkeypatch.delenv("BIGDL_ENGINETYPE", raising=False)
        config.set_property("bigdl.summary.flushSecs", "2.5")
        try:
            assert config.get_float("bigdl.summary.flushSecs") == 2.5
        finally:
            config.clear_property("bigdl.summary.flushSecs")
        try:
            for truthy in ("1", "true", "YES", "on", True):
                config.set_property("bigdl.check.singleton", truthy)
                assert config.get_bool("bigdl.check.singleton") is True
            config.set_property("bigdl.check.singleton", "off")
            assert config.get_bool("bigdl.check.singleton") is False
        finally:
            config.clear_property("bigdl.check.singleton")
        table = config.known_properties()
        assert table["bigdl.engineType"] == "tpu"
        assert "bigdl.pipeline.depth" in table
