"""Interop tests: Torch .t7 round-trips and TF GraphDef import/export with
golden parity against real TensorFlow execution.

Reference analogs: ``utils/TorchFileSpec`` and
``utils/tf/TensorflowLoaderSpec`` / ``TensorflowSaverSpec`` (load a graph,
run both sides on the same input, assert element-wise closeness).
"""

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.utils import torch_file

tf = pytest.importorskip("tensorflow")


class TestTorchFile:
    def test_tensor_roundtrip(self, tmp_path):
        p = str(tmp_path / "t.t7")
        for dtype in (np.float32, np.float64, np.int64, np.uint8):
            arr = (np.arange(24).reshape(2, 3, 4) * 1.5).astype(dtype)
            torch_file.save(p, arr)
            back = torch_file.load(p)
            assert back.dtype == arr.dtype
            np.testing.assert_array_equal(back, arr)

    def test_table_roundtrip(self, tmp_path):
        p = str(tmp_path / "t.t7")
        obj = {"weight": np.ones((3, 2), np.float32), "n": 7,
               "name": "linear", "flag": True, "nested": [1.5, 2.5]}
        torch_file.save(p, obj)
        back = torch_file.load(p)
        assert back["n"] == 7 and back["name"] == "linear"
        assert back["flag"] is True
        assert back["nested"] == [1.5, 2.5]
        np.testing.assert_array_equal(back["weight"], obj["weight"])

    def test_aliased_tensor_memoised(self, tmp_path):
        p = str(tmp_path / "t.t7")
        w = np.random.RandomState(0).normal(size=(4, 4)).astype(np.float32)
        torch_file.save(p, {"a": w, "b": w})
        back = torch_file.load(p)
        assert back["a"] is back["b"], "aliasing lost in round-trip"

    def test_list_roundtrip(self, tmp_path):
        p = str(tmp_path / "t.t7")
        torch_file.save(p, [1, 2, 3])
        assert torch_file.load(p) == [1, 2, 3]


class TestTorchModule:
    """Module-tree .t7 interop (reference ``TorchFile.loadModule`` /
    ``saveModule``, ``TorchFile.scala:142,262``)."""

    def _lenet_ish(self):
        m = (nn.Sequential()
             .add(nn.Reshape([1, 12, 12]))
             .add(nn.SpatialConvolution(1, 4, 5, 5))
             .add(nn.Tanh())
             .add(nn.SpatialMaxPooling(2, 2, 2, 2))
             .add(nn.SpatialBatchNormalization(4))
             .add(nn.Reshape([4 * 4 * 4]))
             .add(nn.Linear(64, 10))
             .add(nn.LogSoftMax()))
        m._ensure_init()
        return m

    def test_module_roundtrip_forward_parity(self, tmp_path):
        from bigdl_tpu.utils import torch_module
        p = str(tmp_path / "m.t7")
        model = self._lenet_ish()
        model.evaluate()
        x = np.random.RandomState(0).normal(size=(3, 144)).astype(np.float32)
        want = np.asarray(model.forward(x))

        torch_module.save_model(p, model)
        back = torch_module.load_model(p)
        back.evaluate()
        got = np.asarray(back.forward(x))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_serialized_shape_is_torch_convention(self, tmp_path):
        from bigdl_tpu.utils import torch_module
        p = str(tmp_path / "m.t7")
        lin = nn.Linear(3, 5)
        lin._ensure_init()
        torch_module.save_model(p, lin)
        raw = torch_file.load(p)
        assert raw.torch_class == "nn.Linear"
        # torch stores (out, in); our native layout is (in, out)
        assert raw.payload["weight"].shape == (5, 3)
        assert raw.payload["_type"] == "torch.FloatTensor"

    def test_conv_weight_2d_view_like_reference_writer(self, tmp_path):
        from bigdl_tpu.utils import torch_module
        p = str(tmp_path / "m.t7")
        conv = nn.SpatialConvolution(2, 3, 4, 5)   # kw=4, kh=5
        conv._ensure_init()
        torch_module.save_model(p, conv)
        raw = torch_file.load(p)
        # reference writer views OIHW 2-D as (nOut, nIn*kH*kW)
        assert raw.payload["weight"].shape == (3, 2 * 5 * 4)
        back = torch_module.load_model(p)
        x = np.random.RandomState(1).normal(size=(2, 2, 9, 8)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(back.forward(x)),
                                   np.asarray(conv.forward(x)),
                                   rtol=1e-5, atol=1e-5)

    def test_containers_and_bn_state(self, tmp_path):
        from bigdl_tpu.utils import torch_module
        p = str(tmp_path / "m.t7")
        bn = nn.BatchNormalization(4)
        bn._ensure_init()
        bn.state = {"running_mean": np.arange(4, dtype=np.float32),
                    "running_var": np.arange(1, 5, dtype=np.float32)}
        model = (nn.Sequential()
                 .add(nn.ConcatTable().add(nn.Identity()).add(nn.Identity()))
                 .add(nn.CAddTable())
                 .add(bn))
        model._ensure_init()
        model.evaluate()
        torch_module.save_model(p, model)
        back = torch_module.load_model(p)
        back.evaluate()
        x = np.random.RandomState(2).normal(size=(5, 4)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(back.forward(x)),
                                   np.asarray(model.forward(x)),
                                   rtol=1e-5, atol=1e-5)

    def test_unsupported_class_reports_name(self, tmp_path):
        from bigdl_tpu.utils import torch_module
        with pytest.raises(ValueError, match="nn.ExoticLayer"):
            torch_module.to_module(
                torch_file.TorchObject("nn.ExoticLayer", {}))

    def test_hardtanh_bounds_and_view_dims_roundtrip(self, tmp_path):
        from bigdl_tpu.utils import torch_module
        p = str(tmp_path / "m.t7")
        model = (nn.Sequential()
                 .add(nn.HardTanh(0.0, 20.0))
                 .add(nn.ReLU6())
                 .add(nn.View(-1).set_num_input_dims(2)))
        model._ensure_init()
        torch_module.save_model(p, model)
        raw = torch_file.load(p)
        # the reader lowers a 1..N-keyed lua table to a python list
        ht = raw.payload["modules"][0].payload
        assert ht["min_val"] == 0.0 and ht["max_val"] == 20.0
        r6 = raw.payload["modules"][1].payload
        assert r6["min_val"] == 0.0 and r6["max_val"] == 6.0
        back = torch_module.load_model(p)
        x = np.random.RandomState(3).normal(
            0, 10, size=(4, 3, 5)).astype(np.float32)
        got = np.asarray(back.forward(x))
        assert got.shape == (4, 15)      # numInputDims=2 keeps the batch dim
        np.testing.assert_allclose(got, np.asarray(model.forward(x)))

    def test_view_num_elements_excludes_inferred_dim(self):
        from bigdl_tpu.utils import torch_module
        obj = torch_module.from_module(nn.View(-1, 6))
        # torch7 divides input element count by numElements to infer the
        # batch; including -1 would make that negative
        assert obj.payload["numElements"] == 6.0
        # torch7 Reshape cannot represent an inferred dim at all
        with pytest.raises(ValueError, match="View instead"):
            torch_module.from_module(nn.Reshape([-1, 4]))
        obj = torch_module.from_module(nn.Reshape([2, 4]))
        assert obj.payload["nelement"] == 8.0

    def test_nhwc_modules_refuse_torch_export(self):
        from bigdl_tpu.utils import torch_module
        conv = nn.SpatialConvolution(2, 3, 3, 3, format="NHWC")
        conv._ensure_init()
        with pytest.raises(ValueError, match="NHWC"):
            torch_module.from_module(conv)
        bn = nn.SpatialBatchNormalization(4, format="NHWC")
        bn._ensure_init()
        with pytest.raises(ValueError, match="NHWC"):
            torch_module.from_module(bn)


def _run_tf(graph_def, feed_name, x, out_name):
    tf.compat.v1.reset_default_graph()
    with tf.compat.v1.Session() as sess:
        tf.import_graph_def(graph_def, name="")
        out = sess.graph.get_tensor_by_name(out_name + ":0")
        return sess.run(out, {feed_name + ":0": x})


def _mlp_graphdef():
    g = tf.Graph()
    rng = np.random.RandomState(0)
    with g.as_default():
        x = tf.compat.v1.placeholder(tf.float32, [None, 8], name="input")
        w1 = tf.constant(rng.normal(size=(8, 16)).astype(np.float32))
        b1 = tf.constant(rng.normal(size=(16,)).astype(np.float32))
        h = tf.nn.relu(tf.nn.bias_add(tf.matmul(x, w1), b1))
        w2 = tf.constant(rng.normal(size=(16, 4)).astype(np.float32))
        b2 = tf.constant(rng.normal(size=(4,)).astype(np.float32))
        y = tf.nn.softmax(tf.nn.bias_add(tf.matmul(h, w2), b2),
                          name="output")
    return g.as_graph_def()


def _cnn_graphdef():
    g = tf.Graph()
    rng = np.random.RandomState(1)
    with g.as_default():
        x = tf.compat.v1.placeholder(tf.float32, [None, 16, 16, 3],
                                     name="input")
        k1 = tf.constant(rng.normal(size=(3, 3, 3, 8)).astype(np.float32) * .2)
        b1 = tf.constant(rng.normal(size=(8,)).astype(np.float32) * .1)
        h = tf.nn.relu(tf.nn.bias_add(
            tf.nn.conv2d(x, k1, strides=[1, 1, 1, 1], padding="SAME"), b1))
        h = tf.nn.max_pool2d(h, ksize=[1, 2, 2, 1], strides=[1, 2, 2, 1],
                             padding="VALID")
        h = tf.reshape(h, [-1, 8 * 8 * 8])
        w = tf.constant(rng.normal(size=(8 * 8 * 8, 5)).astype(np.float32) * .1)
        y = tf.tanh(tf.matmul(h, w), name="output")
    return g.as_graph_def()


class TestTensorflowLoader:
    def test_mlp_golden_parity(self):
        from bigdl_tpu.utils.tf import TensorflowLoader
        gd = _mlp_graphdef()
        model = TensorflowLoader.load(gd, ["input"], ["output"])
        x = np.random.RandomState(2).normal(size=(6, 8)).astype(np.float32)
        ours = np.asarray(model.evaluate().forward(x))
        theirs = _run_tf(gd, "input", x, "output")
        np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-5)

    def test_cnn_golden_parity(self):
        from bigdl_tpu.utils.tf import TensorflowLoader
        gd = _cnn_graphdef()
        model = TensorflowLoader.load(gd, ["input"], ["output"])
        x = np.random.RandomState(3).normal(
            size=(2, 16, 16, 3)).astype(np.float32)
        ours = np.asarray(model.evaluate().forward(x))
        theirs = _run_tf(gd, "input", x, "output")
        np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-4)

    def test_unsupported_op_reports_name(self):
        from bigdl_tpu.utils.tf import TensorflowLoader
        g = tf.Graph()
        with g.as_default():
            x = tf.compat.v1.placeholder(tf.float32, [None, 4], name="input")
            tf.math.cumsum(x, name="output")
        with pytest.raises(ValueError, match="Cumsum"):
            TensorflowLoader.load(g.as_graph_def(), ["input"], ["output"])


class TestTensorflowSaver:
    def test_export_roundtrip_through_tf(self, tmp_path):
        """Export a trained-ish model, execute it with REAL TensorFlow,
        compare with our forward (reference TensorflowSaverSpec)."""
        from bigdl_tpu.utils.tf import saver
        model = (nn.Sequential()
                 .add(nn.SpatialConvolution(3, 8, 3, 3, 1, 1, -1, -1,
                                            format="NHWC"))
                 .add(nn.ReLU())
                 .add(nn.SpatialMaxPooling(2, 2, 2, 2, format="NHWC"))
                 .add(nn.Reshape((8 * 8 * 8,), batch_mode=True))
                 .add(nn.Linear(8 * 8 * 8, 4))
                 .add(nn.LogSoftMax()))
        model._ensure_init()
        path = str(tmp_path / "model.pb")
        saver.save(model, [None, 16, 16, 3], path)

        gd = tf.compat.v1.GraphDef()
        with open(path, "rb") as f:
            gd.ParseFromString(f.read())
        x = np.random.RandomState(4).normal(
            size=(2, 16, 16, 3)).astype(np.float32)
        theirs = _run_tf(gd, "input", x, "output")
        ours = np.asarray(model.evaluate().forward(x))
        np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-4)

    def test_import_of_our_export(self, tmp_path):
        """save -> load round-trip through the GraphDef format."""
        from bigdl_tpu.utils.tf import TensorflowLoader, saver
        model = (nn.Sequential()
                 .add(nn.Linear(6, 12)).add(nn.Tanh())
                 .add(nn.Linear(12, 3)).add(nn.SoftMax()))
        model._ensure_init()
        path = str(tmp_path / "mlp.pb")
        saver.save(model, [None, 6], path)
        back = TensorflowLoader.load(path, ["input"], ["output"])
        x = np.random.RandomState(5).normal(size=(4, 6)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(back.evaluate().forward(x)),
            np.asarray(model.evaluate().forward(x)), rtol=1e-5, atol=1e-6)


class TestTorchFileRegressions:
    def test_distinct_lists_not_aliased(self, tmp_path):
        p = str(tmp_path / "t.t7")
        torch_file.save(p, {"a": [1, 2], "b": [3, 4]})
        back = torch_file.load(p)
        assert back["a"] == [1, 2] and back["b"] == [3, 4]

    def test_nonfinite_numbers_load(self, tmp_path):
        p = str(tmp_path / "t.t7")
        torch_file.save(p, {"nan": float("nan"), "inf": float("inf")})
        back = torch_file.load(p)
        assert np.isnan(back["nan"]) and np.isinf(back["inf"])


class TestLoaderRegressions:
    def test_conv_fanout_not_contaminated_by_bias(self):
        """BiasAdd fusion must not alias the raw Conv2D output when it has
        other consumers."""
        from bigdl_tpu.utils.tf import TensorflowLoader
        g = tf.Graph()
        rng = np.random.RandomState(7)
        with g.as_default():
            x = tf.compat.v1.placeholder(tf.float32, [None, 8], name="input")
            w = tf.constant(rng.normal(size=(8, 8)).astype(np.float32))
            b = tf.constant(np.full((8,), 100.0, np.float32))
            mm = tf.matmul(x, w)
            biased = tf.nn.bias_add(mm, b)
            raw = tf.nn.relu(mm)
            tf.add(biased, raw, name="output")
        gd = g.as_graph_def()
        model = TensorflowLoader.load(gd, ["input"], ["output"])
        xv = rng.normal(size=(3, 8)).astype(np.float32)
        ours = np.asarray(model.evaluate().forward(xv))
        theirs = _run_tf(gd, "input", xv, "output")
        np.testing.assert_allclose(ours, theirs, rtol=1e-5, atol=1e-5)

    def test_dilated_conv_rejected(self):
        from bigdl_tpu.utils.tf import TensorflowLoader
        g = tf.Graph()
        with g.as_default():
            x = tf.compat.v1.placeholder(tf.float32, [None, 16, 16, 3],
                                         name="input")
            k = tf.constant(np.ones((3, 3, 3, 4), np.float32))
            tf.nn.conv2d(x, k, strides=[1, 1, 1, 1], padding="SAME",
                         dilations=[1, 2, 2, 1], name="output")
        with pytest.raises(ValueError, match="dilations"):
            TensorflowLoader.load(g.as_graph_def(), ["input"], ["output"])

    def test_frozen_graph_identity_weights_and_fused_bn(self):
        """Frozen-graph idioms: Const->Identity->op weight reads and
        inference-mode FusedBatchNorm."""
        from bigdl_tpu.utils.tf import TensorflowLoader
        g = tf.Graph()
        rng = np.random.RandomState(11)
        with g.as_default():
            x = tf.compat.v1.placeholder(tf.float32, [None, 8, 8, 3],
                                         name="input")
            k = tf.identity(tf.constant(
                rng.normal(size=(3, 3, 3, 4)).astype(np.float32) * 0.3))
            h = tf.nn.conv2d(x, k, strides=[1, 1, 1, 1], padding="SAME")
            scale = tf.constant(rng.uniform(0.5, 1.5, 4).astype(np.float32))
            offset = tf.constant(rng.normal(size=4).astype(np.float32))
            mean = tf.constant(rng.normal(size=4).astype(np.float32))
            var = tf.constant(rng.uniform(0.5, 2.0, 4).astype(np.float32))
            h, *_ = tf.compat.v1.nn.fused_batch_norm(
                h, scale, offset, mean, var, epsilon=1e-3, is_training=False)
            tf.nn.relu(h, name="output")
        gd = g.as_graph_def()
        model = TensorflowLoader.load(gd, ["input"], ["output"])
        xv = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
        ours = np.asarray(model.evaluate().forward(xv))
        theirs = _run_tf(gd, "input", xv, "output")
        np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-4)

    def test_concat_pad_mean_ops(self):
        """Inception-style idioms: Pad + branch ConcatV2 + global Mean."""
        from bigdl_tpu.utils.tf import TensorflowLoader
        g = tf.Graph()
        rng = np.random.RandomState(13)
        with g.as_default():
            x = tf.compat.v1.placeholder(tf.float32, [None, 6, 6, 2],
                                         name="input")
            p = tf.pad(x, [[0, 0], [1, 1], [1, 1], [0, 0]])
            k1 = tf.constant(rng.normal(size=(3, 3, 2, 3)).astype(np.float32))
            k2 = tf.constant(rng.normal(size=(1, 1, 2, 3)).astype(np.float32))
            b1 = tf.nn.conv2d(p, k1, strides=[1, 1, 1, 1], padding="VALID")
            b2 = tf.nn.conv2d(x, k2, strides=[1, 1, 1, 1], padding="SAME")
            h = tf.concat([tf.nn.relu(b1), tf.nn.relu(b2)], axis=3)
            tf.reduce_mean(h, axis=[1, 2], name="output")
        gd = g.as_graph_def()
        model = TensorflowLoader.load(gd, ["input"], ["output"])
        xv = rng.normal(size=(2, 6, 6, 2)).astype(np.float32)
        ours = np.asarray(model.forward(xv))
        theirs = _run_tf(gd, "input", xv, "output")
        assert ours.shape == theirs.shape == (2, 6)
        np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-4)


class TestTensorflowPatternParity:
    """The remaining reference TensorflowToBigDL patterns (VERDICT r2 row
    31): Split/Pack/Unpack/StridedSlice/Shape/Fill/Mul/Dropout import and
    BatchNorm/LRN/table-op export — each golden-checked against real TF."""

    def _golden(self, build, x, rtol=1e-5, atol=1e-5, outputs=("output",)):
        from bigdl_tpu.utils.tf import TensorflowLoader
        g = tf.Graph()
        with g.as_default():
            build(tf)
        gd = g.as_graph_def()
        model = TensorflowLoader.load(gd, ["input"], list(outputs))
        ours = model.evaluate().forward(x)
        if len(outputs) == 1:
            ours = [ours]
        for out_name, mine in zip(outputs, ours):
            theirs = _run_tf(gd, "input", x, out_name)
            np.testing.assert_allclose(np.asarray(mine), theirs,
                                       rtol=rtol, atol=atol)

    def test_split_mul_parity(self):
        def build(tf):
            x = tf.compat.v1.placeholder(tf.float32, [None, 6],
                                         name="input")
            a, b = tf.split(x, 2, axis=1)
            tf.multiply(a, b, name="output")
        x = np.random.RandomState(0).normal(size=(3, 6)).astype(np.float32)
        self._golden(build, x)

    def test_unpack_pack_parity(self):
        def build(tf):
            x = tf.compat.v1.placeholder(tf.float32, [2, 3, 4],
                                         name="input")
            parts = tf.unstack(x, axis=1)
            tf.stack(parts[::-1], axis=1, name="output")
        x = np.random.RandomState(1).normal(size=(2, 3, 4)).astype(np.float32)
        self._golden(build, x)

    def test_strided_slice_parity(self):
        def build(tf):
            x = tf.compat.v1.placeholder(tf.float32, [2, 6, 4],
                                         name="input")
            tf.identity(x[:, 1:5:2, ::2], name="output")
        x = np.random.RandomState(2).normal(size=(2, 6, 4)).astype(np.float32)
        self._golden(build, x)

    def test_strided_slice_shrink_axis_parity(self):
        def build(tf):
            x = tf.compat.v1.placeholder(tf.float32, [2, 6, 4],
                                         name="input")
            tf.identity(x[:, 2], name="output")
        x = np.random.RandomState(3).normal(size=(2, 6, 4)).astype(np.float32)
        self._golden(build, x)

    def test_shape_and_fill_parity(self):
        from bigdl_tpu.utils.tf import TensorflowLoader
        g = tf.Graph()
        with g.as_default():
            # dynamic batch keeps the Shape op live (static shapes fold)
            x = tf.compat.v1.placeholder(tf.float32, [None, 5],
                                         name="input")
            tf.identity(tf.shape(x), name="shape_out")
            f = tf.fill([2, 5], 3.5)   # static: folds to Const / Fill
            tf.add(x, f, name="output")
        gd = g.as_graph_def()
        x = np.random.RandomState(4).normal(size=(2, 5)).astype(np.float32)
        model = TensorflowLoader.load(gd, ["input"], ["shape_out"])
        np.testing.assert_array_equal(
            np.asarray(model.evaluate().forward(x)), [2, 5])
        model2 = TensorflowLoader.load(gd, ["input"], ["output"])
        got = np.asarray(model2.evaluate().forward(x))
        np.testing.assert_allclose(got, _run_tf(gd, "input", x, "output"),
                                   rtol=1e-6)

    def test_scalar_mul_const_parity(self):
        def build(tf):
            x = tf.compat.v1.placeholder(tf.float32, [None, 4],
                                         name="input")
            tf.multiply(x, tf.constant(2.5), name="output")
        x = np.random.RandomState(5).normal(size=(3, 4)).astype(np.float32)
        self._golden(build, x)

    def test_dropout_subgraph_imports_as_dropout(self):
        """The tf.nn.dropout(v1) mul/div/floor subgraph maps to nn.Dropout
        — identity at inference, the reference's DropoutTF pattern."""
        from bigdl_tpu.utils.tf import TensorflowLoader
        g = tf.Graph()
        with g.as_default():
            x = tf.compat.v1.placeholder(tf.float32, [2, 4], name="input")
            y = tf.compat.v1.nn.dropout(x, keep_prob=0.6)
            tf.identity(y, name="output")
        model = TensorflowLoader.load(g.as_graph_def(), ["input"],
                                      ["output"])
        drops = model.find_modules(nn.Dropout)
        assert drops and abs(drops[0].p - 0.4) < 1e-6
        x = np.random.RandomState(6).normal(size=(2, 4)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(model.evaluate().forward(x)),
                                   x, rtol=1e-6)

    def test_lrn_import_parity(self):
        def build(tf):
            x = tf.compat.v1.placeholder(tf.float32, [2, 6, 6, 8],
                                         name="input")
            tf.nn.lrn(x, depth_radius=2, bias=1.5, alpha=0.3, beta=0.6,
                      name="output")
        x = np.random.RandomState(7).normal(
            size=(2, 6, 6, 8)).astype(np.float32)
        self._golden(build, x, rtol=1e-4, atol=1e-4)

    def test_bn_export_roundtrip_and_tf_parity(self, tmp_path):
        from bigdl_tpu.utils.tf import TensorflowLoader, saver
        rng = np.random.RandomState(8)
        model = (nn.Sequential()
                 .add(nn.SpatialConvolution(3, 4, 3, 3, 1, 1, -1, -1,
                                            format="NHWC"))
                 .add(nn.SpatialBatchNormalization(4, format="NHWC"))
                 .add(nn.ReLU()))
        model._ensure_init()
        bn = model.children[1]
        bn.state["running_mean"] = rng.normal(size=(4,)).astype(np.float32)
        bn.state["running_var"] = rng.uniform(
            0.5, 2.0, size=(4,)).astype(np.float32)
        path = str(tmp_path / "bn.pb")
        saver.save(model, [None, 8, 8, 3], path)
        x = rng.normal(size=(2, 8, 8, 3)).astype(np.float32)
        ours = np.asarray(model.evaluate().forward(x))
        gd = tf.compat.v1.GraphDef()
        with open(path, "rb") as f:
            gd.ParseFromString(f.read())
        theirs = _run_tf(gd, "input", x, "output")
        np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-4)
        back = TensorflowLoader.load(gd, ["input"], ["output"])
        np.testing.assert_allclose(np.asarray(back.evaluate().forward(x)),
                                   ours, rtol=1e-4, atol=1e-4)

    def test_log_softmax_parity_and_import_train(self):
        """tf.nn.log_softmax imports (beyond the reference registry) and
        the imported classifier TRAINS through the public Optimizer —
        the import->fine-tune journey, not just a forward check."""
        def build(tf):
            x = tf.compat.v1.placeholder(tf.float32, [None, 6],
                                         name="input")
            w = tf.constant(np.random.RandomState(8)
                            .normal(size=(6, 3)).astype(np.float32))
            tf.nn.log_softmax(tf.matmul(x, w), name="output")
        x = np.random.RandomState(7).normal(size=(4, 6)).astype(np.float32)
        self._golden(build, x)

        import bigdl_tpu.optim as optim
        from bigdl_tpu.dataset import LocalDataSet, SampleToMiniBatch
        from bigdl_tpu.dataset.datasets import synthetic_separable
        from bigdl_tpu.utils.tf import TensorflowLoader
        g = tf.Graph()
        with g.as_default():
            # frozen-graph form (Const weights), like the reference's
            # loader expects; the imported Linear is trainable HERE
            xx = tf.compat.v1.placeholder(tf.float32, [None, 4],
                                          name="input")
            w = tf.constant(np.random.RandomState(4)
                            .normal(size=(4, 2)).astype(np.float32))
            b = tf.constant(np.zeros(2, np.float32))
            tf.nn.log_softmax(tf.matmul(xx, w) + b, name="output")
        model = TensorflowLoader.load(g.as_graph_def(), ["input"],
                                      ["output"])
        samples = synthetic_separable(64, 4, n_classes=2, seed=5)
        ds = LocalDataSet(samples).transform(SampleToMiniBatch(32))
        o = optim.Optimizer.create(model, ds, nn.ClassNLLCriterion())
        o.set_optim_method(optim.SGD(learning_rate=0.5))
        o.set_end_when(optim.max_epoch(4))
        o.optimize()
        acc = optim.Evaluator(model).test(
            samples, [optim.Top1Accuracy()], 32)[0][1].final_result()
        assert acc > 0.8, acc

    def test_lrn_explicit_zero_attr_parity(self):
        """depth_radius=0 is a legal (degenerate) LRN — each channel
        normalized by itself alone.  The importer must read the explicit 0,
        not truthiness-coerce it to the TF default of 5 (advisor r3)."""
        def build(tf):
            x = tf.compat.v1.placeholder(tf.float32, [None, 4, 4, 8],
                                         name="input")
            tf.nn.lrn(x, depth_radius=0, bias=1.0, alpha=1.0, beta=0.5,
                      name="output")
        x = np.random.RandomState(5).normal(
            size=(2, 4, 4, 8)).astype(np.float32)
        self._golden(build, x, rtol=1e-4, atol=1e-4)

    def test_lrn_export_roundtrip_and_tf_parity(self, tmp_path):
        from bigdl_tpu.utils.tf import TensorflowLoader, saver
        model = (nn.Sequential()
                 .add(nn.SpatialCrossMapLRN(5, 1.0, 0.75, 1.0)))
        model._ensure_init()
        path = str(tmp_path / "lrn.pb")
        saver.save(model, [None, 8, 6, 6], path)
        x = np.random.RandomState(9).normal(
            size=(2, 8, 6, 6)).astype(np.float32)
        ours = np.asarray(model.evaluate().forward(x))
        gd = tf.compat.v1.GraphDef()
        with open(path, "rb") as f:
            gd.ParseFromString(f.read())
        theirs = _run_tf(gd, "input", x, "output")
        np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-4)
        back = TensorflowLoader.load(gd, ["input"], ["output"])
        np.testing.assert_allclose(np.asarray(back.evaluate().forward(x)),
                                   ours, rtol=1e-4, atol=1e-4)

    def test_depthwise_conv_parity(self):
        """DepthwiseConv2dNative (+BiasAdd fusion) imports as grouped
        SpatialConvolution with TF's exact channel ordering."""
        def build(tf):
            rng = np.random.RandomState(10)
            x = tf.compat.v1.placeholder(tf.float32, [None, 8, 8, 6],
                                         name="input")
            k = tf.constant(rng.normal(size=(3, 3, 6, 2))
                            .astype(np.float32) * 0.3)
            b = tf.constant(rng.normal(size=(12,)).astype(np.float32) * .1)
            y = tf.nn.bias_add(tf.nn.depthwise_conv2d(
                x, k, strides=[1, 1, 1, 1], padding="SAME"), b)
            tf.nn.relu(y, name="output")
        x = np.random.RandomState(11).normal(
            size=(2, 8, 8, 6)).astype(np.float32)
        self._golden(build, x, rtol=1e-4, atol=1e-4)
