"""Optimizer trajectory parity against torch.optim.

The reference's optim methods are torch-optim ports tested against torch
(``optim/SGDSpec`` etc. via the TH harness); here each method runs the same
deterministic gradient sequence as its torch.optim twin and the parameter
trajectories must agree step for step.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import bigdl_tpu.optim as optim  # noqa: E402

N_STEPS = 12
DIM = 10


def _problem():
    """Deterministic quadratic: grad(p) = A p - b."""
    rng = np.random.RandomState(0)
    q = rng.normal(size=(DIM, DIM)).astype(np.float64)
    a = (q @ q.T / DIM + np.eye(DIM)).astype(np.float32)
    b = rng.normal(size=DIM).astype(np.float32)
    p0 = rng.normal(size=DIM).astype(np.float32)
    return a, b, p0


def _run_ours(method, a, b, p0, steps=N_STEPS):
    p = np.array(p0)
    traj = []
    for _ in range(steps):
        g = a @ p - b
        p = np.asarray(method.update(g.astype(np.float32), p))
        traj.append(p.copy())
    return np.stack(traj)


def _run_torch(opt_cls, kwargs, a, b, p0, steps=N_STEPS):
    p = torch.from_numpy(np.array(p0)).requires_grad_(True)
    opt = opt_cls([p], **kwargs)
    ta = torch.from_numpy(a)
    tb = torch.from_numpy(b)
    traj = []
    for _ in range(steps):
        opt.zero_grad()
        p.grad = ta @ p.detach() - tb
        opt.step()
        traj.append(p.detach().numpy().copy())
    return np.stack(traj)


@pytest.mark.parametrize("ours,tcls,tkw", [
    (lambda: optim.SGD(learning_rate=0.05),
     torch.optim.SGD, dict(lr=0.05)),
    (lambda: optim.SGD(learning_rate=0.05, momentum=0.9, dampening=0.0),
     torch.optim.SGD, dict(lr=0.05, momentum=0.9)),
    (lambda: optim.SGD(learning_rate=0.05, momentum=0.9, nesterov=True),
     torch.optim.SGD, dict(lr=0.05, momentum=0.9, nesterov=True)),
    (lambda: optim.SGD(learning_rate=0.05, momentum=0.9, dampening=0.0,
                       weight_decay=0.01),
     torch.optim.SGD, dict(lr=0.05, momentum=0.9, weight_decay=0.01)),
    (lambda: optim.Adam(learning_rate=0.1),
     torch.optim.Adam, dict(lr=0.1)),
    (lambda: optim.Adagrad(learning_rate=0.1),
     torch.optim.Adagrad, dict(lr=0.1, eps=1e-10)),
    (lambda: optim.Adadelta(decay_rate=0.9, epsilon=1e-6),
     torch.optim.Adadelta, dict(lr=1.0, rho=0.9, eps=1e-6)),
    (lambda: optim.RMSprop(learning_rate=0.01, decay_rate=0.99),
     torch.optim.RMSprop, dict(lr=0.01, alpha=0.99)),
    (lambda: optim.Adamax(learning_rate=0.02, epsilon=1e-8),
     torch.optim.Adamax, dict(lr=0.02, eps=1e-8)),
], ids=["sgd", "sgd-momentum", "sgd-nesterov", "sgd-wd", "adam", "adagrad",
        "adadelta", "rmsprop", "adamax"])
def test_trajectory_matches_torch(ours, tcls, tkw):
    a, b, p0 = _problem()
    got = _run_ours(ours(), a, b, p0)
    want = _run_torch(tcls, tkw, a, b, p0)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
