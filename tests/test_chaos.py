"""Chaos-injection harness proving the fault-tolerant checkpoint subsystem.

The claims under test (ISSUE 2 acceptance criteria): with the chaos FS
tearing the k-th checkpoint write, recovery restores the newest COMMITTED
snapshot (never a torn one) and resumed training reaches weight parity
with an uninterrupted run; the divergence guard skips non-finite steps
in-step and escalates to a snapshot restore after K consecutive bad
steps.

Parity tests use full-batch datasets (one iteration per epoch, shuffle
order irrelevant) so a killed-and-resumed trajectory is bit-comparable to
an uninterrupted one — the same protocol as
``test_failure_recovery.TestKillAndResume``.
"""

import os

import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset import LocalDataSet, SampleToMiniBatch
from bigdl_tpu.dataset.datasets import synthetic_separable
from bigdl_tpu.optim.evaluator import Evaluator
from bigdl_tpu.utils import chaos, config, file_io


def _mlp(seed=11):
    import jax
    m = (nn.Sequential().add(nn.Linear(4, 16)).add(nn.Tanh())
         .add(nn.Linear(16, 2)).add(nn.LogSoftMax()))
    m.reset(jax.random.PRNGKey(seed))
    return m


def _full_batch_ds(samples):
    return LocalDataSet(samples).transform(SampleToMiniBatch(len(samples)))


def _train(samples, epochs, ckpt_dir=None, seed=11, async_write=None,
           ckpt_trigger=None):
    model = _mlp(seed=seed)
    opt = optim.Optimizer.create(model, _full_batch_ds(samples),
                                 nn.ClassNLLCriterion())
    opt.set_optim_method(optim.SGD(learning_rate=0.3, momentum=0.9))
    opt.set_end_when(optim.max_epoch(epochs))
    if ckpt_dir is not None:
        opt.set_checkpoint(str(ckpt_dir),
                           ckpt_trigger or optim.every_epoch(),
                           async_write=async_write)
    opt.optimize()
    w, _ = model.get_parameters()
    return np.asarray(w), opt


@pytest.fixture(autouse=True)
def _chaos_env():
    """Zero retry sleeps, disarmed chaos before/after every test."""
    config.set_property("bigdl.failure.retryTimeInterval", 0.0)
    yield
    chaos.uninstall()
    for key in ("bigdl.failure.retryTimeInterval",
                "bigdl.failure.retryTimes",
                "bigdl.chaos.failWriteAt", "bigdl.chaos.truncateWriteAt",
                "bigdl.chaos.transientWrites", "bigdl.chaos.failStepAt",
                "bigdl.chaos.nanLossAt", "bigdl.divergence.maxBadSteps",
                "bigdl.divergence.guard", "bigdl.io.retryTimes"):
        config.clear_property(key)


class TestChaosKill:
    """Writer dies mid-snapshot → next restore takes the newest VALID
    snapshot and resumed training reaches weight parity."""

    def test_torn_snapshot_never_selected(self, tmp_path):
        """Kill the writer on snapshot 2's optimMethod write: model.2
        exists, the pair is incomplete — restore must land on snapshot 1,
        never the torn 2."""
        from bigdl_tpu.optim.optimizer import Checkpoint
        ckpt = Checkpoint(str(tmp_path), optim.every_epoch())
        m, sgd = _mlp(), optim.SGD(learning_rate=0.1)
        ckpt.save(m, sgd, 1)
        # counters start at install: snapshot 2's writes are model=1,
        # optimMethod=2, manifest=3, commit=4 — kill the optim write
        config.set_property("bigdl.chaos.failWriteAt", 2)
        chaos.install()
        with pytest.raises(chaos.ChaosError):
            ckpt.save(m, sgd, 2)
        chaos.uninstall()
        names = os.listdir(tmp_path)
        assert "model.2" in names and "optimMethod.2" not in names
        assert any(".tmp_bigdl" in n for n in names), \
            "the killed writer should leave its torn temp behind"
        model_path, _, n = ckpt.latest()
        assert n == 1 and model_path.endswith("model.1")

    @pytest.mark.parametrize("async_write", [False, True])
    def test_recovery_reaches_weight_parity(self, tmp_path, async_write):
        """The acceptance test: chaos tears the k-th checkpoint write
        mid-run; the retry loop restores the newest committed snapshot
        and the finished run's weights match an uninterrupted run's
        exactly.  Covers the sync writer (fault surfaces inside save)
        and the async writer (fault surfaces deferred, at the NEXT
        save)."""
        samples = synthetic_separable(128, 4, n_classes=2, seed=7)
        w_clean, _ = _train(samples, epochs=6)

        # epoch-1 snapshot = writes 1-4; write 6 dies inside the epoch-2
        # snapshot (sync: raises in save; async: raises at epoch-3's save)
        config.set_property("bigdl.chaos.failWriteAt", 6)
        chaos.install()
        w_chaos, opt = _train(samples, epochs=6,
                              ckpt_dir=tmp_path / "ckpt",
                              async_write=async_write)
        assert chaos.write_count() >= 6, "the injected fault never fired"
        np.testing.assert_allclose(w_chaos, w_clean, rtol=1e-5, atol=1e-7)
        # the store ends healthy: newest snapshot is committed and valid
        assert opt.checkpoint.latest() is not None

    def test_truncated_write_caught_by_checksum(self, tmp_path):
        """The nastier failure mode: the write 'succeeds' but the payload
        is silently truncated — rename commits a corrupt object that only
        the manifest CRC can catch."""
        from bigdl_tpu.optim.optimizer import Checkpoint
        ckpt = Checkpoint(str(tmp_path), optim.every_epoch())
        m, sgd = _mlp(), optim.SGD(learning_rate=0.1)
        ckpt.save(m, sgd, 1)
        config.set_property("bigdl.chaos.truncateWriteAt", 1)  # model.2
        chaos.install()
        ckpt.save(m, sgd, 2)       # no error: the corruption is silent
        chaos.uninstall()
        names = os.listdir(tmp_path)
        assert "commit.2" in names, "snapshot 2 should look committed"
        _, _, n = ckpt.latest()
        assert n == 1, "checksum verification must reject the torn payload"

    def test_transient_remote_blip_absorbed_by_retry(self):
        """Two transient write failures on a remote store: the bounded
        retry in file_io absorbs them and the checkpoint lands."""
        import fsspec
        fs = fsspec.filesystem("memory")
        if fs.exists("/chaos_tr"):
            fs.rm("/chaos_tr", recursive=True)
        from bigdl_tpu.optim.optimizer import Checkpoint
        config.set_property("bigdl.io.retryTimes", 3)
        config.set_property("bigdl.chaos.transientWrites", 2)
        chaos.install()
        slept = []
        orig = file_io._sleep
        file_io._sleep = slept.append
        try:
            ckpt = Checkpoint("memory://chaos_tr/ckpt", optim.every_epoch())
            ckpt.save(_mlp(), optim.SGD(learning_rate=0.1), 1)
        finally:
            file_io._sleep = orig
        assert ckpt.latest()[2] == 1
        assert len(slept) == 2 and slept[0] < slept[1], \
            "retry backoff should have spaced the two recovery attempts"


class TestStepInjection:
    def test_simulated_preemption_recovers(self, tmp_path):
        """``bigdl.chaos.failStepAt``: the driver loop dies at iteration 3
        (once); the retry loop restores the snapshot and training reaches
        parity with an uninterrupted run."""
        samples = synthetic_separable(128, 4, n_classes=2, seed=7)
        w_clean, _ = _train(samples, epochs=6)

        config.set_property("bigdl.chaos.failStepAt", 3)
        chaos.install()
        w_chaos, _ = _train(samples, epochs=6, ckpt_dir=tmp_path / "ckpt")
        assert chaos._state.steps_failed == 1, "preemption never fired"
        np.testing.assert_allclose(w_chaos, w_clean, rtol=1e-5, atol=1e-7)


@pytest.mark.slow
class TestChaosSoak:
    def test_soak_multiple_fault_classes_one_run(self, tmp_path):
        """Long soak: one training run survives a simulated preemption, a
        torn checkpoint write, AND a non-finite-loss burst — with
        keep_last retention active throughout — and still reaches parity
        with an uninterrupted run."""
        samples = synthetic_separable(128, 4, n_classes=2, seed=7)
        w_clean, _ = _train(samples, epochs=24)

        config.set_property("bigdl.chaos.failStepAt", 5)
        config.set_property("bigdl.chaos.failWriteAt", 30)
        config.set_property("bigdl.chaos.nanLossAt", "14:15")
        config.set_property("bigdl.divergence.maxBadSteps", 2)
        chaos.install()
        model = _mlp(seed=11)
        opt = optim.Optimizer.create(model, _full_batch_ds(samples),
                                     nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.3, momentum=0.9))
        opt.set_end_when(optim.max_epoch(24))
        opt.set_checkpoint(str(tmp_path / "ckpt"),
                           optim.several_iteration(1), keep_last=3)
        opt.optimize()
        w, _ = model.get_parameters()
        assert chaos._state.steps_failed == 1
        assert chaos._state.steps_seen > 24, "no retry/replay happened"
        # TestKillAndResume's established resume-parity tolerance
        np.testing.assert_allclose(np.asarray(w), w_clean,
                                   rtol=1e-4, atol=1e-6)
        # retention held: at most keep_last committed snapshots remain
        commits = [f for f in os.listdir(tmp_path / "ckpt")
                   if f.startswith("commit.")]
        assert len(commits) <= 3, commits
        assert opt.checkpoint.latest() is not None


class TestDivergenceGuard:
    def test_nonfinite_step_skipped_in_jit(self):
        """A NaN batch must leave params/slots/state at their pre-step
        values (the in-step select), while the loss still reports the
        divergence to the driver."""
        import jax
        samples = synthetic_separable(64, 4, n_classes=2, seed=3)
        model = _mlp()
        opt = optim.Optimizer.create(model, _full_batch_ds(samples),
                                     nn.ClassNLLCriterion())
        method = optim.SGD(learning_rate=0.5, momentum=0.9)
        opt.set_optim_method(method)
        model.training()
        model._ensure_init()
        step = opt._build_step()
        params, mstate = model.params, model.state
        slots = method.slots(params)
        before = jax.tree_util.tree_map(np.asarray, params)
        x = np.full((8, 4), np.nan, np.float32)
        y = np.ones((8,), np.float32)
        new_params, new_slots, new_mstate, loss, aux = step(
            params, slots, mstate, x, y, method.hyper(),
            jax.random.PRNGKey(0))
        assert int(aux["nf"]) != 0x7FFFFFFF  # guard named the bad leaf
        assert not np.isfinite(float(loss))
        for a, b in zip(jax.tree_util.tree_leaves(before),
                        jax.tree_util.tree_leaves(new_params)):
            np.testing.assert_array_equal(a, np.asarray(b))

    def test_guard_off_propagates_nan(self):
        """With ``bigdl.divergence.guard`` disabled the old behaviour is
        back: a NaN gradient poisons the params (the control that proves
        the guard is what saves them)."""
        import jax
        config.set_property("bigdl.divergence.guard", False)
        samples = synthetic_separable(64, 4, n_classes=2, seed=3)
        model = _mlp()
        opt = optim.Optimizer.create(model, _full_batch_ds(samples),
                                     nn.ClassNLLCriterion())
        method = optim.SGD(learning_rate=0.5)
        opt.set_optim_method(method)
        model.training()
        model._ensure_init()
        step = opt._build_step()
        params, mstate = model.params, model.state
        x = np.full((8, 4), np.nan, np.float32)
        y = np.ones((8,), np.float32)
        new_params, _, _, loss, _aux = step(
            params, method.slots(params), mstate, x, y, method.hyper(),
            jax.random.PRNGKey(0))
        leaves = [np.asarray(l)
                  for l in jax.tree_util.tree_leaves(new_params)]
        assert any(not np.isfinite(l).all() for l in leaves)

    def test_consecutive_bad_steps_restore_snapshot(self, tmp_path):
        """K consecutive non-finite losses escalate to a restore of the
        latest valid snapshot, after which training resumes cleanly and
        reaches parity with an uninterrupted run (the injected NaNs are
        host-side only, so the replayed trajectory is identical)."""
        samples = synthetic_separable(128, 4, n_classes=2, seed=7)
        w_clean, _ = _train(samples, epochs=8)

        config.set_property("bigdl.chaos.nanLossAt", "4:5")
        config.set_property("bigdl.divergence.maxBadSteps", 2)
        chaos.install()
        w_chaos, opt = _train(samples, epochs=8,
                              ckpt_dir=tmp_path / "ckpt",
                              ckpt_trigger=optim.several_iteration(1))
        # the restore-and-replay ran extra iterations past the clean 8
        assert chaos._state.steps_seen > 8, \
            "divergence restore never happened"
        np.testing.assert_allclose(w_chaos, w_clean, rtol=1e-5, atol=1e-7)

    def test_persistent_divergence_exhausts_retry_budget(self, tmp_path):
        """A pipeline that produces NaN forever must exhaust
        bigdl.failure.retryTimes and surface the DivergenceError — even
        though guard-skipped iterations keep advancing the counters
        (which would otherwise reset the budget as fake 'progress') the
        loop must not restore-and-replay unbounded."""
        from bigdl_tpu.optim.optimizer import DivergenceError
        config.set_property("bigdl.chaos.nanLossAt", "1:999999")
        config.set_property("bigdl.divergence.maxBadSteps", 2)
        config.set_property("bigdl.failure.retryTimes", 3)
        chaos.install()
        samples = synthetic_separable(128, 4, n_classes=2, seed=7)
        model = _mlp()
        opt = optim.Optimizer.create(model, _full_batch_ds(samples),
                                     nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.3))
        opt.set_end_when(optim.max_epoch(200))
        opt.set_checkpoint(str(tmp_path / "ckpt"),
                           optim.several_iteration(1))
        with pytest.raises(DivergenceError):
            opt.optimize()
        # 3 attempts x (maxBadSteps + a snapshot's worth of slack): far
        # below the 200-epoch horizon an unbounded loop would chew into
        assert chaos._state.steps_seen < 30, chaos._state.steps_seen

    def test_divergence_without_checkpoint_gives_up(self):
        """No snapshot to restore and params still alive: the retry loop
        re-runs until the attempt budget is spent, then surfaces the
        DivergenceError rather than looping forever."""
        from bigdl_tpu.optim.optimizer import DivergenceError
        config.set_property("bigdl.chaos.nanLossAt", "1:999")
        config.set_property("bigdl.divergence.maxBadSteps", 2)
        config.set_property("bigdl.failure.retryTimes", 2)
        chaos.install()
        samples = synthetic_separable(64, 4, n_classes=2, seed=3)
        model = _mlp()
        opt = optim.Optimizer.create(model, _full_batch_ds(samples),
                                     nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.3))
        opt.set_end_when(optim.max_epoch(20))
        with pytest.raises(DivergenceError):
            opt.optimize()
