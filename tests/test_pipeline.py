"""Pipeline-parallelism tests on the virtual 8-device mesh.

Beyond-reference capability: the GPipe scan/ppermute schedule must equal
sequentially applying the S stages, forward and backward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.engine import Engine
from bigdl_tpu.parallel.pipeline import (pipeline_apply,
                                         pipeline_shard_params,
                                         stack_stage_params,
                                         unstack_stage_params)

N_STAGES = 4
D = 8


def _block(seed):
    m = (nn.Sequential()
         .add(nn.Linear(D, D))
         .add(nn.Tanh()))
    m.reset(jax.random.PRNGKey(seed))
    return m


def _stages():
    blocks = [_block(s) for s in range(N_STAGES)]
    return blocks[0], stack_stage_params([b.params for b in blocks]), blocks


class TestPipeline:
    def test_forward_matches_sequential(self):
        mesh = Engine.create_mesh((N_STAGES,), ("stage",),
                                  devices=jax.devices()[:N_STAGES])
        block, stacked, blocks = _stages()
        x = jnp.asarray(np.random.RandomState(0)
                        .normal(size=(8, D)).astype(np.float32))

        want = x
        for b in blocks:
            want = jnp.asarray(b.forward(want))

        stacked = pipeline_shard_params(stacked, mesh)
        got = pipeline_apply(block, stacked, x, n_micro=4, mesh=mesh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_gradients_match_sequential(self):
        mesh = Engine.create_mesh((N_STAGES,), ("stage",),
                                  devices=jax.devices()[:N_STAGES])
        block, stacked, blocks = _stages()
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.normal(size=(8, D)).astype(np.float32))
        y = jnp.asarray(rng.normal(size=(8, D)).astype(np.float32))

        def seq_loss(per_stage):
            h = x
            for i, b in enumerate(blocks):
                h, _ = b.apply(per_stage[i], h, b.state, training=False)
            return jnp.mean((h - y) ** 2)

        want_g = jax.grad(seq_loss)([b.params for b in blocks])

        sharded = pipeline_shard_params(stacked, mesh)

        def pipe_loss(sp):
            out = pipeline_apply(block, sp, x, n_micro=4, mesh=mesh)
            return jnp.mean((out - y) ** 2)

        got_g = jax.jit(jax.grad(pipe_loss))(sharded)
        got_list = unstack_stage_params(got_g, N_STAGES)
        for g_got, g_want in zip(got_list, want_g):
            for a, b in zip(jax.tree_util.tree_leaves(g_got),
                            jax.tree_util.tree_leaves(g_want)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-4, atol=1e-6)

    def test_params_physically_stage_sharded(self):
        mesh = Engine.create_mesh((N_STAGES,), ("stage",),
                                  devices=jax.devices()[:N_STAGES])
        _, stacked, _ = _stages()
        sharded = pipeline_shard_params(stacked, mesh)
        leaf = jax.tree_util.tree_leaves(sharded)[0]   # (S, D, D) weight
        shapes = {s.data.shape[0] for s in leaf.addressable_shards}
        assert shapes == {1}, "each device must hold exactly one stage"

    def test_training_loop_converges(self):
        mesh = Engine.create_mesh((N_STAGES,), ("stage",),
                                  devices=jax.devices()[:N_STAGES])
        block, stacked, _ = _stages()
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.normal(size=(16, D)).astype(np.float32))
        w_true = rng.normal(size=(D, D)).astype(np.float32) * 0.4
        y = jnp.tanh(x @ jnp.asarray(w_true))
        params = pipeline_shard_params(stacked, mesh)

        @jax.jit
        def step(p):
            def loss_fn(pp):
                out = pipeline_apply(block, pp, x, n_micro=4, mesh=mesh)
                return jnp.mean((out - y) ** 2)
            loss, g = jax.value_and_grad(loss_fn)(p)
            return jax.tree_util.tree_map(lambda w, gw: w - 0.5 * gw,
                                          p, g), loss

        losses = []
        for _ in range(30):
            params, loss = step(params)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, losses

    def test_stateful_block_rejected(self):
        mesh = Engine.create_mesh((N_STAGES,), ("stage",),
                                  devices=jax.devices()[:N_STAGES])
        bn_block = nn.Sequential().add(nn.BatchNormalization(D))
        bn_block._ensure_init()
        with pytest.raises(ValueError, match="stateless"):
            pipeline_apply(bn_block, bn_block.params,
                           jnp.zeros((8, D)), 4, mesh)

    def test_microbatch_divisibility_guard(self):
        mesh = Engine.create_mesh((N_STAGES,), ("stage",),
                                  devices=jax.devices()[:N_STAGES])
        block, stacked, _ = _stages()
        with pytest.raises(ValueError, match="microbatch"):
            pipeline_apply(block, stacked, jnp.zeros((7, D)), 4, mesh)
        with pytest.raises(ValueError, match="microbatch"):
            pipeline_apply(block, stacked, jnp.zeros((8, D)), 0, mesh)
    def test_stage_count_mismatch_rejected(self):
        mesh = Engine.create_mesh((2,), ("stage",),
                                  devices=jax.devices()[:2])
        block, stacked, _ = _stages()          # 4 stages vs 2-device mesh
        with pytest.raises(ValueError, match="stages"):
            pipeline_apply(block, stacked, jnp.zeros((8, D)), 4, mesh)

    def test_pp_x_dp_forward_and_grad_parity(self):
        """2-D ("data","stage") mesh: data-parallel pipeline replicas must
        reproduce single-replica results, forward AND gradient (the data
        psum comes from the replicated-in transpose)."""
        mesh = Engine.create_mesh((2, N_STAGES), ("data", "stage"))
        block, stacked, blocks = _stages()
        rng = np.random.RandomState(4)
        x = jnp.asarray(rng.normal(size=(16, D)).astype(np.float32))
        y = jnp.asarray(rng.normal(size=(16, D)).astype(np.float32))

        def seq_loss(per_stage):
            h = x
            for i, b in enumerate(blocks):
                h, _ = b.apply(per_stage[i], h, b.state, training=False)
            return jnp.mean((h - y) ** 2)

        want_l = float(seq_loss([b.params for b in blocks]))
        want_g = jax.grad(seq_loss)([b.params for b in blocks])

        sharded = jax.device_put(
            stacked, jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec("stage")))

        def pipe_loss(sp):
            out = pipeline_apply(block, sp, x, n_micro=4, mesh=mesh,
                                 data_axis="data")
            return jnp.mean((out - y) ** 2)

        got_l = float(jax.jit(pipe_loss)(sharded))
        np.testing.assert_allclose(got_l, want_l, rtol=1e-5)
        got_g = unstack_stage_params(jax.jit(jax.grad(pipe_loss))(sharded),
                                     N_STAGES)
        for g_got, g_want in zip(got_g, want_g):
            for a, b in zip(jax.tree_util.tree_leaves(g_got),
                            jax.tree_util.tree_leaves(g_want)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-4, atol=1e-6)

    def test_pp_x_tp_forward_and_grad_parity(self):
        """('stage','model') mesh: each stage's Megatron-tagged weights
        split over 'model' INSIDE the ppermute schedule (explicit
        copy_to_tp/psum) — forward and gradients must match the unsplit
        sequential stack."""
        from bigdl_tpu.parallel.pipeline import (stage_tp_specs,
                                                 wire_model_parallel)
        from bigdl_tpu.parallel.tensor_parallel import (column_parallel,
                                                        row_parallel)
        mesh = Engine.create_mesh((2, 4), ("stage", "model"),
                                  devices=jax.devices()[:8])

        def tp_block(seed):
            up, down = nn.Linear(D, 2 * D), nn.Linear(2 * D, D)
            column_parallel(up)
            row_parallel(down)
            m = nn.Sequential().add(up).add(nn.ReLU()).add(down)
            m.reset(jax.random.PRNGKey(seed))
            return m

        blocks = [tp_block(s) for s in range(2)]
        stacked = stack_stage_params([b.params for b in blocks])
        rng = np.random.RandomState(7)
        x = jnp.asarray(rng.normal(size=(8, D)).astype(np.float32))
        y = jnp.asarray(rng.normal(size=(8, D)).astype(np.float32))

        def seq_loss(per_stage):
            h = x
            for i, b in enumerate(blocks):
                h, _ = b.apply(per_stage[i], h, b.state, training=False)
            return jnp.mean((h - y) ** 2)

        want_l = float(seq_loss([b.params for b in blocks]))
        want_g = jax.grad(seq_loss)([b.params for b in blocks])

        for b in blocks:
            wire_model_parallel(b, "model", mesh)
        specs = stage_tp_specs(blocks[0])
        sharded = pipeline_shard_params(stacked, mesh, specs=specs)

        def pipe_loss(sp):
            out = pipeline_apply(blocks[0], sp, x, n_micro=4, mesh=mesh,
                                 param_specs=specs)
            return jnp.mean((out - y) ** 2)

        try:
            got_l = float(jax.jit(pipe_loss)(sharded))
            np.testing.assert_allclose(got_l, want_l, rtol=1e-5)
            got_g = unstack_stage_params(
                jax.jit(jax.grad(pipe_loss))(sharded), 2)
            for g_got, g_want in zip(got_g, want_g):
                for a, b in zip(jax.tree_util.tree_leaves(g_got),
                                jax.tree_util.tree_leaves(g_want)):
                    np.testing.assert_allclose(np.asarray(a),
                                               np.asarray(b),
                                               rtol=1e-4, atol=1e-6)
        finally:
            for b in blocks:
                wire_model_parallel(b, None)

    def test_dp_pp_tp_training_matches_single_device(self):
        """THE 3-D composition: dp2 x pp2 x tp2 on the 8-device mesh
        through the public PipelineOptimizer API — transformer blocks
        (Megatron-split MHA heads + MLP pair) trained with momentum SGD
        (ZeRO-1 slots over 'data') must reproduce single-device
        training of the identical sequential stack."""
        import copy

        import bigdl_tpu.optim as optim
        from bigdl_tpu.dataset import LocalDataSet, Sample, SampleToMiniBatch
        from bigdl_tpu.models.transformer import transformer_block
        from bigdl_tpu.parallel import PipelineOptimizer

        T = 4
        mesh = Engine.create_mesh((2, 2, 2), ("data", "stage", "model"))
        blocks = [transformer_block(D, 2, tp=True) for _ in range(2)]
        for s, b in enumerate(blocks):
            b.reset(jax.random.PRNGKey(20 + s))
        init_params = [jax.tree_util.tree_map(np.array, b.params)
                       for b in blocks]

        rng = np.random.RandomState(9)
        samples = [Sample(rng.normal(size=(T, D)).astype(np.float32),
                          rng.normal(size=(T, D)).astype(np.float32))
                   for _ in range(8)]
        # full-batch: epoch shuffles cannot reorder what one batch holds
        ds = LocalDataSet(list(samples)).transform(SampleToMiniBatch(8))
        opt = PipelineOptimizer(blocks, ds, nn.MSECriterion(), mesh=mesh,
                                n_micro=2)
        opt.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
        opt.set_end_when(optim.max_iteration(4))
        trained = opt.optimize()
        w_pipe, _ = trained.get_parameters()

        # single-device oracle: identical stack, same init, same batches
        oracle_blocks = [transformer_block(D, 2) for _ in range(2)]
        model = nn.Sequential()
        for b, p in zip(oracle_blocks, init_params):
            b._ensure_init()
            b.params = jax.tree_util.tree_map(jnp.asarray, copy.deepcopy(p))
            model.add(b)
        ds2 = LocalDataSet(list(samples)).transform(SampleToMiniBatch(8))
        opt2 = optim.Optimizer.create(model, ds2, nn.MSECriterion())
        opt2.set_optim_method(optim.SGD(learning_rate=0.2, momentum=0.9))
        opt2.set_end_when(optim.max_iteration(4))
        w_single, _ = opt2.optimize().get_parameters()
        np.testing.assert_allclose(np.asarray(w_pipe),
                                   np.asarray(w_single),
                                   rtol=2e-4, atol=2e-5)

    def test_pp_x_dp_batch_guard(self):
        mesh = Engine.create_mesh((2, N_STAGES), ("data", "stage"))
        block, stacked, _ = _stages()
        with pytest.raises(ValueError, match="divide"):
            pipeline_apply(block, stacked, jnp.zeros((7, D)), 1, mesh,
                           data_axis="data")

    @pytest.mark.slow
    def test_moe_block_composes_with_pipeline(self):
        """aux_loss is a per-forward diagnostic, not threaded state — it
        must not trip the statelessness guard.  MoE capacity-drop is a
        function of which tokens compete per forward, so the pipeline's
        guarantee is parity with the sequential PER-MICROBATCH forwards
        (each microbatch routes with its own capacity budget), not with
        the monolithic full-batch forward — see pipeline.py / moe.py."""
        from bigdl_tpu.models.transformer import transformer_block
        mesh = Engine.create_mesh((2,), ("stage",),
                                  devices=jax.devices()[:2])
        blocks = []
        for s in range(2):
            b = transformer_block(8, 2, moe_experts=2)
            b.reset(jax.random.PRNGKey(s))
            blocks.append(b)
        stacked = pipeline_shard_params(
            stack_stage_params([b.params for b in blocks]), mesh)
        x = jnp.asarray(np.random.RandomState(5)
                        .normal(size=(4, 6, 8)).astype(np.float32))
        n_micro = 2
        out = pipeline_apply(blocks[0], stacked, x, n_micro=n_micro,
                             mesh=mesh)
        assert out.shape == x.shape
        chunks = []
        for mb in np.split(np.asarray(x), n_micro, axis=0):
            h = mb
            for b in blocks:
                h = np.asarray(b.forward(h))
            chunks.append(h)
        want = np.concatenate(chunks, axis=0)
        np.testing.assert_allclose(np.asarray(out), want,
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.slow
    def test_moe_dropfree_pipeline_matches_full_batch(self):
        """With capacity_factor >= E/top_k no token can ever drop, routing
        is batch-split-invariant, and the pipeline DOES equal the
        monolithic full-batch forward exactly."""
        from bigdl_tpu.models.transformer import transformer_block
        mesh = Engine.create_mesh((2,), ("stage",),
                                  devices=jax.devices()[:2])
        blocks = []
        for s in range(2):
            b = transformer_block(8, 2, moe_experts=2,
                                  moe_capacity_factor=2.0)
            b.reset(jax.random.PRNGKey(s))
            blocks.append(b)
        stacked = pipeline_shard_params(
            stack_stage_params([b.params for b in blocks]), mesh)
        x = jnp.asarray(np.random.RandomState(6)
                        .normal(size=(4, 6, 8)).astype(np.float32))
        out = pipeline_apply(blocks[0], stacked, x, n_micro=2, mesh=mesh)
        want = x
        for b in blocks:
            want = jnp.asarray(b.forward(np.asarray(want)))
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)
