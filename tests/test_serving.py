"""Overload-tolerant serving: the request-path robustness contract.

The claims under test (ISSUE 9 acceptance criteria): admission control
rejects fast with a structured ``Overloaded`` instead of letting tail
latency collapse; expired requests are shed at dequeue time before
wasting a device slot; a poison request is quarantined alone while its
batch survives; a hung dispatch is aborted by the watchdog with
diagnosis and the engine cools down; SIGTERM drains gracefully and
rejects late arrivals retriably — and through ALL of it, every submitted
request terminates with exactly one outcome (the accounting identity),
non-poison results are bit-identical to a clean ``Predictor.predict``,
and the strict retrace sentinel stays at zero across ragged arrival
patterns.
"""

import time

import numpy as np
import pytest

import jax

import bigdl_tpu.nn as nn
from bigdl_tpu import telemetry
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.optim.predictor import Predictor
from bigdl_tpu.serving import (HungDispatchError, Overloaded, ServingDataError,
                               ServingEngine, run_open_loop)
from bigdl_tpu.serving.engine import DeadlineExceeded, OUTCOMES, \
    ServingInfraError
from bigdl_tpu.utils import chaos, config, elastic

DIN, DOUT = 4, 3

_SERVING_KEYS = (
    "bigdl.compile.buckets", "bigdl.serving.warmupBatches",
    "bigdl.chaos.slowRequestAt", "bigdl.chaos.poisonRequestAt",
    "bigdl.chaos.hangDispatchAt", "bigdl.chaos.burstArrivals",
)


@pytest.fixture(autouse=True)
def _serving_env():
    """Disarmed chaos, cleared preemption, clean knobs around every
    test."""
    elastic.clear_preemption()
    yield
    chaos.uninstall()
    elastic.clear_preemption()
    for k in _SERVING_KEYS:
        config.clear_property(k)


def _model(seed=7):
    m = (nn.Sequential().add(nn.Linear(DIN, 16)).add(nn.Tanh())
         .add(nn.Linear(16, DOUT)))
    m.reset(jax.random.PRNGKey(seed))
    return m


def _engine(model=None, buckets="2,4,8", warm=True, **kw):
    if buckets:
        config.set_property("bigdl.compile.buckets", buckets)
    model = model if model is not None else _model()
    eng = ServingEngine(model, **kw)
    if warm:
        eng.warmup(np.zeros((DIN,), np.float32))
    return eng


def _rows(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, DIN)).astype(np.float32)


def _assert_identity(stats_or_rec):
    assert stats_or_rec["unaccounted"] == 0, stats_or_rec
    total = sum(stats_or_rec[o] for o in OUTCOMES)
    assert total == stats_or_rec["submitted"], stats_or_rec


# ---------------------------------------------------------------------------
# Predictor / evaluator empty-dataset satellites
# ---------------------------------------------------------------------------

class TestEmptyDataset:
    def test_predict_empty_dataset_returns_empty_ndarray(self):
        out = Predictor(_model()).predict([])
        assert isinstance(out, np.ndarray)
        assert out.shape == (0,)

    def test_predict_empty_sample_stream(self):
        from bigdl_tpu.dataset.dataset import LocalDataSet
        out = Predictor(_model()).predict(LocalDataSet([]))
        assert isinstance(out, np.ndarray) and out.size == 0

    def test_predict_class_empty_dataset(self):
        out = Predictor(_model()).predict_class([])
        assert isinstance(out, np.ndarray)
        assert out.shape == (0,)
        assert np.issubdtype(out.dtype, np.integer)

    def test_evaluate_dataset_empty_raises_clear_error(self):
        import bigdl_tpu.optim as optim
        from bigdl_tpu.optim.evaluator import evaluate_dataset
        with pytest.raises(ValueError, match="empty dataset"):
            evaluate_dataset(_model(), [], [optim.Top1Accuracy()])


# ---------------------------------------------------------------------------
# The happy path: micro-batching with Predictor parity
# ---------------------------------------------------------------------------

class TestServingBasics:
    def test_results_bit_identical_to_predictor(self):
        x = _rows(11)
        with _engine(deadline_ms=10000.0) as eng:
            handles = [eng.submit(x[i]) for i in range(len(x))]
            got = np.stack([h.result(timeout=30) for h in handles])
            ref = Predictor(eng.model).predict([MiniBatch(x)])
            np.testing.assert_array_equal(got, ref)
            stats = eng.stats()
        _assert_identity(stats)
        assert stats["completed"] == len(x)

    def test_ragged_arrivals_zero_retraces(self):
        """Dribbled arrivals make ragged batch occupancies; every one
        pads to the bucket plan, so the STRICT sentinel (armed for all
        tier-1 tests) sees zero post-warmup retraces."""
        x = _rows(9, seed=3)
        with _engine(deadline_ms=10000.0, max_batch=4) as eng:
            handles = []
            for i in range(len(x)):
                handles.append(eng.submit(x[i]))
                if i % 3 == 0:
                    time.sleep(0.03)     # let occupancy vary
            for h in handles:
                h.result(timeout=30)
            assert eng.sentinel is not None
            assert eng.sentinel.retraces == 0
            assert eng.batches >= 2
            _assert_identity(eng.stats())

    def test_metrics_exported_through_registry(self):
        x = _rows(6)
        with _engine(deadline_ms=10000.0) as eng:
            for h in [eng.submit(r) for r in x]:
                h.result(timeout=30)
        snap = telemetry.REGISTRY.snapshot()
        assert snap["counters"]["Serving/completed"] >= 6
        assert "Serving/p99_ms" in snap["gauges"]
        assert "Serving/latency_ms" in snap["histograms"]
        assert snap["histograms"]["Serving/batch_occupancy"]["count"] >= 1
        prom = telemetry.REGISTRY.prometheus_text()
        assert "Serving_latency_ms" in prom
        assert "Serving_queue_depth" in prom


# ---------------------------------------------------------------------------
# Admission control: reject at the door
# ---------------------------------------------------------------------------

class TestAdmission:
    def test_queue_full_rejects_fast_and_structured(self):
        eng = _engine(warm=False, start=False, max_queue_depth=4,
                      deadline_ms=10000.0)
        try:
            for i in range(4):
                eng.submit(_rows(1)[0])
            t0 = time.monotonic()
            with pytest.raises(Overloaded) as ei:
                eng.submit(_rows(1)[0])
            reject_ms = (time.monotonic() - t0) * 1e3
            assert reject_ms < 50, "reject must be fast, at the door"
            e = ei.value
            assert e.retriable
            assert e.reason == "queue full"
            assert e.queue_depth == 4 and e.max_depth == 4
        finally:
            eng.stop()
        stats = eng.stats()
        _assert_identity(stats)
        assert stats["rejected"] == 1
        assert stats["shed"] == 4        # never-started engine sheds on stop

    def test_projected_wait_rejection(self):
        """With a warmed service-time EMA, admission rejects a request
        whose projected queue wait already blows its deadline budget —
        reject-at-the-door instead of queueing it to die."""
        eng = _engine(warm=False, start=False, max_batch=2,
                      max_queue_depth=64, deadline_ms=100.0)
        try:
            eng._ema.ema = 500.0         # 500 ms per batch, observed
            with pytest.raises(Overloaded) as ei:
                eng.submit(_rows(1)[0])
            assert ei.value.reason == "projected wait"
            assert ei.value.projected_wait_ms >= 500.0
            assert ei.value.retriable
            # a generous per-request deadline CAN still be admitted
            h = eng.submit(_rows(1)[0], deadline_ms=60000.0)
            assert h.index == 0
        finally:
            eng.stop()
        _assert_identity(eng.stats())

    def test_stopped_engine_rejects_closed(self):
        eng = _engine(warm=False, start=False)
        eng.stop()
        with pytest.raises(Overloaded) as ei:
            eng.submit(_rows(1)[0])
        assert ei.value.reason == "closed"
        _assert_identity(eng.stats())


# ---------------------------------------------------------------------------
# Deadline shedding at dequeue
# ---------------------------------------------------------------------------

class TestDeadlineShedding:
    def test_slow_request_sheds_expired_behind_it(self):
        """chaos.slowRequestAt wedges the first handled request for
        0.5 s; everything queued behind it ages past its 120 ms deadline
        and must be shed at DEQUEUE time — cheap, structured, before any
        device work."""
        config.set_property("bigdl.chaos.slowRequestAt", "1:0.5")
        chaos.install()
        x = _rows(4)
        with _engine(deadline_ms=120.0, max_batch=4) as eng:
            handles = [eng.submit(r) for r in x]
            out = []
            for h in handles:
                try:
                    out.append(("ok", h.result(timeout=30)))
                except DeadlineExceeded as e:
                    assert e.retriable
                    assert e.waited_ms > e.deadline_ms
                    out.append(("shed", None))
            stats = eng.stats()
        _assert_identity(stats)
        kinds = [k for k, _ in out]
        assert kinds[0] == "ok", "the slow request itself still completes"
        assert kinds.count("shed") == 3, kinds
        assert stats["shed"] == 3 and stats["completed"] == 1


# ---------------------------------------------------------------------------
# Poison quarantine: the PR 7 taxonomy on the request path
# ---------------------------------------------------------------------------

class TestPoisonQuarantine:
    def test_chaos_poison_fails_one_keeps_batch_alive(self):
        config.set_property("bigdl.chaos.poisonRequestAt", "1")
        chaos.install()
        x = _rows(4, seed=5)
        with _engine(deadline_ms=10000.0, max_batch=4) as eng:
            handles = [eng.submit(r) for r in x]
            ref = Predictor(eng.model).predict([MiniBatch(x)])
            for i, h in enumerate(handles):
                if h.index == 1:
                    with pytest.raises(ServingDataError):
                        h.result(timeout=30)
                    assert h.outcome == "quarantined"
                else:
                    np.testing.assert_array_equal(h.result(timeout=30),
                                                  ref[i])
            stats = eng.stats()
        _assert_identity(stats)
        assert stats["quarantined"] == 1
        assert stats["completed"] == 3

    def test_ill_shaped_payload_quarantined_without_chaos(self):
        x = _rows(3, seed=6)
        with _engine(deadline_ms=10000.0, max_batch=4) as eng:
            good = [eng.submit(r) for r in x]
            bad = eng.submit(np.zeros((DIN + 2,), np.float32))
            with pytest.raises(ServingDataError, match="ill-shaped"):
                bad.result(timeout=30)
            for h in good:
                assert h.result(timeout=30).shape == (DOUT,)
            stats = eng.stats()
        _assert_identity(stats)
        assert stats["quarantined"] == 1 and stats["completed"] == 3

    def test_non_numeric_payload_quarantined(self):
        with _engine(deadline_ms=10000.0) as eng:
            h = eng.submit(np.array(["not", "numbers", "at", "all"]))
            with pytest.raises(ServingDataError):
                h.result(timeout=30)
            assert h.outcome == "quarantined"
        _assert_identity(eng.stats())


# ---------------------------------------------------------------------------
# Hung-dispatch watchdog
# ---------------------------------------------------------------------------

class TestHungDispatch:
    def test_watchdog_aborts_wedged_dispatch_with_diagnosis(self):
        fired_before = telemetry.counter("Serving/watchdog_fired").value
        config.set_property("bigdl.chaos.hangDispatchAt", "5:3.0")
        # the watchdog's first heartbeat covers setup (skipped), the
        # next 2 are warmup observations, and the EMA seeds from their
        # minimum at the one after: 4 dispatches arm detection
        config.set_property("bigdl.serving.warmupBatches", 2)
        chaos.install()
        with _engine(deadline_ms=30000.0, max_batch=2, stall_factor=5.0,
                     cooldown_batches=2) as eng:
            # dispatches 1-4 seed the EMA from the warmup MINIMUM (the
            # PR 5 seeding — a slow first dispatch cannot poison it)
            for _ in range(4):
                eng.submit(_rows(1)[0]).result(timeout=30)
            t0 = time.monotonic()
            victim = eng.submit(_rows(1)[0])
            with pytest.raises(HungDispatchError, match="wedged past"):
                victim.result(timeout=30)
            abort_s = time.monotonic() - t0
            assert victim.outcome == "shed"
            assert abort_s < 3.0, \
                "the abort must land well before the 3 s wedge expires"
            # the engine re-admits (cooldown clears when the backlog is
            # empty) and keeps serving
            deadline = time.monotonic() + 10
            while True:
                try:
                    h = eng.submit(_rows(1)[0])
                    break
                except Overloaded as e:
                    assert e.reason in ("cooldown",), e
                    assert time.monotonic() < deadline
                    time.sleep(0.02)
            assert h.result(timeout=30).shape == (DOUT,)
            stats = eng.stats()
        _assert_identity(stats)
        assert stats["shed"] == 1 and stats["completed"] == 5
        assert telemetry.counter("Serving/watchdog_fired").value == \
            fired_before + 1
        assert telemetry.REGISTRY.snapshot()["gauges"][
            "Serving/watchdog_detect_ms"] >= 0

    def test_cooldown_gates_admission_until_backlog_clears(self):
        with _engine(deadline_ms=10000.0) as eng:
            with eng._lock:
                eng._cooldown = 5
            with pytest.raises(Overloaded) as ei:
                eng.submit(_rows(1)[0])
            assert ei.value.reason == "cooldown" and ei.value.retriable
            # empty backlog: the batcher's next idle poll re-admits
            deadline = time.monotonic() + 5
            while True:
                try:
                    h = eng.submit(_rows(1)[0])
                    break
                except Overloaded:
                    assert time.monotonic() < deadline
                    time.sleep(0.02)
            assert h.result(timeout=30).shape == (DOUT,)
        _assert_identity(eng.stats())


# ---------------------------------------------------------------------------
# Graceful drain (SIGTERM / stop)
# ---------------------------------------------------------------------------

class TestGracefulDrain:
    def test_preemption_drains_inflight_and_rejects_late_arrivals(self):
        x = _rows(6, seed=8)
        with _engine(deadline_ms=30000.0, max_batch=2) as eng:
            handles = [eng.submit(r) for r in x]
            elastic.request_preemption(reason="test SIGTERM")
            # admission must close within one batcher poll
            deadline = time.monotonic() + 5
            rejected = None
            while rejected is None:
                try:
                    eng.submit(x[0])
                    assert time.monotonic() < deadline
                    time.sleep(0.005)
                except Overloaded as e:
                    rejected = e
            assert rejected.reason in ("draining", "closed")
            assert rejected.retriable, \
                "late arrivals carry the retriable marker"
            # everything admitted before the signal still completes
            for h in handles:
                assert h.result(timeout=30).shape == (DOUT,)
            stats = eng.stats()
        assert stats["completed"] >= len(x)
        _assert_identity(eng.stats())

    def test_stop_sheds_undrainable_backlog_retriably(self):
        """A backlog that can never dispatch (the batcher was never
        started) is shed with a retriable infra error when the engine
        goes down — never silently dropped."""
        eng = _engine(warm=False, start=False, deadline_ms=30000.0)
        handles = [eng.submit(r) for r in _rows(3)]
        eng.stop()
        for h in handles:
            with pytest.raises(ServingInfraError, match="draining"):
                h.result(timeout=1)
            assert h.outcome == "shed"
        stats = eng.stats()
        _assert_identity(stats)
        assert stats["shed"] == 3


# ---------------------------------------------------------------------------
# Open-loop load generation + burstArrivals
# ---------------------------------------------------------------------------

class TestLoadGenerator:
    def test_burst_arrivals_injector_accounted(self):
        config.set_property("bigdl.chaos.burstArrivals", "2:5")
        chaos.install()
        x = _rows(6, seed=9)
        with _engine(deadline_ms=30000.0, max_batch=8,
                     max_queue_depth=64) as eng:
            rec = run_open_loop(eng, list(x), rate_hz=0.0, seed=1)
        assert rec["submitted"] == 6 + 5     # the herd rode on position 2
        _assert_identity(rec)
        assert rec["completed"] == 11
        # burst copies carry the same payload: their results match the
        # scheduled arrival's
        for j in range(5):
            np.testing.assert_array_equal(rec["results"][f"2+b{j}"],
                                          rec["results"]["2"])

    def test_open_loop_poisson_under_capacity_all_complete(self):
        x = _rows(20, seed=10)
        with _engine(deadline_ms=30000.0, max_batch=8) as eng:
            rec = run_open_loop(eng, list(x), rate_hz=300.0, seed=2)
        _assert_identity(rec)
        assert rec["completed"] == 20
        assert len(rec["latency_ms"]) == 20


# ---------------------------------------------------------------------------
# The combined chaos proof (ISSUE 9 acceptance criterion)
# ---------------------------------------------------------------------------

class TestCombinedChaosPlan:
    def test_poison_plus_hang_plus_sigterm_exact_accounting(self):
        """One plan, three fault classes, mid-load: a poison request, a
        hung dispatch, and a SIGTERM.  Every submitted request ends in
        exactly one of the four outcomes, non-poison completions are
        bit-identical to a clean Predictor.predict over the same inputs,
        and the strict retrace sentinel stays at zero across the ragged
        batches the faults leave behind."""
        config.set_property("bigdl.chaos.poisonRequestAt", "6")
        config.set_property("bigdl.chaos.hangDispatchAt", "5:1.0")
        config.set_property("bigdl.serving.warmupBatches", 2)
        chaos.install()
        x = _rows(24, seed=11)
        ref = None

        def on_arrival(i):
            if i == 16:
                elastic.request_preemption(reason="combined-plan SIGTERM")
            elif i == 17:
                # give the batcher one beat to observe the signal, so
                # the tail of the load really arrives AFTER admission
                # closed (the late-arrival contract under test)
                time.sleep(0.4)

        with _engine(deadline_ms=30000.0, max_batch=4, stall_factor=5.0,
                     cooldown_batches=2, grace_period=20.0) as eng:
            # dispatches 1-3 seed the watchdog EMA (2 warmup
            # observations past the skipped setup heartbeat); admission
            # indices 0-2 are theirs, so poison position 6 lands
            # mid-load and the hang (dispatch 5) lands post-seed
            for _ in range(3):
                eng.submit(x[0]).result(timeout=30)
            ref = Predictor(eng.model).predict([MiniBatch(x)])
            rec = run_open_loop(eng, list(x), rate_hz=400.0, seed=3,
                                on_arrival=on_arrival)
            sentinel = eng.sentinel
            stats = eng.stats()

        # -- exact accounting: nothing vanished, nothing double-counted
        _assert_identity(rec)
        _assert_identity(stats)
        assert all(h is None or h.outcome in OUTCOMES
                   for _, h in rec["handles"])

        # -- the poison request was quarantined alone
        assert rec["quarantined"] == 1
        poisoned = [e for e in rec["errors"].values()
                    if isinstance(e, ServingDataError)]
        assert len(poisoned) == 1

        # -- the hung dispatch was aborted with diagnosis; its victims
        #    were shed retriably
        hung = [e for e in rec["errors"].values()
                if isinstance(e, HungDispatchError)]
        assert len(hung) >= 1, "the wedged batch must fail diagnosed"
        assert all(e.retriable for e in hung)

        # -- SIGTERM closed admission: late arrivals rejected retriably
        assert rec["rejected"] >= 1
        rejections = [e for e in rec["errors"].values()
                      if isinstance(e, Overloaded)]
        assert rejections and all(e.retriable for e in rejections)

        # -- non-poison completions: bit-identical to the clean batch
        #    Predictor over the same inputs
        assert rec["completed"] >= 5
        for key, out in rec["results"].items():
            idx = int(key.split("+")[0])
            np.testing.assert_array_equal(out, ref[idx])

        # -- zero post-warmup retraces across all the ragged batches
        assert sentinel is not None and sentinel.retraces == 0


# ---------------------------------------------------------------------------
# Bench leg (fast leg inline; soak is slow-marked)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_serving_bench_soak():
    """The ``bench.py --serving-only`` soak variant: calibrated Poisson
    leg long enough to exercise steady-state percentiles, plus the
    overload burst — all asserts live in bench_serving itself."""
    import os
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import bench
    rec = bench.bench_serving(soak=True, write=False)
    assert rec["calibrated"]["p99_ms"] <= rec["deadline_ms"]
    assert rec["overload"]["rejected"] > 0


# ---------------------------------------------------------------------------
# Restart/reuse contract + supervisor abandon (ISSUE 17 satellites)
# ---------------------------------------------------------------------------

class TestStopContract:
    def test_stop_is_idempotent_and_terminal(self):
        eng = _engine()
        h = eng.submit(_rows(1)[0])
        h.result(timeout=5.0)
        eng.stop()
        assert eng.terminal and not eng.batcher_alive()
        eng.stop()          # second stop: a quiet no-op, never a raise
        eng.stop(grace=0.0)
        assert eng.terminal
        _assert_identity(eng.stats())

    def test_post_stop_submit_is_structured_retriable(self):
        eng = _engine()
        eng.stop()
        for _ in range(3):  # stable across repeats, not half-torn state
            with pytest.raises(Overloaded) as ei:
                eng.submit(_rows(1)[0])
            assert ei.value.reason == "closed"
            assert ei.value.retriable
        _assert_identity(eng.stats())

    def test_restart_after_stop_raises_structured(self):
        eng = _engine()
        eng.stop()
        with pytest.raises(ServingInfraError, match="terminal"):
            eng.start()
        # the refusal did not corrupt the terminal state
        assert eng.terminal
        _assert_identity(eng.stats())

    def test_lifecycle_introspection(self):
        eng = _engine()
        assert not eng.terminal and not eng.draining
        assert eng.batcher_alive() and not eng.crashed()
        assert isinstance(eng.batcher_ident(), int)
        assert eng.queue_depth() == 0
        eng.stop()
        assert eng.terminal and not eng.batcher_alive()
        assert not eng.crashed()    # orderly stop is not a crash

    def test_abandon_sheds_once_and_releases_governor_bytes(self):
        from bigdl_tpu.resources import GOVERNOR
        eng = _engine(start=False)          # batcher never runs: the
        h = eng.submit(_rows(1)[0])         # handle stays in flight
        acct = GOVERNOR.account("serving_admission")
        charged = h.payload_nbytes
        assert charged > 0
        before = acct.nbytes
        assert h.abandon(reason="replica_crash") is True
        assert h.outcome == "shed"
        assert h.payload_nbytes == 0
        assert acct.nbytes == before - charged
        with pytest.raises(ServingInfraError, match="abandoned"):
            h.result(timeout=0)
        # terminal states are first-wins: a second abandon is a no-op
        assert h.abandon() is False
        assert acct.nbytes == before - charged
        # abandon moves the outcome to the SUPERVISOR'S ledger (the
        # fleet counts it as shed; tests/test_fleet.py asserts that
        # identity) — the engine's own counts see the handle as
        # stranded, and the later engine-side shed is a first-wins
        # no-op, never a double count
        assert eng.stats()["unaccounted"] == 1
        eng.stop()
        assert eng.stats()["unaccounted"] == 1
        assert eng.stats()["shed"] == 0

    def test_abandon_loses_to_completion(self):
        eng = _engine()
        h = eng.submit(_rows(1)[0])
        out = h.result(timeout=5.0)
        assert h.abandon() is False         # already completed: no-op
        assert h.outcome == "completed"
        np.testing.assert_array_equal(out, h.result(timeout=0))
        eng.stop()
        _assert_identity(eng.stats())
