"""Native data-pipeline tests: SequenceFile cross-implementation round-trip,
MT batch assembly vs numpy reference, and the prefetch transformer."""

import numpy as np
import pytest

from bigdl_tpu.dataset import seqfile
from bigdl_tpu.dataset.mt_batch import Prefetch, assemble_batch
from bigdl_tpu.dataset.native import native_available


class TestSeqFile:
    def _entries(self):
        rng = np.random.RandomState(0)
        return [(f"img_{i}.jpg", float(i % 10 + 1),
                 rng.bytes(rng.randint(10, 2000))) for i in range(32)]

    def test_python_roundtrip(self, tmp_path):
        p = str(tmp_path / "a.seq")
        recs = [(b"k%d" % i, b"v" * i) for i in range(64)]
        seqfile.py_write_records(p, iter(recs))
        back = list(seqfile.py_read_records(p))
        assert back == recs

    def test_native_reads_python_file(self, tmp_path):
        if not native_available():
            pytest.skip("native library unavailable")
        p = str(tmp_path / "b.seq")
        recs = [(b"key%d" % i, bytes([i % 256]) * (i * 7 % 300))
                for i in range(128)]
        seqfile.py_write_records(p, iter(recs))
        back = list(seqfile.read_records(p))   # native path
        assert back == recs

    def test_python_reads_native_file(self, tmp_path):
        if not native_available():
            pytest.skip("native library unavailable")
        p = str(tmp_path / "c.seq")
        recs = [(b"k%d" % i, b"x" * (i * 13 % 500)) for i in range(100)]
        seqfile.write_records(p, iter(recs))   # native writer
        back = list(seqfile.py_read_records(p))
        assert back == recs

    @staticmethod
    def _first_record_offset(path):
        """Parse the header with the module's own helpers: the first
        record's rec_len field starts right after the 16-byte sync."""
        with open(path, "rb") as f:
            f.read(4)                      # SEQ + version
            seqfile._read_text(f)          # key class
            seqfile._read_text(f)          # value class
            f.read(2)                      # compressed, block
            f.read(4)                      # metadata count (0)
            f.read(16)                     # sync
            return f.tell()

    @pytest.fixture(params=["native", "python"])
    def reader(self, request):
        if request.param == "native":
            if not native_available():
                pytest.skip("native library unavailable")
            return seqfile.read_records
        return seqfile.py_read_records

    @pytest.mark.parametrize("cut", ["value", "key_len", "rec_len", "sync"])
    def test_truncated_file_raises_not_crashes(self, tmp_path, reader, cut):
        p = str(tmp_path / "trunc.seq")
        import os
        if cut == "sync":
            # first record big enough (>2000 payload bytes) that the writer
            # emits a sync escape before the second; cut INSIDE the 16-byte
            # marker — truncation, which must NOT read as clean EOF (the
            # native reader used to return 0 here while python raised)
            seqfile.py_write_records(
                p, iter([(b"k", b"v" * 2500), (b"k2", b"w")]))
            rec1 = 4 + 4 + 1 + 2500        # rec_len, key_len, key, value
            off = self._first_record_offset(p) + rec1
            with open(p, "r+b") as f:
                f.truncate(off + 4 + 8)    # -1 escape + half the marker
        else:
            seqfile.py_write_records(p, iter([(b"k", b"v" * 500)]))
            with open(p, "r+b") as f:
                if cut == "value":         # cut inside the value payload
                    f.truncate(os.path.getsize(p) - 100)
                elif cut == "key_len":     # cut inside the key_len field
                    f.truncate(self._first_record_offset(p) + 5)
                else:                      # cut inside rec_len itself
                    f.truncate(self._first_record_offset(p) + 2)
        with pytest.raises(IOError, match="corrupt"):
            list(reader(p))

    def test_clean_eof_at_record_boundary(self, tmp_path, reader):
        """Zero dangling bytes at a boundary is a clean EOF, not corrupt
        — the strictness above must not reject well-formed files."""
        p = str(tmp_path / "clean.seq")
        recs = [(b"a", b"x" * 37), (b"b", b"y" * 53)]
        seqfile.py_write_records(p, iter(recs))
        assert list(reader(p)) == recs

    def test_corrupt_giant_record_length_raises_cheaply(self, tmp_path,
                                                        reader):
        """A flipped length byte (0x7FFFFFFF) must surface as 'corrupt',
        not a ~2 GB allocation, a silent short record (python fallback),
        or a bad_alloc terminating across the C ABI — both readers
        sanity-cap rec_len before reading."""
        p = str(tmp_path / "giant.seq")
        seqfile.py_write_records(p, iter([(b"k", b"v" * 100)]))
        off = self._first_record_offset(p)
        with open(p, "r+b") as f:
            f.seek(off)
            f.write(b"\x7f\xff\xff\xff")
        with pytest.raises(IOError, match="corrupt"):
            list(reader(p))

    def test_record_cap_is_configurable(self, tmp_path):
        """The rec_len sanity cap is a knob (module level or per call), so
        legitimately huge records aren't misreported as corrupt — and a
        non-default cap is actually honoured by read_records (it routes
        around the native reader's compiled-in 1 GiB)."""
        p = str(tmp_path / "cap.seq")
        recs = [(b"k", b"v" * 5000)]
        seqfile.py_write_records(p, iter(recs))
        assert list(seqfile.read_records(p)) == recs
        # a LOWERED cap flags the same record as corrupt (both entrypoints)
        with pytest.raises(IOError, match="corrupt"):
            list(seqfile.py_read_records(p, max_record_bytes=100))
        with pytest.raises(IOError, match="corrupt"):
            list(seqfile.read_records(p, max_record_bytes=100))
        # module-level override is picked up as the default
        old = seqfile.MAX_RECORD_BYTES
        try:
            seqfile.MAX_RECORD_BYTES = 100
            with pytest.raises(IOError, match="corrupt"):
                list(seqfile.read_records(p))
        finally:
            seqfile.MAX_RECORD_BYTES = old
        # a RAISED cap still reads fine (python fallback path)
        assert list(seqfile.read_records(
            p, max_record_bytes=2 << 30)) == recs

    def test_image_seqfile_protocol(self, tmp_path):
        p = str(tmp_path / "imgs.seq")
        entries = self._entries()
        seqfile.write_image_seqfile(p, entries)
        back = list(seqfile.read_image_seqfile(p))
        assert len(back) == len(entries)
        for (n0, l0, d0), (n1, l1, d1) in zip(entries, back):
            assert n0 == n1 and l0 == l1 and d0 == d1


class TestAssembleBatch:
    def _ref(self, images, crop, offsets, flips, mean, std):
        ch, cw = crop
        out = []
        for i, im in enumerate(images):
            oy, ox = offsets[i]
            patch = im[oy:oy + ch, ox:ox + cw].astype(np.float32)
            if flips[i]:
                patch = patch[:, ::-1]
            out.append(((patch - np.asarray(mean, np.float32)) /
                        np.asarray(std, np.float32)).transpose(2, 0, 1))
        return np.stack(out)

    def test_matches_numpy_reference(self):
        rng = np.random.RandomState(1)
        images = [rng.randint(0, 256, size=(40 + i % 3, 44 + i % 5, 3))
                  .astype(np.uint8) for i in range(16)]
        offsets = np.stack([rng.randint(0, 8, size=16),
                            rng.randint(0, 8, size=16)], axis=1)
        flips = rng.randint(0, 2, size=16).astype(np.uint8)
        mean, std = (104.0, 117.0, 123.0), (57.0, 58.0, 59.0)
        got = assemble_batch(images, (32, 32), offsets, flips, mean, std,
                             n_threads=4)
        ref = self._ref(images, (32, 32), offsets, flips, mean, std)
        np.testing.assert_allclose(got, ref, rtol=1e-6)

    def test_u8_variant_matches_float_path(self):
        """assemble_batch_u8 (raw crop/flip/pack, native threads) must
        equal the float path at mean 0 / std 1, cast back to uint8."""
        from bigdl_tpu.dataset.mt_batch import assemble_batch_u8
        rng = np.random.RandomState(3)
        images = [rng.randint(0, 256, size=(40 + i % 3, 44 + i % 5, 3))
                  .astype(np.uint8) for i in range(16)]
        offsets = np.stack([rng.randint(0, 8, size=16),
                            rng.randint(0, 8, size=16)], axis=1)
        flips = rng.randint(0, 2, size=16).astype(np.uint8)
        got = assemble_batch_u8(images, (32, 32), offsets, flips,
                                n_threads=4)
        ref = assemble_batch(images, (32, 32), offsets, flips,
                             (0.0, 0.0, 0.0), (1.0, 1.0, 1.0), n_threads=1)
        assert got.dtype == np.uint8
        np.testing.assert_array_equal(got, ref.astype(np.uint8))

    def test_grey_single_channel(self):
        rng = np.random.RandomState(2)
        images = [rng.randint(0, 256, size=(28, 28)).astype(np.uint8)
                  for _ in range(4)]
        offsets = np.zeros((4, 2), np.int32)
        flips = np.zeros(4, np.uint8)
        out = assemble_batch(images, (28, 28), offsets, flips, (33.0,),
                             (77.0,), n_threads=2)
        assert out.shape == (4, 1, 28, 28)


class TestMTLabeledBGRImgToBatch:
    """The MT ingest stage must reproduce the single-threaded reference
    chain (BytesToBGRImg → CenterCrop → BGRImgNormalizer → BGRImgToSample
    → SampleToMiniBatch) exactly when crop is deterministic and flips off
    — multi-threading is an implementation detail, not a semantics
    change."""

    def _jpeg_records(self, n=12, hw=(40, 48)):
        import io
        from PIL import Image
        from bigdl_tpu.dataset.image import LabeledImageBytes
        rng = np.random.RandomState(3)
        recs = []
        for i in range(n):
            img = rng.randint(0, 256, size=hw + (3,)).astype(np.uint8)
            buf = io.BytesIO()
            Image.fromarray(img).save(buf, "PNG")   # lossless: exact parity
            recs.append(LabeledImageBytes(f"r{i}", float(i % 5 + 1),
                                          buf.getvalue()))
        return recs

    def test_matches_single_threaded_chain(self):
        from bigdl_tpu.dataset.image import (BGRImgNormalizer, BGRImgToSample,
                                             BytesToBGRImg, CenterCrop)
        from bigdl_tpu.dataset.mt_batch import MTLabeledBGRImgToBatch
        from bigdl_tpu.dataset.transformer import SampleToMiniBatch

        recs = self._jpeg_records()
        mean, std = (104.0, 117.0, 123.0), (57.0, 58.0, 59.0)

        mt = MTLabeledBGRImgToBatch(4, crop=(32, 32), mean=mean, std=std,
                                    random_crop=False, hflip=False,
                                    n_threads=3)
        got = list(mt(iter(recs)))

        chain = BytesToBGRImg()(iter(recs))
        chain = CenterCrop(32, 32)(chain)
        chain = BGRImgNormalizer(mean, std)(chain)
        chain = BGRImgToSample()(chain)
        want = list(SampleToMiniBatch(4)(chain))

        assert len(got) == len(want) == 3
        for g, w in zip(got, want):
            np.testing.assert_allclose(g.get_input(), w.get_input(),
                                       rtol=1e-5, atol=1e-5)
            np.testing.assert_array_equal(g.get_target(), w.get_target())

    def test_device_normalize_matches_host_normalize(self):
        """uint8 ingest + nn.ChannelNormalize on device == host-side
        normalized float batches (the TPU-first byte-reduced layout is a
        layout change, not a numerics change)."""
        import bigdl_tpu.nn as nn
        from bigdl_tpu.dataset.mt_batch import MTLabeledBGRImgToBatch

        recs = self._jpeg_records()
        mean, std = (104.0, 117.0, 123.0), (57.0, 58.0, 59.0)
        host = list(MTLabeledBGRImgToBatch(
            4, crop=(32, 32), mean=mean, std=std, random_crop=False,
            hflip=False)(iter(recs)))
        raw = list(MTLabeledBGRImgToBatch(
            4, crop=(32, 32), mean=mean, std=std, random_crop=False,
            hflip=False, device_normalize=True)(iter(recs)))
        norm = nn.ChannelNormalize(mean, std)
        for h, r in zip(host, raw):
            assert r.get_input().dtype == np.uint8
            out = np.asarray(norm.forward(r.get_input()))
            np.testing.assert_allclose(out, h.get_input(),
                                       rtol=1e-5, atol=1e-4)
            np.testing.assert_array_equal(h.get_target(), r.get_target())

    def test_prefetch_chain_continues_caller_rng_stream(self):
        """Random crops/flips drawn inside a Prefetch-wrapped chain must
        continue the CALLER's seeded RandomGenerator stream (the producer
        thread adopts it) — wrapping in Prefetch is a latency detail, not
        a seeding change."""
        from bigdl_tpu.dataset.mt_batch import (MTLabeledBGRImgToBatch,
                                                Prefetch)
        from bigdl_tpu.utils.random_generator import RandomGenerator

        recs = self._jpeg_records(n=8)
        RandomGenerator.RNG().set_seed(777)
        direct = [b.get_input() for b in
                  MTLabeledBGRImgToBatch(4, crop=(32, 32))(iter(recs))]
        RandomGenerator.RNG().set_seed(777)
        chained = [b.get_input() for b in Prefetch(2)(
            MTLabeledBGRImgToBatch(4, crop=(32, 32))(iter(recs)))]
        assert len(direct) == len(chained) == 2
        for a, b in zip(direct, chained):
            np.testing.assert_array_equal(a, b)

    def test_undersized_image_raises_named_error(self):
        """An image smaller than the crop must fail loudly naming the
        record BEFORE offsets reach the native assembler (which does no
        bounds checks — a negative offset would read out of bounds)."""
        import pytest
        from bigdl_tpu.dataset.mt_batch import MTLabeledBGRImgToBatch

        recs = self._jpeg_records(n=4, hw=(40, 48))
        recs[2:3] = self._jpeg_records(n=1, hw=(20, 48))   # too short
        recs[2].label = 9.0
        for random_crop in (False, True):
            mt = MTLabeledBGRImgToBatch(4, crop=(32, 32),
                                        random_crop=random_crop,
                                        n_threads=2)
            with pytest.raises(ValueError, match=r"record 2 .*20x48.*32x32"):
                list(mt(iter(recs)))

    def test_batches_and_shapes(self):
        from bigdl_tpu.dataset.mt_batch import MTLabeledBGRImgToBatch
        recs = self._jpeg_records(n=10)
        mt = MTLabeledBGRImgToBatch(4, crop=(32, 32), random_crop=True,
                                    hflip=True, n_threads=2)
        batches = list(mt(iter(recs)))
        # trailing partial batch included, like SampleToMiniBatch
        assert [b.size() for b in batches] == [4, 4, 2]
        assert batches[0].get_input().shape == (4, 3, 32, 32)

    def test_teardown_cancels_queued_decode_futures(self):
        """A decode error propagating out of pool.map must CANCEL the
        batch's queued decode futures at teardown, not leave them running
        after the generator is gone (the old ``shutdown(wait=False)``
        leak)."""
        import time

        from bigdl_tpu.dataset.mt_batch import MTLabeledBGRImgToBatch

        decoded = []

        class Boom(MTLabeledBGRImgToBatch):
            @staticmethod
            def _decode(data):
                if data == b"BOOM":
                    raise RuntimeError("decode boom")
                time.sleep(0.05)
                decoded.append(1)
                return np.zeros((40, 40, 3), np.uint8)

        recs = self._jpeg_records(n=8)
        from bigdl_tpu.dataset.image import LabeledImageBytes
        recs[0] = LabeledImageBytes("bad", 1.0, b"BOOM")
        mt = Boom(8, crop=(32, 32), n_threads=1)
        with pytest.raises(RuntimeError, match="decode boom"):
            list(mt(iter(recs)))
        # the single worker raised on record 0; with cancel_futures the 7
        # queued slow decodes never run (at most one was already picked up
        # before the cancellation landed)
        time.sleep(0.5)
        assert len(decoded) <= 1, f"{len(decoded)} queued decodes ran"


class TestPrefetch:
    def test_order_preserved(self):
        pf = Prefetch(depth=2)
        assert list(pf(iter(range(100)))) == list(range(100))

    def test_upstream_error_propagates(self):
        def gen():
            yield 1
            raise RuntimeError("upstream boom")

        pf = Prefetch()
        it = pf(gen())
        assert next(it) == 1
        with pytest.raises(RuntimeError, match="upstream boom"):
            next(it)

    def test_consumer_abandonment_releases_producer(self):
        import threading
        started = threading.active_count()
        pf = Prefetch(depth=2)
        it = pf(iter(range(10000)))
        next(it)
        it.close()   # abandon
        import time
        for _ in range(50):
            if threading.active_count() <= started:
                break
            time.sleep(0.05)
        assert threading.active_count() <= started, "producer thread leaked"

    def test_teardown_joins_producer_and_leaves_queue_empty(self):
        """The teardown race: the producer can be blocked in put() when
        the consumer drains — that put lands AFTER the drain and would pin
        a full batch in memory.  Teardown must join the producer (bounded)
        and drain again, leaving the queue verifiably empty."""
        import time

        def slow_big_batches():
            i = 0
            while True:
                yield np.full((256, 256), i, np.float32)   # a "batch"
                i += 1

        for _ in range(5):            # the race is timing-dependent: retry
            pf = Prefetch(depth=1)
            it = pf(slow_big_batches())
            next(it)
            time.sleep(0.05)          # let the producer block in put()
            it.close()
            assert not pf._producer.is_alive(), "producer not joined"
            assert pf._q.empty(), "an item stayed pinned in the queue"


@pytest.mark.skipif(__import__("shutil").which("g++") is None,
                    reason="no C++ toolchain")
def test_native_library_builds():
    assert native_available(), "native toolchain present but lib missing"


@pytest.mark.skipif(__import__("shutil").which("g++") is None,
                    reason="no C++ toolchain")
def test_native_checked_build_has_all_symbols():
    """The CI-facing STRICT build: `make -C native` must succeed (compiler
    errors surface, not pass) and the library must export every dispatch
    symbol — in particular ``assemble_batch_u8``, whose absence (a stale
    pre-r4 .so) would silently fall back to numpy and mis-measure the
    whole ingest path by an order of magnitude."""
    from bigdl_tpu.dataset.native import REQUIRED_SYMBOLS, check_build
    lib = check_build()
    for sym in REQUIRED_SYMBOLS:
        assert hasattr(lib, sym), sym


class TestSeqFileFolder:
    def test_dataset_from_seqfiles(self, tmp_path):
        """End-to-end: write JPEG seq-files, read back as a DataSet."""
        import io
        from PIL import Image
        from bigdl_tpu.dataset.dataset import DataSet

        rng = np.random.RandomState(3)
        entries = []
        for i in range(6):
            arr = rng.randint(0, 256, size=(16, 16, 3)).astype(np.uint8)
            buf = io.BytesIO()
            Image.fromarray(arr).save(buf, format="JPEG")
            entries.append((f"img{i}", float(i % 3 + 1), buf.getvalue()))
        seqfile.write_image_seqfile(str(tmp_path / "part-0.seq"), entries[:3])
        seqfile.write_image_seqfile(str(tmp_path / "part-1.seq"), entries[3:])

        ds = DataSet.seq_file_folder(str(tmp_path))
        assert ds.size() == 6
        imgs = list(ds.data(train=False))
        assert imgs[0].data.shape == (16, 16, 3)
        assert {im.label for im in imgs} == {1.0, 2.0, 3.0}

    def test_lazy_seqfile_training_pipeline(self, tmp_path):
        """seq-file byte records -> lazy decode/scale/crop/normalize/batch
        feeding the optimizer (the inception driver's real-data path)."""
        import io
        from PIL import Image
        import bigdl_tpu.nn as nn
        import bigdl_tpu.optim as optim
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.dataset.image import (BGRImgNormalizer, BGRImgToSample,
                                             CenterCrop, Scale)
        from bigdl_tpu.dataset.transformer import SampleToMiniBatch

        rng = np.random.RandomState(5)
        entries = []
        for i in range(16):
            lab = i % 2
            arr = rng.randint(0, 80, size=(20, 24, 3)).astype(np.uint8)
            if lab:
                arr[:, :12] += 120
            buf = io.BytesIO()
            Image.fromarray(arr).save(buf, format="JPEG")
            entries.append((f"i{i}", float(lab + 1), buf.getvalue()))
        seqfile.write_image_seqfile(str(tmp_path / "p.seq"), entries)

        ds = (DataSet.seq_file_folder(str(tmp_path))
              .transform(Scale(18)).transform(CenterCrop(16, 16))
              .transform(BGRImgNormalizer((90.0,) * 3, (60.0,) * 3))
              .transform(BGRImgToSample())
              .transform(SampleToMiniBatch(8)))
        m = (nn.Sequential().add(nn.Reshape((3 * 16 * 16,)))
             .add(nn.Linear(3 * 16 * 16, 2)).add(nn.LogSoftMax()))
        opt = optim.Optimizer.create(m, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(optim.SGD(learning_rate=0.2))
        opt.set_end_when(optim.max_epoch(6))
        trained = opt.optimize()
        w, _ = trained.get_parameters()
        assert np.all(np.isfinite(np.asarray(w)))
