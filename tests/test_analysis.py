"""Unit tests for the analysis/sanitizer subsystem (ISSUE 4 tentpole):
host-sync guard, module contract checker, AST lint rules, and the
device-scalar Metrics hot path."""

import os
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.analysis import (ContractError, HostSyncError, check_model,
                                host_pull)
from bigdl_tpu.analysis.hostsync import (STATS, HostSyncGuard, NULL_GUARD,
                                         allow_host_sync)
from bigdl_tpu.analysis.lint import (Finding, lint_paths, load_allowlist,
                                     main as lint_main)


# ---------------------------------------------------------------------------
# host-sync guard
# ---------------------------------------------------------------------------

class TestHostSyncGuard:
    def test_implicit_float_raises_with_call_site(self):
        guard = HostSyncGuard("strict")
        x = jnp.ones(()) * 3
        with guard.armed():
            with pytest.raises(HostSyncError) as ei:
                float(x)
        msg = str(ei.value)
        assert "__float__" in msg
        assert "test_analysis.py" in msg          # the offending call-site
        assert "host_pull" in msg                 # the suggested fix

    def test_implicit_bool_and_int_raise(self):
        guard = HostSyncGuard("strict")
        x = jnp.ones(())
        with guard.armed():
            with pytest.raises(HostSyncError):
                bool(x > 0)
            with pytest.raises(HostSyncError):
                int(x)

    def test_item_and_tolist_raise(self):
        guard = HostSyncGuard("strict")
        x = jnp.arange(3)
        with guard.armed():
            with pytest.raises(HostSyncError):
                x[0].item()
            with pytest.raises(HostSyncError):
                x.tolist()

    def test_host_pull_is_the_permitted_choke_point(self):
        guard = HostSyncGuard("strict")
        x = jnp.ones((4,))
        before = STATS.snapshot()["explicit_pulls"]
        with guard.armed():
            out = host_pull({"a": x, "b": x * 2}, what="test")
        assert isinstance(out["a"], np.ndarray)
        np.testing.assert_allclose(out["b"], 2.0)
        assert STATS.snapshot()["explicit_pulls"] == before + 1

    def test_allow_host_sync_escape_hatch(self):
        guard = HostSyncGuard("strict")
        x = jnp.ones(())
        with guard.armed():
            with allow_host_sync():
                assert float(x) == 1.0

    def test_outside_armed_region_everything_is_free(self):
        x = jnp.ones(())
        assert float(x) == 1.0
        assert bool(x > 0)

    def test_warn_mode_counts_instead_of_raising(self):
        guard = HostSyncGuard("warn")
        x = jnp.ones(())
        before = STATS.snapshot()["implicit"]
        with guard.armed():
            assert float(x) == 1.0
        assert STATS.snapshot()["implicit"] == before + 1

    def test_armed_is_thread_local(self):
        import threading
        guard = HostSyncGuard("strict")
        x = jnp.ones(())
        seen = {}

        def other():
            seen["v"] = float(x)      # unguarded thread: free

        with guard.armed():
            t = threading.Thread(target=other)
            t.start()
            t.join()
        assert seen["v"] == 1.0

    def test_null_guard_is_free(self):
        x = jnp.ones(())
        with NULL_GUARD.armed():
            assert float(x) == 1.0


class TestHotLoopIntegration:
    def test_training_loop_strict_clean_and_stray_float_caught(self):
        """The fixture arms strict mode: a 3-step run must be sync-clean,
        and a hot-loop stray float() injected via a poisoned optim method
        must be caught with a diagnostic."""
        from bigdl_tpu.dataset import Sample
        from bigdl_tpu.dataset.dataset import LocalDataSet
        from bigdl_tpu.dataset.transformer import SampleToMiniBatch
        from bigdl_tpu.optim import SGD
        from bigdl_tpu.optim import trigger as triggers
        from bigdl_tpu.optim.optimizer import LocalOptimizer

        rng = np.random.RandomState(0)
        samples = [Sample(rng.randn(4).astype(np.float32),
                          np.array([1.0], np.float32)) for _ in range(16)]

        def build(method):
            m = (nn.Sequential().add(nn.Linear(4, 2)).add(nn.LogSoftMax()))
            m.reset(jax.random.PRNGKey(0))
            opt = LocalOptimizer(
                m, LocalDataSet(samples).transform(SampleToMiniBatch(8)),
                nn.ClassNLLCriterion())
            opt.set_optim_method(method)
            opt.set_end_when(triggers.max_iteration(3))
            return opt

        before = STATS.snapshot()["implicit"]
        build(SGD(learning_rate=0.1)).optimize()
        assert STATS.snapshot()["implicit"] == before, \
            "the fused-step hot loop performed an implicit host sync"

        class StrayFloatSGD(SGD):
            """Deliberately pulls a device value in hyper() — the classic
            implicit sync a refactor sneaks into the hot loop."""

            def hyper(self):
                h = super().hyper()
                h["lr"] = float(jnp.asarray(h["lr"]) * 1)   # device→host!
                return h

        with pytest.raises(HostSyncError) as ei:
            build(StrayFloatSGD(learning_rate=0.1)).optimize()
        assert "__float__" in str(ei.value)

    def test_fetch_path_sanitized_on_producer_thread(self):
        """The guard's hooks are thread-local and the ACTUAL fetch runs on
        the BatchPrefetcher producer thread — a stray float(device) in a
        fetch transformer must still be caught in strict mode with
        prefetching enabled (the default)."""
        from bigdl_tpu.dataset import Sample
        from bigdl_tpu.dataset.dataset import LocalDataSet
        from bigdl_tpu.dataset.transformer import SampleToMiniBatch
        from bigdl_tpu.optim import SGD
        from bigdl_tpu.optim import trigger as triggers
        from bigdl_tpu.optim.optimizer import LocalOptimizer

        rng = np.random.RandomState(0)
        samples = [Sample(rng.randn(4).astype(np.float32),
                          np.array([1.0], np.float32)) for _ in range(16)]
        m = nn.Sequential().add(nn.Linear(4, 2)).add(nn.LogSoftMax())
        m.reset(jax.random.PRNGKey(0))
        opt = LocalOptimizer(
            m, LocalDataSet(samples).transform(SampleToMiniBatch(8)),
            nn.ClassNLLCriterion())
        opt.set_optim_method(SGD(learning_rate=0.1))
        opt.set_end_when(triggers.max_iteration(3))

        orig = opt.dataset.data

        def poisoned_data(*a, **kw):
            for batch in orig(*a, **kw):
                float(jnp.asarray(1.0))           # device pull in fetch
                yield batch

        opt.dataset.data = poisoned_data
        with pytest.raises(HostSyncError):
            opt.optimize()

    def test_retrace_counter_reaches_train_summary(self):
        """Analysis/retraces must land in TrainSummary scalars."""
        from bigdl_tpu.dataset import Sample
        from bigdl_tpu.dataset.dataset import LocalDataSet
        from bigdl_tpu.dataset.transformer import SampleToMiniBatch
        from bigdl_tpu.optim import SGD
        from bigdl_tpu.optim import trigger as triggers
        from bigdl_tpu.optim.optimizer import LocalOptimizer

        class Capture:
            def __init__(self):
                self.tags = {}

            def add_scalar(self, tag, value, step):
                self.tags.setdefault(tag, []).append(value)
                return self

            def save_parameters_due(self, state):
                return False

        rng = np.random.RandomState(0)
        samples = [Sample(rng.randn(4).astype(np.float32),
                          np.array([1.0], np.float32)) for _ in range(16)]
        m = nn.Sequential().add(nn.Linear(4, 2)).add(nn.LogSoftMax())
        m.reset(jax.random.PRNGKey(0))
        opt = LocalOptimizer(
            m, LocalDataSet(samples).transform(SampleToMiniBatch(8)),
            nn.ClassNLLCriterion())
        opt.set_optim_method(SGD(learning_rate=0.1))
        opt.set_end_when(triggers.max_iteration(3))
        cap = Capture()
        opt.set_train_summary(cap)
        opt.optimize()
        assert cap.tags["Analysis/retraces"] == [0, 0, 0]
        # per-run DELTA, independent of process-lifetime counter state
        assert cap.tags["Analysis/implicit_host_syncs"] == [0, 0, 0]

    def test_host_sync_scalar_independent_of_retrace_pass(self):
        """Analysis/implicit_host_syncs must report even with the retrace
        pass off (the two passes gate independently)."""
        from bigdl_tpu.dataset import Sample
        from bigdl_tpu.dataset.dataset import LocalDataSet
        from bigdl_tpu.dataset.transformer import SampleToMiniBatch
        from bigdl_tpu.optim import SGD
        from bigdl_tpu.optim import trigger as triggers
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.utils import config

        class Capture:
            def __init__(self):
                self.tags = {}

            def add_scalar(self, tag, value, step):
                self.tags.setdefault(tag, []).append(value)
                return self

            def save_parameters_due(self, state):
                return False

        config.set_property("bigdl.analysis.retrace", "off")
        try:
            rng = np.random.RandomState(0)
            samples = [Sample(rng.randn(4).astype(np.float32),
                              np.array([1.0], np.float32))
                       for _ in range(16)]
            m = nn.Sequential().add(nn.Linear(4, 2)).add(nn.LogSoftMax())
            m.reset(jax.random.PRNGKey(0))
            opt = LocalOptimizer(
                m, LocalDataSet(samples).transform(SampleToMiniBatch(8)),
                nn.ClassNLLCriterion())
            opt.set_optim_method(SGD(learning_rate=0.1))
            opt.set_end_when(triggers.max_iteration(2))
            cap = Capture()
            opt.set_train_summary(cap)
            opt.optimize()
            assert opt._retrace_sentinel is None
            assert "Analysis/retraces" not in cap.tags
            assert cap.tags["Analysis/implicit_host_syncs"] == [0, 0]
        finally:
            config.set_property("bigdl.analysis.retrace", "strict")


# ---------------------------------------------------------------------------
# module contract checker
# ---------------------------------------------------------------------------

def _convnet():
    m = (nn.Sequential()
         .add(nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1))
         .add(nn.ReLU())
         .add(nn.SpatialMaxPooling(2, 2, 2, 2))
         .add(nn.View([8 * 4 * 4]))
         .add(nn.Linear(8 * 16, 10)))
    m.reset(jax.random.PRNGKey(0))
    return m


class TestContractChecker:
    def test_clean_model_reports_ok(self):
        rep = check_model(_convnet(), jnp.zeros((2, 3, 8, 8)), mode="off")
        assert rep.ok
        assert rep.modules_checked >= 5

    def test_abstract_input_works(self):
        """The walk runs under eval_shape — a ShapeDtypeStruct (no data at
        all) checks the same contracts as concrete arrays."""
        rep = check_model(_convnet(),
                          jax.ShapeDtypeStruct((2, 3, 8, 8), jnp.float32),
                          mode="off")
        assert rep.ok

    def test_int_input_violates_conv_dtype_contract(self):
        rep = check_model(_convnet(), jnp.zeros((2, 3, 8, 8), jnp.int32),
                          mode="off")
        assert any(v.kind == "dtype" and "SpatialConvolution" in v.module
                   for v in rep.violations)

    def test_declared_ndim_violation(self):
        m = nn.Sequential().add(nn.Linear(4, 2))
        m[0].declare_contract(input_ndim=(2,), dtypes="float")
        m.reset(jax.random.PRNGKey(0))
        rep = check_model(m, jnp.zeros((2, 3, 4)), mode="off")
        assert any(v.kind == "ndim" for v in rep.violations)

    def test_promotion_drift_flagged(self):
        """bf16 activations hitting an f32-pinning module must be reported
        as promotion drift."""
        class F32Pin(nn.Module):
            layout_role = "agnostic"

            def apply(self, params, input, state, training=False, rng=None):
                return input + jnp.ones(input.shape[-1:], jnp.float32), state

        m = nn.Sequential().add(F32Pin())
        m.reset(jax.random.PRNGKey(0))
        rep = check_model(m, jnp.zeros((2, 4), jnp.bfloat16), mode="off")
        assert any(v.kind == "promotion" for v in rep.violations)

    def test_nchw_op_inside_nhwc_region_flagged(self):
        """Closing the loop on PR 1: a spatial module left NCHW-configured
        inside the channels-last region is a layout violation."""
        from bigdl_tpu.nn.layout import to_channels_last
        m = to_channels_last(_convnet())
        rep = check_model(m, jnp.zeros((2, 3, 8, 8)), mode="off")
        assert rep.ok, str(rep)
        # sabotage: re-point one interior conv back to NCHW without moving
        # the boundary transposes
        conv = m.find_modules(nn.SpatialConvolution)[0]
        conv.format = "NCHW"
        rep2 = check_model(m, jnp.zeros((2, 3, 8, 8)), mode="off")
        assert any(v.kind == "layout" for v in rep2.violations)

    def test_strict_mode_raises(self):
        with pytest.raises(ContractError):
            check_model(_convnet(), jnp.zeros((2, 3, 8, 8), jnp.int32),
                        mode="strict")

    def test_violation_names_container_path(self):
        """A violation must carry the indexed container path (zoo-sized
        models have dozens of Linears — a bare class name locates
        nothing), including through nested containers."""
        inner = nn.Sequential().add(nn.Linear(4, 4))
        inner[0].declare_contract(input_ndim=(2,), dtypes="float")
        m = nn.Sequential().add(nn.ReLU()).add(inner)
        m.reset(jax.random.PRNGKey(0))
        rep = check_model(m, jnp.zeros((2, 3, 4)), mode="off")
        ndim = [v for v in rep.violations if v.kind == "ndim"]
        assert ndim, str(rep)
        assert ndim[0].module == "Sequential[1].Sequential[0].Linear"
        assert "Sequential[1].Sequential[0].Linear" in str(rep)

    def test_convnet_violation_path_is_indexed(self):
        rep = check_model(_convnet(), jnp.zeros((2, 3, 8, 8), jnp.int32),
                          mode="off")
        assert any(v.kind == "dtype" and
                   v.module == "Sequential[0].SpatialConvolution"
                   for v in rep.violations), str(rep)

    def test_moe_block_checks_clean(self):
        """check_model over a gated MoE block under eval_shape: the
        routed dispatch (top-k gating, capacity slots, stacked expert
        params) traces abstractly with zero violations."""
        expert = (nn.Sequential()
                  .add(nn.Linear(8, 16)).add(nn.ReLU())
                  .add(nn.Linear(16, 8)))
        m = (nn.Sequential()
             .add(nn.Linear(8, 8))
             .add(nn.MixtureOfExperts(8, expert, n_experts=4,
                                      capacity_factor=4.0, top_k=2))
             .add(nn.Linear(8, 3)))
        m.reset(jax.random.PRNGKey(0))
        rep = check_model(m, jax.ShapeDtypeStruct((16, 8), jnp.float32),
                          mode="off")
        assert rep.ok, str(rep)
        assert rep.modules_checked >= 3

    def test_folded_serving_model_checks_clean_and_sabotage_trips(self):
        """fold_conv_bn's serving rewrite (conv<-BN folded, Identity left
        behind) plus channels-last conversion passes the checker clean;
        re-pointing the folded conv back to NCHW inside the NHWC region
        still trips layout — and the report names the indexed path."""
        from bigdl_tpu.nn.layout import to_channels_last
        m = (nn.Sequential()
             .add(nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1))
             .add(nn.SpatialBatchNormalization(8))
             .add(nn.ReLU())
             .add(nn.View([8 * 8 * 8]))
             .add(nn.Linear(8 * 8 * 8, 10)))
        m.reset(jax.random.PRNGKey(0))
        m.evaluate()
        folded = to_channels_last(nn.fold_conv_bn(m))
        rep = check_model(folded,
                          jax.ShapeDtypeStruct((2, 3, 8, 8), jnp.float32),
                          mode="off")
        assert rep.ok, str(rep)
        conv = folded.find_modules(nn.SpatialConvolution)[0]
        conv.format = "NCHW"
        rep2 = check_model(folded,
                           jax.ShapeDtypeStruct((2, 3, 8, 8), jnp.float32),
                           mode="off")
        layout = [v for v in rep2.violations if v.kind == "layout"]
        assert layout, str(rep2)
        assert "SpatialConvolution" in layout[0].module
        assert "[" in layout[0].module      # indexed container path

    def test_restores_apply_after_walk(self):
        m = _convnet()
        check_model(m, jnp.zeros((2, 3, 8, 8)), mode="off")
        assert "apply" not in m[0].__dict__
        out = m.forward(jnp.zeros((2, 3, 8, 8)))
        assert out.shape == (2, 10)


# ---------------------------------------------------------------------------
# AST lint rules
# ---------------------------------------------------------------------------

_SNIPPET_SEQ = iter(range(10 ** 6))


def _lint_snippet(tmp_path, rel, source):
    root = tmp_path / f"snippet{next(_SNIPPET_SEQ)}"   # isolated per call
    p = root / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return lint_paths([str(root)])


class TestLintRules:
    def test_host_sync_in_hot_path(self, tmp_path):
        findings = _lint_snippet(tmp_path, "optim/opt.py", """
            def drain(item, nxt):
                loss = float(item[0])
                n = item[0].item()
            def harmless(x):
                return float(x)
        """)
        rules = [f.rule for f in findings]
        assert rules.count("host-sync-in-hot-path") == 2
        assert all(f.line in (3, 4) for f in findings)

    def test_host_pull_wrapped_calls_exempt(self, tmp_path):
        findings = _lint_snippet(tmp_path, "optim/opt.py", """
            def drain(item, nxt):
                loss = float(host_pull(item[0], what="loss"))
        """)
        assert findings == []

    def test_jnp_dtype_drop_in_forward_path(self, tmp_path):
        findings = _lint_snippet(tmp_path, "nn/layer.py", """
            import jax.numpy as jnp
            class C:
                def apply(self, params, input, state):
                    pad = jnp.zeros((4,))
                    ok = jnp.zeros((4,), jnp.float32)
                    kw = jnp.ones((4,), dtype=input.dtype)
                    idx = jnp.arange(4)
                    return pad
                def _init_params(self, rng):
                    return {"w": jnp.zeros((4,))}
        """)
        assert [f.rule for f in findings] == ["jnp-dtype-drop"]
        assert findings[0].line == 5

    def test_bare_except_anywhere(self, tmp_path):
        findings = _lint_snippet(tmp_path, "utils/x.py", """
            def f():
                try:
                    g()
                except:
                    pass
        """)
        assert [f.rule for f in findings] == ["bare-except"]

    def test_swallowed_exception_in_threaded_files_only(self, tmp_path):
        src = """
            def worker():
                try:
                    g()
                except Exception:
                    pass
        """
        assert [f.rule for f in _lint_snippet(tmp_path, "dataset/ingest.py",
                                              src)] == ["swallowed-exception"]
        assert _lint_snippet(tmp_path, "utils/other.py", src) == []

    def test_unguarded_io_in_stage_thread(self, tmp_path):
        """Raw open() in dataset/ingest.py flags; the same code anywhere
        else (or routed through file_io/seqfile) stays clean."""
        src = """
            import os
            def reader():
                with open("/data/shard.seq", "rb") as f:
                    return f.read()
            def reader2():
                fd = os.open("/data/shard.seq", 0)
        """
        findings = _lint_snippet(tmp_path, "dataset/ingest.py", src)
        assert [f.rule for f in findings] == [
            "unguarded-io-in-stage-thread"] * 2
        assert _lint_snippet(tmp_path, "dataset/seqfile.py", src) == []
        guarded = """
            from bigdl_tpu.utils import file_io
            def reader():
                return file_io.read_bytes("/data/shard.seq")
            def reader2():
                data = open  # a bare name, not a call
        """
        assert _lint_snippet(tmp_path, "dataset/ingest.py", guarded) == []
        allowed = """
            def reader():
                with open("/x", "rb") as f:  # lint: allow(unguarded-io-in-stage-thread)
                    return f.read()
        """
        assert _lint_snippet(tmp_path, "dataset/ingest.py", allowed) == []

    def test_lock_order_cycle_detected(self, tmp_path):
        findings = _lint_snippet(tmp_path, "engine.py", """
            def a(self):
                with self._alpha_lock:
                    with self._beta_lock:
                        work()
            def b(self):
                with self._beta_lock:
                    with self._alpha_lock:
                        work()
        """)
        assert any(f.rule == "lock-order" for f in findings)

    def test_consistent_lock_order_clean(self, tmp_path):
        findings = _lint_snippet(tmp_path, "engine.py", """
            def a(self):
                with self._alpha_lock:
                    with self._beta_lock:
                        work()
            def b(self):
                with self._alpha_lock:
                    with self._beta_lock:
                        other()
        """)
        assert findings == []

    def test_blocking_under_lock(self, tmp_path):
        findings = _lint_snippet(tmp_path, "dataset/ingest.py", """
            def handoff(self):
                with self._lock:
                    self.out_ring.put(item, stop)
            def fine(self):
                with self._lock:
                    self.counts.get("x", 0)
        """)
        assert [f.rule for f in findings] == ["blocking-under-lock"]
        assert findings[0].line == 4

    def test_nonblocking_forms_under_lock_are_clean(self, tmp_path):
        findings = _lint_snippet(tmp_path, "dataset/ingest.py", """
            def handoff(self):
                with self._lock:
                    self.out_ring.put(item, block=False)
                    self.in_ring.get(timeout=0)
        """)
        assert findings == []

    def test_unbounded_queue_in_serving(self, tmp_path):
        """queue.Queue()/deque() without a bound flags in serving/ and
        engine.py; bounded forms and out-of-scope files stay clean."""
        src = """
            import queue
            from collections import deque
            def build(self):
                self.q = queue.Queue()
                self.q2 = queue.Queue(maxsize=0)
                self.sq = queue.SimpleQueue()
                self.d = deque()
                self.d2 = deque([1, 2])
        """
        findings = _lint_snippet(tmp_path, "serving/server.py", src)
        assert [f.rule for f in findings] == \
            ["unbounded-queue-in-serving"] * 5
        assert [f.rule for f in _lint_snippet(tmp_path, "engine.py", src)
                ].count("unbounded-queue-in-serving") == 5
        # out of scope: the same constructions elsewhere are not the
        # serving path's problem
        assert _lint_snippet(tmp_path, "utils/misc.py", src) == []
        bounded = """
            import queue
            from collections import deque
            def build(self):
                self.q = queue.Queue(maxsize=8)
                self.q2 = queue.Queue(16)
                self.d = deque(maxlen=4)
                self.d2 = deque([1, 2], 4)
        """
        assert _lint_snippet(tmp_path, "serving/server.py", bounded) == []

    def test_inline_allow_silences(self, tmp_path):
        findings = _lint_snippet(tmp_path, "optim/opt.py", """
            def drain(item, nxt):
                loss = float(item[0])  # lint: allow(host-sync-in-hot-path)
        """)
        assert findings == []

    def test_allowlist_silences_by_path_and_rule(self, tmp_path):
        p = tmp_path / "optim" / "opt.py"
        p.parent.mkdir(parents=True)
        p.write_text("def drain(i, n):\n    return float(i[0])\n")
        (found,) = lint_paths([str(tmp_path)])
        allow = tmp_path / "allow.txt"
        allow.write_text(f"# comment\n{found.path}:{found.rule}\n")
        assert lint_paths([str(tmp_path)],
                          load_allowlist(str(allow))) == []

    def test_single_file_target_keeps_package_relative_paths(self, tmp_path):
        """Linting one file must apply the same path-scoped rules and
        produce the same Finding.path keys as linting the package — rel
        paths anchor at the topmost package, not the cwd."""
        pkg = tmp_path / "mypkg"
        (pkg / "optim").mkdir(parents=True)
        (pkg / "__init__.py").write_text("")
        (pkg / "optim" / "__init__.py").write_text("")
        bad = pkg / "optim" / "opt.py"
        bad.write_text("def drain(i, n):\n    return float(i[0])\n")
        whole = [(f.path, f.line, f.rule) for f in lint_paths([str(pkg)])]
        single = [(f.path, f.line, f.rule) for f in lint_paths([str(bad)])]
        assert single == whole
        assert single == [(os.path.join("mypkg", "optim", "opt.py"), 2,
                           "host-sync-in-hot-path")]

    def test_cli_exit_codes(self, tmp_path, capsys):
        bad = tmp_path / "optim" / "opt.py"
        bad.parent.mkdir(parents=True)
        bad.write_text("def drain(i, n):\n    return float(i[0])\n")
        assert lint_main([str(tmp_path)]) == 1
        bad.write_text("def drain(i, n):\n    return i[0]\n")
        assert lint_main([str(tmp_path)]) == 0


# ---------------------------------------------------------------------------
# Metrics: device scalars accumulate without per-call float()
# ---------------------------------------------------------------------------

class TestMetricsDeviceScalars:
    def test_add_device_scalar_defers_the_pull(self):
        from bigdl_tpu.optim.metrics import Metrics
        m = Metrics()
        guard = HostSyncGuard("strict")
        with guard.armed():
            # adds inside the sanitized hot loop must not sync
            for i in range(5):
                m.add("loss", jnp.asarray(float(i)))
        assert m.get("loss") == 10.0              # one pull, at read time

    def test_mixed_host_and_device_values(self):
        from bigdl_tpu.optim.metrics import Metrics
        m = Metrics()
        m.add("t", 1.0)
        m.add("t", jnp.asarray(2.0))
        m.add("t", 3)
        assert m.get("t") == 6.0

    def test_set_clears_pending(self):
        from bigdl_tpu.optim.metrics import Metrics
        m = Metrics()
        m.add("t", jnp.asarray(5.0))
        m.set("t", 1.0)
        assert m.get("t") == 1.0

    def test_summary_flushes(self):
        from bigdl_tpu.optim.metrics import Metrics
        m = Metrics()
        m.add("x", jnp.asarray(2e9))
        assert "x: 2.0 s" in m.summary()

    def test_pending_compacts_on_device(self):
        """A long write-only run must not park one live buffer per add:
        past COMPACT_AT the parked scalars fold into one on-device sum
        (an async dispatch, never a host sync)."""
        from bigdl_tpu.optim.metrics import Metrics
        m = Metrics()
        guard = HostSyncGuard("strict")
        with guard.armed():
            for i in range(m.COMPACT_AT * 2 + 7):
                m.add("t", jnp.asarray(1.0))
            assert len(m._pending["t"]) < m.COMPACT_AT
        assert m.get("t") == m.COMPACT_AT * 2 + 7


# ---------------------------------------------------------------------------
# config keys
# ---------------------------------------------------------------------------

class TestAnalysisConfig:
    def test_defaults_present(self):
        from bigdl_tpu.utils import config
        known = config.known_properties()
        for key in ("bigdl.analysis.retrace", "bigdl.analysis.hostSync",
                    "bigdl.analysis.contracts", "bigdl.analysis.hotLoopScope",
                    "bigdl.analysis.retraceWarmupSteps",
                    "bigdl.analysis.retraceBudget"):
            assert key in known, key

    def test_unknown_mode_degrades_to_off(self):
        from bigdl_tpu.analysis import pass_mode
        from bigdl_tpu.utils import config
        config.set_property("bigdl.analysis.retrace", "shout")
        try:
            assert pass_mode("retrace") == "off"
        finally:
            config.clear_property("bigdl.analysis.retrace")
