"""Poisson open-loop load generation with exact outcome accounting.

Open loop is the load model that exposes overload: arrivals come on
their own clock (exponential inter-arrival gaps), never waiting for
completions, so a server that slows down faces a GROWING queue instead
of a conveniently self-throttling client.  The generator is also the
consumer of the ``bigdl.chaos.burstArrivals`` injector — a thundering
herd is an *arrival-process* fault, so it is injected where arrivals
are made.

Accounting: every submission lands in exactly one bucket — ``completed``
/ ``shed`` / ``rejected`` / ``quarantined`` — and the returned record
carries the identity residual (``unaccounted``, asserted zero by the
chaos proofs and the bench leg).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from bigdl_tpu.serving.engine import OUTCOMES, Overloaded, ServingEngine


def run_open_loop(engine: ServingEngine, payloads: Sequence[Any],
                  rate_hz: float, deadline_ms: Optional[float] = None,
                  seed: int = 0,
                  on_arrival: Optional[Callable[[int], None]] = None,
                  result_timeout_s: float = 30.0) -> Dict[str, Any]:
    """Drive ``engine`` with one Poisson open-loop pass over
    ``payloads``.

    ``rate_hz``: mean arrival rate (0 = back-to-back, the pure burst).
    ``on_arrival(i)`` runs before arrival ``i`` is submitted — the chaos
    proofs hook preemption signals here.  Returns the accounting
    record::

        {submitted, completed, shed, rejected, quarantined, unaccounted,
         latency_ms: [...], reject_latency_ms: [...],
         results: {arrival_key: np.ndarray},
         errors: {arrival_key: Exception},
         handles: [(arrival_key, RequestHandle | None)]}

    ``arrival_key`` is ``str(i)`` for scheduled arrivals and ``"i+bj"``
    for the j-th extra arrival of a ``bigdl.chaos.burstArrivals`` herd
    at position ``i``.
    """
    from bigdl_tpu.utils import chaos
    rng = np.random.default_rng(seed)
    handles: List = []
    reject_latency_ms: List[float] = []
    errors: Dict[str, BaseException] = {}
    submitted = 0
    next_due = time.monotonic()

    def _arrive(key: str, payload) -> None:
        nonlocal submitted
        submitted += 1
        t0 = time.monotonic()
        try:
            h = engine.submit(payload, deadline_ms=deadline_ms)
        except Overloaded as e:
            # the reject path must be FAST — its latency is a headline
            # claim of the bench leg
            reject_latency_ms.append((time.monotonic() - t0) * 1e3)
            errors[key] = e
            handles.append((key, None))
        else:
            handles.append((key, h))

    for i, payload in enumerate(payloads):
        if on_arrival is not None:
            on_arrival(i)
        now = time.monotonic()
        if now < next_due:
            time.sleep(next_due - now)
        _arrive(str(i), payload)
        for j in range(chaos.burst_arrivals(i)):
            # a herd arrives back-to-back, on top of the schedule
            _arrive(f"{i}+b{j}", payload)
        if rate_hz > 0:
            next_due = max(next_due, now) + float(
                rng.exponential(1.0 / rate_hz))

    # quiesce: every admitted request must reach its one terminal state
    results: Dict[str, Any] = {}
    latency_ms: List[float] = []
    counts = dict.fromkeys(OUTCOMES, 0)
    for key, h in handles:
        if h is None:
            counts["rejected"] += 1
            continue
        try:
            results[key] = h.result(timeout=result_timeout_s)
        except TimeoutError:
            pass            # stays unaccounted — the identity will flag it
        except Exception as e:  # terminal serving error
            errors[key] = e
        if h.outcome in counts:
            counts[h.outcome] += 1
        if h.outcome == "completed":
            latency_ms.append(h.latency_ms())

    record: Dict[str, Any] = {"submitted": submitted, **counts}
    record["unaccounted"] = submitted - sum(counts[o] for o in OUTCOMES)
    record["latency_ms"] = latency_ms
    record["reject_latency_ms"] = reject_latency_ms
    record["results"] = results
    record["errors"] = errors
    record["handles"] = handles
    return record
