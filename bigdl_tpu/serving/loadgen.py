"""Poisson open-loop load generation with exact outcome accounting.

Open loop is the load model that exposes overload: arrivals come on
their own clock (exponential inter-arrival gaps), never waiting for
completions, so a server that slows down faces a GROWING queue instead
of a conveniently self-throttling client.  The generator is also the
consumer of the ``bigdl.chaos.burstArrivals`` injector — a thundering
herd is an *arrival-process* fault, so it is injected where arrivals
are made.

Accounting: every submission lands in exactly one bucket — ``completed``
/ ``shed`` / ``rejected`` / ``quarantined`` — and the returned record
carries the identity residual (``unaccounted``, asserted zero by the
chaos proofs and the bench leg).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from bigdl_tpu.serving.engine import OUTCOMES, Overloaded, ServingEngine


def run_open_loop(engine: ServingEngine, payloads: Sequence[Any],
                  rate_hz: float, deadline_ms: Optional[float] = None,
                  seed: int = 0,
                  on_arrival: Optional[Callable[[int], None]] = None,
                  result_timeout_s: float = 30.0) -> Dict[str, Any]:
    """Drive ``engine`` with one Poisson open-loop pass over
    ``payloads``.

    ``rate_hz``: mean arrival rate (0 = back-to-back, the pure burst).
    ``on_arrival(i)`` runs before arrival ``i`` is submitted — the chaos
    proofs hook preemption signals here.  Returns the accounting
    record::

        {submitted, completed, shed, rejected, quarantined, unaccounted,
         latency_ms: [...], reject_latency_ms: [...],
         results: {arrival_key: np.ndarray},
         errors: {arrival_key: Exception},
         handles: [(arrival_key, RequestHandle | None)]}

    ``arrival_key`` is ``str(i)`` for scheduled arrivals and ``"i+bj"``
    for the j-th extra arrival of a ``bigdl.chaos.burstArrivals`` herd
    at position ``i``.
    """
    from bigdl_tpu.utils import chaos
    rng = np.random.default_rng(seed)
    handles: List = []
    reject_latency_ms: List[float] = []
    errors: Dict[str, BaseException] = {}
    submitted = 0
    next_due = time.monotonic()

    def _arrive(key: str, payload) -> None:
        nonlocal submitted
        submitted += 1
        t0 = time.monotonic()
        try:
            h = engine.submit(payload, deadline_ms=deadline_ms)
        except Overloaded as e:
            # the reject path must be FAST — its latency is a headline
            # claim of the bench leg
            reject_latency_ms.append((time.monotonic() - t0) * 1e3)
            errors[key] = e
            handles.append((key, None))
        else:
            handles.append((key, h))

    for i, payload in enumerate(payloads):
        if on_arrival is not None:
            on_arrival(i)
        now = time.monotonic()
        if now < next_due:
            time.sleep(next_due - now)
        _arrive(str(i), payload)
        for j in range(chaos.burst_arrivals(i)):
            # a herd arrives back-to-back, on top of the schedule
            _arrive(f"{i}+b{j}", payload)
        if rate_hz > 0:
            next_due = max(next_due, now) + float(
                rng.exponential(1.0 / rate_hz))

    # quiesce: every admitted request must reach its one terminal state
    results: Dict[str, Any] = {}
    latency_ms: List[float] = []
    counts = dict.fromkeys(OUTCOMES, 0)
    for key, h in handles:
        if h is None:
            counts["rejected"] += 1
            continue
        try:
            results[key] = h.result(timeout=result_timeout_s)
        except TimeoutError:
            pass            # stays unaccounted — the identity will flag it
        except Exception as e:  # terminal serving error
            errors[key] = e
        if h.outcome in counts:
            counts[h.outcome] += 1
        if h.outcome == "completed":
            latency_ms.append(h.latency_ms())

    record: Dict[str, Any] = {"submitted": submitted, **counts}
    record["unaccounted"] = submitted - sum(counts[o] for o in OUTCOMES)
    record["latency_ms"] = latency_ms
    record["reject_latency_ms"] = reject_latency_ms
    record["results"] = results
    record["errors"] = errors
    record["handles"] = handles
    return record


# ---------------------------------------------------------------------------
# LM token serving (bigdl_tpu/serving/lm.py)
# ---------------------------------------------------------------------------


def sample_lm_workload(n: int, vocab_size: int, seed: int = 0,
                       prompt_lens: Sequence[int] = (8, 16, 32, 64),
                       output_lens: Sequence[int] = (4, 8, 16),
                       prompt_weights: Optional[Sequence[float]] = None,
                       output_weights: Optional[Sequence[float]] = None
                       ) -> List[Any]:
    """``n`` LM requests sampled from a prompt/output-length
    distribution: a list of ``(prompt_tokens, max_new_tokens)`` pairs
    (token ids 1-based, as the models expect).  Mixed lengths are the
    point — serving heterogeneous sequences through ONE fixed decode
    shape is what the paged cache buys."""
    rng = np.random.default_rng(seed)
    p_lens = np.asarray(list(prompt_lens), int)
    o_lens = np.asarray(list(output_lens), int)
    reqs = []
    for _ in range(n):
        p = int(rng.choice(p_lens, p=prompt_weights))
        o = int(rng.choice(o_lens, p=output_weights))
        prompt = rng.integers(1, vocab_size + 1, size=p).astype(np.int32)
        reqs.append((prompt, o))
    return reqs


def run_lm_open_loop(engine, requests: Sequence[Any], rate_hz: float,
                     deadline_ms: Optional[float] = None, seed: int = 0,
                     on_arrival: Optional[Callable[[int], None]] = None,
                     result_timeout_s: float = 60.0) -> Dict[str, Any]:
    """Poisson open-loop pass over ``(prompt, max_new_tokens)``
    requests against an ``LMServingEngine``, with per-request streaming
    consumption: every admitted stream gets a consumer thread iterating
    its :class:`~bigdl_tpu.serving.lm.TokenStream` (recording TTFT and
    inter-token gaps client-side, on ARRIVAL of each token), so the
    record's percentiles measure the streamed experience, not just the
    terminal state.  Same arrival process, burst injector, and
    accounting identity as :func:`run_open_loop`.  Returns::

        {submitted, completed, shed, rejected, quarantined, unaccounted,
         tokens_total, elapsed_s, tokens_per_s,
         ttft_ms: [...], itl_ms: [...], latency_ms: [...],
         p50_ttft_ms, p99_ttft_ms, p50_itl_ms, p99_itl_ms,
         errors: {arrival_key: Exception},
         streams: [(arrival_key, TokenStream | None)]}
    """
    import threading

    from bigdl_tpu.utils import chaos
    rng = np.random.default_rng(seed)
    streams: List = []
    consumers: List[threading.Thread] = []
    token_ns: Dict[str, List[int]] = {}
    reject_latency_ms: List[float] = []
    errors: Dict[str, BaseException] = {}
    submitted = 0
    t_start = time.monotonic()
    next_due = t_start

    def _consume(key: str, stream) -> None:
        arrivals = token_ns.setdefault(key, [])
        try:
            for _ in stream:
                arrivals.append(time.monotonic_ns())
        except Exception as e:  # terminal serving error, kept for record
            errors[key] = e

    def _arrive(key: str, prompt, max_new: int) -> None:
        nonlocal submitted
        submitted += 1
        t0 = time.monotonic()
        try:
            s = engine.submit(prompt, max_new_tokens=max_new,
                              deadline_ms=deadline_ms)
        except Overloaded as e:
            reject_latency_ms.append((time.monotonic() - t0) * 1e3)
            errors[key] = e
            streams.append((key, None))
        else:
            streams.append((key, s))
            t = threading.Thread(target=_consume, args=(key, s),
                                 daemon=True,
                                 name=f"lm-loadgen-consume-{key}")
            t.start()
            consumers.append(t)

    for i, (prompt, max_new) in enumerate(requests):
        if on_arrival is not None:
            on_arrival(i)
        now = time.monotonic()
        if now < next_due:
            time.sleep(next_due - now)
        _arrive(str(i), prompt, max_new)
        for j in range(chaos.burst_arrivals(i)):
            _arrive(f"{i}+b{j}", prompt, max_new)
        if rate_hz > 0:
            next_due = max(next_due, now) + float(
                rng.exponential(1.0 / rate_hz))

    # quiesce: every admitted stream must reach its one terminal state
    counts = dict.fromkeys(OUTCOMES, 0)
    latency_ms: List[float] = []
    ttft_ms: List[float] = []
    itl_ms: List[float] = []
    tokens_total = 0
    for key, s in streams:
        if s is None:
            counts["rejected"] += 1
            continue
        try:
            s.result(timeout=result_timeout_s)
        except TimeoutError:
            pass            # stays unaccounted — the identity flags it
        except Exception as e:
            errors[key] = e
        if s.outcome in counts:
            counts[s.outcome] += 1
        if s.outcome == "completed":
            latency_ms.append(s.latency_ms())
    for t in consumers:
        t.join(timeout=result_timeout_s)
    elapsed_s = time.monotonic() - t_start
    submit_ns = {key: s.submit_ns for key, s in streams if s is not None}
    for key, arrivals in token_ns.items():
        tokens_total += len(arrivals)
        if not arrivals:
            continue
        # client-side TTFT: submit clock and arrival clock share
        # time.monotonic_ns via telemetry.clock_ns
        ttft_ms.append((arrivals[0] - submit_ns[key]) / 1e6)
        for a, b in zip(arrivals, arrivals[1:]):
            itl_ms.append((b - a) / 1e6)

    def _pct(xs: List[float], q: float) -> Optional[float]:
        return float(np.percentile(xs, q)) if xs else None

    record: Dict[str, Any] = {"submitted": submitted, **counts}
    record["unaccounted"] = submitted - sum(counts[o] for o in OUTCOMES)
    record["tokens_total"] = tokens_total
    record["elapsed_s"] = elapsed_s
    record["tokens_per_s"] = (tokens_total / elapsed_s
                              if elapsed_s > 0 else 0.0)
    record["ttft_ms"] = ttft_ms
    record["itl_ms"] = itl_ms
    record["latency_ms"] = latency_ms
    record["reject_latency_ms"] = reject_latency_ms
    record["p50_ttft_ms"] = _pct(ttft_ms, 50)
    record["p99_ttft_ms"] = _pct(ttft_ms, 99)
    record["p50_itl_ms"] = _pct(itl_ms, 50)
    record["p99_itl_ms"] = _pct(itl_ms, 99)
    record["errors"] = errors
    record["streams"] = streams
    return record
